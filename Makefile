# mpcium_tpu developer entry points (reference Makefile: go install ./cmd/...)

PY ?= python

.PHONY: install lint check shapecheck warmcheck claimscheck prewarm trace-check perfcheck perf-tests test test-all bench tpu-round broker chaos soak soak-tests setup-identities setup-initiator clean

install:
	pip install -e . --no-build-isolation --no-deps

# static analysis (STATIC_ANALYSIS.md): ruff and mypy run when installed
# (the hermetic CI image ships neither — their defect classes are covered
# natively by mpclint MPL6xx); mpclint + mpcflow + mpcshape always run
# and are the gate — check_all parses the AST once and feeds all three.
lint:
	@if $(PY) -c "import ruff" 2>/dev/null || command -v ruff >/dev/null 2>&1; then \
	  echo "== ruff"; ruff check mpcium_tpu/ scripts/ tests/ || exit $$?; \
	else echo "== ruff not installed — skipped (MPL6xx covers its classes)"; fi
	@if $(PY) -c "import mypy" 2>/dev/null; then \
	  echo "== mypy"; $(PY) -m mypy mpcium_tpu/wire.py mpcium_tpu/config.py mpcium_tpu/utils/ || exit $$?; \
	else echo "== mypy not installed — skipped"; fi
	@echo "== mpclint + mpcflow + mpcshape"; $(PY) scripts/check_all.py

# the one-pass static gate alone (mpclint + mpcflow + mpcshape +
# budget/surface drift, shared AST parse) — what CI calls between edit
# and test; the trace gate rides along (--no-sweep: the sweep just
# ran), and perfcheck (statistical micro-bench regression gate, <30 s,
# CPU-safe) closes it
check:
	$(PY) scripts/check_all.py
	$(PY) scripts/trace_check.py --no-sweep
	$(PY) scripts/perfcheck.py

# compile-surface gate alone (STATIC_ANALYSIS.md "Compile surface"):
# MPS9xx rules + COMPILE_SURFACE.json drift. Run
# scripts/mpcshape_surface.py (no --check) after an intentional
# signature change, review the diff, commit the JSON.
shapecheck:
	$(PY) scripts/mpcshape_surface.py --check

# warm-manifest gate alone (PERFORMANCE.md "Warm start"): the pre-warm
# work-list must enumerate exactly surface knobs × engine/buckets with
# no silent gaps — pure stdlib, no jax. Also folded into check_all.
warmcheck:
	$(PY) scripts/prewarm.py --check

# claims drift gate alone (OBSERVABILITY.md "Claims & campaigns"): the
# committed CLAIMS.json/CLAIMS.md must match a fresh evaluation of the
# artifact corpus — 0 unknown metrics, 0 untracked ROADMAP headlines.
# Regenerate after adding an artifact or a claim with
# scripts/claimscheck.py --regen. Also folded into check_all.
claimscheck:
	$(PY) scripts/claimscheck.py

# fill the XLA persistent cache for this host's serving set (the same
# pass the daemon runs at boot with warm_enabled; see scripts/prewarm.py
# for scheme/bucket/budget flags)
prewarm:
	$(PY) scripts/prewarm.py

# statistical perf-regression gate alone (PERFORMANCE.md "perf
# observatory"): micro-benches vs the committed PERF_baseline_micro.json
# under a Mann-Whitney + effect-floor + bootstrap-CI triple gate.
# --update-baseline re-anchors after an intentional perf change;
# --regen-history rebuilds PERF_history.jsonl + PERFORMANCE_dashboard.md
perfcheck:
	$(PY) scripts/perfcheck.py

perf-tests:
	$(PY) -m pytest tests/ -m perf -q

# mpctrace gate alone (OBSERVABILITY.md): committed TRACE_sample.json
# validates + covers every instrumented layer, and a traced protocol
# run is transcript-identical to an untraced one; includes the static
# sweep so it is self-contained. --regen rebuilds the sample.
trace-check:
	$(PY) scripts/trace_check.py

# smoke tier (< ~1 min target on a laptop core; full crypto suites are slow-marked)
test:
	$(PY) -m pytest tests/ -m "not slow" -q

# per-file: XLA's CPU AOT cache deserialization can segfault rarely in
# very long single processes on some hosts; file-scoped runs are isolated
# (and each file's kernels stay warm in the persistent cache)
test-all:
	@set -e; for f in tests/test_*.py; do \
	  echo "== $$f"; \
	  rc=0; $(PY) -m pytest "$$f" -q --no-header || rc=$$?; \
	  if [ $$rc -ge 128 ]; then \
	    echo "== crash (rc=$$rc); retrying without compile cache (AOT flake isolation): $$f"; \
	    MPCIUM_TESTS_NO_CACHE=1 $(PY) -m pytest "$$f" -q --no-header; \
	  elif [ $$rc -ne 0 ]; then \
	    echo "== FAILED (rc=$$rc): $$f"; exit $$rc; \
	  fi; \
	done

bench:
	$(PY) bench.py

# the ROADMAP item-1 round as one resumable command (claims ledger +
# campaign runner). In a live TPU window: `make tpu-round`; anywhere:
# `python scripts/tpu_round.py --rehearse` proves the harness on CPU.
tpu-round:
	$(PY) scripts/tpu_round.py

# chaos drills (ISSUE 3): the full catalog, JSON reports, non-zero exit
# on any missed expected outcome; reproduce a failure with --seed
chaos:
	$(PY) scripts/chaos_drill.py --seed 7

chaos-tests:
	$(PY) -m pytest tests/ -m chaos -q

# SLO load soak (ISSUE 6): bursty mixed traffic + batch-chaos fault plan,
# accounting invariant enforced (non-zero exit on any silent drop);
# committed reports (SOAK_*.json) come from this entry point
soak:
	$(PY) scripts/load_soak.py --out SOAK_local.json

soak-tests:
	$(PY) -m pytest tests/ -m soak -q

# dev stack: durable broker on :4333 (the docker-compose/nats analogue)
broker:
	$(PY) -m mpcium_tpu.cli.main broker --port 4333 --journal ./broker-queue.jsonl

setup-identities:
	bash scripts/setup_identities.sh

setup-initiator:
	bash scripts/setup_initiator.sh

clean:
	rm -rf db control broker-queue.jsonl identity peers.json
