import sys, time
import numpy as np, jax, jax.numpy as jnp
from jax import lax

def bench(name, f, *args, n=10):
    t0=time.perf_counter(); r = jax.block_until_ready(f(*args)); tc=time.perf_counter()-t0
    t0 = time.perf_counter()
    for _ in range(n):
        r = f(*args)
    jax.block_until_ready(r); np.asarray(jax.tree_util.tree_leaves(r)[0][0])
    t = (time.perf_counter()-t0)/n
    print(f"{name}: {t*1e3:.3f} ms (compile {tc:.1f}s)", flush=True)
    return t

B = 4096
rng = np.random.default_rng(0)
which = sys.argv[1]

if which == "mm":
    x32 = jnp.asarray(rng.integers(0,127,(B,293)).astype(np.float32))
    C32 = jnp.asarray(rng.integers(0,127,(293,293)).astype(np.float32))
    f = jax.jit(lambda a,b: a@b)
    t = bench("f32 matmul (4096,293)@(293,293)", f, x32, C32)
    print(f"   -> {B*293*293/t/1e12:.2f} TMAC/s")
    x32b = jnp.asarray(rng.integers(0,127,(B,586)).astype(np.float32))
    C32b = jnp.asarray(rng.integers(0,127,(586,586)).astype(np.float32))
    t = bench("f32 matmul (4096,586)@(586,586)", f, x32b, C32b)
    print(f"   -> {B*586*586/t/1e12:.2f} TMAC/s")
    xbf = jnp.asarray(rng.standard_normal((4096,1024)).astype(jnp.bfloat16))
    Cbf = jnp.asarray(rng.standard_normal((1024,1024)).astype(jnp.bfloat16))
    f2 = jax.jit(lambda a,b: (a@b).astype(jnp.float32))
    t = bench("bf16 matmul 4096x1024x1024", f2, xbf, Cbf)
    print(f"   -> {4096*1024*1024/t/1e12:.2f} TMAC/s")
elif which == "i8":
    xi8 = jnp.asarray(rng.integers(0,127,(B,586)).astype(np.int8))
    Ci8 = jnp.asarray(rng.integers(0,127,(586,586)).astype(np.int8))
    def dg(a,b):
        return lax.dot_general(a,b,(((1,),(0,)),((),())), preferred_element_type=jnp.int32)
    f = jax.jit(dg)
    t = bench("int8 dot (4096,586)@(586,586)->int32", f, xi8, Ci8)
    print(f"   -> {B*586*586/t/1e12:.2f} TMAC/s")
elif which == "carry":
    xi = jnp.asarray(rng.integers(0, 2**30, (B, 373), dtype=np.int64).astype(np.int32))
    def carry_scan(x):
        def step(c, limb):
            t = limb + c
            return t >> 11, t & 2047
        _, out = lax.scan(step, jnp.zeros(x.shape[:-1], jnp.int32), jnp.moveaxis(x,-1,0))
        return jnp.moveaxis(out, 0, -1)
    bench("carry scan len373 B=4096", jax.jit(carry_scan), xi)
    def carry_roll2(x):
        for _ in range(2):
            hi = x >> 11
            x = (x & 2047) + jnp.pad(hi, ((0,0),(1,0)))[:, :-1]
        return x
    bench("carry 2xroll len373 B=4096", jax.jit(carry_roll2), xi)
elif which == "wide":
    from mpcium_tpu.core import bignum as bn
    prof11 = bn.LimbProfile(bits=11, n_limbs=373)
    xa = jnp.asarray(rng.integers(0,2047,(B,373)).astype(np.int32))
    xb = jnp.asarray(rng.integers(0,2047,(B,373)).astype(np.int32))
    f = jax.jit(lambda a,b: bn.mul_wide(a,b,prof11))
    bench("current mul_wide int32 4096b B=4096", f, xa, xb, n=3)
elif which == "conv":
    x32b = jnp.asarray(rng.integers(0,127,(512,586)).astype(np.float32))
    y32b = jnp.asarray(rng.integers(0,127,(512,586)).astype(np.float32))
    def perconv(x, y):
        Bn, n = x.shape
        lhs = x[None]
        rhs = y[:, None, ::-1]
        out = lax.conv_general_dilated(lhs, rhs, (1,), [(n-1, n-1)], feature_group_count=Bn)
        return out[0]
    f = jax.jit(perconv)
    t = bench("per-elt conv f32 n=586 B=512 (grouped)", f, x32b, y32b, n=3)
    print(f"   -> {512*586*586/t/1e12:.3f} TMAC/s useful")
