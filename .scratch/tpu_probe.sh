#!/bin/bash
# Probe until the axon TPU responds; log result.
for i in $(seq 1 600); do
  timeout 90 python -u -c "
import jax, jax.numpy as jnp
d = jax.devices()
x = jnp.ones((256,256))
jax.block_until_ready(jax.jit(lambda a: a@a)(x))
print('TPU-OK', d)
" >> /root/repo/.scratch/tpu_probe.log 2>&1 && { echo "RECOVERED at $(date)" >> /root/repo/.scratch/tpu_probe.log; exit 0; }
  echo "probe $i failed $(date)" >> /root/repo/.scratch/tpu_probe.log
  sleep 60
done
