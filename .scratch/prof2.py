import time, secrets
import numpy as np, jax, jax.numpy as jnp
from mpcium_tpu.core import bignum as bn

def timeit_host(f, *args, n=3):
    np.asarray(f(*args))  # compile + sync
    t0 = time.perf_counter()
    for _ in range(n):
        r = f(*args)
    r = np.asarray(r)  # host transfer forces full drain
    return (time.perf_counter() - t0) / n, r

for nbits in (2048, 4096):
    prof = bn.LimbProfile(bits=11, n_limbs=-(-nbits//11))
    mod = secrets.randbits(nbits) | (1 << (nbits-1)) | 1
    ctx = bn.BarrettCtx(mod, prof)
    B = 256
    xs = [secrets.randbelow(mod) for _ in range(B)]
    ys = [secrets.randbelow(mod) for _ in range(B)]
    x = jnp.asarray(bn.batch_to_limbs(xs, prof)); y = jnp.asarray(bn.batch_to_limbs(ys, prof))
    f = jax.jit(ctx.mulmod)
    t, r = timeit_host(f, x, y)
    ok = bn.from_limbs(r[0], prof) == xs[0]*ys[0] % mod
    print(f"mulmod {nbits}b B={B}: {t*1e3:.2f} ms ({t/B*1e6:.2f} us/op) correct={ok}")
    e_ints = [secrets.randbits(256) for _ in range(B)]
    ebits = jnp.asarray(np.stack([[(e>>i)&1 for i in range(256)] for e in e_ints]).astype(np.int32))
    f2 = jax.jit(ctx.powmod)
    t, r = timeit_host(f2, x, ebits, n=3)
    ok = bn.from_limbs(r[0], prof) == pow(xs[0], e_ints[0], mod)
    print(f"powmod256 {nbits}b B={B}: {t*1e3:.1f} ms ({B/t:.0f} exps/s) correct={ok}")
