"""Profile core bignum primitives on the real chip."""
import time, secrets
import numpy as np, jax, jax.numpy as jnp
from mpcium_tpu.core import bignum as bn

def timeit(f, *args, n=5):
    r = f(*args); jax.block_until_ready(r)  # compile
    t0 = time.perf_counter()
    for _ in range(n):
        r = f(*args)
    jax.block_until_ready(r)
    return (time.perf_counter() - t0) / n

P, Q2 = 1024, 2048
for nbits in (2048, 4096):
    prof = bn.LimbProfile(bits=11, n_limbs=-(-nbits//11))
    mod = secrets.randbits(nbits) | (1 << (nbits-1)) | 1
    ctx = bn.BarrettCtx(mod, prof)
    for B in (64, 256, 1024):
        x = jnp.asarray(bn.batch_to_limbs([secrets.randbelow(mod) for _ in range(B)], prof))
        y = jnp.asarray(bn.batch_to_limbs([secrets.randbelow(mod) for _ in range(B)], prof))
        f = jax.jit(ctx.mulmod)
        t = timeit(f, x, y)
        print(f"mulmod {nbits}b B={B}: {t*1e3:.2f} ms  ({B/t:.0f} ops/s, {t/B*1e6:.1f} us/op)")
    # powmod 256-bit exponent at B=256
    B = 256
    x = jnp.asarray(bn.batch_to_limbs([secrets.randbelow(mod) for _ in range(B)], prof))
    ebits = jnp.asarray(np.random.randint(0, 2, size=(B, 256), dtype=np.int32))
    f2 = jax.jit(ctx.powmod)
    t = timeit(f2, x, ebits, n=3)
    print(f"powmod256 {nbits}b B={B}: {t*1e3:.1f} ms ({B/t:.0f} exps/s)")
