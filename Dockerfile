# mpcium_tpu node/broker image (reference ships NATS+Consul via compose and
# installs the Go binaries on the host; here one image serves both roles).
FROM python:3.12-slim

WORKDIR /app
COPY pyproject.toml ./
COPY mpcium_tpu ./mpcium_tpu
RUN pip install --no-cache-dir -e . \
    && pip install --no-cache-dir "jax[cpu]" pyyaml cryptography

# nodes: mpcium-tpu start -n <name>   broker: mpcium-tpu broker
ENTRYPOINT ["mpcium-tpu"]
CMD ["--help"]
