"""Throughput benchmark: batched threshold signatures per second on one chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...extras}.

Flagship metric (BASELINE.md north star): batched 2-of-3 **secp256k1 GG18**
signing at full key size (2048-bit Paillier, default ZK exponent domains)
through the complete 9-round protocol — MtA with range proofs, phase-5
commit–reveal, final in-protocol ECDSA verification — with all hashing and
bignum work on device (engine.gg18_batch on ops.modmul MXU kernels).

Robust to backend flake (the round-2 lesson): the TPU backend is probed in
a SUBPROCESS with a timeout (a wedged axon relay hangs `import jax`
forever); on persistent failure the bench re-execs itself pinned to CPU
and still emits the JSON line with "platform": "cpu" — a degraded number
beats rc=1.

Env knobs: MPCIUM_BENCH_B (batch, default 1024), MPCIUM_BENCH_RUNS
(timed runs, default 1), MPCIUM_BENCH_NO_SECONDARY=1 (skip the ed25519
signing / batched DKG / batched resharing secondary metrics, which are
reported by default).
"""
from __future__ import annotations

import json
import os
import secrets
import subprocess
import sys
import time

BASELINE_SIGS_PER_SEC = 10_000.0
_PROBE = "import jax; d = jax.devices(); assert d[0].platform != 'cpu'"


def _probe_tpu(attempts: int = 3, timeout_s: int = 120) -> bool:
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE],
                timeout=timeout_s,
                capture_output=True,
            )
            if r.returncode == 0:
                return True
        except subprocess.TimeoutExpired:
            pass
        if i + 1 < attempts:
            time.sleep(15 * (i + 1))
    return False


def _ensure_backend() -> str:
    """Probe the TPU; on failure re-exec pinned to CPU (the axon
    sitecustomize must be stripped from PYTHONPATH or a wedged relay hangs
    the import itself). Returns the platform this process will use."""
    if os.environ.get("MPCIUM_BENCH_CHILD"):
        return os.environ.get("MPCIUM_BENCH_PLATFORM", "cpu")
    if _probe_tpu():
        os.environ["MPCIUM_BENCH_CHILD"] = "1"
        os.environ["MPCIUM_BENCH_PLATFORM"] = "tpu"
        return "tpu"
    env = dict(os.environ)
    env["MPCIUM_BENCH_CHILD"] = "1"
    env["MPCIUM_BENCH_PLATFORM"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":")
        if p and "axon" not in p
    )
    os.execve(sys.executable, [sys.executable] + sys.argv, env)
    raise RuntimeError("unreachable")


def main() -> None:
    platform = _ensure_backend()
    default_b = "1024" if platform == "tpu" else "8"
    # CPU fallback shrinks the batch: full-size GG18 at B=1024 is hours of
    # single-core arithmetic — a small-batch number with platform: "cpu"
    # is the honest degraded result (explicit MPCIUM_BENCH_B overrides)
    B = int(os.environ.get("MPCIUM_BENCH_B", default_b))
    runs = int(os.environ.get("MPCIUM_BENCH_RUNS", "1"))

    import jax

    jax.config.update("jax_compilation_cache_dir", 
                      os.path.join(os.path.dirname(__file__), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    import numpy as np

    from mpcium_tpu.cluster import load_test_preparams
    from mpcium_tpu.engine import gg18_batch as gb

    party_ids = ["node0", "node1", "node2"]
    t0 = time.perf_counter()
    shares = gb.dealer_keygen_secp_batch(B, party_ids, threshold=1)
    preparams = load_test_preparams()
    signer = gb.GG18BatchCoSigners(
        party_ids[:2], shares[:2], preparams, rng=secrets
    )
    setup_s = time.perf_counter() - t0
    digests = np.frombuffer(
        secrets.token_bytes(B * 32), dtype=np.uint8
    ).reshape(B, 32)

    # warmup: compile every kernel at this batch size
    t0 = time.perf_counter()
    out = signer.sign(digests)
    compile_s = time.perf_counter() - t0
    assert out["ok"].all(), "warmup GG18 signatures invalid"

    # one phase-profiled run (sync at phase boundaries)
    phases: dict = {}
    t0 = time.perf_counter()
    out = signer.sign(digests, phase_times=phases)
    profiled_s = time.perf_counter() - t0
    assert out["ok"].all()

    # timed runs (no internal sync)
    t0 = time.perf_counter()
    for _ in range(runs):
        out = signer.sign(digests)
        assert out["ok"].all()
    elapsed = time.perf_counter() - t0

    sigs_per_sec = runs * B / elapsed
    # secondary metrics (BASELINE configs 2/4/5) are emitted by DEFAULT;
    # MPCIUM_BENCH_NO_SECONDARY=1 opts out (quick flagship-only runs). A
    # secondary failure must not cost the flagship line.
    extra = {}
    if not os.environ.get("MPCIUM_BENCH_NO_SECONDARY"):
        try:
            extra = _secondary_metrics(B)
        except Exception as e:  # noqa: BLE001
            extra = {"secondary_error": repr(e)}
    if platform == "cpu":
        # degraded run (tunnel down): attach the most recent REAL on-chip
        # measurement, clearly labeled, so the flagship number isn't lost
        # to tunnel flake (BENCH_TPU_LATEST.json is updated by
        # .scratch/tpu_probe.sh after every successful on-chip bench)
        path = os.path.join(
            os.path.dirname(__file__), "BENCH_TPU_LATEST.json"
        )
        try:
            with open(path) as f:
                rec = json.load(f)
            rec["age_hours"] = round(
                (time.time() - os.path.getmtime(path)) / 3600, 1
            )
            extra["last_tpu_measurement"] = rec
        except FileNotFoundError:
            pass  # no on-chip record yet (fresh clone pre-first-probe)
        except Exception as e:  # noqa: BLE001 — corrupt record: surface it
            extra["last_tpu_measurement_error"] = repr(e)
    print(
        json.dumps(
            {
                "metric": "secp256k1_2of3_gg18_sigs_per_sec",
                "value": round(sigs_per_sec, 3),
                "unit": "signatures/sec",
                "vs_baseline": round(sigs_per_sec / BASELINE_SIGS_PER_SEC, 4),
                "platform": platform,
                "batch": B,
                "runs": runs,
                "setup_s": round(setup_s, 1),
                "compile_s": round(compile_s, 1),
                "profiled_run_s": round(profiled_s, 1),
                "phase_s": {k: round(v, 2) for k, v in phases.items()},
                **extra,
            }
        )
    )


def _secondary_metrics(B: int) -> dict:
    """BASELINE configs 2/4/5: ed25519 signing, batched DKG, batched
    resharing throughputs (on by default; MPCIUM_BENCH_NO_SECONDARY=1
    skips)."""
    import secrets as sec

    from mpcium_tpu.engine import eddsa_batch as eb
    from mpcium_tpu.engine.dkg_batch import BatchedDKG, BatchedReshare

    out = {}
    ids = ["node0", "node1", "node2"]

    shares = eb.dealer_keygen_batch(B, ids, 1, rng=sec)
    signer = eb.BatchedCoSigners(ids[:2], shares[:2], rng=sec)
    messages = [sec.token_bytes(32) for _ in range(B)]
    sigs, ok = signer.sign(messages)  # warmup/compile
    assert ok.all()
    t0 = time.perf_counter()
    sigs, ok = signer.sign(messages)
    out["ed25519_2of3_sigs_per_sec"] = round(
        B / (time.perf_counter() - t0), 1
    )

    dkg = BatchedDKG(ids, threshold=1, key_type="secp256k1", rng=sec)
    # warmup at the SAME batch shape: XLA kernels are shape-specialized,
    # so a smaller warmup left the timed run paying full recompiles
    # (r4 on-chip: 4.3 wallets/s reported where compute alone is far
    # higher)
    dkg.run(B)
    t0 = time.perf_counter()
    dshares = dkg.run(B)
    out["secp256k1_dkg_wallets_per_sec"] = round(
        B / (time.perf_counter() - t0), 1
    )

    Br = max(B // 4, 1)
    rs = BatchedReshare(
        ids[:2], [dshares[0][:Br], dshares[1][:Br]],
        ["node0", "node1", "node2", "node3", "node4"], new_threshold=2,
        rng=sec,
    )
    rs.run()  # warmup/compile at the timed shape
    t0 = time.perf_counter()
    rs.run()
    out["reshare_2of3_to_3of5_wallets_per_sec"] = round(
        Br / (time.perf_counter() - t0), 1
    )
    return out


if __name__ == "__main__":
    main()
