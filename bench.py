"""Throughput benchmark: batched threshold signatures per second on one chip.

Prints the flagship JSON line {"metric", "value", "unit", "vs_baseline", ...}
the MOMENT the flagship number is known; if secondary metrics complete, a
second (merged) line with the same metric name follows, so the last parseable
line of stdout is always the flagship metric.

Flagship metric (BASELINE.md north star): batched 2-of-3 **secp256k1 GG18**
signing at full key size (2048-bit Paillier, default ZK exponent domains)
through the complete 9-round protocol — MtA with range proofs, phase-5
commit–reveal, final in-protocol ECDSA verification — with all hashing and
bignum work on device (engine.gg18_batch on ops.modmul MXU kernels).

Robustness (the round-4 lesson — BENCH_r04.json was rc=124 with nothing
printed):
  * The TPU backend is probed in a SUBPROCESS with a timeout (a wedged axon
    relay hangs `import jax` forever); on persistent failure the bench
    re-execs itself pinned to CPU and still emits the JSON line with
    "platform": "cpu".
  * A hard WATCHDOG (MPCIUM_BENCH_WATCHDOG_S, default 2700 s) dumps the
    best-known record — the last real on-chip measurement if this run hasn't
    produced a number yet — and exits 0 before any outer timeout can kill
    the process silently.
  * The XLA compile cache is keyed by platform + host fingerprint for CPU
    runs: XLA:CPU AOT artifacts are machine-feature-stamped, and this
    container can be live-migrated mid-round, so foreign entries used to
    spam "could lead to SIGILL" warnings and occasionally crash the
    deserializer. TPU executables are not host-stamped and share one dir.
  * The CPU degraded path skips the phase-profiled duplicate run and the
    secondary metrics (a degraded small-batch number exists to beat rc=1,
    not to measure; MPCIUM_BENCH_SECONDARY=1 forces them back on).

Env knobs: MPCIUM_BENCH_B (batch, default 1024 tpu / 2 cpu),
MPCIUM_BENCH_RUNS (timed runs, default 1), MPCIUM_BENCH_NO_SECONDARY=1 /
MPCIUM_BENCH_SECONDARY=1 (secondary metrics off/on override),
MPCIUM_BENCH_NO_OT=1 (skip the OT-MtA variant's extra compile+sign pass
on TPU), MPCIUM_BENCH_WATCHDOG_S (watchdog deadline, 0 disables).
The OT variant also honors MPCIUM_OT_CHUNKS (pipeline chunking,
0/unset = auto) and MPCIUM_NATIVE_THREADS (host hash/transpose/PRG
thread count); its host-vs-device overlap lands in the bench JSON as
gg18_ot_mta_host_s / gg18_ot_mta_device_s / gg18_ot_mta_overlap_ratio.
The host-only extension-stage microbench is scripts/bench_ot_host.py.

Batch sweep: MPCIUM_BENCH_B_SWEEP="1024,4096,8192" appends a final
merged line; unset on TPU it defaults to the DEFAULT_B_SWEEP ladder
("1024,4096,8192,16384" — ISSUE 17 adds the 16384 bucket), and
MPCIUM_BENCH_B_SWEEP=none disables. "b_sweep" maps each batch size to either the measured
sigs/sec or a STRUCTURED DNF — {"dnf": true, "reason": "..."} — never a
bare prose string (the BENCH_TPU_OT B=8192 entry predates this and is
flagged by the ledger as unstructured). Each size runs in a fresh
subprocess with its own deadline (MPCIUM_BENCH_SWEEP_TIMEOUT_S, default
the watchdog deadline), so one superlinear size cannot starve the rest.
"""
from __future__ import annotations

import json
import os
import secrets
import subprocess
import sys
import threading
import time

BASELINE_SIGS_PER_SEC = 10_000.0
_PROBE = "import jax; d = jax.devices(); assert d[0].platform != 'cpu'"
_HERE = os.path.dirname(os.path.abspath(__file__))

# Shared with the watchdog thread. "record" is the most complete result so
# far; "printed" flips once the flagship line has been flushed to stdout.
_STATE: dict = {"record": None, "printed": False, "stage": "init"}


def _probe_tpu(attempts: int = 3, timeout_s: int = 120) -> bool:
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE],
                timeout=timeout_s,
                capture_output=True,
            )
            if r.returncode == 0:
                return True
        except subprocess.TimeoutExpired:
            pass
        if i + 1 < attempts:
            time.sleep(15 * (i + 1))
    return False


def _ensure_backend() -> str:
    """Probe the TPU; on failure re-exec pinned to CPU (the axon
    sitecustomize must be stripped from PYTHONPATH or a wedged relay hangs
    the import itself). Returns the platform this process will use."""
    if os.environ.get("MPCIUM_BENCH_CHILD"):
        return os.environ.get("MPCIUM_BENCH_PLATFORM", "cpu")
    if _probe_tpu():
        os.environ["MPCIUM_BENCH_CHILD"] = "1"
        os.environ["MPCIUM_BENCH_PLATFORM"] = "tpu"
        return "tpu"
    env = dict(os.environ)
    env["MPCIUM_BENCH_CHILD"] = "1"
    env["MPCIUM_BENCH_PLATFORM"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":")
        if p and "axon" not in p
    )
    os.execve(sys.executable, [sys.executable] + sys.argv, env)
    raise RuntimeError("unreachable")


def _host_fingerprint() -> str:
    """Short stable id for THIS host's CPU feature set. XLA:CPU AOT cache
    entries embed the compile machine's features; loading them on a
    different machine (container live-migration) warns or crashes.
    Delegates to perf/envfp (the canonical scheme the perf ledger groups
    by); imported lazily so the pre-backend phase stays import-free."""
    from mpcium_tpu.perf.envfp import host_fingerprint

    return host_fingerprint()


def _cache_dir(platform: str) -> str:
    if platform == "tpu":
        return os.path.join(_HERE, ".jax_cache")
    return os.path.join(_HERE, f".jax_cache_cpu_{_host_fingerprint()}")


def _load_last_tpu_record() -> dict | None:
    """Most recent REAL on-chip measurement (written by
    .scratch/tpu_probe.sh after every successful on-chip bench), for
    degraded/watchdog output. Age comes from the embedded measured_at
    stamp; file mtime is only a fallback (it resets on every checkout)."""
    path = os.path.join(_HERE, "BENCH_TPU_LATEST.json")
    try:
        with open(path) as f:
            rec = json.load(f)
    except FileNotFoundError:
        return None
    except Exception as e:  # noqa: BLE001 — corrupt record: surface it
        return {"corrupt": True, "error": repr(e)}
    try:
        if "measured_at" in rec:
            import calendar

            # measured_at is written with time.gmtime (UTC): decode with
            # timegm, not mktime (which would assume local time and skew
            # the staleness figure by the host's UTC offset)
            then = calendar.timegm(time.strptime(
                rec["measured_at"][:19], "%Y-%m-%dT%H:%M:%S"
            ))
        else:
            then = os.path.getmtime(path)
        rec["age_hours"] = round((time.time() - then) / 3600, 1)
        # explicit seconds-resolution staleness for the claims engine:
        # a claim satisfied only by this embedded record is `stale`
        rec["stale_s"] = round(time.time() - then, 1)
        if "measured_at" not in rec:
            rec["age_hours_is_mtime_guess"] = True
    except Exception:  # noqa: BLE001
        pass
    return rec


def _emit(record: dict) -> None:
    sys.stdout.write(json.dumps(record) + "\n")
    sys.stdout.flush()


def _arm_watchdog(platform: str) -> None:
    deadline = float(os.environ.get("MPCIUM_BENCH_WATCHDOG_S", "2700"))
    if deadline <= 0:
        return

    def _fire() -> None:
        time.sleep(deadline)
        # whatever we emit below is fresher than the process child's
        # arm-time snapshot: stand it down so its staler line cannot
        # shadow ours as the last parseable stdout line
        _mark_flagship_printed()
        if _STATE["record"] is not None:
            # This run produced a number — re-emit it even if "printed" is
            # already set: the main thread may sit BETWEEN setting the flag
            # and the actual write, and a duplicate flagship line is
            # harmless where rc=0-with-empty-stdout is not.
            _emit(_STATE["record"])
            os._exit(0)
        if _STATE["printed"]:
            os._exit(0)
        from mpcium_tpu.perf.envfp import env_fingerprint

        rec = {
            "metric": "secp256k1_2of3_gg18_sigs_per_sec",
            "value": 0.0,
            "unit": "signatures/sec",
            "vs_baseline": 0.0,
            "platform": platform,
            "watchdog_timeout": True,
            "watchdog_s": deadline,
            "elapsed_s": round(deadline, 1),
            "env": env_fingerprint(),
            "stage_reached": _STATE["stage"],
        }
        # loaded at FIRE time, not arm time, so age_hours is current.
        # The live "value" stays 0.0 — a watchdog line is NOT a
        # measurement, and a driver parsing only metric/value must not
        # take a stale number as this run's result; the cached record
        # rides along under last_tpu_measurement only.
        fallback = _load_last_tpu_record()
        if fallback and fallback.get("corrupt"):
            rec["last_tpu_measurement_error"] = fallback.get("error")
        elif fallback:
            rec["last_tpu_measurement"] = fallback
        _emit(rec)
        os._exit(0)

    threading.Thread(target=_fire, daemon=True, name="bench-watchdog").start()
    _arm_process_watchdog(platform, deadline)


_SENTINEL = os.path.join(
    "/tmp" if os.access("/tmp", os.W_OK) else _HERE,
    f".bench_flagship_printed.{os.getpid()}",
)

_CHILD_SRC = r"""
import json, os, sys, time
deadline = float(sys.argv[1]); sentinel = sys.argv[2]
ppid = int(sys.argv[3])


def parent_alive():
    try:
        os.kill(ppid, 0)
        return True
    except OSError:
        return False


def stood_down():
    if os.path.exists(sentinel):
        try:
            os.unlink(sentinel)
        except OSError:
            pass
        return True
    return False


t0 = time.time()
while time.time() - t0 < deadline:
    time.sleep(5)
    if stood_down():
        sys.exit(0)  # parent printed the flagship line
    if not parent_alive():
        # parent EXITED without a flagship line (crash, not a native
        # freeze): a fabricated success line would mask the failure,
        # and holding the inherited stdout open would block a driver
        # reading to EOF -- leave silently.
        sys.exit(0)
if stood_down() or not parent_alive():
    sys.exit(0)
# deadline reached with the parent still alive and silent: it is frozen
# in native code holding the GIL -- emit the best-known record for it.
rec = json.loads(os.environ["MPCIUM_BENCH_FALLBACK"])
rec["watchdog_timeout"] = True
rec["watchdog"] = "process"
rec["elapsed_s"] = round(time.time() - t0, 1)
sys.stdout.write(json.dumps(rec) + "\n")
sys.stdout.flush()
"""


def _arm_process_watchdog(platform: str, deadline: float) -> None:
    """Backstop for the THREAD watchdog: a forked child that shares our
    stdout but not our GIL. The round-5 lesson — a wedged remote-compile
    call can sit in native code HOLDING the GIL for the entire driver
    budget, so no Python thread (watchdog or signal handler) ever runs
    again; BENCH_r04-style rc=124-with-empty-stdout recurred at B=8192
    despite the thread watchdog. The child needs nothing from this
    process after the fork: it sleeps, checks the sentinel file the
    parent writes after the flagship line, and otherwise emits the
    best-known record itself."""
    from mpcium_tpu.perf.envfp import env_fingerprint

    rec = {
        "metric": "secp256k1_2of3_gg18_sigs_per_sec",
        "value": 0.0,
        "unit": "signatures/sec",
        "vs_baseline": 0.0,
        "platform": platform,
        # env stamped at ARM time (the child imports nothing from this
        # repo); the child stamps elapsed_s itself at fire time
        "env": env_fingerprint(),
        "stage_reached": "unknown (parent frozen in native code)",
    }
    # value stays 0.0 (same contract as the thread watchdog): the cached
    # on-chip record is surfaced only under last_tpu_measurement, never
    # as the live value of THIS run
    fallback = _load_last_tpu_record()
    if fallback and fallback.get("corrupt"):
        rec["last_tpu_measurement_error"] = fallback.get("error")
    elif fallback:
        rec["last_tpu_measurement"] = fallback
    env = dict(os.environ)
    env["MPCIUM_BENCH_FALLBACK"] = json.dumps(rec)
    # strip the axon plugin: the child imports nothing heavy, but keep
    # its startup trivially safe even if sitecustomize misbehaves
    env["PYTHONPATH"] = ""
    env.pop("JAX_PLATFORMS", None)
    try:
        os.unlink(_SENTINEL)  # a recycled-PID leftover would disarm us
    except OSError:
        pass
    try:
        subprocess.Popen(
            [sys.executable, "-c", _CHILD_SRC,
             str(deadline), _SENTINEL, str(os.getpid())],
            env=env,
            stdout=None,  # inherit: the driver reads OUR stdout
            stderr=subprocess.DEVNULL,
        )
    except OSError:
        pass  # thread watchdog remains the only backstop


def _mark_flagship_printed() -> None:
    try:
        with open(_SENTINEL, "w") as f:
            f.write("1")
    except OSError:
        pass


def main() -> None:
    platform = _ensure_backend()
    _arm_watchdog(platform)
    default_b = "1024" if platform == "tpu" else "2"
    # CPU fallback shrinks the batch: full-size GG18 at even B=8 is ~8 min
    # of single-core arithmetic after a ~30 min compile — B=2 is the
    # honest degraded result (explicit MPCIUM_BENCH_B overrides), and the
    # per-host cache is kept warm at B=2 so a fallback run stays ~2 min
    B = int(os.environ.get("MPCIUM_BENCH_B", default_b))
    runs = int(os.environ.get("MPCIUM_BENCH_RUNS", "1"))

    import jax

    jax.config.update("jax_compilation_cache_dir", _cache_dir(platform))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    import numpy as np

    from mpcium_tpu.cluster import load_test_preparams
    from mpcium_tpu.engine import gg18_batch as gb

    party_ids = ["node0", "node1", "node2"]
    _STATE["stage"] = "setup"
    t0 = time.perf_counter()
    shares = gb.dealer_keygen_secp_batch(B, party_ids, threshold=1)
    preparams = load_test_preparams()
    signer = gb.GG18BatchCoSigners(
        party_ids[:2], shares[:2], preparams, rng=secrets
    )
    setup_s = time.perf_counter() - t0
    digests = np.frombuffer(
        secrets.token_bytes(B * 32), dtype=np.uint8
    ).reshape(B, 32)

    # warmup: compile every kernel at this batch size
    _STATE["stage"] = "compile"
    t0 = time.perf_counter()
    out = signer.sign(digests)
    compile_s = time.perf_counter() - t0
    assert out["ok"].all(), "warmup GG18 signatures invalid"

    # one phase-profiled run (sync at phase boundaries) — skipped on the
    # degraded CPU path, where a duplicate full run costs minutes and
    # measures nothing the timed run doesn't. Phase shares come from the
    # tracing spans the engine emits (utils/tracing.PhaseTimer), folded
    # back into the legacy table shape by phase_share().
    phases: dict = {}
    profiled_s = 0.0
    idle_fraction = 0.0
    if platform == "tpu":
        from mpcium_tpu.perf import profile as perf_profile
        from mpcium_tpu.utils import tracing

        _STATE["stage"] = "profiled_run"
        spans: list = []
        profile_logdir = perf_profile.default_logdir(_HERE)
        tracing.enable(sink=spans.append)
        try:
            # MPCIUM_PROFILE=1 additionally captures the jax device
            # timeline for this run; no-op context otherwise
            with perf_profile.device_profile(profile_logdir) as profiling:
                t0 = time.perf_counter()
                out = signer.sign(digests)
                profiled_s = time.perf_counter() - t0
        finally:
            tracing.disable()
        assert out["ok"].all()
        phases = tracing.phase_share(spans)
        # span-derived pipeline health: fraction of the profiled window
        # with NO device phase in flight (ISSUE 17 zero-idle target);
        # kept out of phase_s so the 2-decimal rounding there cannot
        # flatten a small idle share to 0.00
        idle_fraction = tracing.device_idle_fraction(spans)
        if profiling:
            # fold per-phase device-op seconds from the captured profile
            # into the phase table (keys <phase>_device_op_s)
            phases.update(perf_profile.fold_device_ops(spans, profile_logdir))

    # timed runs (no internal sync)
    _STATE["stage"] = "timed_run"
    t0 = time.perf_counter()
    for _ in range(runs):
        out = signer.sign(digests)
        assert out["ok"].all()
    elapsed = time.perf_counter() - t0

    from mpcium_tpu.engine.pipeline import resolve_cohorts

    sigs_per_sec = runs * B / elapsed
    record = {
        "metric": "secp256k1_2of3_gg18_sigs_per_sec",
        "value": round(sigs_per_sec, 3),
        "unit": "signatures/sec",
        "vs_baseline": round(sigs_per_sec / BASELINE_SIGS_PER_SEC, 4),
        "platform": platform,
        "batch": B,
        "pipeline_cohorts": resolve_cohorts(B),
        "runs": runs,
        "mta": os.environ.get("MPCIUM_MTA", "paillier"),
        "setup_s": round(setup_s, 1),
        "compile_s": round(compile_s, 1),
        "profiled_run_s": round(profiled_s, 1),
        "device_idle_fraction": round(idle_fraction, 4),
        "phase_s": {k: round(v, 2) for k, v in phases.items()},
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime()),
    }
    # env fingerprint + compile ledger: which machine/toolchain/knob set
    # produced this number (the perf ledger's grouping key) and what the
    # warmup actually compiled vs deserialized from the persistent cache
    from mpcium_tpu.perf import compile_watch
    from mpcium_tpu.perf.envfp import env_fingerprint

    record["env"] = env_fingerprint()
    record["compile"] = compile_watch.health_summary()
    if platform == "cpu":
        last = _load_last_tpu_record()
        if last is not None and last.get("corrupt"):
            record["last_tpu_measurement_error"] = last.get("error")
        elif last is not None:
            record["last_tpu_measurement"] = last
    # Print the flagship line NOW — everything after this is bonus that
    # must not cost the round its number (round-4 failure mode). "printed"
    # flips BEFORE the emit: if the watchdog fires inside the window it
    # must not append a stale record AFTER the fresh flagship line (a
    # duplicate flagship line is harmless; shadowing it is not).
    _STATE["record"] = dict(record)
    _STATE["printed"] = True
    _emit(record)
    _mark_flagship_printed()

    # secondary metrics (BASELINE configs 2/4/5): on by default on TPU,
    # off by default on the degraded CPU path. A secondary failure or
    # straggle must not cost the flagship line (already printed above);
    # on completion a merged line re-states the flagship metric so the
    # LAST parseable stdout line still carries it.
    want_secondary = (
        os.environ.get("MPCIUM_BENCH_SECONDARY") == "1"
        or (platform == "tpu"
            and not os.environ.get("MPCIUM_BENCH_NO_SECONDARY"))
    )
    if want_secondary:
        _STATE["stage"] = "secondary"
        try:
            extra = _secondary_metrics(B)
        except Exception as e:  # noqa: BLE001
            extra = {"secondary_error": repr(e)}
        if extra:
            record.update(extra)
            _STATE["record"] = dict(record)
            _emit(record)

    # OT-MtA variant (MPCIUM_MTA=ot; SECURITY.md "OT-MtA"): measured as
    # a LABELED extra when the main run used the default Paillier MtA —
    # the honest flagship keeps tss-lib security parity, but the
    # variant's number belongs in the driver artifact too.
    if (platform == "tpu"
            and os.environ.get("MPCIUM_MTA", "paillier") == "paillier"
            and not os.environ.get("MPCIUM_BENCH_NO_OT")):
        _STATE["stage"] = "ot_variant"
        try:
            # MPCIUM_MTA is read per-instance in GG18BatchCoSigners
            # (gg18_batch.py), so flipping the env and constructing a
            # second signer is sufficient — no re-import involved
            os.environ["MPCIUM_MTA"] = "ot"
            signer_ot = gb.GG18BatchCoSigners(
                party_ids[:2], shares[:2], preparams, rng=secrets
            )
            out = signer_ot.sign(digests)  # warmup/compile
            assert out["ok"].all()
            t0 = time.perf_counter()
            out = signer_ot.sign(digests)
            assert out["ok"].all()
            checked_s = time.perf_counter() - t0
            record["gg18_ot_mta_sigs_per_sec"] = round(B / checked_s, 3)
            record["gg18_ot_mta_batch"] = B
            # one phase-profiled pass for the host/device A/B split of
            # the OT phase: r2_mta_ot_host (worker-thread IKNP time:
            # PRG + transpose + pad hashing), r2_mta_ot_device
            # (main-thread block time on device arrays) and the
            # pipeline's overlap ratio (fraction of host time hidden
            # behind device compute) — the chunked double-buffer's win,
            # measured rather than asserted.
            from mpcium_tpu.utils import tracing

            spans_ot: list = []
            tracing.enable(sink=spans_ot.append)
            try:
                out = signer_ot.sign(digests)
            finally:
                tracing.disable()
            assert out["ok"].all()
            phases_ot = tracing.phase_share(spans_ot)
            record["gg18_ot_mta_phase_s"] = {
                k: round(v, 3) for k, v in phases_ot.items()
            }
            record["gg18_ot_mta_device_idle_fraction"] = round(
                tracing.device_idle_fraction(spans_ot), 4
            )
            record["gg18_ot_mta_host_s"] = round(
                phases_ot.get("r2_mta_ot_host", 0.0), 3
            )
            record["gg18_ot_mta_device_s"] = round(
                phases_ot.get("r2_mta_ot_device", 0.0), 3
            )
            record["gg18_ot_mta_overlap_ratio"] = round(
                phases_ot.get("r2_mta_ot_overlap_ratio", 0.0), 3
            )
            record["gg18_ot_mta_chunks"] = int(
                phases_ot.get("r2_mta_ot_chunks", 1)
            )
            # checks-on vs checks-off A/B (ISSUE 16): the timed run
            # above paid the active-security check kernels (on by
            # default); one more timed run under MPCIUM_OT_CHECKS=0
            # isolates their cost. gg18_ot_checks_s is the per-batch
            # overhead the KOS + Gilboa + consistency checks add — the
            # number PERFORMANCE.md quotes for the passive escape
            # hatch. Env is read per sign() call, so flip + restore.
            prev_checks = os.environ.get("MPCIUM_OT_CHECKS")
            os.environ["MPCIUM_OT_CHECKS"] = "0"
            try:
                out = signer_ot.sign(digests)  # compile the passive path
                assert out["ok"].all()
                t0 = time.perf_counter()
                out = signer_ot.sign(digests)
                assert out["ok"].all()
                passive_s = time.perf_counter() - t0
            finally:
                if prev_checks is None:
                    os.environ.pop("MPCIUM_OT_CHECKS", None)
                else:
                    os.environ["MPCIUM_OT_CHECKS"] = prev_checks
            record["gg18_ot_checks_on_s"] = round(checked_s, 3)
            record["gg18_ot_checks_off_s"] = round(passive_s, 3)
            record["gg18_ot_checks_s"] = round(checked_s - passive_s, 3)
        except Exception as e:  # noqa: BLE001
            record["gg18_ot_mta_error"] = repr(e)
        finally:
            os.environ["MPCIUM_MTA"] = "paillier"
        _STATE["record"] = dict(record)
        _emit(record)

    _run_b_sweep(record)


def _parse_last_metric_line(stdout: bytes) -> dict | None:
    for line in reversed(stdout.decode(errors="replace").splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict) and "metric" in doc:
            return doc
    return None


def _b_sweep_entry(bsz: int, timeout_s: float) -> object:
    """One sweep point: re-exec this bench in a subprocess at batch bsz.
    Returns the measured sigs/sec (float) or a structured DNF dict —
    {"dnf": True, "reason": ...} — the only two shapes the perf ledger
    accepts without flagging the entry."""
    env = dict(os.environ)
    env.pop("MPCIUM_BENCH_B_SWEEP", None)  # no recursive sweeps
    env["MPCIUM_BENCH_B"] = str(bsz)
    # sweep points measure the flagship metric only
    env["MPCIUM_BENCH_NO_SECONDARY"] = "1"
    env["MPCIUM_BENCH_NO_OT"] = "1"

    # every DNF shape below is stamped with how long the point ran and
    # where (env fingerprint): a DNF in the ledger must be attributable
    # to a host/platform and a timing, not just a reason string
    from mpcium_tpu.perf.envfp import env_fingerprint

    t0 = time.time()

    def _dnf(reason: str) -> dict:
        return {
            "dnf": True,
            "reason": reason,
            "elapsed_s": round(time.time() - t0, 1),
            "env": env_fingerprint(),
        }

    try:
        r = subprocess.run(
            [sys.executable, os.path.join(_HERE, "bench.py")],
            env=env, timeout=timeout_s, capture_output=True,
        )
    except subprocess.TimeoutExpired:
        return _dnf(
            f"no metric line within {timeout_s:.0f}s — "
            "killed by sweep driver"
        )
    doc = _parse_last_metric_line(r.stdout)
    if doc is None:
        return _dnf(f"rc={r.returncode} with no parseable metric line")
    if doc.get("watchdog_timeout"):
        return _dnf(
            f"watchdog fired at {doc.get('watchdog_s', '?')}s "
            f"(stage: {doc.get('stage_reached', 'unknown')})"
        )
    value = doc.get("value")
    if isinstance(value, (int, float)) and value > 0:
        return round(float(value), 3)
    return _dnf(f"rc={r.returncode} with non-positive value {value!r}")


# Default sweep on TPU when MPCIUM_BENCH_B_SWEEP is unset: the ladder the
# perf ledger tracks round over round, now topped by the 16384 bucket
# (ISSUE 17). A size that wedges or times out lands as a structured DNF
# via _b_sweep_entry — never a missing key or a bare prose string.
DEFAULT_B_SWEEP = "1024,4096,8192,16384"


def _run_b_sweep(record: dict) -> None:
    """MPCIUM_BENCH_B_SWEEP: comma-separated batch sizes, each timed in
    its own subprocess; results land under record["b_sweep"] keyed by
    batch size, as numbers or structured DNFs. Unset on TPU → the
    DEFAULT_B_SWEEP ladder; "0"/"none" disables. The degraded CPU path
    never sweeps by default (each point re-pays a multi-minute compile)."""
    spec = os.environ.get("MPCIUM_BENCH_B_SWEEP", "").strip()
    if not spec and record.get("platform") == "tpu":
        spec = DEFAULT_B_SWEEP
    if not spec or spec.lower() in ("0", "none"):
        return
    _STATE["stage"] = "b_sweep"
    timeout_s = float(os.environ.get(
        "MPCIUM_BENCH_SWEEP_TIMEOUT_S",
        os.environ.get("MPCIUM_BENCH_WATCHDOG_S", "2700"),
    ))
    from mpcium_tpu.engine.buckets import bucket_b

    sweep: dict = {}
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        # snap to the pow-2 bucket grid (engine/buckets.py): an off-grid
        # sweep point would time a compile signature no production path
        # requests — the scheduler only ever emits floor_bucket chunks
        bsz = bucket_b(int(tok))
        if str(bsz) in sweep:
            continue
        sweep[str(bsz)] = _b_sweep_entry(bsz, timeout_s)
        # partial progress beats an empty field if a later size wedges
        record["b_sweep"] = dict(sweep)
        _STATE["record"] = dict(record)
    _emit(record)


def _secondary_metrics(B: int) -> dict:
    """BASELINE configs 2/4/5: ed25519 signing, batched DKG, batched
    resharing throughputs. Ed25519 runs at max(B, 4096) — BASELINE config
    2 is a 4096-wallet batch and the round-1 comparison point is B=4096."""
    import secrets as sec

    from mpcium_tpu.engine import eddsa_batch as eb
    from mpcium_tpu.engine.dkg_batch import BatchedDKG, BatchedReshare

    out = {}
    ids = ["node0", "node1", "node2"]

    Be = max(B, 4096) if B >= 256 else B
    shares = eb.dealer_keygen_batch(Be, ids, 1, rng=sec)
    signer = eb.BatchedCoSigners(ids[:2], shares[:2], rng=sec)
    messages = [sec.token_bytes(32) for _ in range(Be)]
    sigs, ok = signer.sign(messages)  # warmup/compile
    assert ok.all()
    t0 = time.perf_counter()
    sigs, ok = signer.sign(messages)
    out["ed25519_2of3_sigs_per_sec"] = round(
        Be / (time.perf_counter() - t0), 1
    )
    out["ed25519_batch"] = Be

    dkg = BatchedDKG(ids, threshold=1, key_type="secp256k1", rng=sec)
    # warmup at the SAME batch shape: XLA kernels are shape-specialized,
    # so a smaller warmup left the timed run paying full recompiles
    # (r4 on-chip: 4.3 wallets/s reported where compute alone is far
    # higher)
    dkg.run(B)
    t0 = time.perf_counter()
    dshares = dkg.run(B)
    out["secp256k1_dkg_wallets_per_sec"] = round(
        B / (time.perf_counter() - t0), 1
    )
    out["dkg_batch"] = B

    Br = max(B // 4, 1)
    rs = BatchedReshare(
        ids[:2], [dshares[0][:Br], dshares[1][:Br]],
        ["node0", "node1", "node2", "node3", "node4"], new_threshold=2,
        rng=sec,
    )
    rs.run()  # warmup/compile at the timed shape
    t0 = time.perf_counter()
    rs.run()
    out["reshare_2of3_to_3of5_wallets_per_sec"] = round(
        Br / (time.perf_counter() - t0), 1
    )
    out["reshare_batch"] = Br
    return out


if __name__ == "__main__":
    main()
