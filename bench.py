"""Throughput benchmark: batched threshold signatures per second on one chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

Current flagship metric: ed25519 2-of-3 threshold signatures/sec through the
full 3-round batched protocol (nonce commit+hash-commitment, decommit+
aggregate, challenge+partials+combine+verify — host hashing included, i.e.
end-to-end per-party work, not just the device kernels). The north-star
baseline is 10k sigs/sec (BASELINE.md: secp256k1 2-of-3 on one TPU v5e; the
reference's own path is sub-second *per* signature, serial). The metric will
switch to secp256k1 GG18 once the ECDSA engine lands.
"""
from __future__ import annotations

import json
import secrets
import time

import numpy as np

BASELINE_SIGS_PER_SEC = 10_000.0


def main() -> None:
    from mpcium_tpu.engine import eddsa_batch as eb

    B = 4096
    q, t = 2, 1
    party_ids = ["node0", "node1", "node2"]
    shares = eb.dealer_keygen_batch(B, party_ids, t, rng=secrets)
    signer = eb.BatchedCoSigners(party_ids[:q], shares[:q], rng=secrets)
    messages = [secrets.token_bytes(32) for _ in range(B)]

    # warmup: compile all kernels at this batch size
    sigs, ok = signer.sign(messages)
    assert ok.all(), "warmup signatures invalid"

    runs = 3
    start = time.perf_counter()
    for _ in range(runs):
        sigs, ok = signer.sign(messages)
        assert ok.all()
    elapsed = time.perf_counter() - start

    sigs_per_sec = runs * B / elapsed
    print(
        json.dumps(
            {
                "metric": "ed25519_2of3_threshold_sigs_per_sec",
                "value": round(sigs_per_sec, 1),
                "unit": "signatures/sec",
                "vs_baseline": round(sigs_per_sec / BASELINE_SIGS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
