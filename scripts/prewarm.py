#!/usr/bin/env python3
"""Pre-warm the compile cache from the committed compile surface.

Walks the warm manifest (COMPILE_SURFACE.json knobs × engine/buckets
pow-2 buckets, serving-reachable templates only, hot shapes first) and
drives each engine at each shape so the XLA persistent cache fills with
exactly the executables the serving set needs. A later daemon boot —
or `make prewarm` on a deploy host — then answers its first request
from the cache: the compile wall is paid once per host+toolchain.

Usage:
    python scripts/prewarm.py --schemes eddsa --max-b 64   # warm
    python scripts/prewarm.py --list                       # print work-list, no jax
    python scripts/prewarm.py --check                      # warmcheck gate, no jax

`--check` (the `make warmcheck` gate) verifies manifest enumeration ==
surface knobs × buckets with no silent gaps — pure stdlib, sub-second,
no backend import.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(_ROOT))

from mpcium_tpu.warm import manifest as wm  # noqa: E402


def _build(args):
    surface = wm.load_default_surface()
    knobs = wm.default_knobs(args.threshold)
    schemes = None
    if args.schemes:
        schemes = tuple(s.strip() for s in args.schemes.split(",") if s.strip())
    buckets = wm.BUCKETS
    if args.buckets:
        buckets = tuple(
            int(b) for b in args.buckets.split(",") if b.strip()
        )
    traffic = wm.load_traffic(
        args.ledger or os.path.join(str(_ROOT), "COMPILE_LEDGER.json"),
        args.history or os.path.join(str(_ROOT), "PERF_history.jsonl"),
    )
    return wm.build_manifest(
        surface, knobs, buckets=buckets, schemes=schemes,
        max_b=args.max_b, traffic=traffic,
    ), surface, knobs


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--schemes", default="",
                   help="comma list of eddsa,ecdsa,dkg,reshare (default all)")
    p.add_argument("--max-b", type=int, default=None,
                   help="largest batch bucket to warm (default: all 14)")
    p.add_argument("--buckets", default="",
                   help="explicit comma list of pow-2 buckets")
    p.add_argument("--threshold", "--q", type=int, default=None, dest="threshold",
                   help="mpc threshold t (warm quorum q = t+1; default 1)")
    p.add_argument("--budget-s", type=float, default=1800.0,
                   help="wall-clock budget; remaining entries are skipped")
    p.add_argument("--cache-dir", default="",
                   help="XLA persistent cache dir (default: "
                        "./warm_cache_<hostfp>)")
    p.add_argument("--ledger", default="",
                   help="COMPILE_LEDGER.json for traffic priority")
    p.add_argument("--history", default="",
                   help="PERF_history.jsonl for traffic priority")
    p.add_argument("--out", default="",
                   help="report dir for WARM_MANIFEST.json "
                        "(default: the cache dir)")
    p.add_argument("--list", action="store_true",
                   help="print the work-list and exit (no jax import)")
    p.add_argument("--check", action="store_true",
                   help="verify enumeration covers knobs × buckets with "
                        "no gaps; exit 1 on any problem (no jax import)")
    args = p.parse_args(argv)

    if args.check:
        surface = wm.load_default_surface()
        problems = wm.coverage_check(surface, wm.default_knobs(args.threshold))
        for prob in problems:
            print(f"WARM GAP: {prob}")
        man = wm.build_manifest(surface, wm.default_knobs(args.threshold))
        print(
            f"warmcheck: {man['counts']['entries']} signatures over "
            f"{man['counts']['serving_templates']} serving templates × "
            f"{man['counts']['buckets']} buckets — "
            f"{len(problems)} problem(s)"
        )
        return 1 if problems else 0

    manifest, _surface, _knobs = _build(args)
    if args.list:
        for e in manifest["entries"]:
            print(f"{e['engine']:16s} {e['shape']:32s} "
                  f"priority={e['priority']:.1f}")
        print(f"{manifest['counts']['entries']} entries")
        return 0

    # jax from here on: configure the cache, then walk
    from mpcium_tpu.warm import prewarm as pw

    cache_dir = args.cache_dir or os.path.join(
        os.getcwd(), f"warm_cache_{wm.envfp.host_fingerprint()}"
    )
    pw.configure_cache(cache_dir)
    report = pw.prewarm(
        manifest, args.budget_s, report_dir=args.out or cache_dir,
        aot_store=None,
    )
    t = report["totals"]
    print(json.dumps(t, indent=1, sort_keys=True))
    if t["unpredicted"]:
        print(
            f"WARNING: {t['unpredicted']} warmed shape(s) were NOT in "
            f"COMPILE_SURFACE.json — static surface drift; run "
            f"python scripts/mpcshape_surface.py"
        )
    print(f"report: {report.get('path', '(unwritten)')}")
    return 0 if t["failed"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
