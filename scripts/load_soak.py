#!/usr/bin/env python
"""Load-soak CLI (ISSUE 6): bursty mixed traffic against an in-process
cluster running the SLO scheduler, with a chaos fault plan active, and a
committed JSON report of what the cluster actually served.

    python scripts/load_soak.py                         # default soak
    python scripts/load_soak.py --out SOAK_r01.json     # committed report
    python scripts/load_soak.py --signs 256 --burst 32 --chaos batch-chaos
    python scripts/load_soak.py --chaos ""              # faults off

Exit status is non-zero when the accounting invariant fails — a request
that produced NO terminal outcome (success, retryable shed, or error) is
a silent drop, the one bug class this harness exists to catch.

Reproducibility: the report embeds the full config, the fault-plan seed
and rule set; rerunning with the same flags replays the same traffic
schedule and fault schedule.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# protocol math on CPU, mirroring tests/conftest.py: never touch a real
# accelerator here, and reuse the tests' persistent XLA compile cache so
# repeat soaks skip the minutes-long kernel compiles
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
if not os.environ.get("MPCIUM_TESTS_NO_CACHE"):
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(__file__), "..", ".jax_cache_tests"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def main() -> int:
    from mpcium_tpu.soak import SoakConfig, run_soak, write_report
    from mpcium_tpu.utils import log

    defaults = SoakConfig()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--signs", type=int, default=defaults.n_sign)
    ap.add_argument("--keygens", type=int, default=defaults.n_keygen)
    ap.add_argument("--reshares", type=int, default=defaults.n_reshare)
    ap.add_argument("--wallets", type=int, default=defaults.n_wallets)
    ap.add_argument("--nodes", type=int, default=defaults.n_nodes)
    ap.add_argument("--threshold", type=int, default=defaults.threshold)
    ap.add_argument("--burst", type=int, default=defaults.burst_size)
    ap.add_argument("--burst-gap", type=float, default=defaults.burst_gap_s)
    ap.add_argument("--seed", type=int, default=defaults.seed,
                    help="traffic-schedule seed")
    ap.add_argument("--chaos", default=defaults.chaos,
                    help='named fault plan (see faults/plan.py), "" = off')
    ap.add_argument("--chaos-seed", type=int, default=defaults.chaos_seed)
    ap.add_argument("--chaos-scale", type=float,
                    default=defaults.chaos_scale)
    ap.add_argument("--interactive-fraction", type=float,
                    default=defaults.interactive_fraction)
    ap.add_argument("--interactive-deadline-ms", type=int,
                    default=defaults.interactive_deadline_ms)
    ap.add_argument("--bulk-deadline-ms", type=int,
                    default=defaults.bulk_deadline_ms)
    ap.add_argument("--max-retries", type=int, default=defaults.max_retries)
    ap.add_argument("--window", type=float, default=defaults.batch_window_s)
    ap.add_argument("--max-batch", type=int, default=defaults.batch_max_batch)
    ap.add_argument("--max-queue-depth", type=int,
                    default=defaults.batch_max_queue_depth)
    ap.add_argument("--manifest-timeout", type=float,
                    default=defaults.manifest_timeout_s)
    ap.add_argument("--warmup", type=int, default=defaults.warmup_signs,
                    help="unmeasured pre-clock signs (absorb XLA compiles)")
    ap.add_argument("--timeout", type=float, default=defaults.wait_timeout_s)
    ap.add_argument("--out", default="",
                    help="write the JSON report here (default: stdout only)")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress cluster logs, print only the report")
    args = ap.parse_args()

    log.init(level="ERROR" if args.quiet else "INFO")
    cfg = SoakConfig(
        n_nodes=args.nodes,
        threshold=args.threshold,
        n_wallets=args.wallets,
        n_sign=args.signs,
        n_keygen=args.keygens,
        n_reshare=args.reshares,
        burst_size=args.burst,
        burst_gap_s=args.burst_gap,
        seed=args.seed,
        interactive_fraction=args.interactive_fraction,
        interactive_deadline_ms=args.interactive_deadline_ms,
        bulk_deadline_ms=args.bulk_deadline_ms,
        max_retries=args.max_retries,
        chaos=args.chaos,
        chaos_seed=args.chaos_seed,
        chaos_scale=args.chaos_scale,
        batch_window_s=args.window,
        batch_max_batch=args.max_batch,
        batch_max_queue_depth=args.max_queue_depth,
        manifest_timeout_s=args.manifest_timeout,
        warmup_signs=args.warmup,
        wait_timeout_s=args.timeout,
    )
    report = run_soak(cfg)
    # keep stdout reviewable: the embedded trace document is for Perfetto,
    # not eyeballs — elide it from the console copy only
    console = dict(report)
    trace = console.pop("trace", {})
    console["trace_events"] = len(trace.get("traceEvents", []))
    print(json.dumps(console, indent=2))
    if args.out:
        write_report(report, args.out)
        stem = os.path.splitext(args.out)[0]
        with open(stem + ".prom", "w") as f:
            f.write(report.get("prometheus", ""))
        print(f"report written to {args.out} "
              f"(+ {stem}.prom metrics sidecar)", file=sys.stderr)
    return 0 if report["accounting_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
