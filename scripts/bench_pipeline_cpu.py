#!/usr/bin/env python
"""CPU A/B proof for the zero-idle cohort pipeline (ISSUE 17).

Runs the real batched-Ed25519 engine at one batch size under K=1 (the
serial transcript oracle) and K=2 (counter-phase cohorts), with mpctrace
armed, and writes ``BENCH_pipeline_cpu.json``:

- signatures must be BYTE-identical across K (the transcript contract);
- the span-derived ``tracing.device_idle_fraction`` must be STRICTLY
  lower at K=2 — the host egress stages drain behind the other cohort's
  device rounds instead of extending the serial tail.

This is the degraded-host half of the round-10 ledger (the decision
numbers are TPU, measurement-owed on ROADMAP item 4); it exists so the
scheduling win is demonstrated, not asserted, on every host that can
run the tier-1 suite. Ed25519 is the vehicle because its kernels
compile in seconds on a 1-core CPU host where GG18's secp ladders need
minutes (test_gg18_batch.py policy); the K-sweep bit-identity of GG18
itself is tests/test_pipeline.py (slow tier).

With ``--device`` (the campaign's live-window step) the CPU pin is
skipped so the same A/B runs on whatever chip JAX finds, and ``--k``
widens the sweep (the owed matrix is K∈{1,2,4} at equal B).

Usage: JAX_PLATFORMS=cpu python scripts/bench_pipeline_cpu.py [--b 8]
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

if "--device" not in sys.argv:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _ROOT)

OUT_BASENAME = "BENCH_pipeline_cpu.json"


class DetRng:
    """Hash-counter CSPRNG stand-in (tests/test_pipeline.py fixture):
    identical seeds draw identical streams, so the K=1 and K=2 runs
    consume byte-identical nonce/blind material."""

    def __init__(self, seed: int):
        self.seed = seed
        self.ctr = 0

    def token_bytes(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            out += hashlib.sha256(
                b"pipebench|%d|%d" % (self.seed, self.ctr)
            ).digest()
            self.ctr += 1
        return bytes(out[:n])

    def randbelow(self, n: int) -> int:
        return int.from_bytes(self.token_bytes(40), "big") % n


def _one_run(ids, shares, messages, k: int):
    from mpcium_tpu.engine import eddsa_batch as eb
    from mpcium_tpu.utils import tracing

    signer = eb.BatchedCoSigners(ids[:2], shares[:2], rng=DetRng(42))
    spans: list = []
    tracing.enable(sink=spans.append)
    try:
        t0 = time.perf_counter()
        sigs, ok = signer.sign(messages, cohorts=k)
        wall_s = time.perf_counter() - t0
    finally:
        tracing.disable()
    import numpy as np

    assert np.asarray(ok).all(), f"K={k} produced invalid signatures"
    return {
        "sig_sha256": hashlib.sha256(
            np.asarray(sigs).tobytes()
        ).hexdigest(),
        "wall_s": round(wall_s, 4),
        "device_idle_fraction": round(
            tracing.device_idle_fraction(spans), 6
        ),
        "phase_s": {
            k2: round(v, 5) for k2, v in tracing.phase_share(spans).items()
        },
        "n_spans": len(spans),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--b", type=int, default=8, help="batch size (pow-2)")
    p.add_argument("--k", default="1,2",
                   help="comma list of cohort counts to A/B (K=1 first)")
    p.add_argument("--device", action="store_true",
                   help="skip the CPU pin — run on whatever JAX finds")
    p.add_argument("--lenient", action="store_true",
                   help="report but do not fail the idle comparison "
                        "(rehearsal: sub-ms CPU idle fractions are noise; "
                        "bit-identity stays a hard failure)")
    p.add_argument("--out", default=os.path.join(_ROOT, OUT_BASENAME))
    args = p.parse_args(argv)
    ks = sorted({int(x) for x in args.k.split(",") if x.strip()})
    if 1 not in ks:
        ks.insert(0, 1)  # K=1 is the serial oracle every K compares to

    import jax

    # share the tier-1 persistent compile cache: the proof shapes are
    # exactly the ones tests/test_pipeline.py compiles
    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(_ROOT, ".jax_cache_tests")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from mpcium_tpu.engine import eddsa_batch as eb
    from mpcium_tpu.perf.envfp import env_fingerprint

    B = args.b
    ids = ["n0", "n1", "n2"]
    shares = eb.dealer_keygen_batch(B, ids, 1, rng=DetRng(3))
    messages = [DetRng(9).token_bytes(32) for _ in range(B)]

    # warm every (K, width) compile signature OUTSIDE the measured runs
    for k in ks:
        signer = eb.BatchedCoSigners(ids[:2], shares[:2], rng=DetRng(42))
        _sigs, ok = signer.sign(messages, cohorts=k)
        assert ok.all()

    runs = {str(k): _one_run(ids, shares, messages, k) for k in ks}

    identical = all(
        runs[str(k)]["sig_sha256"] == runs["1"]["sig_sha256"] for k in ks
    )
    idle_1 = runs["1"]["device_idle_fraction"]
    idle_2 = runs["2"]["device_idle_fraction"] if 2 in ks else None
    doc = {
        "comment": (
            "CPU A/B proof of the counter-phase cohort pipeline "
            "(ISSUE 17, ROADMAP item 4): real batched-Ed25519 engine, "
            "K=1 serial oracle vs K=2 cohorts, mpctrace-armed. "
            "Signatures byte-identical; span-derived device idle "
            "fraction strictly lower at K=2. Degraded-host evidence "
            "only — TPU numbers are measurement-owed. Regenerate with "
            "scripts/bench_pipeline_cpu.py."
        ),
        "engine": "eddsa.sign",
        "batch": B,
        "cohorts": ks,
        "runs": runs,
        "signatures_bit_identical": identical,
        "idle_fraction_k1": idle_1,
        "idle_fraction_k2": idle_2,
        "idle_collapse_ratio": (
            round(idle_2 / idle_1, 4)
            if idle_1 and idle_2 is not None else None
        ),
        "env": env_fingerprint(),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime()),
    }
    for k in ks:
        doc[f"idle_fraction_k{k}"] = runs[str(k)]["device_idle_fraction"]
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps({k: v for k, v in doc.items() if k != "comment"}))
    if not identical:
        print("FAIL: signatures differ across K", file=sys.stderr)
        return 1
    if idle_2 is not None and not idle_2 < idle_1:
        verdict = (
            f"K=2 idle {idle_2} not below K=1 idle {idle_1}"
        )
        if not args.lenient:
            print(f"FAIL: {verdict}", file=sys.stderr)
            return 1
        print(f"warn (lenient): {verdict} — idle claim stays owed")
    print(f"ok: idle {idle_1} (K=1) -> {idle_2} (K=2), sigs identical")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
