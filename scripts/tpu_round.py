#!/usr/bin/env python
"""The ROADMAP item-1 round as ONE command: a resumable TPU campaign.

Inside a live TPU window::

    python scripts/tpu_round.py

executes the whole owed measurement matrix as a step DAG
(perf/campaign.py): the flagship bench with checks on/off and the
1024→16384 b_sweep, the pipeline K∈{1,2,4} idle A/B on chip, the
ed25519 device-hash bench, the ``bench_ot_host.py --device`` crossover,
and the two-process warm cold-boot proof. Each step runs in its own
subprocess under its own timeout (a hung step DNFs without killing the
window), and the state file is checkpointed after every step — a
preempted or re-opened window re-runs the same command and resumes
where it died. On completion the campaign report lands as
``CAMPAIGN_r<N>.json``, the perf history/dashboard regenerate, and the
claims ledger (perf/claims.py) re-evaluates — the round IS the verdict.

``--rehearse`` runs the same DAG, state machine, and verdict path on
CPU with tiny batches; the committed ``CAMPAIGN_rehearsal.json`` is the
proof the harness works end-to-end before a chip window is spent on it.

``--plan steps.json`` substitutes an explicit step list (tests use this
to SIGKILL and resume the real runner without paying bench time).

Internal step modes (the runner re-invokes this script): ``--warmboot``
(prewarm + cold-boot first-signature proof) and ``--ed25519`` (batched
Ed25519 sigs/s).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _ROOT)
sys.path.insert(1, _HERE)  # perfcheck import in ingest()

_PROBE = "import jax; d = jax.devices(); assert d[0].platform != 'cpu'"


def _probe_tpu(timeout_s: int = 120) -> bool:
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE],
            timeout=timeout_s, capture_output=True,
        )
        return r.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


# -- internal step modes -----------------------------------------------------


def run_ed25519(b: int) -> int:
    """Batched-Ed25519 device-hash bench: one warmed measured sign."""
    import secrets

    from mpcium_tpu.engine import eddsa_batch as eb
    from mpcium_tpu.perf.envfp import env_fingerprint

    ids = ["n0", "n1", "n2"]
    shares = eb.dealer_keygen_batch(b, ids, 1, rng=secrets)
    messages = [secrets.token_bytes(32) for _ in range(b)]
    signer = eb.BatchedCoSigners(ids[:2], shares[:2], rng=secrets)
    sigs, ok = signer.sign(messages)  # compile + warm
    assert ok.all()
    t0 = time.perf_counter()
    sigs, ok = signer.sign(messages)
    wall = time.perf_counter() - t0
    assert ok.all()
    print(json.dumps({
        "ed25519_2of3_sigs_per_sec": round(b / wall, 1) if wall else 0.0,
        "ed25519_batch": b,
        "wall_s": round(wall, 4),
        "env": env_fingerprint(),
    }))
    return 0


_BOOT_SNIPPET = r"""
import json, os, secrets, sys, time
import jax
from mpcium_tpu.warm import prewarm as pw
pw.configure_cache(sys.argv[1])
from mpcium_tpu.perf import compile_watch
from mpcium_tpu.engine import eddsa_batch as eb

b = int(sys.argv[2])
t0 = time.monotonic()
ids = [f"warm{i}" for i in range(3)]
shares = eb.dealer_keygen_batch(b, ids, 1, rng=secrets)
signer = eb.BatchedCoSigners(ids[:2], shares[:2], rng=secrets)
sigs, ok = signer.sign([bytes([i % 256]) * 32 for i in range(b)])
assert ok.all(), "warm boot produced invalid signatures"
entries = compile_watch.entries()
print("WARMBOOT_RESULT " + json.dumps({
    "first_sign_s": round(time.monotonic() - t0, 2),
    "cache_hits": sum(1 for e in entries if e["cache"] == "hit"),
    "cache_misses": sum(1 for e in entries if e["cache"] == "miss"),
    "entries": len(entries),
}))
"""


def run_warmboot(cache_dir: str, scheme: str, bucket: int,
                 budget_s: float) -> int:
    """The two-process cold-boot proof (tests/test_warm_boot.py shape):
    prewarm CLI populates the cache, then a COLD python process sharing
    only the cache dir signs once and reports first-signature latency
    plus its compile-ledger hit/miss split."""
    from mpcium_tpu.perf.envfp import env_fingerprint

    out_dir = os.path.dirname(os.path.abspath(cache_dir)) or "."
    r = subprocess.run(
        [sys.executable, os.path.join(_HERE, "prewarm.py"),
         "--schemes", scheme, "--buckets", str(bucket),
         "--cache-dir", cache_dir, "--out", out_dir],
        cwd=_ROOT, capture_output=True, text=True, timeout=budget_s,
    )
    if r.returncode != 0:
        print(json.dumps({
            "dnf": True,
            "reason": f"prewarm rc={r.returncode}: {r.stderr[-300:]}",
        }))
        return 1
    t0 = time.monotonic()
    r = subprocess.run(
        [sys.executable, "-c", _BOOT_SNIPPET, cache_dir, str(bucket)],
        cwd=_ROOT, capture_output=True, text=True, timeout=budget_s,
    )
    if r.returncode != 0:
        print(json.dumps({
            "dnf": True,
            "reason": f"cold boot rc={r.returncode}: {r.stderr[-300:]}",
        }))
        return 1
    line = next(
        (ln for ln in r.stdout.splitlines()
         if ln.startswith("WARMBOOT_RESULT ")), None,
    )
    if line is None:
        print(json.dumps({"dnf": True,
                          "reason": "cold boot printed no result line"}))
        return 1
    boot = json.loads(line[len("WARMBOOT_RESULT "):])
    print(json.dumps({
        "warmboot_first_sign_s": boot["first_sign_s"],
        "warmboot_cache_hits": boot["cache_hits"],
        "warmboot_cache_misses": boot["cache_misses"],
        "warmboot_entries": boot["entries"],
        "warmboot_wall_s": round(time.monotonic() - t0, 2),
        "scheme": scheme,
        "bucket": bucket,
        "env": env_fingerprint(),
    }))
    return 0


# -- plans -------------------------------------------------------------------


def _bench_parse(stdout: str) -> dict:
    from mpcium_tpu.perf.campaign import last_json_line

    doc = last_json_line(stdout)
    if "metric" not in doc:
        raise ValueError("bench printed JSON without a metric field")
    return doc


def default_plan(rehearse: bool, state_dir: str):
    """The owed matrix as steps. Rehearse = same DAG, CPU, tiny sizes;
    live = the real window budgets."""
    from mpcium_tpu.perf.campaign import Step

    py = sys.executable
    cpu_env = {"JAX_PLATFORMS": "cpu"} if rehearse else {}
    pipeline_out = os.path.join(state_dir, "pipeline_ab.json")
    warm_cache = os.path.join(state_dir, "warm_cache")

    if rehearse:
        # tiny-B CPU rehearsal: flagship skips the OT pass and the
        # secondary suite (each is exercised by its own step) so the
        # whole DAG completes inside the tier-2 budget
        flagship_env = dict(cpu_env, MPCIUM_BENCH_B="8",
                            MPCIUM_BENCH_RUNS="1",
                            MPCIUM_BENCH_B_SWEEP="none",
                            MPCIUM_BENCH_NO_OT="1",
                            MPCIUM_BENCH_NO_SECONDARY="1",
                            MPCIUM_BENCH_WATCHDOG_S="1500")
        return [
            Step("flagship", [py, os.path.join(_ROOT, "bench.py")],
                 env=flagship_env, timeout_s=1700, parse=_bench_parse,
                 cwd=_ROOT),
            Step("pipeline_ab",
                 [py, os.path.join(_HERE, "bench_pipeline_cpu.py"),
                  "--b", "8", "--k", "1,2", "--lenient",
                  "--out", pipeline_out],
                 env=cpu_env, timeout_s=900, cwd=_ROOT),
            Step("ed25519",
                 [py, os.path.abspath(__file__), "--ed25519", "--b", "8"],
                 env=cpu_env, timeout_s=600, cwd=_ROOT),
            Step("ot_crossover",
                 [py, os.path.join(_HERE, "bench_ot_host.py"),
                  "--m", "16384", "--runs", "1"],
                 env=cpu_env, timeout_s=600, cwd=_ROOT),
            Step("warm_boot",
                 [py, os.path.abspath(__file__), "--warmboot", warm_cache,
                  "--scheme", "eddsa", "--bucket", "2"],
                 env=cpu_env, timeout_s=900, cwd=_ROOT),
        ]
    # live window: checks on/off + default 1024→16384 sweep are inside
    # the flagship bench itself (bench.py emits gg18_ot_checks_* and the
    # b_sweep ladder on TPU by default)
    return [
        Step("flagship", [py, os.path.join(_ROOT, "bench.py")],
             env={"MPCIUM_BENCH_WATCHDOG_S": "2700"},
             timeout_s=3 * 3600, parse=_bench_parse, cwd=_ROOT),
        Step("pipeline_ab",
             [py, os.path.join(_HERE, "bench_pipeline_cpu.py"),
              "--device", "--b", "4096", "--k", "1,2,4",
              "--out", pipeline_out],
             timeout_s=3600, cwd=_ROOT),
        Step("ed25519",
             [py, os.path.abspath(__file__), "--ed25519", "--b", "4096"],
             timeout_s=1800, cwd=_ROOT),
        Step("ot_crossover",
             [py, os.path.join(_HERE, "bench_ot_host.py"), "--device"],
             timeout_s=1800, cwd=_ROOT),
        Step("warm_boot",
             [py, os.path.abspath(__file__), "--warmboot", warm_cache,
              "--scheme", "eddsa", "--bucket", "4096"],
             timeout_s=3600, cwd=_ROOT),
    ]


def load_plan(path: str):
    """Explicit plan file: a JSON list of Step kwargs (tests drive the
    real runner with trivial steps through this)."""
    from mpcium_tpu.perf.campaign import Step

    with open(path) as f:
        entries = json.load(f)
    return [
        Step(e["id"], e["argv"], env=e.get("env"),
             timeout_s=e.get("timeout_s", 600),
             needs=e.get("needs", ()), cwd=e.get("cwd"))
        for e in entries
    ]


# -- post-run ingestion ------------------------------------------------------


def _next_campaign_basename() -> str:
    import glob
    import re

    top = 0
    for p in glob.glob(os.path.join(_ROOT, "CAMPAIGN_r*.json")):
        m = re.search(r"_r(\d+)\.json$", p)
        if m:
            top = max(top, int(m.group(1)))
    return f"CAMPAIGN_r{top + 1:02d}.json"


def ingest(report_path: str) -> None:
    """Completion hook: the new artifact flows into the history, the
    dashboard, and a fresh claims evaluation — the campaign ends with
    verdicts, not raw JSON."""
    import perfcheck

    from mpcium_tpu.perf import claims, ledger

    perfcheck.regen_history()
    records = ledger.build_history(_ROOT)
    evaluated = claims.evaluate(records)
    with open(os.path.join(_ROOT, claims.CLAIMS_JSON), "w") as f:
        f.write(claims.render_json(evaluated))
    with open(os.path.join(_ROOT, claims.CLAIMS_MD), "w") as f:
        f.write(claims.render_md(evaluated))
    s = claims.summary(evaluated)
    print(f"claims: {s['claimed']} claimed, {s['owed']} owed, "
          f"{s['stale']} stale")
    for c in evaluated:
        mark = {"claimed": "+", "owed": "-", "stale": "~"}[c["status"]]
        print(f"  [{mark}] {c['id']}: {c['status']}")


# -- main --------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    p.add_argument("--rehearse", action="store_true",
                   help="full DAG on CPU with tiny batches (harness proof)")
    p.add_argument("--plan", help="explicit step-list JSON (tests)")
    p.add_argument("--state", help="campaign state file "
                   "(default <root>/.campaign/CAMPAIGN_state.json)")
    p.add_argument("--out", help="campaign report path")
    p.add_argument("--heartbeat", help=".prom heartbeat path")
    p.add_argument("--name", help="campaign name override")
    p.add_argument("--no-ingest", action="store_true",
                   help="skip history/dashboard/claims regeneration")
    # internal step modes
    p.add_argument("--ed25519", action="store_true")
    p.add_argument("--b", type=int, default=4096)
    p.add_argument("--warmboot", metavar="CACHE_DIR")
    p.add_argument("--scheme", default="eddsa")
    p.add_argument("--bucket", type=int, default=2)
    p.add_argument("--budget-s", type=float, default=1800.0)
    args = p.parse_args(argv)

    if args.ed25519:
        return run_ed25519(args.b)
    if args.warmboot:
        return run_warmboot(args.warmboot, args.scheme, args.bucket,
                            args.budget_s)

    from mpcium_tpu.perf.campaign import Campaign

    state_dir = os.path.dirname(os.path.abspath(args.state)) \
        if args.state else os.path.join(_ROOT, ".campaign")
    os.makedirs(state_dir, exist_ok=True)
    state_path = args.state or os.path.join(state_dir,
                                            "CAMPAIGN_state.json")
    heartbeat = args.heartbeat or os.path.join(state_dir,
                                               "campaign_heartbeat.prom")

    if args.plan:
        steps = load_plan(args.plan)
        name = args.name or "custom"
        out = args.out or os.path.join(state_dir, "CAMPAIGN_custom.json")
    elif args.rehearse:
        steps = default_plan(True, state_dir)
        name = args.name or "rehearsal"
        out = args.out or os.path.join(_ROOT, "CAMPAIGN_rehearsal.json")
    else:
        if not _probe_tpu():
            print("tpu_round: no TPU reachable — this command spends a "
                  "chip window; use --rehearse for the CPU harness "
                  "proof", file=sys.stderr)
            return 2
        steps = default_plan(False, state_dir)
        name = args.name or "tpu-round"
        out = args.out or os.path.join(_ROOT, _next_campaign_basename())

    campaign = Campaign(
        name, steps, state_path=state_path,
        rehearse=args.rehearse or bool(args.plan),
        heartbeat_path=heartbeat,
    )
    report = campaign.run()
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, out)
    print(f"campaign: report -> {out} "
          f"({report['steps_done']}/{report['steps_total']} steps, "
          f"{report['steps_dnf']} DNF)")

    # a COMPLETE live round also refreshes the on-chip latest record
    # (the flagship step's parsed line is exactly the BENCH_TPU_LATEST
    # shape the degraded-path fallback embeds)
    if not args.rehearse and not args.plan and report["complete"]:
        flagship = report["steps"].get("flagship") or {}
        if flagship.get("metric") and not flagship.get("dnf"):
            latest = {k: v for k, v in flagship.items()
                      if not k.startswith("_")}
            with open(os.path.join(_ROOT, "BENCH_TPU_LATEST.json"),
                      "w") as f:
                json.dump(latest, f, indent=1)
                f.write("\n")

    if not args.no_ingest:
        ingest(out)
    return 0 if report["complete"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
