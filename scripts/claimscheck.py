#!/usr/bin/env python3
"""Claims drift gate CLI: `make claimscheck`.

Check mode (default): registry hygiene (0 unknown metrics, 0
silently-untracked ROADMAP headline numbers) plus byte-drift of the
committed CLAIMS.json / CLAIMS.md against a fresh evaluation of the
artifact corpus. Exit 0 clean, 1 problems.

--regen: rewrite both renders from the corpus (run after adding an
artifact or a claim, then review the diff).
"""
from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from mpcium_tpu.perf import claims, ledger  # noqa: E402


def regen() -> int:
    records = ledger.build_history(_ROOT)
    problems = claims.registry_problems(records)
    for prob in problems:
        print(f"CLAIMS: {prob}")
    evaluated = claims.evaluate(records)
    for basename, text in ((claims.CLAIMS_JSON,
                            claims.render_json(evaluated)),
                           (claims.CLAIMS_MD, claims.render_md(evaluated))):
        with open(os.path.join(_ROOT, basename), "w") as f:
            f.write(text)
        print(f"wrote {basename}")
    s = claims.summary(evaluated)
    print(f"claims: {s['claimed']} claimed, {s['owed']} owed, "
          f"{s['stale']} stale")
    return 1 if problems else 0


def check() -> int:
    problems = claims.check_problems(_ROOT)
    for prob in problems:
        print(f"CLAIMS: {prob}")
    s = claims.summary(claims.evaluate(ledger.build_history(_ROOT)))
    print(f"claimscheck: {s['claimed']} claimed, {s['owed']} owed, "
          f"{s['stale']} stale — "
          f"{'%d problem(s)' % len(problems) if problems else 'clean'}")
    return 1 if problems else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--regen", action="store_true",
                   help="rewrite CLAIMS.json/CLAIMS.md from the corpus")
    args = p.parse_args(argv)
    return regen() if args.regen else check()


if __name__ == "__main__":
    raise SystemExit(main())
