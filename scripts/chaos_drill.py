#!/usr/bin/env python
"""Chaos drill CLI (ISSUE 3): run named fault-injection drills against an
in-process cluster and print their structured reports as JSON.

    python scripts/chaos_drill.py                       # the whole catalog
    python scripts/chaos_drill.py --plan partition      # one drill
    python scripts/chaos_drill.py --plan kill-resume    # SIGKILL + WAL resume
    python scripts/chaos_drill.py --seed 42 --plan drop-jitter
    python scripts/chaos_drill.py --list

Reproducibility: the report embeds the seed and the full fault-plan
JSON; rerunning with the same ``--seed --plan`` reproduces the identical
fault schedule (see mpcium_tpu/faults/plan.py). Exit status is non-zero
when any drill misses its expected outcome — CI-friendly.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# drills run protocol math on CPU; never touch a real accelerator here
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    from mpcium_tpu.faults.chaos import DEFAULT_SEED, DRILLS, run_drill
    from mpcium_tpu.utils import log

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=DEFAULT_SEED,
                    help=f"fault-schedule seed (default {DEFAULT_SEED})")
    ap.add_argument("--plan", "--drill", dest="plan", default="all",
                    help="drill name, or 'all' (default)")
    ap.add_argument("--scale", type=float, default=1.0,
                    help="time-constant scale for jitter windows "
                    "(probabilities never change; default 1.0)")
    ap.add_argument("--list", action="store_true", help="list drills")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress cluster logs, print only reports")
    args = ap.parse_args()

    if args.list:
        for name, (_fn, expected) in DRILLS.items():
            print(f"{name:18s} expected: {expected}")
        return 0

    log.init(level="ERROR" if args.quiet else "INFO")
    names = list(DRILLS) if args.plan == "all" else [args.plan]
    reports = []
    for name in names:
        r = run_drill(name, seed=args.seed, scale=args.scale)
        reports.append(r)
        print(json.dumps(r.to_json(), indent=2))
    failed = [r.name for r in reports if not r.ok]
    print(json.dumps({
        "seed": args.seed,
        "drills": len(reports),
        "passed": len(reports) - len(failed),
        "failed": failed,
    }))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
