"""Verify-drive: batched DKG -> batched signing (both curves) -> reshare
-> OpenSSL-verified signatures, over the public package surface, plus an
AEAD-encrypted broker roundtrip."""
import os

# mirror tests/conftest.py env so the warmed compile cache is reused
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
import os as _os

jax.config.update(
    "jax_compilation_cache_dir",
    _os.path.join(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
                  ".jax_cache_tests"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import faulthandler
import secrets
import signal
import threading
import time

faulthandler.register(signal.SIGUSR1)

from mpcium_tpu import wire
from mpcium_tpu.cluster import LocalCluster, load_test_preparams
from mpcium_tpu.engine import gg18_batch as gb

pre = load_test_preparams(bits=1024)
cluster = LocalCluster(
    n_nodes=3, threshold=1, preparams=pre, min_paillier_bits=1024,
    batch_signing=True, batch_window_s=0.2, reply_timeout_s=1800.0,
)
for ec in cluster.consumers:
    ec.scheduler.gg18_dom = gb.Domains(alpha=600, beta_prime=320, gamma_bob=600)
    ec.scheduler.manifest_timeout_s = 600.0

# ---- batched wallet creation (2 wallets in one manifest) -------------------
created = {}
done = threading.Event()
sub = cluster.client.on_wallet_creation_result(
    lambda ev: (created.__setitem__(ev.wallet_id, ev),
                len(created) == 2 and done.set())
)
cluster.client.create_wallet("vw0")
cluster.client.create_wallet("vw1")
assert done.wait(900), f"keygen incomplete: {list(created)}"
sub.unsubscribe()
for wid, ev in created.items():
    assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
kg_batches = sum(ec.scheduler.batches_run for ec in cluster.consumers)
print(f"[1] batched keygen OK: 2 wallets, batches_run={kg_batches} (3 nodes)")

# wait until EVERY node persisted both curves' shares (on this 1-core host
# the other nodes' finalize threads can lag the first success event by the
# cold-compile time; production redelivery budgets assume real hardware)
deadline = time.time() + 1200
while time.time() < deadline:
    try:
        for node in cluster.nodes.values():
            for wid in ("vw0", "vw1"):
                node.load_share("ed25519", wid)
                node.load_share("secp256k1", wid)
        break
    except Exception:
        time.sleep(2)
else:
    raise AssertionError("shares did not persist cluster-wide")
print("[1b] all 3 nodes hold both curves' shares for both wallets")

# ---- batched signing, both curves -----------------------------------------
results = {}
sdone = threading.Event()
sub = cluster.client.on_sign_result(
    lambda ev: (results.__setitem__(ev.tx_id, ev),
                len(results) == 4 and sdone.set())
)
txs = {}
for i, wid in enumerate(("vw0", "vw1")):
    for kt in ("ed25519", "secp256k1"):
        tx = secrets.token_bytes(32)
        tid = f"vtx-{kt}-{i}"
        txs[tid] = (wid, kt, tx)
        cluster.client.sign_transaction(wire.SignTxMessage(
            key_type=kt, wallet_id=wid, network_internal_code="x",
            tx_id=tid, tx=tx,
        ))
assert sdone.wait(1800), f"signing incomplete: {list(results)}"
sub.unsubscribe()

# independent verification via OpenSSL (cryptography)
from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec as _ec, utils
from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PublicKey
from mpcium_tpu.core import hostmath as hm

for tid, ev in results.items():
    wid, kt, tx = txs[tid]
    assert ev.result_type == wire.RESULT_SUCCESS, f"{tid}: {ev.error_reason}"
    if kt == "ed25519":
        pub = Ed25519PublicKey.from_public_bytes(
            bytes.fromhex(created[wid].eddsa_pub_key))
        pub.verify(bytes.fromhex(ev.signature), tx)  # raises on failure
    else:
        p = hm.secp_decompress(bytes.fromhex(created[wid].ecdsa_pub_key))
        key = _ec.EllipticCurvePublicNumbers(p.x, p.y, _ec.SECP256K1()).public_key()
        key.verify(
            utils.encode_dss_signature(int(ev.r, 16), int(ev.s, 16)),
            tx, _ec.ECDSA(utils.Prehashed(hashes.SHA256())),
        )
print("[2] batched signing OK: 4 sigs (2 ed25519 + 2 GG18), OpenSSL-verified")

# ---- batched resharing -----------------------------------------------------
rres = {}
rdone = threading.Event()
sub = cluster.client.on_resharing_result(
    lambda ev: (rres.__setitem__((ev.wallet_id, ev.key_type), ev),
                len(rres) == 2 and rdone.set())
)
cluster.client.resharing("vw0", 2, "ed25519")
cluster.client.resharing("vw1", 2, "ed25519")
assert rdone.wait(900), f"reshare incomplete: {list(rres)}"
sub.unsubscribe()
for k, ev in rres.items():
    assert ev.result_type == wire.RESULT_SUCCESS, f"{k}: {ev.error_reason}"
share = cluster.nodes["node0"].load_share("ed25519", "vw0")
assert share.epoch == 1 and share.threshold == 2

# sign after rotation
ev = cluster.sign_sync(wire.SignTxMessage(
    key_type="ed25519", wallet_id="vw0", network_internal_code="x",
    tx_id="vtx-post-reshare", tx=b"\x07" * 32,
), timeout_s=900)
assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
Ed25519PublicKey.from_public_bytes(
    bytes.fromhex(created["vw0"].eddsa_pub_key)
).verify(bytes.fromhex(ev.signature), b"\x07" * 32)
print("[3] batched reshare OK: epoch=1, t=2, post-rotation signature verifies")
cluster.close()

# ---- AEAD broker channel ---------------------------------------------------
from mpcium_tpu.transport.tcp import BrokerServer, tcp_transport

b = BrokerServer(port=0, auth_token="verify-token", encrypt=True)
t1 = tcp_transport(b.host, b.port, auth_token="verify-token", encrypt=True)
t2 = tcp_transport(b.host, b.port, auth_token="verify-token", encrypt=True)
got = []
evt = threading.Event()
t2.pubsub.subscribe("v.enc", lambda d: (got.append(d), evt.set()))
time.sleep(0.2)
t1.pubsub.publish("v.enc", b"over-the-wire")
assert evt.wait(5) and got == [b"over-the-wire"]
b.close()
print("[4] AEAD broker channel OK: encrypted pub/sub roundtrip")
print("VERIFY-DRIVE: ALL OK")
