#!/usr/bin/env python3
"""Run mpclint, the project-native static analyzer. See STATIC_ANALYSIS.md.

    python scripts/mpclint.py               # full sweep, gated on baseline
    python scripts/mpclint.py --list-rules
    make lint                               # ruff + mypy (if present) + this
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from mpcium_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
