#!/usr/bin/env python
"""One-off data migration: backfill the reshare `epoch` field.

Round-3 introduced epoch fencing for signing sessions (KeygenShare.epoch /
KeyInfo.epoch). Stores written by earlier builds lack the field; readers
default it to 0, but a mixed fleet (some nodes re-serializing with epoch,
some not) is easier to reason about after an explicit backfill — the
analogue of the reference's scripts/migration/{update-keyinfo,add-key-type}
(which prefixed legacy records in Consul/Badger).

Usage:
    python scripts/migration/add_epoch.py --db ./db/node0 \
        --control ./control --password <badger_password>

Idempotent: records that already carry `epoch` are left untouched.
"""
from __future__ import annotations

import argparse
import json
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--db", required=True, help="node share-store directory")
    ap.add_argument("--control", required=True, help="control-KV (FileKV) root")
    ap.add_argument("--password", required=True, help="share-store password")
    args = ap.parse_args()

    from mpcium_tpu.store.kvstore import EncryptedFileKV, FileKV

    migrated = 0
    kv = EncryptedFileKV(args.db, args.password)
    for key in kv.keys():
        if not (key.startswith("ecdsa:") or key.startswith("eddsa:")):
            continue
        rec = json.loads(kv.get(key))
        if "epoch" not in rec:
            rec["epoch"] = 0
            kv.put(key, json.dumps(rec).encode())
            migrated += 1

    ckv = FileKV(args.control)
    for key in ckv.keys():
        if not key.startswith("threshold_keyinfo/"):
            continue
        rec = json.loads(ckv.get(key))
        if "epoch" not in rec:
            rec["epoch"] = 0
            ckv.put(key, json.dumps(rec).encode())
            migrated += 1

    print(f"backfilled epoch=0 on {migrated} records")
    return 0


if __name__ == "__main__":
    sys.exit(main())
