#!/usr/bin/env bash
# Bootstrap a 3-node dev cluster's identities (reference setup_identities.sh):
# peers.json, per-node Ed25519 identities, registration into the control KV.
set -euo pipefail
cd "$(dirname "$0")/.."

N="${1:-3}"

mpcium-tpu-cli generate-peers -n "$N"
mpcium-tpu-cli register-peers

for i in $(seq 0 $((N - 1))); do
  mpcium-tpu-cli generate-identity --node "node$i" "${ENCRYPT:+--encrypt}"
done

echo "identities ready: $(ls identity/)"
echo "next: scripts/setup_initiator.sh, then 'make broker' and per-node"
echo "      'mpcium-tpu start -n node<i>' (one process per trust domain)"
