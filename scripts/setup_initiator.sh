#!/usr/bin/env bash
# Bootstrap the event-initiator identity and patch its pubkey into
# config.yaml (reference setup_initiator.sh).
set -euo pipefail
cd "$(dirname "$0")/.."

mpcium-tpu-cli generate-initiator "${ENCRYPT:+--encrypt}"

PUB=$(python - <<'EOF'
import json
print(json.load(open("event_initiator.json"))["public_key"])
EOF
)
touch config.yaml
if grep -q '^event_initiator_pubkey:' config.yaml; then
  sed -i "s/^event_initiator_pubkey:.*/event_initiator_pubkey: \"$PUB\"/" config.yaml
else
  echo "event_initiator_pubkey: \"$PUB\"" >> config.yaml
fi
echo "initiator registered in config.yaml: $PUB"
