#!/usr/bin/env python
"""mpctrace CI gate (`make trace-check`, folded into `make check`).

Three checks, all zero-dependency:

1. The committed TRACE_sample.json validates against the Chrome
   trace-event schema (trace/schema.py) and still covers every layer the
   tracing work instruments: scheduler intake/queue/dispatch, per-round
   protocol spans, session spans, device phases.
2. Transcript equality: the SAME deterministic batched-signing run,
   traced and untraced, produces byte-identical round transcripts and
   signatures — tracing must be observationally free.
3. (unless --no-sweep) the mpclint + mpcflow + mpcshape static gate via
   scripts/check_all.py — span attributes that hit the secret taxonomy
   must go through the declassify registry, never into the baseline.

`--regen` rebuilds TRACE_sample.json from a live miniature cluster run
(batch signing through the scheduler under the flight recorder), then
validates it. Regeneration is the slow path; plain validation is fast.

Exit codes: 0 clean, 1 any check failed.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _ROOT)

SAMPLE = os.path.join(_ROOT, "TRACE_sample.json")

# the layers the sample must witness (acceptance list of the tracing PR)
REQUIRED_SPAN_LAYERS = {
    "scheduler intake": lambda n: n == "intake",
    "scheduler queue": lambda n: n == "queue",
    "scheduler dispatch": lambda n: n == "dispatch",
    "protocol rounds": lambda n: n.startswith("round:"),
    "sessions": lambda n: n == "session",
    "device phases": lambda n: n.startswith("phase:"),
}


def _setup_cpu_jax() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    if not os.environ.get("MPCIUM_TESTS_NO_CACHE"):
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(_ROOT, ".jax_cache_tests"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def regen_sample() -> dict:
    """Rebuild TRACE_sample.json: a miniature batch-signing soak (no
    chaos) through the full cluster under the armed flight recorder —
    the same capture path drills and soaks embed."""
    _setup_cpu_jax()
    from mpcium_tpu.soak import SoakConfig, run_soak
    from mpcium_tpu.utils import log

    log.init(level="ERROR")
    report = run_soak(SoakConfig(
        n_nodes=3, threshold=1, n_wallets=2,
        n_sign=4, burst_size=4, burst_gap_s=0.05, seed=42,
        interactive_fraction=0.5,
        chaos="",  # the sample documents the span model, not chaos
        batch_window_s=0.2, wait_timeout_s=420.0,
    ))
    doc = report["trace"]
    doc["otherData"]["sample"] = (
        "regenerate with: python scripts/trace_check.py --regen"
    )
    with open(SAMPLE, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc


def check_sample() -> list:
    from mpcium_tpu.trace import validate_chrome

    errors = []
    try:
        with open(SAMPLE) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"TRACE_sample.json unreadable: {e!r} "
                f"(regenerate: python scripts/trace_check.py --regen)"]
    try:
        n = validate_chrome(doc)
    except Exception as e:  # noqa: BLE001 — collect, don't crash the gate
        return [f"TRACE_sample.json schema: {e}"]
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") != "M"}
    for layer, pred in REQUIRED_SPAN_LAYERS.items():
        if not any(pred(n) for n in names):
            errors.append(
                f"TRACE_sample.json: no span for layer {layer!r} "
                f"(have {sorted(names)[:12]}...)"
            )
    if not errors:
        print(f"trace-check: sample OK ({n} events, "
              f"{len(names)} span names)")
    return errors


def check_transcript_equality() -> list:
    """The same deterministic 2-party batched EdDSA signing run, traced
    and untraced: round transcripts and signatures must be identical."""
    _setup_cpu_jax()
    import random

    from mpcium_tpu.engine import eddsa_batch as eb
    from mpcium_tpu.protocol.eddsa.batch_signing import (
        BatchedEDDSASigningParty,
    )
    from mpcium_tpu.protocol.runner import run_protocol
    from mpcium_tpu.utils import tracing

    class DetRng:
        def __init__(self, seed):
            self._r = random.Random(seed)

        def token_bytes(self, n):
            return self._r.randbytes(n)

        def randbelow(self, n):
            return self._r.randrange(n)

    def one_run(traced):
        spans = []
        transcript = []
        shares = eb.dealer_keygen_batch(2, ["n0", "n1"], 1, rng=DetRng(5))
        if traced:
            tracing.enable(sink=spans.append)
        try:
            parties = {
                pid: BatchedEDDSASigningParty(
                    "trace-eq", pid, ["n0", "n1"], shares[i],
                    [b"a" * 32, b"b" * 32], rng=DetRng(11 + i),
                )
                for i, pid in enumerate(["n0", "n1"])
            }
            for p in parties.values():
                orig = p.receive

                def rec(m, _o=orig):
                    transcript.append(
                        (m.round, m.from_id, m.to, repr(m.payload))
                    )
                    return _o(m)

                p.receive = rec
            run_protocol(parties)
        finally:
            tracing.disable()
        sigs = {p: parties[p].result["signatures"].tobytes()
                for p in parties}
        return transcript, sigs, spans

    t_off, sig_off, s_off = one_run(False)
    t_on, sig_on, s_on = one_run(True)
    errors = []
    if s_off:
        errors.append("transcript-equality: spans emitted while disabled")
    if not s_on:
        errors.append("transcript-equality: no spans emitted while traced")
    if t_off != t_on:
        errors.append(
            "transcript-equality: traced run CHANGED the round transcript"
        )
    if sig_off != sig_on:
        errors.append(
            "transcript-equality: traced run CHANGED the signatures"
        )
    if not errors:
        print(f"trace-check: transcript equality OK "
              f"({len(t_off)} messages, {len(s_on)} spans)")
    return errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--regen", action="store_true",
                    help="rebuild TRACE_sample.json from a live run first")
    ap.add_argument("--no-sweep", action="store_true",
                    help="skip the mpclint/mpcflow/mpcshape sweep (already run by "
                         "the caller, e.g. make check)")
    args = ap.parse_args(argv)

    errors = []
    if args.regen:
        regen_sample()
    errors += check_sample()
    errors += check_transcript_equality()

    if not args.no_sweep:
        import check_all

        rc = check_all.main([])
        if rc != 0:
            errors.append(f"static sweep failed (check_all rc={rc})")

    for e in errors:
        print(f"TRACE-CHECK FAIL: {e}", file=sys.stderr)
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
