#!/usr/bin/env python3
"""Regenerate COMPILE_SURFACE.json from the mpcshape sweep.

The committed JSON is the static answer to "what is the complete set of
compile signatures this codebase can ever request?": per engine, the
compile_watch.begin template with every dimension classified
constant/knob/bucketed/unbounded, plus the jit entry-point inventory.
perf/compile_watch stamps runtime ledger entries predicted:true|false
against it, and the ROADMAP-item-4 AOT pre-warmer compiles exactly
these signatures. scripts/check_all.py fails when the committed file
drifts from the sweep, so run this after any change that adds an
engine, reshapes a signature, or re-annotates a dimension.

Usage:
    python scripts/mpcshape_surface.py           # rewrite the JSON
    python scripts/mpcshape_surface.py --check   # exit 1 on drift, write nothing
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(_ROOT))

from mpcium_tpu.analysis.shape import (  # noqa: E402
    SURFACE_BASENAME,
    render,
    run_shape,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--check",
        action="store_true",
        help="compare against the committed file instead of writing",
    )
    args = p.parse_args(argv)

    result, surface = run_shape(root=_ROOT)
    for f in result.findings:
        print(f.render())
    text = render(surface)
    out = _ROOT / SURFACE_BASENAME

    if args.check:
        if not out.exists():
            print(f"{SURFACE_BASENAME} missing — run scripts/mpcshape_surface.py")
            return 1
        if out.read_text() != text:
            print(f"{SURFACE_BASENAME} is stale — run scripts/mpcshape_surface.py")
            return 1
        print(f"{SURFACE_BASENAME} in sync")
        return 0

    out.write_text(text)
    c = surface["counts"]
    print(
        f"wrote {SURFACE_BASENAME}: {c['signatures']} signatures across "
        f"{c['engines']} engines, {c['jit_entries']} jit entries, "
        f"finite={c['finite']}"
    )
    return 1 if result.findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
