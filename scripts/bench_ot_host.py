"""Microbench: the host-side IKNP extension stage, thread-count A/B.

Measures exactly the work the OT-MtA pipeline hides behind device
compute — per-chunk PRG expansion of the three seed matrices
(t0/t1/tD), the U/Q xor assembly, the packed bit-matrix transpose and
the per-OT pad hashing for two payload sets — at M = 2^20 OTs
(B = 4096 signing lanes), pure host code, no JAX involved. Runs the
identical byte stream at MPCIUM_NATIVE_THREADS=1 and =N (default 4; the
thread knob is read per native call, so one process measures both) and
prints a JSON line with the speedup. Outputs are asserted bit-identical
across thread counts.

This is the CPU-measurable side of the ISSUE-2 acceptance gate: on a
multi-core host the threaded native path must cut the stage's
wall-clock >= 2x at 4 threads. On a single-core container (the
dev-loop host: nproc == 1) the ratio is honestly ~1.0x — the JSON
carries "cores" so the driver can tell the two apart.

--device adds the host-vs-device hash-suite A/B (ISSUE 11): each
extension sub-stage — PRG expansion, packed bit-transpose, pad
hashing — timed on the host/native path and on the ops.hash_suite
device kernels (warm, post-compile), outputs asserted bit-identical,
and the comparison emitted in the same JSON record under
ot_host_*/ot_device_* keys so the perf ledger (PERF_history.jsonl)
tracks the crossover. JAX is only imported in this mode; the default
host-only run stays JAX-free.

Usage: python scripts/bench_ot_host.py [--m 1048576] [--threads 4]
                                       [--device]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpcium_tpu import native  # noqa: E402
from mpcium_tpu.protocol.ecdsa import mta_ot  # noqa: E402

KAPPA = mta_ot.KAPPA


def _stage(seeds3, delta, delta_packed, delta_rows, r_packed, M, tag):
    """One full host extension stage: PRG x3, U/Q assembly, transpose +
    pads for two payload sets, both roles. Returns a digest of every
    output so the A/B runs can be asserted identical."""
    k0, k1, kD = seeds3
    n_bytes = M // 8
    t0 = mta_ot._prg(k0, n_bytes, tag)
    t1 = mta_ot._prg(k1, n_bytes, tag)
    U = native.xor_rows(t1, t0)            # t1 buffer becomes U
    native.xor_rows(U, r_packed)
    tD = mta_ot._prg(kD, n_bytes, tag)
    for r in delta_rows:
        tD[r] ^= U[r]                      # Q matrix, in place
    prefixes = [b"bench-pad|" + tag + b"|s%d" % s for s in range(2)]
    padsA = mta_ot._derive_pads_multi(prefixes, t0, M)
    padsB = mta_ot._derive_pads_multi(
        prefixes, tD, M, delta=delta_packed
    )
    acc = np.zeros(32, np.uint64)
    for p in padsA:
        acc += p[:64].astype(np.uint64).sum(axis=0)
    for p0, p1 in padsB:
        acc += p0[:64].astype(np.uint64).sum(axis=0)
        acc += p1[:64].astype(np.uint64).sum(axis=0)
    return U[:, :8].copy(), acc


def _timed(n_runs, *args):
    best = float("inf")
    digest = None
    for _ in range(n_runs):
        t0 = time.perf_counter()
        digest = _stage(*args)
        best = min(best, time.perf_counter() - t0)
    return best, digest


def _best_of(n_runs, fn):
    best = float("inf")
    out = None
    for _ in range(n_runs):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return best, out


def _device_ab(seeds3, M, tag, n_runs):
    """Per-sub-stage host vs device A/B: PRG, transpose, pads. Each
    device kernel is compiled once (warmup) and timed warm with
    block_until_ready; outputs are asserted bit-identical to the host
    path before any timing is reported."""
    import jax
    import jax.numpy as jnp

    from mpcium_tpu.ops import hash_suite as hs

    k0 = seeds3[0]
    n_bytes = M // 8
    nblk = -(-n_bytes // 32)
    prg_prefix = b"mpcium-ot-prg|" + tag
    pad_prefix = b"bench-pad|" + tag + b"|s0"

    # --- PRG expansion: (KAPPA, 32) seeds -> (KAPPA, M/8) keystream
    host_prg_s, t0_host = _best_of(
        n_runs, lambda: mta_ot._prg(k0, n_bytes, tag)
    )
    dev_prg = hs.prg_expand_device(prg_prefix, k0, nblk)  # compile
    dev_prg.block_until_ready()
    device_prg_s, dev_prg = _best_of(
        n_runs,
        lambda: hs.prg_expand_device(prg_prefix, k0, nblk)
        .block_until_ready(),
    )
    assert np.array_equal(
        np.asarray(dev_prg)[:, :n_bytes], t0_host
    ), "device PRG diverged from host PRG"

    # --- packed bit-transpose: (KAPPA, M/8) -> (M, KAPPA/8)
    def host_transpose():
        rows = native.ot_transpose(t0_host) if native.available() else None
        if rows is None:
            rows = mta_ot._pack(mta_ot._unpack(t0_host, M).T)
        return rows

    host_transpose_s, rows_host = _best_of(n_runs, host_transpose)
    t0_dev = jnp.asarray(t0_host)
    hs.ot_transpose_device(t0_dev).block_until_ready()  # compile
    device_transpose_s, rows_dev = _best_of(
        n_runs,
        lambda: hs.ot_transpose_device(t0_dev).block_until_ready(),
    )
    assert np.array_equal(
        np.asarray(rows_dev), rows_host
    ), "device transpose diverged from host transpose"

    # --- pad hashing: H(prefix || row || le32(j)) per OT -> (M, 32)
    idx = np.arange(M, dtype=np.uint32).view(np.uint8).reshape(M, 4)

    def host_pads():
        return mta_ot._hash_rows(
            pad_prefix, np.concatenate([rows_host, idx], axis=1)
        )

    host_pads_s, pads_host = _best_of(n_runs, host_pads)
    pref_dev = jnp.asarray(np.frombuffer(pad_prefix, np.uint8))
    rows_dev = jnp.asarray(rows_host)
    m_off = jnp.uint32(0)
    hs.pad_hash_device(pref_dev, rows_dev, m_off).block_until_ready()
    device_pads_s, pads_dev = _best_of(
        n_runs,
        lambda: hs.pad_hash_device(pref_dev, rows_dev, m_off)
        .block_until_ready(),
    )
    assert np.array_equal(
        np.asarray(pads_dev), pads_host
    ), "device pads diverged from host pads"

    host_total = host_prg_s + host_transpose_s + host_pads_s
    dev_total = device_prg_s + device_transpose_s + device_pads_s
    return {
        "device_platform": jax.devices()[0].platform,
        "ot_host_prg_s": round(host_prg_s, 4),
        "ot_device_prg_s": round(device_prg_s, 4),
        "ot_host_transpose_s": round(host_transpose_s, 4),
        "ot_device_transpose_s": round(device_transpose_s, 4),
        "ot_host_pads_s": round(host_pads_s, 4),
        "ot_device_pads_s": round(device_pads_s, 4),
        "ot_host_stage_s": round(host_total, 4),
        "ot_device_stage_s": round(dev_total, 4),
        "ot_device_stage_speedup": (
            round(host_total / dev_total, 3) if dev_total > 0 else 0.0
        ),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--m", type=int, default=1 << 20, help="OT count M")
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--runs", type=int, default=2)
    ap.add_argument(
        "--device", action="store_true",
        help="also A/B each sub-stage against the ops.hash_suite device "
             "kernels (imports JAX)",
    )
    args = ap.parse_args()

    rng = np.random.default_rng(42)
    seeds3 = tuple(
        rng.integers(0, 256, size=(KAPPA, 32), dtype=np.uint8)
        for _ in range(3)
    )
    delta = rng.integers(0, 2, size=KAPPA, dtype=np.uint8)
    delta_packed = np.packbits(delta, bitorder="little")
    delta_rows = np.nonzero(delta)[0]
    r_packed = rng.integers(0, 256, size=args.m // 8, dtype=np.uint8)
    stage_args = (
        seeds3, delta, delta_packed, delta_rows, r_packed, args.m, b"ab",
    )

    os.environ["MPCIUM_NATIVE_THREADS"] = "1"
    t_1, d_1 = _timed(args.runs, *stage_args)
    os.environ["MPCIUM_NATIVE_THREADS"] = str(args.threads)
    t_n, d_n = _timed(args.runs, *stage_args)
    os.environ.pop("MPCIUM_NATIVE_THREADS", None)

    assert np.array_equal(d_1[0], d_n[0]) and np.array_equal(
        d_1[1], d_n[1]
    ), "thread count changed the transcript"

    record = {
        "metric": "ot_host_extension_stage_speedup",
        "value": round(t_1 / t_n, 3) if t_n > 0 else 0.0,
        "unit": "x (1 thread / %d threads wall)" % args.threads,
        "m_ots": args.m,
        "threads": args.threads,
        "cores": os.cpu_count(),
        "native": native.available(),
        "stage_s_1thread": round(t_1, 3),
        "stage_s_nthread": round(t_n, 3),
    }
    if args.device:
        record.update(_device_ab(seeds3, args.m, b"ab", args.runs))
    print(json.dumps(record))


if __name__ == "__main__":
    main()
