#!/usr/bin/env python3
"""One-pass static gate: mpclint + mpcflow + mpcshape + artifact drift.

Parses the project AST exactly once (analysis/core.parse_project) and
hands the same ParsedFile list to all three analyzers — this is the
shared AST cache ``make check`` runs. Findings from all of them gate
against the one .mpclint-baseline.json (fail-closed both ways: new
findings fail AND stale entries fail), and the committed
HOST_TRANSFER_BUDGET.json and COMPILE_SURFACE.json must match their
sweeps byte-for-byte.

Exit codes: 0 clean, 1 violations/drift, 2 operator error.
"""
from __future__ import annotations

import json
import sys
import time
from pathlib import Path

_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(_ROOT))

from mpcium_tpu.analysis.baseline import (  # noqa: E402
    DEFAULT_BASELINE,
    BaselineError,
    load_baseline,
)
from mpcium_tpu.analysis.core import lint_parsed, parse_project  # noqa: E402
from mpcium_tpu.analysis.flow import build_budget, run_flow_parsed  # noqa: E402
from mpcium_tpu.analysis.rules import all_rules  # noqa: E402
from mpcium_tpu.analysis.shape import (  # noqa: E402
    SURFACE_BASENAME,
    run_shape_parsed,
)
from mpcium_tpu.analysis.shape import render as render_surface  # noqa: E402

from mpcflow_budget import BUDGET_FILE, render  # noqa: E402


def main(argv=None) -> int:
    out = sys.stdout
    t0 = time.monotonic()

    # one parse, three analyzers
    files, parse_errors = parse_project([_ROOT / "mpcium_tpu"], root=_ROOT)
    lint_result = lint_parsed(files, all_rules(), parse_errors=parse_errors)
    flow_result, sites = run_flow_parsed(files)
    shape_result, surface = run_shape_parsed(files)
    findings = (
        lint_result.findings + flow_result.findings + shape_result.findings
    )

    for err in parse_errors:
        out.write(f"PARSE ERROR: {err}\n")

    baseline_path = _ROOT / DEFAULT_BASELINE
    try:
        baseline = load_baseline(baseline_path)
    except BaselineError as e:
        out.write(f"BASELINE ERROR: {e}\n")
        return 2
    new, grandfathered, stale = baseline.split(findings)

    for f in new:
        out.write(f.render() + "\n")
    for fp in stale:
        out.write(
            f"STALE BASELINE ENTRY: {fp} — the finding no longer fires; "
            f"delete it from {baseline_path.name}\n"
        )

    budget_path = _ROOT / BUDGET_FILE
    budget_text = render(build_budget(sites))
    drifted = not budget_path.exists() or budget_path.read_text() != budget_text
    if drifted:
        out.write(
            f"BUDGET DRIFT: {BUDGET_FILE} does not match the sweep — "
            f"regenerate with scripts/mpcflow_budget.py and review the diff\n"
        )

    surface_path = _ROOT / SURFACE_BASENAME
    surface_text = render_surface(surface)
    surface_drifted = (
        not surface_path.exists()
        or surface_path.read_text() != surface_text
    )
    if surface_drifted:
        out.write(
            f"SURFACE DRIFT: {SURFACE_BASENAME} does not match the sweep — "
            f"regenerate with scripts/mpcshape_surface.py and review the diff\n"
        )

    # warmcheck off the same sweep's surface: the pre-warm work-list
    # (mpcium_tpu.warm.manifest) must enumerate exactly knobs × buckets —
    # a gap here means a serving shape the boot-time warm pass would
    # silently never compile
    from mpcium_tpu.warm.manifest import coverage_check, default_knobs

    warm_problems = coverage_check(surface, default_knobs())
    for prob in warm_problems:
        out.write(f"WARM GAP: {prob}\n")

    # claimscheck off the same pass: the committed CLAIMS.json/CLAIMS.md
    # must match a fresh evaluation of the artifact corpus, with no
    # unknown metrics and no ROADMAP headline left untracked
    from mpcium_tpu.perf import claims

    claims_problems = claims.check_problems(str(_ROOT))
    for prob in claims_problems:
        out.write(f"CLAIMS: {prob}\n")

    elapsed = time.monotonic() - t0
    out.write(
        f"check_all: {len(files)} files in {elapsed:.2f}s — "
        f"{len(new)} new, {len(grandfathered)} grandfathered, "
        f"{len(stale)} stale, budget "
        f"{'DRIFTED' if drifted else 'in sync'}, surface "
        f"{'DRIFTED' if surface_drifted else 'in sync'}, warm manifest "
        f"{f'{len(warm_problems)} GAP(S)' if warm_problems else 'covered'}, "
        f"claims "
        f"{f'{len(claims_problems)} PROBLEM(S)' if claims_problems else 'in sync'}\n"
    )
    return 1 if (
        new or stale or parse_errors or drifted or surface_drifted
        or warm_problems or claims_problems
    ) else 0


if __name__ == "__main__":
    raise SystemExit(main())
