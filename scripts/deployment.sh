#!/usr/bin/env bash
# Production node launcher (reference deployment_script.sh): pull secrets
# from the environment / secret manager into env-override config keys and
# exec the daemon. Never write secrets into config.yaml on disk.
set -euo pipefail

NODE_NAME="${1:?usage: deployment.sh <node-name>}"

: "${MPCIUM_BADGER_PASSWORD:?export MPCIUM_BADGER_PASSWORD (share-store key)}"
: "${MPCIUM_BROKER_TOKEN:?export MPCIUM_BROKER_TOKEN (broker auth)}"
export MPCIUM_ENVIRONMENT=production

exec mpcium-tpu start -n "$NODE_NAME" --decrypt-private-key
