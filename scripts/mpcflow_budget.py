#!/usr/bin/env python3
"""Regenerate HOST_TRANSFER_BUDGET.json from the mpcflow residency sweep.

The committed JSON is the per-phase ledger of every device→host
materialization on a protocol-hot path: 'intentional' sites carry a
'# mpcflow: host-ok' reason (wire boundaries), 'tracked' sites are
baselined debt tied to ROADMAP items. scripts/check_all.py fails when
the committed file drifts from the sweep, so run this after any change
that moves a host transfer.

Usage:
    python scripts/mpcflow_budget.py           # rewrite the JSON
    python scripts/mpcflow_budget.py --check   # exit 1 on drift, write nothing
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(_ROOT))

from mpcium_tpu.analysis.flow import build_budget, run_flow  # noqa: E402

BUDGET_FILE = "HOST_TRANSFER_BUDGET.json"


def render(budget: dict) -> str:
    return json.dumps(budget, indent=1, ensure_ascii=False) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--check",
        action="store_true",
        help="compare against the committed file instead of writing",
    )
    args = p.parse_args(argv)

    _, sites = run_flow(root=_ROOT)
    budget = build_budget(sites)
    text = render(budget)
    out = _ROOT / BUDGET_FILE

    if args.check:
        if not out.exists():
            print(f"{BUDGET_FILE} missing — run scripts/mpcflow_budget.py")
            return 1
        if out.read_text() != text:
            print(f"{BUDGET_FILE} is stale — run scripts/mpcflow_budget.py")
            return 1
        print(f"{BUDGET_FILE} in sync")
        return 0

    out.write_text(text)
    phases = budget["phases"]
    total = sum(ph["total_sites"] for ph in phases.values())
    tracked = sum(ph["tracked"] for ph in phases.values())
    print(
        f"wrote {BUDGET_FILE}: {total} sites across {len(phases)} phases "
        f"({tracked} tracked debt, {total - tracked} intentional)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
