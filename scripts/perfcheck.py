#!/usr/bin/env python
"""perfcheck: the statistical perf-regression gate (``make perfcheck``).

Runs the CPU-safe micro-benches in ``mpcium_tpu.perf.microbench`` and
compares them against the committed ``PERF_baseline_micro.json`` with
the Mann-Whitney + effect-floor + bootstrap-CI triple gate in
``mpcium_tpu.perf.statcheck``. Whole run stays under ~30 s.

Host honesty: the baseline is stamped with the host fingerprint it was
measured on. On a matching host the gate is STRICT (exit 1 on any
regression, after one retry to absorb a transient CI-box spike). On a
foreign host absolute timings are not comparable, so the comparison is
reported informationally and never fails the build — the tier-1 test
(`tests/test_perfcheck_gate.py`) still proves gate mechanics on every
host via a freshly measured self-baseline.

Flags:
  --samples N          per-bench samples (default 30)
  --update-baseline    re-measure and rewrite PERF_baseline_micro.json
  --inject-slowdown F  multiply current samples by F (demonstrates the
                       gate failing; used by CI self-test)
  --regen-history      rebuild PERF_history.jsonl + the dashboard from
                       the committed bench/soak/multichip artifacts
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _ROOT)

from mpcium_tpu.perf import ledger, microbench, report, statcheck  # noqa: E402
from mpcium_tpu.perf.envfp import host_fingerprint  # noqa: E402

BASELINE_FILE = os.path.join(_ROOT, "PERF_baseline_micro.json")
HISTORY_PATH = os.path.join(_ROOT, ledger.HISTORY_FILE)
DASHBOARD_PATH = os.path.join(_ROOT, "PERFORMANCE_dashboard.md")


def _load_baseline() -> dict | None:
    try:
        with open(BASELINE_FILE) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def _write_baseline(samples: int) -> int:
    benches = microbench.run_all(samples)
    doc = {
        "host": host_fingerprint(),
        "python": ".".join(str(v) for v in sys.version_info[:3]),
        "samples_per_bench": samples,
        "benches": {name: {"samples": vals}
                    for name, vals in sorted(benches.items())},
    }
    with open(BASELINE_FILE, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"perfcheck: baseline rewritten for host {doc['host']} "
          f"-> {os.path.basename(BASELINE_FILE)}")
    return 0


def regen_history() -> int:
    records = ledger.build_history(_ROOT)
    ledger.write_history(records, HISTORY_PATH)
    dashboard = report.render_dashboard(
        records, micro_baseline=_load_baseline()
    )
    with open(DASHBOARD_PATH, "w") as f:
        f.write(dashboard)
    print(f"perfcheck: {len(records)} artifact records -> "
          f"{os.path.basename(HISTORY_PATH)}, "
          f"{os.path.basename(DASHBOARD_PATH)}")
    return 0


def _run_gate(baseline: dict, samples: int, slowdown: float,
              strict: bool) -> statcheck.GateResult:
    currents = microbench.run_all(samples)
    if slowdown != 1.0:
        currents = {k: [v * slowdown for v in vals]
                    for k, vals in currents.items()}
    baselines = {name: b.get("samples") or []
                 for name, b in (baseline.get("benches") or {}).items()}
    result = statcheck.gate(baselines, currents)
    if strict and not result.ok:
        # one retry absorbs a transient spike (another process pinning
        # the box mid-measurement) without weakening the statistics: a
        # real regression reproduces, a scheduler burp does not
        retry_names = {v.bench for v in result.regressions}
        print("perfcheck: regression indicated — re-measuring "
              + ", ".join(sorted(retry_names)) + " once to confirm")
        currents2 = {name: microbench.ALL_BENCHES[name](samples)
                     for name in sorted(retry_names)
                     if name in microbench.ALL_BENCHES}
        if slowdown != 1.0:
            currents2 = {k: [v * slowdown for v in vals]
                         for k, vals in currents2.items()}
        confirm = statcheck.gate(
            {n: baselines[n] for n in currents2}, currents2
        )
        confirmed = {v.bench for v in confirm.regressions}
        for v in result.verdicts:
            if v.regressed and v.bench not in confirmed:
                v.regressed = False
                v.note = "regression not reproduced on retry"
        for v in confirm.verdicts:
            if v.regressed:
                for orig in result.verdicts:
                    if orig.bench == v.bench:
                        orig.note = "confirmed on retry"
    return result


def main(argv: list | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--samples", type=int, default=microbench.DEFAULT_SAMPLES)
    ap.add_argument("--update-baseline", action="store_true")
    ap.add_argument("--inject-slowdown", type=float, default=1.0,
                    metavar="F", help="multiply measured samples by F "
                    "(gate self-test)")
    ap.add_argument("--regen-history", action="store_true")
    args = ap.parse_args(argv)

    if args.regen_history:
        return regen_history()
    if args.update_baseline:
        return _write_baseline(args.samples)

    baseline = _load_baseline()
    if baseline is None:
        print("perfcheck: no PERF_baseline_micro.json committed — run "
              "--update-baseline first", file=sys.stderr)
        return 1

    here = host_fingerprint()
    strict = baseline.get("host") == here
    if not strict:
        print(f"perfcheck: baseline host {baseline.get('host')} != this "
              f"host {here} — informational comparison only (absolute "
              "micro timings are not portable across hosts)")

    result = _run_gate(baseline, args.samples, args.inject_slowdown, strict)
    for v in result.verdicts:
        print("perfcheck:", v.render())
    for note in result.notes:
        print("perfcheck: note:", note)

    if not strict:
        print("perfcheck: OK (foreign host — informational)")
        return 0
    if result.ok:
        print("perfcheck: OK — no regressions")
        return 0
    print(f"perfcheck: FAIL — {len(result.regressions)} regression(s)",
          file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
