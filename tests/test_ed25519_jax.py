"""Batched ed25519 JAX kernels vs hostmath ground truth."""
import secrets

import jax
import jax.numpy as jnp
import numpy as np

from mpcium_tpu.core import ed25519_jax as ej
from mpcium_tpu.core import hostmath as hm


def rand_scalars(n):
    return [secrets.randbelow(hm.ED_L) for _ in range(n)]


def host_points(ks):
    return [hm.ed_mul(k, hm.ED_B) for k in ks]


def test_add_matches_host():
    k1, k2 = rand_scalars(4), rand_scalars(4)
    p1 = ej.from_host(host_points(k1))
    p2 = ej.from_host(host_points(k2))
    out = ej.to_host(jax.jit(ej.add)(p1, p2))
    for a, b, got in zip(k1, k2, out):
        assert got.equals(hm.ed_mul((a + b) % hm.ED_L, hm.ED_B))


def test_add_identity_and_double():
    ks = rand_scalars(3)
    p = ej.from_host(host_points(ks))
    ident = ej.identity((3,))
    out = ej.to_host(ej.add(p, ident))
    for k, got in zip(ks, out):
        assert got.equals(hm.ed_mul(k, hm.ED_B))
    dbl = ej.to_host(ej.double(p))
    for k, got in zip(ks, dbl):
        assert got.equals(hm.ed_mul(2 * k % hm.ED_L, hm.ED_B))


def test_base_mul_matches_host():
    ks = rand_scalars(4) + [0, 1, hm.ED_L - 1]
    bits = jnp.asarray(ej.scalars_to_bits(ks))
    out = ej.to_host(jax.jit(ej.base_mul)(bits))
    for k, got in zip(ks, out):
        assert got.equals(hm.ed_mul(k, hm.ED_B)), k


def test_scalar_mul_variable_base():
    base_k = secrets.randbelow(hm.ED_L)
    base = ej.from_host(host_points([base_k] * 3))
    ks = rand_scalars(3)
    bits = jnp.asarray(ej.scalars_to_bits(ks))
    out = ej.to_host(jax.jit(ej.scalar_mul)(bits, base))
    for k, got in zip(ks, out):
        assert got.equals(hm.ed_mul(k * base_k % hm.ED_L, hm.ED_B))


def test_compress_matches_rfc8032():
    ks = rand_scalars(4) + [1]
    bits = jnp.asarray(ej.scalars_to_bits(ks))
    pts = jax.jit(ej.base_mul)(bits)
    comp = np.asarray(jax.jit(ej.compress)(pts))
    for k, row in zip(ks, comp):
        assert bytes(row.tolist()) == hm.ed_compress(hm.ed_mul(k, hm.ED_B))


def test_equal_batch():
    ks = rand_scalars(3)
    p = ej.from_host(host_points(ks))
    q = ej.from_host(host_points([ks[0], ks[1] + 1, ks[2]]))
    eq = np.asarray(ej.equal(p, q))
    assert list(eq) == [True, False, True]
