"""The ISSUE 13 acceptance proof, CPU-backed: a fresh-process boot with
a pre-populated warm cache answers its first batched sign with every
serving-set entry classified ``cache: hit`` — zero ``miss``. Process 1
is the real CLI (`scripts/prewarm.py`, the same walk `make prewarm` and
the daemon run); process 2 is a cold Python process that only shares
the cache directory on disk."""
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.perf

_ROOT = Path(__file__).resolve().parents[1]

# the serving set under proof: the drill/boot eddsa bucket
_SCHEMES = "eddsa"
_BUCKET = "2"

_BOOT_SNIPPET = r"""
import json, os, secrets, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from mpcium_tpu.warm import prewarm as pw
pw.configure_cache(sys.argv[1])
from mpcium_tpu.perf import compile_watch
from mpcium_tpu.engine import eddsa_batch as eb

t0 = time.monotonic()
ids = [f"warm{i}" for i in range(3)]
shares = eb.dealer_keygen_batch(2, ids, 1, rng=secrets)
signer = eb.BatchedCoSigners(ids[:2], shares[:2], rng=secrets)
sigs, ok = signer.sign([bytes([i]) * 32 for i in range(2)])
assert ok.all(), "warm boot produced invalid signatures"
print("WARMBOOT " + json.dumps({
    "first_sign_s": round(time.monotonic() - t0, 2),
    "entries": compile_watch.entries(),
}))
"""


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("MPCIUM_TESTS_NO_CACHE", None)
    return env


def _run(cmd, timeout):
    r = subprocess.run(
        cmd, cwd=str(_ROOT), env=_env(), capture_output=True, text=True,
        timeout=timeout,
    )
    assert r.returncode == 0, (
        f"{cmd} failed rc={r.returncode}:\n"
        f"{r.stdout[-3000:]}\n{r.stderr[-2000:]}"
    )
    return r


def test_fresh_process_boot_serves_from_cache(tmp_path):
    cache = str(tmp_path / "cache")

    # process 1: populate the cache through the real pre-warm CLI
    r = _run(
        [sys.executable, "scripts/prewarm.py", "--schemes", _SCHEMES,
         "--buckets", _BUCKET, "--cache-dir", cache,
         "--out", str(tmp_path)],
        timeout=420,
    )
    report = json.loads((tmp_path / "WARM_MANIFEST.json").read_text())
    assert report["totals"]["failed"] == 0
    assert report["totals"]["skipped"] == 0
    assert report["totals"]["warmed"] == report["totals"]["entries"] == 1
    assert os.listdir(cache), "pre-warm wrote nothing to the cache"

    # process 2: a cold boot sharing only the cache directory
    r = _run([sys.executable, "-c", _BOOT_SNIPPET, cache], timeout=420)
    line = next(
        ln for ln in r.stdout.splitlines() if ln.startswith("WARMBOOT ")
    )
    boot = json.loads(line[len("WARMBOOT "):])
    entries = boot["entries"]

    # every serving-set compile in the fresh process deserialized from
    # the warm cache: all hit, ZERO miss — the compile wall is gone
    assert entries, "fresh boot ledgered no compiles at all"
    assert all(e["cache"] == "hit" for e in entries), entries
    served = [e for e in entries if e["engine"] == "eddsa.sign"]
    assert len(served) == 1
    assert served[0]["shape"] == f"B{_BUCKET}|q2"
    assert served[0]["predicted"] is True
