"""Per-rule mpclint unit tests: positive + negative snippets per family,
the PR 4 `_started` publish-before-start race as a regression snippet,
suppression/annotation syntax, baseline mechanics, and the runtime side
of the wire-version contract.
"""
from __future__ import annotations

import textwrap
from pathlib import Path

import pytest

from mpcium_tpu import wire
from mpcium_tpu.analysis.baseline import Baseline, BaselineError, load_baseline
from mpcium_tpu.analysis.core import Finding, LintContext, ParsedFile
from mpcium_tpu.analysis.rules import all_rules
from mpcium_tpu.analysis.rules.determinism import (
    DictOrderIteration,
    ForbiddenEntropyCall,
)
from mpcium_tpu.analysis.rules.hygiene import (
    BareExcept,
    MutableDefaultArg,
    UnusedImport,
)
from mpcium_tpu.analysis.rules.jit_hazards import HostSyncInJit, TracedBranchInJit
from mpcium_tpu.analysis.rules.lock_discipline import (
    LockOrderInversion,
    UnguardedLockedField,
)
from mpcium_tpu.analysis.rules.secret_hygiene import (
    SecretCompare,
    SecretInException,
    SecretToLog,
)
from mpcium_tpu.analysis.rules.wire_thread import UnmanagedThread, WireVersionRoundTrip
from mpcium_tpu.analysis.taxonomy import is_compare_sensitive, is_secret_name
from mpcium_tpu.utils.annotations import locked_by

pytestmark = pytest.mark.lint

PROTO_REL = "mpcium_tpu/protocol/snippet.py"


def lint(src: str, rules, rel: str = PROTO_REL):
    """Run fresh rule instances over one dedented snippet."""
    pf = ParsedFile(Path(rel), rel, textwrap.dedent(src))
    ctx = LintContext([pf])
    out = []
    for rule in rules:
        if rule.applies(rel):
            out += [
                f
                for f in rule.check(pf, ctx)
                if not pf.is_suppressed(f.rule, f.line)
            ]
    for rule in rules:
        out += [
            f
            for f in rule.finalize(ctx)
            if not pf.is_suppressed(f.rule, f.line)
        ]
    return out


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# -- taxonomy ---------------------------------------------------------------


def test_taxonomy_secret_names():
    for name in ("share", "old_share", "wal_key", "seed", "otk_pads", "sk"):
        assert is_secret_name(name), name
    for name in ("pub_key", "public_key", "wallet_id", "share_count", "tx_id",
                 "secrets", "hashed_share"):
        assert not is_secret_name(name), name
    assert is_compare_sensitive("auth_tag")
    assert is_compare_sensitive("share")
    assert not is_compare_sensitive("wallet_id")


def test_secret_annotation_registers_extra_names():
    src = """
    def f():
        blob = derive()  # mpclint: secret
        log.info("derived", blob=blob)
    """
    found = lint(src, [SecretToLog()])
    assert rule_ids(found) == ["MPL101"]


# -- MPL1xx secret hygiene --------------------------------------------------


def test_secret_to_log_positive_and_negative():
    bad = """
    def f(share):
        log.info("round done", share=share.hex())
    """
    assert rule_ids(lint(bad, [SecretToLog()])) == ["MPL101"]
    ok = """
    def f(share, wallet_id):
        log.info("round done", wallet=wallet_id, n=1)
    """
    assert lint(ok, [SecretToLog()]) == []


def test_secret_in_exception():
    bad = """
    def f(seed):
        raise ValueError(f"bad seed {seed!r}")
    """
    assert rule_ids(lint(bad, [SecretInException()])) == ["MPL102"]
    ok = """
    def f(seed):
        raise ValueError("bad seed (redacted)")
    """
    assert lint(ok, [SecretInException()]) == []


def test_secret_compare():
    bad = """
    def f(tag, expect):
        if tag != expect:
            raise ValueError("bad mac")
    """
    assert rule_ids(lint(bad, [SecretCompare()])) == ["MPL103"]
    ok = """
    import hmac
    def f(tag, expect):
        if not hmac.compare_digest(tag, expect):
            raise ValueError("bad mac")
    """
    assert lint(ok, [SecretCompare()]) == []
    # non-sensitive compares don't fire
    ok2 = """
    def f(count, other):
        return count == other
    """
    assert lint(ok2, [SecretCompare()]) == []


# -- MPL2xx determinism -----------------------------------------------------


def test_forbidden_entropy_scoped_to_protocol():
    bad = """
    import time
    def decide():
        return time.time()
    """
    assert rule_ids(lint(bad, [ForbiddenEntropyCall()])) == ["MPL201"]
    # time.monotonic is allowed (duration measurement, not decisions)
    ok = """
    import time
    def decide():
        return time.monotonic()
    """
    assert lint(ok, [ForbiddenEntropyCall()]) == []
    # out of scope: same code elsewhere in the package is not flagged
    assert lint(bad, [ForbiddenEntropyCall()], rel="mpcium_tpu/utils/x.py") == []


def test_dict_order_iteration():
    bad = """
    def route(peers):
        for p in peers:
            send(p)
        return [p for p, v in peers.items()]
    """
    found = lint(bad, [DictOrderIteration()])
    assert rule_ids(found) == ["MPL202"] and len(found) == 2
    ok = """
    def route(peers):
        for p in sorted(peers):
            send(p)
    """
    assert lint(ok, [DictOrderIteration()]) == []


# -- MPL3xx lock discipline -------------------------------------------------


def test_locked_field_pr4_started_race_regression():
    # PR 4's bug: consumer published the session (checking `_started`)
    # before start() ran — a write to the guarded flag outside the lock
    bad = """
    from mpcium_tpu.utils.annotations import locked_by

    @locked_by("_lock", "_started")
    class Session:
        def start(self):
            self._started = True
    """
    found = lint(bad, [UnguardedLockedField()])
    assert rule_ids(found) == ["MPL301"]
    assert found[0].key == "_started"
    ok = """
    from mpcium_tpu.utils.annotations import locked_by

    @locked_by("_lock", "_started")
    class Session:
        def __init__(self):
            self._started = False  # unpublished: exempt
        def start(self):
            with self._lock:
                self._started = True
        def _flip(self):  # mpclint: holds=_lock
            self._started = True
    """
    assert lint(ok, [UnguardedLockedField()]) == []


def test_locked_field_catches_container_mutation():
    bad = """
    from mpcium_tpu.utils.annotations import locked_by

    @locked_by("_lock", "_buffer")
    class S:
        def push(self, m):
            self._buffer.append(m)
    """
    assert rule_ids(lint(bad, [UnguardedLockedField()])) == ["MPL301"]


def test_locked_field_one_level_delegation():
    """Regression: an unmarked private helper whose every same-class call
    site holds the declared lock is effectively ``holds=_lock`` — no
    false positive, and no ``# mpclint: holds=`` marker required."""
    ok = """
    from mpcium_tpu.utils.annotations import locked_by

    @locked_by("_lock", "_started")
    class Session:
        def start(self):
            with self._lock:
                self._flip()
        def restart(self):
            with self._lock:
                self._flip()
        def _flip(self):
            self._started = True
    """
    assert lint(ok, [UnguardedLockedField()]) == []
    # one call site does NOT hold the lock → still a finding
    bad = """
    from mpcium_tpu.utils.annotations import locked_by

    @locked_by("_lock", "_started")
    class Session:
        def start(self):
            with self._lock:
                self._flip()
        def hot_path(self):
            self._flip()
        def _flip(self):
            self._started = True
    """
    found = lint(bad, [UnguardedLockedField()])
    assert rule_ids(found) == ["MPL301"]
    assert found[0].key == "_started"
    # the exemption does not chain: a helper reached only through a
    # second unmarked helper keeps its finding (one level only)
    two_deep = """
    from mpcium_tpu.utils.annotations import locked_by

    @locked_by("_lock", "_started")
    class Session:
        def start(self):
            with self._lock:
                self._mid()
        def _mid(self):
            self._flip()
        def _flip(self):
            self._started = True
    """
    assert rule_ids(lint(two_deep, [UnguardedLockedField()])) == ["MPL301"]
    # a public (no leading underscore) method never gets the exemption
    public = """
    from mpcium_tpu.utils.annotations import locked_by

    @locked_by("_lock", "_started")
    class Session:
        def start(self):
            with self._lock:
                self.flip()
        def flip(self):
            self._started = True
    """
    assert rule_ids(lint(public, [UnguardedLockedField()])) == ["MPL301"]


def test_lock_order_inversion_cycle():
    bad = """
    class S:
        def a(self):
            with self._lock:
                with self._cond:
                    pass
        def b(self):
            with self._cond:
                with self._lock:
                    pass
    """
    assert rule_ids(lint(bad, [LockOrderInversion()])) == ["MPL302"]
    # consistent global order: no cycle
    ok = """
    class S:
        def a(self):
            with self._lock:
                with self._cond:
                    pass
        def b(self):
            with self._lock:
                with self._cond:
                    pass
    """
    assert lint(ok, [LockOrderInversion()]) == []
    # release-before-callback (the timing-wheel pattern) creates no edge
    ok2 = """
    class Wheel:
        def run(self):
            while True:
                with self._cond:
                    fn = self._pop()
                fn()
        def schedule(self):
            with self._lock:
                with self._cond:
                    pass
    """
    assert lint(ok2, [LockOrderInversion()]) == []


# -- MPL4xx jit hazards -----------------------------------------------------


def test_host_sync_in_jit(tmp_path):
    bad = """
    import jax
    import numpy as np
    @jax.jit
    def f(x):
        y = np.asarray(x)
        return y
    """
    found = lint(bad, [HostSyncInJit()], rel="mpcium_tpu/engine/x.py")
    assert rule_ids(found) == ["MPL401"]
    # np.* over literals only is trace-time constant folding — legal
    # here; sizing the constant is MPS903's job (analysis/shape)
    ok_const = """
    import jax
    import numpy as np
    @jax.jit
    def f(x):
        tag = np.frombuffer(b"tag", dtype=np.uint8)
        return x
    """
    assert lint(ok_const, [HostSyncInJit()], rel="mpcium_tpu/engine/x.py") == []
    ok = """
    import jax
    import jax.numpy as jnp
    @jax.jit
    def f(x):
        return jnp.zeros_like(x)
    """
    assert lint(ok, [HostSyncInJit()], rel="mpcium_tpu/engine/x.py") == []
    # un-jitted host helpers may use numpy freely
    ok2 = """
    import numpy as np
    def g(x):
        return np.asarray(x)
    """
    assert lint(ok2, [HostSyncInJit()], rel="mpcium_tpu/engine/x.py") == []


def test_traced_branch_in_jit():
    bad = """
    import jax
    @jax.jit
    def f(x):
        if x > 0:
            return x
        return -x
    """
    assert rule_ids(
        lint(bad, [TracedBranchInJit()], rel="mpcium_tpu/ops/x.py")
    ) == ["MPL402"]
    # static args and shape tests are trace-time: fine
    ok = """
    import functools
    import jax
    @functools.partial(jax.jit, static_argnames=("n",))
    def f(x, n):
        if n > 2:
            return x
        if x.shape[0] > 4:
            return -x
        return x
    """
    assert lint(ok, [TracedBranchInJit()], rel="mpcium_tpu/ops/x.py") == []


# -- MPL5xx wire & threads --------------------------------------------------


def test_wire_version_rule():
    bad = """
    from dataclasses import dataclass
    @dataclass
    class PingMessage:
        wallet_id: str
    """
    assert rule_ids(
        lint(bad, [WireVersionRoundTrip()], rel="mpcium_tpu/wire.py")
    ) == ["MPL501"]
    ok = """
    from dataclasses import dataclass
    @dataclass
    class PingMessage:
        wallet_id: str
        v: int = 0
        def to_json(self):
            out = {"wallet_id": self.wallet_id}
            if self.v:
                out["v"] = self.v
            return out
        @classmethod
        def from_json(cls, d):
            return cls(d["wallet_id"], v=int(d.get("v", 0)))
    """
    assert lint(ok, [WireVersionRoundTrip()], rel="mpcium_tpu/wire.py") == []
    # only wire.py is in scope
    assert lint(bad, [WireVersionRoundTrip()], rel="mpcium_tpu/soak.py") == []


def test_unmanaged_thread():
    bad = """
    import threading
    def go(fn):
        t = threading.Thread(target=fn)
        t.start()
    """
    assert rule_ids(lint(bad, [UnmanagedThread()])) == ["MPL502"]
    for ok in (
        # constructor daemon
        """
        import threading
        def go(fn):
            threading.Thread(target=fn, daemon=True).start()
        """,
        # post-construction daemon (the Timer idiom)
        """
        import threading
        def go(fn):
            t = threading.Timer(1.0, fn)
            t.daemon = True
            t.start()
        """,
        # leak-checker-registered singleton
        """
        import threading
        def go(fn):
            threading.Thread(target=fn, name="ot-host-0").start()
        """,
    ):
        assert lint(ok, [UnmanagedThread()]) == [], ok


# -- MPL6xx hygiene ---------------------------------------------------------


def test_hygiene_rules():
    bad = """
    import json
    import os

    def f(xs=[], m={}):
        try:
            return os.getpid()
        except:
            return None
    """
    found = lint(bad, [BareExcept(), MutableDefaultArg(), UnusedImport()])
    assert rule_ids(found) == ["MPL601", "MPL602", "MPL603"]
    keys = sorted(f.key for f in found if f.rule == "MPL602")
    assert keys == ["m", "xs"]
    unused = [f.key for f in found if f.rule == "MPL603"]
    assert unused == ["json"]


# -- suppression & fingerprints ---------------------------------------------


def test_inline_suppression_with_reason():
    src = """
    def f():
        try:
            pass
        except:  # mpclint: disable=MPL601 — probing optional backends
            pass
    """
    assert lint(src, [BareExcept()]) == []


def test_file_level_suppression():
    src = """
    # mpclint: disable-file=MPL601
    def f():
        try:
            pass
        except:
            pass
    """
    assert lint(src, [BareExcept()]) == []


def test_fingerprint_is_line_free():
    a = Finding("MPL101", "p.py", 10, "f", "share", "m")
    b = Finding("MPL101", "p.py", 99, "f", "share", "m")
    assert a.fingerprint == b.fingerprint
    assert a.fingerprint != Finding("MPL101", "p.py", 10, "g", "share", "m").fingerprint


# -- baseline mechanics -----------------------------------------------------


def test_baseline_split_and_fail_closed(tmp_path):
    f1 = Finding("MPL101", "a.py", 1, "f", "share", "m")
    f2 = Finding("MPL102", "b.py", 2, "g", "seed", "m")
    b = Baseline(path=tmp_path / "b.json", entries={f1.fingerprint: "ok because"})
    new, grandfathered, stale = b.split([f1, f2])
    assert new == [f2] and grandfathered == [f1] and stale == []
    # the grandfathered finding disappears -> its entry is stale -> fails
    new, grandfathered, stale = b.split([f2])
    assert stale == [f1.fingerprint]


def test_baseline_load_rejects_bad_files(tmp_path):
    p = tmp_path / "b.json"
    p.write_text("{not json")
    with pytest.raises(BaselineError):
        load_baseline(p)
    p.write_text(
        '{"version": 1, "entries": [{"fingerprint": "MPL1:a::k", '
        '"justification": "  "}]}'
    )
    with pytest.raises(BaselineError):
        load_baseline(p)
    # missing file = empty baseline, not an error
    empty = load_baseline(tmp_path / "nope.json")
    assert empty.entries == {}


def test_all_rules_have_unique_ids_and_summaries():
    rules = all_rules()
    ids = [r.id for r in rules]
    assert len(ids) == len(set(ids))
    assert all(r.summary for r in rules)
    assert len(rules) >= 14


# -- runtime side of the wire-version contract ------------------------------


WIRE_CASES = [
    (wire.Envelope, dict(session_id="s", round="r1", from_id="a", payload={"x": 1})),
    (wire.GenerateKeyMessage, dict(wallet_id="w")),
    (
        wire.SignTxMessage,
        dict(key_type="secp256k1", wallet_id="w", network_internal_code="n",
             tx_id="t", tx=b"ab"),
    ),
    (wire.ResharingMessage, dict(wallet_id="w", new_threshold=2, key_type="secp256k1")),
    (wire.KeygenSuccessEvent, dict(wallet_id="w", ecdsa_pub_key="01", eddsa_pub_key="02")),
    (wire.SigningResultEvent, dict(result_type="success", wallet_id="w", tx_id="t")),
    (
        wire.ResharingSuccessEvent,
        dict(wallet_id="w", new_threshold=2, key_type="secp256k1", pub_key="03"),
    ),
]


@pytest.mark.parametrize("cls,kw", WIRE_CASES, ids=[c.__name__ for c, _ in WIRE_CASES])
def test_wire_version_round_trip(cls, kw):
    legacy = cls(**kw)
    assert cls.from_json(legacy.to_json()) == legacy
    # v=0 is omitted: the v0 JSON shape (and signing bytes) are unchanged
    assert "v" not in legacy.to_json()
    vnext = cls(v=1, **kw)
    assert vnext.to_json()["v"] == 1
    assert cls.from_json(vnext.to_json()).v == 1


def test_envelope_signing_bytes_ignore_version():
    kw = dict(session_id="s", round="r1", from_id="a", payload={"x": 1})
    assert (
        wire.Envelope(**kw).marshal_for_signing()
        == wire.Envelope(v=1, **kw).marshal_for_signing()
    )


# -- runtime side of @locked_by ---------------------------------------------


def test_locked_by_runtime_registry_is_zero_cost():
    @locked_by("_lock", "_a")
    @locked_by("_lock", "_b")
    @locked_by("_other", "_c")
    class K:
        pass

    reg = K.__mpclint_locked_by__
    assert set(reg["_lock"]) == {"_a", "_b"}
    assert reg["_other"] == ("_c",)
    K()  # decorator must not affect construction
