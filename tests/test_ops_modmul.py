"""Property tests: ops.modmul MXU kernels vs python-int ground truth."""
import secrets

import jax.numpy as jnp
import numpy as np
import pytest

from mpcium_tpu.core import bignum as bn
from mpcium_tpu.ops import modmul as mm


def _batch(xs, prof):
    return jnp.asarray(bn.batch_to_limbs(xs, prof))


def _ints(arr, prof):
    return bn.batch_from_limbs(np.asarray(arr), prof)


@pytest.fixture(
    scope="module",
    params=[
        256,
        pytest.param(1024, marks=pytest.mark.slow),
        pytest.param(2048, marks=pytest.mark.slow),
    ],
)
def ctx(request):
    bits = request.param
    mod = secrets.randbits(bits) | (1 << (bits - 1)) | 1
    return mm.MXUBarrett(mod)


def test_carry_matches_bignum():
    rng = np.random.default_rng(0)
    x = rng.integers(0, 1 << 24, (16, 96)).astype(np.int32)
    prof = bn.LimbProfile(bits=7, n_limbs=96)
    got = np.asarray(mm.carry(jnp.asarray(x)))
    ref = np.asarray(bn.carry(jnp.asarray(x), prof))
    np.testing.assert_array_equal(got, ref)


def test_mul_const_exact(ctx):
    B = 8
    xs = [secrets.randbelow(ctx.modulus) for _ in range(B)]
    c = secrets.randbelow(ctx.modulus)
    T = mm._const_matrices(c, ctx.prof.n_limbs)
    out = mm.carry(mm.mul_const(_batch(xs, ctx.prof), T))
    prof_wide = bn.LimbProfile(bits=7, n_limbs=out.shape[-1])
    got = _ints(out, prof_wide)
    assert got == [x * c for x in xs]


def test_mulmod(ctx):
    B = 8
    m = ctx.modulus
    xs = [secrets.randbelow(m) for _ in range(B)]
    ys = [secrets.randbelow(m) for _ in range(B)]
    got = _ints(ctx.mulmod(_batch(xs, ctx.prof), _batch(ys, ctx.prof)), ctx.prof)
    assert got == [x * y % m for x, y in zip(xs, ys)]


def test_add_sub_neg(ctx):
    B = 8
    m = ctx.modulus
    xs = [secrets.randbelow(m) for _ in range(B)]
    ys = [secrets.randbelow(m) for _ in range(B)]
    X, Y = _batch(xs, ctx.prof), _batch(ys, ctx.prof)
    assert _ints(ctx.addmod(X, Y), ctx.prof) == [
        (x + y) % m for x, y in zip(xs, ys)
    ]
    assert _ints(ctx.submod(X, Y), ctx.prof) == [
        (x - y) % m for x, y in zip(xs, ys)
    ]
    assert _ints(ctx.negmod(X), ctx.prof) == [(-x) % m for x in xs]


def test_powmod_const_exp(ctx):
    B = 4
    m = ctx.modulus
    xs = [secrets.randbelow(m) for _ in range(B)]
    e = secrets.randbits(80)
    got = _ints(ctx.powmod_const_exp(_batch(xs, ctx.prof), e), ctx.prof)
    assert got == [pow(x, e, m) for x in xs]


def test_powmod_per_element(ctx):
    B = 4
    m = ctx.modulus
    xs = [secrets.randbelow(m) for _ in range(B)]
    es = [secrets.randbits(64) for _ in range(B)]
    ebits = jnp.asarray(
        np.stack([[(e >> i) & 1 for i in range(64)] for e in es]).astype(
            np.int32
        )
    )
    got = _ints(ctx.powmod(_batch(xs, ctx.prof), ebits), ctx.prof)
    assert got == [pow(x, e, m) for x, e in zip(xs, es)]


def test_powmod_fixed_base(ctx):
    B = 4
    m = ctx.modulus
    g = secrets.randbelow(m - 2) + 2
    es = [secrets.randbits(96) for _ in range(B)]
    ebits = jnp.asarray(
        np.stack([[(e >> i) & 1 for i in range(96)] for e in es]).astype(
            np.int32
        )
    )
    got = _ints(ctx.powmod_fixed_base(g, ebits), ctx.prof)
    assert got == [pow(g, e, m) for e in es]


def test_prod_over_batch(ctx):
    B = 7  # odd on purpose
    m = ctx.modulus
    xs = [secrets.randbelow(m) for _ in range(B)]
    got = _ints(ctx.prod_over_batch(_batch(xs, ctx.prof))[None], ctx.prof)[0]
    want = 1
    for x in xs:
        want = want * x % m
    assert got == want


def test_edge_values(ctx):
    m = ctx.modulus
    xs = [0, 1, m - 1, m // 2]
    X = _batch(xs, ctx.prof)
    assert _ints(ctx.mulmod(X, X), ctx.prof) == [x * x % m for x in xs]
    assert _ints(ctx.addmod(X, X), ctx.prof) == [2 * x % m for x in xs]


def test_mul_pair_bf16_guard_rejects_wide_operands():
    """Operands past the f32 overlap-add exactness bound (min block count
    > 32 ⇒ > 7168 bits) must be rejected, not silently rounded."""
    n = (mm._BF16_MAX_BLOCKS + 1) * mm._BLOCK
    x = jnp.ones((1, n), jnp.int32)
    with pytest.raises(ValueError, match="exactness"):
        mm._mul_pair_bf16(x, x)


@pytest.mark.parametrize("strat", [mm._mul_pair_bf16, mm._mul_pair_i8])
def test_mul_pair_strategies_match_i32(strat):
    """Every MXU pairwise strategy (bf16 and i8) is bit-exact vs the int32
    blocked einsum, including all-max limbs."""
    rng = np.random.default_rng(7)
    for n in (32, 160, 320):
        prof = bn.LimbProfile(bits=7, n_limbs=n)
        x = rng.integers(0, 128, (4, n)).astype(np.int32)
        y = rng.integers(0, 128, (4, n)).astype(np.int32)
        got = np.asarray(strat(jnp.asarray(x), jnp.asarray(y)))
        ref = np.asarray(bn.mul_wide(jnp.asarray(x), jnp.asarray(y), prof))
        np.testing.assert_array_equal(got, ref)
        xm = np.full((2, n), 127, np.int32)
        got = np.asarray(strat(jnp.asarray(xm), jnp.asarray(xm)))
        ref = np.asarray(bn.mul_wide(jnp.asarray(xm), jnp.asarray(xm), prof))
        np.testing.assert_array_equal(got, ref)


def test_comb_window_widths_and_edges(ctx):
    """w-bit comb fixed-base exponentiation: exponent bit-lengths that are
    not multiples of the window width, plus 0 and all-ones exponents
    (regression for the COMB_W=8 generalization of the 4-bit comb)."""
    m = ctx.modulus
    base = secrets.randbits(ctx.modulus.bit_length() - 4) % m
    for ebitlen in (5, 8, 12, 63):
        es = [0, (1 << ebitlen) - 1] + [
            secrets.randbits(ebitlen) for _ in range(4)
        ]
        ebits = jnp.asarray(
            np.array(
                [[(e >> i) & 1 for i in range(ebitlen)] for e in es],
                np.int32,
            )
        )
        got = _ints(ctx.powmod_fixed_base(base, ebits), ctx.prof)
        assert got == [pow(base, e, m) for e in es], f"comb {ebitlen}"


@pytest.mark.parametrize("strat", [mm._mul_pair_bf16, mm._mul_pair_i8])
def test_mul_pair_band_odd_widths(strat):
    """Band strategies at limb counts straddling block boundaries
    (n % 32 in {1, 31, 0} — profiles built directly, since mm.profile
    block-pads) with 0/1/max edge operands."""
    for n_limbs in (31, 33, 64):
        prof = bn.LimbProfile(bits=7, n_limbs=n_limbs)
        bits = 7 * n_limbs
        xs = [0, 1, (1 << bits) - 1] + [
            secrets.randbits(bits) for _ in range(5)
        ]
        ys = [(1 << bits) - 1, (1 << bits) - 1, (1 << bits) - 1] + [
            secrets.randbits(bits) for _ in range(5)
        ]
        P = np.asarray(
            mm.carry(
                strat(
                    jnp.asarray(mm.ints_to_limbs(xs, prof)),
                    jnp.asarray(mm.ints_to_limbs(ys, prof)),
                )
            )
        )
        got = bn.batch_from_limbs(
            P, bn.LimbProfile(bits=7, n_limbs=P.shape[-1])
        )
        assert got == [x * y for x, y in zip(xs, ys)], f"mul_pair {n_limbs}"


def test_mul_pair_i8_wide_fallback():
    """Operands past the 32-block f32 overlap-add bound (where bf16 must
    reject) stay exact on the i8 strategy via its int32 fallback."""
    n_limbs = 33 * mm._BLOCK  # 1056 limbs = 7392 bits > the bf16 bound
    prof = bn.LimbProfile(bits=7, n_limbs=n_limbs)
    bits = 7 * n_limbs
    xs = [(1 << bits) - 1, secrets.randbits(bits)]
    ys = [(1 << bits) - 1, secrets.randbits(bits)]
    P = np.asarray(
        mm.carry(
            mm._mul_pair_i8(
                jnp.asarray(mm.ints_to_limbs(xs, prof)),
                jnp.asarray(mm.ints_to_limbs(ys, prof)),
            )
        )
    )
    got = bn.batch_from_limbs(P, bn.LimbProfile(bits=7, n_limbs=P.shape[-1]))
    assert got == [x * y for x, y in zip(xs, ys)]
