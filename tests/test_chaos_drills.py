"""Chaos drills in the test tier (ISSUE 3). The fast deterministic
drills run in tier-1 under the ``chaos`` marker; the randomized
multi-seed soak is ``slow``. Every drill here goes through the real
cluster stack — nodes, consumers, durable queues, registry liveness —
under an active fault plan."""
import pytest

from mpcium_tpu.faults.chaos import run_drill

pytestmark = pytest.mark.chaos


def _assert_ok(report):
    assert report.ok, (
        f"drill {report.name!r} (seed {report.seed}) expected "
        f"{report.expected!r} got {report.outcome!r}: "
        f"error={report.error!r} notes={report.notes}"
    )


def test_drill_drop_jitter_fast():
    """keygen → 3 signatures → reshare → signature under 10% unicast
    loss + (scaled) jitter: the retry budgets absorb everything."""
    report = run_drill("drop-jitter", seed=3, scale=0.15)
    _assert_ok(report)
    # the plan rode along in the report for reproduction
    assert report.plan["seed"] == 3 and report.plan["rules"]


def test_drill_partition_loud_failure_then_recovery():
    """Over-threshold partition: signing fails LOUDLY (bounded timeout
    ERROR event — no hang, no silent corruption) and succeeds after the
    partition heals."""
    report = run_drill("partition", seed=5)
    _assert_ok(report)
    assert any("error" in n for n in report.notes)
    # the partition rule actually suppressed traffic
    assert any(k.startswith("partition") for k in report.faults["counters"])


def test_drill_broker_failover():
    """Primary broker dies mid-run; clients walk to the hot standby."""
    report = run_drill("broker-failover", seed=13)
    _assert_ok(report)


def test_drill_node_crash_recovers():
    """node2 SIGKILLs as it joins its first signing session: the tx
    fails loudly, survivors detect the death and sign with t+1, the
    restarted node rejoins, and the wallet reshares cleanly."""
    report = run_drill("node-crash", seed=11)
    _assert_ok(report)
    assert report.faults["counters"]["crash_node#0"]["crash"] == 1


def test_drill_kill_resume():
    """node2 SIGKILLs on its round-2 signing broadcast and is respawned:
    the WAL session resumes mid-round and the SAME session completes with
    a bit-identical signature on all three nodes (no restart-from-scratch,
    no fresh nonce)."""
    report = run_drill("kill-resume", seed=7)
    _assert_ok(report)
    assert report.faults["counters"]["crash_node#0"]["crash"] == 1
    # the report carries how long resume took from respawn to signature
    assert report.resume_latency_s > 0
    assert any("bit-identical" in n for n in report.notes)
    # ... and the warm-cache stats from the pre-respawn warm pass beside
    # it (ISSUE 13: resume latency is recovery time, not compile wall)
    assert set(report.warm) == {"warmed", "hits", "budget_s"}
    assert report.warm["warmed"] >= 1
    assert report.to_json()["warm"] == report.warm


@pytest.mark.slow
def test_drill_cheater_caught_and_quarantined():
    """An active cheater corrupts one PRF-chosen OT-MtA wire field in
    one batch lane: the checks catch it and blame exactly the cheating
    party, the scheduler quarantines that session behind one retryable
    culprit-named ABORT event, the survivors re-pack onto pow-2
    sub-batches and complete — under live EdDSA traffic (ISSUE 16).

    Slow-marked: a full GG18+OT signing round with checks on costs
    ~70 s of EC-ladder execution on the 1-core CPU host; the per-check
    adversarial coverage stays tier-1 in test_tamper_checks.py and the
    quarantine semantics in test_cohort_quarantine.py."""
    report = run_drill("cheater", seed=7)
    _assert_ok(report)
    # the report names the culprit: session, lane, party, check
    assert set(report.culprit) == {
        "session", "lane", "party", "check", "field",
    }
    assert report.culprit["party"] in ("alice", "bob")
    # and carries the survivors' completion stats with a closed invariant
    s = report.survivors
    assert s["submitted"] == s["completed"] + s["quarantined"]
    assert s["pending"] == 0 and s["quarantined"] == 1
    assert all(isinstance(n, int) for n in s["chunks"])  # pow-2 snapped
    assert report.to_json()["culprit"] == report.culprit
    # reproducibility: the deviation is PRF-derived from (seed, plan)
    assert report.plan["seed"] == 7
    assert report.plan["rules"][0]["kind"] == "tamper"


def test_drill_report_reproducible_from_seed():
    """Same (drill, seed) ⇒ same outcome and the identical serialized
    plan — the reproduction contract scripts/chaos_drill.py documents."""
    a = run_drill("drop-jitter", seed=21, scale=0.15)
    b = run_drill("drop-jitter", seed=21, scale=0.15)
    assert (a.outcome, a.ok, a.expected) == (b.outcome, b.ok, b.expected)
    assert a.plan == b.plan


@pytest.mark.slow
def test_drill_soak_multi_seed():
    """Randomized soak: the catalog across several seeds at full time
    scale — any seed that fails is directly reproducible via
    scripts/chaos_drill.py --plan <name> --seed <seed>."""
    for seed in range(4):
        for name in ("drop-jitter", "partition", "broker-failover"):
            _assert_ok(run_drill(name, seed=seed))
