"""Tier-1 mpclint gate: the full rule set over the whole package.

This is `make lint`'s mpclint stage as a test: any non-baselined finding
fails, any stale baseline entry fails (the baseline only shrinks), and
the sweep must stay fast enough to live in tier-1.
"""
from __future__ import annotations

import time
from pathlib import Path

import pytest

from mpcium_tpu.analysis import load_baseline, run_lint
from mpcium_tpu.analysis.baseline import DEFAULT_BASELINE
from mpcium_tpu.analysis.cli import main as mpclint_main

pytestmark = pytest.mark.lint

ROOT = Path(__file__).resolve().parents[1]
MAX_BASELINE_ENTRIES = 15


@pytest.fixture(scope="module")
def sweep():
    t0 = time.monotonic()
    result = run_lint(root=ROOT)
    result.elapsed = time.monotonic() - t0
    return result


def test_package_parses_clean(sweep):
    assert not sweep.parse_errors, sweep.parse_errors
    # the whole package is in scope, not a subset
    assert sweep.files_scanned > 60


def test_no_new_findings(sweep):
    baseline = load_baseline(ROOT / DEFAULT_BASELINE)
    # MPL scope: MPF staleness is test_mpcflow's business
    new, _grandfathered, stale = baseline.split(
        sweep.findings, scope=("MPL",)
    )
    assert not new, "non-baselined findings:\n" + "\n".join(
        f.render() for f in new
    )
    assert not stale, (
        "stale baseline entries (delete them — the baseline only "
        "shrinks):\n" + "\n".join(stale)
    )


def test_sweep_is_tier1_fast(sweep):
    # generous bound: ~2s on the CI box; 30s keeps it honest under load
    assert sweep.elapsed < 30, f"sweep took {sweep.elapsed:.1f}s"


def test_baseline_is_small_and_justified():
    baseline = load_baseline(ROOT / DEFAULT_BASELINE)
    assert len(baseline.entries) <= MAX_BASELINE_ENTRIES
    for fp, justification in baseline.entries.items():
        # mpclint (MPL), mpcflow (MPF), mpcshape (MPS) share the
        # baseline + format
        assert fp.startswith(("MPL", "MPF", "MPS")), fp
        # load_baseline enforces non-empty; require a real sentence here
        assert len(justification) > 20, (fp, justification)
        if fp.startswith("MPF"):
            # mpcflow debt must name its exit: either it's a declared
            # wire boundary or the ROADMAP item that deletes it
            assert (
                "wire boundary" in justification or "ROADMAP" in justification
            ), (fp, justification)


def test_cli_agrees(capsys):
    assert mpclint_main([]) == 0
    assert mpclint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    # one line per rule family member, ids are unique
    ids = [line.split()[0] for line in out.strip().splitlines() if line]
    assert len(ids) == len(set(ids)) >= 14
