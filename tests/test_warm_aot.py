"""AOT executable round-trip (mpcium_tpu/warm/aot.py): jax.export
serialize → deserialize → call must be retrace-free and bit-identical
to the jit path, and the ArtifactStore must loudly skip stale or
corrupt artifacts instead of trusting them (ISSUE 13 satellite)."""
import numpy as np
import pytest

from mpcium_tpu.warm import aot
from mpcium_tpu.warm import manifest as wm

pytestmark = pytest.mark.perf


def _traced_fn():
    """A tiny kernel with a Python-side trace counter: the counter only
    ticks when jax re-traces the Python callable."""
    import jax.numpy as jnp

    traces = {"n": 0}

    def fn(x):
        traces["n"] += 1
        return (x * 3 + 1) % 251, jnp.cumsum(x, axis=-1)

    return fn, traces


def test_roundtrip_retrace_free_and_bit_identical():
    import jax
    import jax.numpy as jnp

    fn, traces = _traced_fn()
    x = jnp.arange(24, dtype=jnp.uint32).reshape(2, 12)
    want = jax.jit(fn)(x)

    exported = aot.export_jit(fn, x)
    traces_after_export = traces["n"]
    data = aot.serialize(exported)
    assert isinstance(data, bytes) and len(data) > 0

    restored = aot.deserialize(data)
    got1 = restored.call(x)
    got2 = restored.call(x + 0)
    # calling the deserialized executable never re-traces the Python fn
    assert traces["n"] == traces_after_export
    for w, g in zip(want, got1):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(g))
    for a, b in zip(got1, got2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_export_unsupported_raises_typed_error():
    def bad(x):
        raise RuntimeError("untraceable")

    with pytest.raises(aot.AOTUnsupported):
        aot.export_jit(bad, np.zeros(2))


def test_store_roundtrip_and_stale_invalidation(tmp_path):
    import jax.numpy as jnp

    fn, _ = _traced_fn()
    x = jnp.arange(8, dtype=jnp.uint32)
    exported = aot.export_jit(fn, x)

    store = aot.ArtifactStore(str(tmp_path))
    store.save("k/v:odd name", exported)
    assert store.names() == ["k/v:odd name"]
    loaded = store.load("k/v:odd name")
    assert loaded is not None
    np.testing.assert_array_equal(
        np.asarray(loaded.call(x)[0]), np.asarray(exported.call(x)[0])
    )
    assert store.load("never saved") is None

    # same dir read under a different environment key: every artifact is
    # stale — skipped and recompiled, never trusted
    other = aot.ArtifactStore(
        str(tmp_path), key={"host": "beef", "jax": "0.0", "jaxlib": "0.0"}
    )
    assert other.load("k/v:odd name") is None


def test_store_survives_corrupt_artifacts(tmp_path):
    import jax.numpy as jnp

    fn, _ = _traced_fn()
    exported = aot.export_jit(fn, jnp.arange(4, dtype=jnp.uint32))
    store = aot.ArtifactStore(str(tmp_path))
    bin_path = store.save("c", exported)
    with open(bin_path, "wb") as f:
        f.write(b"garbage")
    assert store.load("c") is None  # bad payload → recompile, not crash
    meta = bin_path[: -len(".bin")] + ".json"
    with open(meta, "w") as f:
        f.write("{not json")
    assert store.load("c") is None  # bad meta → recompile, not crash


def test_eddsa_kernel_registry_exports_on_cpu(tmp_path):
    """The flagship eddsa kernels export, persist, and reload for a real
    manifest entry — the direct-AOT half of the warm pass."""
    entry = wm.WarmEntry(engine="eddsa.sign", shape="B2|q2", B=2,
                         scheme="eddsa", dims={"B": "2", "q": "2"})
    store = aot.ArtifactStore(str(tmp_path))
    stats = aot.warm_entry_artifacts(store, entry)
    assert stats == {"loaded": 0, "exported": 2, "unsupported": 0}
    # second pass: everything loads from disk, nothing re-exports
    stats = aot.warm_entry_artifacts(store, entry)
    assert stats == {"loaded": 2, "exported": 0, "unsupported": 0}
    # engines without registered kernels contribute no artifacts (the
    # persistent-cache fallback covers them)
    other = wm.WarmEntry(engine="dkg.run", shape="B2|q2|ed25519", B=2,
                         scheme="dkg", dims={})
    assert aot.kernels_for_entry(other) == []
