"""Chunked/pipelined OT-MtA (ISSUE 2): scheduling must never change
values. The double-buffered run_multi — host PRG/transpose/pad work
overlapped with device mod-q compute, chunked along the batch — has to
be BIT-identical to the serial three-round composition for every chunk
count, with or without the native library, at any thread count.

Base OTs are synthesized directly from their postcondition
(keysD[j] = k^{Δ_j}_j) instead of running the Chou–Orlandi device
ladders, so this file stays in the fast tier; the real base-OT path is
covered by test_mta_ot.py (slow)."""
import hashlib

import numpy as np
import pytest

import jax.numpy as jnp

from mpcium_tpu.core import bignum as bn
from mpcium_tpu.core.bignum import P256
from mpcium_tpu.protocol.ecdsa import mta_ot

Q = mta_ot.Q
B = 4


class DetRng:
    """Deterministic CSPRNG stand-in: a hash-counter stream, so two
    instances with one seed draw identical bytes in identical call
    order (the bit-exactness fixture)."""

    def __init__(self, seed: int):
        self.seed = seed
        self.ctr = 0

    def token_bytes(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            out += hashlib.sha256(
                b"detrng|%d|%d" % (self.seed, self.ctr)
            ).digest()
            self.ctr += 1
        return bytes(out[:n])

    def randbelow(self, n: int) -> int:
        return int.from_bytes(self.token_bytes(40), "big") % n


def synth_leg(seed: int) -> mta_ot.OTMtALeg:
    """OTMtALeg with synthetic base-OT material satisfying the base-OT
    postcondition, skipping the curve ladders."""
    rng = DetRng(seed)
    leg = mta_ot.OTMtALeg.__new__(mta_ot.OTMtALeg)
    leg.tag = b"t-pipe|%d" % seed
    leg.rng = DetRng(seed + 1000)
    leg.ctr = 0
    leg.k0 = np.frombuffer(
        rng.token_bytes(mta_ot.KAPPA * 32), np.uint8
    ).reshape(-1, 32).copy()
    leg.k1 = np.frombuffer(
        rng.token_bytes(mta_ot.KAPPA * 32), np.uint8
    ).reshape(-1, 32).copy()
    leg.delta = np.frombuffer(rng.token_bytes(mta_ot.KAPPA), np.uint8) & 1
    leg.keysD = np.where(leg.delta[:, None].astype(bool), leg.k1, leg.k0)
    leg.delta_packed = mta_ot._pack(leg.delta)
    leg._delta_rows = np.nonzero(leg.delta)[0]
    return leg


def _limbs(vals):
    return jnp.asarray(bn.batch_to_limbs(vals, P256))


def _ints(arr):
    return bn.batch_from_limbs(np.asarray(arr), P256)


@pytest.fixture(scope="module")
def fixed_inputs():
    r = DetRng(7)
    a = [r.randbelow(Q) for _ in range(B)]
    g = [r.randbelow(Q) for _ in range(B)]
    w = [r.randbelow(Q) for _ in range(B)]
    a[0] = 0
    g[1] = Q - 1
    return a, g, w


@pytest.fixture(scope="module")
def serial_reference(fixed_inputs):
    """The pre-pipeline path: explicit three-round composition (full
    width, no chunking, no worker thread)."""
    a_ints, g_ints, w_ints = fixed_inputs
    leg = synth_leg(1)
    msg_a = leg.alice_round1(_limbs(a_ints), 0)
    msgs_b, betas = leg.bob_round2_multi(
        (_limbs(g_ints), _limbs(w_ints)), msg_a, 0
    )
    alphas = leg.alice_round3_multi(msgs_b)
    ref = [
        (np.asarray(al), np.asarray(be)) for al, be in zip(alphas, betas)
    ]
    # ground truth first: the reference itself multiplies correctly
    for (al, be), b_ints in zip(ref, (g_ints, w_ints)):
        ai, bi = _ints(al), _ints(be)
        for i in range(B):
            assert (ai[i] + bi[i]) % Q == a_ints[i] * b_ints[i] % Q, i
    return ref


@pytest.mark.parametrize("K", [1, 2, 4])
def test_chunked_pipeline_bit_identical_to_serial(
    K, fixed_inputs, serial_reference
):
    a_ints, g_ints, w_ints = fixed_inputs
    leg = synth_leg(1)  # same seed → same base material + rng stream
    out = leg.run_multi(
        _limbs(a_ints), (_limbs(g_ints), _limbs(w_ints)), chunks=K
    )
    for s, (al, be) in enumerate(out):
        assert np.array_equal(np.asarray(al), serial_reference[s][0]), (
            f"K={K} set {s}: alpha diverged from the serial path"
        )
        assert np.array_equal(np.asarray(be), serial_reference[s][1]), (
            f"K={K} set {s}: beta diverged from the serial path"
        )


def test_numpy_fallback_bit_identical(
    monkeypatch, fixed_inputs, serial_reference
):
    """Without libbatchhash.so the whole OT-MtA path (PRG, transpose,
    xor, pads) must still run — numpy/hashlib only — and produce the
    same bytes (environment memory: the soft fallback stays importable
    AND correct)."""
    from mpcium_tpu import native

    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    monkeypatch.setenv("MPCIUM_OT_DEVICE", "0")  # pin the host path: this test is about the numpy fallback
    assert not native.available()
    a_ints, g_ints, w_ints = fixed_inputs
    leg = synth_leg(1)
    out = leg.run_multi(
        _limbs(a_ints), (_limbs(g_ints), _limbs(w_ints)), chunks=2
    )
    for s, (al, be) in enumerate(out):
        assert np.array_equal(np.asarray(al), serial_reference[s][0])
        assert np.array_equal(np.asarray(be), serial_reference[s][1])


def test_single_thread_pin_bit_identical(
    monkeypatch, fixed_inputs, serial_reference
):
    """MPCIUM_NATIVE_THREADS=1 (deterministic single-thread mode) —
    same transcripts, same shares."""
    monkeypatch.setenv("MPCIUM_NATIVE_THREADS", "1")
    monkeypatch.setenv("MPCIUM_OT_DEVICE", "0")  # the thread knob only exists on the host path
    a_ints, g_ints, w_ints = fixed_inputs
    leg = synth_leg(1)
    out = leg.run_multi(
        _limbs(a_ints), (_limbs(g_ints), _limbs(w_ints)), chunks=4
    )
    for s, (al, be) in enumerate(out):
        assert np.array_equal(np.asarray(al), serial_reference[s][0])
        assert np.array_equal(np.asarray(be), serial_reference[s][1])


def test_payload_set_shape_contract():
    """Mismatched payload-set batch shapes fail at entry with a
    contract error, not an opaque broadcast error downstream."""
    leg = synth_leg(2)
    a = _limbs([3, 5])
    good = _limbs([7, 11])
    bad = _limbs([7, 11, 13])
    with pytest.raises(ValueError, match="payload sets disagree"):
        leg.run_multi(a, (good, bad))
    with pytest.raises(ValueError, match="payload sets disagree"):
        leg.bob_round2_multi(
            (good, bad), {"U": None, "v": mta_ot.OT_WIRE_VERSION}, 0
        )


def test_wire_version_mismatch_fails_loudly():
    """A peer speaking another extension-layer version (or a pre-v2
    message with no version field at all) is rejected with a clear
    error instead of unmasking garbage pads."""
    leg = synth_leg(3)
    # B=4 like every other tier-1 OT fixture: the full wire rounds run
    # the check kernels, which must stay inside the shared compile family
    a = _limbs([3, 5, 9, 12])
    b = _limbs([7, 11, 13, 15])
    msg_a = leg.alice_round1(a, 0)
    assert msg_a["v"] == mta_ot.OT_WIRE_VERSION

    legacy = {"U": msg_a["U"]}  # pre-v2: no version field
    with pytest.raises(ValueError, match="version mismatch"):
        leg.bob_round2_multi((b,), legacy, 0)
    with pytest.raises(ValueError, match="version mismatch"):
        leg.bob_round2_multi(
            (b,), {"U": msg_a["U"], "v": mta_ot.OT_WIRE_VERSION + 1}, 0
        )

    msgs_b, _betas = leg.bob_round2_multi((b,), msg_a, 0)
    stripped = [{k: v for k, v in m.items() if k != "v"} for m in msgs_b]
    with pytest.raises(ValueError, match="version mismatch"):
        leg.alice_round3_multi(stripped)
    # and the well-versioned message still flows
    (alpha,) = leg.alice_round3_multi(msgs_b)
    assert np.asarray(alpha).shape[0] == 4


def test_resolve_chunks(monkeypatch):
    monkeypatch.delenv("MPCIUM_OT_CHUNKS", raising=False)
    # auto: ~B/256 capped at 8, min 1, and always a divisor of B
    assert mta_ot.resolve_chunks(2) == 1
    assert mta_ot.resolve_chunks(1024) == 4
    assert mta_ot.resolve_chunks(4096) == 8
    # explicit argument wins and is clamped to a divisor
    assert mta_ot.resolve_chunks(8, 3) == 2
    assert mta_ot.resolve_chunks(8, 64) == 8
    # env knob
    monkeypatch.setenv("MPCIUM_OT_CHUNKS", "2")
    assert mta_ot.resolve_chunks(1024) == 2
    monkeypatch.setenv("MPCIUM_OT_CHUNKS", "0")
    assert mta_ot.resolve_chunks(1024) == 4
