"""Infrastructure layers: wire, identity, loopback transport, stores, registry."""
import threading
import time

import pytest

from mpcium_tpu import wire
from mpcium_tpu.identity.identity import (
    IdentityError,
    IdentityStore,
    InitiatorKey,
    decrypt_private_bytes,
    encrypt_private_bytes,
    generate_identity,
)
from mpcium_tpu.registry.registry import PeerRegistry
from mpcium_tpu.store.keyinfo import KeyInfo, KeyinfoStore
from mpcium_tpu.store.kvstore import EncryptedFileKV, FileKV, MemoryKV
from mpcium_tpu.transport.api import Permanent, TransportError
from mpcium_tpu.transport.loopback import LoopbackFabric, topic_matches
from mpcium_tpu.transport.api import QueueConfig


# -- wire -------------------------------------------------------------------


def test_envelope_roundtrip_and_signing_bytes():
    env = wire.Envelope("w1", "r1", "node0", {"x": "1"}, to="node1", is_broadcast=False)
    rt = wire.Envelope.decode(env.encode())
    assert rt.session_id == "w1" and rt.to == "node1" and rt.payload == {"x": "1"}
    # signature not part of signing bytes
    a = env.marshal_for_signing()
    env.signature = b"\x01" * 64
    assert env.marshal_for_signing() == a


def test_initiator_messages_raw():
    m = wire.SignTxMessage(
        key_type="ed25519", wallet_id="w", network_internal_code="sol",
        tx_id="t1", tx=b"\x01\x02",
    )
    raw1 = m.raw()
    m.signature = b"sig"
    assert m.raw() == raw1  # raw excludes signature
    rt = wire.SignTxMessage.from_json(m.to_json())
    assert rt.tx == b"\x01\x02" and rt.signature == b"sig"


# -- identity ---------------------------------------------------------------


def test_identity_generate_load_sign(tmp_path):
    for n in ("node0", "node1"):
        generate_identity(n, tmp_path)
    store = IdentityStore(tmp_path, "node0", {"node0": "", "node1": ""})
    env = wire.Envelope("w1", "r1", "node0", {"a": "b"})
    store.sign_envelope(env)
    assert store.verify_envelope(env)
    env.payload["a"] = "tampered"
    assert not store.verify_envelope(env)
    # unknown sender
    env2 = wire.Envelope("w1", "r1", "ghost", {})
    env2.signature = b"\x00" * 64
    assert not store.verify_envelope(env2)


def test_identity_encrypted_key(tmp_path):
    with pytest.raises(IdentityError):
        generate_identity("n", tmp_path, passphrase="short")
    generate_identity("node0", tmp_path, passphrase="longpassphrase!x")
    with pytest.raises(IdentityError):
        IdentityStore(tmp_path, "node0", {"node0": ""})  # passphrase missing
    store = IdentityStore(
        tmp_path, "node0", {"node0": ""}, passphrase="longpassphrase!x"
    )
    env = wire.Envelope("s", "r", "node0", {})
    store.sign_envelope(env)
    assert store.verify_envelope(env)


def test_at_rest_encryption_tamper():
    blob = encrypt_private_bytes(b"secret", "pw")
    assert decrypt_private_bytes(blob, "pw") == b"secret"
    with pytest.raises(IdentityError):
        decrypt_private_bytes(blob, "wrong")
    bad = bytearray(blob)
    bad[-1] ^= 1
    with pytest.raises(IdentityError):
        decrypt_private_bytes(bytes(bad), "pw")


def test_initiator_key_roundtrip(tmp_path):
    k = InitiatorKey.generate()
    k.save(tmp_path / "init.key", passphrase="longpassphrase!x")
    k2 = InitiatorKey.load(tmp_path / "init.key", passphrase="longpassphrase!x")
    assert k.public_bytes == k2.public_bytes
    m = wire.GenerateKeyMessage("w1")
    sig = k.sign(m.raw())
    # independent verifier: OpenSSL when available, else the repo's
    # RFC-8032 hostmath implementation (NOT the identity layer under test)
    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PublicKey,
        )

        Ed25519PublicKey.from_public_bytes(k.public_bytes).verify(sig, m.raw())
    except ImportError:
        from mpcium_tpu.core.hostmath import ed25519_verify

        assert ed25519_verify(k.public_bytes, m.raw(), sig)


# -- loopback transport -----------------------------------------------------


def test_topic_matching():
    assert topic_matches("a.b.*", "a.b.c")
    assert topic_matches("x", "x")
    assert not topic_matches("a.b", "a.b.c")


def test_pubsub_fanout():
    f = LoopbackFabric()
    t1, t2 = f.transport(), f.transport()
    got = []
    t1.pubsub.subscribe("topic:x", lambda d: got.append(("t1", d)))
    t2.pubsub.subscribe("topic:x", lambda d: got.append(("t2", d)))
    t1.pubsub.publish("topic:x", b"hello")
    f.drain()
    assert sorted(got) == [("t1", b"hello"), ("t2", b"hello")]
    f.close()


def test_direct_ack_and_failure():
    f = LoopbackFabric()
    t = f.transport()
    got = []
    t.direct.listen("direct:n1", lambda d: got.append(d))
    t.direct.send("direct:n1", b"ping")  # blocks until handled
    assert got == [b"ping"]
    with pytest.raises(TransportError):
        f.direct_send("direct:nobody", b"x", timeout_s=0.05, attempts=2,
                      retry_delay_s=0.01)
    f.close()


def test_queue_redelivery_and_dead_letter():
    f = LoopbackFabric(QueueConfig(max_deliver=3))
    t = f.transport()
    dead = []
    t.set_dead_letter_handler(lambda topic, data, n: dead.append((topic, data, n)))
    attempts = []

    def failing(data):
        attempts.append(data)
        raise RuntimeError("boom")

    t.queues.dequeue("q.fail.*", failing)
    t.queues.enqueue("q.fail.1", b"m")
    f.drain()
    assert len(attempts) == 3  # max_deliver
    assert dead == [("q.fail.1", b"m", 3)]

    # Permanent terminates without dead-letter
    perm = []

    def perm_handler(data):
        perm.append(data)
        raise Permanent()

    t.queues.dequeue("q.perm.*", perm_handler)
    t.queues.enqueue("q.perm.1", b"p")
    f.drain()
    assert len(perm) == 1 and len(dead) == 1
    f.close()


def test_queue_idempotency_and_pending():
    f = LoopbackFabric()
    t = f.transport()
    got = []
    # enqueue BEFORE any consumer exists — must be buffered (durable)
    t.queues.enqueue("q.r.1", b"early", idempotency_key="k1")
    t.queues.enqueue("q.r.1", b"early-dup", idempotency_key="k1")  # deduped
    t.queues.dequeue("q.r.*", lambda d: got.append(d))
    f.drain()
    assert got == [b"early"]
    t.queues.enqueue("q.r.2", b"late", idempotency_key="k2")
    f.drain()
    assert got == [b"early", b"late"]
    f.close()


def test_handler_can_send_direct_without_deadlock():
    f = LoopbackFabric()
    t = f.transport()
    got = []
    t.direct.listen("direct:b", lambda d: got.append(d))
    # a pubsub handler that performs a blocking acked unicast
    t.pubsub.subscribe("go", lambda d: t.direct.send("direct:b", d + b"!"))
    t.pubsub.publish("go", b"chain")
    f.drain()
    assert got == [b"chain!"]
    f.close()


# -- stores -----------------------------------------------------------------


def test_encrypted_kv(tmp_path):
    with pytest.raises(ValueError):
        EncryptedFileKV(tmp_path / "db", "")  # password mandatory
    kv = EncryptedFileKV(tmp_path / "db", "pw123")
    kv.put("ecdsa:w1", b"share-data")
    kv.put("eddsa:w1", b"other")
    assert kv.get("ecdsa:w1") == b"share-data"
    assert kv.keys("ecdsa:") == ["ecdsa:w1"]
    # on-disk bytes are ciphertext
    blobs = [
        p.read_bytes()
        for p in (tmp_path / "db").iterdir()
        if not p.name.startswith(".")
    ]
    assert all(b"share-data" not in b for b in blobs)
    # reopen with right/wrong password
    kv2 = EncryptedFileKV(tmp_path / "db", "pw123")
    assert kv2.get("ecdsa:w1") == b"share-data"
    with pytest.raises(ValueError, match="wrong encryption password"):
        EncryptedFileKV(tmp_path / "db", "wrong")
    kv.delete("ecdsa:w1")
    assert kv.get("ecdsa:w1") is None and kv.keys("ecdsa:") == []


def test_keyinfo_store():
    ks = KeyinfoStore(MemoryKV())
    info = KeyInfo(["a", "b", "c"], threshold=1, public_key="aa", vss_commitments=["bb"])
    ks.save("secp256k1", "w1", info)
    got = ks.get("secp256k1", "w1")
    assert got == info
    assert ks.get("ed25519", "w1") is None
    # key prefix matches reference scheme
    assert ks.kv.keys() == ["threshold_keyinfo/ecdsa:w1"]


def test_file_kv(tmp_path):
    kv = FileKV(tmp_path / "kv")
    kv.put("mpc_peers/node0", b"id0")
    kv.put("ready/node0", b"true")
    assert kv.keys("ready/") == ["ready/node0"]
    assert kv.get("mpc_peers/node0") == b"id0"
    kv.delete("ready/node0")
    assert kv.keys("ready/") == []


# -- registry ---------------------------------------------------------------


def test_registry_ready_flow():
    kv = MemoryKV()
    ids = ["n0", "n1", "n2"]
    regs = {n: PeerRegistry(n, ids, kv, poll_interval_s=0.02) for n in ids}
    regs["n0"].ready()
    assert regs["n0"].ready_count() == 1
    assert not regs["n0"].all_ready()
    for n in ("n1", "n2"):
        regs[n].ready()
    assert regs["n0"].wait_all_ready(timeout_s=2)
    assert regs["n0"].ready_peers() == ids
    # resign → peers notice
    regs["n2"].resign()
    regs["n0"]._poll_once()
    assert not regs["n0"].all_ready()
    assert regs["n0"].ready_peers() == ["n0", "n1"]


def test_remote_cluster_loads_key_before_connecting(tmp_path):
    """A missing initiator key must fail BEFORE any broker connection is
    attempted (no leaked authenticated connection + reader thread): with
    no broker listening, connecting first would surface a connection
    error instead of the key error."""
    import pytest

    from mpcium_tpu.cluster import RemoteCluster

    cfg = tmp_path / "config.yaml"
    cfg.write_text("broker_host: 127.0.0.1\nbroker_port: 1\n")
    with pytest.raises(FileNotFoundError):
        RemoteCluster(str(cfg))
