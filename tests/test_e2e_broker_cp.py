"""Multi-host-shaped end-to-end: nodes share ONLY broker addresses.

The round-4 gap (VERDICT Missing #1): the control plane (registry
liveness, keyinfo, peers) lived in a FileKV directory, so multi-node
operation required a shared filesystem — unusable across
mutually-distrusting hosts, which is MPC's whole deployment model. The
reference serves this via Consul over HTTP(S)+ACL
(/root/reference/pkg/infra/consul.go:19-47).

Here every daemon runs from its OWN disjoint working directory (its own
db/, identity/ copy, config) with ``control_plane: broker``: peers come
from the broker KV (registered over the network by the ops CLI), registry
heartbeats and keyinfo ride the same authenticated AEAD socket as the
MPC traffic. No path is shared between node processes — only
``127.0.0.1:<port>``, exactly what separate machines would share.

Identity files are copied to each node's directory at provision time,
mirroring the reference's deployment_script.sh distributing per-node
secrets — provisioning-time distribution, not a live shared volume.
"""
from __future__ import annotations

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu import wire
from mpcium_tpu.client.client import MPCClient
from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.identity.identity import InitiatorKey
from mpcium_tpu.store.broker_kv import BrokerKV
from mpcium_tpu.transport.tcp import tcp_transport

REPO = Path(__file__).resolve().parent.parent
TOKEN = "e2e-bkv-token"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MPCIUM_BROKER_TOKEN"] = TOKEN
    env["PYTHONPATH"] = ":".join(
        [str(REPO)]
        + [p for p in env.get("PYTHONPATH", "").split(":")
           if p and "axon" not in p and p != str(REPO)]
    )
    env.pop("PYTHONSTARTUP", None)
    return env


def _run_cli(module: str, *args: str, cwd: Path) -> None:
    subprocess.run(
        [sys.executable, "-m", module, *args],
        cwd=cwd, env=_child_env(), check=True, capture_output=True,
    )


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    root = tmp_path_factory.mktemp("e2e-bkv")
    port = _free_port()

    # --- provision-time bootstrap (one staging dir, like an operator's
    # laptop): peers, identities, initiator -----------------------------
    staging = root / "staging"
    staging.mkdir()
    _run_cli("mpcium_tpu.cli.ops", "generate-peers", "-n", "3", cwd=staging)
    for i in range(3):
        _run_cli("mpcium_tpu.cli.ops", "generate-identity",
                 "--node", f"node{i}", cwd=staging)
    _run_cli("mpcium_tpu.cli.ops", "generate-initiator", cwd=staging)
    initiator_pub = json.loads(
        (staging / "event_initiator.json").read_text()
    )["public_key"]

    # --- broker in its own directory ------------------------------------
    broker_dir = root / "broker-host"
    broker_dir.mkdir()
    procs: list = []
    logs = {}

    def _spawn(tag: str, cwd: Path, *args: str) -> None:
        logs[tag] = open(root / f"{tag}.log", "wb")
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "mpcium_tpu.cli.main", *args],
                cwd=cwd, env=_child_env(),
                stdout=logs[tag], stderr=subprocess.STDOUT,
            )
        )

    _spawn("broker", broker_dir, "broker", "--port", str(port),
           "--journal", str(broker_dir / "queue.jsonl"), "--encrypt")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=1).close()
            break
        except OSError:
            time.sleep(0.2)
    else:
        raise RuntimeError("broker never opened its port")

    # --- peers registered over the NETWORK (ops CLI --broker mode) ------
    _run_cli("mpcium_tpu.cli.ops", "register-peers",
             "--broker", f"127.0.0.1:{port}",
             "--broker-token", TOKEN, "--broker-encrypt", cwd=staging)

    # --- three nodes in DISJOINT directories ----------------------------
    for i in range(3):
        nd = root / f"node{i}-host"
        nd.mkdir()
        shutil.copytree(staging / "identity", nd / "identity")
        pool = nd / "safeprimes.json"
        pool.write_bytes(
            (REPO / "mpcium_tpu/data/safeprimes_1024.json").read_bytes()
        )
        (nd / "config.yaml").write_text(
            "\n".join(
                [
                    "environment: development",
                    "mpc_threshold: 1",
                    "control_plane: broker",  # <-- the point of this test
                    f'event_initiator_pubkey: "{initiator_pub}"',
                    f"badger_password: bkv-node{i}-password",
                    f"broker_port: {port}",
                    "broker_encrypt: true",
                    f"safe_prime_pool: {pool}",
                ]
            )
        )
        _spawn(f"node{i}", nd, "start", "-n", f"node{i}")

    # readiness observed through the broker KV — the only shared surface
    t_probe = tcp_transport("127.0.0.1", port, auth_token=TOKEN, encrypt=True)
    kv = BrokerKV(t_probe.client)
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if len(kv.keys("ready/")) == 3:
            break
        dead = [p for p in procs if p.poll() is not None]
        if dead:
            raise RuntimeError(
                "process died during startup: "
                + "".join(
                    (root / f"{t}.log").read_text()[-2500:]
                    for t in logs
                )
            )
        time.sleep(0.5)
    else:
        raise RuntimeError("daemons never became ready (broker KV)")

    transport = tcp_transport("127.0.0.1", port, auth_token=TOKEN,
                              encrypt=True)
    client = MPCClient(
        transport, InitiatorKey.load(staging / "event_initiator.key")
    )
    yield root, client, kv

    transport.client.close()
    t_probe.client.close()
    for p in procs:
        p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=15)
        except subprocess.TimeoutExpired:
            p.kill()
    for f in logs.values():
        f.close()


def _await(subscribe, fire, matches, timeout_s: float):
    import threading

    done = threading.Event()
    box: list = []

    def on_ev(ev):
        if matches(ev):
            box.append(ev)
            done.set()

    sub = subscribe(on_ev)
    try:
        fire()
        assert done.wait(timeout_s), "no result within timeout"
        return box[0]
    finally:
        sub.unsubscribe()


def test_generate_and_sign_with_broker_control_plane(stack):
    root, client, kv = stack
    for attempt in range(5):
        ev = _await(
            client.on_wallet_creation_result,
            lambda a=attempt: client.create_wallet(f"w-bkv-{a}"),
            lambda ev, a=attempt: ev.wallet_id == f"w-bkv-{a}",
            timeout_s=600,
        )
        if ev.result_type == wire.RESULT_SUCCESS:
            break
        assert "not ready" in ev.error_reason, ev.error_reason
        time.sleep(3)
    else:
        raise AssertionError(f"keygen kept failing: {ev.error_reason}")

    # keyinfo lives in the broker KV — visible over the network
    assert any(
        ev.wallet_id in k for k in kv.keys("threshold_keyinfo/")
    ), kv.keys("threshold_keyinfo/")

    tx = b"bkv multi-host transfer"
    sev = _await(
        client.on_sign_result,
        lambda: client.sign_transaction(
            wire.SignTxMessage(
                key_type="ed25519", wallet_id=ev.wallet_id,
                network_internal_code="solana-devnet",
                tx_id="tx-bkv-ed", tx=tx,
            )
        ),
        lambda e: e.tx_id == "tx-bkv-ed",
        timeout_s=300,
    )
    assert sev.result_type == wire.RESULT_SUCCESS, sev.error_reason
    assert hm.ed25519_verify(
        bytes.fromhex(ev.eddsa_pub_key), tx, bytes.fromhex(sev.signature)
    )

    # the ONLY thing node directories share is the broker address:
    # no control/ dir exists anywhere
    assert not list(root.glob("*/control"))
