"""Resharing: old committee → new committee, both curves, sign-after-rotate."""
import json
import secrets
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.core import paillier as pl
from mpcium_tpu.protocol.base import ProtocolError
from mpcium_tpu.protocol.eddsa.keygen import EDDSAKeygenParty
from mpcium_tpu.protocol.eddsa.signing import EDDSASigningParty
from mpcium_tpu.protocol.resharing import ResharingParty
from mpcium_tpu.protocol.runner import run_protocol

DATA = Path(__file__).resolve().parent.parent / "mpcium_tpu" / "data"


@pytest.fixture(scope="module")
def ed_wallet():
    ids = ["n0", "n1", "n2"]
    parties = {
        pid: EDDSAKeygenParty("w-ed", pid, ids, threshold=1) for pid in ids
    }
    run_protocol(parties)
    return {pid: p.result for pid, p in parties.items()}


def test_eddsa_reshare_to_new_committee(ed_wallet):
    old_quorum = ["n0", "n1"]
    new_committee = ["n2", "n3", "n4", "n5"]  # fully disjoint from quorum
    t_new = 2
    pub = ed_wallet["n0"].public_key
    vss = ed_wallet["n0"].vss_commitments
    parties = {}
    for pid in old_quorum:
        parties[pid] = ResharingParty(
            "rs1", pid, "ed25519", old_quorum, new_committee, t_new,
            old_share=ed_wallet[pid],
        )
    for pid in new_committee:
        parties[pid] = ResharingParty(
            "rs1", pid, "ed25519", old_quorum, new_committee, t_new,
            old_public_key=pub, old_vss_commitments=vss,
        )
    run_protocol(parties)
    new_shares = {pid: parties[pid].result for pid in new_committee}
    assert all(s is not None for s in new_shares.values())
    assert parties["n0"].result is None  # old-only
    assert all(s.public_key == pub for s in new_shares.values())
    assert all(s.aux.get("is_reshared") for s in new_shares.values())

    # sign with t_new+1 NEW members; signature verifies under the OLD key
    quorum = ["n3", "n4", "n5"]
    msg = b"post-rotation tx"
    signers = {
        pid: EDDSASigningParty(
            "tx-rs", pid, quorum, new_shares[pid], msg
        )
        for pid in quorum
    }
    run_protocol(signers)
    sig = next(iter(signers.values())).result
    assert hm.ed25519_verify(pub, msg, sig)


def test_eddsa_reshare_overlapping_member(ed_wallet):
    """A node in both committees plays both roles in one party object."""
    old_quorum = ["n0", "n2"]
    new_committee = ["n0", "n1", "n9"]
    pub = ed_wallet["n0"].public_key
    vss = ed_wallet["n0"].vss_commitments
    parties = {}
    for pid in old_quorum:
        parties[pid] = ResharingParty(
            "rs2", pid, "ed25519", old_quorum, new_committee, 1,
            old_share=ed_wallet[pid],
            old_public_key=pub, old_vss_commitments=vss,
        )
    for pid in new_committee:
        if pid in parties:
            continue
        parties[pid] = ResharingParty(
            "rs2", pid, "ed25519", old_quorum, new_committee, 1,
            old_public_key=pub, old_vss_commitments=vss,
        )
    run_protocol(parties)
    shares = {pid: parties[pid].result for pid in new_committee}
    quorum = ["n1", "n9"]
    signers = {
        pid: EDDSASigningParty("tx-rs2", pid, quorum, shares[pid], b"hello")
        for pid in quorum
    }
    run_protocol(signers)
    assert hm.ed25519_verify(pub, b"hello", signers["n1"].result)


def test_reshare_rejects_bad_subshare(ed_wallet):
    """Tampered sub-share must be caught by the VSS check."""
    from mpcium_tpu.protocol.resharing import R2_SHARE

    old_quorum = ["n0", "n1"]
    new_committee = ["n7", "n8"]
    pub = ed_wallet["n0"].public_key
    vss = ed_wallet["n0"].vss_commitments
    parties = {}
    for pid in old_quorum:
        parties[pid] = ResharingParty(
            "rs3", pid, "ed25519", old_quorum, new_committee, 1,
            old_share=ed_wallet[pid],
        )
    for pid in new_committee:
        parties[pid] = ResharingParty(
            "rs3", pid, "ed25519", old_quorum, new_committee, 1,
            old_public_key=pub, old_vss_commitments=vss,
        )

    class TamperingRunner:
        pass

    from collections import deque

    queue = deque()
    for party in parties.values():
        for m in party.start():
            queue.append(m)
    with pytest.raises(ProtocolError, match="VSS"):
        while queue:
            msg = queue.popleft()
            if msg.round == R2_SHARE and msg.from_id == "n0":
                tampered = dict(msg.payload)
                tampered["share"] = str((int(tampered["share"]) + 1) % hm.ED_L)
                msg = type(msg)(
                    msg.session_id, msg.round, msg.from_id, tampered, msg.to
                )
            targets = (
                [p for pid, p in parties.items() if pid != msg.from_id]
                if msg.is_broadcast
                else [parties[msg.to]]
            )
            for t in targets:
                for out in t.receive(msg):
                    queue.append(out)


@pytest.fixture(scope="module")
def ecdsa_setup():
    d = json.load(open(DATA / "test_preparams.json"))["preparams"]
    preparams = {k: pl.PreParams.from_json(v) for k, v in d.items()}
    from mpcium_tpu.protocol.ecdsa.keygen import ECDSAKeygenParty

    ids = sorted(preparams)
    parties = {
        pid: ECDSAKeygenParty(
            "w-ec", pid, ids, threshold=1, preparams=preparams[pid]
        )
        for pid in ids
    }
    run_protocol(parties)
    return preparams, {pid: p.result for pid, p in parties.items()}


def test_ecdsa_reshare_and_sign(ecdsa_setup):
    preparams, wallets = ecdsa_setup
    ids = sorted(wallets)
    old_quorum = ids[:2]
    new_committee = ids  # same 3 nodes, fresh shares
    pub = wallets[ids[0]].public_key
    vss = wallets[ids[0]].vss_commitments
    parties = {}
    for pid in ids:
        parties[pid] = ResharingParty(
            "rs-ec", pid, "secp256k1", old_quorum, new_committee, 1,
            old_share=wallets[pid] if pid in old_quorum else None,
            old_public_key=pub, old_vss_commitments=vss,
            preparams=preparams[pid],
        )
    run_protocol(parties)
    new_shares = {pid: parties[pid].result for pid in ids}
    assert all(s is not None and s.aux["is_reshared"] for s in new_shares.values())
    assert all(s.public_key == pub for s in new_shares.values())
    # old shares + new shares interpolate to the same secret
    from mpcium_tpu.protocol.ecdsa.signing import ECDSASigningParty

    digest = int.from_bytes(secrets.token_bytes(32), "big")
    quorum = [ids[1], ids[2]]
    signers = {
        pid: ECDSASigningParty("tx-ec-rs", pid, quorum, new_shares[pid], digest)
        for pid in quorum
    }
    run_protocol(signers)
    res = signers[quorum[0]].result
    assert hm.ecdsa_verify(
        hm.secp_decompress(pub), digest, res["r"], res["s"]
    )
