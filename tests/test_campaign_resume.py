"""Campaign crash-resume (ISSUE 19 satellite): SIGKILL the real runner
mid-step, re-invoke, and the finished steps replay from the state file
while the killed step re-runs; plus the torn-tail truncation contract
of CAMPAIGN_state.json and the heartbeat sidecar."""
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

from mpcium_tpu.perf import campaign

pytestmark = pytest.mark.perf

_ROOT = Path(__file__).resolve().parents[1]
_DRIVER = str(_ROOT / "scripts" / "tpu_round.py")


def _plan(dirpath: Path, sleep_s: float = 0.0) -> Path:
    """Three trivial steps; s1/s2 bump run-counter files so a test can
    prove exactly which steps re-ran across a kill."""
    c1, c2 = dirpath / "s1.runs", dirpath / "s2.runs"
    mark2 = dirpath / "s2.started"
    steps = [
        {"id": "s1", "argv": [
            sys.executable, "-c",
            f"import json; open({str(c1)!r}, 'a').write('x\\n'); "
            f"print(json.dumps({{'v': 1, 'alpha_per_sec': 10.0}}))",
        ], "timeout_s": 60},
        {"id": "s2", "argv": [
            sys.executable, "-c",
            f"import json, time; open({str(mark2)!r}, 'a').write('s\\n'); "
            f"open({str(c2)!r}, 'a').write('x\\n'); "
            f"time.sleep({sleep_s}); print(json.dumps({{'v': 2}}))",
        ], "timeout_s": 60},
        {"id": "s3", "argv": [
            sys.executable, "-c",
            "import json; print(json.dumps({'v': 3}))",
        ], "needs": ["s2"], "timeout_s": 60},
    ]
    path = dirpath / "plan.json"
    path.write_text(json.dumps(steps))
    return path


def _invoke(plan: Path, state: Path, out: Path, **popen_kw):
    argv = [sys.executable, _DRIVER, "--plan", str(plan),
            "--state", str(state), "--out", str(out), "--no-ingest",
            "--heartbeat", str(state.parent / "hb.prom")]
    return subprocess.Popen(
        argv, cwd=str(_ROOT), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, **popen_kw,
    )


def _wait_for(path: Path, timeout=60.0):
    t0 = time.monotonic()
    while not path.exists():
        assert time.monotonic() - t0 < timeout, f"{path} never appeared"
        time.sleep(0.05)


def _strip_volatile(doc):
    """Everything wall-clock/host-dependent, so two runs of the same
    plan compare equal on content."""
    drop = {"elapsed_s", "_elapsed_s", "measured_at", "env", "plan_fp",
            "comment"}
    if isinstance(doc, dict):
        return {k: _strip_volatile(v) for k, v in doc.items()
                if k not in drop}
    if isinstance(doc, list):
        return [_strip_volatile(v) for v in doc]
    return doc


def test_resume_skips_finished_steps_and_reruns_killed_one(tmp_path):
    run_dir = tmp_path / "resume"
    run_dir.mkdir()
    plan = _plan(run_dir, sleep_s=2.0)
    state, out = run_dir / "state.jsonl", run_dir / "report.json"

    # first invocation: SIGKILL'd while s2 sleeps (after s1 checkpointed)
    p = _invoke(plan, state, out)
    try:
        _wait_for(run_dir / "s2.started")
        time.sleep(0.2)
        os.kill(p.pid, signal.SIGKILL)
    finally:
        p.wait(timeout=30)
    assert not out.exists(), "killed run must not have written a report"
    assert list(campaign.load_state(str(state))["results"]) == ["s1"]

    # second invocation: same plan, same state — runs to completion
    p = _invoke(plan, state, out)
    stdout, _ = p.communicate(timeout=120)
    assert p.returncode == 0, stdout
    assert "[s1] already finished — skipping (resume)" in stdout

    # finished step replayed from state (ran once), killed step re-ran
    assert (run_dir / "s1.runs").read_text() == "x\n"
    assert (run_dir / "s2.runs").read_text() == "x\nx\n"

    report = json.loads(out.read_text())
    assert report["complete"] and report["steps_dnf"] == 0
    assert report["steps"]["s2"]["v"] == 2
    # step metrics were lifted for the ledger
    assert report["metrics"]["alpha_per_sec"] == 10.0
    assert report["metrics"]["campaign_complete"] == 1.0

    # …and the final artifact is content-identical to an uninterrupted
    # run of the same plan (volatile wall-clock/host fields stripped)
    clean_dir = tmp_path / "clean"
    clean_dir.mkdir()
    plan2 = _plan(clean_dir, sleep_s=2.0)
    # identical step text except the tmp paths; normalize by comparing
    # the parsed step results and lifted metrics, not argv echoes
    state2, out2 = clean_dir / "state.jsonl", clean_dir / "report.json"
    p = _invoke(plan2, state2, out2)
    stdout, _ = p.communicate(timeout=120)
    assert p.returncode == 0, stdout
    uninterrupted = json.loads(out2.read_text())
    a, b = _strip_volatile(report), _strip_volatile(uninterrupted)
    for doc in (a, b):
        doc.pop("campaign", None)
        for s in doc["steps"].values():
            s.pop("_rc", None)
    assert a["steps"] == b["steps"]
    assert a["metrics"] == b["metrics"]
    assert a["steps_done"] == b["steps_done"] == 3

    # heartbeat sidecar: prometheus text with the campaign gauges
    hb = (run_dir / "hb.prom").read_text()
    assert "campaign_steps_done" in hb
    assert "campaign_steps_total" in hb


def test_torn_tail_is_truncated_and_step_reruns(tmp_path):
    state = tmp_path / "state.jsonl"
    header = json.dumps({"campaign": "t", "plan_fp": "f" * 16,
                         "rehearse": True, "steps": ["s1", "s2"]})
    good = json.dumps({"step": "s1", "rc": 0, "result": {"v": 1},
                       "elapsed_s": 0.1})
    state.write_text(header + "\n" + good + "\n"
                     + '{"step": "s2", "rc": 0, "result": {"tr')
    st = campaign.load_state(str(state))
    assert st["torn"] is True
    assert list(st["results"]) == ["s1"]
    # the torn bytes are GONE: a reopen sees a clean file
    again = campaign.load_state(str(state))
    assert again["torn"] is False
    assert list(again["results"]) == ["s1"]
    assert again["header"]["campaign"] == "t"


def test_corrupt_middle_line_refuses_resume(tmp_path):
    state = tmp_path / "state.jsonl"
    state.write_text(
        '{"campaign": "t", "plan_fp": "x"}\n'
        '{"step": "s1", "rc": 0, "result"\n'  # corrupt, NOT last
        '{"step": "s2", "rc": 0, "result": {"v": 2}}\n'
    )
    with pytest.raises(campaign.StateMismatch):
        campaign.load_state(str(state))


def test_state_from_different_plan_is_refused(tmp_path):
    plan_dir = tmp_path / "a"
    plan_dir.mkdir()
    plan = _plan(plan_dir, sleep_s=0.0)
    state, out = plan_dir / "state.jsonl", plan_dir / "report.json"
    p = _invoke(plan, state, out)
    stdout, _ = p.communicate(timeout=120)
    assert p.returncode == 0, stdout

    other_dir = tmp_path / "b"
    other_dir.mkdir()
    other_plan = _plan(other_dir, sleep_s=0.0)  # different tmp paths
    p = _invoke(other_plan, state, out)
    stdout, _ = p.communicate(timeout=120)
    assert p.returncode != 0
    assert "different" in stdout and "plan" in stdout


def test_dependency_dnf_cascades(tmp_path):
    """A step whose dependency DNF'd is skipped with a structured DNF
    instead of burning window time."""
    steps = [
        {"id": "boom", "argv": [sys.executable, "-c", "raise SystemExit(3)"],
         "timeout_s": 30},
        {"id": "after", "argv": [
            sys.executable, "-c", "import json; print(json.dumps({'v': 9}))",
        ], "needs": ["boom"], "timeout_s": 30},
    ]
    plan = tmp_path / "plan.json"
    plan.write_text(json.dumps(steps))
    state, out = tmp_path / "state.jsonl", tmp_path / "report.json"
    p = _invoke(plan, state, out)
    stdout, _ = p.communicate(timeout=120)
    assert p.returncode == 1, stdout  # incomplete campaign exits 1
    report = json.loads(out.read_text())
    assert report["steps"]["boom"]["dnf"]
    assert "rc=3" in report["steps"]["boom"]["reason"]
    assert report["steps"]["after"]["dnf"]
    assert "dependency" in report["steps"]["after"]["reason"]
    assert report["metrics"]["campaign_complete"] == 0.0
    # DNFs are attributable: elapsed + env stamped
    assert "elapsed_s" in report["steps"]["boom"]
    assert "env" in report["steps"]["boom"]


def test_step_timeout_becomes_structured_dnf(tmp_path):
    steps = [{"id": "hang", "argv": [
        sys.executable, "-c", "import time; time.sleep(60)",
    ], "timeout_s": 1.5}]
    plan = tmp_path / "plan.json"
    plan.write_text(json.dumps(steps))
    state, out = tmp_path / "state.jsonl", tmp_path / "report.json"
    p = _invoke(plan, state, out)
    stdout, _ = p.communicate(timeout=120)
    assert p.returncode == 1
    report = json.loads(out.read_text())
    res = report["steps"]["hang"]
    assert res["dnf"] and "watchdog" in res["reason"]
    assert res["elapsed_s"] >= 1.0


def test_report_inherits_platform_from_step_envs(monkeypatch):
    """The runner process is jax-free, so its own fingerprint says
    platform=uninitialized; the campaign record must carry the platform
    the step subprocesses measured on, or a live TPU round would
    self-report degraded and satisfy no chip claim."""
    monkeypatch.setattr(
        campaign, "env_fingerprint",
        lambda: {"platform": "uninitialized", "host": "runnerhost"},
    )
    steps = [campaign.Step("s1", ["true"]), campaign.Step("s2", ["true"])]
    c = campaign.Campaign("t", steps, state_path="/dev/null")
    results = {
        "s1": {"step": "s1", "rc": 0, "elapsed_s": 1.0,
               "result": {"v": 1, "env": {"platform": "tpu",
                                          "device_kind": "TPU v4",
                                          "device_count": 4,
                                          "host": "h1"}}},
        "s2": {"step": "s2", "rc": 0, "elapsed_s": 1.0,
               "result": {"v": 2}},
    }
    report = c.report(results)
    assert report["env"]["platform"] == "tpu"
    assert report["env"]["device_kind"] == "TPU v4"
    assert report["env"]["device_count"] == 4
    # host stays the RUNNER's host fingerprint — inheritance is
    # device facts only, never the machine identity
    assert report["env"]["host"] != "h1"
