"""Session-axis sharding through the PRODUCTION consumer path (VERDICT
r4 weak #8): with the mesh armed (daemon-start `arm_session_axis`), the
batch scheduler's EdDSA dispatches shard their session axis over every
local device — same results, same coalescing, multi-device execution.
Runs on the 8-virtual-CPU-device mesh from conftest."""
import secrets
import threading

import jax
import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu import wire
from mpcium_tpu.cluster import LocalCluster, load_test_preparams
from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.engine import eddsa_batch as eb
from mpcium_tpu.engine import sharded

N_WALLETS = 8  # divisible by the 8-device mesh → every tensor shards


@pytest.fixture()
def armed_mesh():
    assert len(jax.devices()) >= 8, "conftest should provide 8 devices"
    mesh = sharded.arm_session_axis()
    assert mesh is not None
    yield mesh
    sharded.arm_session_axis(1)  # disarm for other tests


def test_to_dev_actually_shards(armed_mesh):
    import numpy as np

    x = eb.to_dev(np.zeros((N_WALLETS, 64), np.uint8))
    assert len(x.sharding.device_set) == len(jax.devices())
    # dispatch through a real engine kernel keeps the partitioning
    r, R = eb.nonce_commitments(x)
    assert len(r.sharding.device_set) == len(jax.devices())
    # odd tails degrade to default placement instead of failing
    y = eb.to_dev(np.zeros((N_WALLETS - 1, 64), np.uint8))
    assert len(y.sharding.device_set) == 1
    # party-leading round tensors shard their SESSION axis (axis=1) —
    # sharding axis 0 would partition the committee instead
    z = eb.to_dev(np.zeros((2, N_WALLETS, 32), np.uint8), axis=1)
    assert len(z.sharding.device_set) == len(jax.devices())
    assert z.sharding.spec[0] is None


def test_batched_signing_through_consumers_on_mesh(armed_mesh, tmp_path):
    c = LocalCluster(
        n_nodes=3,
        threshold=1,
        root_dir=str(tmp_path / "shard-consumer"),
        preparams=load_test_preparams(),
        batch_signing=True,
        batch_window_s=0.25,
        reply_timeout_s=30.0,
    )
    try:
        ids = c.node_ids
        shares = eb.dealer_keygen_batch(N_WALLETS, ids, threshold=1)
        pubs = []
        for w in range(N_WALLETS):
            for i, nid in enumerate(ids):
                c.nodes[nid].save_share(shares[i][w], f"sw{w}")
            pubs.append(shares[0][w].public_key)
        for ec in c.consumers:
            ec.scheduler.manifest_timeout_s = 300.0

        results = {}
        done = threading.Event()

        def on_result(ev):
            results[ev.tx_id] = ev
            if len(results) == N_WALLETS:
                done.set()

        sub = c.client.on_sign_result(on_result)
        txs = {}
        try:
            start_batches = sum(
                ec.scheduler.batches_run for ec in c.consumers
            )
            for w in range(N_WALLETS):
                tx = secrets.token_bytes(32)
                tx_id = f"stx-{w}"
                txs[tx_id] = (w, tx)
                c.client.sign_transaction(
                    wire.SignTxMessage(
                        key_type="ed25519", wallet_id=f"sw{w}",
                        network_internal_code="sol", tx_id=tx_id, tx=tx,
                    )
                )
            assert done.wait(900), f"only {len(results)}/{N_WALLETS}"
        finally:
            sub.unsubscribe()

        for tx_id, ev in results.items():
            w, tx = txs[tx_id]
            assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
            assert hm.ed25519_verify(
                pubs[w], tx, bytes.fromhex(ev.signature)
            ), tx_id
        # sharding must not change the batching behavior
        end_batches = sum(ec.scheduler.batches_run for ec in c.consumers)
        per_node = (end_batches - start_batches) / len(c.consumers)
        assert per_node <= 4
    finally:
        c.close()
