"""RFC vectors for the pure-python `cryptography` fallback
(mpcium_tpu/core/softcrypto.py) plus interop sanity for the modules that
consume it. These run regardless of whether OpenSSL's `cryptography` is
installed — the fallback must stay correct even when it is dormant."""
import pytest

from mpcium_tpu.core import softcrypto as sc


# -- ChaCha20-Poly1305 (RFC 8439) -------------------------------------------


def test_chacha20poly1305_rfc8439_vector():
    # RFC 8439 §2.8.2 AEAD test vector
    key = bytes(range(0x80, 0xA0))
    nonce = bytes.fromhex("070000004041424344454647")
    aad = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
    plaintext = (
        b"Ladies and Gentlemen of the class of '99: If I could offer you "
        b"only one tip for the future, sunscreen would be it."
    )
    ct = sc.ChaCha20Poly1305(key).encrypt(nonce, plaintext, aad)
    assert ct[:-16] == bytes.fromhex(
        "d31a8d34648e60db7b86afbc53ef7ec2a4aded51296e08fea9e2b5a736ee62d6"
        "3dbea45e8ca9671282fafb69da92728b1a71de0a9e060b2905d6a5b67ecd3b36"
        "92ddbd7f2d778b8c9803aee328091b58fab324e4fad675945585808b4831d7bc"
        "3ff4def08e4b7a9de576d26586cec64b6116"
    )
    assert ct[-16:] == bytes.fromhex("1ae10b594f09e26a7e902ecbd0600691")
    assert sc.ChaCha20Poly1305(key).decrypt(nonce, ct, aad) == plaintext


def test_chacha20poly1305_tamper_raises_invalidtag():
    key = b"\x01" * 32
    nonce = b"\x02" * 12
    ct = bytearray(sc.ChaCha20Poly1305(key).encrypt(nonce, b"secret", b"ad"))
    ct[0] ^= 1
    with pytest.raises(sc.InvalidTag):
        sc.ChaCha20Poly1305(key).decrypt(nonce, bytes(ct), b"ad")
    # wrong AAD also fails authentication
    ct = sc.ChaCha20Poly1305(key).encrypt(nonce, b"secret", b"ad")
    with pytest.raises(sc.InvalidTag):
        sc.ChaCha20Poly1305(key).decrypt(nonce, ct, b"other")


def test_poly1305_rfc8439_vector():
    # RFC 8439 §2.5.2
    key = bytes.fromhex(
        "85d6be7857556d337f4452fe42d506a80103808afb0db2fd4abff6af4149f51b"
    )
    tag = sc._poly1305(key, b"Cryptographic Forum Research Group")
    assert tag == bytes.fromhex("a8061dc1305136c6c22b8baf0c0127a9")


# -- X25519 (RFC 7748) -------------------------------------------------------


def test_x25519_rfc7748_vector():
    k = bytes.fromhex(
        "a546e36bf0527c9d3b16154b82465edd62144c0ac1fc5a18506a2244ba449ac4"
    )
    u = bytes.fromhex(
        "e6db6867583030db3594c1a424b15f7c726624ec26b3353b10a903a6d0ab1c4c"
    )
    assert sc._x25519_scalarmult(k, u) == bytes.fromhex(
        "c3da55379de9c6908e94ea4df28d084f32eccf03491c71f754b4075577a28552"
    )


def test_x25519_dh_agreement():
    # RFC 7748 §6.1 Diffie-Hellman vector
    a = sc.X25519PrivateKey.from_private_bytes(bytes.fromhex(
        "77076d0a7318a57d3c16c17251b26645df4c2f87ebc0992ab177fba51db92c2a"
    ))
    b = sc.X25519PrivateKey.from_private_bytes(bytes.fromhex(
        "5dab087e624a8a4b79e17f8b83800ee66f3bb1292618b6fd1c2f8b27ff88e0eb"
    ))
    assert a.public_key().public_bytes_raw() == bytes.fromhex(
        "8520f0098930a754748b7ddcb43ef75a0dbf3a0d26381af4eba4a98eaa9b4e6a"
    )
    shared = bytes.fromhex(
        "4a5d9d5ba4ce2de1728e3bf480350f25e07e21c947d19e3376f09b3c1e161742"
    )
    assert a.exchange(b.public_key()) == shared
    assert b.exchange(a.public_key()) == shared


# -- Ed25519 (RFC 8032) ------------------------------------------------------


def test_ed25519_rfc8032_vector():
    # RFC 8032 §7.1 TEST 2 (one-byte message)
    sk = sc.Ed25519PrivateKey.from_private_bytes(bytes.fromhex(
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb"
    ))
    pub = sk.public_key().public_bytes_raw()
    assert pub == bytes.fromhex(
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c"
    )
    sig = sk.sign(b"\x72")
    assert sig == bytes.fromhex(
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00"
    )
    sk.public_key().verify(sig, b"\x72")
    with pytest.raises(sc.InvalidSignature):
        sk.public_key().verify(sig, b"\x73")


# -- HKDF-SHA256 (RFC 5869) --------------------------------------------------


def test_hkdf_rfc5869_case1():
    okm = sc.HKDF(
        algorithm=sc.SHA256(), length=42,
        salt=bytes.fromhex("000102030405060708090a0b0c"),
        info=bytes.fromhex("f0f1f2f3f4f5f6f7f8f9"),
    ).derive(b"\x0b" * 22)
    assert okm == bytes.fromhex(
        "3cb25f25faacd57a90434f64d0362f2a2d2d0a90cf1a5a4c5db02d56ecc4c5bf"
        "34007208d5b887185865"
    )


# -- interop through the consuming modules -----------------------------------


def test_identity_roundtrip_on_fallback(tmp_path):
    """generate_identity → IdentityStore → envelope sign/verify works with
    whichever backend is active."""
    from mpcium_tpu.identity.identity import IdentityStore, generate_identity
    from mpcium_tpu.wire import Envelope

    for nid in ("a", "b"):
        generate_identity(nid, tmp_path)
    store_a = IdentityStore(tmp_path, "a", {"a": "a", "b": "b"})
    store_b = IdentityStore(tmp_path, "b", {"a": "a", "b": "b"})
    env = Envelope(session_id="s", round="r1", from_id="a", payload={"x": 1})
    store_a.sign_envelope(env)
    assert store_b.verify_envelope(env)
    env.payload["x"] = 2
    assert not store_b.verify_envelope(env)


def test_encrypted_kv_roundtrip_on_fallback(tmp_path):
    from mpcium_tpu.store.kvstore import EncryptedFileKV

    kv = EncryptedFileKV(tmp_path / "kv", "pw")
    kv.put("ecdsa:w1", b"share-bytes")
    assert kv.get("ecdsa:w1") == b"share-bytes"
    # wrong password fails loudly
    with pytest.raises(ValueError):
        EncryptedFileKV(tmp_path / "kv", "other")


def test_secure_channel_roundtrip_on_fallback():
    from mpcium_tpu.transport import secure

    c_priv, c_pub = secure.fresh_keypair()
    s_priv, s_pub = secure.fresh_keypair()
    client = secure.derive_cipher(c_priv, s_pub, c_pub, s_pub, "tok", False)
    server = secure.derive_cipher(s_priv, c_pub, c_pub, s_pub, "tok", True)
    assert server.decrypt(client.encrypt(b"hello")) == b"hello"
    assert client.decrypt(server.encrypt(b"world")) == b"world"
    # wrong token ⇒ different keys ⇒ auth failure
    mitm = secure.derive_cipher(s_priv, c_pub, c_pub, s_pub, "bad", True)
    with pytest.raises(Exception):
        mitm.decrypt(client.encrypt(b"hello"))
