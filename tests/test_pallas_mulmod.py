"""Bit-exactness of the fused Pallas mulmod kernel (ops/pallas_mulmod.py)
against python-int ground truth, in interpreter mode on CPU (the Mosaic
lowering itself is gated on the real chip by .scratch/chipcheck.py).

Covers the widths the GG18 engine dispatches (2048-bit Paillier moduli,
4096-bit Paillier-squared / NTilde domains), a small curve-order width,
edge values (0, 1, m-1), squaring, broadcasting, and the powmod scan
path with the module-level MPCIUM_MULMOD=pallas dispatch.
"""
import secrets

import numpy as np
import pytest

import jax.numpy as jnp

from mpcium_tpu.core import bignum as bn
from mpcium_tpu.ops import modmul as mm
from mpcium_tpu.ops import pallas_mulmod as pmm

pytestmark = pytest.mark.slow  # interpret-mode runs ~10 s per width


def _rand_mod(bits: int) -> int:
    return secrets.randbits(bits) | (1 << (bits - 1)) | 1


def _limbs(vals, ctx):
    return jnp.asarray(np.stack([bn.to_limbs(v, ctx.prof) for v in vals]))


def _ints(arr, ctx):
    return [bn.from_limbs(np.asarray(r), ctx.prof) for r in np.asarray(arr)]


@pytest.mark.parametrize("bits", [2048, 4096])
def test_mulmod_matches_host_ints(bits):
    m = _rand_mod(bits)
    ctx = mm.MXUBarrett(m)
    B = 8
    av = [secrets.randbits(bits) % m for _ in range(B)]
    bv = [secrets.randbits(bits) % m for _ in range(B)]
    # edges: zero, one, m-1 (max conditional-subtraction pressure)
    av[0], bv[0] = 0, secrets.randbits(bits) % m
    av[1], bv[1] = 1, m - 1
    av[2], bv[2] = m - 1, m - 1
    out = pmm.mulmod(
        _limbs(av, ctx), _limbs(bv, ctx), ctx._T_mu, ctx._T_m, ctx._comp,
        ctx.occ, ctx.prof.n_limbs, interpret=True,
    )
    got = _ints(out, ctx)
    for i in range(B):
        assert got[i] == av[i] * bv[i] % m, f"lane {i}"


def test_mulmod_small_width_and_broadcast():
    """256-bit modulus (occ close to n — exercises the conv frame guard)
    plus (n,)-constant broadcasting against a batch."""
    m = _rand_mod(256)
    ctx = mm.MXUBarrett(m)
    B = 5  # deliberately not a tile multiple: exercises batch padding
    av = [secrets.randbits(256) % m for _ in range(B)]
    c = secrets.randbits(256) % m
    a = _limbs(av, ctx)
    b1 = jnp.asarray(bn.to_limbs(c, ctx.prof))  # (n,) broadcasts
    out = pmm.mulmod(
        a, b1, ctx._T_mu, ctx._T_m, ctx._comp, ctx.occ, ctx.prof.n_limbs,
        interpret=True,
    )
    got = _ints(out, ctx)
    for i in range(B):
        assert got[i] == av[i] * c % m


def test_squaring_exact():
    m = _rand_mod(2048)
    ctx = mm.MXUBarrett(m)
    av = [secrets.randbits(2048) % m for _ in range(4)]
    a = _limbs(av, ctx)
    out = pmm.mulmod(
        a, a, ctx._T_mu, ctx._T_m, ctx._comp, ctx.occ, ctx.prof.n_limbs,
        interpret=True,
    )
    got = _ints(out, ctx)
    for i, v in enumerate(av):
        assert got[i] == v * v % m


def test_powmod_scan_under_pallas_dispatch(monkeypatch):
    """The module-level MPCIUM_MULMOD=pallas switch routes every
    mul+reduce inside the powmod scans through the fused kernel; the
    full square-and-multiply chain must stay exact end to end."""
    monkeypatch.setattr(mm, "MULMOD_IMPL", "pallas")
    m = _rand_mod(1024)
    ctx = mm.MXUBarrett(m)
    B = 3
    xv = [secrets.randbits(1024) % m for _ in range(B)]
    ev = [secrets.randbits(64) for _ in range(B)]
    x = _limbs(xv, ctx)
    ebits = jnp.asarray(
        np.stack([
            [(e >> i) & 1 for i in range(64)] for e in ev
        ]).astype(np.int32)
    )
    out = ctx.powmod(x, ebits)
    got = _ints(out, ctx)
    for i in range(B):
        assert got[i] == pow(xv[i], ev[i], m), f"lane {i}"
