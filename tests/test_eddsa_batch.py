"""Batched signing engine vs host-math ground truth."""
import hashlib
import secrets

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu.core import bignum as bn
from mpcium_tpu.core import ed25519_jax as ed
from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.core.bignum import P256 as PROF
from mpcium_tpu.engine import eddsa_batch as eb


def test_bytes_limbs_roundtrip():
    rng = np.random.default_rng(0)
    b = rng.integers(0, 256, size=(5, 32), dtype=np.uint8)
    limbs = bn.bytes_to_limbs_le(jnp.asarray(b), PROF, PROF.n_limbs)
    vals = bn.batch_from_limbs(np.asarray(limbs), PROF)
    expect = [int.from_bytes(row.tobytes(), "little") for row in b]
    assert vals == expect
    back = np.asarray(bn.limbs_to_bytes_le(limbs, PROF, 32))
    assert (back == b).all()


def test_limbs_to_bits():
    vals = [0, 1, hm.ED_L - 1, 2**252 + 12345]
    limbs = jnp.asarray(bn.batch_to_limbs(vals, PROF))
    bits = np.asarray(bn.limbs_to_bits(limbs, PROF, 256))
    for i, v in enumerate(vals):
        got = sum(int(bit) << j for j, bit in enumerate(bits[i]))
        assert got == v


def test_decompress_valid_points():
    pts = [hm.ed_mul(k, hm.ED_B) for k in (1, 2, 3, 12345, hm.ED_L - 1)]
    enc = np.stack(
        [np.frombuffer(hm.ed_compress(p), dtype=np.uint8) for p in pts]
    )
    dec, ok = ed.decompress(jnp.asarray(enc))
    assert np.asarray(ok).all()
    for i, p in enumerate(pts):
        got = ed.to_host(
            ed.EdPointJ(dec.X[i], dec.Y[i], dec.Z[i], dec.T[i])
        )[0]
        assert got.equals(p)


def test_decompress_rejects_garbage():
    bad = np.full((2, 32), 0xFF, dtype=np.uint8)  # y = 2^255-1 ≥ p
    _, ok = ed.decompress(jnp.asarray(bad))
    assert not np.asarray(ok).any()


def test_nonce_commitments_match_host():
    r64 = eb.fresh_nonce_bytes(4, secrets)
    r_limbs, R_comp = eb.nonce_commitments(jnp.asarray(r64))
    for i in range(4):
        r_int = int.from_bytes(r64[i].tobytes(), "little") % hm.ED_L
        assert bn.from_limbs(np.asarray(r_limbs)[i], PROF) == r_int
        expect = hm.ed_compress(hm.ed_mul(r_int, hm.ED_B))
        assert np.asarray(R_comp)[i].tobytes() == expect


@pytest.mark.parametrize("q,t", [(3, 2), (2, 1)])
def test_batched_cosigning_end_to_end(q, t):
    B = 8
    # universe of 3 parties, quorum = first q of them (sorted)
    universe = ["node0", "node1", "node2"]
    shares = eb.dealer_keygen_batch(B, universe, t, rng=secrets)
    quorum_ids = sorted(universe)[:q]
    quorum_shares = shares[:q]
    signer = eb.BatchedCoSigners(quorum_ids, quorum_shares, rng=secrets)
    messages = [f"tx-{i}".encode() for i in range(B)]
    sigs, ok = signer.sign(messages)
    assert ok.all()
    # independent host-side RFC 8032 verification
    for i in range(B):
        pub = quorum_shares[0][i].public_key
        assert hm.ed25519_verify(pub, messages[i], sigs[i].tobytes())


def test_batched_verify_rejects_wrong_message():
    B = 4
    universe = ["a", "b", "c"]
    shares = eb.dealer_keygen_batch(B, universe, 1, rng=secrets)
    signer = eb.BatchedCoSigners(sorted(universe)[:2], shares[:2], rng=secrets)
    messages = [f"m{i}".encode() for i in range(B)]
    sigs, ok = signer.sign(messages)
    assert ok.all()
    A = jnp.asarray(signer.A_comp)
    wrong = eb.challenge_hashes(
        np.asarray(sigs[:, :32]), signer.A_comp, [b"evil"] * B
    )
    bad = eb.verify_signatures(jnp.asarray(sigs), A, jnp.asarray(wrong))
    assert not np.asarray(bad).any()
