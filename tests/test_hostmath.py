"""Property tests for the host-side reference math.

Cross-verified against the `cryptography` package (OpenSSL-backed) so the
reference implementation is independently pinned before it is used as ground
truth for the TPU kernels.
"""
import hashlib
import secrets

import pytest

from mpcium_tpu.core import hostmath as hm


def test_secp_generator_on_curve():
    g = hm.SECP_G
    assert (g.y * g.y - g.x**3 - 7) % hm.SECP_P == 0


def test_secp_group_law():
    k1 = secrets.randbelow(hm.SECP_N)
    k2 = secrets.randbelow(hm.SECP_N)
    p1 = hm.secp_mul(k1, hm.SECP_G)
    p2 = hm.secp_mul(k2, hm.SECP_G)
    lhs = hm.secp_add(p1, p2)
    rhs = hm.secp_mul((k1 + k2) % hm.SECP_N, hm.SECP_G)
    assert lhs == rhs
    # order annihilates
    assert hm.secp_mul(hm.SECP_N, hm.SECP_G).is_infinity


def test_secp_compress_roundtrip():
    for _ in range(5):
        pt = hm.secp_mul(secrets.randbelow(hm.SECP_N), hm.SECP_G)
        assert hm.secp_decompress(hm.secp_compress(pt)) == pt
        assert hm.secp_decode_xy(hm.secp_encode_xy(pt)) == pt


def test_ecdsa_sign_verify_roundtrip():
    priv = secrets.randbelow(hm.SECP_N - 1) + 1
    pub = hm.secp_mul(priv, hm.SECP_G)
    digest = int.from_bytes(hashlib.sha256(b"hello mpc").digest(), "big")
    r, s, _rec = hm.ecdsa_sign_plain(priv, digest)
    assert hm.ecdsa_verify(pub, digest, r, s)
    assert not hm.ecdsa_verify(pub, digest + 1, r, s)


def test_ecdsa_verify_against_openssl():
    """Our signer must be accepted by an independent (OpenSSL) verifier."""
    ec = pytest.importorskip("cryptography.hazmat.primitives.asymmetric.ec")
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric.utils import (
        encode_dss_signature,
    )

    priv = secrets.randbelow(hm.SECP_N - 1) + 1
    msg = b"tpu threshold signatures"
    digest = int.from_bytes(hashlib.sha256(msg).digest(), "big")
    r, s, _ = hm.ecdsa_sign_plain(priv, digest)

    ossl_priv = ec.derive_private_key(priv, ec.SECP256K1())
    ossl_pub = ossl_priv.public_key()
    ossl_pub.verify(encode_dss_signature(r, s), msg, ec.ECDSA(hashes.SHA256()))

    # and the reverse: OpenSSL-signed verifies under our verifier
    sig = ossl_priv.sign(msg, ec.ECDSA(hashes.SHA256()))
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature,
    )

    r2, s2 = decode_dss_signature(sig)
    assert hm.ecdsa_verify(
        hm.secp_decode_xy(
            ossl_pub.public_bytes(
                serialization.Encoding.X962,
                serialization.PublicFormat.UncompressedPoint,
            )[1:]
        ),
        digest,
        r2,
        s2,
    )


def test_ed25519_rfc8032_vector():
    # RFC 8032 §7.1 TEST 1 (empty message)
    seed = bytes.fromhex(
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
    )
    pub = bytes.fromhex(
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
    )
    sig = bytes.fromhex(
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"
    )
    assert hm.ed25519_public_from_seed(seed) == pub
    assert hm.ed25519_sign_plain(seed, b"") == sig
    assert hm.ed25519_verify(pub, b"", sig)
    assert not hm.ed25519_verify(pub, b"x", sig)


def test_ed25519_rfc8032_vector2():
    # RFC 8032 §7.1 TEST 2 (1-byte message)
    seed = bytes.fromhex(
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb"
    )
    pub = bytes.fromhex(
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c"
    )
    msg = bytes.fromhex("72")
    sig = hm.ed25519_sign_plain(seed, msg)
    assert sig == bytes.fromhex(
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00"
    )
    assert hm.ed25519_verify(pub, msg, sig)


def test_ed25519_against_openssl():
    ced = pytest.importorskip(
        "cryptography.hazmat.primitives.asymmetric.ed25519"
    )
    seed = secrets.token_bytes(32)
    msg = b"cross-check"
    sig = hm.ed25519_sign_plain(seed, msg)
    ossl = ced.Ed25519PrivateKey.from_private_bytes(seed)
    ossl.public_key().verify(sig, msg)  # raises on mismatch
    # reverse direction
    sig2 = ossl.sign(msg)
    from cryptography.hazmat.primitives import serialization

    pub_raw = ossl.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )
    assert hm.ed25519_verify(pub_raw, msg, sig2)


def test_ed25519_group_law():
    k1 = secrets.randbelow(hm.ED_L)
    k2 = secrets.randbelow(hm.ED_L)
    lhs = hm.ed_add(hm.ed_mul(k1, hm.ED_B), hm.ed_mul(k2, hm.ED_B))
    rhs = hm.ed_mul((k1 + k2) % hm.ED_L, hm.ED_B)
    assert lhs.equals(rhs)
    assert hm.ed_mul(hm.ED_L, hm.ED_B).equals(hm.ED_IDENT)


def test_ed_compress_roundtrip():
    for _ in range(5):
        pt = hm.ed_mul(secrets.randbelow(hm.ED_L), hm.ED_B)
        assert hm.ed_decompress(hm.ed_compress(pt)).equals(pt)


def test_shamir_roundtrip():
    order = hm.SECP_N
    secret = secrets.randbelow(order)
    xs = [1, 2, 3, 4, 5]
    _, shares = hm.shamir_share(secret, threshold=2, xs=xs, order=order)
    # any 3 of 5 reconstruct
    sub = {1: shares[1], 3: shares[3], 5: shares[5]}
    assert hm.shamir_reconstruct(sub, order) == secret
    # 2 of 5 do not
    sub2 = {1: shares[1], 3: shares[3]}
    assert hm.shamir_reconstruct(sub2, order) != secret


def test_lagrange_identity():
    order = hm.ED_L
    xs = [2, 5, 9]
    total = sum(hm.lagrange_coeff(xs, x, order) * x for x in xs) % order
    # sum λ_i(0) * f(x_i) reconstructs f(0); for f(x)=x this is 0
    assert total == 0
