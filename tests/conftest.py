"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip TPU hardware is not available in CI; shardings are validated on a
virtual 8-device CPU mesh (jax.sharding.Mesh semantics are identical). Real
single-chip TPU benchmarking happens in bench.py, not in tests.
"""
import os

# Must happen before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
