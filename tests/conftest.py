"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip TPU hardware is not available in CI; shardings are validated on a
virtual 8-device CPU mesh (jax.sharding.Mesh semantics are identical). Real
single-chip TPU benchmarking happens in bench.py, not in tests.
"""
import os

# Must happen before jax computations run. The ambient environment pins
# JAX_PLATFORMS=axon (the single real TPU chip, reached over a tunnel — eager
# op dispatch there is seconds per op); tests always run on the virtual
# 8-device CPU platform — real-chip benchmarking lives in bench.py.
# NOTE: the env var alone is overridden by the environment's baked-in
# jax config ("axon,cpu"), so set the config knob directly too.
os.environ["JAX_PLATFORMS"] = "cpu"

# Tier-1 runs the SERIAL pipeline path (K=1, the transcript oracle): the
# production default (K=2 counter-phase cohorts) would double the compile
# surface of every engine-touching test on this 1-core host and blow the
# suite budget for zero coverage — cohort scheduling itself is exercised
# explicitly in tests/test_pipeline.py via the `cohorts=` argument, which
# overrides this env default, and on the real engines in the slow tier.
os.environ.setdefault("MPCIUM_PIPELINE_COHORTS", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# persistent compile cache: the crypto kernels are scan-heavy and this host
# has one core — caching compiled executables across runs/processes turns
# minutes of XLA time into milliseconds
# NOTE: tests get their OWN cache dir (bench/dryrun write under different
# XLA flags). Caveat: XLA CPU AOT deserialization can rarely segfault in
# very long single processes on this host — run the suite per file
# (`make test-all`) for crash isolation; every subset is green.
# MPCIUM_TESTS_NO_CACHE=1 disables it — the Makefile's test-all retries a
# crashed file this way, since a poisoned/mismatched AOT entry (e.g.
# machine-feature mismatch) can segfault the deserializer
if not os.environ.get("MPCIUM_TESTS_NO_CACHE"):
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(__file__), "..", ".jax_cache_tests"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import pytest  # noqa: E402


def run_isolated(test_file: str, test_name: str, inner_env: str,
                 timeout: int = 3300) -> None:
    """Run one test in a fresh pytest subprocess (the shared machinery
    of the heavy distributed suites — previously three near-identical
    copies). ``inner_env`` is the wrapper-recursion guard the file's
    inner test checks. On one observed (post-migration) host, XLA:CPU
    deterministically segfaults compiling these suites' graphs; the
    subprocess keeps a crash from killing the whole pytest process, and
    MPCIUM_XFAIL_XLA_CRASH=1 (opt-in, known-bad hosts only) downgrades
    that specific crash class to xfail instead of letting a real crash
    regression merge green everywhere."""
    import subprocess
    import sys

    env = dict(os.environ)
    env[inner_env] = "1"
    try:
        r = subprocess.run(
            [sys.executable, "-m", "pytest", f"{test_file}::{test_name}",
             "-q", "--no-header"],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        pytest.fail(
            f"isolated {test_name} timed out:\n"
            f"{(e.stdout or '')[-2000:]}{(e.stderr or '')[-1000:]}"
        )
    # -11 = SIGSEGV, -6 = SIGABRT (XLA CHECK failure -> abort)
    if (r.returncode in (-11, -6)
            and os.environ.get("MPCIUM_XFAIL_XLA_CRASH") == "1"):
        pytest.xfail(
            "XLA:CPU crashed compiling this test's graphs on this host "
            "(known host-specific codegen crash; green on healthy hosts)"
        )
    assert r.returncode == 0, (r.stdout[-3000:] + r.stderr[-2000:])


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session", autouse=True)
def no_leaked_nondaemon_threads():
    """Fail the session if tests leak non-daemon threads.

    A leaked non-daemon thread hangs the interpreter at exit — in CI that
    reads as a pytest timeout with no traceback, the single worst failure
    mode to debug. Every component here (sessions, consumers, brokers,
    clusters) owns threads; this fixture makes "forgot to close it" loud.
    Daemon threads are exempt: they are explicitly declared kill-at-exit
    (sender loops, GC loops, loopback pools are all daemonized)."""
    import threading
    import time

    # process-lifetime singletons are not leaks: the OT pipeline's host
    # worker pool (mta_ot._host_pool) and the cohort pipeline's host
    # worker (engine/pipeline._host_pool) are created lazily once per
    # process and live until interpreter exit by design
    _SINGLETONS = ("ot-host", "pipe-host")

    baseline = set(threading.enumerate())
    yield
    # grace poll: threads mid-join at the last test's teardown get a
    # moment to finish before we call them leaked
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        leaked = [
            t for t in threading.enumerate()
            if t not in baseline and t.is_alive() and not t.daemon
            and not t.name.startswith(_SINGLETONS)
        ]
        if not leaked:
            return
        time.sleep(0.1)
    names = sorted(t.name for t in leaked)
    pytest.fail(
        f"tests leaked non-daemon thread(s): {names} — close the "
        f"session/consumer/broker that started them", pytrace=False
    )
