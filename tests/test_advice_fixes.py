"""Regression tests for the round-1 advisor findings.

1. Reshare-epoch fencing: a signing request racing a committee rotation is
   retryable instead of building a mixed-polynomial quorum (reference
   IsReshared gating, node.go:149-159).
2. is_reshared/epoch propagation: every reshare participant's keyinfo moves
   to the new topology; old-only members track the new commitments.
3. Safe-prime pool: concurrent takers get disjoint primes (flock) and the
   pool file is 0600 (it holds future secret NTilde factors).
4. Signing commitments/PoKs are sender-bound: one party's transcript cannot
   be replayed as another's (keygen already binds via _proof_bind).
"""
import os
import secrets
import threading

import pytest

from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.core import paillier as pl
from mpcium_tpu.node.node import NotEnoughParticipants
from mpcium_tpu.protocol.base import ProtocolError
from mpcium_tpu.protocol.eddsa.keygen import EDDSAKeygenParty
from mpcium_tpu.protocol.ecdsa.zk import SchnorrProof
from mpcium_tpu.protocol.resharing import ResharingParty
from mpcium_tpu.protocol.runner import run_protocol


# ---------------------------------------------------------------------------
# 1+2: epoch fencing / topology propagation (protocol + node level)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def ed_wallet():
    ids = ["n0", "n1", "n2"]
    parties = {
        pid: EDDSAKeygenParty("w-adv", pid, ids, threshold=1) for pid in ids
    }
    run_protocol(parties)
    return {pid: p.result for pid, p in parties.items()}


def test_reshare_bumps_epoch_and_old_only_tracks_topology(ed_wallet):
    old_quorum = ["n0", "n1"]
    new_committee = ["n2", "n3", "n4"]  # n0, n1 become old-only
    parties = {}
    for pid in old_quorum:
        parties[pid] = ResharingParty(
            "rs-adv", pid, "ed25519", old_quorum, new_committee, 1,
            old_share=ed_wallet[pid], old_epoch=0,
        )
    pub = ed_wallet["n0"].public_key
    vss = ed_wallet["n0"].vss_commitments
    for pid in new_committee:
        parties[pid] = ResharingParty(
            "rs-adv", pid, "ed25519", old_quorum, new_committee, 1,
            old_public_key=pub, old_vss_commitments=vss, old_epoch=0,
        )
    run_protocol(parties)
    # new members: epoch bumped on the share itself
    for pid in new_committee:
        share = parties[pid].result
        assert share is not None and share.epoch == 1
        assert share.participants == sorted(new_committee)
    # old-only members: no share, but full view of the new topology
    for pid in old_quorum:
        p = parties[pid]
        assert p.result is None
        assert p.new_epoch == 1
        assert p.new_agg == parties["n2"].result.vss_commitments
    # the rotated committee can still sign for the unchanged key
    from mpcium_tpu.protocol.eddsa.signing import EDDSASigningParty

    msg = b"epoch-1 message"
    signers = {
        pid: EDDSASigningParty(
            "s-adv", pid, ["n2", "n3"], parties[pid].result, msg
        )
        for pid in ["n2", "n3"]
    }
    run_protocol(signers)
    assert hm.ed25519_verify(pub, msg, signers["n2"].result)


def test_epoch_mismatch_is_retryable(tmp_path):
    """A node whose keyinfo has rotated but whose share has not (or vice
    versa) must fail signing with the retryable NotEnoughParticipants, not
    join a quorum with a stale polynomial."""
    from mpcium_tpu.cluster import LocalCluster, load_test_preparams

    c = LocalCluster(n_nodes=3, threshold=1, root_dir=str(tmp_path),
                     preparams=load_test_preparams())
    try:
        # EdDSA-only wallet setup is too slow through full keygen; deal
        # shares directly into the stores instead.
        from mpcium_tpu.protocol.base import KeygenShare

        ids = c.node_ids
        parties = {
            pid: EDDSAKeygenParty("w-fence", pid, ids, threshold=1)
            for pid in ids
        }
        run_protocol(parties)
        for pid in ids:
            c.nodes[pid].save_share(parties[pid].result, "w-fence")

        node = c.nodes["node0"]
        info = node.keyinfo.get("ed25519", "w-fence")
        assert info.epoch == 0
        # simulate: rotation finished cluster-wide (shared keyinfo bumped)
        # while this node's share is still the old polynomial
        info.epoch = 1
        node.keyinfo.save("ed25519", "w-fence", info)
        with pytest.raises(NotEnoughParticipants, match="epoch"):
            node.create_signing_session(
                "ed25519", "w-fence", "tx-1", b"\x01" * 32
            )
    finally:
        c.close()


# ---------------------------------------------------------------------------
# 3: safe-prime pool locking + permissions
# ---------------------------------------------------------------------------


def test_pool_take_is_locked_and_private(tmp_path):
    path = tmp_path / "pool.json"
    primes = [pl.gen_safe_prime(48) for _ in range(4)]
    pl._pool_write(path, {"bits": 48, "safe_primes": [str(p) for p in primes]})
    assert (os.stat(path).st_mode & 0o777) == 0o600

    got, errs = [], []

    def taker():
        try:
            got.append(tuple(pl.pool_take(path, count=2, bits=48)))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=taker) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    a, b = got
    # disjoint: no safe prime handed to two consumers
    assert not (set(a) & set(b)), "concurrent pool_take returned shared primes"
    data_left = pl.pool_take(path, count=0, bits=48)
    assert data_left == []


def test_pool_fill_sets_permissions(tmp_path):
    path = tmp_path / "fill.json"
    made = pl.pool_fill(path, target=1, bits=48)
    assert made == 1
    assert (os.stat(path).st_mode & 0o777) == 0o600


# ---------------------------------------------------------------------------
# 4: sender-bound signing commitments / PoKs
# ---------------------------------------------------------------------------


def test_signing_pok_not_replayable_across_senders():
    """A Schnorr PoK produced under party A's bind must not verify under
    party B's bind for the same session (the replay ADVICE.md describes)."""
    from mpcium_tpu.protocol.ecdsa.signing import ECDSASigningParty

    gamma = secrets.randbelow(hm.SECP_N - 1) + 1
    Gamma = hm.secp_mul(gamma, hm.SECP_G)
    sid = "sign:ecdsa:w:tx"
    bind_a = f"{sid}:partyA".encode()
    bind_b = f"{sid}:partyB".encode()
    pok = SchnorrProof.prove(gamma, Gamma, bind=bind_a)
    assert pok.verify(Gamma, bind=bind_a)
    assert not pok.verify(Gamma, bind=bind_b)

    # and the hash commitments now carry the sender in the preimage
    from mpcium_tpu.protocol import commitments as cm

    data = hm.secp_compress(Gamma)
    commit, blind = cm.commit(bind_a + data)
    assert cm.verify(commit, blind, bind_a + data)
    assert not cm.verify(commit, blind, bind_b + data)
