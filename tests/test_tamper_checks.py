"""Active-security checks on OT-MtA (ISSUE 16 tentpole): the KOS
correlation check, the Gilboa ψ-encoding check and the MtA
output-consistency check must catch EVERY wire corruption an active
cheater can apply — blaming exactly the deviating party on exactly the
deviating batch lane (identifiable abort, no misattribution) — while
honest transcripts with checks on stay valid and checks off
(MPCIUM_OT_CHECKS=0) degrades to the passive protocol, loudly
incompatible with a checking peer.

Base OTs are synthesized from their postcondition like
test_mta_ot_pipeline.py; tags are 8 bytes and B = 4 so every case lands
in the tier-1 compile family. The engine raising CohortAbort from these
verdicts is covered in test_mta_ot.py (slow); the scheduler quarantine
in test_cohort_quarantine.py.

Named ``test_tamper_*`` (after the fault-rule family) rather than
``test_mta_ot_*`` deliberately: pytest runs tiers alphabetically, and
this file's shared secp-ladder jit units are the most expensive cold
compile in tier-1 (~70 s on a bare CPU host). Sorting it after the
broad protocol/scheduler coverage keeps a cold, time-boxed tier-1 run
spending its budget on the wide suite first and the EC-heavy
adversarial tail last."""
import hashlib

import numpy as np
import pytest

import jax.numpy as jnp

from mpcium_tpu.core import bignum as bn
from mpcium_tpu.core.bignum import P256
from mpcium_tpu.protocol.ecdsa import mta_ot

Q = mta_ot.Q
B = 4


class DetRng:
    def __init__(self, seed: int):
        self.seed = seed
        self.ctr = 0

    def token_bytes(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            out += hashlib.sha256(
                b"advrng|%d|%d" % (self.seed, self.ctr)
            ).digest()
            self.ctr += 1
        return bytes(out[:n])

    def randbelow(self, n: int) -> int:
        return int.from_bytes(self.token_bytes(40), "big") % n


def synth_leg(seed: int) -> mta_ot.OTMtALeg:
    rng = DetRng(seed)
    leg = mta_ot.OTMtALeg.__new__(mta_ot.OTMtALeg)
    leg.tag = b"t-advs|%d" % seed  # 8 bytes: tier-1 compile family
    leg.rng = DetRng(seed + 1000)
    leg.ctr = 0
    leg.k0 = np.frombuffer(
        rng.token_bytes(mta_ot.KAPPA * 32), np.uint8
    ).reshape(-1, 32).copy()
    leg.k1 = np.frombuffer(
        rng.token_bytes(mta_ot.KAPPA * 32), np.uint8
    ).reshape(-1, 32).copy()
    leg.delta = np.frombuffer(rng.token_bytes(mta_ot.KAPPA), np.uint8) & 1
    leg.keysD = np.where(leg.delta[:, None].astype(bool), leg.k1, leg.k0)
    leg.delta_packed = mta_ot._pack(leg.delta)
    leg._delta_rows = np.nonzero(leg.delta)[0]
    return leg


def _limbs(vals):
    return jnp.asarray(bn.batch_to_limbs(vals, P256))


@pytest.fixture(scope="module")
def inputs():
    # nonzero Bob-side scalars: b ≡ 0 makes B = b·G the identity, whose
    # SEC1 encoding the openings reject (the 2^-256 caveat SECURITY.md
    # documents); a = 0 stays fair game for Alice
    r = DetRng(13)
    a = [r.randbelow(Q) for _ in range(B)]
    g = [r.randbelow(Q - 1) + 1 for _ in range(B)]
    w = [r.randbelow(Q - 1) + 1 for _ in range(B)]
    a[0] = 0
    return a, g, w


# Every wire field an active cheater controls, the party that owns it,
# and the check that must catch its corruption. KOS failures blame
# Alice (she owns the extension matrix and its tags); payload/opening
# failures blame Bob. One distinct lane per case: no misattribution
# means the OTHER three lanes stay clean every time.
CASES = [
    ("U", None, "alice", mta_ot.CHECK_KOS),
    ("kos_xbar", None, "alice", mta_ot.CHECK_KOS),
    ("kos_tbar", None, "alice", mta_ot.CHECK_KOS),
    ("y0", 0, "bob", mta_ot.CHECK_GILBOA),
    ("y1", 1, "bob", mta_ot.CHECK_GILBOA),
    ("D", 0, "bob", mta_ot.CHECK_GILBOA),
    ("B_pt", 1, "bob", mta_ot.CHECK_GILBOA),
    ("Beta_pt", 0, "bob", mta_ot.CHECK_CONSISTENCY),
]


@pytest.mark.parametrize(
    "field,set_idx,party,check", CASES,
    ids=[c[0] for c in CASES],
)
def test_cheater_caught_and_blamed(field, set_idx, party, check, inputs):
    a, g, w = inputs
    lane = CASES.index((field, set_idx, party, check)) % B
    leg = synth_leg(1)
    spec = {"field": field, "lane": lane, "byte": 7, "xor": 0x40}
    if set_idx is not None:
        spec["set"] = set_idx
    leg.set_tamper(spec)
    leg.run_multi(_limbs(a), (_limbs(g), _limbs(w)))
    blames = leg.check_blame()
    assert blames is not None, "checks on but no verdicts collected"
    assert blames[lane] == (party, check), (
        f"tampered {field} lane {lane}: expected blame "
        f"({party}, {check}), got {blames[lane]}"
    )
    others = [bl for i, bl in enumerate(blames) if i != lane]
    assert others == [None] * (B - 1), (
        f"honest lanes misblamed: {blames}"
    )


def test_honest_run_all_verdicts_clean_and_shares_valid(inputs):
    """Checks on, no deviation: every verdict true, blame empty, and
    the MtA relation α + β ≡ a·b holds on every lane — on the wire
    three-round composition AND the fused run_multi, whose verdicts
    must agree (same kernels, same tensors)."""
    a, g, w = inputs
    leg = synth_leg(2)
    msg_a = leg.alice_round1(_limbs(a), 0)
    msgs_b, betas = leg.bob_round2_multi((_limbs(g), _limbs(w)), msg_a, 0)
    alphas = leg.alice_round3_multi(msgs_b)
    wire_blames = leg.check_blame()
    assert wire_blames == [None] * B
    assert set(leg.check_verdicts) == {"kos", "gilboa", "consistency"}
    assert all(np.asarray(v).all() for v in leg.check_verdicts.values())
    for al, be, b_ints in zip(alphas, betas, (g, w)):
        ai = bn.batch_from_limbs(np.asarray(al), P256)
        bi = bn.batch_from_limbs(np.asarray(be), P256)
        for i in range(B):
            assert (ai[i] + bi[i]) % Q == a[i] * b_ints[i] % Q, i

    leg2 = synth_leg(2)
    leg2.run_multi(_limbs(a), (_limbs(g), _limbs(w)))
    assert leg2.check_blame() == [None] * B


def test_checks_off_escape_hatch(monkeypatch, inputs):
    """MPCIUM_OT_CHECKS=0: the passive protocol — no verdicts, no
    blame, no check fields on the wire — and shares still correct."""
    monkeypatch.setenv("MPCIUM_OT_CHECKS", "0")
    a, g, w = inputs
    leg = synth_leg(3)
    msg_a = leg.alice_round1(_limbs(a), 0)
    assert "kos_xbar" not in msg_a and "kos_tbar" not in msg_a
    msgs_b, betas = leg.bob_round2_multi((_limbs(g),), msg_a, 0)
    assert "D" not in msgs_b[0] and "B_pt" not in msgs_b[0]
    (alpha,) = leg.alice_round3_multi(msgs_b)
    assert leg.check_blame() is None
    ai = bn.batch_from_limbs(np.asarray(alpha), P256)
    bi = bn.batch_from_limbs(np.asarray(betas[0]), P256)
    for i in range(B):
        assert (ai[i] + bi[i]) % Q == a[i] * g[i] % Q, i


def test_unchecked_peer_rejected_loudly(inputs):
    """A v3 message missing its check fields (a peer running
    MPCIUM_OT_CHECKS=0 against a checking party) fails with a clear
    contract error, never silently skipping verification."""
    a, g, _w = inputs
    leg = synth_leg(4)
    msg_a = leg.alice_round1(_limbs(a), 0)
    stripped_a = {
        k: v for k, v in msg_a.items()
        if k not in ("kos_xbar", "kos_tbar")
    }
    with pytest.raises(ValueError, match="no KOS tags"):
        leg.bob_round2_multi((_limbs(g),), stripped_a, 0)
    msgs_b, _betas = leg.bob_round2_multi((_limbs(g),), msg_a, 0)
    stripped_b = [
        {k: v for k, v in m.items()
         if k not in ("D", "B_pt", "Beta_pt")}
        for m in msgs_b
    ]
    with pytest.raises(ValueError, match="no Gilboa opening"):
        leg.alice_round3_multi(stripped_b)
