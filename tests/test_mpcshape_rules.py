"""Per-rule mpcshape unit tests: positive + negative snippets for each
MPS9xx rule, signature-template extraction and dim classification, the
``# mpcshape: unbounded-ok`` annotation, suppression syntax, the pow-2
bucket helpers, and the COMPILE_SURFACE runtime matcher semantics.
"""
from __future__ import annotations

import textwrap
from pathlib import Path

import pytest

from mpcium_tpu.analysis.core import ParsedFile
from mpcium_tpu.analysis.shape import (
    build_surface,
    run_shape_parsed,
    shape_predicted,
)
from mpcium_tpu.engine.buckets import BUCKETS, bucket_b, floor_bucket, is_bucket

pytestmark = pytest.mark.lint

REL = "mpcium_tpu/engine/snippet.py"


def sweep(src: str, rel: str = REL, serving=()):
    pf = ParsedFile(Path(rel), rel, textwrap.dedent(src))
    return run_shape_parsed([pf], serving_roots=serving)


def rule_ids(result):
    return [f.rule for f in result.findings]


# -- pow-2 buckets ----------------------------------------------------------


def test_bucket_helpers():
    assert all(is_bucket(b) for b in BUCKETS)
    assert not is_bucket(3) and not is_bucket(0) and not is_bucket(8193)
    assert floor_bucket(1) == 1
    assert floor_bucket(6) == 4
    assert floor_bucket(8192) == 8192
    assert floor_bucket(16384) == 16384
    assert floor_bucket(100000) == 16384
    assert bucket_b(1) == 1
    assert bucket_b(5) == 8
    assert bucket_b(1024) == 1024
    assert bucket_b(10000) == 16384
    assert bucket_b(100000) == 16384  # clamped to the largest bucket
    with pytest.raises(ValueError):
        floor_bucket(0)
    with pytest.raises(ValueError):
        bucket_b(0)


# -- signature extraction + dim classes -------------------------------------


ENGINE_SNIPPET = """
import os
from mpcium_tpu.perf import compile_watch
from mpcium_tpu.engine.buckets import floor_bucket

def serve(shares, party_ids):
    B = len(shares)
    q = len(party_ids)
    mta = os.environ.get("MPCIUM_MTA", "paillier")
    nb = floor_bucket(len(shares))
    _cw = compile_watch.begin("snip.sign", f"B{B}|q{q}|mta={mta}|n{nb}")
    compile_watch.finish(_cw)
"""


def test_template_and_dim_classes():
    result, surface = sweep(ENGINE_SNIPPET)
    recs = surface["engines"]["snip.sign"]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["template"] == "B{B}|q{q}|mta={mta}|n{nb}"
    dims = rec["dims"]
    assert dims["B"]["class"] == "unbounded"  # len() provenance
    assert dims["q"]["class"] == "knob"  # knob-named regardless of len()
    assert dims["mta"]["class"] == "knob"  # env read
    assert dims["nb"]["class"] == "bucketed"  # floor_bucket provenance
    assert rec["finite"] is False  # un-annotated unbounded B


def test_mps901_unbounded_on_serving_path():
    result, _ = sweep(ENGINE_SNIPPET, serving={f"{REL}::serve"})
    assert rule_ids(result) == ["MPS901"]
    assert result.findings[0].key == "snip.sign:B"
    # the same site off the serving set does not fire
    result, _ = sweep(ENGINE_SNIPPET, serving=set())
    assert rule_ids(result) == []


def test_mps901_annotation_clears_and_records_reason():
    src = """
    from mpcium_tpu.perf import compile_watch

    def serve(shares):
        B = len(shares)
        # mpcshape: unbounded-ok — scheduler chunks to pow-2
        _cw = compile_watch.begin("snip.sign", f"B{B}")
        compile_watch.finish(_cw)
    """
    result, surface = sweep(src, serving={f"{REL}::serve"})
    assert rule_ids(result) == []
    rec = surface["engines"]["snip.sign"][0]
    assert rec["finite"] is True
    d = rec["dims"]["B"]
    assert d["annotated"] is True
    assert "pow-2" in d["reason"]


def test_mps901_annotation_on_provenance_line():
    src = """
    from mpcium_tpu.perf import compile_watch

    class P:
        def __init__(self, shares):
            # mpcshape: unbounded-ok — bounded by the intake cap
            self.B = len(shares)

        def serve(self):
            _cw = compile_watch.begin("snip.sign", f"B{self.B}")
            compile_watch.finish(_cw)
    """
    result, surface = sweep(src, serving={f"{REL}::P.serve"})
    assert rule_ids(result) == []
    assert surface["engines"]["snip.sign"][0]["finite"] is True


def test_constant_dim_and_self_attr_provenance():
    src = """
    from mpcium_tpu.perf import compile_watch

    class P:
        def __init__(self, q):
            self.q = q
            self.width = 22

        def serve(self):
            _cw = compile_watch.begin("snip.x", f"q{self.q}|w{self.width}")
            compile_watch.finish(_cw)
    """
    _, surface = sweep(src)
    dims = surface["engines"]["snip.x"][0]["dims"]
    assert dims["q"]["class"] == "knob"
    assert dims["width"]["class"] == "constant"
    assert dims["width"]["value"] == 22


def test_mps_suppression_syntax():
    src = """
    from mpcium_tpu.perf import compile_watch

    def serve(shares):
        B = len(shares)
        # mpclint: disable=MPS901 — covered by an intake cap
        _cw = compile_watch.begin("snip.sign", f"B{B}")
        compile_watch.finish(_cw)
    """
    result, _ = sweep(src, serving={f"{REL}::serve"})
    assert rule_ids(result) == []


# -- MPS902 retrace-per-call ------------------------------------------------


def test_mps902_loop_var_into_static_param():
    src = """
    import functools
    import jax

    @functools.partial(jax.jit, static_argnames=("k",))
    def kern(x, k):
        return x

    def caller(xs, party_ids):
        for pid in party_ids:
            kern(xs, pid)
    """
    result, _ = sweep(src)
    assert rule_ids(result) == ["MPS902"]
    assert result.findings[0].key == "kern:k:loop"


def test_mps902_len_into_static_param():
    src = """
    import functools
    import jax

    @functools.partial(jax.jit, static_argnums=(1,))
    def kern(x, k):
        return x

    def caller(xs):
        return kern(xs, len(xs))
    """
    result, _ = sweep(src)
    assert rule_ids(result) == ["MPS902"]
    assert result.findings[0].key == "kern:k:len"


def test_mps902_constant_static_arg_is_fine():
    src = """
    import functools
    import jax

    @functools.partial(jax.jit, static_argnames=("k",))
    def kern(x, k):
        return x

    def caller(xs):
        for _ in range(3):
            kern(xs, 22)
    """
    result, _ = sweep(src)
    assert rule_ids(result) == []


# -- MPS903 large closure constants -----------------------------------------


def test_mps903_large_module_array_in_jit_body():
    src = """
    import jax
    import numpy as np

    TABLE = np.zeros((64, 128))

    @jax.jit
    def f(x):
        return x + TABLE
    """
    result, _ = sweep(src)
    assert rule_ids(result) == ["MPS903"]
    assert result.findings[0].key == "f:TABLE"


def test_mps903_small_or_passed_arrays_are_fine():
    src = """
    import jax
    import numpy as np

    SMALL = np.arange(16)
    BIG = np.zeros(65536)

    @jax.jit
    def f(x, table):
        return x + SMALL + table

    def caller(x):
        return f(x, BIG)  # passed as an argument: operand, not constant
    """
    result, _ = sweep(src)
    assert rule_ids(result) == []


# -- MPS904 dtype instability -----------------------------------------------


def test_mps904_conflicting_dtypes_across_call_sites():
    src = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def g(x):
        return x

    def a():
        return g(jnp.zeros(4, dtype=jnp.float32))

    def b():
        return g(jnp.zeros(4, dtype=jnp.int32))
    """
    result, _ = sweep(src)
    assert rule_ids(result) == ["MPS904"]
    assert result.findings[0].key == "g:x"


def test_mps904_consistent_dtype_is_fine():
    src = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def g(x):
        return x

    def a():
        return g(jnp.zeros(4, dtype=jnp.uint8))

    def b():
        return g(jnp.ones(8, dtype=jnp.uint8))
    """
    result, _ = sweep(src)
    assert rule_ids(result) == []


# -- MPS905 vmap axes / donation --------------------------------------------


def test_mps905_nonconstant_vmap_axes():
    src = """
    import jax

    def core(x):
        return x

    def mk(axes):
        return jax.vmap(core, in_axes=axes)
    """
    result, _ = sweep(src)
    assert rule_ids(result) == ["MPS905"]


def test_mps906_donated_buffer_read_after_call():
    src = """
    import functools
    import jax

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(buf):
        return buf + 1

    def run(buf):
        out = step(buf)
        return buf + out
    """
    result, _ = sweep(src)
    assert rule_ids(result) == ["MPS906"]
    assert result.findings[0].key == "step:buf:donated-reuse"


def test_mps906_rebound_round_state_chain_is_clean():
    # the donated-round-state pattern the pipelined engines use: ``st =
    # round_step(st)`` re-binds the name at the donating call, so later
    # reads see the step's output pytree, not the donated buffer
    src = """
    import functools
    import jax

    @functools.partial(jax.jit, donate_argnums=(0,))
    def round_step(st):
        return {"x": st["x"] + 1}

    def run(st):
        st = round_step(st)
        st = round_step(st)
        return st["x"]
    """
    result, _ = sweep(src)
    assert rule_ids(result) == []


def test_mps906_read_after_unrebound_donation_still_flags():
    # assigning the result to a DIFFERENT name leaves the donated
    # binding live — reading it afterwards is the real bug
    src = """
    import functools
    import jax

    @functools.partial(jax.jit, donate_argnums=(0,))
    def round_step(st):
        return {"x": st["x"] + 1}

    def run(st):
        out = round_step(st)
        later = st["x"]
        return out, later
    """
    result, _ = sweep(src)
    assert rule_ids(result) == ["MPS906"]


def test_mps905_literal_axes_and_clean_donation_are_fine():
    src = """
    import functools
    import jax

    def core(x):
        return x

    batched = jax.vmap(core, in_axes=(0,))

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(buf):
        return buf + 1

    def run(buf):
        return step(buf)
    """
    result, _ = sweep(src)
    assert rule_ids(result) == []


# -- jit inventory ----------------------------------------------------------


def test_jit_inventory_kinds_and_static_resolution():
    src = """
    import functools
    import jax

    @functools.partial(jax.jit, static_argnames=("mode",))
    def decorated(x, mode):
        return x

    def core(x, n):
        return x

    wrapped = jax.jit(core, static_argnums=(1,))
    batched = jax.vmap(core, in_axes=(0, None))
    """
    _, surface = sweep(src)
    rows = {e["symbol"]: e for e in surface["jit_entries"]}
    assert rows["decorated"]["kind"] == "jit"
    assert rows["decorated"]["static"] == ["mode"]
    assert rows["wrapped"]["kind"] == "wrapped"
    assert rows["wrapped"]["static"] == ["n"]  # argnum mapped to a name
    assert rows["batched"]["kind"] == "vmap"


# -- runtime matcher --------------------------------------------------------


def _surface_for(src, serving=()):
    _, surface = sweep(src, serving=serving)
    return surface


def test_shape_predicted_matcher_semantics():
    surface = _surface_for("""
    import os
    from mpcium_tpu.perf import compile_watch
    from mpcium_tpu.engine.buckets import floor_bucket

    def serve(shares, party_ids):
        # mpcshape: unbounded-ok — pow-2 chunked upstream
        B = len(shares)
        q = len(party_ids)
        mta = os.environ.get("MPCIUM_MTA", "paillier")
        nb = floor_bucket(len(shares))
        _cw = compile_watch.begin("snip.sign", f"B{B}|q{q}|mta={mta}|n{nb}")
        compile_watch.finish(_cw)

    class P:
        def __init__(self):
            self.width = 22

        def serve(self):
            _cw = compile_watch.begin("snip.x", f"w{self.width}")
            compile_watch.finish(_cw)
    """)
    # annotated-unbounded B: any value; knob q/mta: any non-empty;
    # bucketed nb: pow-2 members only
    assert shape_predicted(surface, "snip.sign", "B4096|q2|mta=ot|n1024")
    assert shape_predicted(surface, "snip.sign", "B7|q3|mta=paillier|n8")
    assert not shape_predicted(surface, "snip.sign", "B7|q3|mta=ot|n100")
    assert not shape_predicted(surface, "snip.sign", "B7|q|mta=ot|n8")
    assert not shape_predicted(surface, "snip.sign", "B7|q2|mta=ot")
    # constant dim: exact value
    assert shape_predicted(surface, "snip.x", "w22")
    assert not shape_predicted(surface, "snip.x", "w23")
    # unknown engine never predicted
    assert not shape_predicted(surface, "nope", "B1")


def test_unannotated_unbounded_dim_never_matches():
    surface = _surface_for("""
    from mpcium_tpu.perf import compile_watch

    def helper(shares):
        B = len(shares)
        _cw = compile_watch.begin("snip.h", f"B{B}")
        compile_watch.finish(_cw)
    """)
    # off the serving path: no MPS901, but the matcher still refuses —
    # an unbounded dim with no contract is an analysis gap at runtime
    assert not shape_predicted(surface, "snip.h", "B64")


def test_surface_counts_and_render_shape():
    result, surface = sweep(ENGINE_SNIPPET)
    assert surface["counts"]["engines"] == 1
    assert surface["counts"]["signatures"] == 1
    assert surface["counts"]["finite"] is False
    rebuilt = build_surface([], [])
    assert rebuilt["counts"] == {
        "engines": 0, "signatures": 0, "jit_entries": 0, "finite": True,
    }
