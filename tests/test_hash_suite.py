"""Device hash suite (ISSUE 11): every kernel in ops.hash_suite must be
byte-identical to its host oracle — hashlib for the FIPS 180-4 digests,
numpy packbits for the packed transpose, and mta_ot's host PRG / pad
derivation for the OT kernels. These are the proofs that let the device
OT path and the eddsa device hashes ship without a wire version bump."""
import hashlib

import numpy as np
import pytest

import jax.numpy as jnp

from mpcium_tpu.ops import hash_suite as hs


def _rows(seed: bytes, n: int, width: int) -> np.ndarray:
    out = bytearray()
    ctr = 0
    while len(out) < n * width:
        out += hashlib.sha256(seed + ctr.to_bytes(4, "little")).digest()
        ctr += 1
    return np.frombuffer(bytes(out[: n * width]), np.uint8).reshape(n, width)


# ---------------------------------------------------------------------------
# SHA-256 / SHA-512 vs hashlib (FIPS 180-4)
# ---------------------------------------------------------------------------


def test_sha256_known_answer():
    msg = np.frombuffer(b"abc", np.uint8)
    assert bytes(np.asarray(hs.sha256(msg))) == bytes.fromhex(
        "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"
    )


def test_sha512_known_answer():
    msg = np.frombuffer(b"abc", np.uint8)
    assert bytes(np.asarray(hs.sha512(msg))) == bytes.fromhex(
        "ddaf35a193617abacc417349ae20413112e6fa4e89a97ea20a9eeee64b55d39a"
        "2192992a274fc1a836ba3c23a3feebbd454d4423643ce80e2a9ac94fa54ca49f"
    )


@pytest.mark.parametrize("length", [0, 1, 3, 55, 56, 63, 64, 100, 200])
def test_sha256_matches_hashlib(length):
    rows = _rows(b"s256|%d" % length, 8, max(length, 1))[:, :length]
    got = np.asarray(hs.sha256(jnp.asarray(rows)))
    for i in range(rows.shape[0]):
        assert bytes(got[i]) == hashlib.sha256(rows[i].tobytes()).digest()


@pytest.mark.parametrize(
    "length",
    # 111/112 straddle the single-block padding boundary, 128/240/300
    # force the multi-block loop, 0 is the degenerate message
    [0, 1, 3, 64, 111, 112, 127, 128, 240, 300],
)
def test_sha512_matches_hashlib(length):
    rows = _rows(b"s512|%d" % length, 8, max(length, 1))[:, :length]
    got = np.asarray(hs.sha512(jnp.asarray(rows)))
    for i in range(rows.shape[0]):
        assert bytes(got[i]) == hashlib.sha512(rows[i].tobytes()).digest()


def test_sha512_challenge_batch_shape():
    """The eddsa challenge shape: a (B, 96) batch (R‖A‖M with 32-byte
    messages) hashed as one dispatch, vs per-row hashlib."""
    rows = _rows(b"chal", 32, 96)
    got = np.asarray(hs.sha512(jnp.asarray(rows)))
    assert got.shape == (32, 64)
    for i in range(32):
        assert bytes(got[i]) == hashlib.sha512(rows[i].tobytes()).digest()


def test_sha512_bytes_single_digest():
    for msg in (b"", b"x", b"m" * 200):
        assert hs.sha512_bytes(msg) == hashlib.sha512(msg).digest()


# ---------------------------------------------------------------------------
# packed bit-transpose vs numpy packbits
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shape", [(8, 3), (16, 5), (24, 1), (128, 64), (256, 16)])
def test_transpose_matches_numpy(shape):
    R, C = shape
    packed = _rows(b"tr|%d|%d" % shape, R, C)
    bits = np.unpackbits(packed, axis=-1, bitorder="little")  # (R, 8C)
    want = np.packbits(bits.T, axis=-1, bitorder="little")  # (8C, R/8)
    got = np.asarray(hs.ot_transpose_device(jnp.asarray(packed)))
    assert got.shape == (8 * C, R // 8)
    assert np.array_equal(got, want)


def test_transpose_involution():
    packed = _rows(b"inv", 128, 16)
    once = hs.ot_transpose_device(jnp.asarray(packed))
    twice = np.asarray(hs.ot_transpose_device(once))
    assert np.array_equal(twice, packed)


def test_pack_unpack_bits_roundtrip():
    packed = _rows(b"pb", 4, 12)
    bits = np.asarray(hs.unpack_bits_core(jnp.asarray(packed)))
    assert np.array_equal(
        bits, np.unpackbits(packed, axis=-1, bitorder="little")
    )
    assert np.array_equal(
        np.asarray(hs.pack_bits_core(jnp.asarray(bits))), packed
    )


# ---------------------------------------------------------------------------
# OT kernels vs the host path in mta_ot
# ---------------------------------------------------------------------------


def test_prg_expand_matches_host_prg():
    from mpcium_tpu.protocol.ecdsa import mta_ot

    seeds = _rows(b"prg-seeds", 6, 32)
    tag = b"t-hs|v2|9"
    prefix = b"mpcium-ot-prg|" + tag
    for nblk, blk_off in ((1, 0), (3, 0), (4, 7)):
        want = mta_ot._prg(seeds, nblk * 32, tag, blk_off)
        got = np.asarray(hs.prg_expand_device(prefix, seeds, nblk, blk_off))
        assert np.array_equal(got, want), (nblk, blk_off)


def test_pad_hash_matches_host_rows():
    from mpcium_tpu.protocol.ecdsa.mta_ot import _hash_rows

    rows = _rows(b"pad-rows", 64, 16)
    prefix = b"mpcium-ot-pad|t-hs|v2|9|s1"
    m_off = 37
    idx = np.arange(m_off, m_off + 64, dtype=np.uint32).view(np.uint8)
    want = _hash_rows(prefix, np.concatenate([rows, idx.reshape(64, 4)], axis=1))
    got = np.asarray(
        hs.pad_hash_device(
            jnp.asarray(np.frombuffer(prefix, np.uint8)),
            jnp.asarray(rows),
            jnp.uint32(m_off),
        )
    )
    assert np.array_equal(got, want)


def test_le_bytes_helpers():
    x = jnp.asarray(np.array([0, 1, 0x1234, 0xDEADBEEF], np.uint32))
    le32 = np.asarray(hs.le32_bytes(x))
    assert np.array_equal(
        le32, np.array([0, 1, 0x1234, 0xDEADBEEF], np.uint32).view(np.uint8).reshape(4, 4)
    )
    le16 = np.asarray(hs.le16_bytes(jnp.asarray(np.array([0, 0x1234], np.uint32))))
    assert np.array_equal(
        le16, np.array([0, 0x1234], np.uint16).view(np.uint8).reshape(2, 2)
    )


# ---------------------------------------------------------------------------
# eddsa challenge: device vs hashlib, and the ops.sha256 delegation shim
# ---------------------------------------------------------------------------


def test_challenge_device_matches_hashlib():
    from mpcium_tpu.engine import eddsa_batch as eb

    R = _rows(b"R", 8, 32)
    A = _rows(b"A", 8, 32)
    M = _rows(b"M", 8, 32)
    got = np.asarray(eb.challenge_device(R, A, M))
    for i in range(8):
        want = hashlib.sha512(
            R[i].tobytes() + A[i].tobytes() + M[i].tobytes()
        ).digest()
        assert bytes(got[i]) == want


def test_challenge_hashes_paths_agree(monkeypatch):
    """challenge_hashes must produce the same bytes with the device path
    on and off, for equal-length and ragged batches."""
    from mpcium_tpu.engine import eddsa_batch as eb

    R = _rows(b"R2", 4, 32)
    A = _rows(b"A2", 4, 32)
    equal = [bytes(_rows(b"m%d" % i, 1, 32)[0]) for i in range(4)]
    ragged = [b"x" * (i + 1) for i in range(4)]
    for msgs in (equal, ragged):
        monkeypatch.setenv("MPCIUM_EDDSA_DEVICE_HASH", "1")
        dev = eb.challenge_hashes(R, A, msgs)
        monkeypatch.setenv("MPCIUM_EDDSA_DEVICE_HASH", "0")
        host = eb.challenge_hashes(R, A, msgs)
        assert np.array_equal(dev, host)
        for i, m in enumerate(msgs):
            want = hashlib.sha512(
                R[i].tobytes() + A[i].tobytes() + m
            ).digest()
            assert bytes(dev[i]) == want


def test_ops_sha256_shim_unchanged():
    from mpcium_tpu.ops.sha256 import sha256 as dev_sha256

    rows = _rows(b"shim", 4, 96)
    got = np.asarray(dev_sha256(jnp.asarray(rows)))
    for i in range(4):
        assert bytes(got[i]) == hashlib.sha256(rows[i].tobytes()).digest()
