"""Field-arithmetic property tests vs python-int ground truth."""
import secrets

import jax.numpy as jnp
import numpy as np
import pytest

from mpcium_tpu.core import bignum as bn
from mpcium_tpu.core import fields as fl
from mpcium_tpu.core import hostmath as hm

PROF = bn.P256
FIELDS = {
    "ed25519": (fl.ed25519_field, hm.ED_P),
    "secp256k1": (fl.secp256k1_field, hm.SECP_P),
}


def rand_elems(n, p):
    return [secrets.randbelow(p) for _ in range(n)]


@pytest.mark.parametrize("name", list(FIELDS))
def test_field_mul_add_sub(name):
    mk, p = FIELDS[name]
    F = mk()
    n = 8
    xs, ys = rand_elems(n, p), rand_elems(n, p)
    lx = jnp.asarray(F.from_ints(xs))
    ly = jnp.asarray(F.from_ints(ys))
    assert F.to_ints(F.mul(lx, ly)) == [x * y % p for x, y in zip(xs, ys)]
    assert F.to_ints(F.add(lx, ly)) == [(x + y) % p for x, y in zip(xs, ys)]
    assert F.to_ints(F.sub(lx, ly)) == [(x - y) % p for x, y in zip(xs, ys)]
    assert F.to_ints(F.neg(lx)) == [(-x) % p for x in xs]


@pytest.mark.parametrize("name", list(FIELDS))
def test_field_redundant_chains(name):
    """Long chains of non-canonical intermediates stay correct."""
    mk, p = FIELDS[name]
    F = mk()
    xs = rand_elems(4, p)
    acc = jnp.asarray(F.from_ints(xs))
    ref = list(xs)
    for i in range(12):
        acc = F.mul(acc, acc) if i % 3 else F.add(acc, acc)
        ref = [x * x % p if i % 3 else 2 * x % p for x in ref]
    assert F.to_ints(acc) == ref


@pytest.mark.parametrize("name", list(FIELDS))
def test_field_edge_values(name):
    mk, p = FIELDS[name]
    F = mk()
    xs = [0, 1, p - 1, p - 2, 2]
    lx = jnp.asarray(F.from_ints(xs))
    assert F.to_ints(F.mul(lx, lx)) == [x * x % p for x in xs]
    assert list(np.asarray(F.is_zero(lx))) == [x == 0 for x in xs]


@pytest.mark.parametrize("name", list(FIELDS))
def test_field_inverse(name):
    mk, p = FIELDS[name]
    F = mk()
    xs = [x + 1 for x in rand_elems(4, p - 1)]
    lx = jnp.asarray(F.from_ints(xs))
    assert F.to_ints(F.inv(lx)) == [pow(x, -1, p) for x in xs]


def test_ed25519_sqrt():
    S = fl.Ed25519Sqrt()
    p = hm.ED_P
    xs = rand_elems(4, p)
    squares = [x * x % p for x in xs]
    lx = jnp.asarray(S.F.from_ints(squares))
    roots, ok = S.sqrt(lx)
    assert all(np.asarray(ok))
    got = S.F.to_ints(roots)
    for g, sq in zip(got, squares):
        assert g * g % p == sq
    # a non-residue must report ok=False
    nr = 2  # 2 is a non-residue mod 2^255-19
    assert pow(nr, (p - 1) // 2, p) == p - 1
    _, ok2 = S.sqrt(jnp.asarray(S.F.from_ints([nr])))
    assert not np.asarray(ok2)[0]


def test_secp256k1_sqrt():
    S = fl.Secp256k1Sqrt()
    p = hm.SECP_P
    xs = rand_elems(4, p)
    squares = [x * x % p for x in xs]
    roots, ok = S.sqrt(jnp.asarray(S.F.from_ints(squares)))
    assert all(np.asarray(ok))
    for g, sq in zip(S.F.to_ints(roots), squares):
        assert g * g % p == sq
    # find a non-residue
    nr = 3
    while pow(nr, (p - 1) // 2, p) != p - 1:
        nr += 1
    _, ok2 = S.sqrt(jnp.asarray(S.F.from_ints([nr])))
    assert not np.asarray(ok2)[0]
