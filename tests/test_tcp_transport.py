"""TCP bus: same four delivery semantics as loopback, across sockets."""
import threading
import time

import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu.transport.api import Permanent, QueueConfig, TransportError
from mpcium_tpu.transport.tcp import BrokerServer, TcpClient, tcp_transport


@pytest.fixture()
def broker():
    b = BrokerServer(port=0, queue_config=QueueConfig(max_deliver=3))
    yield b
    b.close()


def _wait(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def test_pubsub_fanout(broker):
    t1 = tcp_transport(broker.host, broker.port)
    t2 = tcp_transport(broker.host, broker.port)
    got = []
    t1.pubsub.subscribe("topic:x", lambda d: got.append(("t1", d)))
    t2.pubsub.subscribe("topic:x", lambda d: got.append(("t2", d)))
    time.sleep(0.05)  # sub registration in flight
    t1.pubsub.publish("topic:x", b"hello")
    assert _wait(lambda: len(got) == 2)
    assert sorted(got) == [("t1", b"hello"), ("t2", b"hello")]
    t1.client.close()
    t2.client.close()


def test_direct_ack_and_failure(broker):
    t1 = tcp_transport(broker.host, broker.port)
    t2 = tcp_transport(broker.host, broker.port)
    got = []
    t2.direct.listen("direct:n2", lambda d: got.append(d))
    time.sleep(0.05)
    t1.direct.send("direct:n2", b"ping")  # blocks until acked
    assert got == [b"ping"]
    with pytest.raises(TransportError):
        t1.client.direct_send("direct:nobody", b"x", timeout_s=0.05, attempts=2,
                              retry_delay_s=0.01)
    t1.client.close()
    t2.client.close()


def test_queue_semantics(broker):
    t = tcp_transport(broker.host, broker.port)
    dead = []
    t.set_dead_letter_handler(lambda topic, data, n: dead.append((topic, n)))
    attempts = []

    def failing(d):
        attempts.append(d)
        raise RuntimeError("boom")

    t.queues.dequeue("q.f.*", failing)
    time.sleep(0.05)
    t.queues.enqueue("q.f.1", b"m", idempotency_key="k1")
    t.queues.enqueue("q.f.1", b"m", idempotency_key="k1")  # dedup
    assert _wait(lambda: len(dead) == 1, timeout=10)
    assert len(attempts) == 3
    # durable buffering before consumer exists
    t.queues.enqueue("q.late.1", b"early")
    got = []
    t.queues.dequeue("q.late.*", lambda d: got.append(d))
    assert _wait(lambda: got == [b"early"])
    t.client.close()


def test_reply_wrapper(broker):
    t1 = tcp_transport(broker.host, broker.port)
    t2 = tcp_transport(broker.host, broker.port)
    import json

    seen = []
    t2.pubsub.subscribe("cmd", lambda d: seen.append(json.loads(d)))
    time.sleep(0.05)
    t1.pubsub.publish_with_reply("cmd", "inbox.1", b"\x01\x02")
    assert _wait(lambda: len(seen) == 1)
    assert seen[0]["reply"] == "inbox.1"
    assert bytes.fromhex(seen[0]["data"]) == b"\x01\x02"
    t1.client.close()
    t2.client.close()


def test_full_cluster_over_tcp(tmp_path):
    """A 3-node MPC cluster across the TCP bus: wallet + EdDSA sign."""
    from mpcium_tpu import wire
    from mpcium_tpu.cluster import LocalCluster, load_test_preparams
    from mpcium_tpu.core import hostmath as hm

    cluster = LocalCluster(
        n_nodes=3, threshold=1, root_dir=str(tmp_path),
        preparams=load_test_preparams(), transport="tcp",
    )
    try:
        ev = cluster.create_wallet_sync("tcp-wallet")
        tx = b"tcp tx"
        res = cluster.sign_sync(
            wire.SignTxMessage(
                key_type="ed25519", wallet_id="tcp-wallet",
                network_internal_code="sol", tx_id="tcp-tx-1", tx=tx,
            )
        )
        assert res.result_type == wire.RESULT_SUCCESS, res.error_reason
        assert hm.ed25519_verify(
            bytes.fromhex(ev.eddsa_pub_key), tx, bytes.fromhex(res.signature)
        )
    finally:
        cluster.close()


def test_broker_journal_survives_restart(tmp_path):
    """File-backed queue durability: a broker restart redelivers every
    enqueued-but-unacked message (reference JetStream WorkQueue file
    retention, message_queue.go:56-63)."""
    journal = str(tmp_path / "queue.jsonl")
    b1 = BrokerServer(port=0, journal_path=journal)
    t1 = tcp_transport(b1.host, b1.port)
    t1.queues.enqueue("mpc.results.a", b"payload-1", idempotency_key="k1")
    t1.queues.enqueue("mpc.results.b", b"payload-2")
    time.sleep(0.3)  # let the broker journal the enqueues
    t1.client.close()
    b1.close()  # broker dies with no consumer ever attached

    b2 = BrokerServer(port=0, journal_path=journal)
    t2 = tcp_transport(b2.host, b2.port)
    got = []
    evt = threading.Event()

    def handler(data):
        got.append(data)
        if len(got) == 2:
            evt.set()

    sub = t2.queues.dequeue("mpc.results.*", handler)
    assert evt.wait(10), f"redelivery after restart failed (got {got})"
    assert sorted(got) == [b"payload-1", b"payload-2"]
    # acked messages are NOT redelivered by the next restart
    time.sleep(0.3)
    sub.unsubscribe()
    t2.client.close()
    b2.close()
    b3 = BrokerServer(port=0, journal_path=journal)
    t3 = tcp_transport(b3.host, b3.port)
    got3 = []
    t3.queues.dequeue("mpc.results.*", got3.append)
    time.sleep(0.8)
    assert got3 == []
    t3.client.close()
    b3.close()


def test_broker_auth(tmp_path):
    """Token auth: unauthenticated or wrong-token clients are rejected
    (reference NATS credentials, main.go:346-359)."""
    b = BrokerServer(port=0, auth_token="s3cret-token")
    try:
        # correct token works end-to-end
        t_ok = tcp_transport(b.host, b.port, auth_token="s3cret-token")
        got = []
        evt = threading.Event()
        t_ok.pubsub.subscribe("x.y", lambda d: (got.append(d), evt.set()))
        time.sleep(0.2)
        t_ok.pubsub.publish("x.y", b"hello")
        assert evt.wait(5)

        # wrong token rejected at connect
        with pytest.raises(TransportError):
            tcp_transport(b.host, b.port, auth_token="wrong")

        # tokenless client: frames before auth are ignored/dropped
        t_no = tcp_transport(b.host, b.port)
        got2 = []
        t_no.pubsub.subscribe("x.y", got2.append)
        time.sleep(0.2)
        t_ok.pubsub.publish("x.y", b"again")
        time.sleep(0.5)
        assert got2 == [], "unauthenticated subscribe must not receive"
        t_no.client.close()
        t_ok.client.close()
    finally:
        b.close()


def test_encrypted_channel_roundtrip():
    """AEAD channel (X25519 + token-bound HKDF + ChaCha20-Poly1305):
    pub/sub, direct and queue traffic all work over encrypt=True, and the
    wire carries no plaintext frames."""
    import socket as _socket
    import threading as _threading

    b = BrokerServer(port=0, auth_token="chan-token", encrypt=True)
    try:
        t1 = tcp_transport(b.host, b.port, auth_token="chan-token",
                           encrypt=True)
        t2 = tcp_transport(b.host, b.port, auth_token="chan-token",
                           encrypt=True)
        got = []
        evt = _threading.Event()
        sub = t2.pubsub.subscribe(
            "enc.topic", lambda d: (got.append(d), evt.set())
        )
        time.sleep(0.1)  # sub registration in flight
        t1.pubsub.publish("enc.topic", b"secret-payload")
        assert evt.wait(5) and got == [b"secret-payload"]
        sub.unsubscribe()

        # raw socket peeking: past the plaintext hello, frames are
        # ciphertext (no JSON braces / payload bytes on the wire)
        s = _socket.create_connection((b.host, b.port), timeout=5)
        s.sendall(b'{"op":"ehello","epub":"' + b"00" * 32 + b'"}\n')
        line = b""
        s.settimeout(5)
        while b"\n" not in line:
            line += s.recv(4096)
        import json as _json

        hello = _json.loads(line.split(b"\n", 1)[0])
        assert hello["op"] == "ehello" and len(hello["epub"]) == 64
        s.close()
    finally:
        b.close()


def test_encrypted_channel_rejects_wrong_token():
    from mpcium_tpu.transport.api import TransportError

    b = BrokerServer(port=0, auth_token="right-token", encrypt=True)
    try:
        with pytest.raises(TransportError):
            TcpClient(b.host, b.port, auth_token="wrong-token", encrypt=True)
    finally:
        b.close()


def test_hashed_token_config():
    """The broker accepts a sha256:<hex> stored token; clients still
    present the plaintext."""
    import hashlib

    digest = "sha256:" + hashlib.sha256(b"pw12345").hexdigest()
    b = BrokerServer(port=0, auth_token=digest)
    try:
        t = tcp_transport(b.host, b.port, auth_token="pw12345")
        t.pubsub.publish("x", b"ok")  # connection is live and authed
    finally:
        b.close()


def test_queue_ttl_expires_orphaned_results():
    """A message on a per-tx result topic whose sole requester is gone
    must not pend forever: once past queue_ttl_s it takes the
    dead-letter path on the next dispatch attempt (triggered by any new
    subscription's pending flush) instead of accumulating in memory,
    the journal, and every standby."""
    b = BrokerServer(port=0, queue_ttl_s=0.3)
    try:
        t = tcp_transport(b.host, b.port)
        dead = []
        t.set_dead_letter_handler(
            lambda topic, data, n: dead.append((topic, data))
        )
        # no subscriber for this per-tx topic — the requester timed out
        # and unsubscribed before the node published the result
        t.queues.enqueue("q.result.tx-orphan", b"late-result")
        assert _wait(lambda: len(b._pending_q) == 1)
        time.sleep(0.4)  # let the TTL lapse
        # any unrelated subscription flushes pending through dispatch
        t.queues.dequeue("q.other.*", lambda d: None)
        assert _wait(lambda: ("q.result.tx-orphan", b"late-result") in dead)
        assert _wait(lambda: len(b._pending_q) == 0)
        assert not b._enq_ts
        # a live (young) message is NOT expired by the flush
        got = []
        t.queues.enqueue("q.result.tx-live", b"r2")
        t.queues.dequeue("q.result.tx-live", lambda d: got.append(d))
        assert _wait(lambda: got == [b"r2"])
        t.client.close()
    finally:
        b.close()


def test_queue_ttl_sweep_on_idle_broker():
    """The sweep thread must expire orphans even when NO new
    subscription ever triggers a pending flush (quiet broker)."""
    b = BrokerServer(port=0, queue_ttl_s=0.3)
    b_sweep_interval_floor = 1.0  # _ttl_sweep_loop clamps to >= 1 s
    try:
        t = tcp_transport(b.host, b.port)
        dead = []
        t.set_dead_letter_handler(
            lambda topic, data, n: dead.append((topic, data))
        )
        time.sleep(0.05)  # dead_sub registration in flight
        t.queues.enqueue("q.result.tx-idle", b"late")
        assert _wait(lambda: len(b._pending_q) == 1)
        # no dequeue() anywhere: only the sweep can expire it
        assert _wait(
            lambda: ("q.result.tx-idle", b"late") in dead,
            timeout=b_sweep_interval_floor + 2.0,
        )
        assert len(b._pending_q) == 0 and not b._enq_ts
        t.client.close()
    finally:
        b.close()


def test_broker_kv_roundtrip_and_transient():
    from mpcium_tpu.store.broker_kv import BrokerKV

    b = BrokerServer(port=0)
    try:
        t = tcp_transport(b.host, b.port)
        kv = BrokerKV(t.client)
        assert kv.get("mpc_peers/node0") is None
        kv.put("mpc_peers/node0", b"uuid-0")
        kv.put("mpc_peers/node1", b"uuid-1")
        kv.put_transient("ready/node0", b"171000")
        assert kv.get("mpc_peers/node0") == b"uuid-0"
        assert kv.keys("mpc_peers/") == ["mpc_peers/node0", "mpc_peers/node1"]
        assert kv.keys("ready/") == ["ready/node0"]
        kv.delete("mpc_peers/node1")
        assert kv.get("mpc_peers/node1") is None
        assert kv.keys("mpc_peers/") == ["mpc_peers/node0"]
        # binary-safe values
        kv.put("keyinfo/w1", bytes(range(256)))
        assert kv.get("keyinfo/w1") == bytes(range(256))
        t.client.close()
    finally:
        b.close()


def test_broker_kv_journal_durability(tmp_path):
    """Durable keys survive a broker restart via the journal; transient
    (liveness) keys do not."""
    from mpcium_tpu.store.broker_kv import BrokerKV

    journal = str(tmp_path / "q.jsonl")
    b1 = BrokerServer(port=0, journal_path=journal, journal_fsync=False)
    t1 = tcp_transport(b1.host, b1.port)
    kv1 = BrokerKV(t1.client)
    kv1.put("keyinfo/w1", b"meta")
    kv1.put("mpc_peers/node0", b"uuid-0")
    kv1.put_transient("ready/node0", b"hb")
    kv1.delete("mpc_peers/node0")
    t1.client.close()
    b1.close()

    b2 = BrokerServer(port=0, journal_path=journal, journal_fsync=False)
    try:
        t2 = tcp_transport(b2.host, b2.port)
        kv2 = BrokerKV(t2.client)
        assert kv2.get("keyinfo/w1") == b"meta"
        assert kv2.get("mpc_peers/node0") is None  # deleted before restart
        assert kv2.keys("ready/") == []  # transient: not journaled
        t2.client.close()
    finally:
        b2.close()


def test_broker_kv_replicates_to_standby():
    """Durable KV state reaches a hot standby (snapshot + stream) and is
    readable after the client fails over."""
    from mpcium_tpu.store.broker_kv import BrokerKV

    primary = BrokerServer(port=0)
    t = tcp_transport(primary.host, primary.port)
    kv = BrokerKV(t.client)
    kv.put("keyinfo/pre", b"in-snapshot")
    standby = BrokerServer(port=0, follow=(primary.host, primary.port))
    try:
        assert _wait(lambda: standby._rep_synced.is_set())
        assert standby._kv.get("keyinfo/pre") is not None
        kv.put("keyinfo/live", b"streamed")
        kv.put_transient("ready/node0", b"hb")
        assert _wait(lambda: "keyinfo/live" in standby._kv)
        assert "ready/node0" not in standby._kv  # transient: not streamed
        # failover: client configured with both addresses reads from standby
        t2 = tcp_transport(primary.host, primary.port,
                           standbys=[(standby.host, standby.port)])
        kv2 = BrokerKV(t2.client)
        primary.close()
        t.client.close()
        assert _wait(lambda: kv2.get("keyinfo/live") == b"streamed",
                     timeout=15.0)
        assert kv2.get("keyinfo/pre") == b"in-snapshot"
        t2.client.close()
    finally:
        standby.close()
