"""Warm-manifest drift gate (mpcium_tpu/warm/manifest.py): the pre-warm
work-list must be a pure, gap-free function of the committed
COMPILE_SURFACE.json — knobs × engine/buckets.BUCKETS over
serving-reachable templates only — keyed by the host/toolchain
fingerprint and ordered hot-shapes-first. Pure stdlib: no jax import.
"""
import json
import sys

import pytest

from mpcium_tpu.engine.buckets import BUCKETS
from mpcium_tpu.perf import envfp
from mpcium_tpu.warm import manifest as wm

pytestmark = pytest.mark.perf


@pytest.fixture(scope="module")
def surface():
    return wm.load_default_surface()


@pytest.fixture()
def knobs():
    return wm.default_knobs()


def test_no_jax_needed(surface, knobs):
    """Enumeration must never warm a backend — the daemon builds the
    work-list before deciding whether to compile anything at all."""
    import re

    wm.build_manifest(surface, knobs)
    for mod in ("mpcium_tpu.warm.manifest", "mpcium_tpu.warm"):
        src = open(sys.modules[mod].__file__).read()
        assert not re.search(r"^\s*(import jax|from jax)", src, re.M), mod


def test_enumeration_is_knobs_times_buckets(surface, knobs):
    """The drift gate: every serving template × every knob combination ×
    every pow-2 bucket, nothing more, nothing silently less."""
    man = wm.build_manifest(surface, knobs)
    by_engine = {}
    for e in man["entries"]:
        by_engine[e["engine"]] = by_engine.get(e["engine"], 0) + 1
    nb = len(BUCKETS)
    assert by_engine == {
        "eddsa.sign": nb,            # B × {q}
        "dkg.run": nb * 2,           # B × {q} × {ed25519, secp256k1}
        "gg18.sign": nb * 2,         # B × {q} × {paillier, ot}
        "party.dkg": nb * 2,
        "party.ecdsa": nb,
        "party.reshare": nb * 2,     # B × {q} × key_type × {t_new}
        "reshare.run": nb * 2,       # B × key_type × {t_new}
    }
    assert man["counts"]["entries"] == 12 * nb
    assert man["gaps"] == []


def test_serving_only(surface, knobs):
    """party.eddsa is serving:false on the committed surface (the node
    signs through the batched engine, not the per-party path) — it must
    not burn warm budget."""
    man = wm.build_manifest(surface, knobs)
    assert not any(e["engine"] == "party.eddsa" for e in man["entries"])


def test_every_entry_is_statically_predicted(surface, knobs):
    """Round-trip: every enumerated shape must match its own surface
    template, i.e. a warmed shape can never ledger predicted:false."""
    from mpcium_tpu.analysis.shape.surface import shape_predicted

    for e in wm.manifest_entries(wm.build_manifest(surface, knobs)):
        assert shape_predicted(surface, e.engine, e.shape), e


def test_scheme_and_bucket_filters(surface, knobs):
    man = wm.build_manifest(surface, knobs, schemes=("eddsa",), max_b=8)
    assert {e["engine"] for e in man["entries"]} == {"eddsa.sign"}
    assert sorted(e["B"] for e in man["entries"]) == [1, 2, 4, 8]
    man = wm.build_manifest(surface, knobs, buckets=(2,),
                            schemes=("ecdsa",))
    assert {(e["engine"], e["B"]) for e in man["entries"]} == {
        ("gg18.sign", 2), ("party.ecdsa", 2),
    }


def test_traffic_prioritizes_hot_shapes(surface, knobs):
    traffic = {("eddsa.sign", "B4096|q2"): 10.0, ("__B__", "64"): 1.0}
    man = wm.build_manifest(surface, knobs, schemes=("eddsa",),
                            traffic=traffic)
    shapes = [e["shape"] for e in man["entries"]]
    assert shapes[0] == "B4096|q2"  # exact ledger match outranks all
    assert shapes[1] == "B64|q2"    # bench-history batch size next
    # cold shapes keep the deterministic small-B-first order
    assert shapes[2] == "B1|q2"


def test_traffic_weights_from_ledger_and_history(tmp_path):
    ledger = tmp_path / "COMPILE_LEDGER.json"
    ledger.write_text(json.dumps({"entries": [
        {"engine": "eddsa.sign", "shape": "B2|q2"},
        {"engine": "eddsa.sign", "shape": "B2|q2"},
    ]}))
    history = tmp_path / "PERF_history.jsonl"
    history.write_text(
        json.dumps({"context": {"ed25519_batch": 4096}}) + "\n"
        + "not json\n"
    )
    t = wm.load_traffic(str(ledger), str(history))
    assert t[("eddsa.sign", "B2|q2")] == 2.0
    assert t[("__B__", "4096")] == 0.5
    # missing files are silently empty — a fresh node has no traffic yet
    assert wm.load_traffic(str(tmp_path / "nope"), None) == {}


def test_coverage_check_clean_on_committed_surface(surface, knobs):
    assert wm.coverage_check(surface, knobs) == []


def test_coverage_check_flags_empty_knob(surface):
    bad = wm.WarmKnobs(q=(), key_type=("ed25519",),
                       mta_impl=("paillier",), t_new=(1,))
    problems = wm.coverage_check(surface, bad)
    assert problems and any("q" in p for p in problems)


def test_manifest_key_stability_and_invalidation():
    """Same host+toolchain → same key (a restart reuses the cache); a
    jax version bump → loud invalidation with the reason named."""
    a, b = wm.manifest_key(), wm.manifest_key()
    assert a == b
    ok, _reason = wm.key_matches(a, b)
    assert ok
    bumped = dict(a, jax="999.0.0")
    ok, reason = wm.key_matches(bumped, a)
    assert not ok
    assert "jax" in reason and "999.0.0" in reason
    # a missing stored key (pre-warm cache from an older layout) never
    # validates — stale artifacts are skipped, not trusted
    ok, reason = wm.key_matches(None, a)
    assert not ok


def test_envfp_host_fingerprint_stable():
    """ISSUE 13 satellite: same host → same fingerprint, every time —
    the property the cache-dir naming and manifest key both lean on."""
    fp1 = envfp.host_fingerprint()
    fp2 = envfp.host_fingerprint()
    assert fp1 == fp2
    assert len(fp1) == 12 and all(c in "0123456789abcdef" for c in fp1)
    key = wm.manifest_key()
    assert key["host"] == fp1
    assert key["jax"] == envfp.jax_version()


def test_knobs_from_config_follow_threshold():
    from mpcium_tpu.config import AppConfig

    cfg = AppConfig(mpc_threshold=2)
    knobs = wm.knobs_from_config(cfg)
    assert knobs.q == (3,)
    assert knobs.t_new == (2,)


def test_default_knobs_always_include_ot_backend(monkeypatch):
    """ISSUE 16: the OT backend's check kernels must be enumerated (and
    so pre-warmed) no matter which MtA backend the node serves today —
    deduped when the node already serves ot."""
    monkeypatch.delenv("MPCIUM_MTA", raising=False)
    assert wm.default_knobs().mta_impl == ("paillier", "ot")
    monkeypatch.setenv("MPCIUM_MTA", "ot")
    assert wm.default_knobs().mta_impl == ("ot",)


def test_report_basename_is_stable():
    # scripts/prewarm.py, the daemon, and the docs all point here
    assert wm.REPORT_BASENAME == "WARM_MANIFEST.json"
