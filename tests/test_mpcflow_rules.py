"""mpcflow unit tests: interprocedural taint propagation shapes (method
calls, closures, comprehensions, dict round-trips), sanitizer cuts,
explicit declassification, and device-residency over the call graph —
all as self-contained snippets, no dependency on the live package tree.
"""
from __future__ import annotations

import textwrap
from pathlib import Path

import pytest

from mpcium_tpu.analysis.core import ParsedFile
from mpcium_tpu.analysis.flow import (
    CallGraph,
    ProjectIndex,
    build_budget,
    run_flow_parsed,
)
from mpcium_tpu.analysis.flow import residency as res_mod
from mpcium_tpu.analysis.flow.residency import run_residency

pytestmark = pytest.mark.lint

# taint skips mpcium_tpu/analysis/ and secret-name seeding is off for
# mpcium_tpu/faults/ — snippets live in protocol/ like real phase code
TAINT_REL = "mpcium_tpu/protocol/snippet_flow.py"
RES_REL = "mpcium_tpu/engine/snippet_res.py"


def flow(src: str, rel: str = TAINT_REL):
    pf = ParsedFile(Path(rel), rel, textwrap.dedent(src))
    result, _sites = run_flow_parsed([pf])
    return result.findings


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# -- taint propagation shapes ----------------------------------------------


def test_taint_through_method_call():
    src = """
    class Party:
        def _load(self):
            return self.share

        def run(self):
            v = self._load()
            log.info("loaded", v=v)
    """
    found = flow(src)
    assert rule_ids(found) == ["MPF701"]
    # the finding carries the source→sink chain
    assert "share" in found[0].message
    assert "->" in found[0].message or "chain" in found[0].message


def test_taint_through_module_function_chain():
    # two call hops: reader -> middle -> sink site
    src = """
    def read_share(store):
        return store.share

    def relabel(x):
        return x

    def report(store):
        log.warning("state", s=relabel(read_share(store)))
    """
    assert rule_ids(flow(src)) == ["MPF701"]


def test_taint_through_closure():
    src = """
    def outer(share):
        def fmt():
            return f"{share}"
        raise ValueError(fmt())
    """
    # the closure body formats the secret free variable; the raise is the
    # MPF702 sink (whether attributed to outer or the nested fn)
    assert "MPF702" in rule_ids(flow(src))


def test_taint_through_comprehension():
    src = """
    def dump(shares):
        lines = [f"{s}" for s in shares]
        log.info("all", lines=lines)
    """
    assert rule_ids(flow(src)) == ["MPF701"]


def test_taint_through_dict_round_trip():
    src = """
    def stash(nonce):
        d = {}
        d["k"] = nonce
        log.debug("d", v=d["k"])
    """
    assert rule_ids(flow(src)) == ["MPF701"]


def test_wire_payload_sink():
    src = """
    def leak(bus, seed):
        bus.publish("topic", {"seed": seed})
    """
    assert rule_ids(flow(src)) == ["MPF703"]


# -- sanitizers + declassification -----------------------------------------


def test_hash_sanitizer_cuts_taint():
    src = """
    import hashlib

    def fingerprint(share):
        digest = hashlib.sha256(share).hexdigest()
        log.info("fp", fp=digest)
    """
    assert flow(src) == []


def test_seal_sanitizer_cuts_taint():
    src = """
    def persist(kv, share, path):
        blob = kv.seal(share)
        path.write_bytes(blob)
    """
    assert flow(src) == []


def test_declassified_assignment_is_clean():
    src = """
    def reveal(share):
        delta = (share + 1) % 7  # mpcflow: declassified
        log.info("delta", d=delta)
    """
    assert flow(src) == []
    # without the marker the same shape is a finding
    src_bad = """
    def reveal(share):
        delta = (share + 1) % 7
        log.info("delta", d=delta)
    """
    assert rule_ids(flow(src_bad)) == ["MPF701"]


def test_public_attrs_stay_clean_on_secret_base():
    src = """
    def announce(share):
        log.info("done", wallet=share.wallet_id, n=share.threshold)
    """
    assert flow(src) == []


# -- device residency -------------------------------------------------------


@pytest.fixture
def phase_snippet(monkeypatch):
    monkeypatch.setattr(
        res_mod,
        "PHASE_ENTRY_POINTS",
        {"test.phase": (f"{RES_REL}::run_phase",)},
    )

    def build(src: str):
        pf = ParsedFile(Path(RES_REL), RES_REL, textwrap.dedent(src))
        index = ProjectIndex([pf])
        graph = CallGraph(index)
        return run_residency(index, graph)

    return build


def test_residency_flags_host_pull_on_hot_path(phase_snippet):
    findings, sites = phase_snippet("""
    import jax.numpy as jnp
    import numpy as np

    def run_phase(x_d):
        y = jnp.add(x_d, 1)
        return np.asarray(y)
    """)
    assert rule_ids(findings) == ["MPF801"]
    assert len(sites) == 1 and not sites[0].intentional


def test_residency_reaches_through_the_call_graph(phase_snippet):
    # the materialization lives in a helper the entry point calls
    findings, sites = phase_snippet("""
    import jax.numpy as jnp
    import numpy as np

    def run_phase(x_d):
        y = jnp.mul(x_d, x_d)
        return _drain(y)

    def _drain(y_d):
        return np.asarray(y_d)
    """)
    assert rule_ids(findings) == ["MPF801"]
    assert findings[0].symbol == "_drain"


def test_residency_host_ok_is_intentional_not_a_finding(phase_snippet):
    findings, sites = phase_snippet("""
    import jax.numpy as jnp
    import numpy as np

    def run_phase(x_d):
        y = jnp.add(x_d, 1)
        out = np.asarray(y)  # mpcflow: host-ok — wire egress for the test
        return out
    """)
    assert findings == []
    assert len(sites) == 1
    assert sites[0].intentional
    assert "wire egress" in sites[0].reason
    budget = build_budget(sites)
    ph = budget["phases"]["test.phase"]
    assert ph["total_sites"] == 1
    assert ph["intentional"] == 1 and ph["tracked"] == 0


def test_residency_jit_entry_tracks_jitted_returns(phase_snippet):
    # a value produced by a jitted project function is device-tracked
    findings, _sites = phase_snippet("""
    import jax
    import numpy as np

    @jax.jit
    def kernel(x):
        return x

    def run_phase(x):
        y = kernel(x)
        return np.asarray(y)
    """)
    assert rule_ids(findings) == ["MPF801"]


def test_residency_cold_function_is_not_scanned(phase_snippet):
    # np.asarray of a device value outside any phase-reachable function
    findings, sites = phase_snippet("""
    import jax.numpy as jnp
    import numpy as np

    def run_phase(x_d):
        return x_d

    def offline_tool(x_d):
        return np.asarray(jnp.add(x_d, 1))
    """)
    assert findings == []
    assert sites == []
