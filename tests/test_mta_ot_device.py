"""Device OT extension path (ISSUE 11): MPCIUM_OT_DEVICE=1 (the
default) fuses PRG expansion, bit-transpose, pad hashing and payload
masking into one device dispatch per chunk. The contract that lets it
ship without bumping OT_WIRE_VERSION: transcripts and shares are
BIT-identical to the host/native path — which stays the wire-round
implementation and the oracle — for every chunk count.

Reuses the synthetic-base-OT fixtures of test_mta_ot_pipeline (tier-1,
CPU)."""
import numpy as np
import pytest

from mpcium_tpu.protocol.ecdsa import mta_ot
from test_mta_ot_pipeline import B, DetRng, _ints, _limbs, synth_leg

Q = mta_ot.Q


@pytest.fixture(scope="module")
def fixed_inputs():
    r = DetRng(11)
    a = [r.randbelow(Q) for _ in range(B)]
    g = [r.randbelow(Q) for _ in range(B)]
    w = [r.randbelow(Q) for _ in range(B)]
    a[1] = 0
    w[0] = Q - 1
    return a, g, w


@pytest.fixture(scope="module")
def wire_oracle(fixed_inputs):
    """The serial three-round wire composition — U and y0/y1 exactly as
    they would cross the network — plus the resulting shares."""
    a_ints, g_ints, w_ints = fixed_inputs
    leg = synth_leg(21)
    msg_a = leg.alice_round1(_limbs(a_ints), 0)
    msgs_b, betas = leg.bob_round2_multi(
        (_limbs(g_ints), _limbs(w_ints)), msg_a, 0
    )
    alphas = leg.alice_round3_multi(msgs_b)
    shares = [
        (np.asarray(al), np.asarray(be)) for al, be in zip(alphas, betas)
    ]
    for (al, be), b_ints in zip(shares, (g_ints, w_ints)):
        ai, bi = _ints(al), _ints(be)
        for i in range(B):
            assert (ai[i] + bi[i]) % Q == a_ints[i] * b_ints[i] % Q, i
    return msg_a, msgs_b, shares


@pytest.mark.parametrize("K", [1, 2, 4])
def test_device_transcript_bit_identical_to_host(
    K, monkeypatch, fixed_inputs, wire_oracle
):
    """The SECURITY.md claim, mechanically: the device path changes
    where pads are derived, never the bytes on the wire. Per chunk
    count, the captured U / y0 / y1 wire tensors must concatenate to
    exactly the serial composition's messages, and the shares must
    match."""
    monkeypatch.setenv("MPCIUM_OT_DEVICE", "1")
    msg_a, msgs_b, shares = wire_oracle
    a_ints, g_ints, w_ints = fixed_inputs
    leg = synth_leg(21)
    transcript = []
    out = leg.run_multi(
        _limbs(a_ints), (_limbs(g_ints), _limbs(w_ints)),
        chunks=K, transcript=transcript,
    )
    assert len(transcript) == K
    U = np.concatenate([t["U"] for t in transcript], axis=1)
    assert np.array_equal(U, msg_a["U"]), f"K={K}: U diverged"
    for s in range(2):
        y0 = np.concatenate([t["y0"][s] for t in transcript], axis=0)
        y1 = np.concatenate([t["y1"][s] for t in transcript], axis=0)
        assert np.array_equal(y0, msgs_b[s]["y0"]), f"K={K} set {s}: y0"
        assert np.array_equal(y1, msgs_b[s]["y1"]), f"K={K} set {s}: y1"
        assert np.array_equal(np.asarray(out[s][0]), shares[s][0])
        assert np.array_equal(np.asarray(out[s][1]), shares[s][1])


@pytest.mark.parametrize("K", [1, 2, 4])
def test_host_and_device_shares_identical(K, monkeypatch, fixed_inputs):
    """run_multi itself, flipped both ways on the same rng stream."""
    a_ints, g_ints, w_ints = fixed_inputs
    outs = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("MPCIUM_OT_DEVICE", flag)
        leg = synth_leg(22)
        outs[flag] = leg.run_multi(
            _limbs(a_ints), (_limbs(g_ints), _limbs(w_ints)), chunks=K
        )
    for s in range(2):
        for j in range(2):
            assert np.array_equal(
                np.asarray(outs["0"][s][j]), np.asarray(outs["1"][s][j])
            ), (K, s, "alpha" if j == 0 else "beta")


def test_device_timings_report_no_host_stage(monkeypatch):
    """The device path's whole point: timings carry total_s but no host
    extension time (gg18_batch divides by host_s only when > 0)."""
    monkeypatch.setenv("MPCIUM_OT_DEVICE", "1")
    leg = synth_leg(23)
    timings = {}
    leg.run_multi(_limbs([3, 5, 7, 9]), (_limbs([2, 4, 6, 8]),),
                  chunks=2, timings=timings)
    assert timings["total_s"] > 0.0
    assert timings.get("host_s", 0.0) == 0.0


def test_extension_counter_advances_on_device_path(monkeypatch):
    """Consecutive device extensions must land in disjoint PRF ranges
    (the stateful-IKNP invariant): same inputs, different transcripts."""
    monkeypatch.setenv("MPCIUM_OT_DEVICE", "1")
    leg = synth_leg(24)
    a, b = _limbs([3, 5, 7, 9]), _limbs([2, 4, 6, 8])
    t1, t2 = [], []
    leg.run_multi(a, (b,), chunks=1, transcript=t1)
    leg.run_multi(a, (b,), chunks=1, transcript=t2)
    assert leg.ctr == 2
    assert not np.array_equal(t1[0]["U"], t2[0]["U"])
