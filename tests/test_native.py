"""Native batched hashing vs hashlib ground truth."""
import hashlib
import secrets

import numpy as np
import pytest

from mpcium_tpu import native


def test_native_builds():
    assert native.available(), "g++ toolchain expected in this environment"


def test_batch_sha256_matches_hashlib():
    rng = np.random.default_rng(7)
    for W in (1, 32, 55, 56, 64, 65, 127, 300):
        rows = rng.integers(0, 256, size=(17, W), dtype=np.uint8)
        got = native.batch_sha256(b"tag/", rows)
        for i in range(rows.shape[0]):
            expect = hashlib.sha256(b"tag/" + rows[i].tobytes()).digest()
            assert got[i].tobytes() == expect, f"W={W} row={i}"


def test_batch_sha512_matches_hashlib():
    rng = np.random.default_rng(8)
    for W in (1, 96, 111, 112, 128, 129, 500):
        rows = rng.integers(0, 256, size=(9, W), dtype=np.uint8)
        got = native.batch_sha512(b"x", rows)
        for i in range(rows.shape[0]):
            expect = hashlib.sha512(b"x" + rows[i].tobytes()).digest()
            assert got[i].tobytes() == expect, f"W={W} row={i}"


def test_large_batch_parallel_path():
    rows = np.frombuffer(secrets.token_bytes(1024 * 64), dtype=np.uint8).reshape(
        1024, 64
    )
    got = native.batch_sha256(b"", rows)
    i = 777
    assert got[i].tobytes() == hashlib.sha256(rows[i].tobytes()).digest()


def test_ot_transpose_matches_numpy():
    """Native packed bit-matrix transpose vs numpy unpack/T/pack."""
    rng = np.random.default_rng(9)
    for M in (256, 1024):
        packed = rng.integers(0, 256, size=(128, M // 8), dtype=np.uint8)
        bits = np.unpackbits(packed, axis=-1, count=M, bitorder="little")
        want = np.packbits(bits.T, axis=-1, bitorder="little")  # (M, 16)
        got = native.ot_transpose(packed)
        assert got is not None and (got == want).all()


def test_ot_transpose_rejects_non_multiple_of_8_kappa():
    """kappa % 8 != 0 would silently drop the trailing column bits
    (out is allocated kappa // 8 wide) — must fail loudly instead."""
    with pytest.raises(AssertionError, match="kappa=12"):
        native.ot_transpose(np.zeros((12, 8), dtype=np.uint8))


def test_prg_expand_matches_reference_stream():
    """Fused native PRG vs the documented sha256(prefix ‖ seed ‖
    le16(j) ‖ le32(blk)) stream, including a nonzero block offset."""
    rng = np.random.default_rng(10)
    seeds = rng.integers(0, 256, size=(5, 32), dtype=np.uint8)
    prefix = b"mpcium-ot-prg|t"
    for blk_off in (0, 7):
        got = native.prg_expand(prefix, seeds, 3, blk_off=blk_off)
        assert got is not None and got.shape == (5, 96)
        for j in range(5):
            for b in range(3):
                msg = (
                    prefix + seeds[j].tobytes()
                    + int(j).to_bytes(2, "little")
                    + int(blk_off + b).to_bytes(4, "little")
                )
                expect = hashlib.sha256(msg).digest()
                assert got[j, b * 32:(b + 1) * 32].tobytes() == expect


def test_prg_expand_chunks_concatenate():
    """Block-offset sub-ranges concatenate to the full expansion (the
    pipeline's chunking invariant)."""
    rng = np.random.default_rng(11)
    seeds = rng.integers(0, 256, size=(4, 32), dtype=np.uint8)
    full = native.prg_expand(b"p", seeds, 8)
    parts = [
        native.prg_expand(b"p", seeds, 2, blk_off=o) for o in (0, 2, 4, 6)
    ]
    assert (np.concatenate(parts, axis=1) == full).all()


def test_xor_rows_in_place_and_broadcast():
    rng = np.random.default_rng(12)
    a = rng.integers(0, 256, size=(6, 40), dtype=np.uint8)
    b = rng.integers(0, 256, size=(6, 40), dtype=np.uint8)
    want = a ^ b
    got = native.xor_rows(a, b)
    assert got is a and (a == want).all()  # in place, no new array
    row = rng.integers(0, 256, size=(40,), dtype=np.uint8)
    want = a ^ row
    native.xor_rows(a, row)  # broadcast leg
    assert (a == want).all()


def test_native_threads_env_is_pure_scheduling(monkeypatch):
    """MPCIUM_NATIVE_THREADS must never change output bytes — 1-thread
    pin vs multithread across every threaded entry point."""
    rng = np.random.default_rng(13)
    rows = rng.integers(0, 256, size=(700, 64), dtype=np.uint8)
    packed = rng.integers(0, 256, size=(128, 128), dtype=np.uint8)
    seeds = rng.integers(0, 256, size=(128, 32), dtype=np.uint8)

    monkeypatch.setenv("MPCIUM_NATIVE_THREADS", "1")
    h1 = native.batch_sha256(b"t", rows)
    t1 = native.ot_transpose(packed)
    p1 = native.prg_expand(b"t", seeds, 4)
    monkeypatch.setenv("MPCIUM_NATIVE_THREADS", "4")
    h4 = native.batch_sha256(b"t", rows)
    t4 = native.ot_transpose(packed)
    p4 = native.prg_expand(b"t", seeds, 4)
    assert (h1 == h4).all() and (t1 == t4).all() and (p1 == p4).all()
