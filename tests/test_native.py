"""Native batched hashing vs hashlib ground truth."""
import hashlib
import secrets

import numpy as np

from mpcium_tpu import native


def test_native_builds():
    assert native.available(), "g++ toolchain expected in this environment"


def test_batch_sha256_matches_hashlib():
    rng = np.random.default_rng(7)
    for W in (1, 32, 55, 56, 64, 65, 127, 300):
        rows = rng.integers(0, 256, size=(17, W), dtype=np.uint8)
        got = native.batch_sha256(b"tag/", rows)
        for i in range(rows.shape[0]):
            expect = hashlib.sha256(b"tag/" + rows[i].tobytes()).digest()
            assert got[i].tobytes() == expect, f"W={W} row={i}"


def test_batch_sha512_matches_hashlib():
    rng = np.random.default_rng(8)
    for W in (1, 96, 111, 112, 128, 129, 500):
        rows = rng.integers(0, 256, size=(9, W), dtype=np.uint8)
        got = native.batch_sha512(b"x", rows)
        for i in range(rows.shape[0]):
            expect = hashlib.sha512(b"x" + rows[i].tobytes()).digest()
            assert got[i].tobytes() == expect, f"W={W} row={i}"


def test_large_batch_parallel_path():
    rows = np.frombuffer(secrets.token_bytes(1024 * 64), dtype=np.uint8).reshape(
        1024, 64
    )
    got = native.batch_sha256(b"", rows)
    i = 777
    assert got[i].tobytes() == hashlib.sha256(rows[i].tobytes()).digest()


def test_ot_transpose_matches_numpy():
    """Native packed bit-matrix transpose vs numpy unpack/T/pack."""
    rng = np.random.default_rng(9)
    for M in (256, 1024):
        packed = rng.integers(0, 256, size=(128, M // 8), dtype=np.uint8)
        bits = np.unpackbits(packed, axis=-1, count=M, bitorder="little")
        want = np.packbits(bits.T, axis=-1, bitorder="little")  # (M, 16)
        got = native.ot_transpose(packed)
        assert got is not None and (got == want).all()
