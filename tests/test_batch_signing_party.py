"""BatchedEDDSASigningParty: the distributed batched protocol, driven
transport-free (3 parties, B wallets, per-lane failure isolation)."""
import secrets

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.engine import eddsa_batch as eb
from mpcium_tpu.protocol.base import ProtocolError
from mpcium_tpu.protocol.eddsa.batch_signing import BatchedEDDSASigningParty
from mpcium_tpu.protocol.runner import run_protocol


def test_three_party_batch_signs_and_verifies():
    ids = ["n0", "n1", "n2"]
    B = 5
    shares = eb.dealer_keygen_batch(B, ids, threshold=2)
    messages = [secrets.token_bytes(32) for _ in range(B)]
    parties = {
        pid: BatchedEDDSASigningParty(
            "bs-1", pid, ids, shares[i], messages
        )
        for i, pid in enumerate(ids)
    }
    run_protocol(parties)
    for pid, p in parties.items():
        ok = p.result["ok"]
        assert ok.all(), f"{pid}: {ok}"
        sigs = p.result["signatures"]
        for w in range(B):
            assert hm.ed25519_verify(
                shares[0][w].public_key, messages[w], sigs[w].tobytes()
            )


def test_commitment_fraud_aborts_with_culprit():
    ids = ["n0", "n1"]
    B = 2
    shares = eb.dealer_keygen_batch(B, ids, threshold=1)
    messages = [b"\x01" * 32, b"\x02" * 32]
    parties = {
        pid: BatchedEDDSASigningParty("bs-2", pid, ids, shares[i], messages)
        for i, pid in enumerate(ids)
    }
    # n1 equivocates: reveals a different nonce block than it committed to
    outbox = []
    for p in parties.values():
        outbox.extend(p.start())
    tampered = []
    for m in outbox:
        if m.round == "eddsa/bsign/1/commit" and m.from_id == "n1":
            pass  # commitment goes out as-is
        tampered.append(m)
    # deliver commitments
    second = []
    for m in tampered:
        for pid, p in parties.items():
            if pid != m.from_id:
                second.extend(p.receive(m))
    # corrupt n1's reveal block before delivery
    with pytest.raises(ProtocolError) as ei:
        for m in second:
            if m.round == "eddsa/bsign/2/reveal" and m.from_id == "n1":
                blk = bytearray(bytes.fromhex(m.payload["R"]))
                blk[0] ^= 1
                m.payload["R"] = bytes(blk).hex()
            for pid, p in parties.items():
                if pid != m.from_id:
                    p.receive(m)
    assert ei.value.args[-1] == "n1" or "n1" in str(ei.value)
