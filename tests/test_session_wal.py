"""Crash-recoverable sessions: the encrypted WAL store, the snapshot
codec it relies on, mid-protocol party snapshot/restore bit-fidelity, and
the Session-level close/drop semantics that keep WAL files resumable."""
import json
import threading
import time

from mpcium_tpu.node.session import RetryableSessionError, Session
from mpcium_tpu.identity.identity import IdentityStore, generate_identity
from mpcium_tpu.protocol.base import snap_decode, snap_encode
from mpcium_tpu.protocol.eddsa.keygen import EDDSAKeygenParty
from mpcium_tpu.protocol.eddsa.signing import R1, R2, R3, EDDSASigningParty
from mpcium_tpu.store.kvstore import EncryptedFileKV
from mpcium_tpu.store.session_wal import SessionWALStore
from mpcium_tpu.transport.loopback import LoopbackFabric


def _store(tmp_path, sub="db", pw="wal-pw"):
    return SessionWALStore(EncryptedFileKV(tmp_path / sub, pw), fsync=False)


# ---------------------------------------------------------------------------
# WAL store
# ---------------------------------------------------------------------------


def test_wal_roundtrip(tmp_path):
    st = _store(tmp_path)
    w = st.create("sess-1", {"kind": "sign", "wallet_id": "w1"})
    w.envelope(b"\x01\x02")
    w.checkpoint({"v": 1, "state": "a"}, [{"round": "r1"}])
    w.envelope(b"\x03\x04")
    w.close()
    reps = st.incomplete()
    assert len(reps) == 1
    rep = reps[0]
    assert rep.session_id == "sess-1"
    assert rep.meta == {"kind": "sign", "wallet_id": "w1"}
    assert rep.snapshot == {"v": 1, "state": "a"}
    assert rep.sent == [{"round": "r1"}]
    # the pre-checkpoint envelope lives inside the snapshot's inbox; only
    # the post-checkpoint one needs redelivery
    assert rep.envelopes == [b"\x03\x04"]
    assert not rep.done and not rep.torn


def test_wal_done_excluded_from_incomplete(tmp_path):
    st = _store(tmp_path)
    w = st.create("sess-done", {"kind": "sign"})
    w.checkpoint({"v": 1}, [])
    w.done()
    w.close()
    assert st.incomplete() == []
    rep = st.replay(st._path("sess-done"))
    assert rep is not None and rep.done


def test_wal_torn_tail_falls_back_to_previous_checkpoint(tmp_path):
    st = _store(tmp_path)
    w = st.create("sess-torn", {"kind": "sign"})
    w.checkpoint({"ckpt": 1}, [{"round": "r1"}])
    path = st._path("sess-torn")
    good = path.stat().st_size
    w.checkpoint({"ckpt": 2}, [{"round": "r2"}])
    w.close()
    blob = path.read_bytes()
    path.write_bytes(blob[: good + 7])  # SIGKILL mid-frame
    rep = st.replay(path)
    assert rep.torn
    assert rep.snapshot == {"ckpt": 1}
    assert rep.sent == [{"round": "r1"}]
    assert rep.valid_bytes == good
    # reopen truncates the garbage and appends cleanly at the next seq
    w2 = st.reopen(rep)
    w2.checkpoint({"ckpt": 3}, [])
    w2.close()
    rep2 = st.replay(path)
    assert not rep2.torn and rep2.snapshot == {"ckpt": 3}


def test_wal_flipped_ciphertext_byte_stops_replay(tmp_path):
    # AEAD open fails on the tampered record; the intact prefix survives
    st = _store(tmp_path)
    w = st.create("sess-bits", {"kind": "sign"})
    w.checkpoint({"v": 1}, [])
    w.close()
    path = st._path("sess-bits")
    blob = bytearray(path.read_bytes())
    blob[-1] ^= 0xFF
    path.write_bytes(bytes(blob))
    rep = st.replay(path)
    assert rep.torn and rep.snapshot is None and rep.records == 1


def test_wal_sealed_at_rest(tmp_path):
    st = _store(tmp_path)
    w = st.create("sess-secret", {"wallet_id": "hunter2-wallet"})
    w.checkpoint({"secret": "hunter2"}, [])
    w.close()
    path = st._path("sess-secret")
    raw = path.read_bytes()
    assert b"hunter2" not in raw and b"sess-secret" not in raw
    # the filename is a keyed hash, not the session id
    assert "sess-secret" not in path.name


def test_wal_wrong_key_replays_nothing(tmp_path):
    st = _store(tmp_path)
    w = st.create("sess-pw", {"kind": "sign"})
    w.close()
    other = _store(tmp_path, sub="db2", pw="other")
    # same bytes under a different key: not even the meta record opens
    assert other.replay(st._path("sess-pw")) is None


def test_wal_create_discards_stale_file(tmp_path):
    st = _store(tmp_path)
    w = st.create("sess-re", {"attempt": 1})
    w.checkpoint({"v": 1}, [])
    w.close()
    w2 = st.create("sess-re", {"attempt": 2})
    w2.close()
    rep = st.replay(st._path("sess-re"))
    assert rep.meta == {"attempt": 2} and rep.snapshot is None


def test_wal_append_after_drop_is_noop(tmp_path):
    st = _store(tmp_path)
    w = st.create("sess-drop", {"kind": "sign"})
    w.drop()
    w.checkpoint({"v": 1}, [])  # must not resurrect the file
    assert not st._path("sess-drop").exists()
    assert st.incomplete() == []


# ---------------------------------------------------------------------------
# snapshot codec
# ---------------------------------------------------------------------------


def test_snap_codec_roundtrips_through_json():
    v = {
        1: b"\x00\xff",
        "big": 2**521 - 1,
        "neg": -5,
        "tup": (1, (2, b"x")),
        "list": [True, None, 0.5, "s"],
        "nested": {(1, 2): {"k": b""}},
    }
    out = snap_decode(json.loads(json.dumps(snap_encode(v))))
    assert out == v
    assert isinstance(out["tup"], tuple)
    assert isinstance(out["tup"][1][1], bytes)
    # non-string dict keys survive the JSON trip
    assert 1 in out and (1, 2) in out["nested"]
    assert isinstance(out["big"], int)


# ---------------------------------------------------------------------------
# party snapshot/restore: the restored signer must continue bit-identically
# ---------------------------------------------------------------------------


def _keygen_shares(ids):
    parties = {i: EDDSAKeygenParty("kg-snap", i, ids, threshold=1) for i in ids}
    pending = []
    for p in parties.values():
        pending.extend(p.start())
    while pending:
        m = pending.pop(0)
        for pid, p in parties.items():
            if pid == m.from_id or (m.to is not None and m.to != pid):
                continue
            pending.extend(p.receive(m))
    assert all(p.done for p in parties.values())
    return {i: p.result for i, p in parties.items()}


def test_eddsa_signing_snapshot_restore_bit_identical():
    ids = ["n0", "n1", "n2"]
    shares = _keygen_shares(ids)
    signers = {
        i: EDDSASigningParty("sg-snap", i, ids, shares[i], b"payload")
        for i in ids
    }
    r1 = [m for i in ids for m in signers[i].start()]
    assert all(m.round == R1 for m in r1)
    # n0 absorbs every commitment and emits its decommitment (round 2):
    # the nonce r_0 is now fixed — exactly the state the WAL checkpoints
    out_n0 = []
    for m in r1:
        if m.from_id != "n0":
            out_n0.extend(signers["n0"].receive(m))
    assert any(m.round == R2 for m in out_n0)
    snap = signers["n0"].snapshot()
    clone = EDDSASigningParty("sg-snap", "n0", ids, shares["n0"], b"payload")
    clone.restore(json.loads(json.dumps(snap)))  # same trip the WAL takes
    # drive the survivors forward
    r2 = list(out_n0)
    for i in ("n1", "n2"):
        for m in r1:
            if m.from_id != i:
                r2.extend(signers[i].receive(m))
    r3 = []
    for i in ("n1", "n2"):
        for m in r2:
            if m.from_id != i:
                r3.extend(signers[i].receive(m))
    # both incarnations of n0 see the identical remaining stream
    rest = [m for m in r2 + r3 if m.from_id != "n0"]
    orig_out, clone_out = [], []
    for m in rest:
        orig_out.extend(signers["n0"].receive(m))
        clone_out.extend(clone.receive(m))
    key = lambda ms: [(m.round, m.to, m.payload) for m in ms]  # noqa: E731
    assert key(orig_out) == key(clone_out)
    assert signers["n0"].done and clone.done
    assert signers["n0"].result == clone.result  # bit-identical signature
    from mpcium_tpu.core import hostmath as hm

    assert hm.ed25519_verify(shares["n0"].public_key, b"payload", clone.result)


# ---------------------------------------------------------------------------
# Session-level semantics
# ---------------------------------------------------------------------------


def test_session_close_unblocks_waiters(tmp_path):
    # close() on an unfinished session must signal wait() callers and fire
    # a RETRYABLE error instead of leaving them to their own timeout
    ids = ["node0", "node1"]
    for n in ids:
        generate_identity(n, tmp_path)
    peers = {n: n for n in ids}
    fabric = LoopbackFabric()
    errs, errd = [], threading.Event()
    s = Session(
        session_id="s-close",
        party=EDDSAKeygenParty("s-close", "node0", ids, threshold=1),
        node_id="node0",
        participants=ids,
        transport=fabric.transport(),
        identity=IdentityStore(tmp_path, "node0", peers),
        broadcast_topic="tc.bcast",
        direct_topic_fn=lambda n: f"tc.direct.{n}",
        on_error=lambda e: (errs.append(e), errd.set()),
        hello_timeout_s=None,  # no deadline: only close() can unblock
    )
    s.listen()  # node1 never shows up
    unblocked = threading.Event()
    t = threading.Thread(
        target=lambda: s.wait(30.0) and unblocked.set(), daemon=True
    )
    t.start()
    time.sleep(0.1)
    assert not unblocked.is_set()
    s.close()
    t.join(5.0)
    assert unblocked.is_set(), "close() did not signal wait()"
    assert errd.wait(1.0)
    assert isinstance(errs[0], RetryableSessionError)
    assert "closed" in str(errs[0])
    # idempotent: a second close fires no second error
    s.close()
    assert len(errs) == 1
    fabric.close()


def test_session_wal_dropped_after_completion(tmp_path):
    # a WAL-enabled keygen that completes must leave no resume set behind
    ids = ["node0", "node1"]
    for n in ids:
        generate_identity(n, tmp_path / "ident")
    peers = {n: n for n in ids}
    fabric = LoopbackFabric()
    stores, sessions = {}, []
    for nid in ids:
        stores[nid] = _store(tmp_path, sub=f"db-{nid}")
        wal = stores[nid].create(
            "s-walkg", {"kind": "keygen", "wallet_id": "w-walkg"}
        )
        sessions.append(
            Session(
                session_id="s-walkg",
                party=EDDSAKeygenParty("s-walkg", nid, ids, threshold=1),
                node_id=nid,
                participants=ids,
                transport=fabric.transport(),
                identity=IdentityStore(tmp_path / "ident", nid, peers),
                broadcast_topic="tw.bcast",
                direct_topic_fn=lambda n: f"tw.direct.{n}",
                hello_timeout_s=5.0,
                wal=wal,
            )
        )
    try:
        for s in sessions:
            s.listen()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not all(s.done for s in sessions):
            time.sleep(0.05)
        assert all(s.done for s in sessions), "keygen did not complete"
    finally:
        for s in sessions:
            s.close()
        fabric.close()
    # party.done flips before _finish's WAL drop runs on the delivery
    # thread — give the drop a beat instead of asserting the instant
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and any(
        stores[nid].incomplete() for nid in ids
    ):
        time.sleep(0.05)
    for nid in ids:
        assert stores[nid].incomplete() == []
        assert not stores[nid]._path("s-walkg").exists()
