"""Statistical behavior of the perfcheck gate (perf/statcheck.py).

The gate's whole value is its error rates: on IDENTICAL distributions it
must almost never fire (seeded false-positive sweep), and on an injected
1.5x slowdown it must ALWAYS fire (false-negative sweep). Both sweeps
use synthetic noise from seeded RNGs so the assertions are exact and
replayable, not themselves flaky timing tests.
"""
import random

import pytest

from mpcium_tpu.perf import statcheck

pytestmark = pytest.mark.perf

_N = 30  # matches microbench.DEFAULT_SAMPLES


def _noisy(seed: int, n: int = _N, mu: float = 1.0, sigma: float = 0.08):
    rng = random.Random(seed)
    return [abs(rng.gauss(mu, sigma)) for _ in range(n)]


def test_identical_distributions_pass_on_at_least_99pct_of_seeds():
    regressions = 0
    seeds = 200
    for seed in range(seeds):
        base = _noisy(seed * 2 + 1)
        cur = _noisy(seed * 2 + 2)  # same distribution, independent draw
        if statcheck.compare("x", base, cur, seed=seed).regressed:
            regressions += 1
    # triple gate (rank test AND >=25% effect AND CI_lo > 1) on equal
    # distributions: the effect floor alone makes firing vanishingly
    # rare; allow 2/200 so one unlucky seed pair cannot flake CI
    assert regressions <= 2, f"{regressions}/{seeds} false positives"


def test_injected_slowdown_always_fails():
    for seed in range(50):
        base = _noisy(seed * 2 + 1)
        cur = [v * 1.5 for v in _noisy(seed * 2 + 2)]
        v = statcheck.compare("x", base, cur, seed=seed)
        assert v.regressed, f"seed {seed} missed a 1.5x slowdown: {v.render()}"


def test_constant_tied_distributions_pass():
    # a fully tied pool has zero rank variance: indistinguishable, not a
    # regression (and no ZeroDivisionError)
    v = statcheck.compare("x", [1.0] * _N, [1.0] * _N)
    assert not v.regressed
    assert v.p_value == 1.0
    assert v.ratio == 1.0


def test_effect_floor_blocks_small_but_significant_slowdowns():
    # 10% slower with tiny noise: statistically unambiguous (p ~ 0) but
    # below the 25% practical-effect floor — must NOT fail the gate
    base = _noisy(1, sigma=0.001)
    cur = [v * 1.10 for v in _noisy(2, sigma=0.001)]
    v = statcheck.compare("x", base, cur)
    assert v.p_value < 1e-6
    assert not v.regressed


def test_speedups_never_fail():
    base = _noisy(3)
    cur = [v * 0.5 for v in _noisy(4)]
    v = statcheck.compare("x", base, cur)
    assert not v.regressed
    assert v.ratio < 1.0


def test_bootstrap_ci_is_seeded_and_brackets_true_ratio():
    base = _noisy(5)
    cur = [v * 1.5 for v in _noisy(6)]
    ci1 = statcheck.bootstrap_ratio_ci(base, cur, seed=7)
    ci2 = statcheck.bootstrap_ratio_ci(base, cur, seed=7)
    assert ci1 == ci2  # deterministic, replayable verdicts
    assert ci1[0] < 1.5 < ci1[1] or abs(ci1[0] - 1.5) < 0.2


def test_gate_reports_one_sided_benches_as_notes():
    res = statcheck.gate(
        {"both": _noisy(1), "baseline_only": _noisy(2)},
        {"both": _noisy(3), "current_only": _noisy(4)},
    )
    assert [v.bench for v in res.verdicts] == ["both"]
    assert any("baseline_only" in n for n in res.notes)
    assert any("current_only" in n for n in res.notes)
    assert res.ok


def test_mann_whitney_is_one_sided():
    base = _noisy(8)
    fast = [v * 0.5 for v in _noisy(9)]
    # current FASTER than baseline → p near 1 (we only test "slower")
    assert statcheck.mann_whitney_p(base, fast) > 0.5
    assert statcheck.mann_whitney_p(fast, base) < 1e-6


def test_empty_samples_raise():
    with pytest.raises(ValueError):
        statcheck.median([])
    with pytest.raises(ValueError):
        statcheck.mann_whitney_p([], [1.0])
