"""Property tests: JAX limb arithmetic vs python-int ground truth."""
import secrets

import numpy as np
import pytest

import jax.numpy as jnp

from mpcium_tpu.core import bignum as bn
from mpcium_tpu.core import hostmath as hm

PROF = bn.P256
MODULI = {
    "ed25519_p": hm.ED_P,
    "ed25519_l": hm.ED_L,
    "secp_p": hm.SECP_P,
    "secp_n": hm.SECP_N,
}


def rand_ints(n, bound):
    return [secrets.randbelow(bound) for _ in range(n)]


def test_limb_roundtrip():
    xs = rand_ints(16, 1 << 264) + [0, 1, (1 << 264) - 1]
    arr = bn.batch_to_limbs(xs, PROF)
    assert bn.batch_from_limbs(arr, PROF) == xs


def test_carry_normalizes_redundant():
    # redundant limbs (values beyond radix / negative) normalize to the same
    # integer: perturb limbs in pairs that preserve the represented total
    x = 0xDEADBEEF_CAFEBABE_0123456789ABCDEF
    limbs = bn.to_limbs(x, PROF).copy()
    limbs[3] += PROF.radix  # +radix at weight 2^36 ...
    limbs[4] -= 1  # ... -1 at weight 2^48: net zero
    limbs[0] += 5 * PROF.radix
    limbs[1] -= 5
    out = bn.carry(jnp.asarray(limbs), PROF)
    assert bn.from_limbs(np.asarray(out), PROF) == x


def test_carry_handles_negative_borrow():
    a, b = 2**200 + 12345, 2**199 + 999
    la = jnp.asarray(bn.to_limbs(a, PROF))
    lb = jnp.asarray(bn.to_limbs(b, PROF))
    out = bn.carry(la - lb, PROF)
    assert bn.from_limbs(np.asarray(out), PROF) == a - b


def test_mul_batched():
    xs = rand_ints(8, 1 << 256)
    ys = rand_ints(8, 1 << 256)
    lx = jnp.asarray(bn.batch_to_limbs(xs, PROF))
    ly = jnp.asarray(bn.batch_to_limbs(ys, PROF))
    prod = bn.mul(lx, ly, PROF)
    got = bn.batch_from_limbs(prod, PROF)
    assert got == [x * y for x, y in zip(xs, ys)]


def test_compare():
    pairs = [(5, 5), (1 << 200, (1 << 200) + 1), ((1 << 263) - 1, 77), (0, 0)]
    lx = jnp.asarray(bn.batch_to_limbs([p[0] for p in pairs], PROF))
    ly = jnp.asarray(bn.batch_to_limbs([p[1] for p in pairs], PROF))
    out = np.asarray(bn.compare(lx, ly))
    expected = [0, -1, 1, 0]
    assert list(out) == expected


@pytest.mark.parametrize("name", list(MODULI))
def test_barrett_reduce(name):
    m = MODULI[name]
    ctx = bn.BarrettCtx(m)
    xs = rand_ints(8, 1 << produce_bits()) + [0, m - 1, m, m + 1, 2 * m + 3]
    arr = jnp.asarray(bn.batch_to_limbs(xs, PROF, n_limbs=2 * PROF.n_limbs))
    out = bn.batch_from_limbs(ctx.reduce(arr), PROF)
    assert out == [x % m for x in xs]


def produce_bits():
    return 2 * PROF.capacity_bits - 1  # just under radix^(2n)


@pytest.mark.parametrize("name", list(MODULI))
def test_barrett_ring_ops(name):
    m = MODULI[name]
    ctx = bn.BarrettCtx(m)
    n = 8
    xs, ys = rand_ints(n, m), rand_ints(n, m)
    lx = jnp.asarray(bn.batch_to_limbs(xs, PROF))
    ly = jnp.asarray(bn.batch_to_limbs(ys, PROF))
    assert bn.batch_from_limbs(ctx.mulmod(lx, ly), PROF) == [
        x * y % m for x, y in zip(xs, ys)
    ]
    assert bn.batch_from_limbs(ctx.addmod(lx, ly), PROF) == [
        (x + y) % m for x, y in zip(xs, ys)
    ]
    assert bn.batch_from_limbs(ctx.submod(lx, ly), PROF) == [
        (x - y) % m for x, y in zip(xs, ys)
    ]


def test_barrett_pow_and_inverse():
    m = hm.ED_P  # prime
    ctx = bn.BarrettCtx(m)
    xs = rand_ints(4, m - 1)
    xs = [x + 1 for x in xs]  # nonzero
    lx = jnp.asarray(bn.batch_to_limbs(xs, PROF))
    e = 65537
    assert bn.batch_from_limbs(ctx.powmod_const(lx, e), PROF) == [
        pow(x, e, m) for x in xs
    ]
    inv = ctx.invmod_prime(lx)
    assert bn.batch_from_limbs(inv, PROF) == [pow(x, -1, m) for x in xs]


def test_barrett_scalar_ring_matches_lagrange():
    """End-use smoke: Lagrange coefficient arithmetic in the ed25519 scalar
    ring computed in limbs matches hostmath."""
    m = hm.ED_L
    ctx = bn.BarrettCtx(m)
    xs = [2, 5, 9]
    lam_host = [hm.lagrange_coeff(xs, x, m) for x in xs]
    # compute in limb arithmetic: num/den products then inverse
    lams = []
    for x_i in xs:
        num, den = 1, 1
        for x_j in xs:
            if x_j == x_i:
                continue
            num = num * ((0 - x_j) % m) % m
            den = den * ((x_i - x_j) % m) % m
        ln = jnp.asarray(bn.to_limbs(num, PROF))
        ld = jnp.asarray(bn.to_limbs(den, PROF))
        out = ctx.mulmod(ln, ctx.invmod_prime(ld))
        lams.append(bn.from_limbs(np.asarray(out), PROF))
    assert lams == lam_host


def test_mul_small_and_shift():
    x = secrets.randbelow(1 << 250)
    lx = jnp.asarray(bn.to_limbs(x, PROF))
    out = bn.mul_small(lx, 9728, PROF)
    assert bn.from_limbs(np.asarray(out), PROF) == x * 9728
    sh = bn.shift_limbs(lx, 3)
    assert bn.from_limbs(np.asarray(sh), PROF) == x << 36


def test_paillier_sized_profile():
    """The generic machinery works at Paillier modulus size (2048-bit)."""
    prof = bn.profile_for_bits(2048 + 8)
    p = secrets.randbelow(1 << 1024) | (1 << 1023) | 1
    q = secrets.randbelow(1 << 1024) | (1 << 1023) | 1
    m = p * q  # ~2048-bit odd modulus, top limb occupied
    # ensure top limb occupied for Barrett precondition
    assert prof.radix ** (prof.n_limbs - 1) <= m < prof.radix**prof.n_limbs
    ctx = bn.BarrettCtx(m, prof)
    xs = rand_ints(2, m)
    ys = rand_ints(2, m)
    lx = jnp.asarray(bn.batch_to_limbs(xs, prof))
    ly = jnp.asarray(bn.batch_to_limbs(ys, prof))
    assert bn.batch_from_limbs(ctx.mulmod(lx, ly), prof) == [
        x * y % m for x, y in zip(xs, ys)
    ]
    e = 0x10001
    assert bn.batch_from_limbs(ctx.powmod_const(lx, e), prof) == [
        pow(x, e, m) for x in xs
    ]
