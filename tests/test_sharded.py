"""Multi-device (committee × sessions) sharded signing on the virtual mesh."""
import secrets

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.engine import eddsa_batch as eb
from mpcium_tpu.engine import sharded


@pytest.mark.parametrize("committee", [1, 2])
def test_sharded_sign_matches_rfc8032(eight_devices, committee):
    mesh = sharded.make_mesh(8, committee=committee)
    B = mesh.devices.shape[1] * 2
    q, t = 2, 1
    party_ids = ["n0", "n1", "n2"]
    shares = eb.dealer_keygen_batch(B, party_ids, t, rng=secrets)
    quorum = eb.BatchedCoSigners(party_ids[:q], shares[:q], rng=secrets)
    r64 = np.stack([eb.fresh_nonce_bytes(B, secrets) for _ in range(q)])
    messages = [f"m{i}".encode() for i in range(B)]
    sigs, ok = sharded.sharded_sign(mesh, r64, quorum.lamx, quorum.A_comp, messages)
    assert ok.all()
    for i in range(B):
        assert hm.ed25519_verify(
            shares[0][i].public_key, messages[i], sigs[i].tobytes()
        )


def test_graft_entry_compiles(eight_devices):
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import jax

    from __graft_entry__ import entry

    fn, args = entry()
    jax.jit(fn).lower(*args).compile()
