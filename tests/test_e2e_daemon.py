"""Networked end-to-end: broker + 3 daemon PROCESSES over localhost TCP.

The automated analogue of the reference's INSTALLATION.md flow ("Start
Mpcium Nodes": nats-server + consul + three `mpcium start -n node<i>`
terminals + examples/ as the initiator). Everything the docker-compose
stack deploys is exercised for real here: the ops CLI bootstraps
peers/identities/initiator, `mpcium-tpu broker` and three
`mpcium-tpu start` processes are launched via subprocess, and the client
SDK drives generate → sign (both curves) → reshare → sign over the
authenticated, AEAD-encrypted TCP bus.
"""
from __future__ import annotations

import hashlib
import json
import os
import secrets
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu import wire
from mpcium_tpu.client.client import MPCClient
from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.identity.identity import InitiatorKey
from mpcium_tpu.store.kvstore import FileKV
from mpcium_tpu.transport.tcp import tcp_transport

REPO = Path(__file__).resolve().parent.parent
TOKEN = "e2e-shared-token"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_env() -> dict:
    """Daemon/broker env: pinned to the CPU backend (several processes must
    not race to initialise the single TPU chip; the per-session protocol
    path is host arithmetic anyway) with the axon relay stripped so a
    wedged tunnel cannot hang `import jax`."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MPCIUM_BROKER_TOKEN"] = TOKEN
    env["PYTHONPATH"] = ":".join(
        [str(REPO)]  # children run from the workspace cwd
        + [p for p in env.get("PYTHONPATH", "").split(":")
           if p and "axon" not in p and p != str(REPO)]
    )
    env.pop("PYTHONSTARTUP", None)
    return env


def _run_cli(module: str, *args: str, cwd: Path) -> None:
    subprocess.run(
        [sys.executable, "-m", module, *args],
        cwd=cwd, env=_child_env(), check=True, capture_output=True,
    )


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    """Bootstrap a workspace with the real ops CLI, then launch the broker
    and three node daemons as separate processes."""
    ws = tmp_path_factory.mktemp("e2e")
    port = _free_port()

    # --- ops bootstrap, exactly as scripts/setup_identities.sh does ------
    _run_cli("mpcium_tpu.cli.ops", "generate-peers", "-n", "3", cwd=ws)
    _run_cli("mpcium_tpu.cli.ops", "register-peers",
             "--registry-dir", "control", cwd=ws)
    for i in range(3):
        _run_cli("mpcium_tpu.cli.ops", "generate-identity",
                 "--node", f"node{i}", cwd=ws)
    _run_cli("mpcium_tpu.cli.ops", "generate-initiator", cwd=ws)
    initiator_pub = json.loads(
        (ws / "event_initiator.json").read_text()
    )["public_key"]

    # committed safe-prime pool (copy: pool_take consumes entries) so the
    # daemons' startup pre-params take seconds, not minutes
    pool = ws / "safeprimes.json"
    pool.write_bytes(
        (REPO / "mpcium_tpu/data/safeprimes_1024.json").read_bytes()
    )

    (ws / "config.yaml").write_text(
        "\n".join(
            [
                "environment: development",
                "mpc_threshold: 1",  # t=1 ⇒ 2-of-3 quorums (cluster.py:52)
                f'event_initiator_pubkey: "{initiator_pub}"',
                "badger_password: e2e-badger-password",
                f"broker_port: {port}",
                "broker_encrypt: true",
                f"safe_prime_pool: {pool}",
            ]
        )
    )

    procs: list = []
    logs = {}

    def _spawn(tag: str, *args: str) -> None:
        logs[tag] = open(ws / f"{tag}.log", "wb")
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "mpcium_tpu.cli.main", *args],
                cwd=ws, env=_child_env(),
                stdout=logs[tag], stderr=subprocess.STDOUT,
            )
        )

    _spawn("broker", "broker", "--port", str(port),
           "--journal", str(ws / "queue.jsonl"), "--encrypt")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=1).close()
            break
        except OSError:
            time.sleep(0.2)
    else:
        raise RuntimeError("broker never opened its port")

    for i in range(3):
        _spawn(f"node{i}", "start", "-n", f"node{i}")

    # readiness: the daemons announce in the shared control-plane KV
    kv = FileKV(ws / "control")
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if len(kv.keys("ready/")) == 3:
            break
        dead = [p for p in procs if p.poll() is not None]
        if dead:
            raise RuntimeError(
                "process died during startup: "
                + (ws / "broker.log").read_text()[-2000:]
                + "".join(
                    (ws / f"node{i}.log").read_text()[-2000:] for i in range(3)
                )
            )
        time.sleep(0.5)
    else:
        raise RuntimeError("daemons never became ready")

    transport = tcp_transport("127.0.0.1", port, auth_token=TOKEN, encrypt=True)
    client = MPCClient(transport, InitiatorKey.load(ws / "event_initiator.key"))
    yield ws, client

    transport.client.close()
    for p in procs:
        p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=15)
        except subprocess.TimeoutExpired:
            p.kill()
    for f in logs.values():
        f.close()


def _await(subscribe, fire, matches, timeout_s: float):
    import threading

    done = threading.Event()
    box: list = []

    def on_ev(ev):
        if matches(ev):
            box.append(ev)
            done.set()

    sub = subscribe(on_ev)
    try:
        fire()
        assert done.wait(timeout_s), "no result within timeout"
        return box[0]
    finally:
        sub.unsubscribe()


@pytest.fixture(scope="module")
def wallet(stack):
    _, client = stack
    # "cluster not ready" is retryable (a starved host can let 1 Hz
    # registry heartbeats go stale for a beat) — retry like a real
    # initiator would; any other failure is terminal
    for attempt in range(5):
        ev = _await(
            client.on_wallet_creation_result,
            lambda: client.create_wallet(f"w-e2e-{attempt}"),
            lambda ev, a=attempt: ev.wallet_id == f"w-e2e-{a}",
            timeout_s=600,
        )
        if ev.result_type == wire.RESULT_SUCCESS:
            return ev
        assert "not ready" in ev.error_reason, ev.error_reason
        time.sleep(3)
    raise AssertionError(f"wallet creation kept failing: {ev.error_reason}")


def test_create_wallet(wallet):
    assert not hm.secp_decompress(bytes.fromhex(wallet.ecdsa_pub_key)).is_infinity
    hm.ed_decompress(bytes.fromhex(wallet.eddsa_pub_key))


def test_sign_eddsa(stack, wallet):
    _, client = stack
    tx = b"e2e solana transfer"
    ev = _await(
        client.on_sign_result,
        lambda: client.sign_transaction(
            wire.SignTxMessage(
                key_type="ed25519", wallet_id=wallet.wallet_id,
                network_internal_code="solana-devnet",
                tx_id="tx-e2e-ed", tx=tx,
            )
        ),
        lambda ev: ev.tx_id == "tx-e2e-ed",
        timeout_s=300,
    )
    assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
    assert hm.ed25519_verify(
        bytes.fromhex(wallet.eddsa_pub_key), tx, bytes.fromhex(ev.signature)
    )


def test_sign_ecdsa(stack, wallet):
    _, client = stack
    digest = hashlib.sha256(b"e2e eth transfer").digest()
    ev = _await(
        client.on_sign_result,
        lambda: client.sign_transaction(
            wire.SignTxMessage(
                key_type="secp256k1", wallet_id=wallet.wallet_id,
                network_internal_code="ethereum",
                tx_id="tx-e2e-ec", tx=digest,
            )
        ),
        lambda ev: ev.tx_id == "tx-e2e-ec",
        timeout_s=300,
    )
    assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
    assert hm.ecdsa_verify(
        hm.secp_decompress(bytes.fromhex(wallet.ecdsa_pub_key)),
        int.from_bytes(digest, "big"), int(ev.r, 16), int(ev.s, 16),
    )


def test_reshare_then_sign(stack, wallet):
    _, client = stack
    ev = _await(
        client.on_resharing_result,
        lambda: client.resharing(wallet.wallet_id, new_threshold=2, key_type="ed25519"),
        lambda ev: ev.wallet_id == wallet.wallet_id,
        timeout_s=600,
    )
    assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason

    tx = secrets.token_bytes(24)
    sev = _await(
        client.on_sign_result,
        lambda: client.sign_transaction(
            wire.SignTxMessage(
                key_type="ed25519", wallet_id=wallet.wallet_id,
                network_internal_code="solana-devnet",
                tx_id="tx-e2e-post-reshare", tx=tx,
            )
        ),
        lambda ev: ev.tx_id == "tx-e2e-post-reshare",
        timeout_s=300,
    )
    assert sev.result_type == wire.RESULT_SUCCESS, sev.error_reason
    assert hm.ed25519_verify(
        bytes.fromhex(ev.pub_key or wallet.eddsa_pub_key), tx,
        bytes.fromhex(sev.signature),
    )


def test_example_networked_mode(stack, wallet):
    """examples/generate.py --config drives the SAME running deployment
    (RemoteCluster): the reference examples' mode against a live stack."""
    ws, _ = stack
    r = subprocess.run(
        [
            sys.executable, str(REPO / "examples" / "generate.py"),
            "--config", str(ws / "config.yaml"),
            "wallet-example-net",
        ],
        env=_child_env(), cwd=ws, capture_output=True, text=True,
        timeout=600,
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "wallet created: wallet-example-net" in r.stdout
