"""Batched DKG + resharing engines vs host-math ground truth."""
import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.engine.dkg_batch import BatchedDKG, BatchedReshare


def _recombine(shares, order, gen, mul, compress):
    xs = [s.self_x for s in shares]
    sec = 0
    for s in shares:
        lam = hm.lagrange_coeff(xs, s.self_x, order)
        sec = (sec + lam * s.share) % order
    return compress(mul(sec, gen))


def test_eddsa_batched_dkg_recombines():
    dkg = BatchedDKG(["n0", "n1", "n2"], threshold=1, key_type="ed25519")
    shares = dkg.run(3)
    for w in range(3):
        got = _recombine(
            [shares[0][w], shares[2][w]], hm.ED_L, hm.ED_B, hm.ed_mul,
            hm.ed_compress,
        )
        assert got == shares[0][w].public_key
        assert shares[1][w].epoch == 0
        assert len(shares[0][w].vss_commitments) == 2


def test_secp_batched_dkg_recombines():
    dkg = BatchedDKG(["n0", "n1", "n2"], threshold=1, key_type="secp256k1")
    shares = dkg.run(2)
    for w in range(2):
        got = _recombine(
            [shares[0][w], shares[1][w]], hm.SECP_N, hm.SECP_G, hm.secp_mul,
            hm.secp_compress,
        )
        assert got == shares[0][w].public_key


def test_batched_reshare_2of3_to_3of5():
    dkg = BatchedDKG(["n0", "n1", "n2"], threshold=1, key_type="ed25519")
    shares = dkg.run(3)
    rs = BatchedReshare(
        ["n0", "n1"], [shares[0], shares[1]],
        ["m0", "m1", "m2", "m3", "m4"], new_threshold=2,
    )
    new = rs.run()
    for w in range(3):
        trio = [new[0][w], new[2][w], new[4][w]]
        got = _recombine(trio, hm.ED_L, hm.ED_B, hm.ed_mul, hm.ed_compress)
        assert got == shares[0][w].public_key  # key unchanged
        assert new[0][w].epoch == 1
        assert new[0][w].aux.get("is_reshared")
        assert new[0][w].threshold == 2
    # old 2-subset of new committee alone must NOT recombine (t_new = 2)
    pair = [new[0][0], new[1][0]]
    got = _recombine(pair, hm.ED_L, hm.ED_B, hm.ed_mul, hm.ed_compress)
    assert got != shares[0][0].public_key
