"""Claim-refcount lifecycle of the batch scheduler (no cluster, no
engine): manifest coverage must transfer bucket entries' dedup claims to
the batch thread with no window where ``owns_dedup`` goes false, and the
counts must return to zero when the thread exits — success or crash.
Regression for the set→refcount migration (a live ``.add`` on the dict
crashed every covering manifest, and inherit+register double-counted)."""
import threading
import types

import pytest

from mpcium_tpu.consumers.batch_scheduler import (
    BatchSigningScheduler,
    _Entry,
    _entry_key,
)
from mpcium_tpu.transport.loopback import LoopbackFabric


class _Msg:
    def __init__(self, wallet_id, tx_id):
        self.wallet_id = wallet_id
        self.tx_id = tx_id


def _sched():
    node = types.SimpleNamespace(node_id="n0", peer_ids=["n0", "n1", "n2"])
    return BatchSigningScheduler(node, transport=LoopbackFabric().transport())


def _bucket_with(s, msgs):
    entries = [_Entry(m, f"reply.{m.tx_id}", kind="sign") for m in msgs]
    with s._lock:
        s._buckets[("sign-bucket",)] = list(entries)
    return entries


def test_inherit_transfers_claims_without_gap():
    s = _sched()
    msgs = [_Msg("w1", "t1"), _Msg("w2", "t2")]
    _bucket_with(s, msgs)
    covered = {_entry_key("sign", m) for m in msgs}

    inherited = s._inherit_covered("sign", covered)
    assert sorted(inherited) == sorted(covered)
    assert s._buckets[("sign-bucket",)] == []
    # between manifest processing and the batch thread's start the
    # claims must already be protected (the GC probes owns_dedup)
    assert s.owns_dedup("w1-t1") and s.owns_dedup("w2-t2")

    seen_inside = {}

    def runner(batch_id, reqs, inh):
        seen_inside["w1"] = s.owns_dedup("w1-t1")
        seen_inside["w2"] = s.owns_dedup("w2-t2")

    reqs = [(m, f"reply.{m.tx_id}") for m in msgs]
    s._run_guarded("sign", runner, "b1", reqs, inherited=inherited)
    assert seen_inside == {"w1": True, "w2": True}
    # no refcount leak: the GC owns the claims from here on
    assert not s.owns_dedup("w1-t1") and not s.owns_dedup("w2-t2")
    assert s._batch_claims == {}


def test_crashing_runner_still_releases_claims():
    s = _sched()
    msgs = [_Msg("w3", "t3")]
    _bucket_with(s, msgs)
    inherited = s._inherit_covered(
        "sign", {_entry_key("sign", m) for m in msgs}
    )

    def runner(batch_id, reqs, inh):
        raise RuntimeError("engine died")

    with pytest.raises(RuntimeError):
        s._run_guarded(
            "sign", runner, "b2", [(msgs[0], "r")], inherited=inherited
        )
    assert s._batch_claims == {}
    assert not s.owns_dedup("w3-t3")


def test_double_coverage_refcounts_overlap():
    # deputy takeover + a late original-leader manifest: two batch
    # threads legitimately cover the same request on one node; the
    # first thread's exit must not clobber the second's protection
    s = _sched()
    m = _Msg("w4", "t4")
    key = _entry_key("sign", m)
    _bucket_with(s, [m])
    inherited = s._inherit_covered("sign", {key})
    barrier = threading.Barrier(2)
    release_a = threading.Event()

    def runner_a(batch_id, reqs, inh):
        barrier.wait(timeout=5)
        release_a.wait(timeout=5)

    def runner_b(batch_id, reqs, inh):
        barrier.wait(timeout=5)  # both threads registered
        release_a.set()

    ta = threading.Thread(
        target=s._run_guarded,
        args=("sign", runner_a, "ba", [(m, "r")]),
        kwargs={"inherited": inherited},
    )
    # runner_b path: second manifest arrives with the entry no longer
    # in a bucket -> no inherit, plain registration
    tb = threading.Thread(
        target=s._run_guarded,
        args=("sign", runner_b, "bb", [(m, "r")]),
        kwargs={"inherited": []},
    )
    ta.start()
    tb.start()
    ta.join(timeout=10)
    # thread A exited while B may still run; wait B out
    tb.join(timeout=10)
    assert not ta.is_alive() and not tb.is_alive()
    assert s._batch_claims == {}
    assert not s.owns_dedup("w4-t4")
