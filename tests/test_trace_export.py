"""trace.export / trace.schema + the wire-level trace contract: merged
Chrome-trace documents validate, the committed sample stays valid, and
the optional envelope trace field never changes signing bytes or the
untraced wire format."""
import json
import os

import pytest

from mpcium_tpu import wire
from mpcium_tpu.trace import (
    TraceSchemaError,
    chrome_trace,
    recorder,
    snapshot_chrome,
    validate_chrome,
)
from mpcium_tpu.utils import tracing

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _tracing_off():
    tracing.disable()
    recorder.reset()
    recorder.set_dump_dir(None)
    yield
    tracing.disable()
    recorder.reset()
    recorder.set_dump_dir(None)


def _span(name, node, tid, t0, t1, **attrs):
    return {
        "name": name, "trace_id": "t" * 16, "span_id": "s1",
        "parent_id": None, "node": node, "tid": tid,
        "t0_ns": t0, "t1_ns": t1, "kind": "X",
        "attrs": attrs,
    }


# -- chrome export ------------------------------------------------------------


def test_chrome_trace_merges_nodes_with_pid_per_node():
    doc = chrome_trace({
        "node0": ([_span("session", "node0", "sess-1", 1000, 5000)], 0),
        "node1": ([_span("session", "node1", "sess-1", 2000, 6000)], 3),
    }, meta={"drill": "kill-resume"})
    n = validate_chrome(doc)
    assert n == len(doc["traceEvents"])
    procs = {e["args"]["name"]: e["pid"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert set(procs) == {"node0", "node1"}
    assert len(set(procs.values())) == 2
    threads = [e["args"]["name"] for e in doc["traceEvents"]
               if e["ph"] == "M" and e["name"] == "thread_name"]
    assert threads == ["sess-1", "sess-1"]
    # timestamps are µs relative to the earliest span
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert min(e["ts"] for e in xs) == 0.0
    assert doc["otherData"]["dropped_spans"] == {"node0": 0, "node1": 3}
    assert doc["otherData"]["drill"] == "kill-resume"


def test_chrome_trace_args_carry_span_identity():
    parent = _span("outer", "node0", "s", 0, 10)
    child = dict(_span("inner", "node0", "s", 2, 8), parent_id="p9",
                 span_id="s2")
    doc = chrome_trace({"node0": ([parent, child], 0)})
    inner = next(e for e in doc["traceEvents"] if e.get("name") == "inner")
    assert inner["args"]["parent_id"] == "p9"
    assert inner["args"]["trace_id"] == "t" * 16


def test_snapshot_chrome_from_live_recorders():
    tracing.enable(sink=recorder.record)
    with tracing.span("session", trace_id="abc", node="node0", tid="sess-9"):
        pass
    tracing.instant("intake", node="node1", tid="lane:bulk")
    doc = snapshot_chrome(meta={"soak_seed": 1})
    validate_chrome(doc)
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"session", "intake"} <= names
    assert doc["otherData"]["soak_seed"] == 1


# -- schema checker -----------------------------------------------------------


def test_schema_rejects_malformed_documents():
    with pytest.raises(TraceSchemaError):
        validate_chrome([])  # top level must be an object
    with pytest.raises(TraceSchemaError):
        validate_chrome({"traceEvents": "nope"})
    with pytest.raises(TraceSchemaError, match="unknown ph"):
        validate_chrome({"traceEvents": [{"ph": "Z", "name": "x", "pid": 1}]})
    with pytest.raises(TraceSchemaError, match="dur"):
        validate_chrome({"traceEvents": [
            {"ph": "X", "name": "x", "pid": 1, "tid": 1, "ts": 0}
        ]})
    with pytest.raises(TraceSchemaError, match="ts"):
        validate_chrome({"traceEvents": [
            {"ph": "i", "name": "x", "pid": 1, "tid": 1, "ts": -1}
        ]})


def test_schema_accepts_empty_trace():
    assert validate_chrome({"traceEvents": []}) == 0


def test_committed_sample_trace_is_valid():
    path = os.path.join(HERE, "..", "TRACE_sample.json")
    with open(path) as f:
        doc = json.load(f)
    n = validate_chrome(doc)
    assert n > 0
    assert doc["otherData"]["format"] == "chrome-trace-events"
    # the sample covers the layers the acceptance list names
    names = {e["name"] for e in doc["traceEvents"]}
    assert any(x.startswith("round:") for x in names)
    assert any(x.startswith("phase:") for x in names)


# -- wire contract ------------------------------------------------------------


def _env(**kw):
    return wire.Envelope(
        session_id="sess-1", round="r1", from_id="node0",
        payload={"x": 1}, to=None, is_broadcast=True, **kw,
    )


def test_envelope_trace_absent_when_none():
    assert "trace" not in _env().to_json()
    d = _env(trace={"t": "a" * 16, "s": "b" * 16}).to_json()
    assert d["trace"] == {"t": "a" * 16, "s": "b" * 16}
    rt = wire.Envelope.from_json(d)
    assert rt.trace == {"t": "a" * 16, "s": "b" * 16}
    # legacy envelopes (no trace key) parse to None
    legacy = _env().to_json()
    assert wire.Envelope.from_json(legacy).trace is None


def test_envelope_signing_bytes_ignore_trace():
    plain = _env()
    traced = _env(trace={"t": "a" * 16, "s": "b" * 16})
    assert plain.marshal_for_signing() == traced.marshal_for_signing()


def test_envelope_untraced_json_byte_identical():
    # the transcript-equality contract at the envelope layer: tracing off
    # (trace=None) serializes to exactly the pre-trace wire bytes
    assert json.dumps(_env().to_json(), sort_keys=True) == json.dumps(
        {
            "session_id": "sess-1", "round": "r1", "from": "node0",
            "to": None, "is_broadcast": True, "payload": {"x": 1},
            "signature": "",
        },
        sort_keys=True,
    )


# -- transcript equality through the protocol runner --------------------------


class _DetRng:
    """Deterministic secrets-shaped rng for transcript comparison."""

    def __init__(self, seed: int):
        import random

        self._r = random.Random(seed)

    def token_bytes(self, n: int) -> bytes:
        return self._r.randbytes(n)

    def randbelow(self, n: int) -> int:
        return self._r.randrange(n)


def _run_eddsa_sign(traced: bool):
    """One full in-process batched EdDSA signing run over the protocol
    runner, with every delivered round message recorded. Deterministic
    rng, so a traced and an untraced run must produce byte-identical
    transcripts AND signatures."""
    from mpcium_tpu.engine import eddsa_batch as eb
    from mpcium_tpu.protocol.eddsa.batch_signing import (
        BatchedEDDSASigningParty,
    )
    from mpcium_tpu.protocol.runner import run_protocol

    ids = ["n0", "n1"]
    shares = eb.dealer_keygen_batch(2, ids, 1, rng=_DetRng(7))
    msgs = [b"m0" * 16, b"m1" * 16]
    spans = []
    transcript = []
    if traced:
        tracing.enable(sink=spans.append)
    try:
        parties = {
            pid: BatchedEDDSASigningParty(
                "ts-eq", pid, ids, shares[i], msgs, rng=_DetRng(13 + i)
            )
            for i, pid in enumerate(ids)
        }
        for p in parties.values():
            orig = p.receive

            def recording(m, _o=orig):
                transcript.append((m.round, m.from_id, m.to, repr(m.payload)))
                return _o(m)

            p.receive = recording
        run_protocol(parties)
    finally:
        tracing.disable()
    sigs = {pid: p.result["signatures"].tobytes()
            for pid, p in parties.items()}
    oks = {pid: bool(p.result["ok"].all()) for pid, p in parties.items()}
    return transcript, sigs, oks, spans


def test_runner_transcript_identical_traced_vs_untraced():
    t_off, sig_off, ok_off, no_spans = _run_eddsa_sign(traced=False)
    assert no_spans == []
    assert all(ok_off.values())
    t_on, sig_on, ok_on, spans = _run_eddsa_sign(traced=True)
    # spans exist for the traced run; the protocol transcript and the
    # resulting signatures are bit-identical either way
    assert any(s["name"].startswith("round:") for s in spans)
    assert t_on == t_off and len(t_off) > 0
    assert sig_on == sig_off
    assert all(ok_on.values())
