"""ops.paillier_mxu vs host Paillier ground truth (shrunk keys)."""
import secrets

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu.core import paillier as pl
from mpcium_tpu.ops import paillier_mxu as pmx


@pytest.fixture(scope="module")
def key():
    return pl.gen_paillier_key(bits=512)


def _bits(vals, n_bits):
    return jnp.asarray(
        np.stack([[(v >> i) & 1 for i in range(n_bits)] for v in vals]).astype(
            np.int32
        )
    )


def test_encrypt_matches_host_with_returned_randomizer(key):
    pk = key.public
    pb = pmx.PaillierMXU(pk)
    B = 4
    ms = [secrets.randbelow(pk.N) for _ in range(B)]
    us = [secrets.randbits(pmx.RAND_BITS) for _ in range(B)]
    c, r = pb.encrypt(
        jnp.asarray(pb.to_limbs_N(ms)), _bits(us, pmx.RAND_BITS)
    )
    c_host = pb.from_limbs_N2(c)
    r_host = pb.from_limbs_N(r)
    for i in range(B):
        # r is the effective randomizer: c == Enc(m; r) classically
        assert r_host[i] == pow(pb.y, us[i], pk.N)
        assert c_host[i] == pk.encrypt(ms[i], r=r_host[i])
        assert key.decrypt(c_host[i]) == ms[i]


def test_crt_decrypt(key):
    pb = pmx.PaillierMXUPrivate(key)
    pk = key.public
    B = 5
    ms = [secrets.randbelow(pk.N) for _ in range(B)] + [0]
    cs = [pk.encrypt(m) for m in ms]
    got = pb.from_limbs_N(pb.decrypt(jnp.asarray(pb.to_limbs_N2(cs))))
    assert got == ms


def test_homomorphic_add_scalar(key):
    pk = key.public
    pb = pmx.PaillierMXUPrivate(key)
    B = 3
    a = [secrets.randbelow(pk.N) for _ in range(B)]
    b = [secrets.randbelow(pk.N) for _ in range(B)]
    k = [secrets.randbits(64) for _ in range(B)]
    ca = jnp.asarray(pb.to_limbs_N2([pk.encrypt(x) for x in a]))
    cb = jnp.asarray(pb.to_limbs_N2([pk.encrypt(x) for x in b]))
    s = pb.from_limbs_N(pb.decrypt(pb.add(ca, cb)))
    assert s == [(x + y) % pk.N for x, y in zip(a, b)]
    cm_ = pb.scalar_mul(ca, _bits(k, 64))
    s2 = pb.from_limbs_N(pb.decrypt(cm_))
    assert s2 == [x * kk % pk.N for x, kk in zip(a, k)]
