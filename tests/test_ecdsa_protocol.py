"""GG18 ECDSA: ZK proofs, MtA, keygen + signing end-to-end."""
import json
import secrets
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.core import paillier as pl
from mpcium_tpu.protocol.ecdsa import mta, zk
from mpcium_tpu.protocol.ecdsa.keygen import ECDSAKeygenParty
from mpcium_tpu.protocol.ecdsa.signing import ECDSASigningParty
from mpcium_tpu.protocol.runner import run_protocol

DATA = Path(__file__).resolve().parent.parent / "mpcium_tpu" / "data"


@pytest.fixture(scope="module")
def preparams():
    d = json.load(open(DATA / "test_preparams.json"))["preparams"]
    return {k: pl.PreParams.from_json(v) for k, v in d.items()}


@pytest.fixture(scope="module")
def wallets(preparams):
    """One DKG run shared by the signing tests."""
    ids = sorted(preparams)
    parties = {
        pid: ECDSAKeygenParty("w1", pid, ids, threshold=1, preparams=preparams[pid])
        for pid in ids
    }
    run_protocol(parties)
    return {pid: p.result for pid, p in parties.items()}


def test_dln_proof(preparams):
    pp = preparams["node0"]
    pq = (pp.P - 1) // 2 * ((pp.Q - 1) // 2)
    proof = zk.DLNProof.prove(pp.h1, pp.h2, pp.alpha, pq, pp.NTilde)
    assert proof.verify(pp.h1, pp.h2, pp.NTilde)
    assert not proof.verify(pp.h2, pp.h1, pp.NTilde)  # wrong statement
    rt = zk.DLNProof.from_json(proof.to_json())
    assert rt.verify(pp.h1, pp.h2, pp.NTilde)


def test_paillier_proof(preparams):
    sk = preparams["node0"].paillier
    proof = zk.PaillierProof.prove(sk)
    assert proof.verify(sk.public)
    other = preparams["node1"].paillier.public
    assert not proof.verify(other)


def test_schnorr_and_pedersen():
    x = secrets.randbelow(zk.Q - 1) + 1
    X = hm.secp_mul(x, hm.SECP_G)
    p = zk.SchnorrProof.prove(x, X)
    assert p.verify(X)
    assert not p.verify(hm.secp_mul(x + 1, hm.SECP_G))

    a, b = (secrets.randbelow(zk.Q) for _ in range(2))
    R = hm.secp_mul(7, hm.SECP_G)
    V = hm.secp_add(hm.secp_mul(a, R), hm.secp_mul(b, hm.SECP_G))
    pp = zk.PedersenPoK.prove(a, b, R, V)
    assert pp.verify(R, V)
    assert not pp.verify(R, hm.secp_add(V, hm.SECP_G))


def test_mta_roundtrip(preparams):
    alice, bob = preparams["node0"], preparams["node1"]
    pk_a = alice.paillier.public
    a = secrets.randbelow(zk.Q)
    b = secrets.randbelow(zk.Q)
    init, _ = mta.mta_init(pk_a, bob.NTilde, bob.h1, bob.h2, a)
    resp, beta = mta.mta_respond(
        pk_a,
        alice.NTilde, alice.h1, alice.h2,
        bob.NTilde, bob.h1, bob.h2,
        init, b, with_check=False,
    )
    alpha = mta.mta_finalize(
        alice.paillier, alice.NTilde, alice.h1, alice.h2, init, resp
    )
    assert (alpha + beta) % zk.Q == a * b % zk.Q


def test_mta_with_check_binds_point(preparams):
    alice, bob = preparams["node0"], preparams["node1"]
    pk_a = alice.paillier.public
    a, b = secrets.randbelow(zk.Q), secrets.randbelow(zk.Q)
    init, _ = mta.mta_init(pk_a, bob.NTilde, bob.h1, bob.h2, a)
    resp, beta = mta.mta_respond(
        pk_a,
        alice.NTilde, alice.h1, alice.h2,
        bob.NTilde, bob.h1, bob.h2,
        init, b, with_check=True,
    )
    X = hm.secp_mul(b, hm.SECP_G)
    alpha = mta.mta_finalize(
        alice.paillier, alice.NTilde, alice.h1, alice.h2, init, resp, X=X
    )
    assert (alpha + beta) % zk.Q == a * b % zk.Q
    # wrong public point must be rejected
    with pytest.raises(ValueError):
        mta.mta_finalize(
            alice.paillier, alice.NTilde, alice.h1, alice.h2, init, resp,
            X=hm.secp_mul(b + 1, hm.SECP_G),
        )


def test_range_proof_rejects_negative_s1(preparams):
    """Regression: a negative s1 flips pow() into modular inverses and the
    equations verify for out-of-range plaintexts unless explicitly bounded."""
    import dataclasses

    alice, bob = preparams["node0"], preparams["node1"]
    pk_a = alice.paillier.public
    init, _ = mta.mta_init(pk_a, bob.NTilde, bob.h1, bob.h2, 42)
    assert init.proof.verify(pk_a, bob.NTilde, bob.h1, bob.h2, init.c_a)
    forged = dataclasses.replace(init.proof, s1=-init.proof.s1)
    assert not forged.verify(pk_a, bob.NTilde, bob.h1, bob.h2, init.c_a)


def test_bob_proof_rejects_oversized_beta_prime(preparams):
    """Regression: t1 ≤ q⁷ bound — β′ ≈ N would let Alice's decrypt-wrap
    behavior leak comparison bits on k_i."""
    alice, bob = preparams["node0"], preparams["node1"]
    pk_a = alice.paillier.public
    init, _ = mta.mta_init(pk_a, bob.NTilde, bob.h1, bob.h2, 42)
    b = secrets.randbelow(zk.Q)
    beta_prime = pk_a.N - zk.Q**6  # malicious: way beyond q⁵
    r = zk._rand_unit(pk_a.N)
    c_beta = pk_a.encrypt(beta_prime, r=r)
    c_b = pow(init.c_a, b, pk_a.N2) * c_beta % pk_a.N2
    proof = zk.RespProofBob.prove(
        pk_a, alice.NTilde, alice.h1, alice.h2, init.c_a, c_b, b, beta_prime, r
    )
    assert not proof.verify(pk_a, alice.NTilde, alice.h1, alice.h2, init.c_a, c_b)


def test_keygen_proofs_are_session_bound(preparams):
    """Regression: DLN/Paillier proofs replayed into a different wallet's
    keygen (different session id) must not verify."""
    pp = preparams["node0"]
    pq = (pp.P - 1) // 2 * ((pp.Q - 1) // 2)
    proof = zk.DLNProof.prove(pp.h1, pp.h2, pp.alpha, pq, pp.NTilde, bind=b"w1:node0")
    assert proof.verify(pp.h1, pp.h2, pp.NTilde, bind=b"w1:node0")
    assert not proof.verify(pp.h1, pp.h2, pp.NTilde, bind=b"w2:node1")
    pproof = zk.PaillierProof.prove(pp.paillier, bind=b"w1:node0")
    assert pproof.verify(pp.paillier.public, bind=b"w1:node0")
    assert not pproof.verify(pp.paillier.public, bind=b"w2:node1")


def test_keygen_produces_consistent_wallet(wallets):
    pubs = {w.public_key for w in wallets.values()}
    assert len(pubs) == 1  # same public key everywhere
    # shares interpolate to the secret behind the pubkey (test-only!)
    xs = [w.self_x for w in wallets.values()]
    secret = 0
    for w in wallets.values():
        lam = hm.lagrange_coeff(xs, w.self_x, zk.Q)
        secret = (secret + lam * w.share) % zk.Q
    assert hm.secp_compress(hm.secp_mul(secret, hm.SECP_G)) == next(iter(pubs))
    w0 = next(iter(wallets.values()))
    assert len(w0.vss_commitments) == 2  # t+1 aggregated commitments
    assert len(w0.aux["peer_paillier"]) == 2


@pytest.mark.parametrize("quorum", [["node0", "node1"], ["node0", "node2"]])
def test_signing_end_to_end(wallets, quorum):
    digest = int.from_bytes(secrets.token_bytes(32), "big")
    parties = {
        pid: ECDSASigningParty(
            f"tx-{quorum[-1]}", pid, quorum, wallets[pid], digest
        )
        for pid in quorum
    }
    run_protocol(parties)
    results = [p.result for p in parties.values()]
    assert all(r == results[0] for r in results)
    r, s, rec = results[0]["r"], results[0]["s"], results[0]["recovery"]
    assert s <= zk.Q // 2  # low-s
    pub = hm.secp_decompress(next(iter(wallets.values())).public_key)
    assert hm.ecdsa_verify(pub, digest, r, s)
    # independent verification via OpenSSL
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec, utils

    pn = ec.EllipticCurvePublicNumbers(pub.x, pub.y, ec.SECP256K1())
    key = pn.public_key()
    sig = utils.encode_dss_signature(r, s)
    key.verify(
        sig, digest.to_bytes(32, "big"), ec.ECDSA(utils.Prehashed(hashes.SHA256()))
    )
