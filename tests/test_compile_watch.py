"""Compile-wall ledger (perf/compile_watch.py): shape-bucket dedup,
persistent-cache hit/miss classification, the on-disk ledger file, span
emission (schema-valid through the trace export), warming→ready state,
and the Prometheus gauge mirror."""
import json
import os

import pytest

from mpcium_tpu.perf import compile_watch
from mpcium_tpu.trace.export import chrome_trace
from mpcium_tpu.trace.schema import validate_chrome
from mpcium_tpu.utils import tracing
from mpcium_tpu.utils.metrics import MetricsRegistry

pytestmark = pytest.mark.perf


@pytest.fixture(autouse=True)
def _clean_ledger():
    compile_watch.reset()
    yield
    compile_watch.reset()
    tracing.disable()


def test_first_call_per_shape_ledgers_then_dedups(tmp_path):
    compile_watch.set_ledger_dir(str(tmp_path))
    tok = compile_watch.begin("gg18.sign", "B4|q2|mta=paillier")
    assert tok is not None
    entry = compile_watch.finish(tok)
    assert entry["engine"] == "gg18.sign"
    assert entry["shape"] == "B4|q2|mta=paillier"
    assert entry["compile_s"] >= 0.0
    # same bucket again: one set lookup, no token, no second entry
    assert compile_watch.begin("gg18.sign", "B4|q2|mta=paillier") is None
    # a DIFFERENT shape is a new bucket
    assert compile_watch.begin("gg18.sign", "B8|q2|mta=paillier") is not None
    assert len(compile_watch.entries()) == 1


def test_finish_none_is_noop():
    assert compile_watch.finish(None) is None
    assert compile_watch.entries() == []


def test_cache_miss_hit_none_classification(tmp_path, monkeypatch):
    cache = tmp_path / "xla_cache"
    cache.mkdir()
    monkeypatch.setattr(compile_watch, "_jax_cache_dir",
                        lambda: str(cache))
    compile_watch.set_ledger_dir(str(tmp_path))

    # miss: a new cache artifact appeared between begin and finish
    tok = compile_watch.begin("e", "s1")
    (cache / "artifact_0").write_text("x")
    assert compile_watch.finish(tok)["cache"] == "miss"

    # hit: cache dir exists, nothing new was written (deserialized)
    tok = compile_watch.begin("e", "s2")
    assert compile_watch.finish(tok)["cache"] == "hit"

    # none: no cache dir configured at all
    monkeypatch.setattr(compile_watch, "_jax_cache_dir", lambda: None)
    tok = compile_watch.begin("e", "s3")
    assert compile_watch.finish(tok)["cache"] == "none"


def test_ledger_file_written_and_appended(tmp_path):
    compile_watch.set_ledger_dir(str(tmp_path))
    compile_watch.finish(compile_watch.begin("e", "s1"))
    compile_watch.finish(compile_watch.begin("e", "s2"))
    path = os.path.join(str(tmp_path), compile_watch.LEDGER_BASENAME)
    assert compile_watch.ledger_path() == path
    with open(path) as f:
        doc = json.load(f)
    assert [e["shape"] for e in doc["entries"]] == ["s1", "s2"]


def test_ledger_file_excluded_from_cache_counting(tmp_path, monkeypatch):
    # the ledger lives INSIDE the XLA cache dir in the default layout;
    # its own rewrite must never read as a cache miss
    monkeypatch.setattr(compile_watch, "_jax_cache_dir",
                        lambda: str(tmp_path))
    compile_watch.finish(compile_watch.begin("e", "s1"))  # writes ledger
    assert compile_watch.finish(compile_watch.begin("e", "s2"))["cache"] == "hit"


def test_compile_span_emitted_and_schema_valid(tmp_path):
    compile_watch.set_ledger_dir(str(tmp_path))
    spans = []
    tracing.enable(sink=spans.append)
    compile_watch.finish(compile_watch.begin("dkg.run", "B16|q3|ed25519"))
    tracing.disable()
    assert len(spans) == 1
    s = spans[0]
    assert s["name"] == "compile:dkg.run"
    assert s["node"] == "engine" and s["tid"] == "compile"
    assert s["attrs"]["shape"] == "B16|q3|ed25519"
    assert s["attrs"]["cache"] in ("hit", "miss", "none")
    assert s["t1_ns"] >= s["t0_ns"]
    validate_chrome(chrome_trace({"engine": (spans, 0)}))


def test_no_span_when_tracing_disabled(tmp_path):
    compile_watch.set_ledger_dir(str(tmp_path))
    entry = compile_watch.finish(compile_watch.begin("e", "s"))
    assert entry is not None  # ledger entry regardless of tracing


def test_warming_ready_state_and_health_summary(tmp_path):
    compile_watch.set_ledger_dir(str(tmp_path))
    assert compile_watch.health_summary()["state"] == "ready"  # non-daemon
    compile_watch.mark_warming()
    assert compile_watch.health_summary()["state"] == "warming"
    compile_watch.finish(compile_watch.begin("e", "s"))
    compile_watch.mark_ready()
    h = compile_watch.health_summary()
    assert h["state"] == "ready"
    assert h["compiles"] == 1
    assert h["cache_hits"] + h["cache_misses"] <= 1
    assert h["total_compile_s"] >= 0.0
    assert h["last"]["shape"] == "s"
    assert h["ledger"].endswith(compile_watch.LEDGER_BASENAME)


def test_export_gauges_mirror(tmp_path):
    compile_watch.set_ledger_dir(str(tmp_path))
    compile_watch.mark_warming()
    compile_watch.finish(compile_watch.begin("e", "s"))
    m = MetricsRegistry()
    compile_watch.export_gauges(m)
    g = m.snapshot()["gauges"]
    assert g["compile.ready"] == 0.0
    assert g["compile.count"] == 1.0
    compile_watch.mark_ready()
    compile_watch.export_gauges(m)
    assert m.snapshot()["gauges"]["compile.ready"] == 1.0


def test_seen_accessor(tmp_path):
    compile_watch.set_ledger_dir(str(tmp_path))
    assert not compile_watch.seen("e", "s")
    compile_watch.finish(compile_watch.begin("e", "s"))
    assert compile_watch.seen("e", "s")
    assert not compile_watch.seen("e", "other")


def test_unpredicted_in_summary_and_gauges(tmp_path):
    """predicted:false entries — surface drift that escaped the static
    gate — surface as a count in health and a compile.unpredicted gauge
    (ISSUE 13 satellite)."""
    compile_watch.set_ledger_dir(str(tmp_path))
    compile_watch.finish(compile_watch.begin("gg18.sign", "B4|q2|mta=ot"))
    compile_watch.finish(compile_watch.begin("no-such-engine", "B4"))
    h = compile_watch.health_summary()
    assert h["unpredicted"] == 1
    m = MetricsRegistry()
    compile_watch.export_gauges(m)
    assert m.snapshot()["gauges"]["compile.unpredicted"] == 1.0


def test_engine_hooks_ledger_real_sign(tmp_path):
    """End-to-end: a real (tiny) eddsa batch sign lands exactly one
    ledger entry per shape bucket, with repeat signs deduplicated."""
    import secrets

    from mpcium_tpu.engine import eddsa_batch as eb

    compile_watch.set_ledger_dir(str(tmp_path))
    ids = ["node0", "node1", "node2"]
    shares = eb.dealer_keygen_batch(2, ids, 1, rng=secrets)
    signer = eb.BatchedCoSigners(ids[:2], shares[:2], rng=secrets)
    msgs = [secrets.token_bytes(32) for _ in range(2)]
    _sigs, ok = signer.sign(msgs)
    assert ok.all()
    _sigs, ok = signer.sign(msgs)  # second call: dedup, no new entry
    assert ok.all()
    ents = [e for e in compile_watch.entries() if e["engine"] == "eddsa.sign"]
    assert len(ents) == 1
    assert ents[0]["shape"] == "B2|q2"
    # the runtime shape a real engine requests must be on the committed
    # static surface — an unpredicted compile is an mpcshape gap
    assert ents[0]["predicted"] is True


def test_predicted_stamped_against_explicit_surface(tmp_path):
    surface = {
        "engines": {
            "e": [{
                "template": "B{B}|q{q}",
                "dims": {
                    "B": {"class": "unbounded", "annotated": True,
                          "reason": "test"},
                    "q": {"class": "knob"},
                },
            }],
        },
    }
    path = tmp_path / "COMPILE_SURFACE.json"
    path.write_text(json.dumps(surface))
    compile_watch.set_surface_path(str(path))
    entry = compile_watch.finish(compile_watch.begin("e", "B64|q2"))
    assert entry["predicted"] is True
    # unknown engine / off-template shape → explicitly unpredicted
    entry = compile_watch.finish(compile_watch.begin("other", "B64|q2"))
    assert entry["predicted"] is False
    entry = compile_watch.finish(compile_watch.begin("e", "B64"))
    assert entry["predicted"] is False


def test_no_predicted_key_when_surface_unreadable(tmp_path):
    compile_watch.set_surface_path(str(tmp_path / "missing.json"))
    entry = compile_watch.finish(compile_watch.begin("e", "B64|q2"))
    assert "predicted" not in entry  # no surface: no guessing


def test_default_surface_is_the_committed_artifact():
    """With no override, finish() consults the repo-root
    COMPILE_SURFACE.json — engine shapes of every class match."""
    entry = compile_watch.finish(
        compile_watch.begin("gg18.sign", "B1024|q2|mta=ot")
    )
    assert entry["predicted"] is True
    entry = compile_watch.finish(
        compile_watch.begin("gg18.sign", "B1024|q2")  # template mismatch
    )
    assert entry["predicted"] is False
