"""Broker high availability: hot standby + client failover.

The reference gets HA from NATS clustering / JetStream replication; here a
standby BrokerServer follows the primary's queue state and clients walk an
address list. These tests kill the primary for real and assert traffic
resumes — including delivery of a message that only ever reached the
primary before it died (replication proof)."""
import threading
import time

import pytest

from mpcium_tpu.transport.tcp import (BrokerServer, TcpClient,
                                      parse_addrs, tcp_transport)

TOKEN = "ha-test-token"


def _mk_pair(tmp_path, encrypt=False):
    token = TOKEN if encrypt else None
    primary = BrokerServer(
        port=0, journal_path=str(tmp_path / "primary.jsonl"),
        journal_fsync=False, auth_token=token, encrypt=encrypt,
    )
    standby = BrokerServer(
        port=0, journal_path=str(tmp_path / "standby.jsonl"),
        journal_fsync=False, auth_token=token, encrypt=encrypt,
        follow=(primary.host, primary.port),
    )
    assert standby._rep_synced.wait(10), "standby never synced to primary"
    return primary, standby


def _client(primary, standby, encrypt=False, **kw):
    return TcpClient(
        primary.host, primary.port,
        addrs=[(primary.host, primary.port), (standby.host, standby.port)],
        auth_token=TOKEN if encrypt else None, encrypt=encrypt,
        reconnect_deadline_s=15.0, **kw,
    )


@pytest.mark.parametrize("encrypt", [False, True])
def test_failover_to_standby(tmp_path, encrypt):
    primary, standby = _mk_pair(tmp_path, encrypt=encrypt)
    producer = _client(primary, standby, encrypt=encrypt)

    # m1 reaches ONLY the primary (no consumer yet), then the primary dies
    producer.enqueue("jobs.a", b"m1", idempotency_key="m1")
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not standby._pending_q:
        time.sleep(0.05)
    assert standby._pending_q, "enqueue was not replicated to the standby"
    primary.close()

    got = []
    evt = threading.Event()

    def handler(data):
        got.append(data)
        evt.set()

    # a consumer arriving AFTER the primary's death connects straight to
    # the standby and must receive the replicated backlog
    consumer = _client(primary, standby, encrypt=encrypt)
    consumer._subscribe("queue", "jobs.*", handler)
    assert evt.wait(15), "replicated message never delivered by standby"
    assert got == [b"m1"]

    # the producer's connection died with the primary: its next enqueue
    # rides the transparent failover path. The very first write can vanish
    # into the dead socket's buffer (TCP reports the break on the NEXT
    # write) — publishers re-send under the same idempotency key, exactly
    # how the SDK's at-least-once contract expects them to
    evt.clear()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not evt.is_set():
        try:
            producer.enqueue("jobs.a", b"m2", idempotency_key="m2")
        except Exception:
            pass
        evt.wait(0.5)
    assert evt.is_set(), "post-failover enqueue never delivered"
    assert got[-1] == b"m2"

    producer.close()
    consumer.close()
    standby.close()


def test_subscriptions_replay_after_failover(tmp_path):
    """Pub/sub and queue subscriptions made before the failover keep
    working on the standby (client replays its registry)."""
    primary, standby = _mk_pair(tmp_path)
    a = _client(primary, standby)
    b = _client(primary, standby)

    seen = []
    evt = threading.Event()
    a._subscribe("pubsub", "events.*", lambda d: (seen.append(d), evt.set()))

    primary.close()
    # b notices the dead socket on its next op; a's reader fails over on
    # its own. Publish until a's replayed subscription catches one.
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and not evt.is_set():
        try:
            b.publish("events.x", b"hello")
            time.sleep(0.1)
        except Exception:
            time.sleep(0.1)
    assert evt.is_set(), "pub/sub subscription did not survive failover"
    assert seen[0] == b"hello"

    a.close()
    b.close()
    standby.close()


def test_restart_same_broker_reconnects(tmp_path):
    """Single-broker deployments: a client outlives a broker restart on
    the same endpoint (journal replays, subscriptions replay)."""
    jp = str(tmp_path / "solo.jsonl")
    broker = BrokerServer(port=0, journal_path=jp, journal_fsync=False)
    host, port = broker.host, broker.port
    cli = TcpClient(host, port, reconnect_deadline_s=15.0)

    got = []
    evt = threading.Event()
    cli._subscribe("queue", "work.*", lambda d: (got.append(d), evt.set()))
    cli.enqueue("work.q", b"before-restart", idempotency_key="k1")
    assert evt.wait(10)

    broker.close()
    time.sleep(0.3)
    broker2 = BrokerServer(host=host, port=port, journal_path=jp,
                           journal_fsync=False)
    # the restarted broker may first REdeliver m1 (its qack can race the
    # shutdown, and redelivering completed work is the journal's safe
    # direction) — wait for the new message, tolerating the redelivery
    cli.enqueue("work.q", b"after-restart", idempotency_key="k2")
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and b"after-restart" not in got:
        evt.clear()
        evt.wait(0.5)
    assert b"after-restart" in got, (
        f"client did not recover from a broker restart: {got}"
    )

    cli.close()
    broker2.close()


def test_parse_addrs():
    assert parse_addrs("") == []
    assert parse_addrs("10.0.0.2:4334") == [("10.0.0.2", 4334)]
    assert parse_addrs("a:1, b:2,") == [("a", 1), ("b", 2)]
    assert parse_addrs(":9") == [("127.0.0.1", 9)]
    with pytest.raises(ValueError, match="host:port"):
        parse_addrs("broker-standby")  # port-less config typo


def _wait(cond, timeout=8.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def test_two_standby_chain_failover(tmp_path):
    """primary <- s1 <- s2 chain: records applied on s1 are forwarded to
    s2, so after primary AND s1 die, clients still find the full durable
    state (queues + control-plane KV) on s2."""
    from mpcium_tpu.store.broker_kv import BrokerKV

    primary = BrokerServer(port=0)
    s1 = BrokerServer(port=0, follow=(primary.host, primary.port))
    assert _wait(lambda: s1._rep_synced.is_set())
    s2 = BrokerServer(port=0, follow=(s1.host, s1.port))
    assert _wait(lambda: s2._rep_synced.is_set())
    try:
        t = tcp_transport(
            primary.host, primary.port,
            standbys=[(s1.host, s1.port), (s2.host, s2.port)],
        )
        kv = BrokerKV(t.client)
        kv.put("threshold_keyinfo/w1", b"meta-1")
        t.queues.enqueue("q.work.a", b"payload-1")
        assert _wait(lambda: "threshold_keyinfo/w1" in s2._kv)
        assert _wait(lambda: len(s2._pending_q) == 1)

        primary.close()
        # new writes land on s1 and must chain onward to s2
        assert _wait(lambda: kv.get("threshold_keyinfo/w1") == b"meta-1",
                     timeout=15.0)
        kv.put("threshold_keyinfo/w2", b"meta-2")
        assert _wait(lambda: "threshold_keyinfo/w2" in s2._kv)

        s1.close()
        assert _wait(lambda: kv.get("threshold_keyinfo/w2") == b"meta-2",
                     timeout=15.0)
        got = []
        t.queues.dequeue("q.work.*", lambda d: got.append(d))
        assert _wait(lambda: got == [b"payload-1"])
        t.client.close()
    finally:
        s2.close()
