"""Consumer-side failure surfacing: the dead-letter → timeout-event bridge
(TimeoutConsumer) and the EventConsumer GC that reaps stale sessions and
aged session-less claims."""
import json
import threading
import time
from types import SimpleNamespace

from mpcium_tpu import wire
from mpcium_tpu.consumers.event_consumer import EventConsumer
from mpcium_tpu.consumers.signing_consumer import TimeoutConsumer
from mpcium_tpu.transport.loopback import LoopbackFabric


def _result_box(transport, tx_id):
    """Subscribe the per-tx result queue; returns (events, arrived, sub)."""
    events, arrived = [], threading.Event()

    def h(data):
        events.append(wire.SigningResultEvent.from_json(json.loads(data)))
        arrived.set()

    sub = transport.queues.dequeue(f"{wire.TOPIC_SIGNING_RESULT}.{tx_id}", h)
    return events, arrived, sub


def _sign_msg(tx_id):
    return wire.SignTxMessage(
        key_type=wire.KEY_TYPE_ED25519,
        wallet_id="w-gc",
        network_internal_code="testnet",
        tx_id=tx_id,
        tx=b"\x01\x02",
    )


# ---------------------------------------------------------------------------
# TimeoutConsumer dead-letter bridge
# ---------------------------------------------------------------------------


def test_dead_letter_emits_timeout_error_event():
    fabric = LoopbackFabric()
    transport = fabric.transport()
    tc = TimeoutConsumer(transport)
    tc.run()
    msg = _sign_msg("tx-dl")
    events, arrived, sub = _result_box(transport, "tx-dl")
    tc._on_dead_letter(
        wire.TOPIC_SIGNING_REQUEST, wire.canonical_json(msg.to_json()), 5
    )
    assert arrived.wait(5.0), "no result event emitted"
    ev = events[0]
    assert ev.result_type == wire.RESULT_ERROR
    assert ev.is_timeout
    assert ev.wallet_id == "w-gc" and ev.tx_id == "tx-dl"
    assert ev.network_internal_code == "testnet"
    assert "5 deliveries" in ev.error_reason
    sub.unsubscribe()
    fabric.close()


def test_dead_letter_ignores_foreign_topics():
    fabric = LoopbackFabric()
    transport = fabric.transport()
    tc = TimeoutConsumer(transport)
    msg = _sign_msg("tx-foreign")
    events, arrived, sub = _result_box(transport, "tx-foreign")
    tc._on_dead_letter(
        "mpc.other.queue", wire.canonical_json(msg.to_json()), 5
    )
    assert not arrived.wait(0.3), "event emitted for a non-signing topic"
    assert events == []
    sub.unsubscribe()
    fabric.close()


def test_dead_letter_tolerates_undecodable_payload():
    fabric = LoopbackFabric()
    tc = TimeoutConsumer(fabric.transport())
    # must log-and-return, not raise back into the transport
    tc._on_dead_letter(wire.TOPIC_SIGNING_REQUEST, b"\x00 not json", 3)
    fabric.close()


# ---------------------------------------------------------------------------
# EventConsumer GC
# ---------------------------------------------------------------------------


def _mk_ec(transport, **kw):
    # the GC path touches only node_id (logging); no real Node needed
    node = SimpleNamespace(node_id="n0", session_wal=None)
    return EventConsumer(node, transport, **kw)


class _FakeSession:
    """Looks stale to the GC; close() re-enters the consumer bookkeeping
    the way a real session's on_error does."""

    def __init__(self, ec, key):
        self.last_activity = time.monotonic() - 10_000.0
        self.closed = threading.Event()
        self._ec, self._key = ec, key

    def close(self):
        self.closed.set()
        self._ec._release(self._key)  # must not deadlock: reap closes outside the lock


def test_gc_reaps_stale_signing_claim_and_emits_timeout():
    fabric = LoopbackFabric()
    transport = fabric.transport()
    ec = _mk_ec(transport, session_timeout_s=0.2, gc_interval_s=0.05)
    msg = _sign_msg("tx-reap")
    key = f"{msg.wallet_id}-{msg.tx_id}"
    assert ec._claim(key, meta=("sign", msg))
    fs = _FakeSession(ec, key)
    ec._track(key, [fs])
    events, arrived, sub = _result_box(transport, "tx-reap")
    t = threading.Thread(target=ec._gc_loop, daemon=True)
    t.start()
    try:
        assert fs.closed.wait(5.0), "stale session was not closed"
        assert arrived.wait(5.0), "reap emitted no client-facing event"
        ev = events[0]
        assert ev.result_type == wire.RESULT_ERROR and ev.is_timeout
        assert ev.tx_id == "tx-reap"
        assert "reaped" in ev.error_reason
        with ec._lock:
            assert key not in ec._sessions
            assert key not in ec._claim_meta
    finally:
        ec._gc_stop.set()
        t.join(2.0)
        sub.unsubscribe()
        fabric.close()


def test_gc_reaps_aged_empty_claim_but_spares_fresh_ones():
    # a session-less claim (the _claim→_track window, or an orphan) must be
    # reaped once aged — an unreaped empty claim answers WIP to every
    # redelivery forever — while a fresh claim survives the same sweep
    fabric = LoopbackFabric()
    ec = _mk_ec(fabric.transport(), session_timeout_s=0.5, gc_interval_s=0.05)
    assert ec._claim("keygen-old")
    with ec._lock:
        ec._claim_ts["keygen-old"] -= 10.0  # age it artificially
    assert ec._claim("keygen-fresh")
    t = threading.Thread(target=ec._gc_loop, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with ec._lock:
                if "keygen-old" not in ec._sessions:
                    break
            time.sleep(0.02)
        with ec._lock:
            assert "keygen-old" not in ec._sessions, "aged claim not reaped"
            assert "keygen-fresh" in ec._sessions, "fresh claim reaped"
    finally:
        ec._gc_stop.set()
        t.join(2.0)
        fabric.close()
