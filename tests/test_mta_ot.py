"""OT-based MtA (protocol/ecdsa/mta_ot.py): base-OT correctness, Gilboa
share correctness over the scalar ring, extension-counter separation,
and the engine integration behind MPCIUM_MTA=ot."""
import secrets

import numpy as np
import pytest

import jax.numpy as jnp

from mpcium_tpu.core import bignum as bn
from mpcium_tpu.core.bignum import P256
from mpcium_tpu.protocol.ecdsa import mta_ot

pytestmark = pytest.mark.slow

Q = mta_ot.Q


def _limbs(vals):
    return jnp.asarray(bn.batch_to_limbs(vals, P256))


def _ints(arr):
    return bn.batch_from_limbs(np.asarray(arr), P256)


def test_base_ot_keys_agree_only_on_choice():
    y, S = mta_ot.base_ot_sender_init()
    delta, keysD, msgs = mta_ot.base_ot_receive(S)
    k0, k1 = mta_ot.base_ot_sender_keys(y, msgs)
    for j in range(mta_ot.KAPPA):
        chosen = k1[j] if delta[j] else k0[j]
        other = k0[j] if delta[j] else k1[j]
        assert (keysD[j] == chosen).all(), f"base OT {j}: key mismatch"
        assert not (keysD[j] == other).all(), f"base OT {j}: both keys leaked"


def test_mta_shares_sum_to_product():
    B = 6
    leg = mta_ot.OTMtALeg("t-pair")
    a_ints = [secrets.randbelow(Q) for _ in range(B)]
    b_ints = [secrets.randbelow(Q) for _ in range(B)]
    # edges: zero multiplicands, max values
    a_ints[0], b_ints[0] = 0, secrets.randbelow(Q)
    a_ints[1], b_ints[1] = Q - 1, Q - 1
    alpha, beta = leg.run(_limbs(a_ints), _limbs(b_ints))
    al, be = _ints(alpha), _ints(beta)
    for i in range(B):
        assert (al[i] + be[i]) % Q == a_ints[i] * b_ints[i] % Q, i


def test_extension_counter_gives_independent_instances():
    """Two invocations on one leg (same base OTs, advanced counter) are
    both correct and produce different OT material."""
    B = 2
    leg = mta_ot.OTMtALeg("t-ctr")
    a = _limbs([3, 5])
    b = _limbs([7, 11])
    m1 = leg.alice_round1(a, 0)
    m2 = leg.alice_round1(a, 1)
    assert not np.array_equal(m1["U"], m2["U"]), "PRG ranges overlap"
    a1, b1 = leg.run(a, b)
    a2, b2 = leg.run(a, b)
    s1, s2 = _ints(a1), _ints(a2)
    t1, t2 = _ints(b1), _ints(b2)
    for i, (x, y) in enumerate([(3, 7), (5, 11)]):
        assert (s1[i] + t1[i]) % Q == x * y % Q
        assert (s2[i] + t2[i]) % Q == x * y % Q
    # fresh z per invocation: the shares themselves must differ
    assert s1 != s2


def test_engine_sign_with_ot_mta(monkeypatch):
    """Full GG18 batch signing with MPCIUM_MTA=ot: signatures must
    verify under hostmath ECDSA (independent of the engine)."""
    import mpcium_tpu.engine.gg18_batch as gb
    from mpcium_tpu.core import hostmath as hm

    monkeypatch.setenv("MPCIUM_MTA", "ot")
    B = 2
    ids = ["node0", "node1"]
    shares = gb.dealer_keygen_secp_batch(B, ids, threshold=1)
    signer = gb.GG18BatchCoSigners(ids, shares, preparams={})
    assert signer.mta_impl == "ot"
    digests = np.frombuffer(
        secrets.token_bytes(B * 32), np.uint8
    ).reshape(B, 32)
    out = signer.sign(digests)
    assert out["ok"].all()
    for i in range(B):
        pub = hm.secp_decompress(shares[0][i].public_key)
        assert hm.ecdsa_verify(
            pub,
            int.from_bytes(digests[i].tobytes(), "big"),
            int.from_bytes(out["r"][i].tobytes(), "big"),
            int.from_bytes(out["s"][i].tobytes(), "big"),
        ), i


def test_engine_sign_cheater_raises_cohort_abort(monkeypatch):
    """Full GG18 batch signing with a cheating leg (ISSUE 16): one
    tampered OT wire field in one lane must surface as CohortAbort
    naming exactly the deviating (lane, party, check) — and the same
    engine signs cleanly again once the deviation stops (fresh
    extension counter, verdicts reset per invocation)."""
    import mpcium_tpu.engine.gg18_batch as gb
    from mpcium_tpu.core import hostmath as hm
    from mpcium_tpu.engine.abort import CohortAbort

    monkeypatch.setenv("MPCIUM_MTA", "ot")
    B = 2
    ids = ["node0", "node1"]
    shares = gb.dealer_keygen_secp_batch(B, ids, threshold=1)
    signer = gb.GG18BatchCoSigners(ids, shares, preparams={})
    # leg (0, 1): Alice = node0 (receiver, choice bits k_0), Bob =
    # node1 (sender). Corrupt Bob's Gilboa opening for lane 1 → the
    # gilboa check must blame node1 on lane 1, and lane 0 stays clean.
    signer.ot_legs[(0, 1)].set_tamper(
        {"field": "D", "lane": 1, "set": 0, "byte": 3}
    )
    digests = np.frombuffer(
        secrets.token_bytes(B * 32), np.uint8
    ).reshape(B, 32)
    with pytest.raises(CohortAbort) as exc:
        signer.sign(digests)
    assert exc.value.culprits == [(1, "node1", "gilboa")]
    assert exc.value.lanes() == [1]

    # cheater stops: the SAME engine instance completes honestly
    signer.ot_legs[(0, 1)].set_tamper(None)
    out = signer.sign(digests)
    assert out["ok"].all()
    for i in range(B):
        pub = hm.secp_decompress(shares[0][i].public_key)
        assert hm.ecdsa_verify(
            pub,
            int.from_bytes(digests[i].tobytes(), "big"),
            int.from_bytes(out["r"][i].tobytes(), "big"),
            int.from_bytes(out["s"][i].tobytes(), "big"),
        ), i


def test_run_multi_shared_extension():
    """run_multi: one extension, two payload sets against the same
    Alice scalar (the GG18 k·gamma / k·w pairing). Both products
    correct, and the per-set pad domains (`|s0`, `|s1`) actually
    separate — identical rows hash to different pads per set."""
    B = 4
    leg = mta_ot.OTMtALeg("t-multi")
    # domain separation at the derivation layer: same matrix, set
    # prefixes s0/s1 → unrelated pads (a regression dropping the |s%d
    # suffix would reuse one-time pads across payload sets)
    packed = np.frombuffer(
        secrets.token_bytes(128 * (B * 256 // 8)), np.uint8
    ).reshape(128, -1)
    p0, p1 = mta_ot._derive_pads_multi(
        [b"t|s0", b"t|s1"], packed, B * 256
    )
    assert not np.array_equal(p0, p1)
    a_ints = [secrets.randbelow(Q) for _ in range(B)]
    g_ints = [secrets.randbelow(Q) for _ in range(B)]
    w_ints = [secrets.randbelow(Q) for _ in range(B)]
    a_ints[0] = 0
    g_ints[1] = Q - 1
    (ag, bg), (aw, bw) = leg.run_multi(
        _limbs(a_ints), (_limbs(g_ints), _limbs(w_ints))
    )
    for share_a, share_b, b_ints in ((ag, bg, g_ints), (aw, bw, w_ints)):
        al, be = _ints(share_a), _ints(share_b)
        for i in range(B):
            assert (al[i] + be[i]) % Q == a_ints[i] * b_ints[i] % Q, i
