"""Scheduler-side identifiable abort (ISSUE 16): when a batch dies with
engine.abort.CohortAbort, the scheduler quarantines EXACTLY the blamed
sessions — one retryable ABORT event each, naming the culprit party and
check, under a distinct idempotency key — and re-packs the survivors
onto bucket-snapped pow-2 sub-batches that run to completion. No
cluster, no engine: the same bare-scheduler harness as
test_batch_claims.py, with the batch runner recorded."""
import json
import threading
import types

from mpcium_tpu import wire
from mpcium_tpu.consumers.batch_scheduler import BatchSigningScheduler
from mpcium_tpu.engine.abort import CohortAbort
from mpcium_tpu.transport.loopback import LoopbackFabric


def _msg(i):
    return wire.SignTxMessage(
        key_type="ecdsa", wallet_id=f"qw{i}", network_internal_code="eth",
        tx_id=f"qtx{i}", tx=b"tx-%d" % i,
    )


class _Harness:
    """A scheduler whose engine dispatch records instead of signing."""

    def __init__(self, survivors_expected):
        self.completed = []
        self.done = threading.Event()
        self.events = []
        self._ev_lock = threading.Lock()
        harness = self

        class _Recording(BatchSigningScheduler):
            def _run_batch(self, batch_id, reqs, *mid, **kw):
                harness.completed.append(
                    (batch_id, [m.tx_id for m, _r in reqs])
                )
                if harness.count() >= survivors_expected:
                    harness.done.set()

        self.fabric = LoopbackFabric()
        t = self.fabric.transport()
        self.sub = t.queues.dequeue(
            f"{wire.TOPIC_SIGNING_RESULT}.*", self._on_result
        )
        self.sched = _Recording(
            types.SimpleNamespace(node_id="n0", peer_ids=["n0"]),
            transport=t,
        )

    def _on_result(self, data):
        with self._ev_lock:
            self.events.append(
                wire.SigningResultEvent.from_json(json.loads(data))
            )

    def count(self):
        return sum(len(t) for _b, t in self.completed)

    def close(self):
        self.sched.close()
        self.sub.unsubscribe()
        self.fabric.close()


def test_quarantine_names_culprit_and_repacks_survivors():
    h = _Harness(survivors_expected=3)
    try:
        reqs = [(_msg(i), "") for i in range(4)]
        abort = CohortAbort([(1, "node-b", "gilboa")], engine="gg18.sign")
        h.sched._absorb_cohort_abort("b0", reqs, frozenset(),
                                     abort.culprits)
        assert h.done.wait(10), f"survivors never ran: {h.completed}"
        h.fabric.drain(timeout_s=10)

        # exactly one ABORT event, for the blamed session only —
        # retryable, culprit-named, distinct idempotency key family
        (ev,) = h.events
        assert ev.tx_id == "qtx1" and ev.result_type == wire.RESULT_ERROR
        assert ev.retryable
        assert "node-b" in ev.error_reason and "gilboa" in ev.error_reason
        assert "identifiable abort" in ev.error_reason

        # survivors: every non-blamed tx exactly once, in pow-2 chunks
        survivor_txs = sorted(t for _b, ts in h.completed for t in ts)
        assert survivor_txs == ["qtx0", "qtx2", "qtx3"]
        chunks = [len(ts) for _b, ts in h.completed]
        assert all(n & (n - 1) == 0 for n in chunks), chunks
        assert sorted(b for b, _t in h.completed) == ["b0r0", "b0r1"]

        # soak invariant closes: submitted == completed + quarantined
        assert 4 == h.count() + len(h.events)
        # and no claim leaks once the children exit
        assert h.sched._batch_claims == {}
    finally:
        h.close()


def test_multiple_culprits_one_event_each():
    h = _Harness(survivors_expected=2)
    try:
        reqs = [(_msg(i), "") for i in range(4)]
        abort = CohortAbort(
            [(0, "node-a", "kos"), (3, "node-b", "consistency")],
            engine="gg18.sign",
        )
        h.sched._absorb_cohort_abort("b1", reqs, frozenset(),
                                     abort.culprits)
        assert h.done.wait(10)
        h.fabric.drain(timeout_s=10)
        by_tx = {e.tx_id: e for e in h.events}
        assert set(by_tx) == {"qtx0", "qtx3"}
        assert "kos" in by_tx["qtx0"].error_reason
        assert "node-a" in by_tx["qtx0"].error_reason
        assert "consistency" in by_tx["qtx3"].error_reason
        assert all(e.retryable for e in by_tx.values())
        assert sorted(t for _b, ts in h.completed for t in ts) == \
            ["qtx1", "qtx2"]
    finally:
        h.close()


def test_all_lanes_blamed_no_survivor_batch():
    h = _Harness(survivors_expected=1)  # never reached
    try:
        reqs = [(_msg(i), "") for i in range(2)]
        abort = CohortAbort(
            [(0, "p0", "kos"), (1, "p1", "gilboa")], engine="gg18.sign"
        )
        h.sched._absorb_cohort_abort("b2", reqs, frozenset(),
                                     abort.culprits)
        h.fabric.drain(timeout_s=10)
        assert len(h.events) == 2 and h.completed == []
    finally:
        h.close()


def test_cohort_abort_duck_typing_contract():
    """The on_error seam in _run_batch routes on ``getattr(e,
    "culprits", None)`` — duck-typed so a distributed party can forward
    a peer's verdicts without importing the engine. Pin both sides of
    the contract: CohortAbort coerces and exposes culprits, a plain
    failure exposes none, and the exception text names every blame."""
    abort = CohortAbort(
        [("2", "node-x", "kos"), (0, 7, "gilboa")], engine="gg18.sign"
    )
    assert getattr(abort, "culprits", None) == [
        (2, "node-x", "kos"), (0, "7", "gilboa"),
    ]
    assert abort.lanes() == [0, 2]
    assert "party node-x failed check 'kos'" in str(abort)
    assert "gg18.sign" in str(abort)
    assert getattr(RuntimeError("engine died"), "culprits", None) is None


def test_quarantine_on_closed_scheduler_releases_not_spawns():
    """A cohort abort racing shutdown must not spawn survivor threads
    on a closed scheduler; the quarantine events still go out."""
    h = _Harness(survivors_expected=1)
    try:
        reqs = [(_msg(i), "") for i in range(4)]
        with h.sched._lock:
            h.sched._closed = True
        h.sched._absorb_cohort_abort(
            "b3", reqs, frozenset(),
            CohortAbort([(0, "p", "kos")]).culprits,
        )
        h.fabric.drain(timeout_s=10)
        assert [e.tx_id for e in h.events] == ["qtx0"]
        assert h.completed == []  # no survivor re-pack after close
    finally:
        h.close()
