"""FaultyTransport behavior (ISSUE 3): the empty-plan decorator is
transcript-identical to the bare transport (zero-overhead seam), each
fault kind does what it says on the loopback fabric, and the schedule a
run produces is deterministic for deterministic traffic (the
bit-exactness style of test_mta_ot_pipeline.py, applied to transcripts)."""
import threading
import time

import pytest

from mpcium_tpu.faults.plan import (
    FaultPlan, crash_node, delay, drop, duplicate, partition, reorder,
    tamper,
)
from mpcium_tpu.faults.transport import CrashSwitch, FaultStats, FaultyTransport
from mpcium_tpu.transport.api import Permanent, TransportError
from mpcium_tpu.transport.loopback import LoopbackFabric


@pytest.fixture()
def fabric():
    f = LoopbackFabric()
    yield f
    f.close()


def _drain(fabric, extra_sleep=0.0):
    fabric.drain(timeout_s=30)
    if extra_sleep:
        time.sleep(extra_sleep)
        fabric.drain(timeout_s=30)


# -- zero-overhead transparency ---------------------------------------------


def _transcript(fabric, transport, tag):
    """Deterministic traffic across all three channels; returns the
    delivered transcript."""
    got = {"pubsub": [], "direct": [], "queue": []}
    bare = fabric.transport()
    bare.pubsub.subscribe(f"{tag}:ps:*", lambda d: got["pubsub"].append(d))
    bare.direct.listen(f"{tag}:dm:1", lambda d: got["direct"].append(d))
    bare.queues.dequeue(f"{tag}:q:*", lambda d: got["queue"].append(d))
    for i in range(20):
        transport.pubsub.publish(f"{tag}:ps:{i % 3}", b"ps-%d" % i)
        transport.direct.send(f"{tag}:dm:1", b"dm-%d" % i)
        transport.queues.enqueue(f"{tag}:q:{i % 2}", b"q-%d" % i,
                                 idempotency_key=f"{tag}-{i}")
        # idempotent replay: must be deduped identically on both paths
        transport.queues.enqueue(f"{tag}:q:{i % 2}", b"q-%d" % i,
                                 idempotency_key=f"{tag}-{i}")
    _drain(fabric)
    return {k: sorted(v) for k, v in got.items()}


def test_empty_plan_is_transcript_identical(fabric):
    bare = _transcript(fabric, fabric.transport(), "bare")
    ft = FaultyTransport(fabric.transport(), "nodeA", FaultPlan(7, []))
    wrapped = _transcript(fabric, ft, "wrap")
    # identical multiset of delivered bytes on every channel
    assert bare == wrapped
    # and the decorator recorded nothing — no PRF draws, no schedule
    assert ft.stats.to_json() == {
        "counters": {}, "retries_observed": 0, "events": 0,
    }
    assert ft.stats.canonical_schedule() == []


def test_subscription_passthrough_unsubscribes(fabric):
    ft = FaultyTransport(fabric.transport(), "n", FaultPlan(1, []))
    got = []
    sub = ft.pubsub.subscribe("s:*", lambda d: got.append(d))
    ft.pubsub.publish("s:1", b"a")
    _drain(fabric)
    sub.unsubscribe()
    ft.pubsub.publish("s:1", b"b")
    _drain(fabric)
    assert got == [b"a"]


# -- fault kinds -------------------------------------------------------------


def test_drop_on_direct_consumes_retries_then_raises(fabric):
    ft = FaultyTransport(
        fabric.transport(), "n",
        FaultPlan(3, [drop(p=1.0, topic="d:*", channel="direct")]),
    )
    fabric.transport().direct.listen("d:1", lambda d: None)
    with pytest.raises(TransportError, match="lost"):
        ft.direct.send("d:1", b"x")
    assert ft.stats.retries_observed == 3
    assert ft.stats.counters["drop#0"]["drop"] == 3


def test_drop_on_pubsub_is_true_loss(fabric):
    ft = FaultyTransport(
        fabric.transport(), "n",
        FaultPlan(3, [drop(p=1.0, topic="p:*", channel="pubsub")]),
    )
    got = []
    fabric.transport().pubsub.subscribe("p:*", lambda d: got.append(d))
    ft.pubsub.publish("p:1", b"lost")
    _drain(fabric)
    assert got == []
    assert ft.stats.counters["drop#0"]["drop"] == 1


def test_duplicate_queue_without_key_delivers_twice(fabric):
    ft = FaultyTransport(
        fabric.transport(), "n",
        FaultPlan(3, [duplicate(p=1.0, topic="q:*", channel="queue")]),
    )
    got = []
    fabric.transport().queues.dequeue("q:*", lambda d: got.append(d))
    ft.queues.enqueue("q:1", b"payload")  # no idempotency key
    _drain(fabric)
    assert got == [b"payload", b"payload"]
    # WITH a key, the dedup window must absorb the duplicate
    got.clear()
    ft.queues.enqueue("q:1", b"keyed", idempotency_key="k1")
    _drain(fabric)
    assert got == [b"keyed"]


def test_delay_defers_pubsub_delivery(fabric):
    ft = FaultyTransport(
        fabric.transport(), "n",
        FaultPlan(3, [delay(ms=(80.0, 120.0), topic="p:*",
                            channel="pubsub")]),
    )
    got = []
    fabric.transport().pubsub.subscribe("p:*", lambda d: got.append(d))
    t0 = time.monotonic()
    ft.pubsub.publish("p:1", b"late")
    assert time.monotonic() - t0 < 0.05  # publish itself never blocks
    assert got == []
    time.sleep(0.2)
    _drain(fabric)
    assert got == [b"late"]
    (entry,) = ft.stats.schedule
    assert 80.0 <= entry["ms"] <= 120.0


def test_reorder_swaps_adjacent_messages(fabric):
    ft = FaultyTransport(
        fabric.transport(), "n",
        FaultPlan(3, [reorder(p=1.0, topic="r:*", channel="pubsub")]),
    )
    got = []
    fabric.transport().pubsub.subscribe("r:*", lambda d: got.append(d))
    ft.pubsub.publish("r:1", b"first")
    ft.pubsub.publish("r:1", b"second")
    _drain(fabric, extra_sleep=0.15)
    assert got == [b"second", b"first"]


def test_reorder_flushes_lone_message(fabric):
    ft = FaultyTransport(
        fabric.transport(), "n",
        FaultPlan(3, [reorder(p=1.0, topic="r:*", channel="pubsub",
                              window_ms=50.0)]),
    )
    got = []
    fabric.transport().pubsub.subscribe("r:*", lambda d: got.append(d))
    ft.pubsub.publish("r:1", b"only")
    time.sleep(0.15)
    _drain(fabric)
    assert got == [b"only"]  # no successor: flushed after the window


def test_crash_switch_silences_both_directions(fabric):
    ft = FaultyTransport(fabric.transport(), "n", FaultPlan(3, []))
    got_in, got_out = [], []
    ft.pubsub.subscribe("in:*", lambda d: got_in.append(d))
    fabric.transport().pubsub.subscribe("out:*", lambda d: got_out.append(d))
    ft.crash_switch.crash()
    ft.pubsub.publish("out:1", b"x")  # outbound suppressed
    fabric.transport().pubsub.publish("in:1", b"y")  # inbound suppressed
    _drain(fabric)
    assert got_out == [] and got_in == []
    assert ft.stats.counters["__crashed__"]["drop"] == 2
    with pytest.raises(TransportError):
        ft.direct.send("out:1", b"x")
    ft.crash_switch.restore()
    ft.pubsub.publish("out:1", b"alive")
    fabric.transport().pubsub.publish("in:1", b"alive")
    _drain(fabric)
    assert got_out == [b"alive"] and got_in == [b"alive"]


def test_crash_rule_fires_on_matching_round(fabric):
    plan = FaultPlan(3, [crash_node("n2", at_round="r2", topic="sign:*")])
    ft = FaultyTransport(fabric.transport(), "n2", plan)
    hooks = []
    ft.crash_switch.on_crash(lambda: hooks.append("fired"))
    env_r1 = b'{"round": "r1", "payload": {}}'
    env_r2 = b'{"round": "r2", "payload": {}}'
    ft.pubsub.publish("sign:x", env_r1)
    assert not ft.crash_switch.crashed  # wrong round
    ft.pubsub.publish("keygen:x", env_r2)
    assert not ft.crash_switch.crashed  # wrong topic
    ft.pubsub.publish("sign:x", env_r2)
    assert ft.crash_switch.crashed and hooks == ["fired"]
    # one-shot: restoring and re-sending must not re-crash
    ft.crash_switch.restore()
    ft.pubsub.publish("sign:x", env_r2)
    assert not ft.crash_switch.crashed


def test_partition_isolates_listed_nodes(fabric):
    plan = FaultPlan(3, [partition(("n1",))])
    ft1 = FaultyTransport(fabric.transport(), "n1", plan)
    ft2 = FaultyTransport(fabric.transport(), "n2", plan)
    got = []
    fabric.transport().pubsub.subscribe("t:*", lambda d: got.append(d))
    plan.activate()
    ft1.pubsub.publish("t:1", b"from-isolated")
    ft2.pubsub.publish("t:1", b"from-connected")
    _drain(fabric)
    assert got == [b"from-connected"]
    plan.heal()
    ft1.pubsub.publish("t:1", b"healed")
    _drain(fabric)
    assert got == [b"from-connected", b"healed"]


def test_tamper_flip_corrupts_pubsub_payload(fabric):
    ft = FaultyTransport(
        fabric.transport(), "n",
        FaultPlan(3, [tamper(p=1.0, topic="p:*", channel="pubsub",
                             mode="flip")]),
    )
    got = []
    fabric.transport().pubsub.subscribe("p:*", lambda d: got.append(d))
    sent = b"honest-wire-bytes" * 4
    ft.pubsub.publish("p:1", sent)
    _drain(fabric)
    (delivered,) = got
    assert delivered != sent and len(delivered) == len(sent)
    assert sum(x != y for x, y in zip(sent, delivered)) == 1
    (entry,) = ft.stats.schedule
    assert entry["action"] == "tamper" and entry["mode"] == "flip"
    assert ft.stats.counters["tamper#0"]["tamper"] == 1


def test_tamper_truncate_on_queue_ships_proper_prefix(fabric):
    ft = FaultyTransport(
        fabric.transport(), "n",
        FaultPlan(9, [tamper(p=1.0, topic="q:*", channel="queue",
                             mode="truncate")]),
    )
    got = []
    fabric.transport().queues.dequeue("q:*", lambda d: got.append(d))
    sent = bytes(range(120))
    ft.queues.enqueue("q:1", sent, idempotency_key="t1")
    _drain(fabric)
    (delivered,) = got
    assert len(delivered) < len(sent) and sent.startswith(delivered)


def test_tamper_replay_on_direct_substitutes_stale_payload(fabric):
    ft = FaultyTransport(
        fabric.transport(), "n",
        FaultPlan(5, [tamper(p=1.0, topic="d:*", channel="direct",
                             mode="replay")]),
    )
    got = []
    fabric.transport().direct.listen("d:1", lambda d: got.append(d))
    ft.direct.send("d:1", b"round-1")  # nothing captured yet: flows clean
    ft.direct.send("d:1", b"round-2")  # replaced by the stale round-1
    _drain(fabric)
    assert got == [b"round-1", b"round-1"]


def test_tamper_inbound_corrupts_before_handler(fabric):
    ft = FaultyTransport(
        fabric.transport(), "n",
        FaultPlan(3, [tamper(p=1.0, topic="p:*", channel="pubsub",
                             direction="in", mode="flip")]),
    )
    got = []
    ft.pubsub.subscribe("p:*", lambda d: got.append(d))
    sent = b"inbound-payload-bytes"
    fabric.transport().pubsub.publish("p:1", sent)
    _drain(fabric)
    (delivered,) = got
    assert delivered != sent and len(delivered) == len(sent)


def test_tamper_schedule_deterministic_across_runs():
    def run(seed):
        fabric = LoopbackFabric()
        try:
            ft = FaultyTransport(
                fabric.transport(), "n",
                FaultPlan(seed, [tamper(p=0.5, topic="t:*",
                                        channel="pubsub", mode="flip")]),
            )
            got = []
            fabric.transport().pubsub.subscribe("t:*", lambda d: got.append(d))
            for i in range(40):
                ft.pubsub.publish(f"t:{i % 4}", b"m-%d" % i)
            fabric.drain(timeout_s=30)
            return sorted(got), ft.stats.canonical_schedule()
        finally:
            fabric.close()

    got_a, sched_a = run(21)
    got_b, sched_b = run(21)
    assert got_a == got_b and sched_a == sched_b
    assert sched_a  # p=0.5 over 40 messages: some fired
    got_c, sched_c = run(22)
    assert sched_c != sched_a


# -- deterministic transcripts ----------------------------------------------


def _run_faulty_transcript(seed):
    fabric = LoopbackFabric()
    try:
        plan = FaultPlan(seed, [
            drop(p=0.4, topic="t:*", channel="pubsub"),
            drop(p=0.4, topic="t:*", channel="direct"),
        ])
        ft = FaultyTransport(fabric.transport(), "n", plan)
        got = []
        bare = fabric.transport()
        bare.pubsub.subscribe("t:*", lambda d: got.append(d))
        bare.direct.listen("t:dm", lambda d: got.append(d))
        for i in range(40):
            ft.pubsub.publish(f"t:{i % 4}", b"m-%d" % i)
        for i in range(10):
            try:
                ft.direct.send("t:dm", b"d-%d" % i)
            except TransportError:
                pass  # triple loss — deterministic per seed
        fabric.drain(timeout_s=30)
        return sorted(got), ft.stats.canonical_schedule()
    finally:
        fabric.close()


def test_faulty_transcript_deterministic_across_runs():
    """Same (seed, plan, traffic) ⇒ identical delivered transcript AND
    identical fault schedule; a different seed diverges."""
    got_a, sched_a = _run_faulty_transcript(17)
    got_b, sched_b = _run_faulty_transcript(17)
    assert got_a == got_b
    assert sched_a == sched_b
    got_c, sched_c = _run_faulty_transcript(18)
    assert sched_c != sched_a


def test_stats_merge():
    a, b = FaultStats(), FaultStats()
    from mpcium_tpu.faults.plan import MsgEvent

    ev = MsgEvent("out", "pubsub", "t", b"x", "n")
    a.record("r1", "drop", ev)
    b.record("r1", "drop", ev)
    b.record("r2", "delay", ev, ms=12.0)
    b.retry()
    merged = FaultStats().merge(a).merge(b)
    assert merged.counters["r1"]["drop"] == 2
    assert merged.counters["r2"]["delay"] == 1
    assert merged.retries_observed == 1
    assert len(merged.canonical_schedule()) == 3
