"""Fault-plan determinism (ISSUE 3 satellite): the same (seed, plan)
produces the identical fault schedule across runs, different seeds
diverge, retransmissions re-roll, and plans survive JSON round-trips."""
import pytest

from mpcium_tpu.faults.plan import (
    TAMPER_MODES,
    FaultPlan,
    MsgEvent,
    Rule,
    crash_node,
    delay,
    drop,
    duplicate,
    glob_match,
    named_plan,
    partition,
    reorder,
    tamper,
)


def _mk_plan(seed):
    return FaultPlan(seed, [
        drop(p=0.5, topic="t:*", channel="direct"),
        delay(ms=(10.0, 20.0), topic="t:*"),
    ])


def _traffic():
    return [
        MsgEvent("out", "direct", f"t:{i % 5}", b"payload-%d" % (i % 7), "nodeX")
        for i in range(60)
    ]


def _schedule(plan):
    out = []
    for ev in _traffic():
        for r in plan.matching(ev, ("drop", "delay")):
            u, key, occ = plan.roll(r, ev)
            entry = (r.rule_id, key.hex(), occ, u < r.p)
            if r.kind == "delay":
                entry += (round(plan.delay_ms(r, key, occ), 6),)
            out.append(entry)
    return out


def test_same_seed_identical_schedule():
    assert _schedule(_mk_plan(1234)) == _schedule(_mk_plan(1234))


def test_different_seed_different_schedule():
    a, b = _schedule(_mk_plan(1)), _schedule(_mk_plan(2))
    assert [e[:3] for e in a] == [e[:3] for e in b]  # same judgements...
    assert a != b  # ...different outcomes


def test_retransmission_rerolls():
    """A retried identical message bumps occurrence and draws fresh —
    a 100%-unlucky first roll cannot black-hole the message forever."""
    plan = FaultPlan(99, [drop(p=0.5, topic="x")])
    rule = plan.rules[0]
    ev = MsgEvent("out", "direct", "x", b"same-bytes", "n")
    draws = [plan.roll(rule, ev) for _ in range(32)]
    occs = [occ for _u, _k, occ in draws]
    assert occs == list(range(32))  # per-message occurrence counter
    us = {u for u, _k, _o in draws}
    assert len(us) > 16  # independent draws, not one sticky verdict


def test_delay_bounds():
    plan = FaultPlan(5, [delay(ms=(50.0, 200.0), topic="*")])
    rule = plan.rules[0]
    for i in range(200):
        ev = MsgEvent("out", "pubsub", f"a:{i}", b"%d" % i, "n")
        u, key, occ = plan.roll(rule, ev)
        assert 50.0 <= plan.delay_ms(rule, key, occ) <= 200.0


def test_matching_predicates():
    r = drop(p=1.0, topic="sign:*", node="node1", channel="direct",
             direction="out")
    assert r.matches(MsgEvent("out", "direct", "sign:eddsa:x", b"", "node1"))
    assert not r.matches(MsgEvent("out", "direct", "keygen:x", b"", "node1"))
    assert not r.matches(MsgEvent("out", "direct", "sign:x", b"", "node2"))
    assert not r.matches(MsgEvent("out", "pubsub", "sign:x", b"", "node1"))
    assert not r.matches(MsgEvent("in", "direct", "sign:x", b"", "node1"))
    assert glob_match("*", "anything") and glob_match("a:*", "a:b:c")
    assert not glob_match("a:*", "b:a")


def test_json_roundtrip_preserves_schedule():
    plan = named_plan("drop-jitter", seed=42)
    clone = FaultPlan.from_json(plan.to_json())
    assert clone.to_json() == plan.to_json()
    assert _schedule_all(clone) == _schedule_all(plan)


def _schedule_all(plan):
    out = []
    for ev in _traffic():
        for r in plan.matching(ev, ("drop", "delay", "duplicate", "reorder")):
            out.append((r.rule_id,) + plan.roll(r, ev))
    return out


def test_partition_window():
    plan = FaultPlan(1, [partition(("n1", "n2"), duration_s=2.0, start_s=1.0)])
    assert plan.isolated("n1", now=100.0) is None  # dormant until activate
    plan.activate(now=0.0)
    assert plan.isolated("n1", now=0.5) is None
    assert plan.isolated("n1", now=1.5) is not None
    assert plan.isolated("n3", now=1.5) is None  # not in the partition
    assert plan.isolated("n2", now=3.5) is None  # window over
    open_ended = FaultPlan(1, [partition(("n1",))]).activate(now=0.0)
    assert open_ended.isolated("n1", now=9999.0) is not None
    open_ended.heal()
    assert open_ended.isolated("n1", now=9999.0) is None


def test_crash_rule_is_one_shot():
    plan = FaultPlan(1, [crash_node("n2", topic="sign:*")])
    (rule,) = plan.crash_rules("n2")
    assert plan.crash_rules("n1") == []
    plan.mark_fired(rule)
    assert plan.crash_rules("n2") == []  # a restarted node stays up


def test_named_plans_cover_the_catalog():
    for name in ("drop-jitter", "node-crash", "broker-failover",
                 "partition", "duplicate-reorder", "cheater"):
        p = named_plan(name, seed=3)
        assert isinstance(p, FaultPlan) and p.seed == 3
    with pytest.raises(KeyError):
        named_plan("nope", seed=3)


def test_tamper_flip_is_deterministic_single_byte():
    """flip: same (seed, rule, key, occ, data) ⇒ the identical
    corrupted payload — one byte XORed with a nonzero mask, same
    length, never a no-op."""
    data = bytes(range(64)) * 4
    corrupt = []
    for _ in range(2):
        plan = FaultPlan(17, [tamper(mode="flip", topic="t:*")])
        corrupt.append(plan.tamper_bytes(plan.rules[0], b"k", 0, data))
    assert corrupt[0] == corrupt[1]
    assert corrupt[0] != data and len(corrupt[0]) == len(data)
    diffs = [i for i, (x, y) in enumerate(zip(data, corrupt[0])) if x != y]
    assert len(diffs) == 1
    # different occurrences / keys pick independent positions+masks
    plan = FaultPlan(17, [tamper(mode="flip")])
    outs = {
        plan.tamper_bytes(plan.rules[0], b"k%d" % i, i, data)
        for i in range(16)
    }
    assert len(outs) > 8


def test_tamper_truncate_shortens_to_proper_prefix():
    plan = FaultPlan(23, [tamper(mode="truncate")])
    rule = plan.rules[0]
    data = bytes(range(200))
    out = plan.tamper_bytes(rule, b"k", 0, data)
    assert out == plan.tamper_bytes(rule, b"k", 0, data)  # deterministic
    assert len(out) < len(data) and data.startswith(out)
    # even a maximal draw keeps at least one byte off the wire
    for i in range(64):
        o = plan.tamper_bytes(rule, b"k%d" % i, i, data)
        assert len(o) <= len(data) - 1


def test_tamper_replay_substitutes_previous_matching_payload():
    """replay: every match captures; a triggered match ships the
    PREVIOUSLY captured payload instead of the current one (stale
    retransmission), so the first match always passes through."""
    plan = FaultPlan(5, [tamper(mode="replay")])
    rule = plan.rules[0]
    assert plan.tamper_bytes(rule, b"k", 0, b"first") == b"first"
    assert plan.tamper_bytes(rule, b"k", 1, b"second") == b"first"
    assert plan.tamper_bytes(rule, b"k", 2, b"third") == b"second"
    # untriggered matches still refresh the capture cell
    assert plan.tamper_bytes(rule, b"k", 3, b"fourth",
                             triggered=False) == b"fourth"
    assert plan.tamper_bytes(rule, b"k", 4, b"fifth") == b"fourth"


def test_tamper_mode_validated_and_serialized():
    with pytest.raises(ValueError, match="tamper mode"):
        tamper(mode="scribble")
    for mode in TAMPER_MODES:
        r = tamper(mode=mode, topic="bsign:*", p=0.25)
        clone = Rule.from_json(r.to_json())
        assert clone == r and clone.mode == mode
    # pre-tamper plans (no "mode" key at all) still deserialize
    d = drop(p=0.5, topic="x").to_json()
    del d["mode"]
    assert Rule.from_json(d).mode == ""


def test_tamper_schedule_roundtrips_and_is_seed_deterministic():
    plan = named_plan("cheater", seed=31)
    clone = FaultPlan.from_json(plan.to_json())
    assert clone.to_json() == plan.to_json()
    data = b"payload-bytes" * 9
    traffic = [
        MsgEvent("out", "pubsub", f"bsign:{i % 3}", b"m-%d" % (i % 5), "n")
        for i in range(30)
    ]
    matched = 0
    for ev in traffic:
        for r in plan.matching(ev, ("tamper",)):
            matched += 1
            u, key, occ = plan.roll(r, ev)
            (rc,) = clone.matching(ev, ("tamper",))
            uc, keyc, occc = clone.roll(rc, ev)
            assert (u, key, occ) == (uc, keyc, occc)
            assert plan.tamper_bytes(r, key, occ, data) == \
                clone.tamper_bytes(rc, keyc, occc, data)
    assert matched == 30  # the cheater rule matches its bsign traffic


def test_scale_changes_times_not_structure():
    a = named_plan("drop-jitter", seed=3, scale=1.0)
    b = named_plan("drop-jitter", seed=3, scale=0.1)
    assert [r.kind for r in a.rules] == [r.kind for r in b.rules]
    assert [r.p for r in a.rules] == [r.p for r in b.rules]
    da = next(r for r in a.rules if r.kind == "delay")
    db = next(r for r in b.rules if r.kind == "delay")
    assert db.ms[1] == pytest.approx(da.ms[1] * 0.1)
