"""Deep-profiling fold (perf/profile.py): device-op attribution from a
synthetic jax-profiler capture, the env gate, and fail-to-noop paths."""
import gzip
import json
import os

import pytest

from mpcium_tpu.perf import profile

pytestmark = pytest.mark.perf


def _phase_span(name, t0_ns, t1_ns):
    return {"name": f"phase:{name}", "t0_ns": t0_ns, "t1_ns": t1_ns,
            "trace_id": "t", "span_id": "s", "parent_id": None,
            "node": "engine", "tid": "main", "kind": "X", "attrs": {}}


def _write_capture(logdir, events):
    d = os.path.join(logdir, "plugins", "profile", "run1")
    os.makedirs(d)
    path = os.path.join(d, "host.trace.json.gz")
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)


def test_profiling_disabled_by_default(monkeypatch):
    monkeypatch.delenv(profile.PROFILE_ENV, raising=False)
    assert not profile.profiling_enabled()
    with profile.device_profile("/nonexistent") as on:
        assert on is False
    monkeypatch.setenv(profile.PROFILE_ENV, "1")
    assert profile.profiling_enabled()


def test_fold_attributes_device_ops_to_phase_windows(tmp_path):
    # two phases: [0, 1ms) and [1ms, 3ms) on the span clock
    spans = [_phase_span("r1", 1_000_000, 2_000_000),
             _phase_span("r2", 2_000_000, 4_000_000)]
    # profiler clock starts at ts=500us; alignment maps 500us -> span
    # min t0 (1ms). Op A midpoint lands in r1, op B in r2.
    events = [
        {"ph": "M", "name": "process_name", "pid": 7, "tid": 0,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "process_name", "pid": 9, "tid": 0,
         "args": {"name": "python host threads"}},
        # op A: [500us, 900us) on profiler clock -> [1.0ms, 1.4ms) spans
        {"ph": "X", "name": "fusion.1", "pid": 7, "tid": 1,
         "ts": 500.0, "dur": 400.0},
        # op B: [1600us, 2600us) -> [2.1ms, 3.1ms), midpoint in r2
        {"ph": "X", "name": "fusion.2", "pid": 7, "tid": 1,
         "ts": 1600.0, "dur": 1000.0},
        # host-pid op must be ignored even though it overlaps r1
        {"ph": "X", "name": "host_op", "pid": 9, "tid": 1,
         "ts": 500.0, "dur": 400.0},
    ]
    _write_capture(str(tmp_path), events)
    out = profile.fold_device_ops(spans, str(tmp_path))
    assert out == {"r1_device_op_s": pytest.approx(400 / 1e6),
                   "r2_device_op_s": pytest.approx(1000 / 1e6)}


def test_fold_returns_empty_on_missing_pieces(tmp_path):
    spans = [_phase_span("r1", 0, 1_000_000)]
    # no capture files at all
    assert profile.fold_device_ops(spans, str(tmp_path)) == {}
    # capture but no phase spans
    _write_capture(str(tmp_path), [
        {"ph": "M", "name": "process_name", "pid": 7, "tid": 0,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "name": "f", "pid": 7, "tid": 1, "ts": 0.0,
         "dur": 10.0},
    ])
    assert profile.fold_device_ops([], str(tmp_path)) == {}


def test_fold_survives_torn_capture_file(tmp_path):
    spans = [_phase_span("r1", 0, 1_000_000)]
    d = os.path.join(str(tmp_path), "run")
    os.makedirs(d)
    with open(os.path.join(d, "bad.trace.json.gz"), "wb") as f:
        f.write(b"not gzip at all")
    assert profile.fold_device_ops(spans, str(tmp_path)) == {}


def test_default_logdir_is_repo_scoped():
    assert profile.default_logdir("/some/root") == \
        "/some/root/.mpcium_profile"
