"""Batched secp256k1 JAX kernels vs hostmath ground truth."""
import secrets

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.core import secp256k1_jax as sj


def rand_scalars(n):
    return [secrets.randbelow(hm.SECP_N - 1) + 1 for _ in range(n)]


def host_points(ks):
    return [hm.secp_mul(k, hm.SECP_G) for k in ks]


def test_add_matches_host():
    k1, k2 = rand_scalars(4), rand_scalars(4)
    out = sj.to_host(
        jax.jit(sj.add)(sj.from_host(host_points(k1)), sj.from_host(host_points(k2)))
    )
    for a, b, got in zip(k1, k2, out):
        assert got == hm.secp_mul((a + b) % hm.SECP_N, hm.SECP_G)


@pytest.mark.slow
def test_complete_edge_cases():
    """The completeness claims: P+(-P)=O, P+O=P, O+O=O, P+P=2P."""
    k = rand_scalars(1)[0]
    P = hm.secp_mul(k, hm.SECP_G)
    negP = hm.SecpPoint(P.x, hm.SECP_P - P.y)
    pj = sj.from_host([P, P, P])
    qj = sj.from_host([negP, P, P])
    # batch: P+(-P), P+P (doubling through add), P+P again
    out = sj.to_host(sj.add(pj, qj))
    assert out[0].is_infinity
    assert out[1] == hm.secp_mul(2 * k % hm.SECP_N, hm.SECP_G)
    # identity handling
    ident = sj.identity((3,))
    out2 = sj.to_host(sj.add(pj, ident))
    for got in out2:
        assert got == P
    out3 = sj.to_host(sj.add(ident, ident))
    for got in out3:
        assert got.is_infinity


def test_base_mul_matches_host():
    ks = rand_scalars(4) + [1, hm.SECP_N - 1]
    bits = jnp.asarray(sj.scalars_to_bits(ks))
    out = sj.to_host(jax.jit(sj.base_mul)(bits))
    for k, got in zip(ks, out):
        assert got == hm.secp_mul(k, hm.SECP_G), k


def test_scalar_mul_variable_base():
    base_k = rand_scalars(1)[0]
    base = sj.from_host(host_points([base_k] * 3))
    ks = rand_scalars(3)
    bits = jnp.asarray(sj.scalars_to_bits(ks))
    out = sj.to_host(jax.jit(sj.scalar_mul)(bits, base))
    for k, got in zip(ks, out):
        assert got == hm.secp_mul(k * base_k % hm.SECP_N, hm.SECP_G)


def test_compress_and_x():
    ks = rand_scalars(3)
    bits = jnp.asarray(sj.scalars_to_bits(ks))
    pts = jax.jit(sj.base_mul)(bits)
    comp = np.asarray(jax.jit(sj.compress)(pts))
    xs = np.asarray(jax.jit(sj.x_coordinate)(pts))
    from mpcium_tpu.core import bignum as bn

    for k, row, xl in zip(ks, comp, xs):
        host = hm.secp_mul(k, hm.SECP_G)
        assert bytes(row.tolist()) == hm.secp_compress(host)
        assert bn.from_limbs(xl, bn.P256) == host.x


def test_equal_batch():
    ks = rand_scalars(2)
    p = sj.from_host(host_points(ks + [ks[0]]))
    q = sj.from_host(host_points([ks[0], ks[1], ks[1]]))
    # make third pair identity-vs-point
    eq = np.asarray(sj.equal(p, q))
    assert list(eq) == [True, True, False]
    ident = sj.identity((3,))
    eq2 = np.asarray(sj.equal(ident, ident))
    assert all(eq2)
    eq3 = np.asarray(sj.equal(p, ident))
    assert not any(eq3)


def test_decompress_roundtrip_and_rejection():
    ks = rand_scalars(4)
    bits = jnp.asarray(sj.scalars_to_bits(ks))
    pts = jax.jit(sj.base_mul)(bits)
    comp = jax.jit(sj.compress)(pts)
    got, ok = jax.jit(sj.decompress)(comp)
    assert np.asarray(ok).all()
    assert np.asarray(jax.jit(sj.equal)(got, pts)).all()
    # corrupt one row: bad tag; another: x with no square root
    bad = np.asarray(comp).copy()
    bad[0, 0] = 0x05
    bad[1, 1:] = 0xFF  # x >= p
    _, ok = jax.jit(sj.decompress)(jnp.asarray(bad))
    assert list(np.asarray(ok)) == [False, False, True, True]
