"""Hello-barrier deadline: a never-arriving quorum peer fails the session
retryably within the signing window instead of hanging until the 30-minute
GC (reference window: sign_consumer.go:16-20)."""
import threading
import time

import pytest

from mpcium_tpu.identity.identity import IdentityStore, generate_identity
from mpcium_tpu.node.session import RetryableSessionError, Session
from mpcium_tpu.protocol.eddsa.keygen import EDDSAKeygenParty
from mpcium_tpu.transport.loopback import LoopbackFabric


def test_hello_deadline_fires_retryable(tmp_path):
    ids = ["node0", "node1"]
    for n in ids:
        generate_identity(n, tmp_path)
    peers = {n: n for n in ids}
    store = IdentityStore(tmp_path, "node0", peers)
    fabric = LoopbackFabric()
    party = EDDSAKeygenParty("s-hello", "node0", ids, threshold=1)
    errs = []
    done = threading.Event()
    s = Session(
        session_id="s-hello",
        party=party,
        node_id="node0",
        participants=ids,
        transport=fabric.transport(),
        identity=store,
        broadcast_topic="t.bcast",
        direct_topic_fn=lambda n: f"t.direct.{n}",
        on_error=lambda e: (errs.append(e), done.set()),
        hello_timeout_s=0.3,
    )
    s.listen()  # node1 never says hello
    assert done.wait(5.0), "deadline did not fire"
    assert isinstance(errs[0], RetryableSessionError)
    assert "node1" in str(errs[0])
    assert s.failed
    s.close()
    fabric.close()


def test_hello_deadline_cancelled_on_quorum(tmp_path):
    ids = ["node0", "node1"]
    for n in ids:
        generate_identity(n, tmp_path)
    peers = {n: n for n in ids}
    fabric = LoopbackFabric()
    sessions = []
    errs = []
    for nid in ids:
        store = IdentityStore(tmp_path, nid, peers)
        party = EDDSAKeygenParty("s-ok", nid, ids, threshold=1)
        s = Session(
            session_id="s-ok",
            party=party,
            node_id=nid,
            participants=ids,
            transport=fabric.transport(),
            identity=store,
            broadcast_topic="t2.bcast",
            direct_topic_fn=lambda n: f"t2.direct.{n}",
            on_error=lambda e: errs.append(e),
            hello_timeout_s=0.5,
        )
        sessions.append(s)
    for s in sessions:
        s.listen()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not all(s.done for s in sessions):
        time.sleep(0.05)
    assert all(s.done for s in sessions), "keygen did not complete"
    time.sleep(0.7)  # past the hello deadline: no late spurious failure
    assert not errs
    for s in sessions:
        s.close()
    fabric.close()
