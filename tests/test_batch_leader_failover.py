"""Batch-scheduler leader failover (VERDICT r4 weak #7: the static
manifest leader was a throughput cliff — with the smallest quorum member
down, every request waited out manifest_timeout_s and then crawled down
the per-session path).

Two escalation paths are proven here, with node0 (the rank-0 leader)
killed like a crash (consumers closed, heartbeats stopped, NO resign):

1. Requests submitted BEFORE the survivors notice the death: buffered
   toward the dead leader, then at manifest_timeout_s the deputy (node1,
   next-smallest live) re-fires them under its own manifest — they still
   BATCH, and the per-session fallback is never touched.
2. Requests submitted AFTER the registry has marked node0 dead: node1 is
   computed as acting leader at submit time and the window fires
   normally — no timeout is paid at all.
"""
import secrets
import threading
import time

import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu import wire
from mpcium_tpu.cluster import LocalCluster, load_test_preparams
from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.engine import eddsa_batch as eb

N_WALLETS = 12


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = LocalCluster(
        n_nodes=3,
        threshold=1,
        root_dir=str(tmp_path_factory.mktemp("blf")),
        preparams=load_test_preparams(),
        batch_signing=True,
        batch_window_s=0.25,
        reply_timeout_s=30.0,
    )
    ids = c.node_ids
    shares = eb.dealer_keygen_batch(N_WALLETS, ids, threshold=1)
    pubs = []
    for w in range(N_WALLETS):
        for i, nid in enumerate(ids):
            c.nodes[nid].save_share(shares[i][w], f"fw{w}")
        pubs.append(shares[0][w].public_key)
    c._test_pubs = pubs
    # deputy takeover at 8 s (dead-leader detection needs ~3 s of stale
    # heartbeats first); per-session fallback would only start at 16 s
    for ec in c.consumers:
        ec.scheduler.manifest_timeout_s = 8.0
    # spy: the whole point is that the per-session path stays untouched
    c._fallbacks = []
    for ec in c.consumers[1:]:
        orig = ec.scheduler.on_fallback

        def spy(msg, reply, _orig=orig):
            c._fallbacks.append(msg.tx_id)
            _orig(msg, reply)

        ec.scheduler.on_fallback = spy
    yield c
    c.close()


def _kill_node0(c) -> None:
    """Crash semantics: consumers stop, heartbeats stop, key NOT deleted
    (resign would advertise the death instantly — a crash doesn't)."""
    if getattr(c, "_node0_dead", False):
        return
    c._node0_dead = True
    c.consumers[0].close()
    c.signing_consumers[0].close()
    reg = c.nodes["node0"].registry
    reg._stop.set()
    if reg._thread:
        reg._thread.join(timeout=5)


def _sign_all(c, prefix: str, timeout_s: float):
    results = {}
    done = threading.Event()

    def on_result(ev):
        results[ev.tx_id] = ev
        if len(results) == N_WALLETS:
            done.set()

    sub = c.client.on_sign_result(on_result)
    txs = {}
    try:
        for w in range(N_WALLETS):
            tx = secrets.token_bytes(32)
            tx_id = f"{prefix}-{w}"
            txs[tx_id] = (w, tx)
            c.client.sign_transaction(
                wire.SignTxMessage(
                    key_type="ed25519", wallet_id=f"fw{w}",
                    network_internal_code="sol", tx_id=tx_id, tx=tx,
                )
            )
        assert done.wait(timeout_s), (
            f"only {len(results)}/{N_WALLETS} results; "
            f"fallbacks={c._fallbacks}"
        )
    finally:
        sub.unsubscribe()
    for tx_id, ev in results.items():
        w, tx = txs[tx_id]
        assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
        assert hm.ed25519_verify(
            c._test_pubs[w], tx, bytes.fromhex(ev.signature)
        ), f"invalid signature for {tx_id}"


def test_deputy_takeover_mid_stream(cluster):
    """Kill the leader, submit IMMEDIATELY (survivors still think node0
    is alive): the deputy re-fires the buffered entries at
    manifest_timeout_s and they batch — zero per-session fallbacks."""
    start_batches = sum(
        ec.scheduler.batches_run for ec in cluster.consumers[1:]
    )
    _kill_node0(cluster)
    _sign_all(cluster, "to", timeout_s=600)
    assert not cluster._fallbacks, (
        f"requests leaked to the per-session path: {cluster._fallbacks}"
    )
    end_batches = sum(
        ec.scheduler.batches_run for ec in cluster.consumers[1:]
    )
    per_node = (end_batches - start_batches) / 2
    assert 1 <= per_node <= 4, f"expected batched dispatches, got {per_node}"


def test_submit_after_death_elects_deputy_immediately(cluster):
    """With node0 already marked dead, node1 is the acting leader at
    submit time: the window fires normally and nothing waits out the
    manifest timeout (asserted via wall time well under timeout+compile
    slack)."""
    _kill_node0(cluster)
    reg = cluster.nodes["node1"].registry
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if not reg.is_peer_ready("node0"):
            break
        time.sleep(0.2)
    else:
        raise AssertionError("node1 never marked node0 dead")
    assert cluster.consumers[1].scheduler._acting_leader(
        cluster.node_ids
    ) == "node1"
    _sign_all(cluster, "pd", timeout_s=600)
    assert not cluster._fallbacks
