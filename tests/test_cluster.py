"""Full-cluster end-to-end: create wallet → sign (both curves) → reshare.

The analogue of the reference's manual 3-node docker-compose test flow
(SURVEY.md §4 "de-facto testing"), automated in-process.
"""
import hashlib
import secrets

import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu import wire
from mpcium_tpu.cluster import LocalCluster, load_test_preparams
from mpcium_tpu.core import hostmath as hm


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = LocalCluster(
        n_nodes=3,
        threshold=1,
        root_dir=str(tmp_path_factory.mktemp("cluster")),
        preparams=load_test_preparams(),
        # reference budget: 30 s reply wait (sign_consumer.go:16-20) — a
        # full GG18 signing run fits inside one delivery window
        reply_timeout_s=30.0,
    )
    yield c
    c.close()


@pytest.fixture(scope="module")
def wallet(cluster):
    ev = cluster.create_wallet_sync("wallet-1")
    return ev


def test_create_wallet(wallet):
    assert wallet.wallet_id == "wallet-1"
    # both pubkeys valid encodings
    secp_pub = hm.secp_decompress(bytes.fromhex(wallet.ecdsa_pub_key))
    assert not secp_pub.is_infinity
    hm.ed_decompress(bytes.fromhex(wallet.eddsa_pub_key))


def test_sign_eddsa(cluster, wallet):
    tx = b"solana transfer 1 SOL"
    ev = cluster.sign_sync(
        wire.SignTxMessage(
            key_type="ed25519",
            wallet_id="wallet-1",
            network_internal_code="solana-devnet",
            tx_id="tx-ed-1",
            tx=tx,
        )
    )
    assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
    sig = bytes.fromhex(ev.signature)
    assert hm.ed25519_verify(bytes.fromhex(wallet.eddsa_pub_key), tx, sig)


def test_sign_ecdsa(cluster, wallet):
    digest = hashlib.sha256(b"eth transfer").digest()
    ev = cluster.sign_sync(
        wire.SignTxMessage(
            key_type="secp256k1",
            wallet_id="wallet-1",
            network_internal_code="ethereum",
            tx_id="tx-ec-1",
            tx=digest,
        )
    )
    assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
    pub = hm.secp_decompress(bytes.fromhex(wallet.ecdsa_pub_key))
    assert hm.ecdsa_verify(
        pub, int.from_bytes(digest, "big"), int(ev.r, 16), int(ev.s, 16)
    )
    assert ev.signature_recovery in ("00", "01", "02", "03")


def test_duplicate_sign_is_idempotent(cluster, wallet):
    """Same tx twice: one result (idempotent queue + dup-session check)."""
    tx = b"dup test"
    msg = wire.SignTxMessage(
        key_type="ed25519", wallet_id="wallet-1",
        network_internal_code="sol", tx_id="tx-dup", tx=tx,
    )
    ev = cluster.sign_sync(msg)
    assert ev.result_type == wire.RESULT_SUCCESS
    results = []
    sub = cluster.client.on_sign_result(lambda e: results.append(e))
    try:
        cluster.client.sign_transaction(msg)  # replay
        cluster.fabric.drain(timeout_s=60)
        dups = [e for e in results if e.tx_id == "tx-dup"]
        assert dups == []  # deduped at the queue (Nats-Msg-Id semantics)
    finally:
        sub.unsubscribe()


def test_unknown_wallet_sign_dead_letters(cluster):
    """Unknown wallet: retryable → redelivery exhausts → dead-letter →
    timeout error event to the client (the reference's DLQ path, §5.3c)."""
    ev = cluster.sign_sync(
        wire.SignTxMessage(
            key_type="ed25519", wallet_id="ghost-wallet",
            network_internal_code="sol", tx_id="tx-ghost", tx=b"x",
        ),
        timeout_s=120,
    )
    assert ev.result_type == wire.RESULT_ERROR
    assert ev.is_timeout


def test_forged_initiator_signature_ignored(cluster):
    from mpcium_tpu.identity.identity import InitiatorKey

    rogue = InitiatorKey.generate()
    rogue_client_msg = wire.GenerateKeyMessage(wallet_id="evil-wallet")
    rogue_client_msg.signature = rogue.sign(rogue_client_msg.raw())
    cluster.client.transport.pubsub.publish(
        wire.TOPIC_GENERATE, wire.canonical_json(rogue_client_msg.to_json())
    )
    cluster.fabric.drain(timeout_s=60)
    # no node created the wallet
    for node in cluster.nodes.values():
        assert node.keyinfo.get("ed25519", "evil-wallet") is None


def test_reshare_eddsa_and_sign_after(cluster, wallet):
    ev = cluster.reshare_sync("wallet-1", new_threshold=1, key_type="ed25519")
    assert ev.pub_key == wallet.eddsa_pub_key  # key unchanged
    # is_reshared recorded
    info = cluster.nodes["node0"].keyinfo.get("ed25519", "wallet-1")
    assert info.is_reshared
    # signing still works with the reshared shares
    tx = b"post-reshare tx"
    sev = cluster.sign_sync(
        wire.SignTxMessage(
            key_type="ed25519", wallet_id="wallet-1",
            network_internal_code="sol", tx_id="tx-after-rs", tx=tx,
        )
    )
    assert sev.result_type == wire.RESULT_SUCCESS, sev.error_reason
    assert hm.ed25519_verify(
        bytes.fromhex(wallet.eddsa_pub_key), tx, bytes.fromhex(sev.signature)
    )
