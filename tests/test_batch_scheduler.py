"""Batch scheduler: N concurrent signing requests → far fewer engine
dispatches, each client still gets its own result (SURVEY.md §7.2 step 5;
replaces the reference's per-session goroutines, event_consumer.go:295-338).
"""
import secrets
import threading

import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu import wire
from mpcium_tpu.cluster import LocalCluster, load_test_preparams
from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.engine import eddsa_batch as eb
from mpcium_tpu.protocol.eddsa.keygen import EDDSAKeygenParty
from mpcium_tpu.protocol.runner import run_protocol


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = LocalCluster(
        n_nodes=3,
        threshold=1,
        root_dir=str(tmp_path_factory.mktemp("bsched")),
        preparams=load_test_preparams(),  # committed fixture: no prime search
        batch_signing=True,
        batch_window_s=0.25,
        reply_timeout_s=30.0,
    )
    # deal EdDSA wallets straight into the stores (fast; DKG covered
    # elsewhere)
    ids = c.node_ids
    n_wallets = 12
    shares = eb.dealer_keygen_batch(n_wallets, ids, threshold=1)
    pubs = []
    for w in range(n_wallets):
        for i, nid in enumerate(ids):
            c.nodes[nid].save_share(shares[i][w], f"bw{w}")
        pubs.append(shares[0][w].public_key)
    c._test_pubs = pubs
    # cold-cache hardening: the first batch pays minutes of XLA compiles on
    # this host; don't let the liveness fallback fire mid-compile and split
    # the batch down the per-session path
    for ec in c.consumers:
        ec.scheduler.manifest_timeout_s = 120.0
    yield c
    c.close()


def test_batched_signing_coalesces(cluster):
    """12 concurrent requests over 12 wallets: every tx gets its own valid
    signature while the engine runs ≪ 12 batches."""
    n = 12
    results = {}
    done = threading.Event()

    def on_result(ev):
        results[ev.tx_id] = ev
        if len(results) == n:
            done.set()

    sub = cluster.client.on_sign_result(on_result)
    txs = {}
    try:
        start_batches = sum(
            ec.scheduler.batches_run for ec in cluster.consumers
        )
        for w in range(n):
            tx = secrets.token_bytes(32)
            tx_id = f"btx-{w}"
            txs[tx_id] = (w, tx)
            cluster.client.sign_transaction(
                wire.SignTxMessage(
                    key_type="ed25519",
                    wallet_id=f"bw{w}",
                    network_internal_code="sol",
                    tx_id=tx_id,
                    tx=tx,
                )
            )
        assert done.wait(600), f"only {len(results)}/{n} results arrived"
    finally:
        sub.unsubscribe()

    for tx_id, ev in results.items():
        w, tx = txs[tx_id]
        assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
        assert hm.ed25519_verify(
            cluster._test_pubs[w], tx, bytes.fromhex(ev.signature)
        ), f"invalid signature for {tx_id}"

    # the point of the scheduler: dispatch count ≪ N. Per node, all 12
    # requests should land in a handful of manifests (ideally 1-2 windows).
    end_batches = sum(ec.scheduler.batches_run for ec in cluster.consumers)
    per_node = (end_batches - start_batches) / len(cluster.consumers)
    assert per_node <= 4, (
        f"expected ≤4 batches per node for {n} concurrent txs, got {per_node}"
    )

    # claim-leak regression (round-3 advisor finding): a batch must finish
    # the dedup claims of requests it covered — on the manifest leader as
    # well as on followers. A stranded claim would both leak memory and
    # make any redelivery of the tx a permanent "duplicate session" no-op.
    import time as _time

    deadline = _time.monotonic() + 30
    while _time.monotonic() < deadline:
        leaked = {
            ec.node.node_id: [
                k for k in ec._sessions if k.startswith("bw")
            ]
            for ec in cluster.consumers
        }
        if not any(leaked.values()):
            break
        _time.sleep(0.5)
    assert not any(leaked.values()), f"stranded dedup claims: {leaked}"


def test_batch_preserves_wrong_key_isolation(cluster):
    """A request for an unknown wallet dead-letters (timeout error event)
    without poisoning concurrent valid batches."""
    results = {}
    done = threading.Event()

    def on_result(ev):
        results[ev.tx_id] = ev
        if "good-tx" in results and "bad-tx" in results:
            done.set()

    sub = cluster.client.on_sign_result(on_result)
    try:
        cluster.client.sign_transaction(
            wire.SignTxMessage(
                key_type="ed25519", wallet_id="no-such-wallet",
                network_internal_code="sol", tx_id="bad-tx",
                tx=b"\x01" * 32,
            )
        )
        tx = secrets.token_bytes(32)
        cluster.client.sign_transaction(
            wire.SignTxMessage(
                key_type="ed25519", wallet_id="bw0",
                network_internal_code="sol", tx_id="good-tx", tx=tx,
            )
        )
        assert done.wait(600), f"results: {list(results)}"
    finally:
        sub.unsubscribe()
    assert results["good-tx"].result_type == wire.RESULT_SUCCESS
    bad = results["bad-tx"]
    assert bad.result_type == wire.RESULT_ERROR and bad.is_timeout
