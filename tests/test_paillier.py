"""Paillier host reference + batched device kernels."""
import secrets

import jax.numpy as jnp
import numpy as np
import pytest

from mpcium_tpu.core import bignum as bn
from mpcium_tpu.core import paillier as pl


def small_key(bits=512):
    return pl.gen_paillier_key(bits)


def test_primality_basics():
    assert pl.is_probable_prime(2**127 - 1)  # Mersenne prime
    assert not pl.is_probable_prime(2**128 - 1)
    assert not pl.is_probable_prime(561 * 10**6 + 1 if False else 561)  # Carmichael
    p = pl.gen_prime(128)
    assert p.bit_length() == 128 and pl.is_probable_prime(p)


def test_safe_prime():
    p = pl.gen_safe_prime(64)
    assert pl.is_probable_prime(p) and pl.is_probable_prime((p - 1) // 2)


def test_host_roundtrip_and_homomorphism():
    sk = small_key()
    pk = sk.public
    m1 = secrets.randbelow(pk.N)
    m2 = secrets.randbelow(pk.N)
    c1, c2 = pk.encrypt(m1), pk.encrypt(m2)
    assert sk.decrypt(c1) == m1
    assert sk.decrypt(pk.add(c1, c2)) == (m1 + m2) % pk.N
    k = secrets.randbelow(2**256)
    assert sk.decrypt(pk.scalar_mul(c1, k)) == m1 * k % pk.N


def test_safe_prime_pool(tmp_path):
    import json
    from pathlib import Path

    # the committed fixture pool is loadable
    fixture = (
        Path(__file__).resolve().parent.parent
        / "mpcium_tpu" / "data" / "safeprimes_1024.json"
    )
    d = json.load(open(fixture))
    assert d["bits"] == 1024 and len(d["safe_primes"]) >= 2

    # pool semantics: take consumes, short pool falls back to generation
    pool = tmp_path / "pool.json"
    json.dump({"bits": 64, "safe_primes": [str(pl.gen_safe_prime(64))]}, open(pool, "w"))
    got = pl.pool_take(pool, count=2, bits=64)
    assert len(got) == 2 and all(pl.is_probable_prime(p) for p in got)
    assert json.load(open(pool))["safe_primes"] == []  # consumed
    pp = pl.gen_preparams(bits=128, pool_path=pool)  # regenerates, still works
    assert pp.NTilde.bit_length() >= 126


def test_preparams_structure():
    P = pl.gen_safe_prime(96)
    Q = pl.gen_safe_prime(96)
    while Q == P:
        Q = pl.gen_safe_prime(96)
    pp = pl.gen_preparams(bits=192, safe_primes=(P, Q))
    assert pp.NTilde == P * Q
    assert pow(pp.h1, pp.alpha, pp.NTilde) == pp.h2
    assert pow(pp.h2, pp.beta, pp.NTilde) == pp.h1
    rt = pl.PreParams.from_json(pp.to_json())
    assert rt == pp


@pytest.fixture(scope="module")
def batch_ctx():
    sk = small_key(512)
    return sk, pl.PaillierBatch(sk.public)


def test_batch_encrypt_matches_host(batch_ctx):
    sk, pb = batch_ctx
    pk = pb.pk
    B = 4
    ms = [secrets.randbelow(pk.N) for _ in range(B)]
    rs = [secrets.randbelow(pk.N - 1) + 1 for _ in range(B)]
    c = pb.encrypt(jnp.asarray(pb.to_limbs_N(ms)), jnp.asarray(pb.to_limbs_N2(rs)))
    got = pb.from_limbs_N2(np.asarray(c))
    expect = [pk.encrypt(m, r=r) for m, r in zip(ms, rs)]
    assert got == expect


def test_batch_decrypt_add_scalar(batch_ctx):
    sk, pb = batch_ctx
    pk = pb.pk
    B = 4
    m1 = [secrets.randbelow(pk.N) for _ in range(B)]
    m2 = [secrets.randbelow(pk.N) for _ in range(B)]
    ks = [secrets.randbelow(2**256) for _ in range(B)]
    c1 = jnp.asarray(pb.to_limbs_N2([pk.encrypt(m) for m in m1]))
    c2 = jnp.asarray(pb.to_limbs_N2([pk.encrypt(m) for m in m2]))
    # batched decrypt
    got = pb.from_limbs_N(np.asarray(pb.decrypt(sk, c1)))
    assert got == m1
    # batched homomorphic add
    s = pb.from_limbs_N(np.asarray(pb.decrypt(sk, pb.add(c1, c2))))
    assert s == [(a + b) % pk.N for a, b in zip(m1, m2)]
    # batched scalar mul with per-session 256-bit exponents
    k_limbs = jnp.asarray(bn.batch_to_limbs(ks, pb.prof_n))
    k_bits = bn.limbs_to_bits(k_limbs, pb.prof_n, 256)
    cm = pb.scalar_mul(c1, k_bits)
    got = pb.from_limbs_N(np.asarray(pb.decrypt(sk, cm)))
    assert got == [a * k % pk.N for a, k in zip(m1, ks)]


def test_powmod_fixed_base(batch_ctx):
    sk, pb = batch_ctx
    base = 0xDEADBEEF
    es = [secrets.randbelow(2**200) for _ in range(3)]
    e_limbs = jnp.asarray(bn.batch_to_limbs(es, pb.prof_n))
    e_bits = bn.limbs_to_bits(e_limbs, pb.prof_n, 200)
    got = pb.from_limbs_N(np.asarray(pb.ctx_N.powmod_fixed_base(base, e_bits)))
    assert got == [pow(base, e, pb.pk.N) for e in es]
