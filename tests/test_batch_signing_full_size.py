"""Full-size DISTRIBUTED batched GG18 through the scheduler (VERDICT r4
weak #6 / next #8): N_WALLETS=4 concurrent signing requests at
production key size — 2048-bit Paillier, default ZK exponent domains —
coalesce into batched engine dispatches on every node and come back as
valid secp256k1 signatures. The engine-only full-size path is
test_gg18_full_size; this proves the production consumer→scheduler→
protocol.ecdsa.batch_signing stack at the same size.

Subprocess-isolated like the other heavy suites: the graphs are the
biggest XLA:CPU compiles in the repo, and the known-bad-host AOT crash
(see test_batch_dkg_party) must not kill the whole pytest process.

Observed on the round-5 (live-migrated) host: XLA:CPU deterministically
SEGFAULTs compiling THESE full-size graphs — 3/3 runs, fresh process,
MPCIUM_TESTS_NO_CACHE=1, while the same stack at 1024-bit
(test_batch_scheduler_ecdsa) passes — i.e. the same host-specific
codegen crash class test_batch_dkg_party documents, now size-triggered.
Run with MPCIUM_XFAIL_XLA_CRASH=1 on such hosts; the test is green
where XLA:CPU is healthy and the distributed path itself is proven at
1024-bit plus full-size through the in-process engine
(test_gg18_full_size, bench.py on TPU).
"""
import os
import secrets

import pytest

pytestmark = pytest.mark.slow

from conftest import run_isolated

_INNER = os.environ.get("MPCIUM_BSIGN_FULL_INNER")

N_WALLETS = 4


def test_full_size_batch_signing_isolated():
    if _INNER:
        pytest.skip("wrapper entry; inner run executes the real test")
    run_isolated(
        __file__, "test_full_size_batch_signing_inner",
        "MPCIUM_BSIGN_FULL_INNER", timeout=5400,
    )


@pytest.mark.skipif(not _INNER, reason="runs via the subprocess wrapper")
def test_full_size_batch_signing_inner():
    import threading

    from mpcium_tpu import wire
    from mpcium_tpu.cluster import LocalCluster, load_test_preparams
    from mpcium_tpu.core import hostmath as hm
    from mpcium_tpu.engine import gg18_batch as gb

    pre = load_test_preparams()  # full 2048-bit Paillier / NTilde
    c = LocalCluster(
        n_nodes=3,
        threshold=1,
        root_dir=None,
        preparams=pre,
        batch_signing=True,
        batch_window_s=0.5,
        reply_timeout_s=4800.0,
    )
    try:
        ids = c.node_ids
        shares = gb.dealer_keygen_secp_batch(
            N_WALLETS, ids, threshold=1, preparams=pre
        )
        for w in range(N_WALLETS):
            for i, nid in enumerate(ids):
                c.nodes[nid].save_share(shares[i][w], f"fw{w}")
        for ec in c.consumers:
            # default gg18_dom: FULL-SIZE ZK exponent domains
            ec.scheduler.manifest_timeout_s = 4200.0  # cold-cache compile

        results = {}
        done = threading.Event()

        def on_result(ev):
            results[ev.tx_id] = ev
            if len(results) == N_WALLETS:
                done.set()

        c.client.on_sign_result(on_result)
        start_batches = sum(ec.scheduler.batches_run for ec in c.consumers)
        txs = {}
        for w in range(N_WALLETS):
            tx = secrets.token_bytes(32)
            tx_id = f"ftx-{w}"
            txs[tx_id] = (w, tx)
            c.client.sign_transaction(
                wire.SignTxMessage(
                    key_type="secp256k1",
                    wallet_id=f"fw{w}",
                    network_internal_code="eth",
                    tx_id=tx_id,
                    tx=tx,
                )
            )
        assert done.wait(4800), f"only {len(results)}/{N_WALLETS} arrived"

        for tx_id, (w, tx) in txs.items():
            ev = results[tx_id]
            assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
            pub = hm.secp_decompress(shares[0][w].public_key)
            r = int(ev.r, 16)
            s = int(ev.s, 16)
            assert hm.ecdsa_verify(
                pub, int.from_bytes(tx, "big"), r, s
            ), tx_id
            assert int(ev.signature_recovery, 16) in (0, 1, 2, 3)

        # the point of the test: requests BATCHED (each node runs a few
        # coalesced dispatches, not one per wallet per node)
        batches = (
            sum(ec.scheduler.batches_run for ec in c.consumers)
            - start_batches
        )
        assert 0 < batches < N_WALLETS * len(ids), batches
    finally:
        c.close()
