"""Batched GG18 engine: full 2-of-3 signing over a (tiny) session batch.

Uses 1024-bit Paillier/NTilde keys and shrunk ZK exponent domains
(test-only: proof algebra is size-independent; bounds still satisfy the
no-wrap requirement a·b + β′ < N). The full-size path runs in bench.py on
real hardware.
"""
import secrets

import numpy as np
import pytest

# the whole module is slow-tier: even the shrunk 1024-bit fixture needs
# minutes of kernel compiles on a cold cache (smoke tier must stay <60s);
# GG18 engine coverage therefore lives in the slow tier + bench + dryrun
pytestmark = pytest.mark.slow

from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.core import paillier as pl
from mpcium_tpu.engine import gg18_batch as gb

TEST_DOM = gb.Domains(alpha=600, beta_prime=320, gamma_bob=600)


@pytest.fixture(scope="module")
def small_preparams():
    # committed FIXED keys: the persistent XLA cache stays valid across
    # runs (fresh random moduli would recompile every kernel)
    from mpcium_tpu.cluster import load_test_preparams

    return load_test_preparams(bits=1024)


def test_batched_gg18_3of5(small_preparams):
    """t+1-of-n beyond two parties: a 3-signer quorum out of a 5-party
    universe, all ordered MtA pairs (reference signs with any t+1 quorum,
    ecdsa_signing_session.go:96-139)."""
    B = 2  # same batch shape as the 2-party test: kernel cache is shared
    universe = [f"node{i}" for i in range(5)]
    shares = gb.dealer_keygen_secp_batch(B, universe, threshold=2)
    quorum = ["node0", "node2", "node4"]
    qshares = [shares[0], shares[2], shares[4]]
    signer = gb.GG18BatchCoSigners(
        quorum, qshares, small_preparams, dom=TEST_DOM
    )
    digests = np.frombuffer(secrets.token_bytes(B * 32), dtype=np.uint8).reshape(
        B, 32
    )
    out = signer.sign(digests)
    assert out["ok"].all(), "3-of-5 batched GG18 produced invalid signatures"
    for i in range(B):
        pub = hm.secp_decompress(shares[0][i].public_key)
        r = int.from_bytes(out["r"][i].tobytes(), "big")
        s = int.from_bytes(out["s"][i].tobytes(), "big")
        digest = int.from_bytes(digests[i].tobytes(), "big")
        assert hm.ecdsa_verify(pub, digest, r, s)


def test_batch_verification_attributes_bad_proof(small_preparams):
    """Randomized batch verification (BGR small-exponent test) must not
    hide a cheater: a corrupted proof inside a batch fails the combined
    check, triggers the strict fallback, and is attributed to exactly its
    session (identifiable abort).

    B=2 on purpose: it shares every heavy kernel shape with the engine
    tests above — NEW N²-width shapes in a long pytest process trip the
    XLA CPU AOT serializer segfault on this host (see conftest note).
    """
    import jax.numpy as jnp

    from mpcium_tpu.core import bignum as bn
    from mpcium_tpu.engine.gg18_batch import (
        RAND_BITS, MtaBatch, PartyCtx, rand_bit_tensor, _scalar_to_plain,
    )

    assert gb.BATCH_VERIFY == "rand"  # default fast path under test
    B = 2
    ctx_a = PartyCtx("node0", small_preparams["node0"])
    ctx_b = PartyCtx("node1", small_preparams["node1"])
    mta = MtaBatch(ctx_a, ctx_b, TEST_DOM)

    ks = [secrets.randbelow(gb.Q) for _ in range(B)]
    kp = _scalar_to_plain(
        ctx_a.pmx, jnp.asarray(bn.batch_to_limbs(ks, bn.P256))
    )
    u_bits = rand_bit_tensor(B, RAND_BITS)
    c_a, _r = ctx_a.pmx.encrypt(kp, u_bits)
    Ra = mta.alice_randoms(B)
    T = mta.alice_init(kp, Ra)
    e = mta.e_limbs(mta.alice_challenge(c_a, T))
    P = mta.alice_finish(e, kp, Ra, u_bits)

    ok = np.asarray(mta.bob_check_alice(c_a, T, P, e))
    assert ok.all(), "honest batch must verify on the fast path"

    # corrupt session 2's randomizer response s
    s_np = np.asarray(P["s"]).copy()
    bad = bn.batch_to_limbs(
        [secrets.randbelow(ctx_a.N - 2) + 1], ctx_a.pmx.prof_n
    )
    s_np[1] = bad[0]
    P_bad = dict(P)
    P_bad["s"] = jnp.asarray(s_np)
    ok = np.asarray(mta.bob_check_alice(c_a, T, P_bad, e))
    assert list(ok) == [True, False], (
        f"bad proof not attributed correctly: {list(ok)}"
    )

    # same property for the Bob-direction proof
    bs = [secrets.randbelow(gb.Q) for _ in range(B)]
    b_e = jnp.asarray(bn.batch_to_limbs(bs, mta.p_e))
    Rb = mta.bob_randoms(B)
    Tb = mta.bob_respond(c_a, b_e, Rb)
    e_b = mta.e_limbs(mta.bob_challenge(c_a, Tb))
    Pb = mta.bob_finish(e_b, b_e, Rb)
    ok = np.asarray(mta.alice_check_bob(c_a, Tb, Pb, e_b))
    assert ok.all(), "honest Bob batch must verify on the fast path"
    s_np = np.asarray(Pb["s"]).copy()
    s_np[1] = bad[0]
    Pb_bad = dict(Pb)
    Pb_bad["s"] = jnp.asarray(s_np)
    ok = np.asarray(mta.alice_check_bob(c_a, Tb, Pb_bad, e_b))
    assert list(ok) == [True, False], (
        f"bad Bob proof not attributed correctly: {list(ok)}"
    )


def test_batched_gg18_end_to_end(small_preparams):
    B = 2
    universe = ["node0", "node1", "node2"]
    shares = gb.dealer_keygen_secp_batch(B, universe, threshold=1)
    signer = gb.GG18BatchCoSigners(
        ["node0", "node1"], shares[:2], small_preparams, dom=TEST_DOM
    )
    digests = np.frombuffer(secrets.token_bytes(B * 32), dtype=np.uint8).reshape(
        B, 32
    )
    out = signer.sign(digests)
    assert out["ok"].all(), "batched GG18 produced invalid signatures"
    for i in range(B):
        pub = hm.secp_decompress(shares[0][i].public_key)
        r = int.from_bytes(out["r"][i].tobytes(), "big")
        s = int.from_bytes(out["s"][i].tobytes(), "big")
        digest = int.from_bytes(digests[i].tobytes(), "big")
        assert s <= gb.Q // 2
        assert hm.ecdsa_verify(pub, digest, r, s)
        assert int(out["recovery"][i]) in (0, 1, 2, 3)
    # independent OpenSSL verification
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec, utils

    pub = hm.secp_decompress(shares[0][0].public_key)
    key = ec.EllipticCurvePublicNumbers(pub.x, pub.y, ec.SECP256K1()).public_key()
    key.verify(
        utils.encode_dss_signature(
            int.from_bytes(out["r"][0].tobytes(), "big"),
            int.from_bytes(out["s"][0].tobytes(), "big"),
        ),
        digests[0].tobytes(),
        ec.ECDSA(utils.Prehashed(hashes.SHA256())),
    )
