"""mpcclaims: the claims ledger (ISSUE 19).

Registry hygiene, the predicate engine, the structural guarantees (a
CPU-degraded record can never satisfy a chip claim; an embedded stale
rider yields `stale`, never `claimed`), and the drift gate over the
committed CLAIMS.json / CLAIMS.md — the tier-1 half of `make
claimscheck`."""
import json
import subprocess
import sys
from pathlib import Path

import pytest

from mpcium_tpu.perf import claims, ledger

pytestmark = pytest.mark.perf

_ROOT = Path(__file__).resolve().parents[1]


def _chip_record(**over):
    rec = {
        "source": "BENCH_TPU_X.json", "kind": "bench", "round": None,
        "platform": "tpu", "degraded": False, "fingerprint": "tpu/abc",
        "metrics": {}, "context": {}, "measured_at": "2026-08-07T00:00:00",
        "notes": [],
    }
    rec.update(over)
    return rec


# -- predicate engine ---------------------------------------------------------


def test_predicate_ops():
    rec = {"metrics": {"x": 5.0, "y": 10.0}, "context": {}}
    assert claims.eval_predicate({"op": "gt", "value": 4}, rec, 5.0)
    assert not claims.eval_predicate({"op": "gt", "value": 5}, rec, 5.0)
    assert claims.eval_predicate({"op": "ge", "value": 5}, rec, 5.0)
    assert claims.eval_predicate({"op": "lt", "value": 6}, rec, 5.0)
    assert claims.eval_predicate({"op": "eq", "value": 5}, rec, 5.0)
    assert claims.eval_predicate({"op": "exists"}, rec, 0.0)
    # unresolvable values never satisfy anything — including exists
    assert not claims.eval_predicate({"op": "exists"}, rec, None)
    # cross-metric comparison reads the SAME record
    assert claims.eval_predicate(
        {"op": "lt_metric", "metric": "y"}, rec, 5.0)
    assert not claims.eval_predicate(
        {"op": "lt_metric", "metric": "x"}, rec, 5.0)
    with pytest.raises(ValueError):
        claims.eval_predicate({"op": "spaceship"}, rec, 1.0)


def test_record_value_forms():
    rec = {
        "metrics": {"rate": 7.5},
        "context": {
            "gg18_ot_checks_s": 1.25,
            "phase_s": {
                "r1_commit_encrypt_rangeproof": 10.0, "r2_mta_ot": 30.0,
                "r2_mta_respond": 20.0, "r3_verify_decrypt": 30.0,
                "r4_R_reconstruct_pok": 5.0, "r5_phase5_combine_verify": 5.0,
            },
        },
    }
    assert claims.record_value(rec, "rate") == 7.5
    assert claims.record_value(rec, "ctx:gg18_ot_checks_s") == 1.25
    assert claims.record_value(rec, "missing") is None
    # derived share: 30 / 100, from the six primary phases only
    assert claims.record_value(
        rec, "derived:r2_mta_ot_phase_share") == pytest.approx(0.30)


def test_phase_share_prefers_ot_table_and_ignores_attr_keys():
    # flattened span attrs (_chunks, _overlap_ratio) and device sub-spans
    # must not pollute the time denominator
    rec = {"metrics": {}, "context": {
        "phase_s": {"r2_mta_ot": 99.0, "r2_mta_respond": 1.0},
        "gg18_ot_mta_phase_s": {
            "r1_commit_encrypt_rangeproof": 10.0, "r2_mta_ot": 40.0,
            "r2_mta_respond": 50.0,
            "r2_mta_ot_chunks": 8.0, "r2_mta_ot_overlap_ratio": 0.9,
        },
    }}
    assert claims.record_value(
        rec, "derived:r2_mta_ot_phase_share") == pytest.approx(0.40)


# -- structural guarantees ----------------------------------------------------


def _find(evaluated, claim_id):
    return next(c for c in evaluated if c["id"] == claim_id)


def test_degraded_record_cannot_satisfy_chip_claim():
    """The r05 failure mode, made structurally impossible: a CPU record
    carrying a huge number still leaves the chip claim owed."""
    cpu = _chip_record(
        platform="cpu", degraded=True,
        metrics={"ed25519_2of3_sigs_per_sec": 999999.0},
    )
    ev = claims.evaluate([cpu])
    assert _find(ev, "ed25519-10k")["status"] == "owed"
    # the same number on a non-degraded chip record claims it
    chip = _chip_record(metrics={"ed25519_2of3_sigs_per_sec": 999999.0})
    ev = claims.evaluate([chip])
    c = _find(ev, "ed25519-10k")
    assert c["status"] == "claimed"
    assert c["evidence"]["source"] == "BENCH_TPU_X.json"


def test_watchdog_zero_record_cannot_claim():
    wd = _chip_record(degraded=True,
                      metrics={"b_sweep_16384_sigs_per_sec": 50.0})
    assert _find(claims.evaluate([wd]), "b-sweep-16384")["status"] == "owed"


def test_embedded_stale_rider_yields_stale_never_claimed():
    """A degraded run whose cached last_tpu_measurement rider would pass
    the predicate lands as `stale` with the rider's age in evidence."""
    degraded = _chip_record(
        platform="cpu", degraded=True,
        context={"embedded_tpu_rider": {
            "stale_s": 40000.0,
            "metrics": {"ed25519_2of3_sigs_per_sec": 12000.0},
        }},
    )
    c = _find(claims.evaluate([degraded]), "ed25519-10k")
    assert c["status"] == "stale"
    assert c["evidence"]["stale_s"] == 40000.0
    assert "rider" in c["evidence"]["note"]


def test_requires_gates_which_records_testify():
    """The phase-share claim only counts runs with device=True OT spans
    (ctx gg18_ot_mta_device_s > 0) — a pre-device trace at 40% share
    must not claim it."""
    table = {
        "r1_commit_encrypt_rangeproof": 10.0, "r2_mta_ot": 40.0,
        "r2_mta_respond": 50.0,
    }
    no_device = _chip_record(context={"gg18_ot_mta_phase_s": table})
    ev = claims.evaluate([no_device])
    assert _find(ev, "r2-mta-ot-phase-share")["status"] == "owed"
    with_device = _chip_record(context={
        "gg18_ot_mta_phase_s": table, "gg18_ot_mta_device_s": 3.0,
    })
    ev = claims.evaluate([with_device])
    assert _find(ev, "r2-mta-ot-phase-share")["status"] == "claimed"


def test_rehearsal_class_accepts_degraded_records():
    camp = {
        "source": "CAMPAIGN_rehearsal.json", "kind": "campaign",
        "round": None, "platform": "cpu", "degraded": True,
        "fingerprint": "cpu/x", "metrics": {"campaign_complete": 1.0},
        "context": {"rehearse": True}, "measured_at": None, "notes": [],
    }
    ev = claims.evaluate([camp])
    assert _find(ev, "campaign-rehearsal-complete")["status"] == "claimed"


def test_pipeline_idle_collapse_needs_chip_for_chip_claim():
    pipe = {
        "source": "BENCH_pipeline_cpu.json", "kind": "pipeline",
        "round": None, "platform": "cpu", "degraded": True,
        "fingerprint": "cpu/x",
        "metrics": {"idle_fraction_k1": 0.5, "idle_fraction_k2": 0.2},
        "context": {}, "measured_at": None, "notes": [],
    }
    ev = claims.evaluate([pipe])
    assert _find(ev, "pipeline-idle-collapse")["status"] == "owed"
    assert _find(ev, "pipeline-idle-collapse-rehearsal")["status"] \
        == "claimed"


# -- registry hygiene + drift gate -------------------------------------------


def test_registry_covers_every_roadmap_headline():
    assert claims.registry_problems([]) == []


def test_unknown_metric_is_a_problem(monkeypatch):
    bogus = dict(claims.REGISTRY[0], id="bogus", metric="no_such_metric_x")
    monkeypatch.setattr(claims, "REGISTRY", claims.REGISTRY + [bogus])
    probs = claims.registry_problems([])
    assert any("unknown metric" in p for p in probs)


def test_untracked_headline_is_a_problem(monkeypatch):
    monkeypatch.setattr(
        claims, "ROADMAP_HEADLINES",
        dict(claims.ROADMAP_HEADLINES, brand_new_headline_metric="x"),
    )
    probs = claims.registry_problems([])
    assert any("no claim tracking it" in p for p in probs)


def test_committed_claims_match_regeneration():
    """The drift gate: CLAIMS.json and CLAIMS.md are byte-for-byte pure
    functions of (registry, committed artifacts)."""
    records = ledger.build_history(str(_ROOT))
    evaluated = claims.evaluate(records)
    assert (_ROOT / claims.CLAIMS_JSON).read_text() \
        == claims.render_json(evaluated)
    assert (_ROOT / claims.CLAIMS_MD).read_text() \
        == claims.render_md(evaluated)


def test_claimscheck_cli_green():
    """`make claimscheck` on the committed tree: clean exit, and every
    chip headline is machine-evaluated (owed or claimed, never unknown)."""
    r = subprocess.run(
        [sys.executable, str(_ROOT / "scripts" / "claimscheck.py")],
        cwd=str(_ROOT), capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout


def test_committed_ledger_has_no_cpu_satisfied_chip_claim():
    """Acceptance: on the committed corpus no chip claim's evidence is a
    degraded or stale record — the engine only ever cites live chip
    records for chip claims."""
    records = ledger.build_history(str(_ROOT))
    by_source = {r["source"]: r for r in records}
    for c in claims.evaluate(records):
        if c["envfp_class"] != "chip":
            continue
        assert c["status"] in ("owed", "claimed", "stale")
        if c["status"] == "claimed":
            src = by_source[c["evidence"]["source"]]
            assert not src["degraded"] and src["platform"] == "tpu"


# -- gauges -------------------------------------------------------------------


def test_gauge_summary_counts_and_cache(tmp_path):
    claims.reset_gauge_cache()
    counts = claims.gauge_summary(str(_ROOT))
    total = counts["owed"] + counts["claimed"] + counts["stale"]
    assert total == len(claims.REGISTRY)
    # unreadable corpus: never raises, flags error
    bad = tmp_path / "nowhere"
    bad.mkdir()
    (bad / "BENCH_r99.json").write_text("{not json")
    claims.reset_gauge_cache()
    out = claims.gauge_summary(str(bad))
    assert out.get("error") == 1
    claims.reset_gauge_cache()


def test_export_gauges_into_registry():
    from mpcium_tpu.utils.metrics import MetricsRegistry

    claims.reset_gauge_cache()
    m = MetricsRegistry()
    counts = claims.export_gauges(m, str(_ROOT))
    assert m.gauge("claims.owed").value == float(counts["owed"])
    assert m.gauge("claims.claimed").value == float(counts["claimed"])
    prom = m.to_prometheus(labels={"node": "n0"})
    assert "claims_owed" in prom


def test_renderers_are_deterministic():
    records = ledger.build_history(str(_ROOT))
    ev = claims.evaluate(records)
    assert claims.render_json(ev) == claims.render_json(ev)
    doc = json.loads(claims.render_json(ev))
    assert doc["summary"] == claims.summary(ev)
    assert len(doc["claims"]) == len(claims.REGISTRY)
