"""node.daemon health publisher: payload shape, the Prometheus sidecar
key, periodic republish, and the warn-don't-crash contract when the KV
put raises (no broker, no cluster — stub consumer + in-memory KV)."""
import json
import threading
import time

from mpcium_tpu.node.daemon import health_loop, publish_health
from mpcium_tpu.store.kvstore import MemoryKV
from mpcium_tpu.utils.metrics import MetricsRegistry


class _StubConsumer:
    """The slice of EventConsumer the health beat reads."""

    def __init__(self):
        self.metrics = MetricsRegistry()
        self.metrics.counter("scheduler.batches_fired_total").inc(3)
        self.metrics.gauge("scheduler.queue_depth").set(2)

    def health(self):
        return {
            "sessions": 0,
            "batch_signing": True,
            "metrics": self.metrics.snapshot(),
        }


def test_publish_health_payload_and_prom_sidecar():
    kv = MemoryKV()
    consumer = _StubConsumer()
    snap = publish_health(consumer, kv, "node0")
    assert "ts" in snap and snap["batch_signing"] is True

    stored = json.loads(kv.get("health/node0"))
    assert stored["sessions"] == 0
    assert stored["metrics"]["counters"][
        "scheduler.batches_fired_total"] == 3.0
    assert stored["ts"] == snap["ts"]

    prom = kv.get("health/node0.prom").decode()
    assert "# TYPE scheduler_batches_fired_total counter" in prom
    assert 'scheduler_batches_fired_total{node="node0"} 3.0' in prom
    assert 'scheduler_queue_depth{node="node0"} 2.0' in prom


def test_health_loop_republishes_periodically():
    kv = MemoryKV()
    consumer = _StubConsumer()
    stop = threading.Event()
    seen = []
    orig_put = kv.put

    def counting_put(key, value):
        seen.append(key)
        return orig_put(key, value)

    kv.put = counting_put
    t = threading.Thread(
        target=health_loop, args=(consumer, kv, "node0", stop, 0.05),
        daemon=True,
    )
    t.start()
    deadline = time.monotonic() + 5.0
    while seen.count("health/node0") < 3 and time.monotonic() < deadline:
        time.sleep(0.02)
    stop.set()
    t.join(2.0)
    assert seen.count("health/node0") >= 3
    assert seen.count("health/node0.prom") >= 3


def test_health_payload_tracks_compile_warming_to_ready():
    """The compile-wall section rides the health beat: a daemon that is
    still tracing its first XLA compiles publishes state=warming, then
    flips to ready — visible to anything polling health/<node>."""
    from mpcium_tpu.perf import compile_watch

    class _CompileAwareConsumer(_StubConsumer):
        def health(self):
            compile_watch.export_gauges(self.metrics)
            h = super().health()
            h["compile"] = compile_watch.health_summary()
            return h

    compile_watch.reset()
    try:
        kv = MemoryKV()
        consumer = _CompileAwareConsumer()

        compile_watch.mark_warming()
        compile_watch.finish(compile_watch.begin("dkg.run", "B4|q3|ecdsa"))
        snap = publish_health(consumer, kv, "node0")
        assert snap["compile"]["state"] == "warming"
        assert snap["compile"]["compiles"] == 1
        stored = json.loads(kv.get("health/node0"))
        assert stored["compile"]["state"] == "warming"
        assert stored["metrics"]["gauges"]["compile.ready"] == 0.0

        compile_watch.mark_ready()
        snap = publish_health(consumer, kv, "node0")
        assert snap["compile"]["state"] == "ready"
        stored = json.loads(kv.get("health/node0"))
        assert stored["metrics"]["gauges"]["compile.ready"] == 1.0
        prom = kv.get("health/node0.prom").decode()
        assert 'compile_ready{node="node0"} 1.0' in prom
    finally:
        compile_watch.reset()


def test_health_loop_survives_kv_put_raise():
    consumer = _StubConsumer()
    stop = threading.Event()
    calls = []

    class _BrokenKV:
        def put(self, key, value):
            calls.append(key)
            raise OSError("control plane down")

    t = threading.Thread(
        target=health_loop,
        args=(consumer, _BrokenKV(), "node0", stop, 0.05),
        daemon=True,
    )
    t.start()
    deadline = time.monotonic() + 5.0
    while len(calls) < 3 and time.monotonic() < deadline:
        time.sleep(0.02)
    stop.set()
    t.join(2.0)
    # the beat kept beating THROUGH the failures, and the thread exits
    # cleanly on stop rather than dying on the first raise
    assert len(calls) >= 3
    assert not t.is_alive()
