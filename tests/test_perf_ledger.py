"""Bench-trajectory ledger (perf/ledger.py + perf/report.py): every
committed artifact normalizes, degraded runs segregate from chip trends,
and the dashboard/counter-track renderers are deterministic and
schema-valid."""
import json
import os

import pytest

from mpcium_tpu.perf import ledger, report
from mpcium_tpu.perf.envfp import env_fingerprint
from mpcium_tpu.trace.export import chrome_trace
from mpcium_tpu.trace.schema import validate_chrome

pytestmark = pytest.mark.perf

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_discovers_every_committed_artifact():
    names = {os.path.basename(p) for p in ledger.discover_artifacts(ROOT)}
    expected = (
        {f"BENCH_r0{i}.json" for i in range(1, 6)}
        | {f"MULTICHIP_r0{i}.json" for i in range(1, 6)}
        | {"SOAK_r01.json", "BENCH_TPU_LATEST.json", "BENCH_TPU_OT.json"}
    )
    assert expected <= names


def test_every_committed_artifact_normalizes():
    for path in ledger.discover_artifacts(ROOT):
        rec = ledger.normalize(path)  # raises = gate failure
        assert rec["kind"] in (
            "bench", "soak", "multichip", "pipeline", "campaign")
        assert rec["fingerprint"], path
        assert isinstance(rec["metrics"], dict)


def test_dnf_rounds_are_degraded_with_notes():
    for name, rc in (("BENCH_r02.json", 1), ("BENCH_r04.json", 124)):
        rec = ledger.normalize(os.path.join(ROOT, name))
        assert rec["degraded"]
        assert rec["context"]["rc"] == rc
        assert any("DNF" in n for n in rec["notes"])
        assert not rec["metrics"]


def test_b_sweep_entries_ingest_without_string_sniffing():
    """bench.py's sweep contract: numeric entries become metrics, the
    structured DNF shape {"dnf": true, "reason": ...} becomes a note,
    and anything else (legacy bare strings) is flagged verbatim — the
    ledger never parses prose to classify an entry."""
    rec = ledger._base_record("BENCH_synthetic.json", "bench")
    ledger._normalize_bench_parsed(rec, {
        "metric": "m", "value": 1.0, "platform": "tpu",
        "b_sweep": {
            "1024": 39.7,
            "8192": {"dnf": True, "reason": "watchdog fired"},
            "16384": "DNF: legacy prose entry",
        },
    })
    assert rec["metrics"]["b_sweep_1024_sigs_per_sec"] == 39.7
    assert rec["context"]["b_sweep"]["1024"] == 39.7
    assert rec["context"]["b_sweep"]["8192"] == {"dnf": True}
    assert any(
        "b_sweep B=8192 DNF: watchdog fired" in n for n in rec["notes"]
    )
    assert any("unstructured" in n and "16384" in n for n in rec["notes"])
    assert "b_sweep_8192_sigs_per_sec" not in rec["metrics"]
    assert "b_sweep_16384_sigs_per_sec" not in rec["metrics"]


def test_committed_ot_artifact_b_sweep_is_structured():
    """BENCH_TPU_OT.json's B=8192 DNF was migrated to the structured
    shape: it must normalize to a DNF note, not an unstructured flag."""
    rec = ledger.normalize(os.path.join(ROOT, "BENCH_TPU_OT.json"))
    assert rec["metrics"]["b_sweep_4096_sigs_per_sec"] == 72.091
    assert any("b_sweep B=8192 DNF" in n for n in rec["notes"])
    assert not any("unstructured" in n for n in rec["notes"])


def test_cpu_fallback_rounds_never_look_like_chip_records():
    r5 = ledger.normalize(os.path.join(ROOT, "BENCH_r05.json"))
    chip = ledger.normalize(os.path.join(ROOT, "BENCH_TPU_LATEST.json"))
    assert r5["degraded"] and not chip["degraded"]
    assert r5["fingerprint"] != chip["fingerprint"]
    # the stale-fallback rider is noted, and its chip number did NOT
    # become this record's metric
    assert any("last_tpu_measurement" in n for n in r5["notes"])
    assert r5["metrics"]["secp256k1_2of3_gg18_sigs_per_sec"] < 1.0


def test_soak_without_env_stamp_groups_as_unstamped():
    rec = ledger.normalize(os.path.join(ROOT, "SOAK_r01.json"))
    assert rec["kind"] == "soak"
    assert rec["fingerprint"].endswith("/unstamped")
    assert rec["metrics"]["sigs_per_s"] > 0
    assert "latency_overall_p99_ms" in rec["metrics"]
    assert rec["context"]["accounting_ok"] is True


def test_soak_with_env_stamp_groups_by_platform(tmp_path):
    doc = {
        "throughput": {"duration_s": 10.0, "sigs_per_s": 5.0,
                       "sigs_per_s_under_slo": 4.0, "slo_hit_rate": 0.8},
        "outcomes": {"submitted": 50, "succeeded": 50, "shed": 0,
                     "failed": 0, "retries": 0},
        "latency_ms": {"overall": {"p50": 100.0, "p99": 900.0}},
        "accounting_ok": True,
        "env": env_fingerprint(),
    }
    p = tmp_path / "SOAK_r99.json"
    p.write_text(json.dumps(doc))
    rec = ledger.normalize(str(p))
    assert not rec["fingerprint"].endswith("/unstamped")
    assert rec["platform"] == doc["env"]["platform"]


def test_multichip_ok_vs_failed():
    r1 = ledger.normalize(os.path.join(ROOT, "MULTICHIP_r01.json"))
    r2 = ledger.normalize(os.path.join(ROOT, "MULTICHIP_r02.json"))
    assert r1["metrics"]["dryrun_ok"] == 0.0 and r1["degraded"]
    assert r2["metrics"]["dryrun_ok"] == 1.0 and not r2["degraded"]


def test_history_roundtrip_and_determinism(tmp_path):
    records = ledger.build_history(ROOT)
    assert len(records) >= 13
    path = str(tmp_path / "hist.jsonl")
    ledger.write_history(records, path)
    assert ledger.load_history(path) == records
    # a second build is byte-identical: no wall clock, no host state
    again = ledger.build_history(ROOT)
    assert again == records


def test_group_by_fingerprint_segregates_degraded_from_chip():
    groups = ledger.group_by_fingerprint(ledger.build_history(ROOT))
    for key, recs in groups.items():
        kinds = {r["degraded"] for r in recs if r["kind"] == "bench"}
        # within one bench fingerprint group, degraded status is uniform
        # (a chip trend never averages a CPU fallback)
        assert len(kinds) <= 1, key


def test_dashboard_renders_all_sections_deterministically():
    records = ledger.build_history(ROOT)
    d1 = report.render_dashboard(records)
    d2 = report.render_dashboard(records)
    assert d1 == d2
    for heading in ("## Flagship trajectory — on-chip",
                    "## Bench rounds — degraded / DNF",
                    "## Soak (serving under SLO)",
                    "## Multichip dryruns"):
        assert heading in d1
    # the degraded table and the chip table never share a row
    assert "BENCH_r05.json" in d1 and "BENCH_TPU_LATEST.json" in d1


def test_counter_track_merges_into_valid_chrome_trace():
    records = ledger.build_history(ROOT)
    extra = report.counter_track(records)
    assert any(e["ph"] == "C" for e in extra)
    assert all(e["pid"] == report.COUNTER_PID
               for e in extra if e["ph"] == "C")
    spans = [{
        "name": "phase:x", "trace_id": "t" * 16, "span_id": "s" * 16,
        "parent_id": None, "node": "node0", "tid": "main",
        "t0_ns": 0, "t1_ns": 1000, "kind": "X", "attrs": {},
    }]
    doc = chrome_trace({"node0": (spans, 0)}, extra_events=extra)
    n = validate_chrome(doc)
    assert n == len(doc["traceEvents"])
    # degraded bench records contribute NO counter samples
    degraded_sources = {r["source"] for r in records
                        if r["kind"] == "bench" and r["degraded"]}
    assert degraded_sources  # the committed set has them
    chip_points = [e for e in extra if e["ph"] == "C"]
    bench_chip = [r for r in records
                  if r["kind"] == "bench" and not r["degraded"]]
    assert len(chip_points) >= len(bench_chip)
