"""Cluster-level batched DKG + resharing through the scheduler (VERDICT r3
item 5): concurrent wallet-creation / rotation requests coalesce into few
engine dispatches; results flow through the normal client queues."""
import secrets
import threading
import time

import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu import wire
from mpcium_tpu.cluster import LocalCluster, load_test_preparams
from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.protocol.base import ProtocolError


def _poll_share(load, good, timeout_s=120.0):
    """Poll ``load`` until ``good(share)`` or the consistency window
    closes. Only the missing-share ProtocolError retries — any other
    exception (corrupt persistence) surfaces immediately. Returns the
    last loaded share; the caller's asserts do the final judging."""
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            share = load()
        except ProtocolError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
            continue
        if good(share) or time.monotonic() > deadline:
            return share
        time.sleep(0.5)

N_WALLETS = 4


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = LocalCluster(
        n_nodes=3,
        threshold=1,
        root_dir=str(tmp_path_factory.mktemp("bkg")),
        preparams=load_test_preparams(),
        batch_signing=True,
        batch_window_s=0.25,
        reply_timeout_s=60.0,
    )
    for ec in c.consumers:
        ec.scheduler.manifest_timeout_s = 600.0  # cold-cache compiles
    yield c
    c.close()


def test_batched_wallet_creation_coalesces(cluster):
    n = N_WALLETS
    results = {}
    done = threading.Event()

    def on_result(ev):
        results[ev.wallet_id] = ev
        if len(results) == n:
            done.set()

    start_batches = sum(ec.scheduler.batches_run for ec in cluster.consumers)
    sub = cluster.client.on_wallet_creation_result(on_result)
    try:
        for w in range(n):
            cluster.client.create_wallet(f"bkgw{w}")
        assert done.wait(1800), f"only {len(results)}/{n} wallets created"
    finally:
        sub.unsubscribe()

    for wid, ev in results.items():
        assert ev.result_type == wire.RESULT_SUCCESS, (
            f"{wid}: {ev.error_reason}"
        )
        # both pubkeys decode and the nodes persisted consistent shares.
        # The success event is published by whichever node's batch
        # finishes FIRST; a slower follower may still be persisting its
        # shares (signing tolerates this via NotEnoughParticipants
        # retry), so poll briefly instead of asserting instantly.
        hm.secp_decompress(bytes.fromhex(ev.ecdsa_pub_key))
        assert len(bytes.fromhex(ev.eddsa_pub_key)) == 32
        for node in cluster.nodes.values():
            for kt in ("secp256k1", "ed25519"):
                share = _poll_share(
                    lambda: node.load_share(kt, wid), lambda s: True
                )
                assert share.threshold == 1
    # one batched-DKG dispatch pair per node, not one per wallet
    end_batches = sum(ec.scheduler.batches_run for ec in cluster.consumers)
    per_node = (end_batches - start_batches) / len(cluster.consumers)
    assert per_node <= 2, f"expected ≤2 keygen batches/node, got {per_node}"

    # the batch-created wallets sign (ed25519 fast path)
    tx = secrets.token_bytes(32)
    ev = cluster.sign_sync(
        wire.SignTxMessage(
            key_type="ed25519", wallet_id="bkgw0",
            network_internal_code="sol", tx_id="bkg-tx0", tx=tx,
        ),
        timeout_s=900,
    )
    assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
    assert hm.ed25519_verify(
        bytes.fromhex(results["bkgw0"].eddsa_pub_key), tx,
        bytes.fromhex(ev.signature),
    )


def test_batched_resharing_coalesces(cluster):
    """Rotate two batch-created ed25519+secp wallets 1-of-3 → 2-of-3 in one
    batched re-deal per curve; signing still works after."""
    wallets = ["bkgw1", "bkgw2"]
    results = {}
    done = threading.Event()
    want = {(w, kt) for w in wallets for kt in ("ed25519",)}

    def on_result(ev):
        results[(ev.wallet_id, ev.key_type)] = ev
        if set(results) >= want:
            done.set()

    start_batches = sum(ec.scheduler.batches_run for ec in cluster.consumers)
    sub = cluster.client.on_resharing_result(on_result)
    try:
        for w in wallets:
            cluster.client.resharing(w, 2, "ed25519")
        assert done.wait(1800), f"reshare results: {set(results)}"
    finally:
        sub.unsubscribe()
    for k, ev in results.items():
        assert ev.result_type == wire.RESULT_SUCCESS, (
            f"{k}: {ev.error_reason}"
        )
    end_batches = sum(ec.scheduler.batches_run for ec in cluster.consumers)
    per_node = (end_batches - start_batches) / len(cluster.consumers)
    assert per_node <= 1.5, f"expected ≤1 reshare batch/node, got {per_node}"

    # the success event comes from the FIRST node to finish; poll for
    # the slower nodes' rotated shares (same eventual-consistency
    # window as wallet creation above — here the OLD epoch-0 share
    # still loads, so poll on the epoch, not on existence)
    for node in cluster.nodes.values():
        share = _poll_share(
            lambda: node.load_share("ed25519", "bkgw1"),
            lambda s: s.epoch == 1,
        )
        assert share.epoch == 1 and share.threshold == 2

    tx = secrets.token_bytes(32)
    ev = cluster.sign_sync(
        wire.SignTxMessage(
            key_type="ed25519", wallet_id="bkgw1",
            network_internal_code="sol", tx_id="bkg-tx1", tx=tx,
        ),
        timeout_s=900,
    )
    assert ev.result_type == wire.RESULT_SUCCESS, ev.error_reason
