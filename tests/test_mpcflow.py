"""Tier-1 mpcflow gate: both dataflow analyses over the whole package.

This is ``make check``'s mpcflow stage as a test: any non-baselined
taint/residency finding fails, any stale baseline entry fails, the
committed HOST_TRANSFER_BUDGET.json must match the sweep exactly, and
the sweep must stay fast enough to live in tier-1. The budget's
remaining tracked debt — the two Paillier host-modexp sites, after the
device hash suite retired the IKNP OT host stage and the Ed25519 host
SHA-512 round-trip — is asserted exactly: if an edit makes a site
intentional, removes it, or adds new debt, this test forces the
bookkeeping (baseline + ROADMAP) to move in the same commit.
"""
from __future__ import annotations

import json
import time
from pathlib import Path

import pytest

from mpcium_tpu.analysis import load_baseline
from mpcium_tpu.analysis.baseline import DEFAULT_BASELINE
from mpcium_tpu.analysis.flow import build_budget, run_flow

pytestmark = pytest.mark.lint

ROOT = Path(__file__).resolve().parents[1]
BUDGET_PATH = ROOT / "HOST_TRANSFER_BUDGET.json"


@pytest.fixture(scope="module")
def sweep():
    t0 = time.monotonic()
    result, sites = run_flow(root=ROOT)
    elapsed = time.monotonic() - t0
    return result, sites, elapsed


def test_package_parses_clean(sweep):
    result, _sites, _elapsed = sweep
    assert not result.parse_errors, result.parse_errors
    assert result.files_scanned > 60


def test_no_new_findings_no_stale_entries(sweep):
    result, _sites, _elapsed = sweep
    baseline = load_baseline(ROOT / DEFAULT_BASELINE)
    # MPF scope: stale MPL entries are test_mpclint's business
    new, _grandfathered, stale = baseline.split(
        result.findings, scope=("MPF",)
    )
    assert not new, "non-baselined dataflow findings:\n" + "\n".join(
        f.render() for f in new
    )
    assert not stale, (
        "stale mpcflow baseline entries (the baseline only shrinks):\n"
        + "\n".join(stale)
    )


def test_sweep_is_tier1_fast(sweep):
    _result, _sites, elapsed = sweep
    # ~5s on the CI box for both analyses; 30s keeps it honest under load
    assert elapsed < 30, f"mpcflow sweep took {elapsed:.1f}s"


def test_budget_matches_committed_json(sweep):
    _result, sites, _elapsed = sweep
    assert BUDGET_PATH.exists(), (
        "HOST_TRANSFER_BUDGET.json missing — run scripts/mpcflow_budget.py"
    )
    committed = json.loads(BUDGET_PATH.read_text())
    assert committed == build_budget(sites), (
        "HOST_TRANSFER_BUDGET.json drifted from the sweep — regenerate "
        "with scripts/mpcflow_budget.py and review the diff"
    )


def _tracked(budget, phase):
    return {
        (s["path"], s["symbol"], s["detail"])
        for s in budget["phases"][phase]["sites"]
        if not s["intentional"]
    }


def test_budget_tracks_the_known_host_walls():
    budget = json.loads(BUDGET_PATH.read_text())
    # The device hash suite retired the IKNP OT host stage and the
    # Ed25519 host SHA-512 round-trip: those phases carry NO tracked
    # debt (the fallback paths are annotated intentional).
    assert _tracked(budget, "ecdsa.mta_ot") == set()
    assert _tracked(budget, "eddsa.sign") == set()
    # The only remaining wall: Paillier host modexp in the range-proof
    # batcher (ROADMAP item 2's last leg — device multi-word modmul).
    assert _tracked(budget, "ecdsa.sign") == {
        (
            "mpcium_tpu/engine/gg18_batch.py",
            "_host_pow_single",
            "x_limbs",
        ),
        (
            "mpcium_tpu/engine/gg18_batch.py",
            "_host_pow_batch",
            "x_limbs",
        ),
    }
    # and nothing anywhere else: tracked debt is exactly 2
    total = sum(
        ph["tracked"] for ph in budget["phases"].values()
    )
    assert total == 2, f"tracked debt drifted: {total} != 2"


def test_tracked_debt_is_baselined_with_an_exit():
    """Every tracked budget site corresponds to a baseline entry whose
    justification names its exit (wire boundary or ROADMAP item)."""
    budget = json.loads(BUDGET_PATH.read_text())
    baseline = load_baseline(ROOT / DEFAULT_BASELINE)
    mpf = {
        fp: j for fp, j in baseline.entries.items() if fp.startswith("MPF8")
    }
    for phase, ph in budget["phases"].items():
        for s in ph["sites"]:
            if s["intentional"]:
                continue
            fp = (
                f"MPF801:{s['path']}:{s['symbol']}:"
                f"{s['kind']}:{s['detail']}"
            )
            assert fp in mpf, f"tracked site not baselined: {fp} ({phase})"
            assert "wire boundary" in mpf[fp] or "ROADMAP" in mpf[fp], fp
