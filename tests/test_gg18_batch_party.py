"""BatchedECDSASigningParty: the distributed batched GG18 protocol,
driven transport-free (the secp256k1 analogue of
tests/test_batch_signing_party.py — 9 wire rounds, per-lane ok masks).

Runs via a subprocess wrapper (same machinery as test_batch_dkg_party):
the round-5 live-migrated host deterministically SEGFAULTs in XLA:CPU
codegen compiling the distributed-GG18-party graphs — fresh process,
MPCIUM_TESTS_NO_CACHE=1 — while the engine-level GG18 suites pass.
Isolation keeps the crash from killing the whole pytest process, and
MPCIUM_XFAIL_XLA_CRASH=1 (opt-in, known-bad hosts only) downgrades it
to xfail; everything is green where XLA:CPU is healthy."""
import os
import secrets

import pytest

pytestmark = pytest.mark.slow

from conftest import run_isolated

_INNER = os.environ.get("MPCIUM_GG18_PARTY_INNER")


def test_two_party_batch_isolated():
    if _INNER:
        pytest.skip("wrapper entry; inner run executes the real test")
    run_isolated(
        __file__, "test_two_party_batch_signs_and_verifies",
        "MPCIUM_GG18_PARTY_INNER",
    )


from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.engine import gg18_batch as gb
from mpcium_tpu.protocol.base import ProtocolError
from mpcium_tpu.protocol.ecdsa.batch_signing import (
    BatchedECDSASigningParty, quorum_material_digest,
)
from mpcium_tpu.protocol.runner import run_protocol

TEST_DOM = gb.Domains(alpha=600, beta_prime=320, gamma_bob=600)


@pytest.fixture(scope="module")
def small_preparams():
    from mpcium_tpu.cluster import load_test_preparams

    return load_test_preparams(bits=1024)


@pytest.mark.skipif(not _INNER, reason="runs via the subprocess wrapper")
def test_two_party_batch_signs_and_verifies(small_preparams):
    ids = ["node0", "node1"]
    B = 2
    shares = gb.dealer_keygen_secp_batch(
        B, ids, threshold=1, preparams=small_preparams
    )
    digests = [secrets.token_bytes(32) for _ in range(B)]
    parties = {
        pid: BatchedECDSASigningParty(
            "gbs-1", pid, ids, shares[i], digests, dom=TEST_DOM
        )
        for i, pid in enumerate(ids)
    }
    run_protocol(parties)
    for pid, p in parties.items():
        assert p.result["ok"].all(), f"{pid}: {p.result['ok']}"
        for w in range(B):
            pub = hm.secp_decompress(shares[0][w].public_key)
            r = int.from_bytes(p.result["r"][w].tobytes(), "big")
            s = int.from_bytes(p.result["s"][w].tobytes(), "big")
            d = int.from_bytes(digests[w], "big")
            assert s <= gb.Q // 2
            assert hm.ecdsa_verify(pub, d, r, s), f"{pid} wallet {w}"


def test_material_digest_agrees_across_quorum(small_preparams):
    ids = ["node0", "node1", "node2"]
    shares = gb.dealer_keygen_secp_batch(
        1, ids, threshold=1, preparams=small_preparams
    )
    digs = {quorum_material_digest(shares[i][0]) for i in range(3)}
    assert len(digs) == 1 and "" not in digs


def test_mixed_material_rejected(small_preparams):
    ids = ["node0", "node1"]
    s_a = gb.dealer_keygen_secp_batch(
        1, ids, threshold=1, preparams=small_preparams
    )
    # wallet from a different aux generation (node2's preparams as node0's)
    other = {
        "node0": small_preparams["node2"],
        "node1": small_preparams["node1"],
    }
    s_b = gb.dealer_keygen_secp_batch(1, ids, threshold=1, preparams=other)
    with pytest.raises(ProtocolError, match="mixed Paillier material"):
        BatchedECDSASigningParty(
            "gbs-mix", "node0", ids, [s_a[0][0], s_b[0][0]],
            [b"\x01" * 32, b"\x02" * 32], dom=TEST_DOM,
        )
