"""utils.metrics: the process-local counters/gauges/histograms behind the
scheduler's SLO observability (no cluster, no engine)."""
import threading

import pytest

from mpcium_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)


def test_counter_monotonic_and_threadsafe():
    c = Counter("c")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)

    threads = [
        threading.Thread(target=lambda: [c.inc() for _ in range(1000)])
        for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 5 + 4000


def test_gauge_set_inc_dec():
    g = Gauge("g")
    assert g.value == 0.0
    g.set(7)
    g.inc(3)
    g.dec(4)
    assert g.value == 6.0


def test_histogram_percentiles_and_summary():
    h = Histogram("h")
    for v in range(1, 101):  # 1..100
        h.observe(v)
    assert h.count == 100
    assert h.sum == sum(range(1, 101))
    assert h.min == 1 and h.max == 100
    assert h.percentile(50) == pytest.approx(50, abs=1)
    assert h.percentile(99) == pytest.approx(99, abs=1)
    s = h.summary()
    assert s["count"] == 100
    assert s["p50"] == pytest.approx(50, abs=1)
    assert s["p99"] == pytest.approx(99, abs=1)
    assert s["mean"] == pytest.approx(50.5)


def test_histogram_reservoir_bounded():
    h = Histogram("h", reservoir=64)
    for v in range(10_000):
        h.observe(v)
    # exact aggregates survive the bounded reservoir…
    assert h.count == 10_000
    assert h.max == 9_999 and h.min == 0
    # …while percentiles come from the most recent window
    assert h.percentile(50) >= 9_000


def test_registry_reuses_and_type_checks():
    r = MetricsRegistry()
    c = r.counter("x.total")
    assert r.counter("x.total") is c
    with pytest.raises(TypeError):
        r.gauge("x.total")
    r.gauge("x.depth").set(3)
    r.histogram("x.lat").observe(0.25)

    snap = r.snapshot()
    assert snap["counters"]["x.total"] == 0.0
    assert snap["gauges"]["x.depth"] == 3.0
    assert snap["histograms"]["x.lat"]["count"] == 1
    # snapshots are plain JSON-serializable data
    import json

    json.dumps(snap)
