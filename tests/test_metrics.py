"""utils.metrics: the process-local counters/gauges/histograms behind the
scheduler's SLO observability (no cluster, no engine)."""
import threading

import pytest

from mpcium_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)


def test_counter_monotonic_and_threadsafe():
    c = Counter("c")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)

    threads = [
        threading.Thread(target=lambda: [c.inc() for _ in range(1000)])
        for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 5 + 4000


def test_gauge_set_inc_dec():
    g = Gauge("g")
    assert g.value == 0.0
    g.set(7)
    g.inc(3)
    g.dec(4)
    assert g.value == 6.0


def test_histogram_percentiles_and_summary():
    h = Histogram("h")
    for v in range(1, 101):  # 1..100
        h.observe(v)
    assert h.count == 100
    assert h.sum == sum(range(1, 101))
    assert h.min == 1 and h.max == 100
    assert h.percentile(50) == pytest.approx(50, abs=1)
    assert h.percentile(99) == pytest.approx(99, abs=1)
    s = h.summary()
    assert s["count"] == 100
    assert s["p50"] == pytest.approx(50, abs=1)
    assert s["p99"] == pytest.approx(99, abs=1)
    assert s["mean"] == pytest.approx(50.5)


def test_histogram_reservoir_bounded():
    h = Histogram("h", reservoir=64)
    for v in range(10_000):
        h.observe(v)
    # exact aggregates survive the bounded reservoir…
    assert h.count == 10_000
    assert h.max == 9_999 and h.min == 0
    # …while percentiles come from the most recent window
    assert h.percentile(50) >= 9_000


def test_registry_reuses_and_type_checks():
    r = MetricsRegistry()
    c = r.counter("x.total")
    assert r.counter("x.total") is c
    with pytest.raises(TypeError):
        r.gauge("x.total")
    r.gauge("x.depth").set(3)
    r.histogram("x.lat").observe(0.25)

    snap = r.snapshot()
    assert snap["counters"]["x.total"] == 0.0
    assert snap["gauges"]["x.depth"] == 3.0
    assert snap["histograms"]["x.lat"]["count"] == 1
    # snapshots are plain JSON-serializable data
    import json

    json.dumps(snap)


def test_summary_single_sort_matches_percentile():
    # summary() computes all three quantiles from ONE sorted copy; it
    # must agree with the per-call percentile() path exactly
    h = Histogram("h")
    for v in [5, 1, 9, 3, 7, 2, 8, 4, 6, 10]:
        h.observe(v)
    s = h.summary()
    assert s["p50"] == h.percentile(50)
    assert s["p90"] == h.percentile(90)
    assert s["p99"] == h.percentile(99)
    assert s["min"] == 1 and s["max"] == 10


def test_summary_empty_histogram():
    s = Histogram("h").summary()
    assert s["count"] == 0
    assert s["p50"] is None and s["p90"] is None and s["p99"] is None
    assert s["mean"] is None


def test_to_prometheus_exposition_format():
    r = MetricsRegistry()
    r.counter("scheduler.shed_total").inc(7)
    r.gauge("scheduler.queue_depth").set(3)
    h = r.histogram("scheduler.latency_s")
    for v in range(1, 101):
        h.observe(v / 100.0)

    text = r.to_prometheus(labels={"node": "node0"})
    lines = text.splitlines()
    assert text.endswith("\n")
    assert "# TYPE scheduler_shed_total counter" in lines
    assert 'scheduler_shed_total{node="node0"} 7.0' in lines
    assert "# TYPE scheduler_queue_depth gauge" in lines
    assert 'scheduler_queue_depth{node="node0"} 3.0' in lines
    assert "# TYPE scheduler_latency_s summary" in lines
    assert 'scheduler_latency_s{node="node0",quantile="0.5"} 0.5' in lines
    assert 'scheduler_latency_s_count{node="node0"} 100' in lines
    assert 'scheduler_latency_s_sum{node="node0"} 50.5' in lines
    # names are prometheus-safe: no dots survive
    for ln in lines:
        if not ln.startswith("#"):
            assert "." not in ln.split("{")[0].split(" ")[0]


def test_to_prometheus_no_labels_and_empty_registry():
    r = MetricsRegistry()
    assert r.to_prometheus() == ""
    r.counter("a").inc()
    text = r.to_prometheus()
    assert "a 1.0" in text.splitlines()


def test_to_prometheus_label_escaping():
    r = MetricsRegistry()
    r.counter("c").inc()
    text = r.to_prometheus(labels={"node": 'we"ird\nname'})
    assert 'node="we\\"ird\\nname"' in text
