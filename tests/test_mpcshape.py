"""Tier-1 mpcshape gate: the compile-surface analysis over the whole
package.

This is ``make shapecheck`` as a test: any non-baselined MPS9xx finding
fails, any stale MPS baseline entry fails, the committed
COMPILE_SURFACE.json must match the sweep exactly, every engine's
signature set must be finite (no un-annotated unbounded dims — the
precondition for ROADMAP-item-4 AOT pre-warming), and the sweep must
stay fast enough to live in tier-1. The committed bench artifacts are
cross-checked against the surface: every compile signature a committed
BENCH record implies must be one the static analysis predicted.
"""
from __future__ import annotations

import json
import time
from pathlib import Path

import pytest

from mpcium_tpu.analysis import load_baseline
from mpcium_tpu.analysis.baseline import DEFAULT_BASELINE
from mpcium_tpu.analysis.shape import render, run_shape, shape_predicted

pytestmark = pytest.mark.lint

ROOT = Path(__file__).resolve().parents[1]
SURFACE_PATH = ROOT / "COMPILE_SURFACE.json"

# every engine that calls compile_watch.begin today; a new engine must
# appear here AND in the regenerated surface in the same commit
EXPECTED_ENGINES = {
    "gg18.sign", "eddsa.sign", "dkg.run", "reshare.run",
    "party.ecdsa", "party.eddsa", "party.dkg", "party.reshare",
}


@pytest.fixture(scope="module")
def sweep():
    t0 = time.monotonic()
    result, surface = run_shape(root=ROOT)
    elapsed = time.monotonic() - t0
    return result, surface, elapsed


def test_package_parses_clean(sweep):
    result, _surface, _elapsed = sweep
    assert not result.parse_errors, result.parse_errors
    assert result.files_scanned > 60


def test_no_new_findings_no_stale_entries(sweep):
    result, _surface, _elapsed = sweep
    baseline = load_baseline(ROOT / DEFAULT_BASELINE)
    # MPS scope: stale MPL/MPF entries are the other gates' business
    new, _grandfathered, stale = baseline.split(
        result.findings, scope=("MPS",)
    )
    assert not new, "non-baselined compile-surface findings:\n" + "\n".join(
        f.render() for f in new
    )
    assert not stale, (
        "stale mpcshape baseline entries (the baseline only shrinks):\n"
        + "\n".join(stale)
    )


def test_sweep_is_tier1_fast(sweep):
    _result, _surface, elapsed = sweep
    assert elapsed < 30, f"mpcshape sweep took {elapsed:.1f}s"


def test_surface_matches_committed_json(sweep):
    _result, surface, _elapsed = sweep
    assert SURFACE_PATH.exists(), (
        "COMPILE_SURFACE.json missing — run scripts/mpcshape_surface.py"
    )
    assert SURFACE_PATH.read_text() == render(surface), (
        "COMPILE_SURFACE.json drifted from the sweep — regenerate with "
        "scripts/mpcshape_surface.py and review the diff"
    )


def test_every_engine_signature_set_is_finite(sweep):
    _result, surface, _elapsed = sweep
    assert set(surface["engines"]) == EXPECTED_ENGINES
    infinite = [
        (eng, rec["template"])
        for eng, recs in surface["engines"].items()
        for rec in recs
        if not rec["finite"]
    ]
    assert not infinite, (
        "engines with unbounded un-annotated signature dims (the AOT "
        f"pre-warmer cannot enumerate them): {infinite}"
    )
    assert surface["counts"]["finite"] is True


def test_surface_is_line_number_free(sweep):
    """Unrelated edits must not churn the committed artifact."""
    _result, surface, _elapsed = sweep
    def walk(obj):
        if isinstance(obj, dict):
            for k, v in obj.items():
                assert k not in ("line", "lineno"), f"line number under {k}"
                walk(v)
        elif isinstance(obj, list):
            for v in obj:
                walk(v)
    walk(surface)


def test_jit_inventory_covers_known_entry_points(sweep):
    _result, surface, _elapsed = sweep
    symbols = {e["symbol"] for e in surface["jit_entries"]}
    # spot anchors across the jit-bearing modules: a decorated engine
    # kernel, a partial(jax.jit) with statics, and a wrapped assignment
    assert "_commit_phase" in symbols  # engine/dkg_batch.py
    assert any(s.startswith("_blk_") for s in symbols)  # gg18_batch.py
    assert surface["counts"]["jit_entries"] >= 50


def _bench_shapes():
    """(engine, shape) pairs the committed bench artifacts imply, using
    bench.py's own construction: gg18 signs with quorum ids[:2]; the
    secondary suite runs ed25519 at max(B, 4096), DKG over all 3 ids at
    threshold 1 on secp256k1, and a 2-of-3 → 3-of-5 reshare at B//4."""
    shapes = []
    for name in ("BENCH_TPU_LATEST.json", "BENCH_TPU_OT.json"):
        p = ROOT / name
        if not p.exists():
            continue
        doc = json.loads(p.read_text())
        b, mta = doc.get("batch"), doc.get("mta")
        if not isinstance(b, int) or not isinstance(mta, str):
            continue
        shapes.append(("gg18.sign", f"B{b}|q2|mta={mta}"))
        be = max(b, 4096) if b >= 256 else b
        shapes.append(("eddsa.sign", f"B{be}|q2"))
        shapes.append(("dkg.run", f"B{b}|q3|secp256k1"))
        shapes.append(("reshare.run", f"B{max(b // 4, 1)}|secp256k1|t2"))
    return shapes


def test_committed_bench_artifacts_are_predicted(sweep):
    _result, surface, _elapsed = sweep
    shapes = _bench_shapes()
    assert shapes, "no committed bench artifacts with batch/mta context"
    unpredicted = [
        (eng, shape)
        for eng, shape in shapes
        if not shape_predicted(surface, eng, shape)
    ]
    assert not unpredicted, (
        "committed bench records imply compile signatures the static "
        f"surface does not predict (analysis gap): {unpredicted}"
    )


def test_committed_compile_ledgers_are_predicted(sweep):
    """Every compile entry in any committed COMPILE_LEDGER.json must map
    to a predicted signature (none are committed today — the test is the
    contract for when one lands)."""
    _result, surface, _elapsed = sweep
    for p in ROOT.glob("**/COMPILE_LEDGER.json"):
        if "__pycache__" in str(p) or ".jax_cache" in str(p):
            continue
        doc = json.loads(p.read_text())
        for e in doc.get("entries", []):
            assert shape_predicted(surface, e["engine"], e["shape"]), (
                f"{p}: ledgered compile ({e['engine']}, {e['shape']}) "
                "is not on the static surface"
            )
