"""utils.tracing + trace.recorder: span model, the disabled fast path,
attribute hygiene, the engine PhaseTimer gate, and the flight recorder's
bounds (no cluster, no engine)."""
import threading

import pytest

from mpcium_tpu.trace import recorder
from mpcium_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _tracing_off():
    """Every test starts and ends with tracing disabled and clean
    recorder state — armed tracing must never leak between tests."""
    tracing.disable()
    recorder.reset()
    recorder.set_dump_dir(None)
    yield
    tracing.disable()
    recorder.reset()
    recorder.set_dump_dir(None)


# -- span model ---------------------------------------------------------------


def test_span_parent_and_trace_inheritance():
    spans = []
    tracing.enable(sink=spans.append)
    with tracing.span("outer", trace_id="abc", node="node0", tid="s1") as o:
        with tracing.span("inner") as i:
            assert i.trace_id == "abc"
            assert i.parent_id == o.span_id
        # nested spans inherit the enclosing node/tid ("local"/"main"
        # are the unset sentinels)
    assert [s["name"] for s in spans] == ["inner", "outer"]
    inner, outer = spans
    assert inner["node"] == "node0" and inner["tid"] == "s1"
    assert outer["parent_id"] is None
    assert inner["t1_ns"] >= inner["t0_ns"]


def test_span_ids_deterministic_no_entropy():
    # trace ids are keyed hashes of public names: every node derives the
    # same id for the same session without coordination
    assert tracing.trace_id_for("sess-1") == tracing.trace_id_for("sess-1")
    assert tracing.trace_id_for("sess-1") != tracing.trace_id_for("sess-2")
    assert len(tracing.trace_id_for("x")) == 16


def test_span_error_attribute_on_exception():
    spans = []
    tracing.enable(sink=spans.append)
    with pytest.raises(ValueError):
        with tracing.span("boom"):
            raise ValueError("nope")
    assert spans[0]["attrs"]["error"] == "ValueError"


def test_unknown_span_kwargs_become_attrs():
    spans = []
    tracing.enable(sink=spans.append)
    with tracing.span("s", sender="node1", n=3):
        pass
    assert spans[0]["attrs"] == {"sender": "node1", "n": 3}


def test_emit_retroactive_and_instant():
    spans = []
    tracing.enable(sink=spans.append)
    tracing.emit("queue", 100, 200, node="n0", tid="lane:bulk", outcome="shed")
    tracing.instant("intake", node="n0", tid="lane:bulk")
    assert spans[0]["t0_ns"] == 100 and spans[0]["t1_ns"] == 200
    assert spans[0]["attrs"]["outcome"] == "shed"
    assert spans[1]["kind"] == "i"
    assert spans[1]["t0_ns"] == spans[1]["t1_ns"]


def test_current_ids_and_wire_context():
    tracing.enable()
    assert tracing.current_ids() is None
    assert tracing.wire_context() is None
    with tracing.span("s", trace_id="t1") as s:
        assert tracing.current_ids() == ("t1", s.span_id)
        assert tracing.wire_context() == {"t": "t1", "s": s.span_id}
    assert tracing.wire_context() is None


def test_thread_local_stacks_do_not_cross():
    tracing.enable()
    seen = {}

    def other():
        seen["ids"] = tracing.current_ids()

    with tracing.span("main-span"):
        t = threading.Thread(target=other)
        t.start()
        t.join()
    assert seen["ids"] is None


# -- disabled fast path -------------------------------------------------------


def test_disabled_everything_is_noop():
    assert not tracing.enabled()
    s = tracing.span("x", anything="goes")
    assert s is tracing.NOOP_SPAN
    with s:
        s.set(a=1)
    assert tracing.current_ids() is None
    assert tracing.wire_context() is None
    # emit/instant/incident return before building anything
    tracing.emit("x", 0, 1)
    tracing.instant("x")
    tracing.incident("x")


def test_disabled_span_is_shared_singleton():
    assert tracing.span("a") is tracing.span("b")


# -- attribute hygiene --------------------------------------------------------


def test_clean_attrs_refuses_secret_names():
    out = tracing.clean_attrs({
        "nonce_share": "deadbeef", "secret_key": 1, "batch": 4,
    })
    assert out["nonce_share"] == "<refused:secret-name>"
    assert out["secret_key"] == "<refused:secret-name>"
    assert out["batch"] == 4


def test_clean_attrs_reduces_objects_to_type_names():
    class Opaque:
        pass

    out = tracing.clean_attrs({"thing": Opaque(), "xs": [1, 2]})
    assert out["thing"] == "<obj:Opaque>"
    assert out["xs"] == "<obj:list>"


def test_declassify_requires_reason_and_unblocks_name():
    with pytest.raises(ValueError):
        tracing.declassify_attr("seed_label", "")
    tracing.declassify_attr("seed_label", "chaos replay handle, not key material")
    try:
        out = tracing.clean_attrs({"seed_label": 7})
        assert out["seed_label"] == 7
        assert "seed_label" in tracing.declassified_attrs()
    finally:
        tracing._DECLASSIFIED_ATTRS.pop("seed_label", None)


def test_span_attrs_are_screened_at_record_time():
    spans = []
    tracing.enable(sink=spans.append)
    with tracing.span("s", priv_key="oops"):
        pass
    assert spans[0]["attrs"]["priv_key"] == "<refused:secret-name>"


# -- PhaseTimer ---------------------------------------------------------------


def test_phase_timer_disabled_never_syncs():
    syncs = []
    pt = tracing.PhaseTimer("eng", syncs.append)
    assert not pt.on
    pt.mark("phase1", object())
    assert syncs == []


def test_phase_timer_legacy_dict_without_tracing():
    syncs = []
    phases = {}
    pt = tracing.PhaseTimer("eng", lambda ts: syncs.append(ts),
                            phase_times=phases)
    assert pt.on
    pt.mark("r1", "tensor")
    pt.mark("r2", "tensor", host=0.5, chunks=3.0, label="x")
    assert len(syncs) == 2
    assert set(phases) == {"r1", "r2", "r2_host", "r2_chunks"}
    assert phases["r2_host"] == 0.5 and phases["r2_chunks"] == 3.0
    assert phases["r1"] >= 0.0


def test_phase_timer_spans_and_phase_share_roundtrip():
    spans = []
    tracing.enable(sink=spans.append)
    pt = tracing.PhaseTimer("eng", lambda ts: None, node="engine", tid="e:B4")
    pt.mark("r1")
    pt.mark("r2", host=0.25)
    share = tracing.phase_share(spans)
    assert set(share) == {"r1", "r2", "r2_host"}
    assert share["r2_host"] == 0.25
    assert all(v >= 0.0 for v in share.values())
    assert all(s["node"] == "engine" and s["tid"] == "e:B4" for s in spans)


def test_phase_share_folds_pipeline_host_stages():
    """The cohort pipeline's wire stages (``host:<label>`` spans,
    engine/pipeline._run_host_stage) land in the phase table as
    ``host_<label>`` — without the fold a cohorted run's wire time would
    silently vanish from bench.py's table (ISSUE 17)."""
    spans = []
    tracing.enable(sink=spans.append)
    pt = tracing.PhaseTimer("eng", lambda ts: None, node="engine", tid="e:B4")
    pt.mark("r1")
    tracing.emit("host:sig_egress", 100, 400, node="engine", cohort=0)
    tracing.emit("host:sig_egress", 500, 700, node="engine", cohort=1)
    tracing.emit("queue", 0, 50)  # non-phase spans stay out of the table
    share = tracing.phase_share(spans)
    assert set(share) == {"r1", "host_sig_egress", "host_sig_egress_cohort"}
    # both cohorts' egress stages sum into one table row
    assert share["host_sig_egress"] == pytest.approx((300 + 200) / 1e9)


# -- flight recorder ----------------------------------------------------------


def test_recorder_bounded_with_exact_dropped_count():
    rec = recorder.FlightRecorder("n0", capacity=8)
    for i in range(20):
        rec.record({"name": f"s{i}"})
    spans, dropped = rec.snapshot()
    assert len(spans) == 8
    assert dropped == 12
    assert spans[-1]["name"] == "s19"
    # clear resets both the ring and the counter
    spans, dropped = rec.snapshot(clear=True)
    assert dropped == 12
    assert rec.snapshot() == ([], 0)


def test_record_routes_by_node():
    recorder.record({"name": "a", "node": "node0"})
    recorder.record({"name": "b", "node": "node1"})
    recorder.record({"name": "c", "node": None})
    snap = recorder.snapshot_all()
    assert {n for n in snap} == {"node0", "node1", "local"}
    assert snap["node0"][0][0]["name"] == "a"


def test_reset_named_nodes_only():
    recorder.record({"name": "a", "node": "node0"})
    recorder.record({"name": "b", "node": "node1"})
    recorder.reset(["node0"])
    snap = recorder.snapshot_all()
    assert "node0" not in snap and "node1" in snap


def test_incident_fires_hook_and_dump_is_bounded(tmp_path):
    tracing.enable(sink=recorder.record)
    tracing.set_incident_hook(recorder.dump_incident)
    recorder.set_dump_dir(str(tmp_path))
    for i in range(recorder._DUMP_LIMIT + 5):
        tracing.incident("shed", node="node0", reason="backpressure")
    dumps = sorted(tmp_path.glob("trace_incident_*.json"))
    assert len(dumps) == recorder._DUMP_LIMIT
    import json

    doc = json.loads(dumps[0].read_text())
    assert doc["otherData"]["incident"] == "shed"
    assert any(e["name"] == "incident:shed" for e in doc["traceEvents"])


def test_incident_dump_never_raises_on_bad_dir():
    tracing.enable(sink=recorder.record)
    tracing.set_incident_hook(recorder.dump_incident)
    recorder.set_dump_dir("/proc/definitely/not/writable")
    tracing.incident("shed", node="node0")  # must not raise


# -- utils.log: redaction + trace correlation ---------------------------------


def _capture_json_log():
    import logging

    from mpcium_tpu.utils import log as ulog

    lines = []

    class _H(logging.Handler):
        def emit(self, record):
            lines.append(record.getMessage())

    ulog.init(production=True, level="DEBUG")
    ulog._logger.handlers[:] = [_H()]
    return lines


def test_log_safe_redacts_secret_typed_objects():
    from mpcium_tpu.utils.log import _safe

    class NonceShare:
        def __repr__(self):
            raise AssertionError("repr of secret-typed object must not run")

    class Carrier:
        def __init__(self):
            self.secret_key = 42

        def __repr__(self):
            raise AssertionError("repr of secret-carrying object must not run")

    class Boring:
        def __init__(self):
            self.batch = 4

    assert _safe(NonceShare()) == "<redacted:NonceShare>"
    assert _safe(Carrier()) == "<redacted:Carrier>"
    assert _safe(Boring()).startswith("<")  # plain repr, not redacted
    assert "redacted" not in _safe(Boring())
    # scalars and bytes keep their existing behavior
    assert _safe(b"\x01\x02") == "0102"
    assert _safe("x") == "x" and _safe(3) == 3


def test_log_safe_redacts_slots_carriers():
    from mpcium_tpu.utils.log import _safe

    class SlotCarrier:
        __slots__ = ("pad_bytes",)

        def __repr__(self):
            raise AssertionError("must not repr")

    assert _safe(SlotCarrier()) == "<redacted:SlotCarrier>"


def test_log_records_carry_trace_ids_when_span_open():
    import json as _json

    from mpcium_tpu.utils import log as ulog

    lines = _capture_json_log()
    try:
        tracing.enable()
        ulog.info("before span", x=1)
        with tracing.span("s", trace_id="t" * 16) as s:
            ulog.info("inside span", x=2)
        rec0 = _json.loads(lines[0])
        rec1 = _json.loads(lines[1])
        assert "trace_id" not in rec0
        assert rec1["trace_id"] == "t" * 16
        assert rec1["span_id"] == s.span_id
    finally:
        ulog.init()  # restore default handlers/mode
