"""BatchedDKGParty / BatchedReshareParty: distributed batched wallet
creation + committee rotation, driven transport-free (protocol.batch_dkg;
VERDICT r3 item 5 — the production keygen path).

The DKG→sign and reshare tests run via a subprocess wrapper: on one
observed (post-migration) host, XLA:CPU deterministically segfaults
compiling their graphs — even uncached and in a fresh process. The
wrapper keeps the tests live (they pass unchanged on healthy hosts) and
converts that specific crash into an xfail instead of killing the whole
pytest process.
"""
import os
import secrets

import pytest

pytestmark = pytest.mark.slow

from conftest import run_isolated

_INNER = os.environ.get("MPCIUM_DKG_PARTY_INNER")


def _run_isolated(test_name: str) -> None:
    run_isolated(__file__, test_name, "MPCIUM_DKG_PARTY_INNER")

from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.protocol.base import ProtocolError, party_xs
from mpcium_tpu.protocol.batch_dkg import BatchedDKGParty, BatchedReshareParty
from mpcium_tpu.protocol.runner import run_protocol


@pytest.fixture(scope="module")
def small_preparams():
    from mpcium_tpu.cluster import load_test_preparams

    return load_test_preparams(bits=1024)


def _reconstruct(shares_by_party, wallet, order, t):
    """Lagrange-combine t+1 shares and check against the public key."""
    pts = []
    for p in shares_by_party[: t + 1]:
        s = p[wallet]
        pts.append((s.self_x, s.share))
    xs = [x for x, _ in pts]
    secret = 0
    for x_i, y_i in pts:
        secret = (secret + hm.lagrange_coeff(xs, x_i, order) * y_i) % order
    return secret


def test_batched_dkg_both_curves(small_preparams):
    ids = ["node0", "node1", "node2"]
    B = 3
    for kt, order, mul, compress in (
        ("ed25519", hm.ED_L, None, None),
        ("secp256k1", hm.SECP_N, None, None),
    ):
        parties = {
            pid: BatchedDKGParty(
                f"bdkg-{kt}", pid, ids, 1, kt, B,
                preparams=(
                    small_preparams[pid] if kt == "secp256k1" else None
                ),
                min_paillier_bits=1024,
            )
            for pid in ids
        }
        run_protocol(parties)
        all_shares = [parties[pid].result for pid in ids]
        for w in range(B):
            pubs = {all_shares[i][w].public_key for i in range(3)}
            assert len(pubs) == 1, f"{kt}: pubkey mismatch wallet {w}"
            secret = _reconstruct(all_shares, w, order, t=1)
            if kt == "ed25519":
                expect = hm.ed_compress(hm.ed_mul(secret, hm.ED_B))
            else:
                expect = hm.secp_compress(hm.secp_mul(secret, hm.SECP_G))
            assert expect == all_shares[0][w].public_key, f"{kt} wallet {w}"
        if kt == "secp256k1":
            aux = all_shares[0][0].aux
            assert set(aux["peer_paillier"]) == {"node1", "node2"}
            assert aux["paillier_sk"]


@pytest.mark.skipif(bool(_INNER), reason="already inside the wrapper")
def test_batched_dkg_shares_sign_isolated():
    _run_isolated("test_batched_dkg_shares_sign")


@pytest.mark.skipif(not _INNER, reason="runs via the subprocess wrapper")
def test_batched_dkg_shares_sign(small_preparams):
    """DKG output feeds straight into the batched signing party."""
    from mpcium_tpu.engine import gg18_batch as gb
    from mpcium_tpu.protocol.ecdsa.batch_signing import (
        BatchedECDSASigningParty,
    )

    ids = ["node0", "node1"]
    B = 2
    parties = {
        pid: BatchedDKGParty(
            "bdkg-sign", pid, ids, 1, "secp256k1", B,
            preparams=small_preparams[pid], min_paillier_bits=1024,
        )
        for pid in ids
    }
    run_protocol(parties)
    digests = [secrets.token_bytes(32) for _ in range(B)]
    dom = gb.Domains(alpha=600, beta_prime=320, gamma_bob=600)
    signers = {
        pid: BatchedECDSASigningParty(
            "bdkg-sign-2", pid, ids, parties[pid].result, digests, dom=dom
        )
        for pid in ids
    }
    run_protocol(signers)
    for pid, p in signers.items():
        assert p.result["ok"].all(), f"{pid}: {p.result['ok']}"
        for w in range(B):
            pub = hm.secp_decompress(parties[pid].result[w].public_key)
            assert hm.ecdsa_verify(
                pub,
                int.from_bytes(digests[w], "big"),
                int.from_bytes(p.result["r"][w].tobytes(), "big"),
                int.from_bytes(p.result["s"][w].tobytes(), "big"),
            )


@pytest.mark.skipif(bool(_INNER), reason="already inside the wrapper")
def test_batched_reshare_preserves_keys_isolated():
    _run_isolated("test_batched_reshare_preserves_keys")


@pytest.mark.skipif(not _INNER, reason="runs via the subprocess wrapper")
def test_batched_reshare_preserves_keys(small_preparams):
    """2-of-3 → 2-of-4 rotation: public keys unchanged, epoch bumped,
    old+new reconstruct the same secret."""
    ids = ["node0", "node1", "node2"]
    new_ids = ["node0", "node1", "node2", "node3"]
    B = 2
    kt = "ed25519"
    dkg = {
        pid: BatchedDKGParty(f"bdkg-rs", pid, ids, 1, kt, B)
        for pid in ids
    }
    run_protocol(dkg)
    old_quorum = ["node0", "node1"]
    pubs = [dkg["node0"].result[w].public_key for w in range(B)]
    parties = {}
    for pid in sorted(set(old_quorum) | set(new_ids)):
        parties[pid] = BatchedReshareParty(
            "brs-1", pid, kt, old_quorum, new_ids, 2, B,
            old_shares=(dkg[pid].result if pid in old_quorum else None),
            old_public_keys=pubs,
        )
    run_protocol(parties)
    new_shares = [parties[pid].result for pid in new_ids]
    for w in range(B):
        assert new_shares[0][w].public_key == pubs[w]
        assert new_shares[0][w].epoch == 1
        secret = _reconstruct(new_shares, w, hm.ED_L, t=2)
        assert hm.ed_compress(hm.ed_mul(secret, hm.ED_B)) == pubs[w]
