"""Tier-1 smoke soak (ISSUE 6): a seconds-long miniature of
scripts/load_soak.py that still proves the serving invariants.

The real soak (`make soak`, committed SOAK_*.json) runs minutes of
bursty traffic at full chaos scale; this smoke keeps the cluster tiny
and the batch-chaos plan scaled down so it fits the tier-1 budget, but
it is NOT a happy-path run: the fault plan stays active (loss on acked
unicasts + jitter) and the queue depth is set below the burst size, so the
backpressure shed → retryable error event → client retry with a fresh
tx id path is exercised end to end through the full cluster, and the
closing-of-the-books invariant is asserted the same way the CLI
enforces it:

    submitted == succeeded + shed + failed   (and pending == 0)
"""

import threading

import pytest

from mpcium_tpu.soak import SoakConfig, run_soak
from mpcium_tpu.utils.annotations import REGISTERED_THREAD_PREFIXES

pytestmark = pytest.mark.soak


def _foreign_threads():
    """Live non-daemon threads other than the main thread and the
    registered process-lifetime singletons (MPL502's runtime twin)."""
    return [
        t
        for t in threading.enumerate()
        if t is not threading.main_thread()
        and not t.daemon
        and not (t.name or "").startswith(REGISTERED_THREAD_PREFIXES)
    ]


def test_smoke_soak_sheds_retries_and_closes_the_books(tmp_path):
    cfg = SoakConfig(
        n_nodes=3,
        threshold=1,
        n_wallets=3,
        root_dir=str(tmp_path),
        n_sign=6,
        burst_size=6,          # one burst...
        burst_gap_s=0.1,
        seed=1234,
        interactive_fraction=0.5,
        interactive_deadline_ms=300_000,
        bulk_deadline_ms=600_000,
        max_retries=3,
        retry_backoff_s=0.4,   # > batch_window_s, so retries land in a
                               # drained queue instead of re-shedding
        chaos="batch-chaos",   # fault plan ACTIVE, scaled down: drops on
        chaos_seed=7,          # acked unicasts + light jitter; the books
        chaos_scale=0.25,      # must still close exactly
        batch_window_s=0.25,
        batch_max_batch=1024,
        batch_max_queue_depth=3,  # ...< burst: forces backpressure sheds
        manifest_timeout_s=120.0,
        wait_timeout_s=420.0,
    )
    report = run_soak(cfg)

    # The one bug class the harness exists to catch: silent drops.
    out = report["outcomes"]
    assert report["accounting_ok"], report
    assert out["pending"] == 0
    assert out["submitted"] == 6
    assert out["submitted"] == (
        out["succeeded"] + out["shed"] + out["failed"])

    # Shed is retryable, not fatal: every request ultimately signs.
    assert out["succeeded"] == 6
    assert out["failed"] == 0
    assert report["by_kind"]["sign"]["succeeded"] == 6

    # The burst overflowed the bounded queue, loudly, and the client's
    # retry (fresh tx id) recovered each shed request.
    sched = report["scheduler"]
    assert sched["shed_backpressure"] >= 1
    assert out["retries"] >= 1
    assert sched["batches_fired"] >= 2  # original batch + retry batch

    # Per-node metric consistency: shed reasons partition shed_total,
    # and nothing is left sitting in a lane at the end.
    for node, snap in sched["per_node"].items():
        c, g = snap["counters"], snap["gauges"]
        assert c["scheduler.shed_total"] == (
            c["scheduler.shed_backpressure_total"]
            + c["scheduler.shed_deadline_total"]), (node, c)
        assert g["scheduler.queue_depth.interactive"] == 0, (node, g)
        assert g["scheduler.queue_depth.bulk"] == 0, (node, g)
        # intake counts every attempt, including retries
        assert c["scheduler.submitted_total"] >= 6, (node, c)

    # Latency is measured from the ORIGINAL submission for every
    # request, retried or not — all six have a number.
    assert report["latency_ms"]["overall"]["count"] == 6

    # The report embeds a merged cross-node Chrome trace spanning every
    # instrumented layer (the acceptance list of the tracing PR) plus
    # the Prometheus text for all nodes.
    from mpcium_tpu.trace import validate_chrome

    trace = report["trace"]
    assert validate_chrome(trace) > 0
    names = {e["name"] for e in trace["traceEvents"] if e.get("ph") != "M"}
    assert {"intake", "queue", "dispatch", "session"} <= names, sorted(names)
    assert any(n.startswith("round:") for n in names), sorted(names)
    assert any(n.startswith("phase:") for n in names), sorted(names)
    pids = {e["pid"] for e in trace["traceEvents"]}
    assert len(pids) >= 3  # one pid per node
    assert "scheduler_batches_fired_total" in report["prometheus"]
    assert 'node="node0"' in report["prometheus"]

    # Zero leaked threads: every worker the whole cluster+scheduler+chaos
    # stack started must be gone (or daemon/registered) once the soak
    # returns — the conftest leak fixture would catch this at session end,
    # but asserting here pins the leak to the soak path.
    leaked = _foreign_threads()
    assert not leaked, [t.name for t in leaked]
