"""The bench process-watchdog child (bench._CHILD_SRC): the GIL-immune
backstop that makes BENCH_r{N}.json un-killable. Three behaviors, each a
real subprocess:

  * sentinel written  → child stands down silently (and cleans up)
  * parent exits      → child exits silently (a fabricated success line
                        would mask a crash; holding the inherited stdout
                        would block a driver reading to EOF)
  * parent alive+silent past deadline → child emits the fallback record

No jax involved — this is pure process machinery."""
import json
import os
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow  # multi-second sleeps, subprocesses

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import bench  # noqa: E402


def _spawn_child(tmp_path, deadline, ppid, record=None):
    sentinel = str(tmp_path / "sentinel")
    env = dict(os.environ)
    env["MPCIUM_BENCH_FALLBACK"] = json.dumps(
        record or {"metric": "m", "value": 1.25}
    )
    env["PYTHONPATH"] = ""
    p = subprocess.Popen(
        [sys.executable, "-c", bench._CHILD_SRC,
         str(deadline), sentinel, str(ppid)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True,
    )
    return p, sentinel


def test_child_stands_down_on_sentinel(tmp_path):
    p, sentinel = _spawn_child(tmp_path, deadline=60, ppid=os.getpid())
    with open(sentinel, "w") as f:
        f.write("1")
    out, _ = p.communicate(timeout=30)
    assert out == ""  # no fabricated line
    assert p.returncode == 0
    assert not os.path.exists(sentinel)  # cleaned up for PID reuse


def test_child_exits_silently_when_parent_dies(tmp_path):
    # a short-lived stand-in parent that is already gone
    dead = subprocess.Popen([sys.executable, "-c", "pass"])
    dead.wait(timeout=30)
    p, _ = _spawn_child(tmp_path, deadline=60, ppid=dead.pid)
    out, _ = p.communicate(timeout=30)
    assert out == ""
    assert p.returncode == 0


def test_child_emits_fallback_for_frozen_parent(tmp_path):
    # "frozen parent": this test process stays alive and never writes
    # the sentinel; a short deadline makes the child emit
    rec = {"metric": "secp256k1_2of3_gg18_sigs_per_sec", "value": 4.5}
    p, _ = _spawn_child(tmp_path, deadline=6, ppid=os.getpid(), record=rec)
    out, _ = p.communicate(timeout=60)
    assert p.returncode == 0
    line = json.loads(out.strip())
    assert line["value"] == 4.5
    assert line["watchdog_timeout"] is True
    assert line["watchdog"] == "process"
