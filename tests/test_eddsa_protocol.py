"""End-to-end EdDSA threshold keygen + signing over the in-process runner.

Independent verification via OpenSSL (cryptography) — the signature must be
a standard RFC 8032 Ed25519 signature under the DKG public key.
"""
import secrets

import pytest

from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.protocol.base import ProtocolError
from mpcium_tpu.protocol.eddsa.keygen import EDDSAKeygenParty
from mpcium_tpu.protocol.eddsa.signing import EDDSASigningParty
from mpcium_tpu.protocol.runner import run_protocol

IDS = ["node-a", "node-b", "node-c"]


def run_keygen(ids=IDS, threshold=1, session="w1"):
    parties = {
        pid: EDDSAKeygenParty(session, pid, ids, threshold) for pid in ids
    }
    run_protocol(parties)
    return {pid: p.result for pid, p in parties.items()}


def test_keygen_3party():
    shares = run_keygen()
    pubs = {s.public_key for s in shares.values()}
    assert len(pubs) == 1
    pub = pubs.pop()
    # secret reconstructs consistently with the public key
    pts = {s.self_x: s.share for s in shares.values()}
    secret = hm.shamir_reconstruct(pts, hm.ED_L)
    assert hm.ed_compress(hm.ed_mul(secret, hm.ED_B)) == pub
    # t+1 = 2 shares reconstruct as well
    two = dict(list(pts.items())[:2])
    assert hm.shamir_reconstruct(two, hm.ED_L) == secret


@pytest.mark.parametrize("quorum", [["node-a", "node-b"], IDS])
def test_sign_with_quorum(quorum):
    shares = run_keygen()
    msg = b"solana-devnet tx: " + secrets.token_bytes(24)
    signers = {
        pid: EDDSASigningParty("w1-tx1", pid, quorum, shares[pid], msg)
        for pid in quorum
    }
    run_protocol(signers)
    sigs = {p.result for p in signers.values()}
    assert len(sigs) == 1
    sig = sigs.pop()
    pub = shares[quorum[0]].public_key
    assert hm.ed25519_verify(pub, msg, sig)
    # independent OpenSSL verification
    ed = pytest.importorskip("cryptography.hazmat.primitives.asymmetric.ed25519")
    ed.Ed25519PublicKey.from_public_bytes(pub).verify(sig, msg)


def test_sign_wrong_message_fails_verify():
    shares = run_keygen()
    quorum = ["node-a", "node-c"]
    msg = b"real tx"
    signers = {
        pid: EDDSASigningParty("w1-tx2", pid, quorum, shares[pid], msg)
        for pid in quorum
    }
    run_protocol(signers)
    sig = signers["node-a"].result
    assert not hm.ed25519_verify(shares["node-a"].public_key, b"forged", sig)


def test_sign_below_threshold_rejected():
    shares = run_keygen(threshold=2)  # needs 3 signers
    with pytest.raises(ProtocolError):
        EDDSASigningParty(
            "w1-tx3", "node-a", ["node-a", "node-b"], shares["node-a"], b"m"
        )


def test_keygen_detects_bad_share():
    """A corrupted VSS share must be attributed to the sender."""
    parties = {
        pid: EDDSAKeygenParty("w2", pid, IDS, 1) for pid in IDS
    }
    from collections import deque

    queue = deque()
    for p in parties.values():
        queue.extend(p.start())
    try:
        while queue:
            msg = queue.popleft()
            if (
                msg.round == "eddsa/kg/2/share"
                and msg.from_id == "node-b"
                and msg.to == "node-a"
            ):
                bad = dict(msg.payload)
                bad["share"] = str((int(bad["share"]) + 1) % hm.ED_L)
                msg = type(msg)(msg.session_id, msg.round, msg.from_id, bad, msg.to)
            targets = (
                [p for pid, p in parties.items() if pid != msg.from_id]
                if msg.is_broadcast
                else [parties[msg.to]]
            )
            for t in targets:
                queue.extend(t.receive(msg))
        raise AssertionError("corruption went undetected")
    except ProtocolError as e:
        assert e.culprit == "node-b"


def test_signing_detects_equivocating_decommit():
    """R2 decommit not matching the R1 commitment is detected + attributed."""
    shares = run_keygen()
    quorum = IDS
    signers = {
        pid: EDDSASigningParty("w1-tx4", pid, quorum, shares[pid], b"m")
        for pid in quorum
    }
    from collections import deque

    queue = deque()
    for p in signers.values():
        queue.extend(p.start())
    try:
        while queue:
            msg = queue.popleft()
            if msg.round == "eddsa/sign/2" and msg.from_id == "node-c":
                fake_R = hm.ed_compress(
                    hm.ed_mul(secrets.randbelow(hm.ED_L), hm.ED_B)
                )
                bad = dict(msg.payload)
                bad["R"] = fake_R.hex()
                msg = type(msg)(msg.session_id, msg.round, msg.from_id, bad, msg.to)
            targets = (
                [p for pid, p in signers.items() if pid != msg.from_id]
                if msg.is_broadcast
                else [signers[msg.to]]
            )
            for t in targets:
                queue.extend(t.receive(msg))
        raise AssertionError("equivocation went undetected")
    except ProtocolError as e:
        assert e.culprit == "node-c"
