"""Full-size batched GG18 (2048-bit Paillier, default ZK domains) — the
bench configuration at B=2. Own module: the heavy compiles keep crash
exposure to XLA's flaky CPU AOT cache isolated from the core GG18 tests.
"""
import secrets

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.engine import gg18_batch as gb


def test_gg18_full_size():
    """One batched 2-of-3 sign at FULL key size (2048-bit Paillier,
    default GG18 exponent domains) — the bench configuration at B=2.
    Slow-marked: minutes on a CPU host. Runs in routine `make test-all`
    (per-file isolation contains the rare XLA CPU AOT cache segfault);
    the wider-batch variants stay in bench.py."""
    from mpcium_tpu.cluster import load_test_preparams

    B = 2
    universe = ["node0", "node1", "node2"]
    shares = gb.dealer_keygen_secp_batch(B, universe, threshold=1)
    signer = gb.GG18BatchCoSigners(
        ["node0", "node1"], shares[:2], load_test_preparams()
    )
    digests = np.frombuffer(secrets.token_bytes(B * 32), dtype=np.uint8).reshape(
        B, 32
    )
    out = signer.sign(digests)
    assert out["ok"].all(), "full-size batched GG18 produced invalid signatures"
    for i in range(B):
        pub = hm.secp_decompress(shares[0][i].public_key)
        r = int.from_bytes(out["r"][i].tobytes(), "big")
        s = int.from_bytes(out["s"][i].tobytes(), "big")
        digest = int.from_bytes(digests[i].tobytes(), "big")
        assert hm.ecdsa_verify(pub, digest, r, s)


