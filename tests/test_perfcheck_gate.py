"""Tier-1 perf-observatory gate: `make perfcheck` passes on HEAD, the
committed history/dashboard match a regeneration (drift gate, same
contract as HOST_TRANSFER_BUDGET.json), and the gate demonstrably fails
on an injected slowdown — proven against a freshly measured
self-baseline so the assertion holds on any host."""
import json
import os
import subprocess
import sys

import pytest

from mpcium_tpu.perf import ledger, microbench, report, statcheck

pytestmark = pytest.mark.perf

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import perfcheck  # noqa: E402


def test_perfcheck_main_passes_on_head():
    # strict on the baseline's host, informational elsewhere — either
    # way HEAD must exit 0 (this IS the tier-1 regression gate).
    # Measured in a fresh subprocess so the samples share a process
    # context with the committed baseline (--update-baseline measures
    # standalone): hundreds of tests into a shared pytest process the
    # thread-handoff rows inflate ~2x on a contended 1-core host and
    # flag regressions in code that did not change.
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "perfcheck.py")],
        capture_output=True, text=True, timeout=600, cwd=ROOT,
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_committed_baseline_exists_and_has_all_benches():
    with open(os.path.join(ROOT, "PERF_baseline_micro.json")) as f:
        doc = json.load(f)
    assert set(doc["benches"]) == set(microbench.ALL_BENCHES)
    for name, b in doc["benches"].items():
        assert len(b["samples"]) >= 8, name
        assert all(v > 0 for v in b["samples"]), name
    assert doc["host"]


def test_committed_history_matches_regeneration():
    committed = ledger.load_history(
        os.path.join(ROOT, ledger.HISTORY_FILE)
    )
    regenerated = ledger.build_history(ROOT)
    assert committed == regenerated, (
        "PERF_history.jsonl drifted from the committed artifacts — "
        "run `python scripts/perfcheck.py --regen-history`"
    )
    sources = {r["source"] for r in committed}
    for i in range(1, 6):
        assert f"BENCH_r0{i}.json" in sources
        assert f"MULTICHIP_r0{i}.json" in sources
    assert "SOAK_r01.json" in sources


def test_committed_dashboard_matches_regeneration():
    with open(os.path.join(ROOT, "PERFORMANCE_dashboard.md")) as f:
        committed = f.read()
    with open(os.path.join(ROOT, "PERF_baseline_micro.json")) as f:
        baseline = json.load(f)
    regenerated = report.render_dashboard(
        ledger.build_history(ROOT), micro_baseline=baseline
    )
    assert committed == regenerated, (
        "PERFORMANCE_dashboard.md drifted — run "
        "`python scripts/perfcheck.py --regen-history`"
    )


def test_gate_fails_on_injected_slowdown_vs_self_baseline():
    # host-independent proof of gate mechanics: measure a baseline NOW,
    # inject 1.5x on a second measurement of the same bench
    base = microbench.field_mulmod(samples=15)
    cur = [v * 1.5 for v in microbench.field_mulmod(samples=15)]
    v = statcheck.compare("field_mulmod", base, cur)
    assert v.regressed, v.render()
    # and the unscaled re-measurement passes
    v2 = statcheck.compare("field_mulmod", base,
                           microbench.field_mulmod(samples=15))
    assert not v2.regressed, v2.render()


def test_perfcheck_inject_slowdown_exits_nonzero():
    # through the CLI path (retry-once included): only asserted strictly
    # when this host matches the committed baseline, because a foreign
    # host is informational by design
    with open(os.path.join(ROOT, "PERF_baseline_micro.json")) as f:
        doc = json.load(f)
    from mpcium_tpu.perf.envfp import host_fingerprint

    rc = perfcheck.main(["--inject-slowdown", "4.0", "--samples", "12"])
    if doc["host"] == host_fingerprint():
        assert rc == 1
    else:
        assert rc == 0  # informational on a foreign host, never fails
