"""Counter-phase cohort pipeline (ISSUE 17): the zero-idle round
schedule must be INVISIBLE everywhere except the clock.

Fast tier: cohort resolution stays on the bucket grid, the stub
scheduler interleaves and preserves order, host stages surface as
``host:*`` spans, the idle-fraction math holds on synthetic spans,
CohortAbort blame survives the split, and the scheduler's
cohort-aligned manifests are signature-covered and engine-clamped.

Slow tier (the engine-compile policy of test_gg18_batch.py /
test_eddsa_batch.py): signatures and transcripts are bit-identical for
K ∈ {1, 2, 4} on real GG18-OT and EdDSA signing at B=8 — cohorting is
a scheduling choice, never a protocol one.
"""
import hashlib
import threading

import numpy as np
import pytest

from mpcium_tpu.engine import pipeline as pl
from mpcium_tpu.engine.abort import CohortAbort
from mpcium_tpu.engine.buckets import BUCKETS, is_bucket
from mpcium_tpu.utils import tracing


class DetRng:
    """Deterministic CSPRNG stand-in (test_mta_ot_pipeline.py pattern):
    a hash-counter stream, so two instances with one seed draw identical
    bytes in identical call order — the bit-exactness fixture."""

    def __init__(self, seed: int):
        self.seed = seed
        self.ctr = 0

    def token_bytes(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            out += hashlib.sha256(
                b"pipedet|%d|%d" % (self.seed, self.ctr)
            ).digest()
            self.ctr += 1
        return bytes(out[:n])

    def randbelow(self, n: int) -> int:
        return int.from_bytes(self.token_bytes(40), "big") % n


# -- cohort resolution: always on the bucket grid -----------------------------


def test_resolve_cohorts_defaults(monkeypatch):
    # conftest pins the tier-1 suite to K=1; this test is ABOUT the
    # production default, so clear the pin
    monkeypatch.delenv(pl.ENV_COHORTS, raising=False)
    assert pl.resolve_cohorts(1) == 1
    assert pl.resolve_cohorts(2) == 1  # 2/2 = 1 lane < MIN_COHORT_LANES
    assert pl.resolve_cohorts(4) == 2
    assert pl.resolve_cohorts(8) == 2
    assert pl.resolve_cohorts(16384) == 2


def test_resolve_cohorts_explicit_clamps_to_grid():
    assert pl.resolve_cohorts(8, 1) == 1
    assert pl.resolve_cohorts(8, 4) == 4
    assert pl.resolve_cohorts(8, 8) == 4   # 8/8 = 1 lane → halve
    assert pl.resolve_cohorts(8, 64) == 4  # absurd K from the wire → grid
    assert pl.resolve_cohorts(4, 4) == 2
    assert pl.resolve_cohorts(2, 2) == 1
    assert pl.resolve_cohorts(6, 3) == 1   # non-pow-2 floor + off-grid width
    with pytest.raises(ValueError):
        pl.resolve_cohorts(0)


def test_resolve_cohorts_env_override(monkeypatch):
    monkeypatch.setenv(pl.ENV_COHORTS, "4")
    assert pl.resolve_cohorts(16) == 4
    monkeypatch.setenv(pl.ENV_COHORTS, "1")
    assert pl.resolve_cohorts(16) == 1
    monkeypatch.setenv(pl.ENV_COHORTS, "not-a-number")
    assert pl.resolve_cohorts(16) == pl.DEFAULT_COHORTS


def test_every_bucket_splits_back_onto_the_grid(monkeypatch):
    """The compile-surface invariant: for every serving bucket B the
    resolved cohort width B/K is itself a bucket, so a cohorted dispatch
    reuses a prewarmed compile instead of minting a new signature."""
    monkeypatch.delenv(pl.ENV_COHORTS, raising=False)
    for b in BUCKETS:
        k = pl.resolve_cohorts(b)
        assert b % k == 0
        assert k == 1 or is_bucket(b // k)
        # and for any advertised K, however hostile
        for adv in (0, 1, 2, 3, 7, 8, 64, 4096):
            kk = pl.resolve_cohorts(b, adv)
            assert b % kk == 0
            assert kk == 1 or is_bucket(b // kk)


# -- CohortPlan geometry ------------------------------------------------------


def test_plan_slices_and_split():
    plan = pl.CohortPlan(8, 2)
    assert plan.width == 4 and not plan.serial
    assert plan.slices() == [slice(0, 4), slice(4, 8)]
    arr = np.arange(16).reshape(8, 2)
    lo, hi = plan.split(arr)
    assert (np.concatenate([lo, hi]) == arr).all()
    byaxis = plan.split(arr.T, axis=1)
    assert (byaxis[1] == arr.T[:, 4:]).all()


def test_plan_split_tree_keeps_structure():
    from typing import NamedTuple

    class Pt(NamedTuple):
        x: np.ndarray
        y: np.ndarray

    plan = pl.CohortPlan(4, 2)
    tree = {"p": Pt(np.arange(4), np.arange(4) * 10), "raw": np.arange(4)}
    parts = plan.split_tree(tree)
    assert len(parts) == 2
    assert isinstance(parts[0]["p"], Pt)
    assert (parts[1]["p"].y == np.array([20, 30])).all()
    assert (parts[0]["raw"] == np.array([0, 1])).all()


def test_plan_to_global_bounds_checked():
    plan = pl.CohortPlan(8, 4)
    assert plan.to_global(0, 0) == 0
    assert plan.to_global(3, 1) == 7
    with pytest.raises(ValueError):
        plan.to_global(1, 2)


def test_merge_rows_restores_batch_order():
    plan = pl.CohortPlan(8, 2)
    arr = np.arange(24).reshape(8, 3)
    assert (pl.merge_rows(plan.split(arr)) == arr).all()
    only = np.arange(3)
    assert pl.merge_rows([only]) is only


# -- counter-phase scheduler --------------------------------------------------


def test_run_counter_phase_serial_runs_inline():
    """K=1 is the transcript oracle: host stages run on the CALLING
    thread — no worker, no reordering, byte-for-byte the old path."""
    seen = []

    def job():
        seen.append(("host-thread", threading.current_thread().name))
        out = yield ("stage", lambda: threading.current_thread().name)
        return out

    [res] = pl.run_counter_phase([job])
    assert res == threading.current_thread().name
    assert seen[0][1] == threading.current_thread().name


def test_run_counter_phase_overlap_results_in_cohort_order():
    def make_job(ci):
        def job():
            a = yield ("first", lambda: ci * 10)
            b = yield ("second", lambda: a + 1)
            return (ci, a, b)

        return job

    outs = pl.run_counter_phase([make_job(ci) for ci in range(4)])
    assert outs == [(ci, ci * 10, ci * 10 + 1) for ci in range(4)]


def test_run_counter_phase_host_stages_on_worker_and_interleaved():
    """K=2: host thunks run on the shared pipe-host worker, and the
    schedule is counter-phase — cohort 1's first stage is submitted
    before cohort 0's second (round-robin), so a device dispatch always
    has a draining host stage to hide behind."""
    order = []

    def make_job(ci):
        def job():
            for stage in ("a", "b"):
                yield (
                    f"{stage}{ci}",
                    lambda s=stage: order.append(
                        (s, ci, threading.current_thread().name)
                    ),
                )
            return ci

        return job

    outs = pl.run_counter_phase([make_job(ci) for ci in range(2)])
    assert outs == [0, 1]
    assert all(name.startswith("pipe-host") for _s, _c, name in order)
    assert [(s, c) for s, c, _n in order] == [
        ("a", 0), ("a", 1), ("b", 0), ("b", 1)
    ]


def test_run_counter_phase_emits_host_spans():
    spans = []
    tracing.enable(sink=spans.append)
    try:

        def make_job(ci):
            def job():
                yield ("pack_wire", lambda: None)
                return ci

            return job

        pl.run_counter_phase([make_job(ci) for ci in range(2)])
    finally:
        tracing.disable()
    host = [s for s in spans if s["name"] == "host:pack_wire"]
    assert len(host) == 2
    assert sorted(s["attrs"]["cohort"] for s in host) == [0, 1]
    assert all(s["t1_ns"] >= s["t0_ns"] for s in host)


def test_run_counter_phase_propagates_exceptions():
    def bad():
        yield ("x", lambda: None)
        raise CohortAbort([(1, "node-evil", "kos")])

    def good():
        yield ("y", lambda: None)
        return "fine"

    with pytest.raises(CohortAbort):
        pl.run_counter_phase([bad, good])


# -- idle-fraction math -------------------------------------------------------


def _mkspan(name, t0, t1):
    return {"name": name, "t0_ns": t0, "t1_ns": t1, "attrs": {}}


def test_device_idle_fraction_empty_and_nondevice():
    assert tracing.device_idle_fraction([]) == 0.0
    # host stages alone claim nothing: no device span ⇒ nothing claimable
    assert tracing.device_idle_fraction(
        [_mkspan("host:pack", 0, 100)]
    ) == 0.0
    # unrelated spans are ignored entirely
    assert tracing.device_idle_fraction(
        [_mkspan("queue", 0, 100), _mkspan("phase:r1", 0, 50)]
    ) == 0.0


def test_device_idle_fraction_gap_between_rounds():
    spans = [
        _mkspan("phase:r1", 0, 40),
        _mkspan("host:wire", 40, 60),
        _mkspan("phase:r2", 60, 100),
    ]
    # window [0, 100], device busy 80 → idle 0.2 (the serial-path shape)
    assert tracing.device_idle_fraction(spans) == pytest.approx(0.2)


def test_device_idle_fraction_unions_counter_phase_overlap():
    spans = [
        _mkspan("phase:r1", 0, 60),      # cohort 0
        _mkspan("phase:r1", 40, 100),    # cohort 1, overlapping
        _mkspan("host:wire", 90, 110),   # trailing host stage widens window
    ]
    # union busy [0,100] = 100 over window [0,110] → idle 10/110,
    # NOT (60+60)/110: overlap is the effect being measured, never
    # double-counted as extra busy time
    assert tracing.device_idle_fraction(spans) == pytest.approx(10 / 110)


# -- abort blame through the split --------------------------------------------


def test_remap_abort_names_same_culprits_at_every_k():
    """A cohort-LOCAL abort remapped through the plan blames the same
    batch-global (lane, party, check) triples the serial run would."""
    serial = CohortAbort(
        [(5, "node-b", "gilboa"), (6, "node-b", "kos")], engine="gg18.sign"
    )
    for k in (2, 4):
        plan = pl.CohortPlan(8, k)
        # lanes 5 and 6 land in the last cohort (k=2) or cohorts 2/3 (k=4)
        remapped = []
        for ci, (lo, hi) in enumerate(plan.bounds):
            local = [
                (lane - lo, pid, chk)
                for lane, pid, chk in serial.culprits
                if lo <= lane < hi
            ]
            if local:
                err = plan.remap_abort(
                    CohortAbort(local, engine="gg18.sign"), ci
                )
                remapped.extend(err.culprits)
        assert sorted(remapped) == sorted(serial.culprits)
        assert err.engine == "gg18.sign"


def test_remap_abort_rejects_out_of_cohort_lane():
    plan = pl.CohortPlan(8, 2)
    with pytest.raises(ValueError):
        plan.remap_abort(CohortAbort([(4, "p", "kos")]), 0)


# -- scheduler: cohort-aligned manifests --------------------------------------


def test_manifest_body_covers_cohorts():
    """The cohort count rides INSIDE the signed canonical body — a relay
    cannot flip K without breaking the leader's signature."""
    from mpcium_tpu.consumers.batch_scheduler import _manifest_body

    a = _manifest_body("b1", "node0", [{"i": 1}], "sign", cohorts=2)
    b = _manifest_body("b1", "node0", [{"i": 1}], "sign", cohorts=4)
    assert a != b
    assert b'"cohorts":2' in a.replace(b" ", b"")
    # legacy manifests (no cohorts field pre-ISSUE-17) default to serial
    legacy = _manifest_body("b1", "node0", [{"i": 1}], "sign")
    assert b'"cohorts":1' in legacy.replace(b" ", b"")


def test_advertised_cohorts_are_engine_clamped(monkeypatch):
    """A leader advertises K but every receiver re-derives it through
    resolve_cohorts, so a hostile/buggy manifest can never force an
    off-grid cohort width (a compile signature no one prewarmed)."""
    monkeypatch.delenv(pl.ENV_COHORTS, raising=False)
    for n_reqs, advertised in ((8, 64), (8, 3), (2, 2), (5, 4), (16, 0)):
        k = pl.resolve_cohorts(n_reqs, advertised)
        assert n_reqs % k == 0
        width = n_reqs // k
        assert k == 1 or (width >= pl.MIN_COHORT_LANES and is_bucket(width))


# -- slow tier: real engines, bit-identical transcripts across K --------------


@pytest.mark.slow
def test_eddsa_bit_identical_across_cohorts():
    """B=8 threshold-Ed25519 through the real engine at K ∈ {1, 2, 4}:
    identical signatures, ok masks, and nonce material — cohorting is
    pure scheduling."""
    from mpcium_tpu.engine import eddsa_batch as eb

    B = 8
    ids = ["n0", "n1", "n2"]
    shares = eb.dealer_keygen_batch(B, ids, 1, rng=DetRng(3))
    messages = [DetRng(9).token_bytes(32) for _ in range(B)]
    outs = {}
    for k in (1, 2, 4):
        signer = eb.BatchedCoSigners(ids[:2], shares[:2], rng=DetRng(42))
        sigs, ok = signer.sign(messages, cohorts=k)
        assert np.asarray(ok).all(), (k, ok)
        outs[k] = (np.asarray(sigs).tobytes(), np.asarray(ok).tobytes())
    assert outs[1] == outs[2] == outs[4]


@pytest.mark.slow
def test_gg18_ot_bit_identical_across_cohorts():
    """B=8 GG18 with OT-MtA at K ∈ {1, 2, 4}: r, s, recovery and ok are
    byte-identical — all signing randomness is drawn full-batch in K=1
    serial order before the cohort split (gg18_batch._finish_sign)."""
    from mpcium_tpu.engine import gg18_batch as gb

    B = 8
    ids = ["n0", "n1", "n2"]
    shares = gb.dealer_keygen_secp_batch(B, ids, 1, rng=DetRng(3))
    digests = np.frombuffer(
        DetRng(9).token_bytes(B * 32), dtype=np.uint8
    ).reshape(B, 32)
    outs = {}
    for k in (1, 2, 4):
        signer = gb.GG18BatchCoSigners(
            ids[:2], shares[:2], mta_impl="ot", rng=DetRng(42)
        )
        out = signer.sign(digests, cohorts=k)
        assert out["ok"].all(), (k, out["ok"])
        outs[k] = tuple(
            out[key].tobytes() for key in ("r", "s", "recovery", "ok")
        )
    assert outs[1] == outs[2] == outs[4]
