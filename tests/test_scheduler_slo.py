"""SLO-aware continuous batching, unit level (no cluster, no engine):
the timing wheel, lane-ordered batch fill, bounded-intake backpressure,
deadline sheds, deputy-takeover × deadline interaction, the decline-
responder cap, and the secp digest LRU bound."""
import threading
import time
import types

import pytest

import mpcium_tpu.consumers.batch_scheduler as bs
from mpcium_tpu import wire
from mpcium_tpu.consumers.batch_scheduler import (
    BatchSigningScheduler,
    _Entry,
    _TimingWheel,
    _entry_key,
)
from mpcium_tpu.transport.loopback import LoopbackFabric


class _Registry:
    def __init__(self, ready=()):
        self._ready = set(ready)

    def is_peer_ready(self, p):
        return p in self._ready

    def ready_count(self):
        return len(self._ready) + 1


class _Identity:
    """Just enough identity for manifests + declines; content checks are
    covered by the cluster-level suites."""

    def sign_raw(self, body):
        return b"\x00" * 64

    def sign_envelope(self, env):
        env.signature = b"\x00" * 64

    def verify_peer(self, peer, body, sig):
        # loopback manifests land back on _on_manifest_raw; this harness
        # only inspects the published manifests, so reject the loopback
        return False


def _wait_for(cond, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return cond()


def _node(node_id="n0", peers=("n0", "n1", "n2"), ready=()):
    return types.SimpleNamespace(
        node_id=node_id,
        peer_ids=list(peers),
        registry=_Registry(ready),
        identity=_Identity(),
    )


def _tx(wallet, tx_id, deadline_ms=0, priority=wire.PRIORITY_BULK):
    return wire.SignTxMessage(
        key_type="ed25519", wallet_id=wallet,
        network_internal_code="sol", tx_id=tx_id, tx=b"\x01" * 32,
        deadline_ms=deadline_ms, priority=priority,
    )


# the bucket-key shape used by submit(): key[0] = participant tuple
KEY = (("n0", "n1", "n2"), 1, 0, "ed25519")


@pytest.fixture
def fabric():
    f = LoopbackFabric()
    yield f
    f.close()


def _sched(fabric, node=None, **kw):
    s = BatchSigningScheduler(
        node or _node(), transport=fabric.transport(), **kw
    )
    return s


# -- timing wheel ----------------------------------------------------------


def test_timing_wheel_fires_replaces_cancels():
    w = _TimingWheel(name="test-wheel")
    try:
        fired = []
        evt = threading.Event()
        w.schedule("a", 0.05, lambda: (fired.append("a"), evt.set()))
        assert evt.wait(2.0)
        assert fired == ["a"]
        assert not w.contains("a")  # one-shot: disarmed after firing

        # replace: the first fn for a key must never fire
        evt2 = threading.Event()
        w.schedule("b", 0.05, lambda: fired.append("b-old"))
        w.schedule("b", 0.05, lambda: (fired.append("b-new"), evt2.set()))
        assert evt2.wait(2.0)
        assert "b-old" not in fired and "b-new" in fired

        # cancel: disarmed before the deadline
        w.schedule("c", 0.05, lambda: fired.append("c"))
        w.cancel("c")
        time.sleep(0.15)
        assert "c" not in fired

        # schedule_if_absent: no-op while armed, arms when clear
        w.schedule("d", 5.0, lambda: fired.append("d-first"))
        assert not w.schedule_if_absent("d", 0.01, lambda: None)
        assert w.contains("d")
        w.cancel("d")
        evt3 = threading.Event()
        assert w.schedule_if_absent("d", 0.01, lambda: evt3.set())
        assert evt3.wait(2.0)

        # a crashing callback must not kill the wheel thread
        evt4 = threading.Event()
        w.schedule("crash", 0.01, lambda: 1 / 0)
        w.schedule("after", 0.05, evt4.set)
        assert evt4.wait(2.0)
    finally:
        w.close()
        w.close()  # idempotent


# -- lane-ordered continuous fill ------------------------------------------


def test_fire_fills_interactive_first_oldest_deadline_first(fabric):
    s = _sched(fabric, window_s=60.0, max_batch=3)
    manifests = []
    got = threading.Event()

    def on_manifest(raw):
        import json

        manifests.append(json.loads(raw))
        got.set()

    sub = fabric.transport().pubsub.subscribe(
        wire.TOPIC_BATCH_MANIFEST, on_manifest
    )
    try:
        now = time.monotonic()
        order = [
            ("bulk-soon", wire.PRIORITY_BULK, now + 5),
            ("int-late", wire.PRIORITY_INTERACTIVE, now + 50),
            ("bulk-late", wire.PRIORITY_BULK, now + 50),
            ("int-soon", wire.PRIORITY_INTERACTIVE, now + 5),
        ]
        with s._lock:
            s._buckets[KEY] = [
                _Entry(_tx("w", t), "", kind="sign",
                       deadline_at=dl, lane=lane)
                for t, lane, dl in order
            ]
        s._fire(KEY)
        # continuous drain in pow-2 chunks: max_batch=3 snaps to a chunk
        # cap of floor_bucket(3)=2, so the 4 entries go as two full
        # bucket-grid manifests in fill order — never a one-off 3-wide
        # compile shape
        assert _wait_for(lambda: len(manifests) == 2), (
            f"expected 2 manifests, got {len(manifests)}"
        )
        txs = [r["msg"]["tx_id"] for r in manifests[0]["requests"]]
        # both interactive entries first (oldest deadline leading)
        assert txs == ["int-soon", "int-late"]
        rest = [r["msg"]["tx_id"] for r in manifests[1]["requests"]]
        assert rest == ["bulk-soon", "bulk-late"]
        assert s.metrics.counter("scheduler.batches_fired_total").value == 2
        fill = s.metrics.get("scheduler.batch_fill_ratio")
        assert fill.count == 2 and fill.max == 1.0
    finally:
        sub.unsubscribe()
        s.close()


# -- backpressure ----------------------------------------------------------


def test_backpressure_shed_is_loud_and_releases_claim(fabric):
    released = []
    s = _sched(
        fabric, window_s=60.0, max_queue_depth=1,
        on_tx_released=lambda w, t: released.append((w, t)),
    )
    events = []
    got_event = threading.Event()
    err_reply = threading.Event()
    t = fabric.transport()
    sub_q = t.queues.dequeue(
        f"{wire.TOPIC_SIGNING_RESULT}.*",
        lambda raw: (
            events.append(wire.SigningResultEvent.from_json(
                __import__("json").loads(raw))),
            got_event.set(),
        ),
    )
    sub_r = t.pubsub.subscribe(
        "reply.t2", lambda d: d == b"ERR" and err_reply.set()
    )
    try:
        leader = "n1"  # not us: intake only, no fire/window on this node
        assert s._buffer_entry(
            KEY, s._mk_entry(_tx("w", "t1"), "reply.t1", "sign"), leader
        )
        # depth now 1 == max_queue_depth: the next submit is REFUSED —
        # handled (True), not routed to the per-session path
        assert s._buffer_entry(
            KEY, s._mk_entry(_tx("w", "t2"), "reply.t2", "sign"), leader
        )
        assert got_event.wait(5.0), "no shed event published"
        assert err_reply.wait(5.0), "reply inbox never got ERR"
        ev = events[0]
        assert ev.tx_id == "t2"
        assert ev.result_type == wire.RESULT_ERROR
        assert ev.retryable is True
        assert _wait_for(lambda: released == [("w", "t2")]), released
        m = s.metrics
        assert m.counter("scheduler.submitted_total").value == 2
        assert m.counter("scheduler.shed_total").value == 1
        assert m.counter("scheduler.shed_backpressure_total").value == 1
        assert m.counter("scheduler.shed_deadline_total").value == 0
        # the surviving entry still counts toward lane depth
        assert m.gauge(
            f"scheduler.queue_depth.{wire.PRIORITY_BULK}"
        ).value == 1
    finally:
        sub_q.unsubscribe()
        sub_r.unsubscribe()
        s.close()


def test_settled_sign_duplicate_absorbed_not_stranded(fabric):
    """A chaos-dropped sign intake redelivered AFTER its covering batch
    settled (claims forgotten) must be absorbed, not buffered: sign
    retries carry fresh tx ids, so a same-dedup arrival inside the TTL
    is a duplicate of an answered request — buffering it would strand a
    lane entry (nonzero depth gauge) until the fallback sweep."""
    s = _sched(fabric, window_s=60.0, max_queue_depth=10)
    try:
        leader = "n1"
        msg = _tx("w", "t1")
        d = s._dedup_str("sign", _entry_key("sign", msg))
        # batch lifecycle in miniature: claim registered, then settled
        with s._lock:
            s._batch_claims[d] = 1
            s._forget_locked("sign", [_entry_key("sign", msg)])
        assert d in s._settled
        # the late redelivery is handled (True) but NOT buffered
        assert s._buffer_entry(
            KEY, s._mk_entry(msg, "reply.t1", "sign"), leader
        )
        depth = s.metrics.gauge(
            f"scheduler.queue_depth.{wire.PRIORITY_BULK}"
        ).value
        assert depth == 0, "late duplicate stranded a lane entry"
        # past the TTL the same dedup buffers normally again
        with s._lock:
            s._settled[d] = time.monotonic() - (bs._SETTLED_TTL_S + 1)
        assert s._buffer_entry(
            KEY, s._mk_entry(msg, "reply.t1", "sign"), leader
        )
        assert s.metrics.gauge(
            f"scheduler.queue_depth.{wire.PRIORITY_BULK}"
        ).value == 1
        assert d not in s._settled  # expired stamp pruned on read
    finally:
        s.close()


# -- deadline sheds --------------------------------------------------------


def test_deadline_expiry_sheds_retryably(fabric):
    released = []
    s = _sched(
        fabric, window_s=60.0, manifest_timeout_s=60.0,
        on_tx_released=lambda w, t: released.append((w, t)),
    )
    events = []
    got = threading.Event()
    t = fabric.transport()
    sub = t.queues.dequeue(
        f"{wire.TOPIC_SIGNING_RESULT}.*",
        lambda raw: (
            events.append(wire.SigningResultEvent.from_json(
                __import__("json").loads(raw))),
            got.set(),
        ),
    )
    try:
        # leader is a peer: nothing fires locally, the entry can only age
        s._buffer_entry(
            KEY,
            s._mk_entry(_tx("w", "t-dl", deadline_ms=80), "", "sign"),
            "n1",
        )
        assert got.wait(5.0), "deadline sweep never shed the entry"
        ev = events[0]
        assert ev.tx_id == "t-dl" and ev.retryable is True
        assert _wait_for(lambda: released == [("w", "t-dl")]), released
        m = s.metrics
        assert m.counter("scheduler.shed_deadline_total").value == 1
        assert m.gauge(
            f"scheduler.queue_depth.{wire.PRIORITY_BULK}"
        ).value == 0
        with s._lock:
            assert not any(s._buckets.get(KEY, []))
    finally:
        sub.unsubscribe()
        s.close()


# -- deputy takeover × deadline lanes (satellite: leader dies between
# _fire and manifest loopback) ---------------------------------------------


def test_deputy_takeover_sheds_expired_instead_of_refiring(fabric):
    """n0 (leader) fired a manifest and died before it looped back: n1's
    registry now sees n0 dead, and n1's fallback sweep runs as deputy.
    Deadline-expired entries must be shed retryably — NOT re-fired under
    the deputy's manifest — while live entries take over normally."""
    released = []
    node = _node(node_id="n1", ready=("n2",))  # n0 dead, n2 live
    s = _sched(
        fabric, node=node, window_s=60.0, manifest_timeout_s=0.2,
        on_tx_released=lambda w, t: released.append((w, t)),
    )
    import json

    manifests = []
    fired = threading.Event()
    shed_events = []
    shed_got = threading.Event()
    t = fabric.transport()
    sub_m = t.pubsub.subscribe(
        wire.TOPIC_BATCH_MANIFEST,
        lambda raw: (manifests.append(json.loads(raw)), fired.set()),
    )
    sub_q = t.queues.dequeue(
        f"{wire.TOPIC_SIGNING_RESULT}.*",
        lambda raw: (
            shed_events.append(
                wire.SigningResultEvent.from_json(json.loads(raw))),
            shed_got.set(),
        ),
    )
    try:
        now = time.monotonic()
        T = s.manifest_timeout_s
        with s._lock:
            # all entries are past the takeover age; one is past its SLO
            stale_age = now - T - 0.05
            expired = _Entry(_tx("w", "t-expired"), "", kind="sign",
                             deadline_at=now - 0.01,
                             lane=wire.PRIORITY_INTERACTIVE)
            live1 = _Entry(_tx("w", "t-live1"), "", kind="sign",
                           deadline_at=now + 60)
            live2 = _Entry(_tx("w", "t-live2"), "", kind="sign",
                           deadline_at=now + 60)
            for e in (expired, live1, live2):
                e.added_at = stale_age
                s._note_depth(e.lane, +1)
            s._buckets[KEY] = [expired, live1, live2]
        s._fallback_sweep(KEY)
        assert fired.wait(5.0), "deputy never re-fired the live entries"
        assert shed_got.wait(5.0), "expired entry never shed"

        covered = [r["msg"]["tx_id"] for m in manifests
                   for r in m["requests"]]
        assert sorted(covered) == ["t-live1", "t-live2"]
        assert "t-expired" not in covered, (
            "deputy re-fired a deadline-expired entry"
        )
        assert len(manifests) == 1, "live entries double-fired"
        assert [e.tx_id for e in shed_events] == ["t-expired"]
        assert shed_events[0].retryable is True
        assert _wait_for(lambda: released == [("w", "t-expired")]), released
        m = s.metrics
        assert m.counter("scheduler.deputy_takeover_total").value == 1
        assert m.counter("scheduler.shed_deadline_total").value == 1
        # depth bookkeeping: expired decremented by the sweep, live
        # entries stay buffered (awaiting our own manifest's loopback,
        # which this transport-only harness never routes back)
        assert m.gauge(
            f"scheduler.queue_depth.{wire.PRIORITY_INTERACTIVE}"
        ).value == 0
        # an immediate second sweep must not double-fire the taken-over
        # entries (their clocks were reset by the takeover)
        s._fallback_sweep(KEY)
        time.sleep(0.1)
        assert len(manifests) == 1
    finally:
        sub_m.unsubscribe()
        sub_q.unsubscribe()
        s.close()


# -- decline-responder cap + expiry ----------------------------------------


class _RecordingSub:
    def __init__(self, inner):
        self.inner = inner
        self.unsubscribed = False

    def unsubscribe(self):
        self.unsubscribed = True
        self.inner.unsubscribe()


def test_decline_responder_cap_and_expiry_unsubscribe(fabric):
    s = _sched(fabric, decline_cap=2, batch_patience_s=0.3)
    subs = []
    orig_subscribe = s.transport.pubsub.subscribe

    def recording_subscribe(topic, handler):
        sub = _RecordingSub(orig_subscribe(topic, handler))
        subs.append(sub)
        return sub

    s.transport.pubsub.subscribe = recording_subscribe
    try:
        for i in range(3):
            s._decline_batch(f"sid{i}", f"decl.topic{i}", "refused")
        # cap enforced: the OLDEST responder was evicted and unsubscribed
        with s._lock:
            assert list(s._decline_responders) == ["sid1", "sid2"]
        assert subs[0].unsubscribed, "evicted responder still subscribed"
        assert not subs[1].unsubscribed and not subs[2].unsubscribed
        assert s.metrics.counter(
            "scheduler.declines_evicted_total"
        ).value == 1

        # expiry: after the patience window every responder is gone AND
        # its transport subscription is actually torn down
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with s._lock:
                if not s._decline_responders:
                    break
            time.sleep(0.05)
        with s._lock:
            assert not s._decline_responders, "expiry never fired"
        assert subs[1].unsubscribed and subs[2].unsubscribed, (
            "expired responders left live subscriptions behind"
        )
    finally:
        s.transport.pubsub.subscribe = orig_subscribe
        s.close()


# -- secp digest LRU bound -------------------------------------------------


def test_digest_cache_lru_bounded(fabric, monkeypatch):
    import mpcium_tpu.protocol.ecdsa.batch_signing as ebs

    monkeypatch.setattr(bs, "_DIGEST_CACHE_CAP", 3)
    loads = []
    monkeypatch.setattr(
        ebs, "quorum_material_digest", lambda share: f"dig-{share.wid}"
    )

    info = types.SimpleNamespace(
        participant_peer_ids=("n0", "n1", "n2"), threshold=1, epoch=0
    )
    node = _node()
    node.keyinfo = types.SimpleNamespace(get=lambda kt, w: info)

    def load_share(kt, w):
        loads.append(w)
        return types.SimpleNamespace(epoch=0, wid=w)

    node.load_share = load_share
    s = _sched(fabric, node=node, window_s=60.0)
    try:
        def sign(w, t):
            msg = wire.SignTxMessage(
                key_type=wire.KEY_TYPE_SECP256K1, wallet_id=w,
                network_internal_code="eth", tx_id=t, tx=b"\x02" * 32,
            )
            assert s.submit(msg, f"reply.{t}")

        for i in range(5):
            sign(f"w{i}", f"t{i}")
        with s._lock:
            cached = [k[1] for k in s._digest_cache]
        # bounded at the cap, oldest evicted first
        assert cached == ["w2", "w3", "w4"]

        # cache hit: a second tx for a resident wallet loads no share...
        n_loads = len(loads)
        sign("w4", "t4b")
        assert len(loads) == n_loads
        # ...and LRU-touches it, so it outlives a newer insertion
        sign("w2", "t2b")  # touch w2 → w3 is now the LRU victim
        sign("w5", "t5")
        with s._lock:
            cached = [k[1] for k in s._digest_cache]
        assert "w2" in cached and "w3" not in cached
    finally:
        s.close()
