"""Batch scheduler, secp256k1: N concurrent GG18 signing requests
coalesce into ONE distributed engine dispatch per node (VERDICT r3 item 4
— the production ECDSA path no longer runs per-session host arithmetic).
Shrunk 1024-bit keys/domains; full-size GG18 runs in bench.py and
test_gg18_full_size."""
import secrets
import threading
import time

import pytest

pytestmark = pytest.mark.slow

from mpcium_tpu import wire
from mpcium_tpu.cluster import LocalCluster, load_test_preparams
from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.engine import gg18_batch as gb

TEST_DOM = gb.Domains(alpha=600, beta_prime=320, gamma_bob=600)
N_WALLETS = 2  # shares kernel shapes with the engine tests (serializer quirk)


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    pre = load_test_preparams(bits=1024)
    c = LocalCluster(
        n_nodes=3,
        threshold=1,
        root_dir=str(tmp_path_factory.mktemp("bsched-ecdsa")),
        preparams=pre,
        batch_signing=True,
        batch_window_s=0.25,
        reply_timeout_s=60.0,
    )
    ids = c.node_ids
    shares = gb.dealer_keygen_secp_batch(
        N_WALLETS, ids, threshold=1, preparams=pre
    )
    for w in range(N_WALLETS):
        for i, nid in enumerate(ids):
            c.nodes[nid].save_share(shares[i][w], f"gw{w}")
    c._test_shares = shares
    for ec in c.consumers:
        ec.scheduler.gg18_dom = TEST_DOM
        ec.scheduler.manifest_timeout_s = 600.0  # cold-cache compiles
    yield c
    c.close()


def test_ecdsa_batched_signing_coalesces(cluster):
    n = N_WALLETS
    results = {}
    done = threading.Event()

    def on_result(ev):
        results[ev.tx_id] = ev
        if len(results) == n:
            done.set()

    sub = cluster.client.on_sign_result(on_result)
    txs = {}
    try:
        start_batches = sum(
            ec.scheduler.batches_run for ec in cluster.consumers
        )
        for w in range(n):
            tx = secrets.token_bytes(32)
            tx_id = f"gtx-{w}"
            txs[tx_id] = (w, tx)
            cluster.client.sign_transaction(
                wire.SignTxMessage(
                    key_type="secp256k1",
                    wallet_id=f"gw{w}",
                    network_internal_code="eth",
                    tx_id=tx_id,
                    tx=tx,
                )
            )
        assert done.wait(1800), f"only {len(results)}/{n} results arrived"
    finally:
        sub.unsubscribe()

    for tx_id, ev in results.items():
        w, tx = txs[tx_id]
        assert ev.result_type == wire.RESULT_SUCCESS, (
            f"{tx_id}: {ev.error_reason}"
        )
        pub = hm.secp_decompress(cluster._test_shares[0][w].public_key)
        r = int(ev.r, 16)
        s = int(ev.s, 16)
        assert hm.ecdsa_verify(pub, int.from_bytes(tx, "big"), r, s), tx_id
        assert int(ev.signature_recovery, 16) in (0, 1, 2, 3)

    # the point: N concurrent ECDSA requests ran as ~1 engine dispatch per
    # node, not N per-session protocols
    end_batches = sum(ec.scheduler.batches_run for ec in cluster.consumers)
    per_node = (end_batches - start_batches) / len(cluster.consumers)
    assert per_node <= 2, (
        f"expected ≤2 batches per node for {n} concurrent txs, got {per_node}"
    )

    # claim hygiene: no stranded dedup claims after the batch completes
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        leaked = {
            ec.node.node_id: [k for k in ec._sessions if k.startswith("gw")]
            for ec in cluster.consumers
        }
        if not any(leaked.values()):
            break
        time.sleep(0.5)
    assert not any(leaked.values()), f"stranded dedup claims: {leaked}"
