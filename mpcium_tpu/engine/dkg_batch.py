"""Batched distributed key generation + resharing engines.

BASELINE configs 4–5 (SURVEY.md §6): 4096-wallet DKG and 1024-wallet
committee rotation. The reference runs one tss-lib keygen/resharing party
per wallet (ecdsa_keygen_session.go:89-152, ecdsa_resharing_session.go:
114-203); here B wallets' Feldman-VSS arithmetic runs as fused device
dispatches per party per round:

- polynomial sampling: (B, t+1) scalars per party;
- Feldman commitments: batched fixed-base scalar-mults;
- sub-shares f_i(x_j): Horner over the scalar ring (constant x_j);
- sub-share verification: f_i(x_j)·G == Σ_k x_j^k·C_ik via point-Horner
  (x_j is a tiny participant index ⇒ 8-bit ladders);
- hash commit/reveal binding: device SHA-256 over compressed-point blocks.

For secp256k1 the per-NODE Paillier/ring-Pedersen material (preparams) is
independent of the wallet batch — generated once at startup (reference
node.go:69) and attached outside this engine — so ECDSA and EdDSA DKG
share the same batched curve core.

In-process fabric (like eddsa_batch.BatchedCoSigners / GG18BatchCoSigners):
computes every party's side for bench/tests; the distributed node runs the
same kernels per party.
"""
from __future__ import annotations

import functools
import secrets
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import bignum as bn
from ..core import ed25519_jax as ed
from ..core import hostmath as hm
from ..core import secp256k1_jax as sp
from ..core.bignum import P256
from ..ops.sha256 import sha256 as dev_sha256
from ..perf import compile_watch
from ..protocol.base import KeygenShare, party_xs
from ..utils import tracing


def _trace_sync(tensors) -> None:
    """Phase-boundary sync for mpctrace phase timers — reached only when
    tracing is armed (untraced runs never sync here)."""
    jax.block_until_ready(tensors)  # mpcflow: host-ok — trace instrumentation, only when tracing is armed

SCALAR_BITS = 256


def _curve(key_type: str):
    if key_type == "ed25519":
        return ed, hm.ED_L
    if key_type == "secp256k1":
        return sp, hm.SECP_N
    raise ValueError(key_type)


def _compress_host(key_type: str, pt) -> List[bytes]:
    mod, _ = _curve(key_type)
    # mpcflow: host-ok — public-point wire serialization (compressed bytes)
    return [bytes(c) for c in np.asarray(mod.compress(pt))]


def _rand_scalars(shape: Tuple[int, ...], order: int, rng) -> np.ndarray:
    """Uniform scalars mod order as limb tensors (wide-reduction)."""
    flat = int(np.prod(shape))
    vals = [
        int.from_bytes(rng.token_bytes(40), "little") % order
        for _ in range(flat)
    ]
    return bn.batch_to_limbs(vals, P256).reshape(*shape, P256.n_limbs)


@functools.partial(jax.jit, static_argnames=("key_type",))
def _commit_phase(coeffs: jnp.ndarray, blinds: jnp.ndarray, key_type: str):
    """coeffs (q, t+1, B, 22) → (commitment points [per party: list over k],
    compressed blocks (q, t+1, B, 32), hash commitments (q, B, 32))."""
    mod, _ = _curve(key_type)
    q, tp1 = coeffs.shape[0], coeffs.shape[1]
    pts, comps, commits = [], [], []
    for i in range(q):
        row_pts, row_comps = [], []
        for kdeg in range(tp1):
            pt = mod.base_mul(
                bn.limbs_to_bits(coeffs[i, kdeg], P256, SCALAR_BITS)
            )
            row_pts.append(pt)
            row_comps.append(mod.compress(pt))
        pts.append(row_pts)
        block = jnp.concatenate(row_comps, axis=-1)  # (B, 32·(t+1))
        tag = np.frombuffer(b"mpcium-tpu/dkg/%d" % i, dtype=np.uint8)
        tag_t = jnp.broadcast_to(jnp.asarray(tag), block.shape[:-1] + tag.shape)
        commits.append(
            dev_sha256(jnp.concatenate([tag_t, blinds[i], block], axis=-1))
        )
        comps.append(jnp.stack(row_comps))
    return pts, jnp.stack(comps), jnp.stack(commits)


@functools.partial(jax.jit, static_argnames=("key_type", "xs"))
def _subshare_phase(coeffs: jnp.ndarray, key_type: str, xs: Tuple[int, ...]):
    """f_i(x_j) for every (party i, recipient j): (q, n_recv, B, 22)."""
    _, order = _curve(key_type)
    ring = (sp if key_type == "secp256k1" else ed).scalar_ring()
    q, tp1, B = coeffs.shape[0], coeffs.shape[1], coeffs.shape[2]
    out = []
    for i in range(q):
        row = []
        for xj in xs:
            acc = coeffs[i, tp1 - 1]
            xl = ring.const(xj, (B,))
            for kdeg in range(tp1 - 2, -1, -1):
                acc = ring.addmod(ring.mulmod(acc, xl), coeffs[i, kdeg])
            row.append(acc)
        out.append(jnp.stack(row))
    return jnp.stack(out)


def _xj_bits(xj: int, B: int) -> jnp.ndarray:
    """Participant x-coordinate as an 8-bit operand row (indices are tiny)."""
    assert xj.bit_length() <= 8
    return jnp.broadcast_to(
        jnp.asarray([(xj >> b) & 1 for b in range(8)], jnp.int32), (B, 8)
    )


@functools.partial(jax.jit, static_argnames=("key_type",))
def _blk_vss_check(subshare, pts_desc, xbits, key_type: str):
    """One (dealer i, recipient j) Feldman check: f_i(x_j)·G == Σ x^k·C_ik.
    Point-Horner with the tiny x as an OPERAND — one compiled block reused
    for every (i, j) pair (monolithic verify executables crashed XLA's
    CPU serializer; block granularity also caches better)."""
    mod, _ = _curve(key_type)
    lhs = mod.base_mul(bn.limbs_to_bits(subshare, P256, SCALAR_BITS))
    acc = pts_desc[0]
    for pt in pts_desc[1:]:
        acc = mod.add(mod.scalar_mul(xbits, acc), pt)
    return mod.equal(lhs, acc)


def _verify_phase_points(subshares, pts, key_type: str, xs):
    """VSS check against in-process commitment POINTS (both curves)."""
    q = len(pts)
    B = subshares.shape[2]
    ok = jnp.ones((B,), bool)
    for i in range(q):
        pts_desc = tuple(pts[i][::-1])
        for j, xj in enumerate(xs):
            ok = ok & _blk_vss_check(
                subshares[i, j], pts_desc, _xj_bits(xj, B), key_type
            )
    return ok


class BatchedDKG:
    """In-process q-party Feldman DKG for B wallets (bench/test fabric —
    the distributed node runs one side of the same kernels per party)."""

    def __init__(
        self,
        party_ids: Sequence[str],
        threshold: int,
        key_type: str,
        rng=secrets,
    ):
        # preserve caller order: run()'s result[i] belongs to party_ids[i]
        self.ids = list(party_ids)
        self.t = threshold
        self.key_type = key_type
        self.rng = rng
        if not 0 < threshold < len(self.ids):
            raise ValueError("need 0 < t < n")
        self.xs = party_xs(self.ids)

    def run(self, n_wallets: int) -> List[List[KeygenShare]]:
        """Returns per-party share lists (result[i] → party_ids[i]),
        wallet-aligned. Raises on any VSS/commitment failure."""
        mod, order = _curve(self.key_type)
        q, t, B = len(self.ids), self.t, n_wallets
        _pt = tracing.PhaseTimer(
            "dkg.run", _trace_sync, node="engine", tid=f"dkg:B{B}",
        )
        # mpcshape: unbounded-ok — B is pow-2 snapped upstream (scheduler chunks via engine/buckets.floor_bucket; bench via bucket_b)
        _cw = compile_watch.begin("dkg.run", f"B{B}|q{q}|{self.key_type}")
        xs_tuple = tuple(self.xs[p] for p in self.ids)
        coeffs = jnp.asarray(
            _rand_scalars((q, t + 1, B), order, self.rng)
        )
        blinds = jnp.asarray(
            np.frombuffer(
                self.rng.token_bytes(q * B * 32), dtype=np.uint8
            ).reshape(q, B, 32)
        )
        pts, comps, commits = _commit_phase(coeffs, blinds, self.key_type)
        _pt.mark("commit", commits)
        # reveal phase is implicit in-process; re-check binding + VSS
        subshares = _subshare_phase(coeffs, self.key_type, xs_tuple)
        _pt.mark("subshare", subshares)
        ok = _verify_phase_points(subshares, pts, self.key_type, xs_tuple)
        _pt.mark("vss_verify", ok)
        if not bool(np.asarray(ok).all()):
            raise RuntimeError("batched DKG: VSS verification failed")
        # aggregate
        ring = mod.scalar_ring()
        agg = subshares[0]
        for i in range(1, q):
            agg = ring.addmod(agg, subshares[i])
        # single device→host pull for the whole (q, B) share block instead
        # of one np.asarray round-trip per party
        agg_host = np.asarray(agg)  # mpcflow: host-ok — aggregated shares leave device once, for the returned share objects
        agg_shares = [agg_host[j] for j in range(q)]
        agg_pts = []
        for kdeg in range(t + 1):
            acc = pts[0][kdeg]
            for i in range(1, q):
                acc = mod.add(acc, pts[i][kdeg])
            agg_pts.append(acc)
        agg_comp = [
            _compress_host(self.key_type, acc) for acc in agg_pts
        ]  # (t+1) lists of B byte strings
        pubs = agg_comp[0]
        shares_int = [
            bn.batch_from_limbs(s, P256) for s in agg_shares
        ]
        out: List[List[KeygenShare]] = [[] for _ in self.ids]
        for w in range(B):
            vss = [agg_comp[kdeg][w] for kdeg in range(t + 1)]
            for j, pid in enumerate(self.ids):
                out[j].append(
                    KeygenShare(
                        key_type=self.key_type,
                        share=shares_int[j][w],
                        self_x=self.xs[pid],
                        public_key=pubs[w],
                        vss_commitments=vss,
                        participants=list(self.ids),
                        threshold=t,
                    )
                )
        _pt.mark("aggregate_assemble")
        compile_watch.finish(_cw)
        return out


class BatchedReshare:
    """In-process batched committee rotation (BASELINE config 5): an old
    quorum re-deals B wallets' secrets to a new committee under a new
    threshold; public keys unchanged (protocol/resharing.py semantics,
    batched)."""

    def __init__(
        self,
        old_quorum: Sequence[str],
        old_shares: Sequence[Sequence[KeygenShare]],  # per old member
        new_committee: Sequence[str],
        new_threshold: int,
        rng=secrets,
    ):
        self.old_quorum = list(old_quorum)
        self.old_shares = old_shares
        # preserve caller order: run()'s result[j] → new_committee[j]
        self.new_committee = list(new_committee)
        self.t_new = new_threshold
        self.rng = rng
        first = old_shares[0][0]
        self.key_type = first.key_type
        self.B = len(old_shares[0])
        if not 0 < new_threshold < len(self.new_committee):
            raise ValueError("need 0 < t_new < |new committee|")

    def run(self) -> List[List[KeygenShare]]:
        """Returns per-NEW-member share lists; verifies the redeal binds to
        the old public keys."""
        mod, order = _curve(self.key_type)
        ring = mod.scalar_ring()
        B, t_new = self.B, self.t_new
        q_old = len(self.old_quorum)
        _pt = tracing.PhaseTimer(
            "reshare.run", _trace_sync, node="engine", tid=f"reshare:B{B}",
        )
        # mpcshape: unbounded-ok — B is pow-2 snapped upstream (scheduler chunks via engine/buckets.floor_bucket; bench via bucket_b)
        _cw = compile_watch.begin(
            "reshare.run", f"B{B}|{self.key_type}|t{t_new}"
        )
        new_xs = party_xs(self.new_committee)
        xs_tuple = tuple(new_xs[p] for p in self.new_committee)
        first = self.old_shares[0][0]
        old_xs = party_xs(first.participants)
        quorum_xs = [old_xs[p] for p in self.old_quorum]

        # coeff0 = w_i = λ_i·x_i; higher coeffs fresh
        coeffs_np = _rand_scalars((q_old, t_new + 1, B), order, self.rng)
        for i, pid in enumerate(self.old_quorum):
            lam = hm.lagrange_coeff(quorum_xs, old_xs[pid], order)
            w = [
                lam * s.share % order for s in self.old_shares[i]
            ]
            coeffs_np[i, 0] = bn.batch_to_limbs(w, P256)
        coeffs = jnp.asarray(coeffs_np)
        blinds = jnp.asarray(
            np.frombuffer(
                self.rng.token_bytes(q_old * B * 32), dtype=np.uint8
            ).reshape(q_old, B, 32)
        )
        pts, comps, commits = _commit_phase(coeffs, blinds, self.key_type)
        _pt.mark("commit", commits)
        subshares = _subshare_phase(coeffs, self.key_type, xs_tuple)
        _pt.mark("subshare", subshares)
        ok = _verify_phase_points(subshares, pts, self.key_type, xs_tuple)
        _pt.mark("vss_verify", ok)

        # redeal binding: Σ_i C_i0 must equal the old public key
        pub_sum = pts[0][0]
        for i in range(1, q_old):
            pub_sum = mod.add(pub_sum, pts[i][0])
        pub_comp = _compress_host(self.key_type, pub_sum)
        for w in range(B):
            if pub_comp[w] != self.old_shares[0][w].public_key:
                raise RuntimeError(
                    f"resharing changed the public key for wallet {w}"
                )
        if not bool(np.asarray(ok).all()):
            raise RuntimeError("batched resharing: VSS verification failed")

        agg = subshares[0]
        for i in range(1, q_old):
            agg = ring.addmod(agg, subshares[i])
        # single device→host pull, mirroring BatchedDKG.run
        agg_host = np.asarray(agg)  # mpcflow: host-ok — aggregated shares leave device once, for the returned share objects
        agg_shares = [agg_host[j] for j in range(len(self.new_committee))]
        agg_comp = []
        for kdeg in range(t_new + 1):
            acc = pts[0][kdeg]
            for i in range(1, q_old):
                acc = mod.add(acc, pts[i][kdeg])
            agg_comp.append(_compress_host(self.key_type, acc))
        shares_int = [bn.batch_from_limbs(s, P256) for s in agg_shares]
        epoch = first.epoch + 1
        out: List[List[KeygenShare]] = [[] for _ in self.new_committee]
        for w in range(B):
            vss = [agg_comp[kdeg][w] for kdeg in range(t_new + 1)]
            for j, pid in enumerate(self.new_committee):
                out[j].append(
                    KeygenShare(
                        key_type=self.key_type,
                        share=shares_int[j][w],
                        self_x=new_xs[pid],
                        public_key=self.old_shares[0][w].public_key,
                        vss_commitments=vss,
                        participants=list(self.new_committee),
                        threshold=t_new,
                        epoch=epoch,
                        aux={"is_reshared": True},
                    )
                )
        _pt.mark("aggregate_assemble")
        compile_watch.finish(_cw)
        return out
