"""Batched distributed key generation + resharing engines.

BASELINE configs 4–5 (SURVEY.md §6): 4096-wallet DKG and 1024-wallet
committee rotation. The reference runs one tss-lib keygen/resharing party
per wallet (ecdsa_keygen_session.go:89-152, ecdsa_resharing_session.go:
114-203); here B wallets' Feldman-VSS arithmetic runs as fused device
dispatches per party per round:

- polynomial sampling: (B, t+1) scalars per party;
- Feldman commitments: batched fixed-base scalar-mults;
- sub-shares f_i(x_j): Horner over the scalar ring (constant x_j);
- sub-share verification: f_i(x_j)·G == Σ_k x_j^k·C_ik via point-Horner
  (x_j is a tiny participant index ⇒ 8-bit ladders);
- hash commit/reveal binding: device SHA-256 over compressed-point blocks.

For secp256k1 the per-NODE Paillier/ring-Pedersen material (preparams) is
independent of the wallet batch — generated once at startup (reference
node.go:69) and attached outside this engine — so ECDSA and EdDSA DKG
share the same batched curve core.

In-process fabric (like eddsa_batch.BatchedCoSigners / GG18BatchCoSigners):
computes every party's side for bench/tests; the distributed node runs the
same kernels per party.
"""
from __future__ import annotations

import functools
import secrets
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import bignum as bn
from ..core import ed25519_jax as ed
from ..core import hostmath as hm
from ..core import secp256k1_jax as sp
from ..core.bignum import P256
from ..ops.sha256 import sha256 as dev_sha256
from ..perf import compile_watch
from ..protocol.base import KeygenShare, party_xs
from ..utils import tracing
from . import pipeline as pl


def _trace_sync(tensors) -> None:
    """Phase-boundary sync for mpctrace phase timers — reached only when
    tracing is armed (untraced runs never sync here)."""
    jax.block_until_ready(tensors)  # mpcflow: host-ok — trace instrumentation, only when tracing is armed

SCALAR_BITS = 256


def _curve(key_type: str):
    if key_type == "ed25519":
        return ed, hm.ED_L
    if key_type == "secp256k1":
        return sp, hm.SECP_N
    raise ValueError(key_type)


def _compress_host(key_type: str, pt) -> List[bytes]:
    mod, _ = _curve(key_type)
    # mpcflow: host-ok — public-point wire serialization (compressed bytes)
    return [bytes(c) for c in np.asarray(mod.compress(pt))]


def _rand_scalars(shape: Tuple[int, ...], order: int, rng) -> np.ndarray:
    """Uniform scalars mod order as limb tensors (wide-reduction)."""
    flat = int(np.prod(shape))
    vals = [
        int.from_bytes(rng.token_bytes(40), "little") % order
        for _ in range(flat)
    ]
    return bn.batch_to_limbs(vals, P256).reshape(*shape, P256.n_limbs)


@functools.partial(jax.jit, static_argnames=("key_type",))
def _commit_phase(coeffs: jnp.ndarray, blinds: jnp.ndarray, key_type: str):
    """coeffs (q, t+1, B, 22) → (commitment points [per party: list over k],
    compressed blocks (q, t+1, B, 32), hash commitments (q, B, 32))."""
    mod, _ = _curve(key_type)
    q, tp1 = coeffs.shape[0], coeffs.shape[1]
    pts, comps, commits = [], [], []
    for i in range(q):
        row_pts, row_comps = [], []
        for kdeg in range(tp1):
            pt = mod.base_mul(
                bn.limbs_to_bits(coeffs[i, kdeg], P256, SCALAR_BITS)
            )
            row_pts.append(pt)
            row_comps.append(mod.compress(pt))
        pts.append(row_pts)
        block = jnp.concatenate(row_comps, axis=-1)  # (B, 32·(t+1))
        tag = np.frombuffer(b"mpcium-tpu/dkg/%d" % i, dtype=np.uint8)
        tag_t = jnp.broadcast_to(jnp.asarray(tag), block.shape[:-1] + tag.shape)
        commits.append(
            dev_sha256(jnp.concatenate([tag_t, blinds[i], block], axis=-1))
        )
        comps.append(jnp.stack(row_comps))
    return pts, jnp.stack(comps), jnp.stack(commits)


@functools.partial(jax.jit, static_argnames=("key_type", "xs"))
def _subshare_phase(coeffs: jnp.ndarray, key_type: str, xs: Tuple[int, ...]):
    """f_i(x_j) for every (party i, recipient j): (q, n_recv, B, 22)."""
    _, order = _curve(key_type)
    ring = (sp if key_type == "secp256k1" else ed).scalar_ring()
    q, tp1, B = coeffs.shape[0], coeffs.shape[1], coeffs.shape[2]
    out = []
    for i in range(q):
        row = []
        for xj in xs:
            acc = coeffs[i, tp1 - 1]
            xl = ring.const(xj, (B,))
            for kdeg in range(tp1 - 2, -1, -1):
                acc = ring.addmod(ring.mulmod(acc, xl), coeffs[i, kdeg])
            row.append(acc)
        out.append(jnp.stack(row))
    return jnp.stack(out)


def _xj_bits(xj: int, B: int) -> jnp.ndarray:
    """Participant x-coordinate as an 8-bit operand row (indices are tiny)."""
    assert xj.bit_length() <= 8
    return jnp.broadcast_to(
        jnp.asarray([(xj >> b) & 1 for b in range(8)], jnp.int32), (B, 8)
    )


@functools.partial(jax.jit, static_argnames=("key_type",))
def _blk_vss_check(subshare, pts_desc, xbits, key_type: str):
    """One (dealer i, recipient j) Feldman check: f_i(x_j)·G == Σ x^k·C_ik.
    Point-Horner with the tiny x as an OPERAND — one compiled block reused
    for every (i, j) pair (monolithic verify executables crashed XLA's
    CPU serializer; block granularity also caches better)."""
    mod, _ = _curve(key_type)
    lhs = mod.base_mul(bn.limbs_to_bits(subshare, P256, SCALAR_BITS))
    acc = pts_desc[0]
    for pt in pts_desc[1:]:
        acc = mod.add(mod.scalar_mul(xbits, acc), pt)
    return mod.equal(lhs, acc)


def _verify_phase_points(subshares, pts, key_type: str, xs):
    """VSS check against in-process commitment POINTS (both curves)."""
    q = len(pts)
    B = subshares.shape[2]
    ok = jnp.ones((B,), bool)
    for i in range(q):
        pts_desc = tuple(pts[i][::-1])
        for j, xj in enumerate(xs):
            ok = ok & _blk_vss_check(
                subshares[i, j], pts_desc, _xj_bits(xj, B), key_type
            )
    return ok


def _vss_core(
    engine: str,
    key_type: str,
    xs_tuple: Tuple[int, ...],
    coeffs: jnp.ndarray,
    blinds: jnp.ndarray,
    plan: pl.CohortPlan,
    _pt: tracing.PhaseTimer,
):
    """The shared DKG/reshare round core — commit → subshare → VSS
    verify → aggregate — run per counter-phase cohort (engine/pipeline).

    All secret material (``coeffs``, ``blinds``) is drawn by the caller
    for the FULL batch in K=1 serial order before the split; each cohort
    only ever slices it along the wallet axis, so share values and
    commitment bytes are bit-identical for every K.

    Returns ``(ok, agg, comp)`` merged back to batch order: ``ok`` a
    host (B,) verdict row, ``agg`` the aggregated sub-share block
    (n_recv, B, limbs) pulled device→host once per cohort, and ``comp``
    the aggregate commitment bytes ``[t+1][B]`` (``comp[0]`` is the
    public-key row).
    """
    mod, _ = _curve(key_type)
    ring = mod.scalar_ring()
    q = int(coeffs.shape[0])  # mpcflow: host-ok — static shape metadata, no device readback
    tp1 = int(coeffs.shape[1])

    def rounds(mark, c_coeffs, c_blinds):
        pts, _comps, commits = _commit_phase(c_coeffs, c_blinds, key_type)
        mark("commit", commits)
        subshares = _subshare_phase(c_coeffs, key_type, xs_tuple)
        mark("subshare", subshares)
        ok = _verify_phase_points(subshares, pts, key_type, xs_tuple)
        mark("vss_verify", ok)
        agg = subshares[0]
        for i in range(1, q):
            agg = ring.addmod(agg, subshares[i])
        agg_pts = []
        for kdeg in range(tp1):
            acc = pts[0][kdeg]
            for i in range(1, q):
                acc = mod.add(acc, pts[i][kdeg])
            agg_pts.append(acc)
        return ok, agg, agg_pts

    if plan.serial:
        ok, agg, agg_pts = rounds(_pt.mark, coeffs, blinds)
        ok_h = np.asarray(ok)  # mpcflow: host-ok — verdict egress
        agg_h = np.asarray(agg)  # mpcflow: host-ok — aggregated shares leave device once, for the returned share objects
        comp = [_compress_host(key_type, acc) for acc in agg_pts]
        return ok_h, agg_h, comp

    cohort_phases = [
        dict() if _pt.phases is not None else None for _ in range(plan.k)
    ]

    def make_job(ci: int, sl: slice):
        def job():
            cpt = tracing.PhaseTimer(
                engine, _trace_sync, phase_times=cohort_phases[ci],
                node="engine", tid=f"{_pt.tid}:c{ci}",
            )
            ok, agg, agg_pts = rounds(
                cpt.mark, coeffs[:, :, sl], blinds[:, sl]
            )
            out = yield (
                "share_egress",
                lambda: (
                    np.asarray(ok),  # mpcflow: host-ok — verdict egress
                    np.asarray(agg),  # mpcflow: host-ok — aggregated shares leave device once per cohort
                    [_compress_host(key_type, acc) for acc in agg_pts],
                ),
            )
            return out

        return job

    outs = pl.run_counter_phase(
        [make_job(ci, sl) for ci, sl in enumerate(plan.slices())]
    )
    if _pt.phases is not None:
        for d in cohort_phases:
            for name, dt in d.items():
                _pt.phases[name] = _pt.phases.get(name, 0.0) + dt
    ok_h = pl.merge_rows([o[0] for o in outs])
    agg_h = pl.merge_rows([o[1] for o in outs], axis=1)
    comp = [
        [c for o in outs for c in o[2][kdeg]] for kdeg in range(tp1)
    ]
    return ok_h, agg_h, comp


class BatchedDKG:
    """In-process q-party Feldman DKG for B wallets (bench/test fabric —
    the distributed node runs one side of the same kernels per party)."""

    def __init__(
        self,
        party_ids: Sequence[str],
        threshold: int,
        key_type: str,
        rng=secrets,
    ):
        # preserve caller order: run()'s result[i] belongs to party_ids[i]
        self.ids = list(party_ids)
        self.t = threshold
        self.key_type = key_type
        self.rng = rng
        if not 0 < threshold < len(self.ids):
            raise ValueError("need 0 < t < n")
        self.xs = party_xs(self.ids)

    def run(
        self, n_wallets: int, cohorts: Optional[int] = None
    ) -> List[List[KeygenShare]]:
        """Returns per-party share lists (result[i] → party_ids[i]),
        wallet-aligned. Raises on any VSS/commitment failure.

        ``cohorts`` picks the counter-phase cohort count (see
        engine/pipeline.resolve_cohorts); shares and commitment bytes
        are bit-identical for every K because all polynomial
        coefficients and blinds are drawn full-batch before the split.
        """
        _, order = _curve(self.key_type)
        q, t, B = len(self.ids), self.t, n_wallets
        _pt = tracing.PhaseTimer(
            "dkg.run", _trace_sync, node="engine", tid=f"dkg:B{B}",
        )
        # mpcshape: unbounded-ok — B is pow-2 snapped upstream (scheduler chunks via engine/buckets.floor_bucket; bench via bucket_b)
        _cw = compile_watch.begin("dkg.run", f"B{B}|q{q}|{self.key_type}")
        xs_tuple = tuple(self.xs[p] for p in self.ids)
        coeffs = jnp.asarray(
            _rand_scalars((q, t + 1, B), order, self.rng)
        )
        blinds = jnp.asarray(
            np.frombuffer(
                self.rng.token_bytes(q * B * 32), dtype=np.uint8
            ).reshape(q, B, 32)
        )
        plan = pl.CohortPlan.for_batch(B, cohorts)
        ok, agg_host, agg_comp = _vss_core(
            "dkg.run", self.key_type, xs_tuple, coeffs, blinds, plan, _pt
        )
        if not bool(ok.all()):
            raise RuntimeError("batched DKG: VSS verification failed")
        agg_shares = [agg_host[j] for j in range(q)]
        pubs = agg_comp[0]
        shares_int = [
            bn.batch_from_limbs(s, P256) for s in agg_shares
        ]
        out: List[List[KeygenShare]] = [[] for _ in self.ids]
        for w in range(B):
            vss = [agg_comp[kdeg][w] for kdeg in range(t + 1)]
            for j, pid in enumerate(self.ids):
                out[j].append(
                    KeygenShare(
                        key_type=self.key_type,
                        share=shares_int[j][w],
                        self_x=self.xs[pid],
                        public_key=pubs[w],
                        vss_commitments=vss,
                        participants=list(self.ids),
                        threshold=t,
                    )
                )
        _pt.mark("aggregate_assemble")
        compile_watch.finish(_cw)
        return out


class BatchedReshare:
    """In-process batched committee rotation (BASELINE config 5): an old
    quorum re-deals B wallets' secrets to a new committee under a new
    threshold; public keys unchanged (protocol/resharing.py semantics,
    batched)."""

    def __init__(
        self,
        old_quorum: Sequence[str],
        old_shares: Sequence[Sequence[KeygenShare]],  # per old member
        new_committee: Sequence[str],
        new_threshold: int,
        rng=secrets,
    ):
        self.old_quorum = list(old_quorum)
        self.old_shares = old_shares
        # preserve caller order: run()'s result[j] → new_committee[j]
        self.new_committee = list(new_committee)
        self.t_new = new_threshold
        self.rng = rng
        first = old_shares[0][0]
        self.key_type = first.key_type
        self.B = len(old_shares[0])
        if not 0 < new_threshold < len(self.new_committee):
            raise ValueError("need 0 < t_new < |new committee|")

    def run(self, cohorts: Optional[int] = None) -> List[List[KeygenShare]]:
        """Returns per-NEW-member share lists; verifies the redeal binds to
        the old public keys. ``cohorts`` as in :meth:`BatchedDKG.run`."""
        _, order = _curve(self.key_type)
        B, t_new = self.B, self.t_new
        q_old = len(self.old_quorum)
        _pt = tracing.PhaseTimer(
            "reshare.run", _trace_sync, node="engine", tid=f"reshare:B{B}",
        )
        # mpcshape: unbounded-ok — B is pow-2 snapped upstream (scheduler chunks via engine/buckets.floor_bucket; bench via bucket_b)
        _cw = compile_watch.begin(
            "reshare.run", f"B{B}|{self.key_type}|t{t_new}"
        )
        new_xs = party_xs(self.new_committee)
        xs_tuple = tuple(new_xs[p] for p in self.new_committee)
        first = self.old_shares[0][0]
        old_xs = party_xs(first.participants)
        quorum_xs = [old_xs[p] for p in self.old_quorum]

        # coeff0 = w_i = λ_i·x_i; higher coeffs fresh
        coeffs_np = _rand_scalars((q_old, t_new + 1, B), order, self.rng)
        for i, pid in enumerate(self.old_quorum):
            lam = hm.lagrange_coeff(quorum_xs, old_xs[pid], order)
            w = [
                lam * s.share % order for s in self.old_shares[i]
            ]
            coeffs_np[i, 0] = bn.batch_to_limbs(w, P256)
        coeffs = jnp.asarray(coeffs_np)
        blinds = jnp.asarray(
            np.frombuffer(
                self.rng.token_bytes(q_old * B * 32), dtype=np.uint8
            ).reshape(q_old, B, 32)
        )
        plan = pl.CohortPlan.for_batch(B, cohorts)
        ok, agg_host, agg_comp = _vss_core(
            "reshare.run", self.key_type, xs_tuple, coeffs, blinds, plan, _pt
        )

        # redeal binding: Σ_i C_i0 must equal the old public key
        pub_comp = agg_comp[0]
        for w in range(B):
            if pub_comp[w] != self.old_shares[0][w].public_key:
                raise RuntimeError(
                    f"resharing changed the public key for wallet {w}"
                )
        if not bool(ok.all()):
            raise RuntimeError("batched resharing: VSS verification failed")

        agg_shares = [agg_host[j] for j in range(len(self.new_committee))]
        shares_int = [bn.batch_from_limbs(s, P256) for s in agg_shares]
        epoch = first.epoch + 1
        out: List[List[KeygenShare]] = [[] for _ in self.new_committee]
        for w in range(B):
            vss = [agg_comp[kdeg][w] for kdeg in range(t_new + 1)]
            for j, pid in enumerate(self.new_committee):
                out[j].append(
                    KeygenShare(
                        key_type=self.key_type,
                        share=shares_int[j][w],
                        self_x=new_xs[pid],
                        public_key=self.old_shares[0][w].public_key,
                        vss_commitments=vss,
                        participants=list(self.new_committee),
                        threshold=t_new,
                        epoch=epoch,
                        aux={"is_reshared": True},
                    )
                )
        _pt.mark("aggregate_assemble")
        compile_watch.finish(_cw)
        return out
