"""Batched execution engines — the TPU layer with no reference analogue.

- ``eddsa_batch``  threshold-Ed25519 co-signing over a session batch
- ``gg18_batch``   threshold-ECDSA (GG18) co-signing on the MXU kernels
- ``dkg_batch``    batched Feldman DKG + committee resharing
- ``sharded``      multi-device meshes: (committee × sessions) shard_map
                   for EdDSA, session-axis GSPMD sharding for GG18
"""
