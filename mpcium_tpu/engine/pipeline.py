"""Counter-phase cohort pipeline: zero-idle round scheduling (ROADMAP 4).

Every batch engine used to march its rounds serially: the device sat
idle while the host packed/unpacked wire bytes and peers exchanged
messages. PR 1's chunked OT overlap (protocol/ecdsa/mta_ot.py
``run_multi``) proved the win for exactly one leg; this module is that
trick promoted to the engine's native shape, usable by *every* round of
the GG18, EdDSA, DKG and reshare engines.

The model
---------
A batch of B sessions splits into K **cohorts** — contiguous,
equal-width lane ranges (``MPCIUM_PIPELINE_COHORTS``, default 2; K=1 is
today's serial path and the transcript oracle). Each cohort's round
schedule is written as a generator that *yields* its host stages::

    def job(cohort_slice):
        x = device_round(inputs[cohort_slice])      # async dispatch
        packed = yield ("pack_wire", lambda: pack(x))   # host stage
        y = device_round2(unpack(packed))
        return finish(y)

``run_counter_phase`` drives the K generators round-robin on the main
thread with ONE background host worker: while cohort A's host thunk
drains on the worker, the scheduler advances cohort B, whose device
stage dispatches asynchronously (JAX never blocks until a value is
read).  With K=2 the cohorts execute in counter-phase — one's device
round overlaps the other's host wire stage — and the device idle
fraction between rounds collapses (``tracing.device_idle_fraction``).
Host stages are surfaced as ``host:<label>`` spans with a ``cohort``
attribute so span-derived phase tables account for them.

Transcript discipline
---------------------
Cohorting must be invisible on the wire: callers draw ALL secret
randomness for the full batch in K=1 serial order *before* splitting,
then row-slice per cohort, so signatures and transcripts are
bit-identical for every K (tests/test_pipeline.py). Cohort widths stay
on the pow-2 bucket grid (pow-2 B ÷ pow-2 K), so every pipeline stage
is a known, prewarmable compile signature; ``resolve_cohorts`` falls
back to K=1 whenever a split would leave the grid.

Pure stdlib on purpose (like engine/buckets.py): the scheduler imports
this at module load and must not pull jax.
"""
from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Generator, List, Optional, Sequence, Tuple

from ..utils import tracing
from .abort import CohortAbort
from .buckets import is_bucket

ENV_COHORTS = "MPCIUM_PIPELINE_COHORTS"
DEFAULT_COHORTS = 2
# below this many lanes per cohort the split costs more than it overlaps
MIN_COHORT_LANES = 2

# One background worker, shared process-wide (the mta_ot _HOST_POOL
# pattern): host stages of different cohorts serialize against each
# other — they contend for the GIL and wire anyway — while the main
# thread keeps dispatching device rounds.
_HOST_POOL: Optional[ThreadPoolExecutor] = None
_POOL_LOCK = threading.Lock()


def _host_pool() -> ThreadPoolExecutor:
    global _HOST_POOL
    with _POOL_LOCK:
        if _HOST_POOL is None:
            _HOST_POOL = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="pipe-host"
            )
    return _HOST_POOL


def resolve_cohorts(B: int, cohorts: Optional[int] = None) -> int:
    """The cohort count a batch of B sessions actually runs with.

    Explicit ``cohorts`` wins, then ``MPCIUM_PIPELINE_COHORTS``, then
    the default (2). The result is clamped onto the bucket grid: K must
    be a power of two dividing B with at least MIN_COHORT_LANES lanes
    per cohort — otherwise K halves until it fits (worst case K=1, the
    serial oracle). A pow-2 B therefore always yields pow-2 cohort
    widths, so cohort dispatches reuse the prewarmed bucket compiles.
    """
    if B < 1:
        raise ValueError(f"need B >= 1, got {B}")
    if cohorts is None:
        raw = os.environ.get(ENV_COHORTS, "")
        try:
            cohorts = int(raw) if raw else DEFAULT_COHORTS
        except ValueError:
            cohorts = DEFAULT_COHORTS
    k = max(1, int(cohorts))
    # floor to a power of two
    while k & (k - 1):
        k &= k - 1
    while k > 1 and (
        B % k != 0
        or (B // k) < MIN_COHORT_LANES
        or not is_bucket(B // k)
    ):
        k //= 2
    return k


class CohortPlan:
    """The lane geometry of one cohorted batch: K contiguous equal
    slices of range(B), plus the lane maps that keep identifiable abort
    (engine.abort.CohortAbort) attributable through the split."""

    def __init__(self, B: int, k: int):
        if k < 1 or B % k != 0:
            raise ValueError(f"invalid cohort plan B={B} k={k}")
        self.B = B
        self.k = k
        self.width = B // k
        self.bounds: List[Tuple[int, int]] = [
            (i * self.width, (i + 1) * self.width) for i in range(k)
        ]

    @classmethod
    def for_batch(cls, B: int, cohorts: Optional[int] = None) -> "CohortPlan":
        return cls(B, resolve_cohorts(B, cohorts))

    @property
    def serial(self) -> bool:
        return self.k == 1

    def slices(self) -> List[slice]:
        return [slice(lo, hi) for lo, hi in self.bounds]

    def split(self, arr: Any, axis: int = 0) -> List[Any]:
        """Row-slice any indexable array-like into the K cohort views
        along ``axis`` (views, not copies, for numpy/jax arrays)."""
        idx_head: Tuple = (slice(None),) * axis
        return [arr[idx_head + (sl,)] for sl in self.slices()]

    def split_tree(self, tree: Any, axis: int = 0) -> List[Any]:
        """Like :meth:`split` over a nested dict/list/tuple of arrays:
        returns K trees of the same structure with every leaf sliced."""
        if isinstance(tree, dict):
            parts = {k: self.split_tree(v, axis) for k, v in tree.items()}
            return [
                {k: v[i] for k, v in parts.items()} for i in range(self.k)
            ]
        if isinstance(tree, (list, tuple)):
            parts = [self.split_tree(v, axis) for v in tree]
            if hasattr(tree, "_fields"):  # NamedTuple (jax point pytrees)
                return [
                    type(tree)(*(p[i] for p in parts))
                    for i in range(self.k)
                ]
            return [
                type(tree)(p[i] for p in parts) for i in range(self.k)
            ]
        return self.split(tree, axis)

    def to_global(self, cohort: int, lane: int) -> int:
        """Cohort-local lane index → batch-global lane index."""
        lo, hi = self.bounds[cohort]
        if not 0 <= lane < hi - lo:
            raise ValueError(f"lane {lane} outside cohort {cohort}")
        return lo + lane

    def remap_abort(self, err: CohortAbort, cohort: int) -> CohortAbort:
        """A CohortAbort raised with cohort-LOCAL lane indices, remapped
        to batch-global lanes — blame attribution (party, check) rides
        through unchanged, so the scheduler's quarantine path (PR 16)
        names the same culprit at every K."""
        return CohortAbort(
            [
                (self.to_global(cohort, lane), party, check)
                for lane, party, check in err.culprits
            ],
            engine=err.engine,
        )


# One cohort's schedule: a generator yielding (label, host_thunk) and
# returning its result via StopIteration.value.
CohortJob = Callable[[], Generator[Tuple[str, Callable[[], Any]], Any, Any]]


def _run_host_stage(label: str, thunk: Callable[[], Any], cohort: int) -> Any:
    """Execute one host stage, surfaced as a ``host:<label>`` span with
    the cohort attribute — the other half of the idle-fraction ledger
    (device spans stay ``phase:*``)."""
    t0 = tracing.now_ns()
    try:
        return thunk()
    finally:
        tracing.emit(
            f"host:{label}", t0, tracing.now_ns(),
            node="engine", kind="X", cohort=cohort,
        )


def run_counter_phase(jobs: Sequence[CohortJob]) -> List[Any]:
    """Drive K cohort jobs in counter-phase; returns their results in
    cohort order.

    K=1 (or a single job) runs fully inline on the calling thread —
    byte-for-byte today's serial path, the transcript oracle. K>1
    round-robins the generators: each advance runs the cohort's device
    dispatches (async) up to its next host stage, which is shipped to
    the shared host worker; while that drains, the next cohort advances.
    Exceptions propagate to the caller unchanged (wrap CohortAborts with
    :meth:`CohortPlan.remap_abort` inside the job before raising).
    """
    gens = [job() for job in jobs]
    n = len(gens)
    results: List[Any] = [None] * n

    if n == 1:
        g = gens[0]
        try:
            req = next(g)
            while True:
                label, thunk = req
                req = g.send(_run_host_stage(label, thunk, 0))
        except StopIteration as fin:
            results[0] = fin.value
        return results

    pool = _host_pool()
    pending: List[Any] = [None] * n
    done = [False] * n
    remaining = n
    while remaining:
        for i, g in enumerate(gens):
            if done[i]:
                continue
            try:
                if pending[i] is None:
                    req = next(g)
                else:
                    fut, pending[i] = pending[i], None
                    req = g.send(fut.result())
                label, thunk = req
                pending[i] = pool.submit(_run_host_stage, label, thunk, i)
            except StopIteration as fin:
                results[i] = fin.value
                done[i] = True
                remaining -= 1
    return results


def merge_rows(parts: Sequence[Any], axis: int = 0):
    """Concatenate per-cohort result rows back into batch order. Works
    for numpy arrays without importing jax (jnp arrays concatenate via
    numpy's protocol and come back host-side, which is what result
    egress wants anyway)."""
    import numpy as np  # local: keep module import jax- and numpy-free

    if len(parts) == 1:
        return parts[0]
    return np.concatenate([np.asarray(p) for p in parts], axis=axis)
