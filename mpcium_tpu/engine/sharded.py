"""Multi-device execution of the batched signing step.

Two mesh axes map the framework's two parallelism dimensions (SURVEY.md
§2.2): ``committee`` — the n MPC parties (the reference's n processes,
dimension 1) — and ``sessions`` — the concurrent-wallet batch (dimension 2).
Round tensors cross the committee axis as XLA collectives over ICI
(`all_gather`), replacing the reference's NATS fan-out for the *intra-pod
simulation / bench* topology. Production trust domains keep parties on
separate hosts (SURVEY.md §7.4 item 6) — there the committee axis is 1 and
cross-party bytes ride the host transport instead; the session axis still
shards across each operator's own devices.

The full signing step is two device phases with one host hash point between
(the RFC 8032 challenge is SHA-512, control-plane):

  phase A  nonce commit:  r64 → r, R_i;  all_gather(R) → R = Σ R_i
  (host)   c = SHA512(R ‖ A ‖ M) per session
  phase B  partials s_i = r + c·λ·x;  all_gather(s_i) → s = Σ s_i;
           batched verify s·B == R + c·A
"""
from __future__ import annotations

import functools
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from . import eddsa_batch as eb

COMMITTEE = "committee"
SESSIONS = "sessions"


def arm_session_axis(n_devices: Optional[int] = None) -> Optional[Mesh]:
    """Production wiring of the session axis (SURVEY.md §2.2 dim 2):
    when this host has more than one accelerator, install a 1×N
    (committee=1 — trust domains stay on separate hosts) mesh whose
    SESSIONS axis partitions every batch tensor entering the EdDSA
    engine; GSPMD then splits each party-round dispatch across all local
    devices with no kernel or scheduler changes (the consumers' batched
    parties go through `eddsa_batch.to_dev`). Returns the mesh, or None
    on a single-device host (no-op). The daemon calls this at startup."""
    import jax as _jax
    from jax.sharding import NamedSharding

    from . import eddsa_batch as eb

    n = len(_jax.devices()) if n_devices is None else n_devices
    if n <= 1:
        eb.arm_session_sharding(None)
        return None
    mesh = make_mesh(n, committee=1)
    eb.arm_session_sharding(NamedSharding(mesh, P(SESSIONS)))
    return mesh


def make_mesh(n_devices: Optional[int] = None, committee: Optional[int] = None) -> Mesh:
    """Mesh over (committee, sessions). Committee axis defaults to 2 when it
    divides the device count (parties on distinct device rows), else 1
    (committee unsharded; sessions take every device)."""
    if n_devices is not None:
        devs = jax.devices()[:n_devices]
        assert len(devs) == n_devices, (
            f"asked for {n_devices} devices, only {len(devs)} available — "
            f"refusing to silently degrade the multi-device path"
        )
    else:
        devs = jax.devices()
    n = len(devs)
    q_axis = committee if committee is not None else (2 if n % 2 == 0 and n >= 2 else 1)
    assert n % q_axis == 0, f"committee axis {q_axis} must divide {n} devices"
    arr = np.array(devs).reshape(q_axis, n // q_axis)
    return Mesh(arr, (COMMITTEE, SESSIONS))


@functools.lru_cache(maxsize=None)
def commit_phase(mesh: Mesh):
    """Jitted phase A over the mesh: (q, B, 64) nonce bytes →
    ((q, B, 22) nonce scalars [sharded], (B, 32) compressed R [replicated
    across committee], (B,) ok mask). Cached per mesh."""

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(COMMITTEE, SESSIONS),),
        out_specs=(P(COMMITTEE, SESSIONS), P(SESSIONS), P(SESSIONS)),
        check_vma=False,  # scan carries start as unvarying consts
    )
    def _phase(r64):
        r, R_comp = eb.nonce_commitments(r64)
        R_all = lax.all_gather(R_comp, COMMITTEE, tiled=True)  # (q, B_loc, 32)
        R_sum, ok = eb.aggregate_nonce(R_all)
        return r, R_sum, ok

    return _phase


@functools.lru_cache(maxsize=None)
def sign_phase(mesh: Mesh):
    """Jitted phase B over the mesh: nonce scalars + challenge hashes +
    λ·x → ((B, 64) signatures, (B,) verified mask). Signature combine uses
    an all_gather over the committee axis (modular sum is not a psum —
    reduction happens in the scalar ring). Cached per mesh."""

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            P(COMMITTEE, SESSIONS),  # r limbs
            P(SESSIONS),  # c64 (replicated over committee)
            P(COMMITTEE, SESSIONS),  # λ·x limbs
            P(SESSIONS),  # R_sum compressed
            P(SESSIONS),  # A compressed
        ),
        out_specs=(P(SESSIONS), P(SESSIONS)),
        check_vma=False,  # scan carries start as unvarying consts
    )
    def _phase(r, c64, lamx, R_sum, A_comp):
        q_loc = r.shape[0]
        parts = eb.partial_signature(
            r, jnp.broadcast_to(c64, (q_loc,) + c64.shape), lamx
        )
        parts_all = lax.all_gather(parts, COMMITTEE, tiled=True)  # (q, B_loc, 22)
        sigs, _ = eb.combine_signatures(parts_all, R_sum)
        ok = eb.verify_signatures(sigs, A_comp, c64)
        return sigs, ok

    return _phase


def sharded_sign(
    mesh: Mesh,
    r64: np.ndarray,
    lamx: np.ndarray,
    A_comp: np.ndarray,
    messages,
) -> Tuple[np.ndarray, np.ndarray]:
    """Full two-phase signing step over the mesh (host hash between)."""
    r, R_sum, ok_R = commit_phase(mesh)(jnp.asarray(r64))
    c64 = eb.challenge_hashes(np.asarray(R_sum), np.asarray(A_comp), messages)
    sigs, ok = sign_phase(mesh)(
        r, jnp.asarray(c64), jnp.asarray(lamx), R_sum, jnp.asarray(A_comp)
    )
    return np.asarray(sigs), np.asarray(ok) & np.asarray(ok_R)


# ---------------------------------------------------------------------------
# GG18: session-axis sharding (GSPMD)
# ---------------------------------------------------------------------------


def shard_gg18_sessions(signer, mesh: Mesh) -> None:
    """Shard a GG18BatchCoSigners fabric's per-wallet state over the mesh's
    SESSIONS axis (in place). Every GG18 kernel is batch-parallel — MXU
    Toeplitz matmuls, powmod scans, curve ladders, device SHA-256 — so
    GSPMD partitions each dispatch across devices once the operands carry a
    sessions sharding; no collectives are needed inside a party.

    The COMMITTEE axis for GG18 is deliberately NOT a mesh axis: each
    party's Paillier/ring-Pedersen moduli are trust-domain-local compile
    constants, so parties are separate programs exchanging round tensors
    (in production: separate hosts — SURVEY.md §7.4 item 6). The EdDSA
    engine above demonstrates the on-mesh committee axis where per-party
    state is share-shaped, not modulus-shaped.
    """
    from jax.sharding import NamedSharding

    s = NamedSharding(mesh, P(SESSIONS))
    put = lambda x: jax.device_put(x, s)
    signer.w = [put(w) for w in signer.w]
    signer.W_pts = [
        type(p)(*(put(f) for f in p)) for p in signer.W_pts
    ]
    signer.Y = type(signer.Y)(*(put(f) for f in signer.Y))
