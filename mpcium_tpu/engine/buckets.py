"""Canonical pow-2 shape buckets for the batch/session dimension.

One XLA compile exists per (engine, shape signature), so every distinct
batch size B the engines are handed is a compile wall paid once and a
cache entry kept forever. This module is the single source of truth for
the allowed B values: the scheduler drains manifests in pow-2 chunks
(consumers/batch_scheduler._fire), bench.py snaps b_sweep points, and
the ROADMAP-item-4 AOT pre-warmer will compile exactly these buckets.

mpcshape (analysis/shape/) classifies a signature dimension as
*bucketed* when its provenance flows through these helpers; the
committed COMPILE_SURFACE.json is finite because everything batch-sized
on the serving path does.

Pure stdlib on purpose: the scheduler imports this at module load and
must not pull jax.
"""
from __future__ import annotations

# 16384 entered the grid with the donated-round-state pipeline (ISSUE
# 17): donate_argnums on per-round session state halves peak HBM per
# round step, which is exactly the headroom the biggest bucket needs.
BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192,
           16384)

_BUCKET_SET = frozenset(BUCKETS)


def is_bucket(n: int) -> bool:
    return n in _BUCKET_SET


def floor_bucket(n: int) -> int:
    """Largest bucket <= n — the chunk size a scheduler drain uses so a
    manifest (hence the engine batch dim) is always a bucket."""
    if n < 1:
        raise ValueError(f"need n >= 1, got {n}")
    best = BUCKETS[0]
    for b in BUCKETS:
        if b > n:
            break
        best = b
    return best


def bucket_b(n: int) -> int:
    """Smallest bucket >= n (clamped to the largest bucket) — the
    pad-up form bench sweeps and pre-warming use."""
    if n < 1:
        raise ValueError(f"need n >= 1, got {n}")
    for b in BUCKETS:
        if b >= n:
            return b
    return BUCKETS[-1]
