"""Batched GG18 threshold-ECDSA signing: the secp256k1 execution engine.

The north-star path (SURVEY.md §6: batched 2-of-3 secp256k1 signing): B
concurrent sessions' round compute coalesced into fixed-shape device
dispatches per party. The protocol is mathematically identical to
``protocol.ecdsa.signing`` (GG18: MtA with range proofs, phase-5
commit–reveal) — re-expressed over limb tensors:

- curve ops ride :mod:`core.secp256k1_jax` (12-bit limb family);
- Paillier / ring-Pedersen arithmetic rides :mod:`ops.modmul` (7-bit limb
  family: MXU Toeplitz constant-muls, lookahead carries) via
  :mod:`ops.paillier_mxu` (short-randomizer encryption, CRT decryption);
- hashing (commitments, Fiat–Shamir challenges) runs ON DEVICE
  (:mod:`ops.sha256`) over fixed-width byte serializations — no host
  round-trips inside the protocol (the host orchestrates dispatches only).

Quorum size is generic: ``party_ids`` may list any t+1-of-n quorum
(reference signs with any quorum ≥ t+1, ecdsa_signing_session.go:96-139);
MtA runs over all ordered pairs.

Transcript note: the batched fabric hashes fixed-width byte encodings (not
the per-session host protocol's length-prefixed ints) — the two paths are
separate wire universes; parity with the reference is at the result level
(signatures verify under the same pubkeys).

Randomness policy: a value mod M is sampled as CSPRNG bits of
``bits(M) - 8`` (for masks, where slight undersampling only strengthens the
bound) or reduced mod M on device. Paillier randomizers are y^u for
256-bit u (ops.paillier_mxu short-randomizer encryption — DCR + standard
short-exponent assumption).

Test note: proof-equation algebra holds for any key size, so unit tests run
512-bit keys with shrunk exponent domains (the ``bits`` knobs below); the
full-size path is exercised by bench.py and the slow-marked
test_gg18_full_size.
"""
from __future__ import annotations

import functools
import os
import secrets
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import bignum as bn
from ..core import hostmath as hm
from ..core import secp256k1_jax as sp
from ..core.bignum import P256
from ..core.fields import secp256k1_field
from ..core.paillier import PreParams
from ..ops import modmul as mm
from ..ops.paillier_mxu import RAND_BITS, PaillierMXUPrivate
from ..ops.sha256 import sha256 as dev_sha256
from ..perf import compile_watch
from ..protocol.base import KeygenShare, party_xs
from ..utils import log, tracing


def _trace_sync(tensors) -> None:
    """Phase-boundary sync for mpctrace/bench phase timers — reached only
    when tracing is armed or a phase_times dict was requested."""
    jax.block_until_ready(tensors)  # mpcflow: host-ok — trace/bench instrumentation, only when tracing or phase_times is requested

Q = hm.SECP_N
SCALAR_BITS = 256

# Randomized batch verification (Bellare–Garay–Rabin small-exponent test)
# for the s^N ciphertext legs: instead of one 2048-bit-exponent modexp per
# session per leg (~2560 sequential mulmod steps over the batch), the
# verifier samples per-session 128-bit ρ_b and checks ONE combined
# equation, using Π_b s_b^{ρ_b·N} = (Π_b s_b^{ρ_b})^N and
# Π_b (1+s1_b·N)^{ρ_b} = 1 + (Σ_b ρ_b·s1_b)·N mod N². Per-element cost
# drops to one 128-bit modexp (+ log-depth folds + one single-value host
# modexp). On combined-check failure the verifier falls back to strict
# per-session verification, so a bad proof is still attributed to its
# session (identifiable abort). Soundness: 2^-128 for deviations of odd
# order in Z_{N²}*; see SECURITY.md for the even-order caveat.
# MPCIUM_BATCH_VERIFY=strict restores reference-equivalent per-session
# verification.
BATCH_VERIFY = os.environ.get("MPCIUM_BATCH_VERIFY", "rand")
RHO_BITS = 128


def _fold_add(x: jnp.ndarray, extra_limbs: int = 3) -> jnp.ndarray:
    """Σ over the batch axis of normalized 7-bit limb tensors → (1, n+extra)
    normalized limbs. Exact while B·127 < 2²⁴ (B ≤ ~131k)."""
    assert x.shape[0] <= (1 << 17)
    x = bn.pad_limbs(x, extra_limbs)
    return mm.carry(jnp.sum(x, axis=0, keepdims=True))


def _host_pow_single(x_limbs: jnp.ndarray, exp: int, ctx) -> jnp.ndarray:
    """(1, n) limbs → x^exp mod ctx.modulus via one host bigint modexp
    (a single 2048-bit-exponent value: device scan would serialize ~2.5k
    tiny dispatches; CPython pow is milliseconds)."""
    v = bn.batch_from_limbs(np.asarray(x_limbs), ctx.prof)[0]
    return jnp.asarray(
        bn.batch_to_limbs([pow(v, exp, ctx.modulus)], ctx.prof)
    )


def _host_pow_batch(x_limbs: jnp.ndarray, exp: int, ctx) -> jnp.ndarray:
    """(B, n) limbs → x^exp per element on HOST. Only the strict-fallback
    (attack/abort-attribution) path uses this: the full-width-exponent
    device kernel it replaces is exactly the executable that crashes XLA's
    CPU AOT cache serializer on this class of host, and the fallback is
    cold by construction."""
    vals = bn.batch_from_limbs(np.asarray(x_limbs), ctx.prof)
    return jnp.asarray(
        bn.batch_to_limbs([pow(v, exp, ctx.modulus) for v in vals], ctx.prof)
    )


@dataclass(frozen=True)
class Domains:
    """Exponent-domain bit sizes (GG18 appendix A). Shrunk in unit tests."""

    scalar: int = 256       # curve scalars (a, b, e)
    alpha: int = 760        # < q³
    beta_prime: int = 1272  # < q⁵
    gamma_bob: int = 1784   # < q⁷
    rho_extra: int = 248    # ρ < q·NTilde  → scalar-8 + nt bits
    s1_bound: int = 768     # q³ bound checked by verifiers

    def q3(self) -> int:
        return Q**3


def _prof7(bits: int) -> bn.LimbProfile:
    """Unpadded 7-bit profile (proof-domain integers; widths stay exact so
    serializations are minimal)."""
    return bn.LimbProfile(bits=7, n_limbs=max(2, -(-bits // 7)))


def rand_bits(batch: int, bits: int, rng=secrets) -> np.ndarray:
    """(B, ceil(bits/8)) CSPRNG bytes encoding a uniform `bits`-bit int."""
    nbytes = -(-bits // 8)
    raw = np.frombuffer(rng.token_bytes(batch * nbytes), dtype=np.uint8)
    out = raw.reshape(batch, nbytes).copy()
    extra = 8 * nbytes - bits
    if extra:
        out[:, -1] &= (1 << (8 - extra)) - 1
    return out


def rand_bit_tensor(batch: int, bits: int, rng=secrets) -> jnp.ndarray:
    """(B, bits) int32 uniform CSPRNG bits, LSB-first per value."""
    by = rand_bits(batch, bits, rng)
    arr = np.unpackbits(by, axis=-1, bitorder="little")[:, :bits]
    return jnp.asarray(arr.astype(np.int32))


def dev_hash(tag: bytes, *rows) -> jnp.ndarray:
    """Batched SHA-256 on device over tag ‖ fixed-width rows → (B, 32)."""
    rows = [jnp.asarray(r).astype(jnp.uint8) for r in rows]
    B = rows[0].shape[0]
    t = np.frombuffer(b"mpcium-tpu/gg18-batch/" + tag, dtype=np.uint8)
    tag_t = jnp.broadcast_to(jnp.asarray(t), (B, t.shape[0]))
    return dev_sha256(jnp.concatenate([tag_t] + rows, axis=-1))


def bytes_to_bits(b: jnp.ndarray, n_bits: int) -> jnp.ndarray:
    """(..., nB) uint8 little-endian → (..., n_bits) int32 bits LSB-first."""
    bits = (b[..., :, None].astype(jnp.int32) >> jnp.arange(8)) & 1
    bits = bits.reshape(b.shape[:-1] + (b.shape[-1] * 8,))
    if bits.shape[-1] < n_bits:
        return jnp.pad(
            bits, [(0, 0)] * (bits.ndim - 1) + [(0, n_bits - bits.shape[-1])]
        )
    return bits[..., :n_bits]


def _bits_of(x: jnp.ndarray, prof: bn.LimbProfile, n_bits: int) -> jnp.ndarray:
    return bn.limbs_to_bits(x, prof, n_bits)


@functools.partial(jax.jit, static_argnums=3)
def _int_mul_add(e, m, add, prof) -> jnp.ndarray:
    """e·m + add over plain integers (no modulus), normalized to the width
    of `prof`. Inputs normalized 7-bit limbs."""
    prod = mm.mul_pair(e, m)
    width = prof.n_limbs
    return mm.carry(
        bn.take_limbs(prod, 0, width) + bn.take_limbs(add, 0, width)
    )


def _eq_all(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=-1)


# ---------------------------------------------------------------------------
# per-party static contexts
# ---------------------------------------------------------------------------


class PartyCtx:
    """One signer's static crypto material + device contexts.

    The normal constructor holds the party's PRIVATE material (own
    PreParams). :meth:`public` builds a peer's context from the public
    material exchanged at keygen (peer_paillier / peer_ring_pedersen in
    the share aux) — everything MtaBatch needs from the *other* side of a
    pair: encryption under the peer's N (with a locally-chosen randomizer
    base y), ring-Pedersen commitments in the peer's NTilde, and the
    verification contexts. Decryption obviously stays private-only.
    """

    def __init__(
        self,
        pid: str,
        pre: Optional[PreParams],
        rng=secrets,
        *,
        public_material: Optional[Tuple[int, int, int, int]] = None,
    ):
        self.pid = pid
        self.pre = pre
        if public_material is not None:
            if pre is not None:
                raise ValueError("pass private PreParams OR public material")
            from ..core.paillier import PaillierPublicKey
            from ..ops.paillier_mxu import PaillierMXU

            N, NTilde, h1, h2 = public_material
            self.pmx = PaillierMXU(PaillierPublicKey(N), rng=rng)
            self._common(N, NTilde, h1, h2)
        else:
            if pre is None:
                raise ValueError("private PartyCtx requires PreParams")
            self.pmx = PaillierMXUPrivate(pre.paillier, rng=rng)
            self._common(pre.paillier.N, pre.NTilde, pre.h1, pre.h2)

    @classmethod
    def public(
        cls, pid: str, N: int, NTilde: int, h1: int, h2: int, rng=secrets
    ) -> "PartyCtx":
        return cls(pid, None, rng, public_material=(N, NTilde, h1, h2))

    def _common(self, N: int, NTilde: int, h1: int, h2: int) -> None:
        self.N = N
        self.NTilde = NTilde
        self.ctx_nt = mm.MXUBarrett(NTilde)
        self.h1 = h1
        self.h2 = h2
        self.nt_bytes = -(-NTilde.bit_length() // 8)
        self.n2_bytes = -(-(2 * N.bit_length()) // 8)
        self.n_bytes = -(-N.bit_length() // 8)

    def commit_ring(self, m_bits: jnp.ndarray, r_bits: jnp.ndarray) -> jnp.ndarray:
        """h1^m · h2^r mod NTilde — two comb-table fixed-base exps."""
        a = self.ctx_nt.powmod_fixed_base(self.h1, m_bits)
        b = self.ctx_nt.powmod_fixed_base(self.h2, r_bits)
        return self.ctx_nt.mulmod(a, b)

    def nt_row(self, x: jnp.ndarray) -> jnp.ndarray:
        return bn.limbs_to_bytes_le(x, self.ctx_nt.prof, self.nt_bytes)

    def n2_row(self, x: jnp.ndarray) -> jnp.ndarray:
        return bn.limbs_to_bytes_le(x, self.pmx.prof_n2, self.n2_bytes)


# ---------------------------------------------------------------------------
# batched MtA with range proofs (one ordered direction Alice → Bob → Alice)
# ---------------------------------------------------------------------------


class MtaBatch:
    """Batched MtA + proofs for the ordered pair (alice, bob).

    The flow mirrors protocol.ecdsa.{mta,zk} exactly, over MXU limb
    tensors with device-side Fiat–Shamir. State dicts hold limb tensors;
    every heavy call runs through jitted kernels.
    """

    def __init__(self, alice: PartyCtx, bob: PartyCtx, dom: Domains = Domains()):
        self.alice = alice
        self.bob = bob
        self.dom = dom
        d = dom
        self.p_e = _prof7(d.scalar)
        self.p_alpha = _prof7(d.alpha)
        self.p_s1 = _prof7(d.scalar + d.alpha + 7)
        nt_bits = bob.NTilde.bit_length()
        nt_bits_a = alice.NTilde.bit_length()
        self.p_rho = _prof7(d.scalar + max(nt_bits, nt_bits_a) + d.rho_extra)
        self.p_s2 = _prof7(d.scalar + self.p_rho.n_limbs * 7 + 7)
        self.p_bp = _prof7(d.beta_prime)
        self.p_gb = _prof7(d.gamma_bob)
        self.p_t1 = _prof7(d.scalar + d.gamma_bob + 7)

    # -- randomness bundles (host CSPRNG → device) --------------------------

    @staticmethod
    def _dom_limbs(B, bits, prof, rng):
        return bn.bytes_to_limbs_le(
            jnp.asarray(rand_bits(B, bits, rng)), prof, prof.n_limbs
        )

    def alice_randoms(self, B: int, rng=secrets) -> Dict[str, jnp.ndarray]:
        d = self.dom
        nt_b = self.bob.NTilde.bit_length()
        return {
            "u_enc": rand_bit_tensor(B, RAND_BITS, rng),  # Enc(α) randomizer
            "alpha": self._dom_limbs(B, d.alpha - 8, self.p_alpha, rng),
            "rho": self._dom_limbs(B, d.scalar + nt_b - 8, self.p_rho, rng),
            "gamma": self._dom_limbs(B, d.alpha + nt_b - 8, self.p_s2, rng),
        }

    def bob_randoms(self, B: int, rng=secrets) -> Dict[str, jnp.ndarray]:
        d = self.dom
        nt_a = self.alice.NTilde.bit_length()
        return {
            "beta_prime": self._dom_limbs(B, d.beta_prime - 8, self.p_bp, rng),
            "u_bp": rand_bit_tensor(B, RAND_BITS, rng),  # Enc(β′) randomizer
            "alpha": self._dom_limbs(B, d.alpha - 8, self.p_alpha, rng),
            "rho": self._dom_limbs(B, d.scalar + nt_a - 8, self.p_rho, rng),
            "rho_p": self._dom_limbs(B, d.alpha + nt_a - 8, self.p_s2, rng),
            "sigma": self._dom_limbs(B, d.scalar + nt_a - 8, self.p_rho, rng),
            "tau": self._dom_limbs(B, d.alpha + nt_a - 8, self.p_s2, rng),
            "u_g": rand_bit_tensor(B, RAND_BITS, rng),  # Enc(γ) randomizer
            "gamma": self._dom_limbs(B, d.gamma_bob - 8, self.p_gb, rng),
        }

    # -- Alice: range proof for c_a = Enc_A(m; y^u) -------------------------

    def alice_init(self, m_limbs, R: Dict[str, jnp.ndarray]):
        """m: plaintext (< q) in Alice's prof_n. Returns the pre-challenge
        transcript {z, u, w} (c_a itself is per-party, passed separately).
        """
        A, Bo = self.alice, self.bob
        z = Bo.commit_ring(
            _bits_of(m_limbs, A.pmx.prof_n, self.dom.scalar),
            _bits_of(R["rho"], self.p_rho, self.p_rho.n_limbs * 7),
        )
        u_c, _u_r = A.pmx.encrypt(
            bn.take_limbs(R["alpha"], 0, A.pmx.prof_n.n_limbs), R["u_enc"]
        )
        w = Bo.commit_ring(
            _bits_of(R["alpha"], self.p_alpha, self.dom.alpha),
            _bits_of(R["gamma"], self.p_s2, self.p_s2.n_limbs * 7),
        )
        return {"z": z, "u": u_c, "w": w}

    def alice_challenge(self, c_a, T) -> jnp.ndarray:
        A, Bo = self.alice, self.bob
        return dev_hash(
            b"alice",
            A.n2_row(c_a),
            Bo.nt_row(T["z"]),
            A.n2_row(T["u"]),
            Bo.nt_row(T["w"]),
        )

    def e_limbs(self, e32: jnp.ndarray) -> jnp.ndarray:
        return bn.bytes_to_limbs_le(
            jnp.asarray(e32), self.p_e, self.p_e.n_limbs
        )

    def alice_finish(self, e, m_limbs, R, u_ca_bits):
        """Responses: s = y^(u_ca·e + u_enc) mod N (the randomizer leg,
        all in the exponent thanks to short-randomizer encryption);
        s1 = e·m + α; s2 = e·ρ + γ.

        ``u_ca_bits``: the 256-bit exponent that produced c_a's randomizer
        (r = y^u_ca)."""
        A = self.alice
        p_u = _prof7(RAND_BITS)
        u_ca = _bits_pack(u_ca_bits, p_u)
        u_enc = _bits_pack(R["u_enc"], p_u)
        prod = mm.mul_pair(u_ca, self.e_limbs_from(e))  # 512-bit integer
        p_E = _prof7(2 * RAND_BITS + 8)
        E = mm.carry(
            bn.take_limbs(prod, 0, p_E.n_limbs)
            + bn.take_limbs(u_enc, 0, p_E.n_limbs)
        )
        s = A.pmx.ctx_N.powmod_fixed_base(
            A.pmx.y % A.N, _bits_of(E, p_E, p_E.n_limbs * 7)
        )
        m_e = bn.take_limbs(m_limbs, 0, self.p_e.n_limbs)
        e_l = self.e_limbs_from(e)
        s1 = _int_mul_add(
            e_l, m_e, bn.take_limbs(R["alpha"], 0, self.p_s1.n_limbs), self.p_s1
        )
        s2 = _int_mul_add(
            e_l, R["rho"], bn.take_limbs(R["gamma"], 0, self.p_s2.n_limbs),
            self.p_s2,
        )
        return {"s": s, "s1": s1, "s2": s2}

    def e_limbs_from(self, e) -> jnp.ndarray:
        """Accept either raw (B, 32) digest bytes or already-packed limbs."""
        if e.shape[-1] == 32 and e.dtype == jnp.uint8:
            return self.e_limbs(e)
        return e

    def bob_check_alice(self, c_a, T, P, e, rng=secrets) -> jnp.ndarray:
        """Batched Alice-proof verification → (B,) bool."""
        A, Bo = self.alice, self.bob
        e_l = self.e_limbs_from(e)
        q3 = jnp.broadcast_to(
            jnp.asarray(bn.to_limbs(self.dom.q3(), self.p_s1)), P["s1"].shape
        )
        ok = bn.compare(P["s1"], q3) <= 0
        e_bits = _bits_of(e_l, self.p_e, self.dom.scalar)
        s1_modN = A.pmx.ctx_N.reduce(
            bn.take_limbs(P["s1"], 0, min(P["s1"].shape[-1], 2 * A.pmx.prof_n.n_limbs))
        )
        ok = ok & self._alice_enc_leg(c_a, T, P, e_bits, s1_modN, rng)
        lhs2 = Bo.commit_ring(
            _bits_of(P["s1"], self.p_s1, self.p_s1.n_limbs * 7),
            _bits_of(P["s2"], self.p_s2, self.p_s2.n_limbs * 7),
        )
        rhs2 = Bo.ctx_nt.mulmod(T["w"], Bo.ctx_nt.powmod(T["z"], e_bits))
        return ok & _eq_all(lhs2, rhs2)

    def _alice_enc_leg_strict(self, c_a, T, P, e_bits, s1_modN) -> jnp.ndarray:
        """Per-session ciphertext-leg check:
        Enc_det(s1)·s^N == u·c_a^e (mod N²). The s^N piece runs on host
        (see _host_pow_batch)."""
        A = self.alice
        n2 = A.pmx.ctx_N2
        lhs = n2.mulmod(
            A.pmx.enc_deterministic(s1_modN),
            _host_pow_batch(
                bn.take_limbs(P["s"], 0, n2.prof.n_limbs), A.N, n2
            ),
        )
        rhs = n2.mulmod(T["u"], n2.powmod(c_a, e_bits))
        return _eq_all(lhs, rhs)

    def _alice_enc_leg(self, c_a, T, P, e_bits, s1_modN, rng) -> jnp.ndarray:
        """Ciphertext leg of the Alice proof, batch-verified (module
        docstring at BATCH_VERIFY): Enc_det(Σρ·s1) · (Πs^ρ)^N ==
        Π(u·c_a^e)^ρ. Strict per-session fallback attributes failures."""
        if BATCH_VERIFY != "rand":
            return self._alice_enc_leg_strict(c_a, T, P, e_bits, s1_modN)
        A = self.alice
        n2 = A.pmx.ctx_N2
        B = s1_modN.shape[0]
        rho_bits = rand_bit_tensor(B, RHO_BITS, rng)
        rhs = n2.mulmod(T["u"], n2.powmod(c_a, e_bits))
        Rp = n2.prod_over_batch(n2.powmod(rhs, rho_bits))[None]
        s2 = bn.take_limbs(P["s"], 0, n2.prof.n_limbs)
        Sp = n2.prod_over_batch(n2.powmod(s2, rho_bits))[None]
        SN = _host_pow_single(Sp, A.N, n2)
        rho_l = _bits_pack(rho_bits, _prof7(RHO_BITS))
        tot = A.pmx.ctx_N.reduce(_fold_add(mm.mul_pair(rho_l, s1_modN)))
        lhs = n2.mulmod(A.pmx.enc_deterministic(tot), SN)
        if bool(np.asarray(_eq_all(lhs, Rp))[0]):  # mpcflow: host-ok — single aggregated proof verdict gates the strict fallback
            return jnp.ones((B,), bool)
        log.warn("batched Alice-proof check failed — strict re-verification")
        return self._alice_enc_leg_strict(c_a, T, P, e_bits, s1_modN)

    # -- Bob: homomorphic response + proof ----------------------------------

    def bob_respond(self, c_a, b_limbs, R):
        """c_b = c_a^b · Enc_A(β′; y^u_bp); pre-challenge proof transcript.
        ``b_limbs``: Bob's secret (< q) in the 7-bit e-profile."""
        A = self.alice
        b_bits = _bits_of(b_limbs, self.p_e, self.dom.scalar)
        enc_bp, _r = A.pmx.encrypt(
            bn.take_limbs(R["beta_prime"], 0, A.pmx.prof_n.n_limbs), R["u_bp"]
        )
        c_b = A.pmx.ctx_N2.mulmod(A.pmx.ctx_N2.powmod(c_a, b_bits), enc_bp)
        z = A.commit_ring(
            _bits_of(b_limbs, self.p_e, self.dom.scalar),
            _bits_of(R["rho"], self.p_rho, self.p_rho.n_limbs * 7),
        )
        z_p = A.commit_ring(
            _bits_of(R["alpha"], self.p_alpha, self.dom.alpha),
            _bits_of(R["rho_p"], self.p_s2, self.p_s2.n_limbs * 7),
        )
        t = A.commit_ring(
            _bits_of(R["beta_prime"], self.p_bp, self.dom.beta_prime),
            _bits_of(R["sigma"], self.p_rho, self.p_rho.n_limbs * 7),
        )
        enc_g, _r2 = A.pmx.encrypt(
            bn.take_limbs(R["gamma"], 0, A.pmx.prof_n.n_limbs), R["u_g"]
        )
        v = A.pmx.ctx_N2.mulmod(
            A.pmx.ctx_N2.powmod(
                c_a, _bits_of(R["alpha"], self.p_alpha, self.dom.alpha)
            ),
            enc_g,
        )
        w = A.commit_ring(
            _bits_of(R["gamma"], self.p_gb, self.dom.gamma_bob),
            _bits_of(R["tau"], self.p_s2, self.p_s2.n_limbs * 7),
        )
        return {"c_b": c_b, "z": z, "z_p": z_p, "t": t, "v": v, "w": w}

    def bob_challenge(self, c_a, T, extra_rows: Sequence = ()) -> jnp.ndarray:
        A = self.alice
        rows = [
            A.n2_row(c_a),
            A.n2_row(T["c_b"]),
            A.nt_row(T["z"]),
            A.nt_row(T["z_p"]),
            A.nt_row(T["t"]),
            A.n2_row(T["v"]),
            A.nt_row(T["w"]),
        ]
        rows.extend(extra_rows)
        return dev_hash(b"bob", *rows)

    def bob_finish(self, e, b_limbs, R):
        A = self.alice
        e_l = self.e_limbs_from(e)
        p_u = _prof7(RAND_BITS)
        u_bp = _bits_pack(R["u_bp"], p_u)
        u_g = _bits_pack(R["u_g"], p_u)
        prod = mm.mul_pair(u_bp, e_l)
        p_E = _prof7(2 * RAND_BITS + 8)
        E = mm.carry(
            bn.take_limbs(prod, 0, p_E.n_limbs)
            + bn.take_limbs(u_g, 0, p_E.n_limbs)
        )
        s = A.pmx.ctx_N.powmod_fixed_base(
            A.pmx.y % A.N, _bits_of(E, p_E, p_E.n_limbs * 7)
        )
        s1 = _int_mul_add(
            e_l, bn.take_limbs(b_limbs, 0, self.p_e.n_limbs),
            bn.take_limbs(R["alpha"], 0, self.p_s1.n_limbs), self.p_s1,
        )
        s2 = _int_mul_add(
            e_l, R["rho"], bn.take_limbs(R["rho_p"], 0, self.p_s2.n_limbs),
            self.p_s2,
        )
        t1 = _int_mul_add(
            e_l, bn.take_limbs(R["beta_prime"], 0, self.p_t1.n_limbs),
            bn.take_limbs(R["gamma"], 0, self.p_t1.n_limbs), self.p_t1,
        )
        t2 = _int_mul_add(
            e_l, R["sigma"], bn.take_limbs(R["tau"], 0, self.p_s2.n_limbs),
            self.p_s2,
        )
        return {"s": s, "s1": s1, "s2": s2, "t1": t1, "t2": t2}

    def alice_check_bob(self, c_a, T, P, e, rng=secrets) -> jnp.ndarray:
        """Batched Bob-proof verification (ciphertext + ring legs; the
        with-check curve leg is checked by the caller)."""
        A = self.alice
        e_l = self.e_limbs_from(e)
        q3 = jnp.broadcast_to(
            jnp.asarray(bn.to_limbs(self.dom.q3(), self.p_s1)), P["s1"].shape
        )
        ok = bn.compare(P["s1"], q3) <= 0
        t1_cap = (1 << (self.p_t1.bits * self.p_t1.n_limbs)) - 1
        q7 = jnp.broadcast_to(
            jnp.asarray(bn.to_limbs(min(Q**7, t1_cap), self.p_t1)),
            P["t1"].shape,
        )
        ok = ok & (bn.compare(P["t1"], q7) <= 0)
        e_bits = _bits_of(e_l, self.p_e, self.dom.scalar)
        lhs = A.commit_ring(
            _bits_of(P["s1"], self.p_s1, self.p_s1.n_limbs * 7),
            _bits_of(P["s2"], self.p_s2, self.p_s2.n_limbs * 7),
        )
        rhs = A.ctx_nt.mulmod(T["z_p"], A.ctx_nt.powmod(T["z"], e_bits))
        ok = ok & _eq_all(lhs, rhs)
        lhs = A.commit_ring(
            _bits_of(P["t1"], self.p_t1, self.p_t1.n_limbs * 7),
            _bits_of(P["t2"], self.p_s2, self.p_s2.n_limbs * 7),
        )
        rhs = A.ctx_nt.mulmod(T["w"], A.ctx_nt.powmod(T["t"], e_bits))
        ok = ok & _eq_all(lhs, rhs)
        n2 = A.pmx.ctx_N2
        t1_modN = A.pmx.ctx_N.reduce(
            bn.take_limbs(P["t1"], 0, min(P["t1"].shape[-1], 2 * A.pmx.prof_n.n_limbs))
        )
        # ciphertext leg: c_a^s1 · Enc_det(t1) · s^N == v · c_b^e (mod N²)
        M = n2.mulmod(
            n2.powmod(c_a, _bits_of(P["s1"], self.p_s1, self.p_s1.n_limbs * 7)),
            A.pmx.enc_deterministic(t1_modN),
        )
        rhs = n2.mulmod(T["v"], n2.powmod(T["c_b"], e_bits))
        s_lift = bn.take_limbs(P["s"], 0, n2.prof.n_limbs)
        if BATCH_VERIFY == "rand":
            B = s_lift.shape[0]
            rho_bits = rand_bit_tensor(B, RHO_BITS, rng)
            Mp = n2.prod_over_batch(n2.powmod(M, rho_bits))[None]
            Sp = n2.prod_over_batch(n2.powmod(s_lift, rho_bits))[None]
            Rp = n2.prod_over_batch(n2.powmod(rhs, rho_bits))[None]
            SN = _host_pow_single(Sp, A.N, n2)
            if bool(np.asarray(_eq_all(n2.mulmod(Mp, SN), Rp))[0]):  # mpcflow: host-ok — single aggregated proof verdict gates the strict fallback
                return ok
            log.warn("batched Bob-proof check failed — strict re-verification")
        lhs = n2.mulmod(M, _host_pow_batch(s_lift, A.N, n2))
        return ok & _eq_all(lhs, rhs)

    def alice_decrypt_share(self, c_b) -> jnp.ndarray:
        """Dec_A(c_b) mod q → curve-scalar limbs (12-bit family)."""
        A = self.alice
        plain = A.pmx.decrypt(c_b)  # (B, n) mod N, 7-bit limbs
        return _mod_q_from_limbs(plain, A.pmx.prof_n)


@functools.partial(jax.jit, static_argnums=1)
def _bits_pack(bits: jnp.ndarray, prof: bn.LimbProfile) -> jnp.ndarray:
    """(..., n_bits) LSB-first bit tensor → normalized limbs in prof."""
    n_bits = bits.shape[-1]
    want = prof.n_limbs * prof.bits
    if n_bits < want:
        bits = jnp.pad(
            bits, [(0, 0)] * (bits.ndim - 1) + [(0, want - n_bits)]
        )
    else:
        bits = bits[..., :want]
    groups = bits.reshape(bits.shape[:-1] + (prof.n_limbs, prof.bits))
    w = 1 << jnp.arange(prof.bits, dtype=jnp.int32)
    return jnp.sum(groups * w, axis=-1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# curve-side jitted helpers (12-bit family)
# ---------------------------------------------------------------------------


@jax.jit
def _scalar_from_wide_bytes(b: jnp.ndarray) -> jnp.ndarray:
    """(B, 40) uniform bytes → canonical scalar mod q (bias 2^-64)."""
    ring = sp.scalar_ring()
    return ring.reduce(bn.bytes_to_limbs_le(b, P256, 30))


@jax.jit
def _base_mul_compressed(k_limbs: jnp.ndarray):
    pt = sp.base_mul(bn.limbs_to_bits(k_limbs, P256, SCALAR_BITS))
    return pt, sp.compress(pt)


def _scalar_to_plain(pmx, k_limbs: jnp.ndarray) -> jnp.ndarray:
    """curve scalar (12-bit limbs) → Paillier plaintext limbs (7-bit)."""
    b = bn.limbs_to_bytes_le(k_limbs, P256, 32)
    return bn.bytes_to_limbs_le(b, pmx.prof_n, pmx.prof_n.n_limbs)


def _scalar_to_prof(k_limbs: jnp.ndarray, prof: bn.LimbProfile) -> jnp.ndarray:
    b = bn.limbs_to_bytes_le(k_limbs, P256, 32)
    return bn.bytes_to_limbs_le(b, prof, prof.n_limbs)


@functools.partial(jax.jit, static_argnums=1)
def _mod_q_from_limbs(x: jnp.ndarray, prof: bn.LimbProfile) -> jnp.ndarray:
    """Reduce an arbitrary-width non-negative value mod q → 12-bit curve
    limbs, via chunked folding: v = Σ chunk_i · (2^(176·i)) mod q."""
    ring = sp.scalar_ring()
    n_bytes = -(-prof.n_limbs * prof.bits // 8)
    b = bn.limbs_to_bytes_le(x, prof, n_bytes)
    chunk_bytes = 22  # 176 bits per chunk < 2^253
    n_chunks = -(-n_bytes // chunk_bytes)
    pad = n_chunks * chunk_bytes - n_bytes
    if pad:
        b = jnp.pad(b, [(0, 0)] * (b.ndim - 1) + [(0, pad)])
    chunks = b.reshape(b.shape[:-1] + (n_chunks, chunk_bytes))
    acc = ring.const(0, x.shape[:-1])
    shift = pow(2, chunk_bytes * 8, Q)
    shift_l = ring.const(shift, x.shape[:-1])
    for i in range(n_chunks - 1, -1, -1):
        c = bn.bytes_to_limbs_le(chunks[..., i, :], P256, P256.n_limbs)
        acc = ring.addmod(ring.mulmod(acc, shift_l), ring.reduce(c))
    return acc


# ---------------------------------------------------------------------------
# curve-phase BLOCKS — jitted at per-party granularity so one compiled
# executable is reused q times per sign and shared across runs. (Both
# extremes failed on this host: fusing a whole phase into one jit produced
# 15+ minute XLA compiles; fully-eager execution paid ~ms of dispatch per
# primitive across tens of thousands of curve ops. Party domain separation
# rides an index-byte OPERAND, not per-party hash tags, so block HLO is
# party-independent.)
# ---------------------------------------------------------------------------


def _idx_row(i: int, B: int) -> jnp.ndarray:
    return jnp.full((B, 1), i, jnp.uint8)


@jax.jit
def _blk_commit(tagged_payload_rows):
    """Generic hash commitment over pre-concatenated (B, L) uint8 rows."""
    return dev_sha256(tagged_payload_rows)


@jax.jit
def _blk_gamma(gamma_i, blind_i, idx):
    """Γ_i = γ_i·G, compressed + hash-committed (round 1, per party)."""
    pt = sp.base_mul(bn.limbs_to_bits(gamma_i, P256, SCALAR_BITS))
    comp = sp.compress(pt)
    commit = dev_hash(b"gamma", idx, blind_i, comp)
    return pt, comp, commit


@jax.jit
def _blk_gamma_check(blind_i, comp_i, idx, commit_i):
    return _eq_all(dev_hash(b"gamma", idx, blind_i, comp_i), commit_i)


@jax.jit
def _blk_point_add(a: sp.SecpPointJ, b: sp.SecpPointJ) -> sp.SecpPointJ:
    return sp.add(a, b)


@jax.jit
def _blk_point_eq(a: sp.SecpPointJ, b: sp.SecpPointJ) -> jnp.ndarray:
    return sp.equal(a, b)


@jax.jit
def _blk_R(delta, Gamma_sum):
    """δ⁻¹·ΣΓ, r = R_x mod q, recovery metadata, degeneracy flags."""
    ring = sp.scalar_ring()
    ok = ~jnp.all(delta == 0, axis=-1)
    delta_inv = ring.powmod_const(delta, Q - 2)
    R_pt = sp.scalar_mul(
        bn.limbs_to_bits(delta_inv, P256, SCALAR_BITS), Gamma_sum
    )
    Rx = sp.x_coordinate(R_pt)
    r = ring.reduce(Rx)
    ok = ok & ~jnp.all(r == 0, axis=-1)
    F = secp256k1_field()
    zi = F.inv(R_pt.Z)
    y_aff = F.canonical(F.mul(R_pt.Y, zi))
    n_limbs_ = jnp.broadcast_to(jnp.asarray(bn.to_limbs(Q, P256)), Rx.shape)
    rec = (y_aff[..., 0] & 1) | jnp.where(bn.compare(Rx, n_limbs_) >= 0, 2, 0)
    return ok, R_pt, r, rec


@jax.jit
def _blk_schnorr(kpok_i, gamma_i, Gamma_i, comp_i, idx):
    """Batched Schnorr PoK of γ_i: prove + self-verify (honest fabric)."""
    ring = sp.scalar_ring()
    A_pt = sp.base_mul(bn.limbs_to_bits(kpok_i, P256, SCALAR_BITS))
    A_comp = sp.compress(A_pt)
    e32 = dev_hash(b"schnorr", idx, A_comp, comp_i)
    e = ring.reduce(bn.bytes_to_limbs_le(e32, P256, 22))
    s_pok = ring.submod(kpok_i, ring.mulmod(e, gamma_i))
    lhs = sp.add(
        sp.base_mul(bn.limbs_to_bits(s_pok, P256, SCALAR_BITS)),
        sp.scalar_mul(bn.limbs_to_bits(e, P256, SCALAR_BITS), Gamma_i),
    )
    return _eq_all(sp.compress(lhs), A_comp)


# -- prover/verifier split variants of the PoK blocks (the distributed
# protocol sends proofs across the transport; the in-process fabric keeps
# the fused prove+self-verify blocks above) --------------------------------


@jax.jit
def _blk_schnorr_prove(kpok_i, gamma_i, comp_i, idx):
    """Schnorr PoK of γ_i, prover side → (A_comp, s_pok)."""
    ring = sp.scalar_ring()
    A_pt = sp.base_mul(bn.limbs_to_bits(kpok_i, P256, SCALAR_BITS))
    A_comp = sp.compress(A_pt)
    e32 = dev_hash(b"schnorr", idx, A_comp, comp_i)
    e = ring.reduce(bn.bytes_to_limbs_le(e32, P256, 22))
    s_pok = ring.submod(kpok_i, ring.mulmod(e, gamma_i))
    return A_comp, s_pok


@jax.jit
def _blk_schnorr_verify(A_comp, s_pok, Gamma_i: sp.SecpPointJ, comp_i, idx):
    """Schnorr PoK verify: s·G + e·Γ ?= A → (B,) bool."""
    ring = sp.scalar_ring()
    e32 = dev_hash(b"schnorr", idx, A_comp, comp_i)
    e = ring.reduce(bn.bytes_to_limbs_le(e32, P256, 22))
    lhs = sp.add(
        sp.base_mul(bn.limbs_to_bits(s_pok, P256, SCALAR_BITS)),
        sp.scalar_mul(bn.limbs_to_bits(e, P256, SCALAR_BITS), Gamma_i),
    )
    return _eq_all(sp.compress(lhs), A_comp)


@jax.jit
def _blk_pedersen_prove(ka, kb, s_i, l_i, R_pt, vc, ac, idx):
    """Phase-5B PedersenPoK of (s_i, l_i), prover side →
    (Apok_comp, sa, sb)."""
    ring = sp.scalar_ring()
    Apok = sp.add(
        sp.scalar_mul(bn.limbs_to_bits(ka, P256, SCALAR_BITS), R_pt),
        sp.base_mul(bn.limbs_to_bits(kb, P256, SCALAR_BITS)),
    )
    Apok_comp = sp.compress(Apok)
    e32 = dev_hash(b"pedersen", idx, Apok_comp, vc, ac)
    e5 = ring.reduce(bn.bytes_to_limbs_le(e32, P256, 22))
    sa = ring.submod(ka, ring.mulmod(e5, s_i))
    sb = ring.submod(kb, ring.mulmod(e5, l_i))
    return Apok_comp, sa, sb


@jax.jit
def _blk_pedersen_verify(Apok_comp, sa, sb, V_i: sp.SecpPointJ, R_pt, vc, ac, idx):
    """Phase-5B PedersenPoK verify: sa·R + sb·G + e·V ?= Apok."""
    ring = sp.scalar_ring()
    e32 = dev_hash(b"pedersen", idx, Apok_comp, vc, ac)
    e5 = ring.reduce(bn.bytes_to_limbs_le(e32, P256, 22))
    lhs = sp.add(
        sp.add(
            sp.scalar_mul(bn.limbs_to_bits(sa, P256, SCALAR_BITS), R_pt),
            sp.base_mul(bn.limbs_to_bits(sb, P256, SCALAR_BITS)),
        ),
        sp.scalar_mul(bn.limbs_to_bits(e5, P256, SCALAR_BITS), V_i),
    )
    return _eq_all(sp.compress(lhs), Apok_comp)


@jax.jit
def _blk_va_check(blind_i, vc, ac, idx, commit):
    """Phase-5B decommit check of a peer's (V_c, A_c) commitment."""
    return _eq_all(dev_hash(b"VA", idx, blind_i, vc, ac), commit)


@functools.partial(jax.jit, static_argnums=(1,))
def _blk_W_from_vss(C_comp, xj: int, lam_bits):
    """W_j = λ_j · Σ_k x_j^k · C_k from aggregated VSS commitments.

    ``C_comp``: (t+1, B, 33) compressed commitment points (wallet order),
    ``xj``: the party's Shamir x (static small int), ``lam_bits``: (256,)
    LSB-first bits of λ_j (shared across the batch; an operand so one
    executable serves every quorum). Returns (W points, ok mask)."""
    pts, ok_all = sp.decompress(C_comp)
    ok = jnp.all(ok_all, axis=0)
    t1 = C_comp.shape[0]
    acc = sp.SecpPointJ(pts.X[t1 - 1], pts.Y[t1 - 1], pts.Z[t1 - 1])
    nb = max(1, xj.bit_length())
    xj_bits = jnp.asarray(sp.scalars_to_bits([xj], n_bits=nb)[0])
    for k in range(t1 - 2, -1, -1):
        acc = sp.scalar_mul(
            jnp.broadcast_to(xj_bits, acc.X.shape[:-1] + (nb,)), acc
        )
        acc = sp.add(acc, sp.SecpPointJ(pts.X[k], pts.Y[k], pts.Z[k]))
    W = sp.scalar_mul(
        jnp.broadcast_to(lam_bits, acc.X.shape[:-1] + (SCALAR_BITS,)), acc
    )
    return W, ok


@jax.jit
def _blk_va(m, r, k_i, sigma_i, l_i, rho_i, R_pt, blind_i, idx):
    """Phase 5A per party: s_i, V_i = s_i·R + l_i·G, A_i = ρ_i·G, commit."""
    ring = sp.scalar_ring()
    s_i = ring.addmod(ring.mulmod(m, k_i), ring.mulmod(r, sigma_i))
    V_i = sp.add(
        sp.scalar_mul(bn.limbs_to_bits(s_i, P256, SCALAR_BITS), R_pt),
        sp.base_mul(bn.limbs_to_bits(l_i, P256, SCALAR_BITS)),
    )
    A_i = sp.base_mul(bn.limbs_to_bits(rho_i, P256, SCALAR_BITS))
    vc, ac = sp.compress(V_i), sp.compress(A_i)
    commit = dev_hash(b"VA", idx, blind_i, vc, ac)
    return s_i, V_i, A_i, vc, ac, commit


@jax.jit
def _blk_pedersen(ka, kb, s_i, l_i, V_i, R_pt, vc, ac, blind_i, idx, commit):
    """Phase 5B per party: decommit check + PedersenPoK of (s_i, l_i)."""
    ring = sp.scalar_ring()
    ok = _eq_all(dev_hash(b"VA", idx, blind_i, vc, ac), commit)
    Apok = sp.add(
        sp.scalar_mul(bn.limbs_to_bits(ka, P256, SCALAR_BITS), R_pt),
        sp.base_mul(bn.limbs_to_bits(kb, P256, SCALAR_BITS)),
    )
    Apok_comp = sp.compress(Apok)
    e32 = dev_hash(b"pedersen", idx, Apok_comp, vc, ac)
    e5 = ring.reduce(bn.bytes_to_limbs_le(e32, P256, 22))
    sa = ring.submod(ka, ring.mulmod(e5, s_i))
    sb = ring.submod(kb, ring.mulmod(e5, l_i))
    lhs = sp.add(
        sp.add(
            sp.scalar_mul(bn.limbs_to_bits(sa, P256, SCALAR_BITS), R_pt),
            sp.base_mul(bn.limbs_to_bits(sb, P256, SCALAR_BITS)),
        ),
        sp.scalar_mul(bn.limbs_to_bits(e5, P256, SCALAR_BITS), V_i),
    )
    return ok & _eq_all(sp.compress(lhs), Apok_comp)


@jax.jit
def _blk_V(V_sum, m, r, Y):
    """V = ΣV_i - m·G - r·Y (phase 5C prelude)."""
    m_bits = bn.limbs_to_bits(m, P256, SCALAR_BITS)
    return sp.add(
        V_sum,
        sp.add(
            sp.neg(sp.base_mul(m_bits)),
            sp.neg(sp.scalar_mul(bn.limbs_to_bits(r, P256, SCALAR_BITS), Y)),
        ),
    )


@jax.jit
def _blk_ut(rho_i, l_i, V, A_sum, blind_i, idx):
    """Phase 5C per party: U_i = ρ_i·V, T_i = l_i·ΣA, commit."""
    U_i = sp.scalar_mul(bn.limbs_to_bits(rho_i, P256, SCALAR_BITS), V)
    T_i = sp.scalar_mul(bn.limbs_to_bits(l_i, P256, SCALAR_BITS), A_sum)
    uc, tc = sp.compress(U_i), sp.compress(T_i)
    commit = dev_hash(b"UT", idx, blind_i, uc, tc)
    return U_i, T_i, uc, tc, commit


@jax.jit
def _blk_ut_check(blind_i, uc, tc, idx, commit):
    return _eq_all(dev_hash(b"UT", idx, blind_i, uc, tc), commit)


@jax.jit
def _blk_final(s, m, r, Y, rec):
    """Low-s normalize + batched ECDSA verification x(u1·G+u2·Y) == r."""
    ring = sp.scalar_ring()
    ok = ~jnp.all(s == 0, axis=-1)
    half = jnp.broadcast_to(jnp.asarray(bn.to_limbs(Q // 2, P256)), s.shape)
    high = bn.compare(s, half) > 0
    s = jnp.where(high[..., None], ring.negmod(s), s)
    rec = jnp.where(high, rec ^ 1, rec)
    s_inv = ring.powmod_const(s, Q - 2)
    u1 = ring.mulmod(m, s_inv)
    u2 = ring.mulmod(r, s_inv)
    Rv = sp.add(
        sp.base_mul(bn.limbs_to_bits(u1, P256, SCALAR_BITS)),
        sp.scalar_mul(bn.limbs_to_bits(u2, P256, SCALAR_BITS), Y),
    )
    ok = ok & jnp.all(ring.reduce(sp.x_coordinate(Rv)) == r, axis=-1)
    return ok, s, rec


@jax.jit
def _withcheck_curve(s1_q, e_q, U_pt, W_pt):
    """MtAwc curve binding: s1·G ?= U + e·W → (B,) bool."""
    lhs = sp.base_mul(bn.limbs_to_bits(s1_q, P256, SCALAR_BITS))
    rhs = sp.add(
        U_pt,
        sp.scalar_mul(bn.limbs_to_bits(e_q, P256, SCALAR_BITS), W_pt),
    )
    return sp.equal(lhs, rhs)


# ---------------------------------------------------------------------------
# q-party batched co-signing fabric (bench / loopback deployments)
# ---------------------------------------------------------------------------


class GG18BatchCoSigners:
    """Runs B concurrent (t+1)-of-n GG18 signing sessions with every
    signer's round compute batched on device (the in-process measurement
    fabric — the distributed node runs the same kernels per party).

    ``party_ids``: the signing quorum (any ≥ t+1 subset of the keygen
    universe — reference ecdsa_signing_session.go:96-139).
    ``party_shares[i]`` are signer i's per-wallet shares (same wallet order
    across parties, one quorum topology per batch).
    """

    def __init__(
        self,
        party_ids: Sequence[str],
        party_shares: Sequence[Sequence[KeygenShare]],
        preparams: Optional[Dict[str, PreParams]] = None,
        dom: Domains = Domains(),
        rng=secrets,
        *,
        mta_impl: Optional[str] = None,
    ):
        self.q = len(party_ids)
        assert self.q >= 2, "need at least a 2-party quorum"
        self.ids = list(party_ids)
        self.B = len(party_shares[0])
        self.dom = dom
        self.rng = rng
        self.ring = sp.scalar_ring()

        first = party_shares[0][0]
        assert self.q >= first.threshold + 1, "quorum below threshold+1"
        universe_xs = party_xs(first.participants)
        quorum_xs = [universe_xs[p] for p in party_ids]
        # all ordered MtA directions
        self.pairs = [
            (a, b)
            for a in range(self.q)
            for b in range(self.q)
            if a != b
        ]
        # MtA implementation: "paillier" (default — the GG18 MtA with
        # range proofs), "ot" (OT-based Gilboa multiplication,
        # protocol.ecdsa.mta_ot: no Paillier anywhere in signing;
        # KOS/DKLs-style checks with identifiable abort — see
        # SECURITY.md "OT-MtA" for exact coverage), or
        # "none" (curve state only — no MtA contexts, cannot sign();
        # the multichip dryrun builds its sharding probe this way via
        # :meth:`curve_only` instead of hand-wiring ``__new__``)
        self.mta_impl = os.environ.get("MPCIUM_MTA", "paillier")
        if mta_impl is not None:
            self.mta_impl = mta_impl
        if self.mta_impl not in ("paillier", "ot", "none"):
            raise ValueError(
                f"MPCIUM_MTA={self.mta_impl!r}: expected 'paillier' or 'ot'"
            )
        if self.mta_impl == "ot":
            from ..protocol.ecdsa.mta_ot import OTMtALeg

            self.ctx = None
            self.mta = None
            self.ot_legs = {
                (a, b): OTMtALeg(
                    f"{party_ids[a]}->{party_ids[b]}", rng=rng
                )
                for (a, b) in self.pairs
            }
        elif self.mta_impl == "none":
            self.ctx = None
            self.mta = None
            self.ot_legs = None
        else:
            if preparams is None:
                raise ValueError("mta_impl='paillier' requires preparams")
            self.ctx = [PartyCtx(pid, preparams[pid], rng) for pid in party_ids]
            self.mta = {
                (a, b): MtaBatch(self.ctx[a], self.ctx[b], dom)
                for (a, b) in self.pairs
            }
        # additive shares w_i = λ_i·x_i mod q (λ shared across the batch)
        self.w = []
        self.W_pts = []
        for i, (pid, shares) in enumerate(zip(party_ids, party_shares)):
            lam = hm.lagrange_coeff(quorum_xs, universe_xs[pid], Q)
            w_ints = [lam * s.share % Q for s in shares]
            w_limbs = jnp.asarray(bn.batch_to_limbs(w_ints, P256))
            self.w.append(w_limbs)
            for s in shares:
                if s.key_type != "secp256k1":
                    raise ValueError("wrong key type")
                if s.self_x != universe_xs[pid]:
                    raise ValueError("party_shares misaligned with party_ids")
            W, _ = _base_mul_compressed(w_limbs)
            self.W_pts.append(W)
        # wallet public keys (host decompress once at setup)
        pubs = [hm.secp_decompress(s.public_key) for s in party_shares[0]]
        self.Y = sp.from_host(pubs)

    @classmethod
    def curve_only(
        cls,
        party_ids: Sequence[str],
        party_shares: Sequence[Sequence[KeygenShare]],
        rng=secrets,
    ) -> "GG18BatchCoSigners":
        """Curve state (w, W_pts, Y) without any MtA machinery — for
        sharding probes and dryruns that exercise the batched point math
        but never run the signing protocol. ``sign()`` raises."""
        return cls(party_ids, party_shares, None, rng=rng, mta_impl="none")

    # -- small helpers -------------------------------------------------------

    def _rand_scalar(self) -> jnp.ndarray:
        return _scalar_from_wide_bytes(
            jnp.asarray(rand_bits(self.B, 320, self.rng))
        )

    def _rand_scalars_q(self) -> jnp.ndarray:
        """(q, B, 22) uniform scalars mod q (one upload + one dispatch)."""
        raw = rand_bits(self.q * self.B, 320, self.rng).reshape(
            self.q, self.B, 40
        )
        return _scalar_from_wide_bytes(jnp.asarray(raw))

    def _blinds_q(self) -> jnp.ndarray:
        return jnp.asarray(
            rand_bits(self.q * self.B, 256, self.rng).reshape(
                self.q, self.B, 32
            )
        )

    # -- the protocol --------------------------------------------------------

    def sign(
        self, digests: np.ndarray, phase_times: Optional[dict] = None,
        cohorts: Optional[int] = None,
    ) -> Dict[str, np.ndarray]:
        """``digests``: (B, 32) big-endian digests. Returns dict with
        r, s (B, 32 BE bytes), recovery (B,), ok mask (B,).

        ``phase_times``: optional dict — when given (or when mpctrace is
        armed), the engine blocks at phase boundaries and records wall
        seconds per protocol phase as ``phase:*`` spans plus the legacy
        dict (bench diagnostics; adds sync overhead only then).

        ``cohorts``: counter-phase cohort count for the signing tail
        (engine/pipeline; None → MPCIUM_PIPELINE_COHORTS, default 2).
        Signatures and transcripts are bit-identical for every K —
        randomness is drawn full-batch in serial order before any
        split."""
        if self.mta_impl == "none":
            raise RuntimeError(
                "curve_only signer has no MtA contexts — cannot sign()"
            )
        _pt = tracing.PhaseTimer(
            "gg18.sign", _trace_sync, phase_times=phase_times,
            node="engine", tid=f"gg18:B{self.B}",
        )
        _mark = _pt.mark
        # first call per (engine, shape-bucket) pays the compile wall:
        # ledger it (one set lookup + None on every later call)
        # mpcshape: unbounded-ok — B is pow-2 snapped upstream (scheduler chunks via engine/buckets.floor_bucket; bench via bucket_b)
        _cw = compile_watch.begin(
            "gg18.sign", f"B{self.B}|q{self.q}|mta={self.mta_impl}"
        )
        B, q = self.B, self.q
        ring = self.ring
        m = ring.reduce(
            bn.bytes_to_limbs_le(jnp.asarray(digests[:, ::-1].copy()), P256, 22)
        )

        # ---- round 1: k, γ, Γ commitments; shared c_i = Enc_i(k_i) ---------
        k_st = self._rand_scalars_q()
        gamma_st = self._rand_scalars_q()
        k = [k_st[i] for i in range(q)]
        gamma = [gamma_st[i] for i in range(q)]
        g_blind = self._blinds_q()
        Gamma, Gamma_comp, g_commit = [], [], []
        for i in range(q):
            pt, comp, commit = _blk_gamma(gamma_st[i], g_blind[i], _idx_row(i, B))
            Gamma.append(pt)
            Gamma_comp.append(comp)
            g_commit.append(commit)

        if self.mta_impl == "ot":
            # ---- OT path: no Paillier in signing at all. Rounds 1-3 of
            # the MtA machinery collapse into Gilboa OT multiplication
            # per (ordered pair, secret): alpha+beta ≡ k_a·secret_b
            # (mod q). Commitments/Γ from round 1 above are unchanged,
            # as is everything from δ/σ assembly on — the signature
            # itself is still verified in-protocol at phase 5.
            _mark("r1_commit_encrypt_rangeproof", *Gamma_comp)
            ok = jnp.ones((B,), bool)
            alpha_shares = {}
            beta_shares = {}
            # pipeline chunking knob (MPCIUM_OT_CHUNKS; 0/unset → auto
            # from B) — resolved here so every leg of the quorum runs
            # the same schedule
            from ..protocol.ecdsa.mta_ot import resolve_chunks

            ot_chunks = resolve_chunks(B)
            ot_timings = {} if _pt.on else None
            for (a, b) in self.pairs:
                leg = self.ot_legs[(a, b)]
                # one extension serves BOTH products (same k_a choice
                # bits; set-separated pad domains — mta_ot.run_multi)
                shares = leg.run_multi(
                    k[a], (gamma[b], self.w[b]),
                    chunks=ot_chunks, timings=ot_timings,
                )
                for name, (al, be) in zip(("gamma", "w"), shares):
                    alpha_shares[(a, b, name)] = al
                    beta_shares[(a, b, name)] = be
            # host/device A/B split of the OT phase rides the span as
            # attrs (and the legacy dict as r2_mta_ot_* keys): host_s is
            # worker-thread busy time, device is main-thread block time
            # on device arrays; hidden host time (host_s minus the
            # residual main-thread wait on the worker) over host_s is
            # the pipeline's overlap ratio.
            ot_attrs = {}
            if ot_timings:
                host_s = ot_timings.get("host_s", 0.0)
                hidden = max(0.0, host_s - ot_timings.get("host_wait_s", 0.0))
                ot_attrs = {
                    "host": host_s,
                    "device": ot_timings.get("device_wait_s", 0.0),
                    "overlap_ratio": hidden / host_s if host_s > 0 else 0.0,
                    "chunks": float(ot_chunks),
                }
            _mark("r2_mta_ot",
                  *[alpha_shares[(p[0], p[1], "w")] for p in self.pairs],
                  **ot_attrs)
            # Identifiable abort (ISSUE 16): every leg ran its KOS /
            # Gilboa / consistency checks inside run_multi; a blamed
            # lane aborts the cohort with the offending (lane, party)
            # named, so the scheduler can quarantine exactly those
            # sessions and re-pack the survivors. Alice = the leg's
            # receiver = party a (its choice bits are k_a); Bob = party
            # b. A lane keeps its FIRST blame — a tampered extension
            # garbles downstream pads, so later checks on the same lane
            # are side effects, not independent evidence.
            blamed: Dict[int, Tuple[str, str]] = {}
            for (a, b) in self.pairs:
                per_lane = self.ot_legs[(a, b)].check_blame()
                if per_lane is None:
                    continue
                for lane, verdict in enumerate(per_lane):
                    if verdict is None or lane in blamed:
                        continue
                    role, check = verdict
                    blamed[lane] = (
                        self.ids[a] if role == "alice" else self.ids[b],
                        check,
                    )
            if blamed:
                from .abort import CohortAbort

                raise CohortAbort(
                    [(lane, pid, check)
                     for lane, (pid, check) in sorted(blamed.items())],
                    engine="gg18.sign",
                )
            out = self._finish_sign(
                _pt, m, ok, k, gamma, Gamma, Gamma_comp,
                g_commit, g_blind, alpha_shares, beta_shares,
                cohorts=cohorts,
            )
            compile_watch.finish(_cw)
            return out

        # per-party encryption of k_i (one ciphertext reused by all pairs)
        c_k, u_k, k_plain = [], [], []
        for i in range(q):
            u_bits = rand_bit_tensor(B, RAND_BITS, self.rng)
            kp = _scalar_to_plain(self.ctx[i].pmx, k[i])
            c, _r = self.ctx[i].pmx.encrypt(kp, u_bits)
            c_k.append(c)
            u_k.append(u_bits)
            k_plain.append(kp)

        mta_state: Dict[Tuple[int, int], Dict] = {}
        for (a, b) in self.pairs:
            mta = self.mta[(a, b)]
            Ra = mta.alice_randoms(B, self.rng)
            T = mta.alice_init(k_plain[a], Ra)
            e = mta.e_limbs(mta.alice_challenge(c_k[a], T))
            P = mta.alice_finish(e, k_plain[a], Ra, u_k[a])
            mta_state[(a, b)] = {"Ra": Ra, "T": T, "e": e, "P": P}
        _mark("r1_commit_encrypt_rangeproof",
              *[mta_state[p]["P"]["s"] for p in self.pairs])

        ok = jnp.ones((B,), bool)

        # ---- round 2: Bob verifies + responds (γ and w) --------------------
        for (a, b) in self.pairs:
            mta = self.mta[(a, b)]
            st = mta_state[(a, b)]
            ok = ok & mta.bob_check_alice(
                c_k[a], st["T"], st["P"], st["e"], rng=self.rng
            )
            for name, secret in (("gamma", gamma[b]), ("w", self.w[b])):
                Rb = mta.bob_randoms(B, self.rng)
                b_e = _scalar_to_prof(secret, mta.p_e)
                Tb = mta.bob_respond(c_k[a], b_e, Rb)
                extra = ()
                U_pt = None
                if name == "w":
                    alpha_q = _mod_q_from_limbs(Rb["alpha"], mta.p_alpha)
                    U_pt, U_comp = _base_mul_compressed(alpha_q)
                    X_comp = sp.compress(self.W_pts[b])
                    extra = (U_comp, X_comp)
                e_b = mta.e_limbs(mta.bob_challenge(c_k[a], Tb, extra))
                Pb = mta.bob_finish(e_b, b_e, Rb)
                st[name] = {"Rb": Rb, "Tb": Tb, "e": e_b, "Pb": Pb, "U": U_pt}

        _mark("r2_mta_respond", ok,
              *[mta_state[p]["w"]["Tb"]["c_b"] for p in self.pairs])

        # ---- round 3: Alice verifies + decrypts; δ_i, σ_i ------------------
        alpha_shares = {}   # (a, b, name) -> alice's additive share mod q
        beta_shares = {}    # (a, b, name) -> bob's additive share mod q
        for (a, b) in self.pairs:
            mta = self.mta[(a, b)]
            st = mta_state[(a, b)]
            for name in ("gamma", "w"):
                sub = st[name]
                ok = ok & mta.alice_check_bob(
                    c_k[a], sub["Tb"], sub["Pb"], sub["e"], rng=self.rng
                )
                if name == "w":
                    # with-check: s1·G ?= U + e·W_b (one fused dispatch)
                    ok = ok & _withcheck_curve(
                        _mod_q_from_limbs(sub["Pb"]["s1"], mta.p_s1),
                        _mod_q_from_limbs(sub["e"], mta.p_e),
                        sub["U"],
                        self.W_pts[b],
                    )
                alpha_shares[(a, b, name)] = mta.alice_decrypt_share(
                    sub["Tb"]["c_b"]
                )
                beta_shares[(a, b, name)] = ring.negmod(
                    _mod_q_from_limbs(sub["Rb"]["beta_prime"], mta.p_bp)
                )

        out = self._finish_sign(
            _pt, m, ok, k, gamma, Gamma, Gamma_comp, g_commit, g_blind,
            alpha_shares, beta_shares, cohorts=cohorts,
        )
        compile_watch.finish(_cw)
        return out

    def _finish_sign(
        self, _pt, m, ok, k, gamma, Gamma, Gamma_comp, g_commit,
        g_blind, alpha_shares, beta_shares,
        cohorts: Optional[int] = None,
    ) -> Dict[str, np.ndarray]:
        """Shared tail of both MtA implementations, cohort-pipelined
        (engine/pipeline): δ/σ assembly, R reconstruction, Schnorr PoKs,
        the full phase-5 commit–reveal and the in-protocol ECDSA
        verification. With K>1 each cohort's device rounds dispatch
        while another cohort's signature egress drains on the pipeline
        host worker; K=1 is byte-for-byte the old serial path.

        Transcript discipline: ALL tail randomness is drawn here — full
        batch, in the K=1 serial order (kpok, li, ri, ka, kb, va_blind,
        ut_blind) — then row-sliced per cohort, so the rng stream and
        every commitment/signature byte is identical for every K. (The
        MtA rounds BEFORE this tail always run full-batch: the OT
        extension's PRF tags are width- and counter-dependent, so
        splitting them would change transcripts; its own chunk overlap
        already pipelines that stage.)"""
        B, q = self.B, self.q
        rand = {
            "kpok": self._rand_scalars_q(),
            "li": self._rand_scalars_q(),
            "ri": self._rand_scalars_q(),
            "ka": self._rand_scalars_q(),
            "kb": self._rand_scalars_q(),
            "va_blind": self._blinds_q(),
            "ut_blind": self._blinds_q(),
        }
        from . import pipeline as pl

        plan = pl.CohortPlan.for_batch(B, cohorts)
        if plan.serial:
            r_d, s_d, rec_d, ok_d = self._tail_cohort(
                _pt.mark, m, ok, k, gamma, Gamma, Gamma_comp, g_commit,
                g_blind, alpha_shares, beta_shares, rand,
                list(self.w), self.Y,
            )
            return _sig_egress(r_d, s_d, rec_d, ok_d)

        # per-cohort phase timers: independent spans (tid …:cN) so the
        # idle meter sees the counter-phase overlap; legacy phase dicts
        # are summed back into the caller's afterwards
        cohort_phases = [
            {} if _pt.phases is not None else None for _ in range(plan.k)
        ]

        def job(ci: int, sl: slice):
            def run():
                pt_c = tracing.PhaseTimer(
                    "gg18.sign", _trace_sync,
                    phase_times=cohort_phases[ci],
                    node="engine", tid=f"gg18:B{B}:c{ci}",
                )
                r_d, s_d, rec_d, ok_d = self._tail_cohort(
                    pt_c.mark,
                    m[sl], ok[sl],
                    [x[sl] for x in k],
                    [x[sl] for x in gamma],
                    [_slice_pt(p, sl) for p in Gamma],
                    [x[sl] for x in Gamma_comp],
                    [x[sl] for x in g_commit],
                    g_blind[:, sl],
                    {kk: v[sl] for kk, v in alpha_shares.items()},
                    {kk: v[sl] for kk, v in beta_shares.items()},
                    {kk: v[:, sl] for kk, v in rand.items()},
                    [x[sl] for x in self.w],
                    _slice_pt(self.Y, sl),
                )
                res = yield (
                    "sig_egress",
                    lambda: _sig_egress(r_d, s_d, rec_d, ok_d),
                )
                return res

            return run

        parts = pl.run_counter_phase(
            [job(ci, sl) for ci, sl in enumerate(plan.slices())]
        )
        if _pt.phases is not None:
            for d in cohort_phases:
                for name, v in (d or {}).items():
                    _pt.phases[name] = _pt.phases.get(name, 0.0) + v
        return {
            key: pl.merge_rows([p[key] for p in parts])
            for key in parts[0]
        }

    def _tail_cohort(
        self, _mark, m, ok, k, gamma, Gamma, Gamma_comp, g_commit,
        g_blind, alpha_shares, beta_shares, rand, w, Y,
    ):
        """One cohort's tail rounds over pre-sliced device views —
        every kernel here is per-lane in B, so a cohort slice computes
        exactly the rows it would as part of the full batch. Returns
        DEVICE tensors (r, s, recovery, ok); the host egress is the
        caller's pipeline stage."""
        B = int(m.shape[0])
        q = self.q
        ring = self.ring
        delta_i, sigma_i = [], []
        for i in range(q):
            d = ring.mulmod(k[i], gamma[i])
            s_ = ring.mulmod(k[i], w[i])
            for j in range(q):
                if j == i:
                    continue
                d = ring.addmod(
                    d,
                    ring.addmod(
                        alpha_shares[(i, j, "gamma")],
                        beta_shares[(j, i, "gamma")],
                    ),
                )
                s_ = ring.addmod(
                    s_,
                    ring.addmod(
                        alpha_shares[(i, j, "w")], beta_shares[(j, i, "w")]
                    ),
                )
            delta_i.append(d)
            sigma_i.append(s_)

        _mark("r3_verify_decrypt", ok, *delta_i, *sigma_i)

        # ---- rounds 4-9: R reconstruction + phase 5 (jitted per-party
        # blocks, each compiled once and reused q times) ------------------
        for i in range(q):
            ok = ok & _blk_gamma_check(
                g_blind[i], Gamma_comp[i], _idx_row(i, B), g_commit[i]
            )
        delta = delta_i[0]
        Gamma_sum = Gamma[0]
        for i in range(1, q):
            delta = ring.addmod(delta, delta_i[i])
            Gamma_sum = _blk_point_add(Gamma_sum, Gamma[i])
        ok_R, R_pt, r, rec = _blk_R(delta, Gamma_sum)
        ok = ok & ok_R
        kpok = rand["kpok"]
        for i in range(q):
            ok = ok & _blk_schnorr(
                kpok[i], gamma[i], Gamma[i], Gamma_comp[i], _idx_row(i, B)
            )
        _mark("r4_R_reconstruct_pok", ok, r)

        # phase 5A: commitments to V_i, A_i (randomness pre-drawn by
        # _finish_sign in serial order — see its transcript note)
        li = rand["li"]
        ri = rand["ri"]
        ka = rand["ka"]
        kb = rand["kb"]
        va_blind = rand["va_blind"]
        ut_blind = rand["ut_blind"]
        s_i, V_i, A_i, V_c, A_c, va_commit = [], [], [], [], [], []
        for i in range(q):
            si, Vi, Ai, vc, ac, cmt = _blk_va(
                m, r, k[i], sigma_i[i], li[i], ri[i], R_pt, va_blind[i],
                _idx_row(i, B),
            )
            s_i.append(si); V_i.append(Vi); A_i.append(Ai)
            V_c.append(vc); A_c.append(ac); va_commit.append(cmt)
        # phase 5B: decommit + PedersenPoK
        for i in range(q):
            ok = ok & _blk_pedersen(
                ka[i], kb[i], s_i[i], li[i], V_i[i], R_pt, V_c[i], A_c[i],
                va_blind[i], _idx_row(i, B), va_commit[i],
            )
        # phase 5C/5D: U/T commit–reveal + ΣU == ΣT
        V_sum, A_sum = V_i[0], A_i[0]
        for i in range(1, q):
            V_sum = _blk_point_add(V_sum, V_i[i])
            A_sum = _blk_point_add(A_sum, A_i[i])
        V = _blk_V(V_sum, m, r, Y)
        U_pts, T_pts, U_c, T_c, ut_commit = [], [], [], [], []
        for i in range(q):
            Ui, Ti, uc, tc, cmt = _blk_ut(
                ri[i], li[i], V, A_sum, ut_blind[i], _idx_row(i, B)
            )
            U_pts.append(Ui); T_pts.append(Ti)
            U_c.append(uc); T_c.append(tc); ut_commit.append(cmt)
        for i in range(q):
            ok = ok & _blk_ut_check(
                ut_blind[i], U_c[i], T_c[i], _idx_row(i, B), ut_commit[i]
            )
        U_s, T_s = U_pts[0], T_pts[0]
        for i in range(1, q):
            U_s = _blk_point_add(U_s, U_pts[i])
            T_s = _blk_point_add(T_s, T_pts[i])
        ok = ok & _blk_point_eq(U_s, T_s)
        # phase 5E: reveal + combine + verify — the carried round state
        # goes through the donated final step (rebind-only: MPS906)
        s = s_i[0]
        for i in range(1, q):
            s = ring.addmod(s, s_i[i])
        st = {"s": s, "m": m, "r": r, "rec": rec, "ok": ok}
        st = _step_final(st, Y)
        _mark("r5_phase5_combine_verify", st["ok"], st["s"])
        return st["r"], st["s"], st["rec"], st["ok"]


def _slice_pt(pt, sl: slice):
    """Row-slice a point pytree (NamedTuple of (B, …) leaf arrays) into
    one cohort's lane view."""
    return type(pt)(*(leaf[sl] for leaf in pt))


@functools.partial(jax.jit, donate_argnums=(0,))
def _step_final(st, Y):
    """Phase-5E combine + in-protocol verify as a DONATED round step:
    the carried per-round state pytree {s, m, r, rec, ok} is consumed
    (XLA reuses/frees its buffers — the HBM headroom for B=16384) and
    replaced by the output state. Callers rebind, never re-read
    (mpcshape MPS906)."""
    ok_f, s, rec = _blk_final(st["s"], st["m"], st["r"], Y, st["rec"])
    return {"r": st["r"], "s": s, "rec": rec, "ok": st["ok"] & ok_f}


def _sig_egress(r, s, rec, ok) -> Dict[str, np.ndarray]:
    """Signature egress: device limbs → host BE bytes. Runs as a
    pipeline host stage under K>1."""
    return {
        "r": np.asarray(bn.limbs_to_bytes_le(r, P256, 32))[:, ::-1].copy(),  # mpcflow: host-ok — signature egress
        "s": np.asarray(bn.limbs_to_bytes_le(s, P256, 32))[:, ::-1].copy(),  # mpcflow: host-ok — signature egress
        "recovery": np.asarray(rec),  # mpcflow: host-ok — signature egress
        "ok": np.asarray(ok),  # mpcflow: host-ok — per-wallet verdicts, egress with the signatures
    }


def dealer_keygen_secp_batch(
    n_wallets: int,
    party_ids: Sequence[str],
    threshold: int,
    rng=secrets,
    preparams: Optional[Dict[str, PreParams]] = None,
) -> List[List[KeygenShare]]:
    """Trusted-dealer batch keygen for tests/bench setup ONLY — production
    wallets come from protocol.ecdsa.keygen. result[i] belongs to
    party_ids[i], wallet order aligned.

    With ``preparams``, shares also carry the keygen aux material
    (paillier/ring-Pedersen maps + VSS commitments) that the distributed
    signing parties (per-session and batched) consume."""
    xs = party_xs(party_ids)
    out: List[List[KeygenShare]] = [[] for _ in party_ids]
    aux_by_pid: Dict[str, Dict] = {}
    if preparams is not None:
        for pid in party_ids:
            pre = preparams[pid]
            aux_by_pid[pid] = {
                "paillier_sk": pre.paillier.to_json(),
                "preparams": {
                    "ntilde": str(pre.NTilde),
                    "h1": str(pre.h1),
                    "h2": str(pre.h2),
                },
                "peer_paillier": {
                    p: str(preparams[p].paillier.N)
                    for p in party_ids
                    if p != pid
                },
                "peer_ring_pedersen": {
                    p: {
                        "ntilde": str(preparams[p].NTilde),
                        "h1": str(preparams[p].h1),
                        "h2": str(preparams[p].h2),
                    }
                    for p in party_ids
                    if p != pid
                },
            }
    for _ in range(n_wallets):
        secret = rng.randbelow(Q - 1) + 1
        coeffs, shares = hm.shamir_share(
            secret, threshold, [xs[p] for p in party_ids], Q, rng=rng
        )
        pub = hm.secp_compress(hm.secp_mul(secret, hm.SECP_G))
        vss = (
            [hm.secp_compress(hm.secp_mul(c, hm.SECP_G)) for c in coeffs]
            if preparams is not None
            else []
        )
        for i, pid in enumerate(party_ids):
            out[i].append(
                KeygenShare(
                    key_type="secp256k1",
                    share=shares[xs[pid]],
                    self_x=xs[pid],
                    public_key=pub,
                    vss_commitments=list(vss),
                    participants=sorted(party_ids),
                    threshold=threshold,
                    aux=aux_by_pid.get(pid, {}),
                )
            )
    return out
