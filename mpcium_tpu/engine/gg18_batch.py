"""Batched GG18 threshold-ECDSA signing: the secp256k1 execution engine.

The north-star path (SURVEY.md §6: batched 2-of-3 secp256k1 signing): B
concurrent sessions' round compute coalesced into fixed-shape device
dispatches per party. The protocol is mathematically identical to
``protocol.ecdsa.signing`` (GG18: MtA with range proofs, phase-5
commit–reveal) — re-expressed over limb tensors:

- curve ops ride :mod:`core.secp256k1_jax` (12-bit limb family);
- Paillier / ring-Pedersen modexps ride :mod:`core.bignum` Barrett contexts
  in the 11-bit limb family (block-structured wide muls);
- hashing (commitments, Fiat–Shamir challenges) stays host-side over
  fixed-width byte serializations pulled from device.

Transcript note: the batched fabric hashes fixed-width byte encodings (not
the per-session host protocol's length-prefixed ints) — the two paths are
separate wire universes; parity with the reference is at the result level
(signatures verify under the same pubkeys).

Randomness policy: a value mod M is sampled as CSPRNG bits of
``bits(M) - 8`` (for masks, where slight undersampling only strengthens the
bound) or reduced mod M on device; Paillier randomizers skip the
gcd(r, N) = 1 rejection (a non-unit hit implies factoring N).

Test note: proof-equation algebra holds for any key size, so unit tests run
512-bit keys with shrunk exponent domains (the ``bits`` knobs below); the
full-size path is exercised by bench.py on real hardware.
"""
from __future__ import annotations

import functools
import hashlib
import secrets
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import bignum as bn
from ..core import hostmath as hm
from ..core import secp256k1_jax as sp
from ..core.bignum import P256
from ..core.paillier import PaillierBatch, PreParams
from ..protocol.base import KeygenShare, party_xs

Q = hm.SECP_N
SCALAR_BITS = 256


@dataclass(frozen=True)
class Domains:
    """Exponent-domain bit sizes (GG18 appendix A). Shrunk in unit tests."""

    scalar: int = 256       # curve scalars (a, b, e)
    alpha: int = 760        # < q³
    beta_prime: int = 1272  # < q⁵
    gamma_bob: int = 1784   # < q⁷
    rho_extra: int = 248    # ρ < q·NTilde  → scalar-8 + nt bits
    s1_bound: int = 768     # q³ bound checked by verifiers

    def q3(self) -> int:
        return Q**3


def _prof11(bits: int) -> bn.LimbProfile:
    return bn.LimbProfile(bits=11, n_limbs=max(2, -(-bits // 11)))


def rand_bits(batch: int, bits: int, rng=secrets) -> np.ndarray:
    """(B, ceil(bits/8)) CSPRNG bytes encoding a uniform `bits`-bit int."""
    nbytes = -(-bits // 8)
    raw = np.frombuffer(rng.token_bytes(batch * nbytes), dtype=np.uint8)
    out = raw.reshape(batch, nbytes).copy()
    extra = 8 * nbytes - bits
    if extra:
        out[:, -1] &= (1 << (8 - extra)) - 1
    return out


def hash_rows(tag: bytes, *parts) -> np.ndarray:
    """Per-session SHA-256 over concatenated fixed-width rows → (B, 32)."""
    parts = [np.asarray(p) for p in parts]
    B = parts[0].shape[0]
    out = np.empty((B, 32), dtype=np.uint8)
    for i in range(B):
        h = hashlib.sha256(b"mpcium-tpu/gg18-batch/" + tag)
        for p in parts:
            h.update(p[i].tobytes())
        out[i] = np.frombuffer(h.digest(), dtype=np.uint8)
    return out


def _int_mul_add(e, m, add, prof) -> jnp.ndarray:
    """e·m + add over plain integers (no modulus), normalized to the width
    of `prof`."""
    prod = bn.mul_wide(e, m, prof)
    width = prof.n_limbs
    return bn.carry(
        bn.take_limbs(prod, 0, width) + bn.take_limbs(add, 0, width), prof
    )


def _bits_of(x: jnp.ndarray, prof: bn.LimbProfile, n_bits: int) -> jnp.ndarray:
    return bn.limbs_to_bits(x, prof, n_bits)


# ---------------------------------------------------------------------------
# per-party static contexts
# ---------------------------------------------------------------------------


class PartyCtx:
    """One signer's static crypto material + device contexts."""

    def __init__(self, pid: str, pre: PreParams):
        self.pid = pid
        self.pre = pre
        self.pb = PaillierBatch(pre.paillier.public)
        self.N = pre.paillier.N
        self.NTilde = pre.NTilde
        self.prof_nt = _prof11(self.NTilde.bit_length())
        self.ctx_nt = bn.BarrettCtx(self.NTilde, self.prof_nt)
        self.h1 = pre.h1
        self.h2 = pre.h2
        self.nt_bytes = -(-self.NTilde.bit_length() // 8)
        self.n2_bytes = -(-(2 * self.N.bit_length()) // 8)
        self.n_bytes = -(-self.N.bit_length() // 8)

    def commit_ring(self, m_bits: jnp.ndarray, r_bits: jnp.ndarray) -> jnp.ndarray:
        """h1^m · h2^r mod NTilde — two fixed-base table modexps."""
        a = self.ctx_nt.powmod_fixed_base(self.h1, m_bits)
        b = self.ctx_nt.powmod_fixed_base(self.h2, r_bits)
        return self.ctx_nt.mulmod(a, b)


def _enc_deterministic(pb: PaillierBatch, m_limbs) -> jnp.ndarray:
    """(1 + m·N) mod N² for m < N — the deterministic Paillier leg."""
    N_l = jnp.broadcast_to(
        jnp.asarray(pb.N_limbs), m_limbs.shape[:-1] + (pb.prof_n.n_limbs,)
    )
    mN = bn.mul_wide(m_limbs, N_l, pb.prof_n2)
    out = bn.take_limbs(mN, 0, pb.prof_n2.n_limbs).at[..., 0].add(1)
    return bn.carry(out, pb.prof_n2)


# ---------------------------------------------------------------------------
# batched MtA with range proofs (one ordered direction Alice → Bob → Alice)
# ---------------------------------------------------------------------------


class MtaBatch:
    """Batched MtA + proofs for the ordered pair (alice, bob).

    The flow mirrors protocol.ecdsa.{mta,zk} exactly; the caller drives the
    host Fiat–Shamir points between device steps. State dicts hold limb
    tensors; every function is shape-stable and jit-compiled on first use.
    """

    def __init__(self, alice: PartyCtx, bob: PartyCtx, dom: Domains = Domains()):
        self.alice = alice
        self.bob = bob
        self.dom = dom
        d = dom
        self.p_e = _prof11(d.scalar)
        self.p_alpha = _prof11(d.alpha)
        self.p_s1 = _prof11(d.scalar + d.alpha + 11)
        nt_bits = bob.NTilde.bit_length()
        nt_bits_a = alice.NTilde.bit_length()
        self.p_rho = _prof11(d.scalar + max(nt_bits, nt_bits_a) + d.rho_extra)
        self.p_s2 = _prof11(d.scalar + self.p_rho.n_limbs * 11 + 11)
        self.p_bp = _prof11(d.beta_prime)
        self.p_gb = _prof11(d.gamma_bob)
        self.p_t1 = _prof11(d.scalar + d.gamma_bob + 11)

    # -- randomness bundles (host) ------------------------------------------

    def _unit_mod_NA(self, B: int, rng) -> jnp.ndarray:
        """Paillier randomizer mod N_A: (bits(N)+64)-bit sample reduced on
        device (bias 2^-64; unit whp)."""
        A = self.alice
        nb = A.N.bit_length()
        return A.pb.ctx_N.reduce(
            bn.bytes_to_limbs_le(
                jnp.asarray(rand_bits(B, nb + 64, rng)),
                A.pb.prof_n, 2 * A.pb.prof_n.n_limbs,
            )
        )

    @staticmethod
    def _dom_bits(B, bits, prof, rng):
        return bn.bytes_to_limbs_le(
            jnp.asarray(rand_bits(B, bits, rng)), prof, prof.n_limbs
        )

    def alice_randoms(self, B: int, rng=secrets) -> Dict[str, jnp.ndarray]:
        d = self.dom
        nt_b = self.bob.NTilde.bit_length()
        return {
            "r": self._unit_mod_NA(B, rng),
            "alpha": self._dom_bits(B, d.alpha - 8, self.p_alpha, rng),
            "rho": self._dom_bits(B, d.scalar + nt_b - 8, self.p_rho, rng),
            "gamma": self._dom_bits(B, d.alpha + nt_b - 8, self.p_s2, rng),
            "beta_r": self._unit_mod_NA(B, rng),
        }

    def bob_randoms(self, B: int, rng=secrets) -> Dict[str, jnp.ndarray]:
        d = self.dom
        nt_a = self.alice.NTilde.bit_length()
        return {
            "beta_prime": self._dom_bits(B, d.beta_prime - 8, self.p_bp, rng),
            "r": self._unit_mod_NA(B, rng),
            "alpha": self._dom_bits(B, d.alpha - 8, self.p_alpha, rng),
            "rho": self._dom_bits(B, d.scalar + nt_a - 8, self.p_rho, rng),
            "rho_p": self._dom_bits(B, d.alpha + nt_a - 8, self.p_s2, rng),
            "sigma": self._dom_bits(B, d.scalar + nt_a - 8, self.p_rho, rng),
            "tau": self._dom_bits(B, d.alpha + nt_a - 8, self.p_s2, rng),
            "beta_r": self._unit_mod_NA(B, rng),
            "gamma": self._dom_bits(B, d.gamma_bob - 8, self.p_gb, rng),
        }

    # -- Alice: encrypt + range proof ---------------------------------------

    def alice_init(self, m_limbs, R: Dict[str, jnp.ndarray]):
        """m: plaintext (< q) as Alice-N plaintext limbs. Returns the
        pre-challenge transcript {c_a, z, u, w}."""
        A, Bo = self.alice, self.bob
        c_a = A.pb.encrypt(m_limbs, R["r"])
        z = Bo.commit_ring(
            _bits_of(m_limbs, A.pb.prof_n, self.dom.scalar),
            _bits_of(R["rho"], self.p_rho, self.p_rho.n_limbs * 11),
        )
        u = A.pb.encrypt(
            bn.take_limbs(R["alpha"], 0, A.pb.prof_n.n_limbs), R["beta_r"]
        )
        w = Bo.commit_ring(
            _bits_of(R["alpha"], self.p_alpha, self.dom.alpha),
            _bits_of(R["gamma"], self.p_s2, self.p_s2.n_limbs * 11),
        )
        return {"c_a": c_a, "z": z, "u": u, "w": w}

    def alice_challenge(self, T) -> np.ndarray:
        """Fiat–Shamir e ← H(transcript) (host)."""
        A, Bo = self.alice, self.bob
        return hash_rows(
            b"alice",
            bn.limbs_to_bytes_le(T["c_a"], A.pb.prof_n2, A.n2_bytes),
            bn.limbs_to_bytes_le(T["z"], Bo.prof_nt, Bo.nt_bytes),
            bn.limbs_to_bytes_le(T["u"], A.pb.prof_n2, A.n2_bytes),
            bn.limbs_to_bytes_le(T["w"], Bo.prof_nt, Bo.nt_bytes),
        )

    def e_limbs(self, e32: np.ndarray) -> jnp.ndarray:
        return bn.bytes_to_limbs_le(jnp.asarray(e32), self.p_e, self.p_e.n_limbs)

    def alice_finish(self, e, m_limbs, R):
        """Challenge responses: s = r^e·β mod N_A; s1 = e·m + α;
        s2 = e·ρ + γ."""
        A = self.alice
        e_bits = _bits_of(e, self.p_e, self.dom.scalar)
        s = A.pb.ctx_N.mulmod(A.pb.ctx_N.powmod(R["r"], e_bits), R["beta_r"])
        m_e = bn.take_limbs(m_limbs, 0, self.p_e.n_limbs)
        s1 = _int_mul_add(
            e, m_e, bn.take_limbs(R["alpha"], 0, self.p_s1.n_limbs), self.p_s1
        )
        s2 = _int_mul_add(
            e, R["rho"], bn.take_limbs(R["gamma"], 0, self.p_s2.n_limbs), self.p_s2
        )
        return {"s": s, "s1": s1, "s2": s2}

    def bob_check_alice(self, T, P, e) -> jnp.ndarray:
        """Batched Alice-proof verification → (B,) bool."""
        A, Bo = self.alice, self.bob
        q3 = jnp.broadcast_to(
            jnp.asarray(bn.to_limbs(self.dom.q3(), self.p_s1)), P["s1"].shape
        )
        ok = bn.compare(P["s1"], q3) <= 0
        e_bits = _bits_of(e, self.p_e, self.dom.scalar)
        n2 = A.pb.ctx_N2
        s1_modN = A.pb.ctx_N.reduce(
            bn.take_limbs(P["s1"], 0, 2 * A.pb.prof_n.n_limbs)
        )
        lhs = n2.mulmod(
            _enc_deterministic(A.pb, s1_modN),
            n2.powmod_const(
                bn.take_limbs(P["s"], 0, n2.prof.n_limbs), A.N
            ),
        )
        rhs = n2.mulmod(T["u"], n2.powmod(T["c_a"], e_bits))
        ok = ok & jnp.all(lhs == rhs, axis=-1)
        lhs2 = Bo.commit_ring(
            _bits_of(P["s1"], self.p_s1, self.p_s1.n_limbs * 11),
            _bits_of(P["s2"], self.p_s2, self.p_s2.n_limbs * 11),
        )
        rhs2 = Bo.ctx_nt.mulmod(T["w"], Bo.ctx_nt.powmod(T["z"], e_bits))
        return ok & jnp.all(lhs2 == rhs2, axis=-1)

    # -- Bob: homomorphic response + proof ----------------------------------

    def bob_respond(self, c_a, b_limbs, R, with_check: bool):
        """c_b = c_a^b · Enc_A(β′); pre-challenge proof transcript.
        ``b_limbs``: Bob's secret (< q) in the 11-bit e-profile.
        with_check adds U = α·G for the curve binding (computed by caller
        in the 12-bit curve family)."""
        A = self.alice
        b_bits = _bits_of(b_limbs, self.p_e, self.dom.scalar)
        c_b = A.pb.ctx_N2.mulmod(
            A.pb.ctx_N2.powmod(c_a, b_bits),
            A.pb.encrypt(
                bn.take_limbs(R["beta_prime"], 0, A.pb.prof_n.n_limbs), R["r"]
            ),
        )
        z = A.commit_ring(
            _bits_of(b_limbs, self.p_e, self.dom.scalar),
            _bits_of(R["rho"], self.p_rho, self.p_rho.n_limbs * 11),
        )
        z_p = A.commit_ring(
            _bits_of(R["alpha"], self.p_alpha, self.dom.alpha),
            _bits_of(R["rho_p"], self.p_s2, self.p_s2.n_limbs * 11),
        )
        t = A.commit_ring(
            _bits_of(R["beta_prime"], self.p_bp, self.dom.beta_prime),
            _bits_of(R["sigma"], self.p_rho, self.p_rho.n_limbs * 11),
        )
        v = A.pb.ctx_N2.mulmod(
            A.pb.ctx_N2.powmod(c_a, _bits_of(R["alpha"], self.p_alpha, self.dom.alpha)),
            A.pb.encrypt(
                bn.take_limbs(R["gamma"], 0, A.pb.prof_n.n_limbs), R["beta_r"]
            ),
        )
        w = A.commit_ring(
            _bits_of(R["gamma"], self.p_gb, self.dom.gamma_bob),
            _bits_of(R["tau"], self.p_s2, self.p_s2.n_limbs * 11),
        )
        return {"c_b": c_b, "z": z, "z_p": z_p, "t": t, "v": v, "w": w}

    def bob_challenge(self, c_a, T, extra_rows: Sequence[np.ndarray] = ()) -> np.ndarray:
        A = self.alice
        rows = [
            bn.limbs_to_bytes_le(c_a, A.pb.prof_n2, A.n2_bytes),
            bn.limbs_to_bytes_le(T["c_b"], A.pb.prof_n2, A.n2_bytes),
            bn.limbs_to_bytes_le(T["z"], A.prof_nt, A.nt_bytes),
            bn.limbs_to_bytes_le(T["z_p"], A.prof_nt, A.nt_bytes),
            bn.limbs_to_bytes_le(T["t"], A.prof_nt, A.nt_bytes),
            bn.limbs_to_bytes_le(T["v"], A.pb.prof_n2, A.n2_bytes),
            bn.limbs_to_bytes_le(T["w"], A.prof_nt, A.nt_bytes),
        ]
        rows.extend(extra_rows)
        return hash_rows(b"bob", *rows)

    def bob_finish(self, e, b_limbs, R):
        e_bits = _bits_of(e, self.p_e, self.dom.scalar)
        A = self.alice
        s = A.pb.ctx_N.mulmod(A.pb.ctx_N.powmod(R["r"], e_bits), R["beta_r"])
        s1 = _int_mul_add(
            e, bn.take_limbs(b_limbs, 0, self.p_e.n_limbs),
            bn.take_limbs(R["alpha"], 0, self.p_s1.n_limbs), self.p_s1,
        )
        s2 = _int_mul_add(
            e, R["rho"], bn.take_limbs(R["rho_p"], 0, self.p_s2.n_limbs), self.p_s2
        )
        t1 = _int_mul_add(
            e, bn.take_limbs(R["beta_prime"], 0, self.p_t1.n_limbs),
            bn.take_limbs(R["gamma"], 0, self.p_t1.n_limbs), self.p_t1,
        )
        t2 = _int_mul_add(
            e, R["sigma"], bn.take_limbs(R["tau"], 0, self.p_s2.n_limbs), self.p_s2
        )
        return {"s": s, "s1": s1, "s2": s2, "t1": t1, "t2": t2}

    def alice_check_bob(self, c_a, T, P, e) -> jnp.ndarray:
        """Batched Bob-proof verification (ciphertext + ring legs; the
        with-check curve leg is checked by the caller)."""
        A = self.alice
        q3 = jnp.broadcast_to(
            jnp.asarray(bn.to_limbs(self.dom.q3(), self.p_s1)), P["s1"].shape
        )
        ok = bn.compare(P["s1"], q3) <= 0
        # q⁷ bound; in shrunk test domains the profile capacity caps it
        # (honest t1 always fits the profile by construction)
        t1_cap = (1 << (self.p_t1.bits * self.p_t1.n_limbs)) - 1
        q7 = jnp.broadcast_to(
            jnp.asarray(bn.to_limbs(min(Q**7, t1_cap), self.p_t1)),
            P["t1"].shape,
        )
        ok = ok & (bn.compare(P["t1"], q7) <= 0)
        e_bits = _bits_of(e, self.p_e, self.dom.scalar)
        lhs = A.commit_ring(
            _bits_of(P["s1"], self.p_s1, self.p_s1.n_limbs * 11),
            _bits_of(P["s2"], self.p_s2, self.p_s2.n_limbs * 11),
        )
        rhs = A.ctx_nt.mulmod(T["z_p"], A.ctx_nt.powmod(T["z"], e_bits))
        ok = ok & jnp.all(lhs == rhs, axis=-1)
        lhs = A.commit_ring(
            _bits_of(P["t1"], self.p_t1, self.p_t1.n_limbs * 11),
            _bits_of(P["t2"], self.p_s2, self.p_s2.n_limbs * 11),
        )
        rhs = A.ctx_nt.mulmod(T["w"], A.ctx_nt.powmod(T["t"], e_bits))
        ok = ok & jnp.all(lhs == rhs, axis=-1)
        n2 = A.pb.ctx_N2
        t1_modN = A.pb.ctx_N.reduce(
            bn.take_limbs(P["t1"], 0, 2 * A.pb.prof_n.n_limbs)
        )
        lhs = n2.mulmod(
            n2.mulmod(
                n2.powmod(c_a, _bits_of(P["s1"], self.p_s1, self.p_s1.n_limbs * 11)),
                _enc_deterministic(A.pb, t1_modN),
            ),
            n2.powmod_const(bn.take_limbs(P["s"], 0, n2.prof.n_limbs), A.N),
        )
        rhs = n2.mulmod(T["v"], n2.powmod(T["c_b"], e_bits))
        return ok & jnp.all(lhs == rhs, axis=-1)

    def alice_decrypt_share(self, c_b) -> jnp.ndarray:
        """Dec_A(c_b) mod q → curve-scalar limbs (12-bit family)."""
        A = self.alice
        plain = A.pb.decrypt(A.pre.paillier, c_b)  # (B, n) mod N
        return _mod_q_from_limbs(plain, A.pb.prof_n)


# ---------------------------------------------------------------------------
# curve-side jitted helpers (12-bit family)
# ---------------------------------------------------------------------------


@jax.jit
def _scalar_from_wide_bytes(b: jnp.ndarray) -> jnp.ndarray:
    """(B, 40) uniform bytes → canonical scalar mod q (bias 2^-64)."""
    ring = sp.scalar_ring()
    return ring.reduce(bn.bytes_to_limbs_le(b, P256, 30))


@jax.jit
def _base_mul_compressed(k_limbs: jnp.ndarray):
    pt = sp.base_mul(bn.limbs_to_bits(k_limbs, P256, SCALAR_BITS))
    return pt, sp.compress(pt)


def _scalar_to_plain(pb: PaillierBatch, k_limbs: jnp.ndarray) -> jnp.ndarray:
    """curve scalar (12-bit limbs) → Paillier plaintext limbs (11-bit)."""
    b = bn.limbs_to_bytes_le(k_limbs, P256, 32)
    return bn.bytes_to_limbs_le(b, pb.prof_n, pb.prof_n.n_limbs)


def _scalar_to_prof(k_limbs: jnp.ndarray, prof: bn.LimbProfile) -> jnp.ndarray:
    b = bn.limbs_to_bytes_le(k_limbs, P256, 32)
    return bn.bytes_to_limbs_le(b, prof, prof.n_limbs)


def _mod_q_from_limbs(x: jnp.ndarray, prof: bn.LimbProfile) -> jnp.ndarray:
    """Reduce an arbitrary-width non-negative value mod q → 12-bit curve
    limbs, via chunked folding: v = Σ chunk_i · (2^(176·i)) mod q."""
    ring = sp.scalar_ring()
    n_bytes = -(-prof.n_limbs * prof.bits // 8)
    b = bn.limbs_to_bytes_le(x, prof, n_bytes)
    chunk_bytes = 22  # 176 bits per chunk < 2^253
    n_chunks = -(-n_bytes // chunk_bytes)
    pad = n_chunks * chunk_bytes - n_bytes
    if pad:
        b = jnp.pad(b, [(0, 0)] * (b.ndim - 1) + [(0, pad)])
    chunks = b.reshape(b.shape[:-1] + (n_chunks, chunk_bytes))
    acc = ring.const(0, x.shape[:-1])
    shift = pow(2, chunk_bytes * 8, Q)
    shift_l = ring.const(shift, x.shape[:-1])
    for i in range(n_chunks - 1, -1, -1):
        c = bn.bytes_to_limbs_le(chunks[..., i, :], P256, P256.n_limbs)
        acc = ring.addmod(ring.mulmod(acc, shift_l), ring.reduce(c))
    return acc


# ---------------------------------------------------------------------------
# two-party batched co-signing fabric (bench / loopback deployments)
# ---------------------------------------------------------------------------


class GG18BatchCoSigners:
    """Runs B concurrent 2-of-n GG18 signing sessions with both signers'
    round compute batched on device (the in-process measurement fabric —
    the distributed node runs the same kernels per party).

    ``party_shares[i]`` are signer i's per-wallet shares (same wallet order
    across parties, one quorum topology per batch — like
    eddsa_batch.BatchedCoSigners). Quorum size is fixed at 2 (the
    reference's default 2-of-3 deployment); wider quorums add directions
    pairwise.
    """

    def __init__(
        self,
        party_ids: Sequence[str],
        party_shares: Sequence[Sequence[KeygenShare]],
        preparams: Dict[str, PreParams],
        dom: Domains = Domains(),
        rng=secrets,
    ):
        assert len(party_ids) == 2, "fabric currently models the 2-signer quorum"
        self.ids = list(party_ids)
        self.B = len(party_shares[0])
        self.dom = dom
        self.rng = rng
        self.ring = sp.scalar_ring()

        first = party_shares[0][0]
        universe_xs = party_xs(first.participants)
        quorum_xs = [universe_xs[p] for p in party_ids]
        self.ctx = [PartyCtx(pid, preparams[pid]) for pid in party_ids]
        # both MtA directions
        self.mta = {
            (0, 1): MtaBatch(self.ctx[0], self.ctx[1], dom),
            (1, 0): MtaBatch(self.ctx[1], self.ctx[0], dom),
        }
        # additive shares w_i = λ_i·x_i mod q (λ shared across the batch)
        self.w = []
        self.W_pts = []
        for i, (pid, shares) in enumerate(zip(party_ids, party_shares)):
            lam = hm.lagrange_coeff(quorum_xs, universe_xs[pid], Q)
            w_ints = [lam * s.share % Q for s in shares]
            w_limbs = jnp.asarray(bn.batch_to_limbs(w_ints, P256))
            self.w.append(w_limbs)
            for s in shares:
                if s.key_type != "secp256k1":
                    raise ValueError("wrong key type")
                if s.self_x != universe_xs[pid]:
                    raise ValueError("party_shares misaligned with party_ids")
            W, _ = _base_mul_compressed(w_limbs)
            self.W_pts.append(W)
        # wallet public keys (host decompress once at setup)
        pubs = [hm.secp_decompress(s.public_key) for s in party_shares[0]]
        self.Y = sp.from_host(pubs)

    # -- small helpers -------------------------------------------------------

    def _rand_scalar(self) -> jnp.ndarray:
        return _scalar_from_wide_bytes(jnp.asarray(rand_bits(self.B, 320, self.rng)))

    def _commit(self, tag: bytes, *rows) -> Tuple[np.ndarray, np.ndarray]:
        blind = rand_bits(self.B, 256, self.rng)
        return hash_rows(tag, blind, *rows), blind

    # -- the protocol --------------------------------------------------------

    def sign(self, digests: np.ndarray) -> Dict[str, np.ndarray]:
        """``digests``: (B, 32) big-endian digests. Returns dict with
        r, s (B, 32 BE bytes), recovery (B,), ok mask (B,)."""
        B = self.B
        ring = self.ring
        # m = digest mod q  (big-endian → little for limb decode)
        m = ring.reduce(
            bn.bytes_to_limbs_le(jnp.asarray(digests[:, ::-1].copy()), P256, 22)
        )
        m_bits = bn.limbs_to_bits(m, P256, SCALAR_BITS)

        # ---- round 1: k, γ, Γ commitments + MtA inits ----------------------
        k = [self._rand_scalar() for _ in range(2)]
        gamma = [self._rand_scalar() for _ in range(2)]
        Gamma, Gamma_comp, g_commit, g_blind = [], [], [], []
        for i in range(2):
            pt, comp = _base_mul_compressed(gamma[i])
            Gamma.append(pt)
            Gamma_comp.append(np.asarray(comp))
            c, bl = self._commit(b"gamma", Gamma_comp[i])
            g_commit.append(c)
            g_blind.append(bl)

        mta_state = {}
        for (a, b), mta in self.mta.items():
            Ra = mta.alice_randoms(B, self.rng)
            k_plain = _scalar_to_plain(self.ctx[a].pb, k[a])
            T = mta.alice_init(k_plain, Ra)
            e = mta.e_limbs(mta.alice_challenge(T))
            P = mta.alice_finish(e, k_plain, Ra)
            mta_state[(a, b)] = {
                "Ra": Ra, "T": T, "e": e, "P": P, "k_plain": k_plain,
            }

        ok = jnp.ones((B,), bool)

        # ---- round 2: Bob verifies + responds (γ and w) --------------------
        for (a, b), mta in self.mta.items():
            st = mta_state[(a, b)]
            ok = ok & mta.bob_check_alice(st["T"], st["P"], st["e"])
            for name, secret in (("gamma", gamma[b]), ("w", self.w[b])):
                Rb = mta.bob_randoms(B, self.rng)
                b_e = _scalar_to_prof(secret, mta.p_e)
                Tb = mta.bob_respond(st["T"]["c_a"], b_e, Rb,
                                     with_check=(name == "w"))
                extra = ()
                U_pt = None
                if name == "w":
                    alpha_q = _mod_q_from_limbs(Rb["alpha"], mta.p_alpha)
                    U_pt, U_comp = _base_mul_compressed(alpha_q)
                    X_comp = sp.compress(self.W_pts[b])
                    extra = (np.asarray(U_comp), np.asarray(X_comp))
                e_b = mta.e_limbs(mta.bob_challenge(st["T"]["c_a"], Tb, extra))
                Pb = mta.bob_finish(e_b, b_e, Rb)
                st[name] = {"Rb": Rb, "Tb": Tb, "e": e_b, "Pb": Pb, "U": U_pt}

        # ---- round 3: Alice verifies + decrypts; δ_i, σ_i ------------------
        alpha_shares = {}   # (a,b,name) -> alice's additive share mod q
        beta_shares = {}    # (a,b,name) -> bob's additive share mod q
        for (a, b), mta in self.mta.items():
            st = mta_state[(a, b)]
            for name in ("gamma", "w"):
                sub = st[name]
                ok = ok & mta.alice_check_bob(
                    st["T"]["c_a"], sub["Tb"], sub["Pb"], sub["e"]
                )
                if name == "w":
                    # with-check: s1·G ?= U + e·W_b
                    s1_q = _mod_q_from_limbs(sub["Pb"]["s1"], mta.p_s1)
                    lhs = sp.base_mul(bn.limbs_to_bits(s1_q, P256, SCALAR_BITS))
                    e_q = _mod_q_from_limbs(sub["e"], mta.p_e)
                    rhs = sp.add(
                        sub["U"],
                        sp.scalar_mul(
                            bn.limbs_to_bits(e_q, P256, SCALAR_BITS),
                            self.W_pts[b],
                        ),
                    )
                    ok = ok & sp.equal(lhs, rhs)
                alpha_shares[(a, b, name)] = mta.alice_decrypt_share(
                    sub["Tb"]["c_b"]
                )
                beta_shares[(a, b, name)] = ring.negmod(
                    _mod_q_from_limbs(sub["Rb"]["beta_prime"], mta.p_bp)
                )

        delta_i, sigma_i = [], []
        for i in range(2):
            j = 1 - i
            d = ring.addmod(
                ring.mulmod(k[i], gamma[i]),
                ring.addmod(
                    alpha_shares[(i, j, "gamma")], beta_shares[(j, i, "gamma")]
                ),
            )
            s_ = ring.addmod(
                ring.mulmod(k[i], self.w[i]),
                ring.addmod(
                    alpha_shares[(i, j, "w")], beta_shares[(j, i, "w")]
                ),
            )
            delta_i.append(d)
            sigma_i.append(s_)

        # ---- round 4: δ reveal, Γ decommit + PoK, R ------------------------
        for i in range(2):
            again = hash_rows(b"gamma", g_blind[i], Gamma_comp[i])
            ok = ok & jnp.asarray((again == g_commit[i]).all(axis=1))
        delta = ring.addmod(delta_i[0], delta_i[1])
        nz = ~jnp.all(delta == 0, axis=-1)
        ok = ok & nz
        delta_inv = ring.powmod_const(delta, Q - 2)
        Gamma_sum = sp.add(Gamma[0], Gamma[1])
        R_pt = sp.scalar_mul(
            bn.limbs_to_bits(delta_inv, P256, SCALAR_BITS), Gamma_sum
        )
        Rx = sp.x_coordinate(R_pt)          # canonical field limbs
        r = ring.reduce(Rx)
        ok = ok & ~jnp.all(r == 0, axis=-1)
        # recovery metadata
        F = __import__("mpcium_tpu.core.fields", fromlist=["secp256k1_field"]).secp256k1_field()
        zi = F.inv(R_pt.Z)
        y_aff = F.canonical(F.mul(R_pt.Y, zi))
        n_limbs_ = jnp.broadcast_to(jnp.asarray(bn.to_limbs(Q, P256)), Rx.shape)
        rec = (y_aff[..., 0] & 1) | jnp.where(bn.compare(Rx, n_limbs_) >= 0, 2, 0)

        # Schnorr PoK of γ_i (batched prove + cross-verify)
        for i in range(2):
            k_pok = self._rand_scalar()
            _, A_comp = _base_mul_compressed(k_pok)
            e32 = hash_rows(b"schnorr", np.asarray(A_comp), Gamma_comp[i])
            e_pok = ring.reduce(
                bn.bytes_to_limbs_le(jnp.asarray(e32), P256, 22)
            )
            s_pok = ring.submod(k_pok, ring.mulmod(e_pok, gamma[i]))
            lhs = sp.add(
                sp.base_mul(bn.limbs_to_bits(s_pok, P256, SCALAR_BITS)),
                sp.scalar_mul(bn.limbs_to_bits(e_pok, P256, SCALAR_BITS), Gamma[i]),
            )
            ok = ok & jnp.asarray(
                (np.asarray(sp.compress(lhs)) == np.asarray(A_comp)).all(axis=1)
            )

        # ---- phase 5 -------------------------------------------------------
        s_i, l_i, rho5, V_i, A_i = [], [], [], [], []
        V_comp, A_comp5, va_commit, va_blind = [], [], [], []
        for i in range(2):
            si = ring.addmod(ring.mulmod(m, k[i]), ring.mulmod(r, sigma_i[i]))
            li = self._rand_scalar()
            ri = self._rand_scalar()
            Vi = sp.add(
                sp.scalar_mul(bn.limbs_to_bits(si, P256, SCALAR_BITS), R_pt),
                sp.base_mul(bn.limbs_to_bits(li, P256, SCALAR_BITS)),
            )
            Ai, Ai_comp = _base_mul_compressed(ri)
            s_i.append(si); l_i.append(li); rho5.append(ri)
            V_i.append(Vi); A_i.append(Ai)
            vc = np.asarray(sp.compress(Vi))
            V_comp.append(vc); A_comp5.append(np.asarray(Ai_comp))
            c, bl = self._commit(b"VA", vc, A_comp5[i])
            va_commit.append(c); va_blind.append(bl)

        # decommit + PedersenPoK of (s_i, l_i) in V_i = s_i·R + l_i·G
        for i in range(2):
            again = hash_rows(b"VA", va_blind[i], V_comp[i], A_comp5[i])
            ok = ok & jnp.asarray((again == va_commit[i]).all(axis=1))
            ka, kb = self._rand_scalar(), self._rand_scalar()
            Apok = sp.add(
                sp.scalar_mul(bn.limbs_to_bits(ka, P256, SCALAR_BITS), R_pt),
                sp.base_mul(bn.limbs_to_bits(kb, P256, SCALAR_BITS)),
            )
            Apok_comp = np.asarray(sp.compress(Apok))
            e32 = hash_rows(b"pedersen", Apok_comp, V_comp[i], A_comp5[i])
            e5 = ring.reduce(bn.bytes_to_limbs_le(jnp.asarray(e32), P256, 22))
            sa = ring.submod(ka, ring.mulmod(e5, s_i[i]))
            sb = ring.submod(kb, ring.mulmod(e5, l_i[i]))
            lhs = sp.add(
                sp.add(
                    sp.scalar_mul(bn.limbs_to_bits(sa, P256, SCALAR_BITS), R_pt),
                    sp.base_mul(bn.limbs_to_bits(sb, P256, SCALAR_BITS)),
                ),
                sp.scalar_mul(bn.limbs_to_bits(e5, P256, SCALAR_BITS), V_i[i]),
            )
            ok = ok & jnp.asarray(
                (np.asarray(sp.compress(lhs)) == Apok_comp).all(axis=1)
            )

        # V = ΣV_i - m·G - r·Y ;  U_i = ρ_i·V ;  T_i = l_i·A_sum
        V = sp.add(
            sp.add(V_i[0], V_i[1]),
            sp.add(
                sp.neg(sp.base_mul(m_bits)),
                sp.neg(sp.scalar_mul(bn.limbs_to_bits(r, P256, SCALAR_BITS), self.Y)),
            ),
        )
        A_sum = sp.add(A_i[0], A_i[1])
        U_pts, T_pts, ut_commit, ut_blind, U_comp, T_comp = [], [], [], [], [], []
        for i in range(2):
            Ui = sp.scalar_mul(bn.limbs_to_bits(rho5[i], P256, SCALAR_BITS), V)
            Ti = sp.scalar_mul(bn.limbs_to_bits(l_i[i], P256, SCALAR_BITS), A_sum)
            U_pts.append(Ui); T_pts.append(Ti)
            uc, tc = np.asarray(sp.compress(Ui)), np.asarray(sp.compress(Ti))
            U_comp.append(uc); T_comp.append(tc)
            c, bl = self._commit(b"UT", uc, tc)
            ut_commit.append(c); ut_blind.append(bl)
        for i in range(2):
            again = hash_rows(b"UT", ut_blind[i], U_comp[i], T_comp[i])
            ok = ok & jnp.asarray((again == ut_commit[i]).all(axis=1))
        ok = ok & sp.equal(
            sp.add(U_pts[0], U_pts[1]), sp.add(T_pts[0], T_pts[1])
        )

        # ---- reveal s_i, combine, normalize, verify ------------------------
        s = ring.addmod(s_i[0], s_i[1])
        ok = ok & ~jnp.all(s == 0, axis=-1)
        half = jnp.broadcast_to(jnp.asarray(bn.to_limbs(Q // 2, P256)), s.shape)
        high = bn.compare(s, half) > 0
        s = jnp.where(high[..., None], ring.negmod(s), s)
        rec = jnp.where(high, rec ^ 1, rec)

        # batched ECDSA verification: x(u1·G + u2·Y) mod q == r
        s_inv = ring.powmod_const(s, Q - 2)
        u1 = ring.mulmod(m, s_inv)
        u2 = ring.mulmod(r, s_inv)
        Rv = sp.add(
            sp.base_mul(bn.limbs_to_bits(u1, P256, SCALAR_BITS)),
            sp.scalar_mul(bn.limbs_to_bits(u2, P256, SCALAR_BITS), self.Y),
        )
        ok = ok & jnp.all(ring.reduce(sp.x_coordinate(Rv)) == r, axis=-1)

        return {
            "r": np.asarray(bn.limbs_to_bytes_le(r, P256, 32))[:, ::-1].copy(),
            "s": np.asarray(bn.limbs_to_bytes_le(s, P256, 32))[:, ::-1].copy(),
            "recovery": np.asarray(rec),
            "ok": np.asarray(ok),
        }


def dealer_keygen_secp_batch(
    n_wallets: int,
    party_ids: Sequence[str],
    threshold: int,
    rng=secrets,
) -> List[List[KeygenShare]]:
    """Trusted-dealer batch keygen for tests/bench setup ONLY — production
    wallets come from protocol.ecdsa.keygen. result[i] belongs to
    party_ids[i], wallet order aligned."""
    xs = party_xs(party_ids)
    out: List[List[KeygenShare]] = [[] for _ in party_ids]
    for _ in range(n_wallets):
        secret = rng.randbelow(Q - 1) + 1
        _, shares = hm.shamir_share(
            secret, threshold, [xs[p] for p in party_ids], Q, rng=rng
        )
        pub = hm.secp_compress(hm.secp_mul(secret, hm.SECP_G))
        for i, pid in enumerate(party_ids):
            out[i].append(
                KeygenShare(
                    key_type="secp256k1",
                    share=shares[xs[pid]],
                    self_x=xs[pid],
                    public_key=pub,
                    participants=sorted(party_ids),
                    threshold=threshold,
                )
            )
    return out
