"""Identifiable-abort vocabulary shared by the batched engines and the
scheduler (ISSUE 16).

A batched cohort fails *attributably*: when a protocol check (OT-MtA
KOS correlation, Gilboa encoding, MtA output consistency — see
protocol.ecdsa.mta_ot) catches deviation, the engine raises
:class:`CohortAbort` naming every (lane, party, check) it can blame
instead of silently zeroing the lane's ok bit. The scheduler catches it,
quarantines exactly the culprit sessions (retryable, culprit-named ABORT
events) and re-packs the survivors onto the next bucket
(consumers.batch_scheduler._absorb_cohort_abort) — one cheater never
poisons a 4096-session batch.

Pure stdlib on purpose: the scheduler and its unit tests import this
without touching jax.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

Culprit = Tuple[int, str, str]  # (batch lane, party id, check name)


class CohortAbort(RuntimeError):
    """An attributable check failed inside a batched cohort.

    ``culprits`` lists every blamed (lane, party_id, check_name); a lane
    appears at most once (the engine keeps the first — most upstream —
    check that caught it). Lanes not listed are honest-so-far survivors:
    their inputs were consumed by the aborted batch, so the caller must
    re-run them (the scheduler re-packs them bucket-snapped).
    """

    def __init__(self, culprits: Sequence[Culprit], engine: str = "gg18.sign"):
        self.culprits: List[Culprit] = [
            (int(lane), str(pid), str(check)) for lane, pid, check in culprits
        ]
        self.engine = engine
        detail = "; ".join(
            f"lane {lane}: party {pid} failed check '{check}'"
            for lane, pid, check in self.culprits
        )
        super().__init__(f"cohort abort ({engine}): {detail}")

    def lanes(self) -> List[int]:
        """Sorted culprit lane indices."""
        return sorted({lane for lane, _pid, _check in self.culprits})
