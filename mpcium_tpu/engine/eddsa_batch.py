"""Batched threshold-Ed25519 signing: the TPU execution engine.

This is the framework's replacement for the reference's per-session
goroutine concurrency (SURVEY.md §2.2 dimension 2 → the session batch
axis): each MPC party coalesces the round compute of B concurrent signing
sessions into single fixed-shape XLA dispatches. The protocol is the same
commit–reveal threshold Schnorr as ``protocol.eddsa.signing`` (3 rounds,
matching reference pkg/mpc/eddsa_rounds.go:23-25); here the per-round math
runs on device over ``(B, …)`` tensors, and since the device hash suite
(ops.hash_suite) the hashing does too: commitments batch through the
SHA-256 kernel and the RFC 8032 challenge through the 64-bit-lane
SHA-512 kernel, so the round tensors never round-trip through the host
(MPCIUM_EDDSA_DEVICE_HASH=0 restores the native/hashlib path).

Wire format for batched rounds is *byte tensors*, not JSON: a party's
round-1 message is the (B, 32) array of compressed nonce commitments, etc.
Device-side pack/unpack (`bignum.bytes_to_limbs_le`) keeps the host out of
the hot loop.

Every public function is shape-stable: jit caches one executable per batch
size. Use powers of two (pad the tail of a partial batch with dummy
sessions; the `ok` masks make padding harmless).
"""
from __future__ import annotations

import hashlib
import os
import secrets
from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import bignum as bn
from ..core import ed25519_jax as ed
from ..core import hostmath as hm
from ..core.bignum import P256 as PROF
from ..ops import hash_suite as hs
from ..perf import compile_watch
from ..utils import tracing


def _trace_sync(tensors) -> None:
    """Phase-boundary sync for mpctrace phase timers — reached only when
    tracing is armed (untraced runs never sync here)."""
    jax.block_until_ready(tensors)  # mpcflow: host-ok — trace instrumentation, only when tracing is armed

# 512-bit inputs (hash outputs / wide nonces) occupy 43 twelve-bit limbs —
# within BarrettCtx.reduce's 2n = 44-limb bound.
_WIDE_LIMBS = 43

# Session-axis sharding (engine/sharded.py arms this): when a mesh is
# armed, every batch tensor entering the engine is placed with its
# leading (session) axis partitioned over the local devices, and GSPMD
# partitions every downstream dispatch — a multi-device host then runs
# each party-round across all its chips with no kernel changes
# (SURVEY.md §2.2 dimension 2). None ⇒ plain single-device placement.
_SESSION_SHARDING = None


def arm_session_sharding(sharding) -> None:
    """Install (or clear, with None) the NamedSharding applied by
    :func:`to_dev`. Called by engine.sharded.arm_session_axis()."""
    global _SESSION_SHARDING
    _SESSION_SHARDING = sharding


def to_dev(x, axis: int = 0) -> jnp.ndarray:
    """Engine ingress: jnp.asarray plus the armed session sharding on
    ``axis`` — callers MUST name the axis that is the session batch
    (round tensors like (q, B, 32) are party-leading: sharding axis 0
    there would partition the committee, forcing cross-device gathers in
    the aggregations). Axes that don't divide the mesh fall back to
    default placement rather than failing the dispatch."""
    arr = jnp.asarray(x)
    s = _SESSION_SHARDING
    if s is None or arr.ndim <= axis:
        return arr
    n = s.mesh.devices.size
    if arr.shape[axis] % n != 0:
        return arr
    if axis == 0:
        return jax.device_put(arr, s)
    from jax.sharding import NamedSharding, PartitionSpec

    spec = PartitionSpec(*([None] * axis + list(s.spec)))
    return jax.device_put(arr, NamedSharding(s.mesh, spec))


def _reduce_wide(b64: jnp.ndarray) -> jnp.ndarray:
    """(…, 64) uint8 little-endian → canonical scalar limbs mod l."""
    L = ed.scalar_ring()
    return L.reduce(bn.bytes_to_limbs_le(b64, PROF, _WIDE_LIMBS))


# ---------------------------------------------------------------------------
# jitted round kernels (party-local, batched over sessions)
# ---------------------------------------------------------------------------


@jax.jit
def nonce_commitments(r64: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Round 1 compute. ``r64``: (..., 64) uint8 of fresh CSPRNG bytes.

    Returns (r_limbs mod l, compressed R_i = r·B as (..., 32) uint8).
    The 512→252-bit reduction makes the nonce statistically uniform mod l
    (RFC 8032's own wide-reduction trick).
    """
    r = _reduce_wide(r64)
    R = ed.base_mul(bn.limbs_to_bits(r, PROF, ed.SCALAR_BITS))
    return r, ed.compress(R)


@jax.jit
def aggregate_nonce(R_all: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(q, B, 32) compressed nonce shares → ((B, 32) compressed R = Σ R_i,
    (B,) validity mask). Decompression + point adds on device."""
    pts, ok = ed.decompress(R_all)
    acc = ed.EdPointJ(pts.X[0], pts.Y[0], pts.Z[0], pts.T[0])
    for i in range(1, R_all.shape[0]):
        acc = ed.add(acc, ed.EdPointJ(pts.X[i], pts.Y[i], pts.Z[i], pts.T[i]))
    return ed.compress(acc), jnp.all(ok, axis=0)


@jax.jit
def partial_signature(
    r_limbs: jnp.ndarray, c64: jnp.ndarray, lamx_limbs: jnp.ndarray
) -> jnp.ndarray:
    """Round 3 compute: s_i = r + H(R‖A‖M)·λ_i·x_i (mod l), batched.

    ``c64``: raw SHA-512 digests (B, 64); ``lamx_limbs``: λ_i·x_i mod l as
    limbs (λ from the keygen-universe x-coords; see protocol.eddsa.signing).
    """
    L = ed.scalar_ring()
    c = _reduce_wide(c64)
    return L.addmod(r_limbs, L.mulmod(c, lamx_limbs))


@jax.jit
def combine_signatures(
    s_parts: jnp.ndarray, R_comp: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(q, B, 22) partial-sig limbs + (B, 32) R → ((B, 64) signatures,
    (B, 22) s limbs). Signature layout per RFC 8032: R ‖ s little-endian."""
    L = ed.scalar_ring()
    s = s_parts[0]
    for i in range(1, s_parts.shape[0]):
        s = L.addmod(s, s_parts[i])
    s_bytes = bn.limbs_to_bytes_le(s, PROF, 32)
    return jnp.concatenate([R_comp, s_bytes], axis=-1), s


@jax.jit
def verify_signatures(
    sig: jnp.ndarray, A_comp: jnp.ndarray, c64: jnp.ndarray
) -> jnp.ndarray:
    """Batched RFC 8032 verification given precomputed challenge hashes:
    s·B == R + c·A. Returns (B,) bool. (The challenge c64 = SHA512(R‖A‖M)
    is hashed host-side; everything else runs on device.)"""
    L = ed.scalar_ring()
    R_pt, okR = ed.decompress(sig[..., :32])
    A_pt, okA = ed.decompress(A_comp)
    s = bn.bytes_to_limbs_le(sig[..., 32:], PROF, PROF.n_limbs)
    l_l = jnp.broadcast_to(jnp.asarray(bn.to_limbs(hm.ED_L, PROF)), s.shape)
    ok_range = bn.compare(s, l_l) < 0
    c = _reduce_wide(c64)
    lhs = ed.base_mul(bn.limbs_to_bits(s, PROF, ed.SCALAR_BITS))
    rhs = ed.add(R_pt, ed.scalar_mul(bn.limbs_to_bits(c, PROF, ed.SCALAR_BITS), A_pt))
    return ed.equal(lhs, rhs) & okR & okA & ok_range


@jax.jit
def fused_sign_step(
    r64: jnp.ndarray, c64: jnp.ndarray, lamx_limbs: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The whole device side of one batched signing step in ONE dispatch:
    nonce scalars + commitments, nonce aggregation, partial signatures,
    combine. ``r64`` (q, B, 64); ``c64`` (B, 64) challenge hashes;
    ``lamx_limbs`` (q, B, 22). Returns ((B, 64) signatures, (B,) R-valid).

    This is the single-chip flagship step (__graft_entry__.entry): in the
    two-phase production flow the challenge is hashed between nonce
    aggregation and partials, but the fused form is what one party executes
    when replaying a round pipeline whose hashes are already known.
    """
    q = r64.shape[0]
    r, R_comp = nonce_commitments(r64)
    R_sum, ok_R = aggregate_nonce(R_comp)
    parts = partial_signature(r, jnp.broadcast_to(c64, (q,) + c64.shape), lamx_limbs)
    sigs, _ = combine_signatures(parts, R_sum)
    return sigs, ok_R


# ---------------------------------------------------------------------------
# donated round steps (counter-phase cohort pipeline, engine/pipeline.py)
# ---------------------------------------------------------------------------
#
# Per-round session state is an explicit carried pytree and every step
# DONATES its input state (donate_argnums=(0,)): XLA reuses or frees the
# previous round's buffers instead of keeping both rounds live, which is
# the HBM headroom that makes B=16384 viable (engine/buckets.py). The
# donation contract for callers: rebind, never re-read — ``st =
# round_step_x(st)``; mpcshape rule MPS906 flags any read of a donated
# binding after the call site. Chaining step-to-step keeps the state on
# device with its ingress sharding (to_dev's session axis), so cohort
# handoffs never reshard.


@partial(jax.jit, donate_argnums=(0,))
def round_step_nonce(st, pref):
    """R1 as one donated step: ``{r64 (q,B,64), blinds (q,B,32)}`` →
    ``{r, R_comp, commit_msg, commits}``. Same kernel composition as the
    unpipelined path (nonce_commitments + device SHA-256 commitments) —
    bit-identical outputs, one dispatch."""
    r, R_comp = nonce_commitments(st["r64"])
    q, B = R_comp.shape[0], R_comp.shape[1]
    commit_msg = jnp.concatenate(
        [jnp.broadcast_to(pref, (q, B) + pref.shape), st["blinds"], R_comp],
        axis=-1,
    )
    return {
        "r": r,
        "R_comp": R_comp,
        "commit_msg": commit_msg,
        "commits": hs.sha256(commit_msg),
    }


@partial(jax.jit, donate_argnums=(0,))
def round_step_aggregate(st):
    """R2 as one donated step: re-hash the received commitment tensors
    (one fraud verdict for the batch) and aggregate the nonce points."""
    again = hs.sha256(st["commit_msg"])
    R_sum, ok_R = aggregate_nonce(st["R_comp"])
    return {
        "r": st["r"],
        "R_sum": R_sum,
        "ok_R": ok_R,
        "fraud_free": jnp.all(again == st["commits"]),
    }


@partial(jax.jit, donate_argnums=(0,))
def round_step_partial(st, c64, lamx):
    """R3 as one donated step: partial signatures + combine."""
    q = st["r"].shape[0]
    parts = partial_signature(
        st["r"], jnp.broadcast_to(c64, (q,) + c64.shape), lamx
    )
    sigs, _ = combine_signatures(parts, st["R_sum"])
    return {"sigs": sigs, "ok_R": st["ok_R"], "R_sum": st["R_sum"]}


# ---------------------------------------------------------------------------
# host helpers
# ---------------------------------------------------------------------------


def device_hash_enabled() -> bool:
    """MPCIUM_EDDSA_DEVICE_HASH gates the device hash path (default ON):
    commitments and the RFC 8032 challenge hash through ops.hash_suite's
    SHA-256/SHA-512 kernels where the round tensors already live. Set to
    0 to restore the native C++ / hashlib host path (which stays the
    reference oracle — all paths are byte-identical)."""
    return os.environ.get("MPCIUM_EDDSA_DEVICE_HASH", "1") != "0"


def challenge_device(R_comp, A_comp, M) -> jnp.ndarray:
    """Device challenge hashes: SHA-512(R ‖ A ‖ M) over (B, 32)/(B, 32)/
    (B, L) uint8 rows (device or host) → (B, 64) device digests, one
    fused dispatch through the 64-bit-lane kernel. The batch engine calls
    this directly so c64 never leaves the device."""
    msg = jnp.concatenate(
        [jnp.asarray(R_comp), jnp.asarray(A_comp), jnp.asarray(M)], axis=-1
    )
    return hs.sha512(msg)


def challenge_hashes(
    R_comp: np.ndarray, A_comp: np.ndarray, messages: Sequence[bytes]
) -> np.ndarray:
    """Per-session SHA-512(R ‖ A ‖ M) → (B, 64) uint8.

    Equal-length messages (the common case: 32-byte tx digests) hash on
    device as ONE fused dispatch (:func:`challenge_device`);
    MPCIUM_EDDSA_DEVICE_HASH=0 falls back to the native C++ batch call
    and ragged batches fall back to per-row hashlib. All three paths are
    byte-identical (tests/test_hash_suite.py, tests/test_eddsa_batch.py).
    """
    from .. import native

    lens = {len(m) for m in messages}
    if len(lens) == 1:
        M = np.frombuffer(b"".join(messages), dtype=np.uint8).reshape(
            len(messages), lens.pop()
        )
        if device_hash_enabled():
            return np.asarray(challenge_device(R_comp, A_comp, M))  # mpcflow: host-ok — host-facing helper egress; the batch engine uses challenge_device and keeps c64 on device
        return native.batch_sha512(
            b"",
            np.concatenate(
                [np.asarray(R_comp), np.asarray(A_comp), M], axis=1  # mpcflow: host-ok — MPCIUM_EDDSA_DEVICE_HASH=0 fallback: the native batch hasher reads host rows
            ),
        )
    R = np.asarray(R_comp)  # mpcflow: host-ok — ragged-message fallback: per-row hashlib reads host bytes
    A = np.asarray(A_comp)  # mpcflow: host-ok — ragged-message fallback: per-row hashlib reads host bytes
    out = np.empty((len(messages), 64), dtype=np.uint8)
    for i, m in enumerate(messages):
        out[i] = np.frombuffer(
            hashlib.sha512(R[i].tobytes() + A[i].tobytes() + m).digest(),
            dtype=np.uint8,
        )
    return out


def fresh_nonce_bytes(batch: int, rng=secrets) -> np.ndarray:
    """(B, 64) CSPRNG bytes for round 1."""
    return np.frombuffer(rng.token_bytes(batch * 64), dtype=np.uint8).reshape(
        batch, 64
    )


def scalars_to_limb_batch(xs: Sequence[int]) -> np.ndarray:
    """Host scalars (already reduced mod l) → (B, 22) int32."""
    return bn.batch_to_limbs([x % hm.ED_L for x in xs], PROF)


# ---------------------------------------------------------------------------
# in-process co-signing fabric (bench / tests / loopback deployments)
# ---------------------------------------------------------------------------


class BatchedCoSigners:
    """Drives q parties × B sessions of the 3-round signing protocol with
    batched device compute per party per round — the measurement harness for
    the throughput north star (SURVEY.md §6) and the reference
    implementation for the distributed node's batched rounds.

    ``party_shares``: for each of the q quorum parties, that party's
    per-session key shares (length B, same wallet order). All sessions must
    share one quorum topology (same party ids / x-coords); mixed topologies
    belong in separate batches (the engine buckets by topology).
    """

    def __init__(
        self,
        party_ids: Sequence[str],
        party_shares: Sequence[Sequence["KeygenShare"]],  # noqa: F821
        rng=secrets,
    ):
        from ..protocol.base import party_xs

        assert len(party_ids) == len(party_shares) >= 2
        self.party_ids = list(party_ids)
        self.q = len(party_ids)
        self.B = len(party_shares[0])
        assert all(len(s) == self.B for s in party_shares)
        self.rng = rng

        first = party_shares[0][0]
        if self.q < first.threshold + 1:
            raise ValueError("not enough participants for threshold")
        universe_xs = party_xs(first.participants)
        quorum_xs = [universe_xs[p] for p in party_ids]
        # λ_i·x_i per (party, session): λ depends only on the quorum
        # topology, shared across the batch
        self.lamx = np.empty((self.q, self.B, PROF.n_limbs), dtype=np.int32)
        for pi, (pid, shares) in enumerate(zip(party_ids, party_shares)):
            lam = hm.lagrange_coeff(quorum_xs, universe_xs[pid], hm.ED_L)
            self.lamx[pi] = scalars_to_limb_batch(
                [lam * s.share % hm.ED_L for s in shares]
            )
            for s in shares:
                if s.key_type != "ed25519":
                    raise ValueError("wrong key type")
                if s.participants != first.participants:
                    raise ValueError(
                        f"share for {pid!r} from a different participant "
                        f"universe — bucket sessions by topology"
                    )
                if s.threshold != first.threshold:
                    raise ValueError("mixed thresholds in one batch")
                if s.self_x != universe_xs[pid]:
                    raise ValueError(
                        f"share self_x {s.self_x} does not belong to "
                        f"{pid!r} (expected {universe_xs[pid]}) — "
                        f"party_shares misaligned with party_ids"
                    )
        self.A_comp = np.stack(
            [
                np.frombuffer(s.public_key, dtype=np.uint8)
                for s in party_shares[0]
            ]
        )
        self._A_dev = jnp.asarray(self.A_comp)  # uploaded once, reused every batch

    def sign(
        self, messages: Sequence[bytes], cohorts: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Run the full 3-round protocol for B sessions → ((B, 64)
        signatures, (B,) ok mask). Raises on commitment fraud.

        The batch executes as K counter-phase cohorts (engine/pipeline;
        ``cohorts=`` overrides MPCIUM_PIPELINE_COHORTS): each cohort's
        donated round steps dispatch asynchronously while another
        cohort's host stage (fraud verdict, signature egress) drains on
        the pipeline worker. ALL nonce/blind bytes are drawn for the
        full batch, in K=1 serial order, before the split — signatures
        are bit-identical for every K (tests/test_pipeline.py). The
        hashlib/native fallback paths (MPCIUM_EDDSA_DEVICE_HASH=0,
        ragged messages) stay serial.

        With mpctrace armed, device-phase spans (``phase:*``) are emitted
        with a sync at each phase boundary; untraced runs take the no-op
        path — no syncs, bit-identical results."""
        assert len(messages) == self.B
        q, B = self.q, self.B
        # mpcshape: unbounded-ok — B is pow-2 snapped upstream (scheduler chunks via engine/buckets.floor_bucket; bench via bucket_b)
        _cw = compile_watch.begin("eddsa.sign", f"B{B}|q{q}")

        # ALL secret randomness precedes the cohort split (transcript
        # discipline: the rng stream is identical for every K)
        r64 = np.stack([fresh_nonce_bytes(B, self.rng) for _ in range(q)])
        blinds = np.stack([
            np.frombuffer(self.rng.token_bytes(B * 32), dtype=np.uint8)
            .reshape(B, 32) for _ in range(q)
        ])

        use_dev_hash = device_hash_enabled()
        lens = {len(m) for m in messages}
        if not use_dev_hash or len(lens) != 1:
            out = self._sign_fallback(messages, r64, blinds, use_dev_hash)
            compile_watch.finish(_cw)
            return out

        from . import pipeline as pl

        plan = pl.CohortPlan.for_batch(B, cohorts)
        Mrows = np.frombuffer(b"".join(messages), np.uint8).reshape(
            B, lens.pop()
        )
        pref = jnp.asarray(
            np.frombuffer(b"mpcium-tpu/eddsa-commit", np.uint8)
        )

        def job(ci: int, sl: slice):
            def run():
                _pt = tracing.PhaseTimer(
                    "eddsa.sign", _trace_sync, node="engine",
                    tid=f"eddsa:B{B}" if plan.serial
                    else f"eddsa:B{B}:c{ci}",
                )
                # donated round-step chain: st stays on device with its
                # ingress sharding; rebind-only (MPS906)
                st = {
                    "r64": to_dev(r64[:, sl], axis=1),
                    "blinds": to_dev(blinds[:, sl], axis=1),
                }
                st = round_step_nonce(st, pref)
                _pt.mark("r1_nonce_commit", st["commits"])
                st = round_step_aggregate(st)
                _pt.mark("r2_decommit_aggregate", st["R_sum"])
                fraud_free = yield (
                    "fraud_verdict",
                    lambda: bool(np.asarray(st["fraud_free"])),  # mpcflow: host-ok — commitment-fraud verdict egress (one bool)
                )
                if not fraud_free:
                    raise RuntimeError("commitment fraud detected")
                A_c = self._A_dev[sl]
                c64 = challenge_device(st["R_sum"], A_c, to_dev(Mrows[sl]))
                st = round_step_partial(
                    st, c64, to_dev(self.lamx[:, sl], axis=1)
                )
                _pt.mark("r3_challenge_partials_combine", st["sigs"])
                # local verification before publishing (reference
                # eddsa_signing_session.go:147)
                ok = verify_signatures(st["sigs"], A_c, c64) & st["ok_R"]
                _pt.mark("verify", ok)
                sigs = st["sigs"]
                out = yield (
                    "sig_egress",
                    lambda: (np.asarray(sigs), np.asarray(ok)),  # mpcflow: host-ok — signature egress: final (R,s) + verdicts leave device for callers
                )
                return out

            return run

        parts = pl.run_counter_phase(
            [job(ci, sl) for ci, sl in enumerate(plan.slices())]
        )
        out = (
            pl.merge_rows([p[0] for p in parts]),
            pl.merge_rows([p[1] for p in parts]),
        )
        compile_watch.finish(_cw)
        return out

    def _sign_fallback(
        self,
        messages: Sequence[bytes],
        r64: np.ndarray,
        blinds: np.ndarray,
        use_dev_hash: bool,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """The serial (K=1) path for the native/hashlib fallbacks:
        MPCIUM_EDDSA_DEVICE_HASH=0 and ragged message batches. Same
        rounds, host hashing, no cohort split."""
        q, B = self.q, self.B
        _pt = tracing.PhaseTimer(
            "eddsa.sign", _trace_sync, node="engine", tid=f"eddsa:B{B}",
        )

        # -- round 1: nonce commitments (one (q, B) dispatch) + batch
        # commitments (device SHA-256 over the (q, B) rows where R
        # already lives; MPCIUM_EDDSA_DEVICE_HASH=0 restores the native
        # C++ per-party calls) ------------------------------------------------
        from .. import native

        r_limbs, R_comp = nonce_commitments(jnp.asarray(r64))  # (q,B,22)/(q,B,32)
        if use_dev_hash:
            pref = jnp.asarray(
                np.frombuffer(b"mpcium-tpu/eddsa-commit", np.uint8)
            )
            commit_msg = jnp.concatenate(
                [
                    jnp.broadcast_to(pref, (q, B) + pref.shape),
                    jnp.asarray(blinds),
                    R_comp,
                ],
                axis=-1,
            )
            commits = hs.sha256(commit_msg)
        else:
            R_host = np.asarray(R_comp)  # mpcflow: host-ok — MPCIUM_EDDSA_DEVICE_HASH=0 fallback: native hasher reads host rows; the default device path keeps R on device
            commits = [
                native.batch_sha256(
                    b"mpcium-tpu/eddsa-commit",
                    np.concatenate([blinds[p], R_host[p]], axis=1),
                )
                for p in range(q)
            ]
        _pt.mark("r1_nonce_commit", commits)

        # -- round 2: decommit + verify (re-hash the received tensors,
        # one fraud verdict; device aggregate) --------------------------------
        if use_dev_hash:
            again = hs.sha256(commit_msg)
            fraud_free = np.asarray(jnp.all(again == commits))  # mpcflow: host-ok — commitment-fraud verdict egress (one bool)
            if not fraud_free:
                raise RuntimeError("commitment fraud detected")
            R_sum, ok_R = aggregate_nonce(R_comp)
        else:
            for p in range(q):
                again = native.batch_sha256(
                    b"mpcium-tpu/eddsa-commit",
                    np.concatenate([blinds[p], R_host[p]], axis=1),
                )
                if not (again == commits[p]).all():
                    raise RuntimeError("commitment fraud detected")
            R_sum, ok_R = aggregate_nonce(jnp.asarray(R_host))
        _pt.mark("r2_decommit_aggregate", R_sum)

        # -- round 3: challenge (device SHA-512, fused; ragged messages
        # fall back to the host hasher) + partials (one (q, B) dispatch)
        lens = {len(m) for m in messages}
        if use_dev_hash and len(lens) == 1:
            Mrows = np.frombuffer(b"".join(messages), np.uint8).reshape(
                B, lens.pop()
            )
            c64 = challenge_device(R_sum, self._A_dev, Mrows)
        else:
            c64 = jnp.asarray(
                challenge_hashes(
                    np.asarray(R_sum), self.A_comp, messages  # mpcflow: host-ok — ragged-message fallback: per-row hashlib reads host bytes; the equal-length default stays on device
                )
            )
        parts = partial_signature(
            r_limbs,
            jnp.broadcast_to(c64, (q,) + c64.shape),
            jnp.asarray(self.lamx),
        )
        sigs, _ = combine_signatures(parts, R_sum)
        _pt.mark("r3_challenge_partials_combine", sigs)

        # -- local verification before publishing (reference
        # eddsa_signing_session.go:147) --------------------------------------
        ok = verify_signatures(sigs, self._A_dev, c64)
        _pt.mark("verify", ok)
        return (
            np.asarray(sigs),  # mpcflow: host-ok — signature egress: final (R,s) leave device for callers
            np.asarray(ok & ok_R),  # mpcflow: host-ok — per-wallet verification verdicts, egress with the signatures
        )


def dealer_keygen_batch(
    n_wallets: int,
    party_ids: Sequence[str],
    threshold: int,
    rng=secrets,
):
    """Trusted-dealer batch keygen for tests/bench setup ONLY — production
    wallets come from the DKG protocol (protocol.eddsa.keygen). Returns
    per-party lists of KeygenShare: result[i] belongs to party_ids[i],
    wallet order aligned across parties."""
    from ..protocol.base import KeygenShare, party_xs

    xs = party_xs(party_ids)
    out = [[] for _ in party_ids]
    for _ in range(n_wallets):
        secret = rng.randbelow(hm.ED_L - 1) + 1
        _, shares = hm.shamir_share(
            secret, threshold, [xs[p] for p in party_ids], hm.ED_L, rng=rng
        )
        pub = hm.ed_compress(hm.ed_mul(secret, hm.ED_B))
        for i, pid in enumerate(party_ids):
            out[i].append(
                KeygenShare(
                    key_type="ed25519",
                    share=shares[xs[pid]],
                    self_x=xs[pid],
                    public_key=pub,
                    participants=sorted(party_ids),
                    threshold=threshold,
                )
            )
    return out
