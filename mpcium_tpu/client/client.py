"""Client SDK (reference pkg/client — the external initiator process).

`MPCClient`: signs commands with the initiator Ed25519 key and publishes
them to the cluster; consumes result queues for callbacks (client.go:28-37):

  create_wallet     → ``mpc:generate``           (ephemeral fan-out)
  sign_transaction  → durable signing queue      (at-least-once)
  resharing         → ``mpc:reshare``
  on_wallet_creation_result / on_sign_result / on_resharing_result
"""
from __future__ import annotations

import json
from typing import Callable, Optional

from .. import wire
from ..identity.identity import InitiatorKey
from ..transport.api import Transport
from ..utils import log


def _result_topic(base: str, scope_id: Optional[str]) -> str:
    """Result topics are per-wallet/per-tx (``base.{id}``); a scoped
    subscription sees only its own result, the wildcard sees all."""
    return f"{base}.{scope_id}" if scope_id is not None else f"{base}.*"


class MPCClient:
    def __init__(self, transport: Transport, initiator: InitiatorKey):
        self.transport = transport
        self.initiator = initiator

    # -- commands -----------------------------------------------------------

    def create_wallet(self, wallet_id: str) -> None:
        msg = wire.GenerateKeyMessage(wallet_id=wallet_id)
        msg.signature = self.initiator.sign(msg.raw())
        self.transport.pubsub.publish(
            wire.TOPIC_GENERATE, wire.canonical_json(msg.to_json())
        )
        log.info("wallet creation requested", wallet=wallet_id)

    def sign_transaction(self, msg: wire.SignTxMessage) -> None:
        msg.signature = self.initiator.sign(msg.raw())
        self.transport.queues.enqueue(
            wire.TOPIC_SIGNING_REQUEST,
            wire.canonical_json(msg.to_json()),
            idempotency_key=msg.tx_id,
        )
        log.info("signing requested", wallet=msg.wallet_id, tx=msg.tx_id)

    def resharing(self, wallet_id: str, new_threshold: int, key_type: str,
                  deadline_ms: int = 0,
                  priority: str = wire.PRIORITY_BULK) -> None:
        msg = wire.ResharingMessage(
            wallet_id=wallet_id, new_threshold=new_threshold,
            key_type=key_type, deadline_ms=deadline_ms, priority=priority,
        )
        msg.signature = self.initiator.sign(msg.raw())
        self.transport.pubsub.publish(
            wire.TOPIC_RESHARE, wire.canonical_json(msg.to_json())
        )
        log.info("resharing requested", wallet=wallet_id, key_type=key_type)

    # -- results ------------------------------------------------------------

    def on_wallet_creation_result(
        self,
        handler: Callable[[wire.KeygenSuccessEvent], None],
        wallet_id: str | None = None,
    ):
        """Subscribe to keygen results. Results are published to per-wallet
        topics (TOPIC_KEYGEN_RESULT.{wallet_id}); passing ``wallet_id``
        narrows the work-queue subscription to that wallet, so concurrent
        clients on one broker can't steal (and eventually dead-letter)
        each other's results via round-robin delivery."""
        return self.transport.queues.dequeue(
            _result_topic(wire.TOPIC_KEYGEN_RESULT, wallet_id),
            lambda raw: handler(
                wire.KeygenSuccessEvent.from_json(json.loads(raw))
            ),
        )

    def on_sign_result(
        self,
        handler: Callable[[wire.SigningResultEvent], None],
        tx_id: str | None = None,
    ):
        """Subscribe to signing results. Like keygen/resharing, results
        land on per-tx topics (TOPIC_SIGNING_RESULT.{tx_id}); passing
        ``tx_id`` scopes the work-queue subscription so concurrent
        clients can't round-robin-steal each other's results."""
        return self.transport.queues.dequeue(
            _result_topic(wire.TOPIC_SIGNING_RESULT, tx_id),
            lambda raw: handler(
                wire.SigningResultEvent.from_json(json.loads(raw))
            ),
        )

    def on_resharing_result(
        self,
        handler: Callable[[wire.ResharingSuccessEvent], None],
        wallet_id: str | None = None,
    ):
        """Subscribe to resharing results; ``wallet_id`` narrows to that
        wallet's topic (see :meth:`on_wallet_creation_result`)."""
        return self.transport.queues.dequeue(
            _result_topic(wire.TOPIC_RESHARING_RESULT, wallet_id),
            lambda raw: handler(
                wire.ResharingSuccessEvent.from_json(json.loads(raw))
            ),
        )
