"""Batched secp256k1 group operations in JAX.

Projective points (X:Y:Z) on y² = x³ + 7 with the *complete* addition
formulas of Renes–Costello–Batina 2015 (Algorithm 7, short Weierstrass
a = 0): one branch-free formula valid for every input pair, including
doubling and the identity (0:1:0). Completeness costs ~40% more field muls
than dedicated Jacobian add/double but removes all data-dependent control
flow — the right trade for XLA/TPU batching (SURVEY.md §7).

This is the curve under GG18 ECDSA (reference uses tss.S256() via
btcec/dcrec — pkg/mpc/ecdsa_keygen_session.go:83); the hot ops are the nonce
commitments Γ_i = γ_i·G and R reconstruction in the signing rounds.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from . import bignum as bn
from . import hostmath as hm
from .fields import secp256k1_field

PROF = bn.P256
SCALAR_BITS = 256
_B3 = 21  # 3·b for b = 7


class SecpPointJ(NamedTuple):
    """Batch of projective points; fields shaped (..., 22)."""

    X: jnp.ndarray
    Y: jnp.ndarray
    Z: jnp.ndarray

    @property
    def batch_shape(self):
        return self.X.shape[:-1]


def identity(batch_shape=()) -> SecpPointJ:
    F = secp256k1_field()
    return SecpPointJ(
        F.const(0, batch_shape), F.const(1, batch_shape), F.const(0, batch_shape)
    )


def from_host(points) -> SecpPointJ:
    """hostmath.SecpPoint list (no identities) → batch."""
    F = secp256k1_field()
    xs = [p.x for p in points]
    ys = [p.y for p in points]
    return SecpPointJ(
        jnp.asarray(F.from_ints(xs)),
        jnp.asarray(F.from_ints(ys)),
        F.const(1, (len(points),)),
    )


def to_host(p: SecpPointJ) -> list:
    """Batch → list of affine hostmath.SecpPoint (identity-aware)."""
    F = secp256k1_field()
    zs = F.to_ints(p.Z)
    xs = F.to_ints(p.X)
    ys = F.to_ints(p.Y)
    out = []
    for x, y, z in zip(xs, ys, zs):
        if z == 0:
            out.append(hm.SECP_INF)
        else:
            zi = pow(z, -1, hm.SECP_P)
            out.append(hm.SecpPoint(x * zi % hm.SECP_P, y * zi % hm.SECP_P))
    return out


def add(a: SecpPointJ, b: SecpPointJ) -> SecpPointJ:
    """Complete addition, RCB15 Algorithm 7 (a=0, b3=21)."""
    F = secp256k1_field()
    m, s, A = F.mul, F.mul_small, F.add
    S = F.sub
    t0 = m(a.X, b.X)
    t1 = m(a.Y, b.Y)
    t2 = m(a.Z, b.Z)
    t3 = A(a.X, a.Y)
    t4 = A(b.X, b.Y)
    t3 = m(t3, t4)
    t4 = A(t0, t1)
    t3 = S(t3, t4)
    t4 = A(a.Y, a.Z)
    x3 = A(b.Y, b.Z)
    t4 = m(t4, x3)
    x3 = A(t1, t2)
    t4 = S(t4, x3)
    x3 = A(a.X, a.Z)
    y3 = A(b.X, b.Z)
    x3 = m(x3, y3)
    y3 = A(t0, t2)
    y3 = S(x3, y3)
    x3 = A(t0, t0)
    t0 = A(x3, t0)
    t2 = s(t2, _B3)
    z3 = A(t1, t2)
    t1 = S(t1, t2)
    y3 = s(y3, _B3)
    x3 = m(t4, y3)
    t2 = m(t3, t1)
    x3 = S(t2, x3)
    y3 = m(y3, t0)
    t1 = m(t1, z3)
    y3 = A(t1, y3)
    t0 = m(t0, t3)
    z3 = m(z3, t4)
    z3 = A(z3, t0)
    return SecpPointJ(x3, y3, z3)


def double(a: SecpPointJ) -> SecpPointJ:
    return add(a, a)


def select(mask: jnp.ndarray, a: SecpPointJ, b: SecpPointJ) -> SecpPointJ:
    m = mask[..., None]
    return SecpPointJ(
        jnp.where(m, a.X, b.X), jnp.where(m, a.Y, b.Y), jnp.where(m, a.Z, b.Z)
    )


def scalars_to_bits(ks, n_bits: int = SCALAR_BITS) -> np.ndarray:
    out = np.zeros((len(ks), n_bits), dtype=np.int32)
    for i, k in enumerate(ks):
        assert 0 <= k < 1 << n_bits
        for j in range(n_bits):
            out[i, j] = (k >> j) & 1
    return out


def scalar_mul(bits: jnp.ndarray, p: SecpPointJ) -> SecpPointJ:
    """Variable-base double-and-add; bits (..., 256) LSB-first."""
    acc = identity(bits.shape[:-1])

    def step(carry, bit):
        acc, addend = carry
        acc = select(bit > 0, add(acc, addend), acc)
        return (acc, double(addend)), None

    (acc, _), _ = lax.scan(step, (acc, p), jnp.moveaxis(bits, -1, 0))
    return acc


@functools.lru_cache(maxsize=None)
def _base_table() -> tuple:
    """Constants G·2^i for i in [0, 256): three (256, 22) int32 arrays."""
    F = secp256k1_field()
    pts = []
    cur = hm.SECP_G
    for _ in range(SCALAR_BITS):
        pts.append((cur.x, cur.y))
        cur = hm.secp_add(cur, cur)
    X = F.from_ints([p[0] for p in pts])
    Y = F.from_ints([p[1] for p in pts])
    Z = np.broadcast_to(bn.to_limbs(1, PROF), X.shape).copy()
    return X, Y, Z


def base_mul(bits: jnp.ndarray) -> SecpPointJ:
    """Fixed-base mult k·G via the G·2^i table."""
    Xt, Yt, Zt = (jnp.asarray(a) for a in _base_table())
    acc = identity(bits.shape[:-1])

    def step(acc, sl):
        bit, X, Y, Z = sl
        tbl = SecpPointJ(*(jnp.broadcast_to(c, acc.X.shape) for c in (X, Y, Z)))
        return select(bit > 0, add(acc, tbl), acc), None

    acc, _ = lax.scan(step, acc, (jnp.moveaxis(bits, -1, 0), Xt, Yt, Zt))
    return acc


@functools.lru_cache(maxsize=None)
def scalar_ring() -> bn.BarrettCtx:
    """Barrett context for the group order n (the ECDSA scalar ring)."""
    return bn.BarrettCtx(hm.SECP_N, PROF)


def neg(a: SecpPointJ) -> SecpPointJ:
    """Batch point negation (Y ↦ -Y)."""
    F = secp256k1_field()
    return SecpPointJ(a.X, F.neg(a.Y), a.Z)


def equal(a: SecpPointJ, b: SecpPointJ) -> jnp.ndarray:
    """Batch equality: cross-multiplied, Z-invariant, identity-aware."""
    F = secp256k1_field()
    ex = F.eq(F.mul(a.X, b.Z), F.mul(b.X, a.Z))
    ey = F.eq(F.mul(a.Y, b.Z), F.mul(b.Y, a.Z))
    za = F.is_zero(a.Z)
    zb = F.is_zero(b.Z)
    return jnp.where(za | zb, za == zb, ex & ey)


def x_coordinate(p: SecpPointJ) -> jnp.ndarray:
    """Affine x as canonical limbs (the ECDSA r source)."""
    F = secp256k1_field()
    return F.canonical(F.mul(p.X, F.inv(p.Z)))


def compress(p: SecpPointJ) -> jnp.ndarray:
    """Batch SEC1 compressed encoding → (..., 33) uint8 big-endian."""
    F = secp256k1_field()
    zi = F.inv(p.Z)
    x = F.canonical(F.mul(p.X, zi))
    y = F.canonical(F.mul(p.Y, zi))
    xb = pack_be_32(x)
    tag = (2 + (y[..., 0] & 1)).astype(jnp.uint8)
    return jnp.concatenate([tag[..., None], xb], axis=-1)


def pack_be_32(limbs: jnp.ndarray) -> jnp.ndarray:
    """Canonical limbs (< 2^256) → (..., 32) uint8 big-endian."""
    shifts = jnp.arange(PROF.bits, dtype=jnp.int32)
    bits = (limbs[..., :, None] >> shifts) & 1  # LSB-first
    bits = bits.reshape(limbs.shape[:-1] + (PROF.n_limbs * PROF.bits,))[..., :256]
    by = bits.reshape(bits.shape[:-1] + (32, 8))
    vals = jnp.sum(by << jnp.arange(8, dtype=jnp.int32), axis=-1)
    return jnp.flip(vals, axis=-1).astype(jnp.uint8)


@functools.lru_cache(maxsize=None)
def _sqrt_ctx():
    from .fields import Secp256k1Sqrt

    return Secp256k1Sqrt()


def decompress(b: jnp.ndarray) -> "Tuple[SecpPointJ, jnp.ndarray]":
    """Batch SEC1 decompression: (..., 33) uint8 → (SecpPointJ, ok mask).

    Bad encodings (wrong tag, x ≥ p, non-residue) yield ok=False with an
    arbitrary valid-shape point — callers gate on the mask (the device
    analogue of hostmath.secp_decompress raising)."""
    F = secp256k1_field()
    tag = b[..., 0].astype(jnp.int32)
    xb = jnp.flip(b[..., 1:], axis=-1)  # big-endian bytes → little-endian
    x = bn.bytes_to_limbs_le(xb, PROF, PROF.n_limbs)
    p_l = jnp.broadcast_to(jnp.asarray(bn.to_limbs(hm.SECP_P, PROF)), x.shape)
    ok = (bn.compare(x, p_l) < 0) & ((tag == 2) | (tag == 3))
    rhs = F.add(F.mul(F.square(x), x), F.const(7, x.shape[:-1]))
    y, has_root = _sqrt_ctx().sqrt(rhs)
    ok = ok & has_root
    y = F.canonical(y)
    flip = (y[..., 0] & 1) != (tag & 1)
    y = jnp.where(flip[..., None], F.canonical(F.neg(y)), y)
    one = jnp.broadcast_to(
        jnp.asarray(bn.to_limbs(1, PROF)), x.shape
    )
    return SecpPointJ(x, y, one), ok
