"""Batched edwards25519 group operations in JAX.

Points are extended twisted-Edwards coordinates (X:Y:Z:T) with x·y = T·Z,
each coordinate a 22-limb int32 tensor with arbitrary leading batch shape.
The unified addition law is *complete* on the curve (a = -1, d non-square):
no branches, identical code for add/double — exactly what XLA wants
(SURVEY.md §7: compiler-friendly control flow, static shapes).

Hot-path design: EdDSA keygen/signing is dominated by fixed-base scalar
multiplications (nonce commitments R_i = r_i·B — reference round structure in
pkg/mpc/eddsa_rounds.go). Fixed-base mults use a precomputed table of
B·2^i constants (half the field-muls of double-and-add); variable-base mults
(verification) use the double-and-add ladder with completeness-based selects.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from . import bignum as bn
from . import hostmath as hm
from .fields import ed25519_field

PROF = bn.P256
SCALAR_BITS = 256


class EdPointJ(NamedTuple):
    """Batch of extended-coordinate points; fields shaped (..., 22)."""

    X: jnp.ndarray
    Y: jnp.ndarray
    Z: jnp.ndarray
    T: jnp.ndarray

    @property
    def batch_shape(self):
        return self.X.shape[:-1]


def identity(batch_shape=()) -> EdPointJ:
    F = ed25519_field()
    zero = F.const(0, batch_shape)
    one = F.const(1, batch_shape)
    return EdPointJ(zero, one, one, zero)


def from_host(points, batch_shape=None) -> EdPointJ:
    """Build a batch from host points (hostmath.EdPoint or (x, y) ints)."""
    F = ed25519_field()
    xs, ys = [], []
    for pt in points:
        x, y = pt.affine() if isinstance(pt, hm.EdPoint) else pt
        xs.append(x)
        ys.append(y)
    X = jnp.asarray(F.from_ints(xs))
    Y = jnp.asarray(F.from_ints(ys))
    T = F.mul(X, Y)
    Z = F.const(1, X.shape[:-1])
    return EdPointJ(X, Y, Z, T)


def to_host(p: EdPointJ) -> list:
    """Batch → list of hostmath.EdPoint (affine check included)."""
    F = ed25519_field()
    xs = F.to_ints(p.X)
    ys = F.to_ints(p.Y)
    zs = F.to_ints(p.Z)
    ts = F.to_ints(p.T)
    return [hm.EdPoint(x, y, z, t) for x, y, z, t in zip(xs, ys, zs, ts)]


@functools.lru_cache(maxsize=None)
def _d2_limbs() -> np.ndarray:
    F = ed25519_field()
    return bn.to_limbs(2 * hm.ED_D % hm.ED_P, PROF)


def add(a: EdPointJ, b: EdPointJ) -> EdPointJ:
    """Unified complete addition (RFC 8032 / HWCD08 'add-2008-hwcd-3')."""
    F = ed25519_field()
    A = F.mul(F.sub(a.Y, a.X), F.sub(b.Y, b.X))
    B = F.mul(F.add(a.Y, a.X), F.add(b.Y, b.X))
    C = F.mul(F.mul(a.T, b.T), jnp.broadcast_to(jnp.asarray(_d2_limbs()), a.T.shape))
    D = F.mul_small(F.mul(a.Z, b.Z), 2)
    E = F.sub(B, A)
    Fv = F.sub(D, C)
    G = F.add(D, C)
    H = F.add(B, A)
    return EdPointJ(F.mul(E, Fv), F.mul(G, H), F.mul(Fv, G), F.mul(E, H))


def double(a: EdPointJ) -> EdPointJ:
    return add(a, a)


def select(mask: jnp.ndarray, a: EdPointJ, b: EdPointJ) -> EdPointJ:
    """mask ? a : b, elementwise over the batch (mask: bool (...,))."""
    m = mask[..., None]
    return EdPointJ(
        jnp.where(m, a.X, b.X),
        jnp.where(m, a.Y, b.Y),
        jnp.where(m, a.Z, b.Z),
        jnp.where(m, a.T, b.T),
    )


def scalars_to_bits(ks, n_bits: int = SCALAR_BITS) -> np.ndarray:
    """Host ints → (batch, n_bits) int32 little-endian bit array."""
    out = np.zeros((len(ks), n_bits), dtype=np.int32)
    for i, k in enumerate(ks):
        assert 0 <= k < 1 << n_bits
        for j in range(n_bits):
            out[i, j] = (k >> j) & 1
    return out


def scalar_mul(bits: jnp.ndarray, p: EdPointJ) -> EdPointJ:
    """Variable-base double-and-add; bits (..., 256) LSB-first."""
    acc = identity(bits.shape[:-1])

    def step(carry, bit):
        acc, addend = carry
        acc = select(bit > 0, add(acc, addend), acc)
        return (acc, double(addend)), None

    (acc, _), _ = lax.scan(step, (acc, p), jnp.moveaxis(bits, -1, 0))
    return acc


@functools.lru_cache(maxsize=None)
def _base_table() -> tuple:
    """Constants B·2^i for i in [0, 256): four (256, 22) int32 arrays."""
    F = ed25519_field()
    pts = []
    cur = hm.ED_B
    for _ in range(SCALAR_BITS):
        pts.append(cur.affine())
        cur = hm.ed_add(cur, cur)
    X = F.from_ints([p[0] for p in pts])
    Y = F.from_ints([p[1] for p in pts])
    T = F.from_ints([p[0] * p[1] % hm.ED_P for p in pts])
    Z = np.broadcast_to(bn.to_limbs(1, PROF), X.shape).copy()
    return X, Y, Z, T


def base_mul(bits: jnp.ndarray) -> EdPointJ:
    """Fixed-base mult k·B via the B·2^i table: 256 conditional adds, no
    doubling chain — the hot op for nonce commitments and keygen."""
    Xt, Yt, Zt, Tt = (jnp.asarray(a) for a in _base_table())
    acc = identity(bits.shape[:-1])

    def step(acc, sl):
        bit, X, Y, Z, T = sl
        tbl = EdPointJ(*(jnp.broadcast_to(c, acc.X.shape) for c in (X, Y, Z, T)))
        return select(bit > 0, add(acc, tbl), acc), None

    acc, _ = lax.scan(
        step, acc, (jnp.moveaxis(bits, -1, 0), Xt, Yt, Zt, Tt)
    )
    return acc


@functools.lru_cache(maxsize=None)
def scalar_ring() -> bn.BarrettCtx:
    """Barrett context for the group order l (the EdDSA scalar ring)."""
    return bn.BarrettCtx(hm.ED_L, PROF)


def decompress(b: jnp.ndarray) -> "Tuple[EdPointJ, jnp.ndarray]":
    """Batch RFC 8032 decode: (..., 32) uint8 → (EdPointJ, ok mask).

    Invalid encodings (y ≥ p, non-residue x², x=0 with sign=1) yield the
    identity with ok=False — callers mask, never branch. Square root per
    p ≡ 5 (mod 8): x = u·v³·(u·v⁷)^((p-5)/8), fixed up by √-1.
    """
    F = ed25519_field()
    sign = (b[..., 31] >> 7).astype(jnp.int32)
    y_bytes = b.at[..., 31].set(b[..., 31] & 0x7F)
    y = bn.bytes_to_limbs_le(y_bytes, PROF, PROF.n_limbs)
    p_l = jnp.broadcast_to(jnp.asarray(bn.to_limbs(hm.ED_P, PROF)), y.shape)
    ok = bn.compare(y, p_l) < 0
    y2 = F.square(y)
    one = F.one_like(y2)
    u = F.sub(y2, one)
    v = F.add(F.mul(F.const(hm.ED_D, y.shape[:-1]), y2), one)
    v3 = F.mul(F.square(v), v)
    v7 = F.mul(F.square(v3), v)
    pw = F.pow_const(F.mul(u, v7), (hm.ED_P - 5) // 8)
    x = F.mul(F.mul(u, v3), pw)
    vx2 = F.mul(v, F.square(x))
    is_u = F.eq(vx2, u)
    is_neg_u = F.eq(vx2, F.neg(u))
    sqrt_m1 = F.const(pow(2, (hm.ED_P - 1) // 4, hm.ED_P), y.shape[:-1])
    x = jnp.where(is_neg_u[..., None], F.mul(x, sqrt_m1), x)
    ok = ok & (is_u | is_neg_u)
    xc = F.canonical(x)
    x_is_zero = jnp.all(xc == 0, axis=-1)
    ok = ok & ~(x_is_zero & (sign == 1))
    flip = (xc[..., 0] & 1) != sign
    x = jnp.where(flip[..., None], F.neg(x), x)
    pt = EdPointJ(x, y, F.one_like(y), F.mul(x, y))
    return select(ok, pt, identity(ok.shape)), ok


def equal(a: EdPointJ, b: EdPointJ) -> jnp.ndarray:
    """Batch equality, Z-invariant: X1·Z2 == X2·Z1 and Y1·Z2 == Y2·Z1."""
    F = ed25519_field()
    ex = F.eq(F.mul(a.X, b.Z), F.mul(b.X, a.Z))
    ey = F.eq(F.mul(a.Y, b.Z), F.mul(b.Y, a.Z))
    return ex & ey


def compress(p: EdPointJ) -> jnp.ndarray:
    """Batch compress → (..., 32) uint8, RFC 8032 encoding (little-endian y
    with sign bit of x in the top bit)."""
    F = ed25519_field()
    zi = F.inv(p.Z)
    x = F.canonical(F.mul(p.X, zi))
    y = F.canonical(F.mul(p.Y, zi))
    return _pack_bytes_le(y, sign=x[..., 0] & 1)


def _pack_bytes_le(limbs: jnp.ndarray, sign=None) -> jnp.ndarray:
    """Canonical 22×12-bit limbs → 32 bytes little-endian (values < 2^256)."""
    bit_w = PROF.bits
    # spread limbs to bits then regroup — static shapes, vector ops only
    shifts = jnp.arange(bit_w, dtype=jnp.int32)
    bits = (limbs[..., :, None] >> shifts) & 1  # (..., 22, 12)
    bits = bits.reshape(limbs.shape[:-1] + (PROF.n_limbs * bit_w,))[..., :256]
    if sign is not None:
        bits = bits.at[..., 255].add(sign)  # top bit is 0 for canonical y < p
    byte_shifts = jnp.arange(8, dtype=jnp.int32)
    by = bits.reshape(bits.shape[:-1] + (32, 8))
    return jnp.sum(by << byte_shifts, axis=-1).astype(jnp.uint8)


def pack_scalar_bytes_le(limbs: jnp.ndarray) -> jnp.ndarray:
    """Canonical scalar limbs → (..., 32) uint8 little-endian."""
    return _pack_bytes_le(limbs)
