"""Host-side (CPU, python-int) elliptic-curve and field arithmetic.

This is the *reference* implementation of the math the TPU kernels batch:
secp256k1 (for GG18 ECDSA) and edwards25519 (for threshold EdDSA). It serves
three roles:

1. ground truth for property tests of the JAX/Pallas kernels in
   ``mpcium_tpu.core.{bignum,ed25519,secp256k1}``;
2. the control-plane math for single-shot operations that are not worth a
   TPU dispatch (key decode, verification of a single signature, Feldman VSS
   checks during keygen);
3. an independent verifier: Ed25519 per RFC 8032 and standard ECDSA, so that
   protocol outputs can be checked without trusting the batched kernels.

Capability parity: the reference delegates these ops to Go dependencies
(`decred/dcrd/dcrec/secp256k1`, `decred/dcrd/dcrec/edwards` — see
reference pkg/mpc/ecdsa_keygen_session.go:83 `tss.S256()`,
eddsa_keygen_session.go `tss.Edwards()`). Everything here is written from
scratch against the public curve specifications.
"""
from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass
from typing import Optional, Tuple

# ---------------------------------------------------------------------------
# secp256k1  (short Weierstrass y^2 = x^3 + 7 over F_p)
# ---------------------------------------------------------------------------

SECP_P = 2**256 - 2**32 - 977
SECP_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
SECP_GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
SECP_GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


@dataclass(frozen=True)
class SecpPoint:
    """Affine secp256k1 point; None coordinates encode the identity."""

    x: Optional[int]
    y: Optional[int]

    @property
    def is_infinity(self) -> bool:
        return self.x is None

    def __add__(self, other: "SecpPoint") -> "SecpPoint":
        return secp_add(self, other)

    def __rmul__(self, k: int) -> "SecpPoint":
        return secp_mul(k, self)


SECP_INF = SecpPoint(None, None)
SECP_G = SecpPoint(SECP_GX, SECP_GY)


def secp_add(a: SecpPoint, b: SecpPoint) -> SecpPoint:
    if a.is_infinity:
        return b
    if b.is_infinity:
        return a
    p = SECP_P
    if a.x == b.x:
        if (a.y + b.y) % p == 0:
            return SECP_INF
        # doubling
        lam = (3 * a.x * a.x) * pow(2 * a.y, -1, p) % p
    else:
        lam = (b.y - a.y) * pow(b.x - a.x, -1, p) % p
    x3 = (lam * lam - a.x - b.x) % p
    y3 = (lam * (a.x - x3) - a.y) % p
    return SecpPoint(x3, y3)


def secp_mul(k: int, pt: SecpPoint) -> SecpPoint:
    k %= SECP_N
    acc = SECP_INF
    add = pt
    while k:
        if k & 1:
            acc = secp_add(acc, add)
        add = secp_add(add, add)
        k >>= 1
    return acc


def secp_compress(pt: SecpPoint) -> bytes:
    """SEC1 compressed encoding (33 bytes)."""
    assert not pt.is_infinity
    return bytes([2 + (pt.y & 1)]) + pt.x.to_bytes(32, "big")


def secp_decompress(data: bytes) -> SecpPoint:
    assert len(data) == 33 and data[0] in (2, 3)
    x = int.from_bytes(data[1:], "big")
    if x >= SECP_P:
        raise ValueError("x out of field range")
    y2 = (pow(x, 3, SECP_P) + 7) % SECP_P
    y = pow(y2, (SECP_P + 1) // 4, SECP_P)
    if y * y % SECP_P != y2:
        raise ValueError("not a curve point")
    if (y & 1) != (data[0] & 1):
        y = SECP_P - y
    return SecpPoint(x, y)


def secp_encode_xy(pt: SecpPoint) -> bytes:
    """Fixed-width X||Y (64 bytes).

    The reference emits *unpadded* X||Y (encoding/ecdsa.go:7-10), which can be
    shorter than 64 bytes for leading-zero coordinates — SURVEY.md §7.5 flags
    that as a wart. We emit fixed-width; ``secp_decode_xy`` also accepts the
    reference's variable-width form.
    """
    assert not pt.is_infinity
    return pt.x.to_bytes(32, "big") + pt.y.to_bytes(32, "big")


def secp_decode_xy(data: bytes) -> SecpPoint:
    if len(data) == 64:
        x = int.from_bytes(data[:32], "big")
        y = int.from_bytes(data[32:], "big")
    else:
        # reference-compat: unpadded big.Int concatenation is ambiguous in
        # general; accept the common case where both halves are equal length.
        half = len(data) // 2
        x = int.from_bytes(data[:half], "big")
        y = int.from_bytes(data[half:], "big")
    if x >= SECP_P or y >= SECP_P:
        raise ValueError("coordinate out of field range")
    pt = SecpPoint(x, y)
    if (y * y - pow(x, 3, SECP_P) - 7) % SECP_P != 0:
        raise ValueError("not a curve point")
    return pt


def ecdsa_verify(pub: SecpPoint, digest: int, r: int, s: int) -> bool:
    """Standard ECDSA verification over secp256k1.

    Mirrors the reference's local self-check before publishing a signing
    result (ecdsa_signing_session.go:162).
    """
    if not (1 <= r < SECP_N and 1 <= s < SECP_N):
        return False
    w = pow(s, -1, SECP_N)
    u1 = digest * w % SECP_N
    u2 = r * w % SECP_N
    pt = secp_add(secp_mul(u1, SECP_G), secp_mul(u2, pub))
    if pt.is_infinity:
        return False
    return pt.x % SECP_N == r


def ecdsa_sign_plain(priv: int, digest: int, k: Optional[int] = None) -> Tuple[int, int, int]:
    """Single-party ECDSA (test harness only). Returns (r, s, recovery_id)."""
    while True:
        kk = k if k is not None else (secrets.randbelow(SECP_N - 1) + 1)
        R = secp_mul(kk, SECP_G)
        r = R.x % SECP_N
        if r == 0:
            if k is not None:
                raise ValueError("degenerate fixed nonce: r == 0")
            continue
        s = pow(kk, -1, SECP_N) * (digest + r * priv) % SECP_N
        if s == 0:
            if k is not None:
                raise ValueError("degenerate fixed nonce: s == 0")
            continue
        rec = (R.y & 1) | (2 if R.x >= SECP_N else 0)
        # low-s normalization flips parity of the recovery bit
        if s > SECP_N // 2:
            s = SECP_N - s
            rec ^= 1
        return r, s, rec


# ---------------------------------------------------------------------------
# edwards25519 (RFC 8032)
# ---------------------------------------------------------------------------

ED_P = 2**255 - 19
ED_L = 2**252 + 27742317777372353535851937790883648493
ED_D = (-121665 * pow(121666, -1, ED_P)) % ED_P


def _ed_recover_x(y: int, sign: int) -> Optional[int]:
    if y >= ED_P:
        return None
    x2 = (y * y - 1) * pow(ED_D * y * y + 1, -1, ED_P) % ED_P
    if x2 == 0:
        return None if sign else 0
    # p = 5 mod 8 → sqrt via x2^((p+3)/8), correct by sqrt(-1) if needed
    x = pow(x2, (ED_P + 3) // 8, ED_P)
    if (x * x - x2) % ED_P != 0:
        x = x * pow(2, (ED_P - 1) // 4, ED_P) % ED_P
    if (x * x - x2) % ED_P != 0:
        return None
    if (x & 1) != sign:
        x = ED_P - x
    return x


@dataclass(frozen=True)
class EdPoint:
    """Extended twisted-Edwards coordinates (X:Y:Z:T), x*y = T*Z."""

    X: int
    Y: int
    Z: int
    T: int

    def __add__(self, other: "EdPoint") -> "EdPoint":
        return ed_add(self, other)

    def __rmul__(self, k: int) -> "EdPoint":
        return ed_mul(k, self)

    def affine(self) -> Tuple[int, int]:
        zi = pow(self.Z, -1, ED_P)
        return self.X * zi % ED_P, self.Y * zi % ED_P

    def equals(self, other: "EdPoint") -> bool:
        # cross-multiplied comparison, Z-invariant
        return (
            (self.X * other.Z - other.X * self.Z) % ED_P == 0
            and (self.Y * other.Z - other.Y * self.Z) % ED_P == 0
        )


ED_IDENT = EdPoint(0, 1, 1, 0)
_BY = 4 * pow(5, -1, ED_P) % ED_P
_BX = _ed_recover_x(_BY, 0)
ED_B = EdPoint(_BX, _BY, 1, _BX * _BY % ED_P)


def ed_add(a: EdPoint, b: EdPoint) -> EdPoint:
    """Unified (complete) addition — same formula for double and add."""
    p = ED_P
    A = (a.Y - a.X) * (b.Y - b.X) % p
    Bv = (a.Y + a.X) * (b.Y + b.X) % p
    C = 2 * a.T * b.T * ED_D % p
    Dv = 2 * a.Z * b.Z % p
    E, F, G, H = Bv - A, Dv - C, Dv + C, Bv + A
    return EdPoint(E * F % p, G * H % p, F * G % p, E * H % p)


def ed_mul(k: int, pt: EdPoint) -> EdPoint:
    """Scalar multiplication. NOTE: does not reduce k mod ED_L — RFC 8032
    cofactorless verification relies on the unreduced hash scalar when the
    input point has a torsion component."""
    if k < 0:
        raise ValueError("negative scalar")
    acc = ED_IDENT
    add = pt
    while k:
        if k & 1:
            acc = ed_add(acc, add)
        add = ed_add(add, add)
        k >>= 1
    return acc


def ed_compress(pt: EdPoint) -> bytes:
    x, y = pt.affine()
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def ed_decompress(data: bytes) -> EdPoint:
    assert len(data) == 32
    raw = int.from_bytes(data, "little")
    sign = raw >> 255
    y = raw & ((1 << 255) - 1)
    x = _ed_recover_x(y, sign)
    if x is None:
        raise ValueError("not a curve point")
    return EdPoint(x, y, 1, x * y % ED_P)


def sha512_int_le(*chunks: bytes) -> int:
    return int.from_bytes(hashlib.sha512(b"".join(chunks)).digest(), "little")


def ed25519_verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """RFC 8032 verification (the independent check for threshold outputs)."""
    if len(sig) != 64:
        return False
    try:
        A = ed_decompress(pub)
        R = ed_decompress(sig[:32])
    except ValueError:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= ED_L:
        return False
    h = sha512_int_le(sig[:32], pub, msg)  # unreduced: cofactorless verify
    lhs = ed_mul(s, ED_B)
    rhs = ed_add(R, ed_mul(h, A))
    return lhs.equals(rhs)


def ed25519_sign_plain(seed: bytes, msg: bytes) -> bytes:
    """Single-party RFC 8032 signing (identity layer / test harness)."""
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    prefix = h[32:]
    A = ed_compress(ed_mul(a, ED_B))
    r = sha512_int_le(prefix, msg) % ED_L
    Rb = ed_compress(ed_mul(r, ED_B))
    k = sha512_int_le(Rb, A, msg) % ED_L
    s = (r + k * a) % ED_L
    return Rb + s.to_bytes(32, "little")


def ed25519_public_from_seed(seed: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return ed_compress(ed_mul(a, ED_B))


# ---------------------------------------------------------------------------
# Shamir / Feldman VSS over a generic prime order group
# ---------------------------------------------------------------------------


def poly_eval(coeffs, x: int, order: int) -> int:
    """Evaluate sum(coeffs[i] * x^i) mod order (Horner)."""
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % order
    return acc


def shamir_share(secret: int, threshold: int, xs, order: int, rng=secrets):
    """Degree-`threshold` polynomial sharing: t+1 shares reconstruct.

    Matches tss-lib convention where `threshold` t means t+1 parties are
    required (reference: node.go factories pass threshold through to
    tss.NewParameters).
    """
    coeffs = [secret] + [rng.randbelow(order - 1) + 1 for _ in range(threshold)]
    return coeffs, {x: poly_eval(coeffs, x, order) for x in xs}


def lagrange_coeff(xs, x_i: int, order: int, at: int = 0) -> int:
    """Lagrange basis polynomial for x_i over points xs, evaluated at `at`."""
    num, den = 1, 1
    for x_j in xs:
        if x_j == x_i:
            continue
        num = num * ((at - x_j) % order) % order
        den = den * ((x_i - x_j) % order) % order
    return num * pow(den, -1, order) % order


def shamir_reconstruct(shares: dict, order: int, at: int = 0) -> int:
    xs = list(shares)
    acc = 0
    for x_i, y_i in shares.items():
        acc = (acc + y_i * lagrange_coeff(xs, x_i, order, at)) % order
    return acc
