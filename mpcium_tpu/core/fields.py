"""Batched prime-field arithmetic for the two curve fields.

Both curve primes are pseudo-Mersenne (p = 2^k - c with small c):
  ed25519    p = 2^255 - 19
  secp256k1  p = 2^256 - 2^32 - 977

which admits a reduction far cheaper than Barrett: limbs above the capacity
boundary fold back multiplied by ``c · 2^(capacity-k)``. Elements live in the
P256 limb profile (22 × 12-bit limbs, 264-bit capacity), normalized but *not*
canonical — values are kept in [0, 2^264) between operations and only mapped
to [0, p) by :meth:`canonical` at export/comparison points.

The scalar rings (ed25519 l, secp256k1 n) are not pseudo-Mersenne and use
``bignum.BarrettCtx`` directly.

Everything is shape-polymorphic over leading batch dimensions — this is the
per-session math that the batch engine vmaps over thousands of concurrent
wallets (SURVEY.md §2.2 "TPU mapping").
"""
from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np
from jax import lax

from . import bignum as bn
from .bignum import P256

PROF = P256


class PseudoMersenneField:
    """F_p for p = 2^k - c, elements as 22-limb int32 tensors in [0, 2^264)."""

    def __init__(self, k: int, c: int):
        assert PROF.capacity_bits >= k
        self.k = k
        self.c = c
        self.p = (1 << k) - c
        shift = PROF.capacity_bits - k  # 2^264 ≡ c · 2^shift  (mod p)
        self.fold_const = c << shift
        # fold multiplier as (short) limbs
        n_fc = max(1, -(-self.fold_const.bit_length() // PROF.bits))
        self.fc_limbs = bn.to_limbs(self.fold_const, PROF, n_limbs=n_fc)
        self.p_limbs = bn.to_limbs(self.p, PROF)
        # K·p ≥ 2^264 for borrow-free subtraction, 23 limbs
        K = (1 << shift) + 1
        self.kp_limbs = bn.to_limbs(K * self.p, PROF, n_limbs=PROF.n_limbs + 1)
        # 2^shift·p = 2^264 - c·2^shift < 2^264: the conditional-subtract
        # constant that caps fold results below capacity
        self.cap_limbs = bn.to_limbs(
            (1 << shift) * self.p, PROF, n_limbs=PROF.n_limbs + 1
        )
        # top-limb quotient estimate uses k = 21*12 + r
        self.top_shift = k - 21 * PROF.bits
        assert 0 < self.top_shift <= PROF.bits
        self.c_limbs = bn.to_limbs(self.c, PROF, n_limbs=4)

    # -- reduction ----------------------------------------------------------

    def _fold_pass(self, x: jnp.ndarray, out_width: int) -> jnp.ndarray:
        """One fold: value(x) → lo + fc·hi, carried into ``out_width`` limbs.
        Caller guarantees the folded value fits ``out_width`` limbs."""
        n = PROF.n_limbs
        lo, hi = x[..., :n], x[..., n:]
        fc = jnp.broadcast_to(
            jnp.asarray(self.fc_limbs),
            hi.shape[:-1] + (self.fc_limbs.shape[0],),
        )
        contrib = bn.mul(hi, fc, PROF)
        return bn.carry(
            bn.take_limbs(lo, 0, out_width) + bn.take_limbs(contrib, 0, out_width),
            PROF,
        )

    def fold(self, x: jnp.ndarray) -> jnp.ndarray:
        """Normalized x (any width) → congruent 22-limb value < 2^264.

        Bound accounting (fc < 2^42): a pass over w>n limbs yields
        < 2^264 + fc·2^(12(w-n)); widths shrink geometrically to n+1 limbs,
        and a final conditional subtract of 2^shift·p (< 2^264, ≥ value-2^264)
        caps the result strictly below capacity.
        """
        n = PROF.n_limbs
        while x.shape[-1] > n + 1:
            hi_limbs = x.shape[-1] - n
            contrib_limbs = hi_limbs + self.fc_limbs.shape[0]
            x = self._fold_pass(x, max(n + 1, contrib_limbs + 1))
        if x.shape[-1] == n + 1:
            x = self._fold_pass(x, n + 1)  # < 2^264 + fc·2^12 ≤ 2^264 + 2^54
            cap = jnp.broadcast_to(jnp.asarray(self.cap_limbs), x.shape)
            x = bn.cond_sub(x, cap, PROF)[..., :n]
        return x

    def mul(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        return self.fold(bn.mul(a, b, PROF))

    def square(self, a: jnp.ndarray) -> jnp.ndarray:
        return self.mul(a, a)

    def add(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        return self.fold(bn.carry(bn.pad_limbs(a + b, 1), PROF))

    def sub(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        kp = jnp.broadcast_to(
            jnp.asarray(self.kp_limbs), a.shape[:-1] + (PROF.n_limbs + 1,)
        )
        t = bn.carry(kp + bn.pad_limbs(a, 1) - bn.pad_limbs(b, 1), PROF)
        return self.fold(t)

    def neg(self, a: jnp.ndarray) -> jnp.ndarray:
        return self.sub(jnp.zeros_like(a), a)

    def mul_small(self, a: jnp.ndarray, s: int) -> jnp.ndarray:
        return self.fold(bn.mul_small(a, s, PROF))

    # -- canonical form -----------------------------------------------------

    def canonical(self, x: jnp.ndarray) -> jnp.ndarray:
        """Map [0, 2^264) → [0, p): quotient estimate + conditional subtracts."""
        n = PROF.n_limbs
        q = x[..., n - 1] >> self.top_shift  # floor(x / 2^k), ≤ 2^(264-k)
        # x ← x - q·2^k + q·c  (≡ x mod p; result < 2^k + 2^54 < 2p)
        x = x.at[..., n - 1].add(-(q << self.top_shift))
        c_l = jnp.broadcast_to(jnp.asarray(self.c_limbs), q.shape + (4,))
        qc = bn.mul(q[..., None], c_l, PROF)  # q·c ≤ 2^51, 5 limbs
        x = bn.carry(x + bn.take_limbs(qc, 0, n), PROF)
        p = jnp.broadcast_to(jnp.asarray(self.p_limbs), x.shape)
        x = bn.cond_sub(x, p, PROF)
        x = bn.cond_sub(x, p, PROF)
        return x

    def eq(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        ca, cb = self.canonical(a), self.canonical(b)
        return jnp.all(ca == cb, axis=-1)

    def is_zero(self, a: jnp.ndarray) -> jnp.ndarray:
        return jnp.all(self.canonical(a) == 0, axis=-1)

    # -- exponentiation -----------------------------------------------------

    def pow_const(self, x: jnp.ndarray, exponent: int) -> jnp.ndarray:
        if exponent == 0:
            return self.one_like(x)
        ebits = jnp.asarray(
            [(exponent >> i) & 1 for i in range(exponent.bit_length())][::-1],
            dtype=jnp.int32,
        )

        def step(acc, bit):
            acc = self.square(acc)
            acc = jnp.where(bit > 0, self.mul(acc, x), acc)
            return acc, None

        acc, _ = lax.scan(step, self.one_like(x), ebits)
        return acc

    def inv(self, x: jnp.ndarray) -> jnp.ndarray:
        """Batched inverse via Fermat. inv(0) = 0 (callers gate on is_zero)."""
        return self.pow_const(x, self.p - 2)

    # -- helpers ------------------------------------------------------------

    def one_like(self, x: jnp.ndarray) -> jnp.ndarray:
        return jnp.zeros_like(x).at[..., 0].set(1)

    def const(self, value: int, batch_shape=()) -> jnp.ndarray:
        v = jnp.asarray(bn.to_limbs(value % self.p, PROF))
        return jnp.broadcast_to(v, tuple(batch_shape) + (PROF.n_limbs,))

    def to_ints(self, x) -> list:
        return bn.batch_from_limbs(self.canonical(jnp.asarray(x)), PROF)

    def from_ints(self, xs) -> np.ndarray:
        return bn.batch_to_limbs([v % self.p for v in xs], PROF)


@functools.lru_cache(maxsize=None)
def ed25519_field() -> PseudoMersenneField:
    return PseudoMersenneField(k=255, c=19)


@functools.lru_cache(maxsize=None)
def secp256k1_field() -> PseudoMersenneField:
    return PseudoMersenneField(k=256, c=(1 << 32) + 977)


class Ed25519Sqrt:
    """Square roots in F_p for p ≡ 5 (mod 8): candidate x^((p+3)/8),
    corrected by sqrt(-1) when needed. Returns (root, exists_mask)."""

    def __init__(self):
        self.F = ed25519_field()
        p = self.F.p
        self.sqrt_m1 = pow(2, (p - 1) // 4, p)

    def sqrt(self, x: jnp.ndarray):
        F = self.F
        cand = F.pow_const(x, (F.p + 3) // 8)
        c2 = F.square(cand)
        need_fix = ~F.eq(c2, x)
        fixed = F.mul(cand, F.const(self.sqrt_m1, x.shape[:-1]))
        root = jnp.where(need_fix[..., None], fixed, cand)
        ok = F.eq(F.square(root), x)
        return root, ok


class Secp256k1Sqrt:
    """Square roots in F_p for p ≡ 3 (mod 4): x^((p+1)/4)."""

    def __init__(self):
        self.F = secp256k1_field()

    def sqrt(self, x: jnp.ndarray):
        F = self.F
        root = F.pow_const(x, (F.p + 1) // 4)
        ok = F.eq(F.square(root), x)
        return root, ok
