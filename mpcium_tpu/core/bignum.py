"""Batched multi-word (big-integer) arithmetic for JAX/TPU.

This is the foundation of the TPU crypto core (SURVEY.md §7.2 step 1): the
reference delegates all bignum work to Go libraries executed one session at a
time (tss-lib Paillier/curve math, reference pkg/mpc/*_session.go); here every
operation is expressed over fixed-shape int32 limb tensors with an arbitrary
leading batch shape, so thousands of concurrent sessions' field operations run
as one XLA dispatch.

Representation
--------------
A big integer is a little-endian vector of ``n_limbs`` limbs, each holding
``bits`` bits, stored in int32: shape (..., n_limbs). Radix ``B = 1<<bits``.

Two bounds regimes:
- *normalized*: every limb in [0, B) — produced by :func:`carry`.
- *redundant*: limbs may temporarily exceed B (bounded by int32) between a
  multiply and the following carry; all public helpers return normalized
  values.

The default profile (bits=12, n_limbs=22 → 264-bit capacity) is chosen so a
schoolbook product column never overflows int32: 22 · (2^12-1)^2 < 2^31.
Larger (Paillier-sized) integers pick a smaller radix via
:func:`profile_for_bits`.

Design notes (TPU): no data-dependent shapes and no Python branching on
traced values; carry propagation is one `lax.scan`; multiplication is an
einsum against a constant one-hot "convolution" tensor; exponentiation with a
*constant* exponent is a `lax.scan` over the exponent's bits.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np
from jax import lax


@dataclass(frozen=True)
class LimbProfile:
    """Static limb layout: ``n_limbs`` limbs of ``bits`` bits."""

    bits: int
    n_limbs: int

    @property
    def radix(self) -> int:
        return 1 << self.bits

    @property
    def mask(self) -> int:
        return self.radix - 1

    @property
    def capacity_bits(self) -> int:
        return self.bits * self.n_limbs

    def __post_init__(self):
        # product column bound: n_limbs * (B-1)^2 + carry headroom < 2^31
        assert self.n_limbs * (self.radix - 1) ** 2 < 2**31, (
            "limb profile overflows int32 accumulation"
        )


# 264-bit capacity: covers all four ~256-bit moduli
# (secp256k1 p and n, ed25519 p and l)
P256 = LimbProfile(bits=12, n_limbs=22)


def profile_for_bits(value_bits: int) -> LimbProfile:
    """Pick an int32-safe limb profile for integers up to ``value_bits``."""
    for bits in (12, 11, 10, 9, 8, 7):
        n = -(-value_bits // bits)
        if n * ((1 << bits) - 1) ** 2 < 2**31:
            return LimbProfile(bits=bits, n_limbs=n)
    raise ValueError(f"no int32-safe profile for {value_bits} bits")


# ---------------------------------------------------------------------------
# host <-> limb conversion
# ---------------------------------------------------------------------------


def to_limbs(x: int, prof: LimbProfile, n_limbs: int | None = None) -> np.ndarray:
    n = n_limbs or prof.n_limbs
    assert 0 <= x < 1 << (prof.bits * n), "value exceeds limb capacity"
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & prof.mask
        x >>= prof.bits
    return out


def from_limbs(limbs, prof: LimbProfile) -> int:
    arr = np.asarray(limbs)
    assert arr.ndim == 1, "from_limbs is host-side, single value"
    acc = 0
    for i in range(arr.shape[0] - 1, -1, -1):
        acc = (acc << prof.bits) + int(arr[i])
    return acc


def batch_to_limbs(xs, prof: LimbProfile, n_limbs: int | None = None) -> np.ndarray:
    return np.stack([to_limbs(x, prof, n_limbs) for x in xs])


def batch_from_limbs(arr, prof: LimbProfile) -> list:
    a = np.asarray(arr)
    flat = a.reshape(-1, a.shape[-1])
    return [from_limbs(row, prof) for row in flat]


# ---------------------------------------------------------------------------
# carries / add / compare
# ---------------------------------------------------------------------------


def carry(x: jnp.ndarray, prof: LimbProfile) -> jnp.ndarray:
    """Full carry propagation → normalized limbs, same shape.

    Valid for any int32 limb values (including negative intermediates from
    borrow-style subtraction) as long as the represented *total* is
    non-negative and fits the limb count: the arithmetic right-shift
    implements floor division, so negative limbs borrow correctly. Carry out
    of the top limb is dropped (callers size tensors so it never occurs,
    or deliberately exploit the mod-radix^n semantics).
    """
    bits = prof.bits

    def step(c, limb):
        t = limb + c
        return t >> bits, t & prof.mask

    _, out = lax.scan(
        step, jnp.zeros(x.shape[:-1], jnp.int32), jnp.moveaxis(x, -1, 0)
    )
    return jnp.moveaxis(out, 0, -1)


def compare(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic compare of normalized values: -1 / 0 / +1 (int32)."""
    diff = jnp.sign(x - y)

    def step(acc, d):
        return jnp.where(acc == 0, d, acc), None

    acc, _ = lax.scan(
        step,
        jnp.zeros(x.shape[:-1], jnp.int32),
        jnp.moveaxis(diff, -1, 0),
        reverse=True,
    )
    return acc


def cond_sub(x: jnp.ndarray, m: jnp.ndarray, prof: LimbProfile) -> jnp.ndarray:
    """If x ≥ m: x - m, else x. Normalized in/out, same width."""
    ge = compare(x, m) >= 0
    return jnp.where(ge[..., None], carry(x - m, prof), x)


def pad_limbs(x: jnp.ndarray, extra: int) -> jnp.ndarray:
    """Append ``extra`` zero limbs at the most-significant end."""
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, extra)])


def shift_limbs(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by radix^k (prepend k zero limbs at the little end)."""
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(k, 0)])


def take_limbs(x: jnp.ndarray, start: int, count: int) -> jnp.ndarray:
    """Limbs [start, start+count), zero-padded past the top."""
    n = x.shape[-1]
    if start >= n:
        return jnp.zeros(x.shape[:-1] + (count,), x.dtype)
    sl = x[..., start : min(n, start + count)]
    pad = count - sl.shape[-1]
    if pad:
        sl = pad_limbs(sl, pad)
    return sl


# ---------------------------------------------------------------------------
# device-side byte/bit packing (wire format <-> limbs without host round-trip)
# ---------------------------------------------------------------------------


def bytes_to_limbs_le(b: jnp.ndarray, prof: LimbProfile, n_limbs: int) -> jnp.ndarray:
    """(..., n_bytes) uint8 little-endian → (..., n_limbs) normalized limbs.

    Batched wire decode: round payloads arrive as fixed-shape byte tensors
    (the TPU-native envelope) and are unpacked on device. Truncates or
    zero-extends to the requested limb count.
    """
    n_bytes = b.shape[-1]
    bit_idx = jnp.arange(8, dtype=jnp.int32)
    bits = (b[..., :, None].astype(jnp.int32) >> bit_idx) & 1  # (..., nB, 8)
    bits = bits.reshape(b.shape[:-1] + (n_bytes * 8,))
    want = n_limbs * prof.bits
    if bits.shape[-1] < want:
        bits = jnp.pad(bits, [(0, 0)] * (bits.ndim - 1) + [(0, want - bits.shape[-1])])
    else:
        bits = bits[..., :want]
    groups = bits.reshape(bits.shape[:-1] + (n_limbs, prof.bits))
    weights = (1 << jnp.arange(prof.bits, dtype=jnp.int32))
    return jnp.sum(groups * weights, axis=-1).astype(jnp.int32)


def limbs_to_bytes_le(x: jnp.ndarray, prof: LimbProfile, n_bytes: int) -> jnp.ndarray:
    """Normalized limbs → (..., n_bytes) uint8 little-endian (wire encode)."""
    bit_idx = jnp.arange(prof.bits, dtype=jnp.int32)
    bits = (x[..., :, None] >> bit_idx) & 1  # (..., n, bits)
    bits = bits.reshape(x.shape[:-1] + (x.shape[-1] * prof.bits,))
    want = n_bytes * 8
    if bits.shape[-1] < want:
        bits = jnp.pad(bits, [(0, 0)] * (bits.ndim - 1) + [(0, want - bits.shape[-1])])
    else:
        bits = bits[..., :want]
    by = bits.reshape(bits.shape[:-1] + (n_bytes, 8))
    return jnp.sum(by << jnp.arange(8, dtype=jnp.int32), axis=-1).astype(jnp.uint8)


def limbs_to_bits(x: jnp.ndarray, prof: LimbProfile, n_bits: int) -> jnp.ndarray:
    """Normalized limbs → (..., n_bits) int32 bit vector, LSB first (the
    input format of the scalar-mult ladders)."""
    bit_idx = jnp.arange(prof.bits, dtype=jnp.int32)
    bits = (x[..., :, None] >> bit_idx) & 1
    bits = bits.reshape(x.shape[:-1] + (x.shape[-1] * prof.bits,))
    if bits.shape[-1] < n_bits:
        return jnp.pad(
            bits, [(0, 0)] * (bits.ndim - 1) + [(0, n_bits - bits.shape[-1])]
        )
    return bits[..., :n_bits]


# ---------------------------------------------------------------------------
# multiplication
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _conv_tensor(n_a: int, n_b: int) -> np.ndarray:
    """One-hot (n_a, n_b, n_a+n_b-1) tensor M with M[i,j,i+j] = 1."""
    m = np.zeros((n_a, n_b, n_a + n_b - 1), dtype=np.int32)
    for i in range(n_a):
        for j in range(n_b):
            m[i, j, i + j] = 1
    return m


def mul(x: jnp.ndarray, y: jnp.ndarray, prof: LimbProfile) -> jnp.ndarray:
    """Schoolbook product → normalized (..., n_x + n_y) limbs.

    Inputs must be normalized (limb < radix) so column sums fit int32.
    """
    n_x, n_y = x.shape[-1], y.shape[-1]
    m = jnp.asarray(_conv_tensor(n_x, n_y))
    cols = jnp.einsum("...i,...j,ijn->...n", x, y, m)
    return carry(pad_limbs(cols, 1), prof)


def mul_small(x: jnp.ndarray, k: int, prof: LimbProfile) -> jnp.ndarray:
    """x * k for a small python-int constant 0 ≤ k with k·radix < 2^31;
    one extra output limb."""
    assert 0 <= k * prof.radix < 2**31
    return carry(pad_limbs(x * k, 1), prof)


# ---------------------------------------------------------------------------
# wide multiplication (Paillier-sized operands)
# ---------------------------------------------------------------------------
#
# The one-hot conv tensor of :func:`mul` is O(n²·2n) memory — fine for 22
# limbs, hopeless for the 373-limb (4096-bit) Paillier domain. Wide values
# multiply block-wise instead: split each operand into 32-limb blocks, form
# all pairwise block products with the small conv tensor (an einsum XLA maps
# to batched matmul), then overlap-add block products at their limb offsets.
# Column bounds (11-bit limbs): 32·(2^11-1)² ≈ 1.3e8 per block product,
# ≤ 12 block pairs per output block at 4096 bits → < 1.7e9 < 2^31. Larger
# operand widths need a smaller radix via :func:`profile_for_bits`.

_BLOCK = 32


def _ceil_blocks(n: int) -> int:
    return -(-n // _BLOCK)


def mul_wide(x: jnp.ndarray, y: jnp.ndarray, prof: LimbProfile) -> jnp.ndarray:
    """Schoolbook product for wide operands → normalized (..., n_x + n_y)
    limbs. Inputs normalized; blocked into 32-limb chunks internally."""
    n_x, n_y = x.shape[-1], y.shape[-1]
    bx, by = _ceil_blocks(n_x), _ceil_blocks(n_y)
    # int32 column bound: ≤ min(bx, by) block pairs per output block
    assert min(bx, by) * _BLOCK * prof.mask**2 < 2**31, (
        "limb radix too large for blocked accumulation at this width — "
        "use profile_for_bits"
    )
    xb = take_limbs(x, 0, bx * _BLOCK).reshape(x.shape[:-1] + (bx, _BLOCK))
    yb = take_limbs(y, 0, by * _BLOCK).reshape(y.shape[:-1] + (by, _BLOCK))
    m = jnp.asarray(_conv_tensor(_BLOCK, _BLOCK))  # (32, 32, 63)
    # all pairwise block products: (..., bx, by, 63)
    prods = jnp.einsum("...ui,...vj,ijn->...uvn", xb, yb, m)
    # overlap-add: block (u, v) lands at limb offset 32(u+v). Split each
    # 63-limb product into low 32 + high 31 and scatter both halves onto the
    # block grid via one-hot block-conv tensors.
    bt = bx + by - 1
    blk = jnp.asarray(_conv_tensor(bx, by))  # (bx, by, bt)
    lo = jnp.einsum("...uvn,uvt->...tn", prods[..., :_BLOCK], blk)
    hi = jnp.einsum("...uvn,uvt->...tn", prods[..., _BLOCK:], blk)
    hi = jnp.pad(hi, [(0, 0)] * (hi.ndim - 1) + [(0, 1)])  # 31 → 32 limbs
    out_limbs = (bt + 1) * _BLOCK
    lo_flat = jnp.pad(
        lo.reshape(lo.shape[:-2] + (bt * _BLOCK,)),
        [(0, 0)] * (lo.ndim - 2) + [(0, _BLOCK)],
    )
    hi_flat = jnp.pad(
        hi.reshape(hi.shape[:-2] + (bt * _BLOCK,)),
        [(0, 0)] * (hi.ndim - 2) + [(_BLOCK, 0)],
    )
    # normalize halves separately first: their raw column sums can each
    # approach 2^31, so adding before a carry would overflow int32
    total = carry(carry(lo_flat, prof) + carry(hi_flat, prof), prof)
    assert out_limbs >= n_x + n_y
    return total[..., : n_x + n_y]


def mul_auto(x: jnp.ndarray, y: jnp.ndarray, prof: LimbProfile) -> jnp.ndarray:
    """Dispatch to the dense conv product (narrow) or blocked product (wide)."""
    if max(x.shape[-1], y.shape[-1]) > 2 * _BLOCK:
        return mul_wide(x, y, prof)
    return mul(x, y, prof)


# ---------------------------------------------------------------------------
# Barrett reduction (generic modulus)
# ---------------------------------------------------------------------------


class BarrettCtx:
    """Precomputed Barrett context for a fixed modulus m with
    radix^(n-1) ≤ m < radix^n (top limb in use).

    reduce(x) maps normalized x < radix^(2n) (≤ 2n limbs) to x mod m using
    the classic estimate  q̂ = floor(floor(x / r^(n-1)) · mu / r^(n+1)),
    mu = floor(r^(2n) / m);  q̂ ∈ [q-2, q], fixed by two conditional
    subtractions.

    Used for the curve scalar rings (ed25519 l, secp256k1 n) and as the
    generic engine behind Paillier arithmetic; the two field primes also have
    faster pseudo-Mersenne folds in ``core.fields``.
    """

    def __init__(self, modulus: int, prof: LimbProfile = P256):
        n = prof.n_limbs
        assert prof.radix ** (n - 1) <= modulus < prof.radix**n, (
            "modulus must occupy the top limb for Barrett"
        )
        self.prof = prof
        self.modulus = modulus
        self.m_limbs = to_limbs(modulus, prof)
        self.m_limbs_p1 = to_limbs(modulus, prof, n_limbs=n + 1)
        mu = (1 << (2 * n * prof.bits)) // modulus
        self.mu_limbs = to_limbs(mu, prof, n_limbs=n + 2)

    # -- core ---------------------------------------------------------------

    def reduce(self, x: jnp.ndarray) -> jnp.ndarray:
        """x (normalized, any width ≤ 2n limbs) → x mod m (n limbs, canonical)."""
        prof, n = self.prof, self.prof.n_limbs
        batch = x.shape[:-1]
        m1 = jnp.broadcast_to(jnp.asarray(self.m_limbs_p1), batch + (n + 1,))
        mu = jnp.broadcast_to(jnp.asarray(self.mu_limbs), batch + (n + 2,))

        q1 = take_limbs(x, n - 1, n + 1)  # floor(x / r^(n-1))
        q2 = mul_auto(q1, mu, prof)  # (n+1)+(n+2) limbs
        q3 = take_limbs(q2, n + 1, n + 1)  # floor(q2 / r^(n+1))
        q3m = mul_auto(q3, m1, prof)

        # r = (x mod r^(n+1)) - (q3·m mod r^(n+1)), then + r^(n+1) to keep the
        # integer total positive; carry over n+2 limbs and drop limb n+1 (the
        # mod). True r = x - q3·m ∈ [0, 3m) ⊂ [0, r^(n+1)), so the result is
        # exact (HAC Alg. 14.42).
        t = pad_limbs(take_limbs(x, 0, n + 1) - take_limbs(q3m, 0, n + 1), 1)
        t = t.at[..., n + 1].add(1)
        r = carry(t, prof)[..., : n + 1]
        r = cond_sub(r, m1, prof)
        r = cond_sub(r, m1, prof)
        return r[..., :n]

    # -- ring ops -----------------------------------------------------------

    def mulmod(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        return self.reduce(mul_auto(a, b, self.prof))

    def addmod(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        n = self.prof.n_limbs
        s = carry(pad_limbs(a + b, 1), self.prof)  # < 2m, n+1 limbs
        m1 = jnp.broadcast_to(jnp.asarray(self.m_limbs_p1), s.shape)
        return cond_sub(s, m1, self.prof)[..., :n]

    def submod(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        n = self.prof.n_limbs
        m1 = jnp.broadcast_to(
            jnp.asarray(self.m_limbs_p1), a.shape[:-1] + (n + 1,)
        )
        d = carry(m1 + pad_limbs(a, 1) - pad_limbs(b, 1), self.prof)  # a-b+m ∈ (0, 2m)
        return cond_sub(d, m1, self.prof)[..., :n]

    def negmod(self, a: jnp.ndarray) -> jnp.ndarray:
        return self.submod(jnp.zeros_like(a), a)

    def powmod_const(self, x: jnp.ndarray, exponent: int) -> jnp.ndarray:
        """x^e mod m for a python-int constant exponent ≥ 0 (left-to-right
        square & multiply as one lax.scan over the exponent bits)."""
        if exponent == 0:
            return self.one_like(x)
        ebits = jnp.asarray(
            [(exponent >> i) & 1 for i in range(exponent.bit_length())][::-1],
            dtype=jnp.int32,
        )
        one = self.one_like(x)

        def step(acc, bit):
            acc = self.mulmod(acc, acc)
            acc = jnp.where(bit > 0, self.mulmod(acc, x), acc)
            return acc, None

        acc, _ = lax.scan(step, one, ebits)
        return acc

    def invmod_prime(self, x: jnp.ndarray) -> jnp.ndarray:
        """Batched modular inverse via Fermat — prime modulus only."""
        return self.powmod_const(x, self.modulus - 2)

    def powmod(self, x: jnp.ndarray, ebits: jnp.ndarray) -> jnp.ndarray:
        """x^e mod m with *per-element* exponents: ``ebits`` (..., n_bits)
        int32 LSB-first (see :func:`limbs_to_bits`). Right-to-left binary:
        two mulmods per bit, batched. The workhorse of Paillier homomorphic
        scalar-mul and ZK-proof responses, where exponents vary by session."""
        one = self.one_like(x)

        def step(acc_base, bit):
            acc, base = acc_base
            acc = jnp.where((bit > 0)[..., None], self.mulmod(acc, base), acc)
            return (acc, self.mulmod(base, base)), None

        (acc, _), _ = lax.scan(step, (one, x), jnp.moveaxis(ebits, -1, 0))
        return acc

    def powmod_fixed_base(self, base: int, ebits: jnp.ndarray) -> jnp.ndarray:
        """base^e mod m for a python-int base with per-element exponents.
        Precomputes the base^(2^i) table host-side (cached per base/width)
        → one mulmod per bit (half the device work of :meth:`powmod`)."""
        n_bits = ebits.shape[-1]
        cache = getattr(self, "_fb_tables", None)
        if cache is None:
            cache = self._fb_tables = {}
        tbl = cache.get((base, n_bits))
        if tbl is None:
            tbl = np.empty((n_bits, self.prof.n_limbs), dtype=np.int32)
            b = base % self.modulus
            for i in range(n_bits):
                tbl[i] = to_limbs(b, self.prof)
                b = b * b % self.modulus
            cache[(base, n_bits)] = tbl
        one = self.one_like(ebits)  # one_like only uses the batch shape

        def step(acc, sl):
            bit, t = sl
            t = jnp.broadcast_to(t, acc.shape)
            return jnp.where((bit > 0)[..., None], self.mulmod(acc, t), acc), None

        acc, _ = lax.scan(
            step, one, (jnp.moveaxis(ebits, -1, 0), jnp.asarray(tbl))
        )
        return acc

    # -- helpers ------------------------------------------------------------

    def one_like(self, x: jnp.ndarray) -> jnp.ndarray:
        return (
            jnp.zeros(x.shape[:-1] + (self.prof.n_limbs,), jnp.int32)
            .at[..., 0]
            .set(1)
        )

    def const(self, value: int, batch_shape=()) -> jnp.ndarray:
        v = jnp.asarray(to_limbs(value % self.modulus, self.prof))
        return jnp.broadcast_to(v, tuple(batch_shape) + (self.prof.n_limbs,))
