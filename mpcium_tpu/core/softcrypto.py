"""Pure-Python stand-ins for the `cryptography` package (bare environments).

The control-plane crypto (envelope auth, share-store sealing, broker
channel) normally rides OpenSSL via `cryptography`. CI containers and
minimal deploys do not always carry that wheel, and a missing optional
dependency must degrade to a slower implementation — never to an
ImportError that kills test collection (ISSUE 3 satellite). This module
implements the exact API subset the repo uses, written from the public
specs:

- Ed25519 sign/verify (RFC 8032) — delegating to :mod:`.hostmath`, the
  repo's existing from-scratch implementation;
- ChaCha20-Poly1305 AEAD (RFC 8439);
- X25519 (RFC 7748);
- HKDF-SHA256 (RFC 5869);
- the tiny `serialization` surface identity.py touches (Raw encodings).

Class and exception names mirror `cryptography` so call sites can do
``try: from cryptography... except ImportError: from ..core.softcrypto
import ...`` and run unchanged. All of it is validated against the RFCs'
test vectors in tests/test_softcrypto.py. Throughput is pure-Python
(≈MB/s, not GB/s): fine for envelopes, key files and broker frames; a
production deployment that moves bulk data should install `cryptography`.
"""
from __future__ import annotations

import hashlib
import hmac as _hmac
import secrets
import struct
from typing import Optional

from . import hostmath as _hm

HAVE_OPENSSL = False  # marker: this is the fallback implementation


class InvalidSignature(Exception):
    """cryptography.exceptions.InvalidSignature equivalent."""


class InvalidTag(Exception):
    """cryptography.exceptions.InvalidTag equivalent (AEAD auth failure)."""


# ---------------------------------------------------------------------------
# serialization shim (identity.py only ever uses Raw/Raw/NoEncryption)
# ---------------------------------------------------------------------------


class _Sentinel:
    def __init__(self, name: str):
        self.name = name

    def __repr__(self):  # pragma: no cover - debugging nicety
        return f"<softcrypto.{self.name}>"

    def __call__(self):
        return self


class serialization:  # noqa: N801 — mirrors the cryptography module name
    class Encoding:
        Raw = _Sentinel("Encoding.Raw")

    class PrivateFormat:
        Raw = _Sentinel("PrivateFormat.Raw")

    class PublicFormat:
        Raw = _Sentinel("PublicFormat.Raw")

    class NoEncryption:
        def __init__(self):
            pass


# ---------------------------------------------------------------------------
# Ed25519 (RFC 8032) over hostmath's from-scratch curve ops
# ---------------------------------------------------------------------------


class Ed25519PublicKey:
    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("Ed25519 public key must be 32 bytes")
        self._raw = bytes(raw)

    @classmethod
    def from_public_bytes(cls, data: bytes) -> "Ed25519PublicKey":
        return cls(data)

    def public_bytes(self, encoding=None, format=None) -> bytes:  # noqa: A002
        return self._raw

    def public_bytes_raw(self) -> bytes:
        return self._raw

    def verify(self, signature: bytes, data: bytes) -> None:
        if not _hm.ed25519_verify(self._raw, data, signature):
            raise InvalidSignature("ed25519 signature mismatch")


class Ed25519PrivateKey:
    def __init__(self, seed: bytes):
        if len(seed) != 32:
            raise ValueError("Ed25519 private key must be 32 bytes")
        self._seed = bytes(seed)
        self._pub = _hm.ed25519_public_from_seed(self._seed)

    @classmethod
    def generate(cls) -> "Ed25519PrivateKey":
        return cls(secrets.token_bytes(32))

    @classmethod
    def from_private_bytes(cls, data: bytes) -> "Ed25519PrivateKey":
        return cls(data)

    def private_bytes(self, encoding=None, format=None, encryption_algorithm=None) -> bytes:  # noqa: A002,E501
        return self._seed

    def private_bytes_raw(self) -> bytes:
        return self._seed

    def sign(self, data: bytes) -> bytes:
        return _hm.ed25519_sign_plain(self._seed, data)

    def public_key(self) -> Ed25519PublicKey:
        return Ed25519PublicKey(self._pub)


# ---------------------------------------------------------------------------
# ChaCha20-Poly1305 AEAD (RFC 8439)
# ---------------------------------------------------------------------------

_MASK32 = 0xFFFFFFFF


def _rotl32(v: int, c: int) -> int:
    return ((v << c) | (v >> (32 - c))) & _MASK32


def _chacha20_block(key_words, counter: int, nonce_words) -> bytes:
    x = [
        0x61707865, 0x3320646E, 0x79622D32, 0x6B206574,
        *key_words,
        counter & _MASK32, *nonce_words,
    ]
    s = list(x)
    for _ in range(10):  # 20 rounds = 10 column+diagonal double rounds
        for a, b, c, d in (
            (0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14), (3, 7, 11, 15),
            (0, 5, 10, 15), (1, 6, 11, 12), (2, 7, 8, 13), (3, 4, 9, 14),
        ):
            s[a] = (s[a] + s[b]) & _MASK32
            s[d] = _rotl32(s[d] ^ s[a], 16)
            s[c] = (s[c] + s[d]) & _MASK32
            s[b] = _rotl32(s[b] ^ s[c], 12)
            s[a] = (s[a] + s[b]) & _MASK32
            s[d] = _rotl32(s[d] ^ s[a], 8)
            s[c] = (s[c] + s[d]) & _MASK32
            s[b] = _rotl32(s[b] ^ s[c], 7)
    return struct.pack("<16I", *((s[i] + x[i]) & _MASK32 for i in range(16)))


def _chacha20_xor(key: bytes, counter: int, nonce: bytes, data: bytes) -> bytes:
    key_words = struct.unpack("<8I", key)
    nonce_words = struct.unpack("<3I", nonce)
    out = bytearray(len(data))
    for i in range(0, len(data), 64):
        block = _chacha20_block(key_words, counter + i // 64, nonce_words)
        chunk = data[i:i + 64]
        out[i:i + len(chunk)] = bytes(
            a ^ b for a, b in zip(chunk, block)
        )
    return bytes(out)


_P1305 = (1 << 130) - 5


def _poly1305(key32: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key32[:16], "little") & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key32[16:32], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        chunk = msg[i:i + 16]
        n = int.from_bytes(chunk, "little") + (1 << (8 * len(chunk)))
        acc = ((acc + n) * r) % _P1305
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(data: bytes) -> bytes:
    rem = len(data) % 16
    return b"\x00" * (16 - rem) if rem else b""


class ChaCha20Poly1305:
    """RFC 8439 AEAD construction; API-compatible with
    cryptography.hazmat.primitives.ciphers.aead.ChaCha20Poly1305."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = bytes(key)

    def _tag(self, nonce: bytes, ct: bytes, aad: bytes) -> bytes:
        otk = _chacha20_block(
            struct.unpack("<8I", self._key), 0, struct.unpack("<3I", nonce)
        )[:32]
        mac_data = (
            aad + _pad16(aad) + ct + _pad16(ct)
            + struct.pack("<QQ", len(aad), len(ct))
        )
        return _poly1305(otk, mac_data)

    def encrypt(self, nonce: bytes, data: bytes, associated_data: Optional[bytes]) -> bytes:  # noqa: E501
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        aad = associated_data or b""
        ct = _chacha20_xor(self._key, 1, nonce, data)
        return ct + self._tag(nonce, ct, aad)

    def decrypt(self, nonce: bytes, data: bytes, associated_data: Optional[bytes]) -> bytes:  # noqa: E501
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        if len(data) < 16:
            raise InvalidTag("ciphertext shorter than the Poly1305 tag")
        aad = associated_data or b""
        ct, tag = data[:-16], data[-16:]
        if not _hmac.compare_digest(self._tag(nonce, ct, aad), tag):
            raise InvalidTag("AEAD authentication failed")
        return _chacha20_xor(self._key, 1, nonce, ct)


# ---------------------------------------------------------------------------
# X25519 (RFC 7748)
# ---------------------------------------------------------------------------

_X25519_P = 2**255 - 19
_X25519_A24 = 121665


def _x25519_scalarmult(k: bytes, u: bytes) -> bytes:
    # decodeScalar25519 + decodeUCoordinate (RFC 7748 §5)
    ki = int.from_bytes(k, "little")
    ki &= ~(7) & ((1 << 256) - 1)
    ki &= (1 << 254) - 1
    ki |= 1 << 254
    x1 = int.from_bytes(u, "little") & ((1 << 255) - 1)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    p = _X25519_P
    for t in range(254, -1, -1):
        k_t = (ki >> t) & 1
        if swap ^ k_t:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        A = (x2 + z2) % p
        AA = A * A % p
        B = (x2 - z2) % p
        BB = B * B % p
        E = (AA - BB) % p
        C = (x3 + z3) % p
        D = (x3 - z3) % p
        DA = D * A % p
        CB = C * B % p
        x3 = (DA + CB) % p
        x3 = x3 * x3 % p
        z3 = (DA - CB) % p
        z3 = x1 * (z3 * z3 % p) % p
        x2 = AA * BB % p
        z2 = E * (AA + _X25519_A24 * E) % p
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return (x2 * pow(z2, p - 2, p) % p).to_bytes(32, "little")


_X25519_BASE = (9).to_bytes(32, "little")


class X25519PublicKey:
    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("X25519 public key must be 32 bytes")
        self._raw = bytes(raw)

    @classmethod
    def from_public_bytes(cls, data: bytes) -> "X25519PublicKey":
        return cls(data)

    def public_bytes_raw(self) -> bytes:
        return self._raw

    def public_bytes(self, encoding=None, format=None) -> bytes:  # noqa: A002
        return self._raw


class X25519PrivateKey:
    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("X25519 private key must be 32 bytes")
        self._raw = bytes(raw)

    @classmethod
    def generate(cls) -> "X25519PrivateKey":
        return cls(secrets.token_bytes(32))

    @classmethod
    def from_private_bytes(cls, data: bytes) -> "X25519PrivateKey":
        return cls(data)

    def public_key(self) -> X25519PublicKey:
        return X25519PublicKey(_x25519_scalarmult(self._raw, _X25519_BASE))

    def exchange(self, peer_public_key: X25519PublicKey) -> bytes:
        ss = _x25519_scalarmult(self._raw, peer_public_key.public_bytes_raw())
        if ss == b"\x00" * 32:
            # RFC 7748 §6.1: all-zero output means a low-order point
            raise ValueError("X25519 exchange produced the all-zero value")
        return ss


# ---------------------------------------------------------------------------
# HKDF-SHA256 (RFC 5869) — only the (salt, info, length).derive(ikm) shape
# the broker channel uses
# ---------------------------------------------------------------------------


class SHA256:
    """Algorithm marker matching cryptography's hashes.SHA256."""

    digest_size = 32
    name = "sha256"


class HKDF:
    def __init__(self, algorithm=None, length: int = 32,
                 salt: Optional[bytes] = None, info: Optional[bytes] = None):
        if length > 255 * 32:
            raise ValueError("HKDF-SHA256 output too long")
        self._length = length
        self._salt = salt or b"\x00" * 32
        self._info = info or b""

    def derive(self, key_material: bytes) -> bytes:
        prk = _hmac.new(self._salt, key_material, hashlib.sha256).digest()
        okm = b""
        t = b""
        i = 1
        while len(okm) < self._length:
            t = _hmac.new(
                prk, t + self._info + bytes([i]), hashlib.sha256
            ).digest()
            okm += t
            i += 1
        return okm[: self._length]
