"""Paillier cryptosystem + GG18 pre-parameters.

The reference's entire Paillier stack lives in tss-lib (ECDSA keygen round 1
broadcasts each party's Paillier pubkey; the MtA share-conversion in signing
is Paillier-homomorphic arithmetic — SURVEY.md §2.3). Pre-parameters
(`keygen.GeneratePreParams`, reference pkg/mpc/node.go:69) are the expensive
startup artifact: a Paillier keypair plus the ring-Pedersen modulus
NTilde = P·Q (safe primes) with bases h1, h2 used by the MtA range proofs.

Split of labor (SURVEY.md §7.2 step 3):
- key/prime generation: host-side python-int (safe-prime search is
  branch-heavy trial division — hostile to XLA; the reference also runs it
  on CPU at startup with a 5-minute budget). A pool file amortizes it.
- encrypt/decrypt/homomorphic ops: host reference implementation here, and
  *batched device kernels* in :class:`PaillierBatch` — fixed-shape modexps
  over the session axis, the dominant GG18 signing cost.

Limb layout: one radix (11-bit limbs) across the 2048-bit (mod N, mod
NTilde) and 4096-bit (mod N²) domains so values move between them by
zero-padding, no repacking.
"""
from __future__ import annotations

import functools
import math
import secrets
from dataclasses import dataclass
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from . import bignum as bn

# one radix family for all Paillier-domain arithmetic
PROF_2048 = bn.LimbProfile(bits=11, n_limbs=187)  # capacity 2057 bits
PROF_4096 = bn.LimbProfile(bits=11, n_limbs=373)  # capacity 4103 bits

PAILLIER_BITS = 2048


# ---------------------------------------------------------------------------
# host primality / prime generation
# ---------------------------------------------------------------------------

_SMALL_PRIMES = [p for p in range(3, 1000) if all(p % d for d in range(2, p))]


def is_probable_prime(n: int, rounds: int = 30, rng=secrets) -> bool:
    """Miller–Rabin with random bases (error ≤ 4^-rounds)."""
    if n < 2:
        return False
    for p in _SMALL_PRIMES:
        if n == p:
            return True
        if n % p == 0:
            return False
    d, r = n - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    for _ in range(rounds):
        a = rng.randbelow(n - 3) + 2
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = x * x % n
            if x == n - 1:
                break
        else:
            return False
    return True


def gen_prime(bits: int, rng=secrets) -> int:
    while True:
        c = rng.randbits(bits) | (1 << (bits - 1)) | 1
        if is_probable_prime(c, rng=rng):
            return c


def gen_safe_prime(bits: int, rng=secrets) -> int:
    """p = 2q+1 with q prime. Sieve on both p and q before Miller–Rabin."""
    while True:
        q = rng.randbits(bits - 1) | (1 << (bits - 2)) | 1
        p = 2 * q + 1
        if any(q % s == 0 or p % s == 0 for s in _SMALL_PRIMES):
            continue
        # cheap base-2 Fermat screens before full MR
        if pow(2, q - 1, q) != 1:
            continue
        if pow(2, p - 1, p) != 1:
            continue
        if is_probable_prime(q, rng=rng) and is_probable_prime(p, rng=rng):
            return p


# ---------------------------------------------------------------------------
# Paillier keys (host)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PaillierPublicKey:
    N: int

    @property
    def N2(self) -> int:
        return self.N * self.N

    @property
    def g(self) -> int:  # standard g = N + 1
        return self.N + 1

    def encrypt(self, m: int, r: Optional[int] = None, rng=secrets) -> int:
        assert 0 <= m < self.N
        if r is None:
            while True:
                r = rng.randbelow(self.N)
                if r and math.gcd(r, self.N) == 1:
                    break
        # (1+N)^m = 1 + mN (mod N²)
        return (1 + m * self.N) % self.N2 * pow(r, self.N, self.N2) % self.N2

    def add(self, c1: int, c2: int) -> int:
        return c1 * c2 % self.N2

    def scalar_mul(self, c: int, k: int) -> int:
        return pow(c, k, self.N2)

    def to_json(self) -> dict:
        return {"N": str(self.N)}

    @classmethod
    def from_json(cls, d: dict) -> "PaillierPublicKey":
        return cls(N=int(d["N"]))


@dataclass(frozen=True)
class PaillierPrivateKey:
    p: int
    q: int

    @property
    def public(self) -> PaillierPublicKey:
        return PaillierPublicKey(self.p * self.q)

    @property
    def N(self) -> int:
        return self.p * self.q

    @functools.cached_property
    def lam(self) -> int:  # λ = lcm(p-1, q-1)
        return (self.p - 1) * (self.q - 1) // math.gcd(self.p - 1, self.q - 1)

    @functools.cached_property
    def mu(self) -> int:  # μ = (L(g^λ mod N²))⁻¹ mod N
        N = self.N
        u = pow(N + 1, self.lam, N * N)
        return pow((u - 1) // N, -1, N)

    def decrypt(self, c: int) -> int:
        N = self.N
        u = pow(c, self.lam, N * N)
        return (u - 1) // N * self.mu % N

    def to_json(self) -> dict:
        return {"p": str(self.p), "q": str(self.q)}

    @classmethod
    def from_json(cls, d: dict) -> "PaillierPrivateKey":
        return cls(p=int(d["p"]), q=int(d["q"]))


def gen_paillier_key(bits: int = PAILLIER_BITS, rng=secrets) -> PaillierPrivateKey:
    """Distinct primes p≠q with N exactly ``bits`` bits."""
    half = bits // 2
    while True:
        p = gen_prime(half, rng)
        q = gen_prime(half, rng)
        if p != q and (p * q).bit_length() == bits:
            return PaillierPrivateKey(p=min(p, q), q=max(p, q))


# ---------------------------------------------------------------------------
# safe-prime pool (amortizes the startup search; reference budget is 5 min,
# node.go:69 — a pool file makes restarts instant)
# ---------------------------------------------------------------------------


import contextlib


@contextlib.contextmanager
def _pool_lock(path):
    """Exclusive flock guarding pool read-modify-write: two daemons sharing
    one pool path must never consume the SAME safe primes (shared NTilde
    factors let each forge the other's MtA range proofs). The lock file and
    the pool itself are 0600 — the pool holds future secret NTilde factors,
    same sensitivity as identity keys."""
    import fcntl
    import os

    lock_path = str(path) + ".lock"
    fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o600)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)


def _pool_write(path, data) -> None:
    import json
    import os

    tmp = str(path) + ".tmp"
    fd = os.open(tmp, os.O_CREAT | os.O_TRUNC | os.O_WRONLY, 0o600)
    with os.fdopen(fd, "w") as f:
        json.dump(data, f)
    os.replace(tmp, path)


def pool_take(path, count: int = 2, bits: int = 1024, rng=secrets) -> list:
    """Pop ``count`` safe primes from a JSON pool file ({"bits", "safe_primes":
    [str]}), generating fresh ones when the pool is short. The file is
    rewritten without the consumed primes (a prime must never be reused
    across NTilde moduli); an exclusive flock serializes concurrent takers.
    Missing file ⇒ all primes generated fresh."""
    import json
    import os

    primes: list = []
    if path is not None and os.path.exists(path):
        with _pool_lock(path):
            data = json.load(open(path))
            assert data.get("bits", bits) == bits, "pool bit-size mismatch"
            avail = [int(p) for p in data.get("safe_primes", [])]
            take, rest = avail[:count], avail[count:]
            primes.extend(take)
            if take:
                data["safe_primes"] = [str(p) for p in rest]
                _pool_write(path, data)
    while len(primes) < count:
        primes.append(gen_safe_prime(bits, rng))
    return primes


def pool_fill(path, target: int, bits: int = 1024, rng=secrets) -> int:
    """Top the pool file up to ``target`` primes; returns how many were
    generated. Run from a background thread / cron on production nodes.
    Prime search happens outside the lock; each append re-takes it."""
    import json
    import os

    made = 0
    while True:
        with _pool_lock(path):
            data = {"bits": bits, "safe_primes": []}
            if os.path.exists(path):
                data = json.load(open(path))
                assert data.get("bits", bits) == bits
            if len(data["safe_primes"]) >= target:
                return made
        p = gen_safe_prime(bits, rng)
        with _pool_lock(path):
            data = {"bits": bits, "safe_primes": []}
            if os.path.exists(path):
                data = json.load(open(path))
                assert data.get("bits", bits) == bits, "pool bit-size mismatch"
            data["safe_primes"].append(str(p))
            _pool_write(path, data)
        made += 1


# ---------------------------------------------------------------------------
# GG18 pre-parameters (ring-Pedersen / NTilde)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PreParams:
    """Per-node startup artifact (reference node.go:69 GeneratePreParams):
    Paillier key + ring-Pedersen parameters for MtA range proofs.
    ``alpha``/``beta`` are the secret dlogs (h2 = h1^alpha, h1 = h2^beta
    mod NTilde) needed to produce the DLN proofs exchanged in keygen."""

    paillier: PaillierPrivateKey
    NTilde: int
    h1: int
    h2: int
    alpha: int
    beta: int
    # safe-prime factors of NTilde (kept for possible proof extensions)
    P: int
    Q: int

    def to_json(self) -> dict:
        return {
            "paillier": self.paillier.to_json(),
            "NTilde": str(self.NTilde),
            "h1": str(self.h1),
            "h2": str(self.h2),
            "alpha": str(self.alpha),
            "beta": str(self.beta),
            "P": str(self.P),
            "Q": str(self.Q),
        }

    @classmethod
    def from_json(cls, d: dict) -> "PreParams":
        return cls(
            paillier=PaillierPrivateKey.from_json(d["paillier"]),
            NTilde=int(d["NTilde"]),
            h1=int(d["h1"]),
            h2=int(d["h2"]),
            alpha=int(d["alpha"]),
            beta=int(d["beta"]),
            P=int(d["P"]),
            Q=int(d["Q"]),
        )


def gen_preparams(
    bits: int = PAILLIER_BITS,
    rng=secrets,
    safe_primes: Optional[Tuple[int, int]] = None,
    pool_path=None,
) -> PreParams:
    """Generate node pre-parameters. ``safe_primes`` short-circuits the
    expensive search; ``pool_path`` draws from a :func:`pool_take` file.
    Matches tss-lib's construction: NTilde from safe primes, h1 a random
    square, h2 = h1^alpha."""
    half = bits // 2
    if safe_primes is not None:
        P, Q = safe_primes
    elif pool_path is not None:
        P, Q = pool_take(pool_path, count=2, bits=half, rng=rng)
    else:
        P = gen_safe_prime(half, rng)
        while True:
            Q = gen_safe_prime(half, rng)
            if Q != P:
                break
    NTilde = P * Q
    pq = (P - 1) // 2 * ((Q - 1) // 2)  # order of the squares subgroup
    f = rng.randbelow(NTilde - 2) + 2
    h1 = f * f % NTilde
    alpha = rng.randbelow(pq - 1) + 1
    beta = pow(alpha, -1, pq)
    h2 = pow(h1, alpha, NTilde)
    key = gen_paillier_key(bits, rng)
    return PreParams(
        paillier=key, NTilde=NTilde, h1=h1, h2=h2, alpha=alpha, beta=beta, P=P, Q=Q
    )


# ---------------------------------------------------------------------------
# batched device kernels
# ---------------------------------------------------------------------------


class PaillierBatch:
    """Batched Paillier arithmetic for ONE public key over a session axis.

    One node holds one Paillier key (generated in pre-params at startup) and
    runs B concurrent sessions — so N is a compile-time constant and every
    ciphertext tensor is (..., 373) limbs mod N². Curve-scalar exponents
    arrive as (..., n_bits) bit tensors (see bignum.limbs_to_bits).
    """

    def __init__(self, pk: PaillierPublicKey):
        self.pk = pk
        # 11-bit radix family sized to the key (2048-bit keys → the module
        # PROF_2048/PROF_4096; smaller keys in tests shrink proportionally).
        # Sized from actual bit lengths: Barrett needs the modulus to occupy
        # the top limb (N² may have 2b-1 bits).
        self.prof_n = bn.LimbProfile(bits=11, n_limbs=-(-pk.N.bit_length() // 11))
        self.prof_n2 = bn.LimbProfile(
            bits=11, n_limbs=-(-pk.N2.bit_length() // 11)
        )
        self.ctx_N2 = bn.BarrettCtx(pk.N2, self.prof_n2)
        self.ctx_N = bn.BarrettCtx(pk.N, self.prof_n)
        self.N_limbs = bn.to_limbs(pk.N, self.prof_n)
        # N⁻¹ mod radix^n for the exact division in L(u) = (u-1)/N
        r_n = 1 << (self.prof_n.bits * self.prof_n.n_limbs)
        self.Ninv_limbs = bn.to_limbs(pow(pk.N, -1, r_n), self.prof_n)

    # -- host <-> device ----------------------------------------------------

    def to_limbs_N2(self, xs) -> np.ndarray:
        return bn.batch_to_limbs(xs, self.prof_n2)

    def from_limbs_N2(self, arr) -> list:
        return bn.batch_from_limbs(arr, self.prof_n2)

    def to_limbs_N(self, xs) -> np.ndarray:
        return bn.batch_to_limbs(xs, self.prof_n)

    def from_limbs_N(self, arr) -> list:
        return bn.batch_from_limbs(arr, self.prof_n)

    # -- kernels ------------------------------------------------------------

    def encrypt(self, m_limbs: jnp.ndarray, r_limbs: jnp.ndarray) -> jnp.ndarray:
        """c = (1 + mN) · r^N mod N². ``m_limbs`` (..., 187) plaintexts
        < N; ``r_limbs`` (..., 373) random units mod N (zero-padded)."""
        N_l = jnp.broadcast_to(
            jnp.asarray(self.N_limbs), m_limbs.shape[:-1] + (self.prof_n.n_limbs,)
        )
        mN = bn.mul_wide(m_limbs, N_l, self.prof_n2)  # < N², one spare limb
        one_plus = bn.take_limbs(mN, 0, self.prof_n2.n_limbs).at[..., 0].add(1)
        one_plus = bn.carry(one_plus, self.prof_n2)
        rN = self.ctx_N2.powmod_const(r_limbs, self.pk.N)
        return self.ctx_N2.mulmod(one_plus, rN)

    def add(self, c1: jnp.ndarray, c2: jnp.ndarray) -> jnp.ndarray:
        """Enc(a)·Enc(b) = Enc(a+b mod N)."""
        return self.ctx_N2.mulmod(c1, c2)

    def scalar_mul(self, c: jnp.ndarray, k_bits: jnp.ndarray) -> jnp.ndarray:
        """Enc(a)^k = Enc(a·k mod N) with per-session exponent bits."""
        return self.ctx_N2.powmod(c, k_bits)

    def decrypt(self, sk: PaillierPrivateKey, c: jnp.ndarray) -> jnp.ndarray:
        """Batched decrypt → (..., 187) plaintext limbs mod N.

        Exact-division form of L: u = c^λ mod N²; (u-1)/N =
        (u-1)·N⁻¹ mod radix^187 (v < N so the low limbs are exact)."""
        assert sk.N == self.pk.N
        n = self.prof_n.n_limbs
        u = self.ctx_N2.powmod_const(c, sk.lam)
        u_minus = bn.carry(u.at[..., 0].add(-1), self.prof_n2)
        lo = bn.take_limbs(u_minus, 0, n)
        Ninv = jnp.broadcast_to(
            jnp.asarray(self.Ninv_limbs), lo.shape[:-1] + (n,)
        )
        v = bn.mul_wide(lo, Ninv, self.prof_n)[..., :n]
        mu_l = self.ctx_N.const(sk.mu, v.shape[:-1])
        return self.ctx_N.mulmod(v, mu_l)
