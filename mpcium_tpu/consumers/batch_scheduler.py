"""The TPU batch scheduler: coalesce concurrent signing requests into
fixed-shape engine dispatches (SURVEY.md §7.2 step 5).

The reference spawns one goroutine-backed session per signing request
(event_consumer.go:295-338); here concurrent ed25519 requests are BUCKETED
by (participant set, threshold, epoch), padded into one batch, and signed
by ONE protocol instance whose per-round compute is one engine dispatch
(protocol.eddsa.batch_signing). Per-session results demux back through the
normal result queues / reply inboxes.

Batch composition must be identical on every quorum member, so one member
is the MANIFEST LEADER — deterministically the lexicographically-smallest
participant (static: no election, no races). The leader buffers requests
for ``window_s`` (or until ``max_batch``), then broadcasts a manifest
listing the batch, **signed with its node identity**; receivers verify
both the leader signature and — because the leader is otherwise untrusted
for content — every entry's ORIGINAL initiator signature. Followers buffer
their requests purely as a liveness fallback: if no manifest covers a
request within ``manifest_timeout_s`` (leader down), it falls back to the
per-session signing path (one bucket-level timer, not one per request).

secp256k1 note: GG18's batched engine (engine.gg18_batch) currently runs
as an in-process fabric (bench/measurement); its distributed per-party
round exchange is future work, so ECDSA requests take the per-session
path. The scheduler's bucketing/manifest machinery is curve-agnostic.
"""
from __future__ import annotations

import json
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .. import wire
from ..node.node import Node, NotEnoughParticipants
from ..node.session import Session
from ..protocol.base import KeygenShare, ProtocolError
from ..protocol.eddsa.batch_signing import BatchedEDDSASigningParty
from ..transport.api import Transport
from ..utils import log


@dataclass
class _Entry:
    msg: wire.SignTxMessage
    reply_topic: str
    added_at: float = field(default_factory=time.monotonic)


def _bucket_key(info) -> Tuple:
    return (tuple(info.participant_peer_ids), info.threshold, info.epoch)


def _manifest_body(batch_id: str, leader: str, requests: List[dict]) -> bytes:
    return wire.canonical_json(
        {"batch_id": batch_id, "leader": leader, "requests": requests}
    )


class BatchSigningScheduler:
    """Per-node scheduler instance (every node runs one)."""

    def __init__(
        self,
        node: Node,
        transport: Transport,
        window_s: float = 0.05,
        max_batch: int = 1024,
        manifest_timeout_s: float = 2.0,
        on_fallback: Optional[Callable[[wire.SignTxMessage, str], None]] = None,
        on_tx_done: Optional[Callable[[str, str], None]] = None,
        on_tx_released: Optional[Callable[[str, str], None]] = None,
        claim_tx: Optional[Callable[[str, str], bool]] = None,
    ):
        self.node = node
        self.transport = transport
        self.window_s = window_s
        self.max_batch = max_batch
        self.manifest_timeout_s = manifest_timeout_s
        self.on_fallback = on_fallback  # per-session path (consumer wires it)
        # lifecycle callbacks into the consumer's dedup bookkeeping
        self.on_tx_done = on_tx_done or (lambda w, t: None)
        self.on_tx_released = on_tx_released or (lambda w, t: None)
        self.claim_tx = claim_tx or (lambda w, t: True)
        self._lock = threading.RLock()
        self._buckets: Dict[Tuple, List[_Entry]] = {}
        self._timers: Dict[Tuple, threading.Timer] = {}  # leader windows +
        # follower fallbacks, keyed ("win"|"fb", bucket)
        self._sessions: List[Session] = []
        self.batches_run = 0  # engine-dispatch diagnostic (tests assert ≪ N)
        self._sub = transport.pubsub.subscribe(
            wire.TOPIC_BATCH_MANIFEST, self._on_manifest_raw
        )
        self._closed = False

    def close(self) -> None:
        self._closed = True
        self._sub.unsubscribe()
        with self._lock:
            for t in self._timers.values():
                t.cancel()
            self._timers.clear()
            for s in self._sessions:
                s.close()

    # -- request intake ------------------------------------------------------

    def submit(self, msg: wire.SignTxMessage, reply_topic: str) -> bool:
        """Buffer a verified signing request for batching. Returns False if
        the request cannot be batched (caller should use the per-session
        path). The caller holds the dedup claim for this tx."""
        if msg.key_type != wire.KEY_TYPE_ED25519:
            return False
        info = self.node.keyinfo.get(msg.key_type, msg.wallet_id)
        if info is None:
            return False
        key = _bucket_key(info)
        leader = sorted(info.participant_peer_ids)[0]
        entry = _Entry(msg, reply_topic)
        with self._lock:
            if self._closed:
                return False
            self._buckets.setdefault(key, []).append(entry)
            if self.node.node_id == leader:
                if len(self._buckets[key]) >= self.max_batch:
                    self._fire(key)
                elif ("win", key) not in self._timers:
                    t = threading.Timer(self.window_s, self._fire, (key,))
                    t.daemon = True
                    t.start()
                    self._timers[("win", key)] = t
            elif ("fb", key) not in self._timers:
                # follower: ONE bucket-level liveness timer (re-armed while
                # entries remain), not one thread per request
                t = threading.Timer(
                    self.manifest_timeout_s, self._fallback_sweep, (key,)
                )
                t.daemon = True
                t.start()
                self._timers[("fb", key)] = t
        return True

    # -- leader: manifest emission ------------------------------------------

    def _fire(self, key: Tuple) -> None:
        with self._lock:
            t = self._timers.pop(("win", key), None)
            if t:
                t.cancel()
            entries = self._buckets.pop(key, [])
        if not entries:
            return
        batch_id = secrets.token_hex(8)
        requests = [
            {"msg": e.msg.to_json(), "reply": e.reply_topic} for e in entries
        ]
        body = _manifest_body(batch_id, self.node.node_id, requests)
        manifest = {
            "batch_id": batch_id,
            "leader": self.node.node_id,
            "requests": requests,
            "sig": self.node.identity.sign_raw(body).hex(),
        }
        self.transport.pubsub.publish(
            wire.TOPIC_BATCH_MANIFEST, json.dumps(manifest).encode()
        )

    def _fallback_sweep(self, key: Tuple) -> None:
        """Follower liveness: entries the leader never covered go down the
        per-session path; re-arm while the bucket stays non-empty."""
        now = time.monotonic()
        stale: List[_Entry] = []
        with self._lock:
            self._timers.pop(("fb", key), None)
            if self._closed:
                return
            bucket = self._buckets.get(key, [])
            stale = [
                e for e in bucket
                if now - e.added_at >= self.manifest_timeout_s
            ]
            bucket[:] = [e for e in bucket if e not in stale]
            if bucket:
                t = threading.Timer(
                    self.manifest_timeout_s, self._fallback_sweep, (key,)
                )
                t.daemon = True
                t.start()
                self._timers[("fb", key)] = t
        for e in stale:
            log.warn("batch manifest timeout — per-session fallback",
                     wallet=e.msg.wallet_id, tx=e.msg.tx_id,
                     node=self.node.node_id)
            if self.on_fallback:
                self.on_fallback(e.msg, e.reply_topic)

    # -- all quorum members: manifest execution ------------------------------

    def _on_manifest_raw(self, raw: bytes) -> None:
        try:
            man = json.loads(raw)
            batch_id = man["batch_id"]
            leader = man["leader"]
            sig = bytes.fromhex(man["sig"])
            requests = man["requests"]
            reqs = [
                (wire.SignTxMessage.from_json(r["msg"]), r.get("reply", ""))
                for r in requests
            ]
        except Exception as e:  # noqa: BLE001
            log.warn("bad batch manifest dropped", error=repr(e))
            return
        if not reqs:
            return
        # leader authenticity: must be signed by the node it claims to be
        # from, and that node must be the deterministic leader for the
        # wallets' topology (checked against OUR keyinfo below)
        body = _manifest_body(batch_id, leader, requests)
        if not self.node.identity.verify_peer(leader, body, sig):
            log.warn("batch manifest with BAD leader signature dropped",
                     batch=batch_id)
            return
        info = self.node.keyinfo.get(reqs[0][0].key_type, reqs[0][0].wallet_id)
        if info is None or sorted(info.participant_peer_ids)[0] != leader:
            log.warn("batch manifest from non-leader dropped",
                     batch=batch_id, claimed=leader)
            return
        # batch homogeneity: the leader is untrusted — every request must be
        # ed25519 and share the (participants, threshold, epoch) bucket of
        # the first (otherwise a leader for ONE wallet could smuggle foreign
        # topologies/curves into followers' batches)
        want = _bucket_key(info)
        for msg, _reply in reqs:
            if msg.key_type != wire.KEY_TYPE_ED25519:
                log.warn("non-ed25519 request in manifest dropped",
                         batch=batch_id)
                return
            winfo = self.node.keyinfo.get(msg.key_type, msg.wallet_id)
            if winfo is None or _bucket_key(winfo) != want:
                log.warn("mixed-topology batch manifest dropped",
                         batch=batch_id, wallet=msg.wallet_id)
                return
        # the leader is untrusted for content: re-verify every initiator
        # signature
        for msg, _reply in reqs:
            if not self.node.identity.verify_initiator(msg.raw(), msg.signature):
                log.warn("batch manifest with BAD initiator signature dropped",
                         batch=batch_id)
                return
        # drop covered entries from local buffers BEFORE any early return,
        # so follower fallback timers cannot race a manifest we act on
        covered = {(m.wallet_id, m.tx_id) for m, _ in reqs}
        with self._lock:
            for bucket in self._buckets.values():
                bucket[:] = [
                    e for e in bucket
                    if (e.msg.wallet_id, e.msg.tx_id) not in covered
                ]
        threading.Thread(
            target=self._run_batch, args=(batch_id, reqs),
            name=f"bsign-{batch_id}", daemon=True,
        ).start()

    def _run_batch(
        self, batch_id: str, reqs: List[Tuple[wire.SignTxMessage, str]]
    ) -> None:
        node = self.node
        first = reqs[0][0]
        info = node.keyinfo.get(first.key_type, first.wallet_id)
        if info is None:
            return
        # claim lanes we don't already own (e.g. the manifest beat the
        # pub/sub copy of the request to this node). Claims held by the
        # normal _on_sign path for these txs also count as ours: the
        # consumer routed them to submit(), so the batch is their owner.
        # Only claims WE acquire (or that _on_sign routed to submit(), i.e.
        # already covered by a manifest) belong to the batch; a claim held
        # by a live per-session run (manifest raced the fallback) must not
        # be finished/released by us — that run owns its own lifecycle.
        owned: List[Tuple[str, str]] = []
        for msg, _r in reqs:
            if self.claim_tx(msg.wallet_id, msg.tx_id):
                owned.append((msg.wallet_id, msg.tx_id))

        owned_set = set(owned)

        def release_all():
            for w, t in owned:
                self.on_tx_released(w, t)

        try:
            quorum = node._ready_quorum(
                info.participant_peer_ids, info.threshold + 1
            )
        except NotEnoughParticipants:
            release_all()
            return  # no reply ⇒ durable redelivery retries
        if node.node_id not in quorum:
            release_all()
            return
        shares: List[KeygenShare] = []
        messages: List[bytes] = []
        try:
            for msg, _r in reqs:
                share = node.load_share(msg.key_type, msg.wallet_id)
                winfo = node.keyinfo.get(msg.key_type, msg.wallet_id)
                if winfo is None or share.epoch != winfo.epoch:
                    raise NotEnoughParticipants("epoch fence (mid-reshare)")
                shares.append(share)
                messages.append(msg.tx)
            party = BatchedEDDSASigningParty(
                f"bsign:{batch_id}", node.node_id, quorum, shares, messages
            )
        except (ProtocolError, NotEnoughParticipants) as e:
            log.warn("batch not signable here — waiting for redelivery",
                     batch=batch_id, reason=str(e), node=node.node_id)
            release_all()
            return

        def on_done(result):
            sigs, ok = result["signatures"], result["ok"]
            for i, (msg, reply) in enumerate(reqs):
                if bool(ok[i]):
                    ev = wire.SigningResultEvent(
                        result_type=wire.RESULT_SUCCESS,
                        wallet_id=msg.wallet_id,
                        tx_id=msg.tx_id,
                        network_internal_code=msg.network_internal_code,
                        signature=sigs[i].tobytes().hex(),
                    )
                else:
                    ev = wire.SigningResultEvent(
                        result_type=wire.RESULT_ERROR,
                        wallet_id=msg.wallet_id,
                        tx_id=msg.tx_id,
                        network_internal_code=msg.network_internal_code,
                        error_reason="batched signature failed verification",
                    )
                self.transport.queues.enqueue(
                    wire.TOPIC_SIGNING_RESULT,
                    wire.canonical_json(ev.to_json()),
                    idempotency_key=msg.tx_id,
                )
                if reply:
                    self.transport.pubsub.publish(
                        reply, b"OK" if bool(ok[i]) else b"ERR"
                    )
                if (msg.wallet_id, msg.tx_id) in owned_set:
                    self.on_tx_done(msg.wallet_id, msg.tx_id)
            log.info("batch signed", batch=batch_id, size=len(reqs),
                     node=node.node_id)
            _prune()

        def on_error(e):
            # retryable/protocol failure: emit nothing — durable redelivery
            # retries each request (possibly down the per-session path)
            log.warn("batch signing failed", batch=batch_id, error=str(e),
                     node=node.node_id)
            release_all()
            _prune()

        def _prune():
            with self._lock:
                if session in self._sessions:
                    self._sessions.remove(session)
            session.close()

        session = Session(
            session_id=f"bsign:{batch_id}",
            party=party,
            node_id=node.node_id,
            participants=quorum,
            transport=self.transport,
            identity=node.identity,
            broadcast_topic=f"bsign:broadcast:{batch_id}",
            direct_topic_fn=lambda n: f"bsign:direct:{n}:{batch_id}",
            on_done=on_done,
            on_error=on_error,
        )
        with self._lock:
            if self._closed:
                release_all()
                return
            self._sessions.append(session)
            self.batches_run += 1
        session.listen()
