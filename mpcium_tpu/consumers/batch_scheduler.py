"""The TPU batch scheduler: coalesce concurrent signing requests into
fixed-shape engine dispatches (SURVEY.md §7.2 step 5).

The reference spawns one goroutine-backed session per signing request
(event_consumer.go:295-338); here concurrent ed25519 requests are BUCKETED
by (participant set, threshold, epoch), padded into one batch, and signed
by ONE protocol instance whose per-round compute is one engine dispatch
(protocol.eddsa.batch_signing). Per-session results demux back through the
normal result queues / reply inboxes.

Batch composition must be identical on every quorum member, so one member
is the MANIFEST LEADER — the lexicographically-smallest participant the
local registry sees as LIVE (rank-based: no election protocol; the
registry's liveness view is the election). The leader buffers requests
for ``window_s`` (or until ``max_batch``), then broadcasts a manifest
listing the batch, **signed with its node identity**; receivers verify
the leader signature, that the leader is a topology member, and —
because the leader is otherwise untrusted for content — every entry's
ORIGINAL initiator signature. Requests stay buffered on EVERY member
(leader included) until a manifest covers them. Escalation when no
manifest arrives (one bucket-level timer, not one per request): at
``manifest_timeout_s`` the DEPUTY — the next-smallest live member —
re-fires the entries under its own manifest (no throughput cliff when
the leader dies); at twice that, surviving entries fall back to the
per-session signing path. Registry-view skew can at worst produce two
manifests for one request — redundant idempotent work, never a drop.

Both curves batch: ed25519 via protocol.eddsa.batch_signing (3 rounds)
and secp256k1 via protocol.ecdsa.batch_signing (distributed GG18, 9
rounds on the engine kernels). ECDSA buckets additionally key on the
quorum's Paillier/ring-Pedersen material digest so one batch maps to one
modulus-context set; wallets with no GG18 aux material (never produced by
this framework's keygen) fall back to the per-session path.

SLO-aware continuous batching: every entry carries a DEADLINE (from the
request's ``deadline_ms`` or the config default) and a LANE (interactive
or bulk, from the request's ``priority``). Dispatch is continuous — a
bucket fires whenever ``max_batch`` entries are buffered OR the oldest
entry reaches ``window_s`` — and batches fill interactive-lane-first,
oldest-deadline-first. All timing (windows, liveness fallbacks, decline
expiries, deadline sweeps) runs on ONE timing-wheel thread, so a million
buffered wallets costs one thread, not thousands of ``threading.Timer``s.
Intake is BOUNDED: past ``max_queue_depth`` buffered entries, a submit is
refused honestly — a *retryable* error event is published, the reply inbox
gets ERR, the dedup claim is released, and a shed counter ticks; nothing
is ever dropped silently. A buffered entry whose deadline expires before
a manifest covers it is shed the same way (the deputy never re-fires an
already-expired entry). Everything is observable through a
``utils.metrics.MetricsRegistry``: per-lane queue depth, batch fill
ratio, dispatch age, shed/takeover/fallback counts, end-to-end latency.
"""
from __future__ import annotations

import heapq
import itertools
import json
import secrets
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .. import wire
from ..engine.buckets import floor_bucket
from ..engine.pipeline import resolve_cohorts
from ..node.node import Node, NotEnoughParticipants
from ..node.session import Session
from ..protocol.base import KeygenShare, ProtocolError
from ..protocol.eddsa.batch_signing import BatchedEDDSASigningParty
from ..transport.api import Transport
from ..utils import log, tracing
from ..utils.annotations import locked_by
from ..utils.metrics import MetricsRegistry

_DIGEST_CACHE_CAP = 4096  # (key_type, wallet, epoch) -> material digest LRU
_INTAKE_TS_CAP = 1 << 18  # e2e-latency bookkeeping bound (entries, not bytes)
# late-duplicate absorption window after a sign batch settles: must
# outlast the transport's redelivery backoff for a chaos-dropped intake
_SETTLED_TTL_S = 30.0
_SETTLED_CAP = 4096


class _TimingWheel:
    """One daemon thread serving every scheduler timer.

    ``schedule(key, delay, fn)`` arms (or re-arms, replacing) a named
    timer; ``cancel(key)`` disarms it. Internally a heap of
    (fire_at, seq, key) with a per-key generation dict so replaced or
    cancelled entries are skipped lazily — no heap surgery on the hot
    path. Callbacks run on the wheel thread and must not block: every
    scheduler callback either grabs the scheduler lock briefly or hands
    real work to a batch thread.
    """

    def __init__(self, name: str = "timing-wheel") -> None:
        self._cond = threading.Condition()
        self._heap: List[Tuple[float, int, object]] = []
        self._armed: Dict[object, Tuple[int, Callable[[], None]]] = {}
        self._seq = itertools.count()
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()

    def schedule(self, key, delay_s: float, fn: Callable[[], None]) -> None:
        fire_at = time.monotonic() + max(0.0, delay_s)
        with self._cond:
            if self._closed:
                return
            seq = next(self._seq)
            self._armed[key] = (seq, fn)
            heapq.heappush(self._heap, (fire_at, seq, key))
            self._cond.notify()

    def schedule_if_absent(
        self, key, delay_s: float, fn: Callable[[], None]
    ) -> bool:
        with self._cond:
            if self._closed or key in self._armed:
                return False
        self.schedule(key, delay_s, fn)
        return True

    def cancel(self, key) -> None:
        with self._cond:
            self._armed.pop(key, None)

    def contains(self, key) -> bool:
        with self._cond:
            return key in self._armed

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._armed.clear()
            self._heap.clear()
            self._cond.notify()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while True:
            with self._cond:
                if self._closed:
                    return
                now = time.monotonic()
                fn = None
                if self._heap:
                    fire_at, seq, key = self._heap[0]
                    armed = self._armed.get(key)
                    if armed is None or armed[0] != seq:
                        heapq.heappop(self._heap)  # replaced/cancelled
                        continue
                    if fire_at <= now:
                        heapq.heappop(self._heap)
                        del self._armed[key]
                        fn = armed[1]
                    else:
                        self._cond.wait(fire_at - now)
                        continue
                else:
                    self._cond.wait()
                    continue
            try:
                fn()
            except Exception as e:  # noqa: BLE001
                log.error("timing-wheel callback crashed", error=repr(e))


@dataclass
class _Entry:
    msg: object  # SignTxMessage ("sign") or GenerateKeyMessage ("kg")
    reply_topic: str
    added_at: float = field(default_factory=time.monotonic)
    fired: bool = False  # leader: already covered by a published manifest
    kind: str = "sign"
    took_over: bool = False  # deputy already re-fired this entry once
    # SLO lane + absolute deadline (monotonic clock). inf = no deadline,
    # which keeps every legacy positional construction un-sheddable.
    deadline_at: float = float("inf")
    lane: str = wire.PRIORITY_BULK

    def fill_rank(self) -> Tuple[int, float, float]:
        """Batch-fill order: interactive lane first, then oldest deadline,
        then arrival."""
        return (
            0 if self.lane == wire.PRIORITY_INTERACTIVE else 1,
            self.deadline_at,
            self.added_at,
        )


def _key_participants(key: Tuple) -> Tuple:
    """The candidate-leader set encoded in a bucket key (see the three
    submit paths for the key shapes)."""
    if key[0] == "kg":
        return key[1]
    if key[0] == "rs":
        return key[2]
    return key[0]


def _bucket_key(info) -> Tuple:
    return (tuple(info.participant_peer_ids), info.threshold, info.epoch)


def _entry_key(kind: str, msg) -> Tuple[str, str]:
    """The (wallet, tx) identity used for claims and manifest coverage;
    keygen/reshare requests have no tx axis."""
    if kind == "kg":
        return (msg.wallet_id, "")
    if kind == "rs":
        return (f"{msg.key_type}:{msg.wallet_id}", "")
    return (msg.wallet_id, msg.tx_id)


def _manifest_body(
    batch_id: str, leader: str, requests: List[dict], kind: str,
    cohorts: int = 1,
) -> bytes:
    return wire.canonical_json(
        {
            "batch_id": batch_id,
            "leader": leader,
            "requests": requests,
            "kind": kind,
            "cohorts": cohorts,
        }
    )


@locked_by(
    "_lock",
    "_buckets",
    "_batch_claims",
    "_live_claims",
    "_settled",
    "_sessions",
    "_decline_responders",
    "_digest_cache",
    "_intake_ts",
    "_depth_n",
)
class BatchSigningScheduler:
    """Per-node scheduler instance (every node runs one)."""

    def __init__(
        self,
        node: Node,
        transport: Transport,
        window_s: Optional[float] = None,
        max_batch: Optional[int] = None,
        manifest_timeout_s: Optional[float] = None,
        default_deadline_ms: Optional[int] = None,
        max_queue_depth: Optional[int] = None,
        decline_cap: Optional[int] = None,
        batch_patience_s: Optional[float] = None,
        metrics: Optional[MetricsRegistry] = None,
        on_fallback: Optional[Callable[[wire.SignTxMessage, str], None]] = None,
        on_tx_done: Optional[Callable[[str, str], None]] = None,
        on_tx_released: Optional[Callable[[str, str], None]] = None,
        claim_tx: Optional[Callable[[str, str], bool]] = None,
        on_fallback_keygen: Optional[Callable] = None,
        on_kg_done: Optional[Callable[[str], None]] = None,
        on_kg_released: Optional[Callable[[str], None]] = None,
        claim_kg: Optional[Callable[[str], bool]] = None,
        on_fallback_reshare: Optional[Callable] = None,
        on_rs_done: Optional[Callable[[str, str], None]] = None,
        on_rs_released: Optional[Callable[[str, str], None]] = None,
        claim_rs: Optional[Callable[[str, str], bool]] = None,
    ):
        from ..config import get_config

        cfg = get_config()
        self.node = node
        self.transport = transport
        # every knob: explicit argument wins, else the config value (which
        # itself defaults to the historical constants)
        self.window_s = window_s if window_s is not None else cfg.batch_window_s
        self.max_batch = (
            max_batch if max_batch is not None else cfg.batch_max_batch
        )
        # manifests are cut in pow-2 chunks (engine/buckets.py) so every
        # batch the engines see is a COMPILE_SURFACE.json signature the
        # AOT pre-warmer can compile ahead of traffic — a non-pow-2
        # max_batch only lowers the cap, it never emits an off-bucket size
        self._chunk_cap = floor_bucket(max(1, self.max_batch))
        self.manifest_timeout_s = (
            manifest_timeout_s
            if manifest_timeout_s is not None
            else cfg.batch_manifest_timeout_s
        )
        self.default_deadline_ms = (
            default_deadline_ms
            if default_deadline_ms is not None
            else cfg.batch_deadline_ms
        )
        self.max_queue_depth = (
            max_queue_depth
            if max_queue_depth is not None
            else cfg.batch_max_queue_depth
        )
        self.decline_cap = (
            decline_cap if decline_cap is not None else cfg.batch_decline_cap
        )
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.on_fallback = on_fallback  # per-session path (consumer wires it)
        # lifecycle callbacks into the consumer's dedup bookkeeping
        self.on_tx_done = on_tx_done or (lambda w, t: None)
        self.on_tx_released = on_tx_released or (lambda w, t: None)
        self.claim_tx = claim_tx or (lambda w, t: True)
        self.on_fallback_keygen = on_fallback_keygen
        self.on_kg_done = on_kg_done or (lambda w: None)
        self.on_kg_released = on_kg_released or (lambda w: None)
        self.claim_kg = claim_kg or (lambda w: True)
        self.on_fallback_reshare = on_fallback_reshare
        self.on_rs_done = on_rs_done or (lambda kt, w: None)
        self.on_rs_released = on_rs_released or (lambda kt, w: None)
        self.claim_rs = claim_rs or (lambda kt, w: True)
        self._lock = threading.RLock()
        self._buckets: Dict[Tuple, List[_Entry]] = {}
        # dedup strings of claims owned by RUNNING batch threads, as a
        # REFCOUNT (see owns_dedup / the consumer GC's empty-claim
        # reaping): deputy takeover plus a late original-leader manifest
        # can legitimately run two batch threads covering one request on
        # one node, and the second thread's exit must not clobber the
        # first's claim protection
        self._batch_claims: Dict[str, int] = {}
        # session_id -> dedup strings owned by a LIVE async batch session
        # (sign/reshare runners hand off to a Session and return; the
        # claims stay owned until that session's _prune)
        self._live_claims: Dict[str, set] = {}
        # dedup string -> monotonic settle time, SIGN ONLY: a chaos-
        # dropped intake can be redelivered seconds after the batch that
        # answered it finished and forgot its claims, and buffering it
        # then strands a lane entry until the fallback sweep. Sign
        # retries always carry a FRESH tx id, so a same-dedup arrival
        # inside the TTL is by construction a duplicate delivery, never
        # a retry — absorb it. (kg/rs dedup keys are wallet-scoped and
        # ARE reused by retries, so they never enter this map.)
        self._settled: OrderedDict[str, float] = OrderedDict()
        # ONE timing-wheel thread serves every window, liveness fallback,
        # deadline sweep, and decline expiry — keys ("win"|"fb"|"dl", bucket)
        # and ("decl", session_id)
        self._wheel = _TimingWheel(name=f"batch-wheel-{node.node_id}")
        self._sessions: List[Session] = []
        self.batches_run = 0  # engine-dispatch diagnostic (tests assert ≪ N)
        # GG18 exponent domains (None = production defaults); tests with
        # shrunk keys set this on every quorum member's scheduler
        self.gg18_dom = None
        # hello/unicast budgets for batch sessions: one round of a batched
        # party can spend minutes in XLA compiles or DLN verification, so
        # a busy (not gone) peer must not trip the 3x3s transport budget
        # or the 20s hello deadline
        self.batch_patience_s = (
            batch_patience_s
            if batch_patience_s is not None
            else cfg.batch_patience_s
        )
        # session_id -> pubsub subscription, insertion-ordered so the cap
        # evicts the OLDEST responder (its peers have had the longest to
        # hear the decline); expiry timers live on the wheel
        self._decline_responders: "OrderedDict[str, object]" = OrderedDict()
        # secp material digests are constant per (wallet, epoch) — LRU cache
        # so a request burst costs one share load, not one per tx, and a
        # long-lived node serving many wallets stays bounded
        self._digest_cache: "OrderedDict[Tuple[str, str, int], str]" = (
            OrderedDict()
        )
        # intake timestamps for end-to-end latency: (kind, wallet, tx) ->
        # monotonic submit time, popped at done/shed (bounded FIFO)
        self._intake_ts: "OrderedDict[Tuple[str, str, str], float]" = (
            OrderedDict()
        )
        self._shed_seq = itertools.count()  # distinct shed idempotency keys
        # authoritative per-lane buffered-entry counts (under self._lock);
        # the gauges mirror them for snapshots
        self._depth_n: Dict[str, int] = {lane: 0 for lane in wire.PRIORITIES}
        # per-lane depth gauges + shared counters, created eagerly so a
        # snapshot shows zeros instead of missing series
        m = self.metrics
        self._m_depth = {
            lane: m.gauge(f"scheduler.queue_depth.{lane}")
            for lane in wire.PRIORITIES
        }
        self._m_submitted = m.counter("scheduler.submitted_total")
        self._m_shed = m.counter("scheduler.shed_total")
        self._m_shed_bp = m.counter("scheduler.shed_backpressure_total")
        self._m_shed_dl = m.counter("scheduler.shed_deadline_total")
        self._m_batches = m.counter("scheduler.batches_fired_total")
        self._m_fill = m.histogram("scheduler.batch_fill_ratio")
        self._m_age = m.histogram("scheduler.dispatch_age_s")
        self._m_takeover = m.counter("scheduler.deputy_takeover_total")
        self._m_fallback = m.counter("scheduler.fallback_total")
        self._m_quarantined = m.counter("scheduler.quarantined_total")
        self._m_repacked = m.counter("scheduler.repacked_total")
        self._m_e2e = m.histogram("scheduler.e2e_latency_s")
        self._m_decl_evict = m.counter("scheduler.declines_evicted_total")
        self._sub = transport.pubsub.subscribe(
            wire.TOPIC_BATCH_MANIFEST, self._on_manifest_raw
        )
        self._closed = False

    def settled_size(self) -> int:
        """Current entry count of the settled-digest TTL map — the
        absorption window for post-dispatch redeliveries. Exposed as a
        gauge so a leak here (entries not aging out) is visible before
        the cap turns it into silent forgetting."""
        with self._lock:
            return len(self._settled)

    def close(self) -> None:
        self._closed = True
        self._sub.unsubscribe()
        self._wheel.close()
        with self._lock:
            for s in self._sessions:
                s.close()
            for sub in self._decline_responders.values():
                try:
                    sub.unsubscribe()
                except Exception:  # noqa: BLE001
                    pass
            self._decline_responders.clear()

    # -- request intake ------------------------------------------------------

    def submit(self, msg: wire.SignTxMessage, reply_topic: str) -> bool:
        """Buffer a verified signing request for batching. Returns False if
        the request cannot be batched (caller should use the per-session
        path). The caller holds the dedup claim for this tx."""
        if msg.key_type not in (
            wire.KEY_TYPE_ED25519, wire.KEY_TYPE_SECP256K1
        ):
            return False
        info = self.node.keyinfo.get(msg.key_type, msg.wallet_id)
        if info is None:
            return False
        extra: Tuple = ()
        if msg.key_type == wire.KEY_TYPE_SECP256K1:
            # one batch = one modulus-context set: bucket on the quorum's
            # Paillier/ring-Pedersen material (batch_signing module doc).
            # The digest is constant per (wallet, epoch) — cached, so a
            # burst of txs costs one share load, not one per tx.
            ck = (msg.key_type, msg.wallet_id, info.epoch)
            # LOCKED read (concurrent submits on the transport pool mutate
            # this dict) + LRU touch so hot wallets stay resident
            with self._lock:
                dig = self._digest_cache.get(ck)
                if dig is not None:
                    self._digest_cache.move_to_end(ck)
            if dig is None:
                from ..protocol.ecdsa.batch_signing import (
                    quorum_material_digest,
                )

                try:
                    share = self.node.load_share(msg.key_type, msg.wallet_id)
                except ProtocolError:
                    return False
                if share.epoch != info.epoch:
                    return False  # mid-reshare — per-session path retries
                dig = quorum_material_digest(share)
                # one live epoch per wallet: evict superseded epochs; the
                # LRU cap bounds the cache even across millions of wallets
                with self._lock:
                    stale = [
                        k for k in self._digest_cache
                        if k[0] == msg.key_type and k[1] == msg.wallet_id
                    ]
                    for k in stale:
                        del self._digest_cache[k]
                    self._digest_cache[ck] = dig
                    while len(self._digest_cache) > _DIGEST_CACHE_CAP:
                        self._digest_cache.popitem(last=False)
            if not dig:
                return False  # no GG18 aux → per-session path
            extra = (dig,)
        key = _bucket_key(info) + (msg.key_type,) + extra
        leader = self._acting_leader(info.participant_peer_ids)
        return self._buffer_entry(
            key, self._mk_entry(msg, reply_topic, "sign"), leader
        )

    def submit_keygen(self, msg: wire.GenerateKeyMessage) -> bool:
        """Buffer a verified wallet-creation request for batched DKG
        (engine kernels via protocol.batch_dkg, both curves). Returns False
        when batching does not apply; the caller holds the keygen dedup
        claim."""
        # keygen runs over the FULL configured cluster (reference
        # node.go:95); every node sees every request via pub/sub
        if self.node.registry.ready_count() < len(self.node.peer_ids):
            return False
        key = ("kg", tuple(self.node.peer_ids), self._threshold())
        leader = self._acting_leader(self.node.peer_ids)
        return self._buffer_entry(key, self._mk_entry(msg, "", "kg"), leader)

    def submit_reshare(self, msg: wire.ResharingMessage) -> bool:
        """Buffer a verified resharing request for batched rotation
        (protocol.batch_dkg.BatchedReshareParty). Wallets bucket by curve +
        old topology + new threshold so one re-deal serves the batch."""
        info = self.node.keyinfo.get(msg.key_type, msg.wallet_id)
        if info is None:
            return False
        key = (
            "rs", msg.key_type, tuple(info.participant_peer_ids),
            info.threshold, info.epoch, msg.new_threshold,
        )
        leader = self._acting_leader(info.participant_peer_ids)
        return self._buffer_entry(key, self._mk_entry(msg, "", "rs"), leader)

    def _mk_entry(self, msg, reply_topic: str, kind: str) -> _Entry:
        """Stamp the SLO lane + absolute deadline onto a fresh entry.
        ``deadline_ms`` 0 on the wire means "server default"; keygen
        commands carry no SLO fields and always take the defaults."""
        deadline_ms = getattr(msg, "deadline_ms", 0) or self.default_deadline_ms
        lane = getattr(msg, "priority", wire.PRIORITY_BULK)
        if lane not in wire.PRIORITIES:
            lane = wire.PRIORITY_BULK
        deadline_at = (
            time.monotonic() + deadline_ms / 1000.0
            if deadline_ms > 0
            else float("inf")
        )
        return _Entry(
            msg, reply_topic, kind=kind, deadline_at=deadline_at, lane=lane
        )

    def _acting_leader(self, candidates) -> str:
        """Manifest leadership is RANK-based, not static: the smallest
        participant the local registry sees as live leads; if it dies,
        the next-smallest takes over (at submit time when the registry
        already knows, or via the fallback sweep's deputy escalation when
        it finds out the hard way). Receivers verify manifest signatures
        and content but accept any MEMBER as leader — rank only decides
        who sends, so registry-view skew degrades to a redundant
        (idempotent) batch instead of a dropped one."""
        cand = sorted(candidates)
        live = [
            p for p in cand
            if p == self.node.node_id or self.node.registry.is_peer_ready(p)
        ]
        return (live or cand)[0]

    def _buffer_entry(self, key: Tuple, entry: _Entry, leader: str) -> bool:
        """Shared intake: depth-bounded append to the bucket, continuous
        fire (at max_batch) or window arm, bucket-level liveness fallback,
        deadline sweep. Returns True when the request is HANDLED — which
        includes an honest refusal (shed): the caller must not route a
        shed request down the per-session path, that would defeat the
        backpressure bound."""
        fire_after = False
        with self._lock:
            if self._closed:
                return False
            self._m_submitted.inc()
            over_depth = sum(self._depth_n.values()) >= self.max_queue_depth
        if over_depth:
            # bounded intake: refuse NOW, loudly. Claim released, a
            # retryable error event published, reply inbox answered —
            # never a silent drop. (Outside the lock: the release
            # callback re-enters the consumer's bookkeeping.)
            self._shed(entry, "queue depth exceeded", backpressure=True)
            return True
        with self._lock:
            if self._closed:
                return False
            ek = _entry_key(entry.kind, entry.msg)
            d = self._dedup_str(entry.kind, ek)
            if self._batch_claims.get(d, 0) > 0 or any(
                d in claims for claims in self._live_claims.values()
            ):
                # Late intake: pub/sub ordering across topics is not
                # guaranteed, so the manifest covering this very request
                # can be processed BEFORE the request itself arrives here.
                # A batch/session already owns the claim and will answer
                # the same reply inbox; buffering a duplicate would strand
                # an orphaned lane entry (nonzero depth gauge) until a
                # sweep collects it. Absorb it instead.
                return True
            settled_at = self._settled.get(d)
            if settled_at is not None:
                if time.monotonic() - settled_at < _SETTLED_TTL_S:
                    # Later still: the covering batch already finished
                    # and forgot its claims (a dropped delivery can be
                    # redelivered after the whole batch settled). Sign
                    # retries carry fresh tx ids, so this is a duplicate
                    # of an ANSWERED request — absorb, don't strand.
                    return True
                del self._settled[d]
            self._buckets.setdefault(key, []).append(entry)
            self._note_depth(entry.lane, +1)
            ts_key = (entry.kind, ek[0], ek[1])
            self._intake_ts[ts_key] = entry.added_at
            while len(self._intake_ts) > _INTAKE_TS_CAP:
                self._intake_ts.popitem(last=False)
            if self.node.node_id == leader:
                unfired = sum(1 for e in self._buckets[key] if not e.fired)
                if unfired >= self._chunk_cap:
                    fire_after = True
                else:
                    self._wheel.schedule_if_absent(
                        ("win", key), self.window_s,
                        lambda: self._fire(key),
                    )
            # ONE bucket-level liveness task (re-armed while entries
            # remain), not one thread per request. The leader arms it
            # too: entries stay bucketed until its own manifest loops
            # back through pub/sub, so a lost manifest degrades to the
            # per-session path instead of stranding the dedup claims.
            self._wheel.schedule_if_absent(
                ("fb", key), self.manifest_timeout_s,
                lambda: self._fallback_sweep(key),
            )
            if entry.deadline_at != float("inf"):
                self._arm_deadline_locked(key, entry.deadline_at)
        tracing.instant(
            "intake", node=self.node.node_id, tid=f"lane:{entry.lane}",
            req_kind=entry.kind, deadline_ms=(
                0 if entry.deadline_at == float("inf")
                else int((entry.deadline_at - entry.added_at) * 1000)
            ),
        )
        if fire_after:
            # continuous batching: drain every full chunk ready right now
            # (the remainder waits for the window or the next submit)
            self._fire(key, only_full=True)
        return True

    def _note_depth(self, lane: str, delta: int) -> None:
        """Caller holds self._lock."""
        n = self._depth_n.get(lane, 0) + delta
        self._depth_n[lane] = max(0, n)
        g = self._m_depth.get(lane)
        if g is not None:
            g.set(self._depth_n[lane])

    def _arm_deadline_locked(self, key: Tuple, deadline_at: float) -> None:
        """Arm (or pull earlier) the bucket's deadline sweep. Caller holds
        self._lock. The wheel key is per-bucket: one task per bucket, not
        one per entry."""
        delay = max(0.0, deadline_at - time.monotonic())
        wk = ("dl", key)
        if not self._wheel.schedule_if_absent(
            wk, delay, lambda: self._deadline_sweep(key)
        ):
            # already armed — only replace if this deadline is sooner;
            # the sweep itself re-arms to the next-soonest survivor
            bucket = self._buckets.get(key, [])
            soonest = min(
                (e.deadline_at for e in bucket), default=float("inf")
            )
            if deadline_at <= soonest:
                self._wheel.schedule(
                    wk, delay, lambda: self._deadline_sweep(key)
                )

    def _deadline_sweep(self, key: Tuple) -> None:
        """Shed every buffered entry whose deadline passed (the batch it
        would join could no longer meet the SLO), then re-arm for the
        next-soonest survivor."""
        now = time.monotonic()
        with self._lock:
            if self._closed:
                return
            bucket = self._buckets.get(key, [])
            expired = [e for e in bucket if e.deadline_at <= now]
            bucket[:] = [e for e in bucket if e.deadline_at > now]
            for e in expired:
                self._note_depth(e.lane, -1)
            nxt = min((e.deadline_at for e in bucket), default=float("inf"))
            if nxt != float("inf"):
                self._wheel.schedule(
                    ("dl", key), max(0.0, nxt - now),
                    lambda: self._deadline_sweep(key),
                )
        for e in expired:
            self._shed(e, "deadline expired before dispatch")

    # -- honest shedding -----------------------------------------------------

    def _shed(self, e: _Entry, reason: str,
              backpressure: bool = False) -> None:
        """Refuse one request honestly: publish a *retryable* error event
        (distinct idempotency key — a later retry's result must not dedupe
        against it), answer the reply inbox, release the dedup claim, and
        count it. Runs OUTSIDE self._lock — the release callback re-enters
        the consumer's bookkeeping (its own lock)."""
        self._m_shed.inc()
        (self._m_shed_bp if backpressure else self._m_shed_dl).inc()
        # the queued lifetime of the refused entry as a lane span, plus a
        # shed incident (which triggers a flight-recorder dump when a
        # dump dir is configured) — an SLO miss is explainable from the
        # trace alone: lane, age, reason, backpressure-vs-deadline
        tracing.emit(
            "queue", int(e.added_at * 1e9), tracing.now_ns(),
            node=self.node.node_id, tid=f"lane:{e.lane}",
            req_kind=e.kind, outcome="shed", backpressure=backpressure,
        )
        tracing.incident(
            "shed", node=self.node.node_id, tid=f"lane:{e.lane}",
            req_kind=e.kind, reason=reason, backpressure=backpressure,
        )
        ek = _entry_key(e.kind, e.msg)
        self._observe_e2e(e.kind, ek)
        seq = next(self._shed_seq)
        msg = e.msg
        try:
            if e.kind == "kg":
                ev = wire.KeygenSuccessEvent(
                    wallet_id=msg.wallet_id, ecdsa_pub_key="",
                    eddsa_pub_key="", result_type=wire.RESULT_ERROR,
                    error_reason=reason, retryable=True,
                )
                self.transport.queues.enqueue(
                    f"{wire.TOPIC_KEYGEN_RESULT}.{msg.wallet_id}",
                    wire.canonical_json(ev.to_json()),
                    idempotency_key=f"{msg.wallet_id}-shed-{seq}",
                )
                self.on_kg_released(msg.wallet_id)
            elif e.kind == "rs":
                ev = wire.ResharingSuccessEvent(
                    wallet_id=msg.wallet_id,
                    new_threshold=msg.new_threshold,
                    key_type=msg.key_type, pub_key="",
                    result_type=wire.RESULT_ERROR, error_reason=reason,
                    retryable=True,
                )
                self.transport.queues.enqueue(
                    f"{wire.TOPIC_RESHARING_RESULT}.{msg.wallet_id}",
                    wire.canonical_json(ev.to_json()),
                    idempotency_key=(
                        f"{msg.wallet_id}-{msg.key_type}-shed-{seq}"
                    ),
                )
                self.on_rs_released(msg.key_type, msg.wallet_id)
            else:
                ev = wire.SigningResultEvent(
                    result_type=wire.RESULT_ERROR,
                    wallet_id=msg.wallet_id, tx_id=msg.tx_id,
                    network_internal_code=msg.network_internal_code,
                    error_reason=reason, retryable=True,
                )
                self.transport.queues.enqueue(
                    f"{wire.TOPIC_SIGNING_RESULT}.{msg.tx_id}",
                    wire.canonical_json(ev.to_json()),
                    idempotency_key=f"{msg.tx_id}-shed-{seq}",
                )
                if e.reply_topic:
                    # consume the durable delivery: the refusal IS the
                    # answer; the client owns the retry (fresh tx id)
                    self.transport.pubsub.publish(e.reply_topic, b"ERR")
                self.on_tx_released(msg.wallet_id, msg.tx_id)
        except Exception as err:  # noqa: BLE001
            log.warn("shed notification failed (transport closing?)",
                     wallet=getattr(msg, "wallet_id", "?"), error=repr(err))
        log.warn("request shed", kind=e.kind, lane=e.lane, reason=reason,
                 wallet=getattr(msg, "wallet_id", "?"),
                 node=self.node.node_id)

    def _absorb_cohort_abort(
        self,
        batch_id: str,
        reqs: List[Tuple[wire.SignTxMessage, str]],
        owned_set,
        culprits,
    ) -> None:
        """Survivable identifiable abort (ISSUE 16): a batch died because
        attributable protocol checks blamed specific lanes
        (engine.abort.CohortAbort). Quarantine exactly those sessions —
        one *retryable* ABORT event each, naming the culprit (party +
        check), distinct idempotency key so a retry's result never
        dedupes against the refusal — then re-pack the surviving
        sessions onto fresh bucket-snapped sub-batches and run them to
        completion. Deterministic across the quorum: every member saw
        the same verdicts, derives the same survivor order and the same
        child batch ids, so the re-packed sessions re-form without
        another manifest round."""
        by_lane: Dict[int, Tuple[str, str]] = {}
        for lane, party, check in culprits:
            by_lane.setdefault(int(lane), (str(party), str(check)))
        survivors: List[Tuple[wire.SignTxMessage, str]] = []
        for i, (msg, reply) in enumerate(reqs):
            if i not in by_lane:
                survivors.append((msg, reply))
                continue
            party, check = by_lane[i]
            self._m_quarantined.inc()
            reason = (
                f"identifiable abort: party {party} failed OT check "
                f"'{check}' (session {msg.tx_id}) — quarantined"
            )
            tracing.incident(
                "cheater", node=self.node.node_id, tid=f"batch:{batch_id}",
                req_kind="sign", reason=reason, party=party, check=check,
            )
            seq = next(self._shed_seq)
            try:
                ev = wire.SigningResultEvent(
                    result_type=wire.RESULT_ERROR,
                    wallet_id=msg.wallet_id, tx_id=msg.tx_id,
                    network_internal_code=msg.network_internal_code,
                    error_reason=reason, retryable=True,
                )
                self.transport.queues.enqueue(
                    f"{wire.TOPIC_SIGNING_RESULT}.{msg.tx_id}",
                    wire.canonical_json(ev.to_json()),
                    idempotency_key=f"{msg.tx_id}-abort-{seq}",
                )
                if reply:
                    # the refusal IS the answer; the client owns the
                    # retry (fresh tx id, ideally a cleaner quorum)
                    self.transport.pubsub.publish(reply, b"ERR")
                if (msg.wallet_id, msg.tx_id) in owned_set:
                    self.on_tx_released(msg.wallet_id, msg.tx_id)
            except Exception as err:  # noqa: BLE001
                log.warn("quarantine notification failed",
                         wallet=msg.wallet_id, error=repr(err))
            self._observe_e2e("sign", (msg.wallet_id, msg.tx_id))
            log.warn("session quarantined (cohort abort)",
                     batch=batch_id, wallet=msg.wallet_id, tx=msg.tx_id,
                     party=party, check=check, node=self.node.node_id)
        if not survivors:
            return
        # Bucket-snapped re-pack: pow-2 chunks exactly like _fire, so the
        # retry batches land on prewarmed COMPILE_SURFACE shapes. Claims
        # we hold for survivors transfer to the child runs via the same
        # bump-then-forget handoff _inherit_covered uses — the refcount
        # never touches zero, the consumer GC can't reap in between.
        chunks: List[List[Tuple[wire.SignTxMessage, str]]] = []
        rest = survivors
        while rest:
            n = floor_bucket(min(len(rest), self._chunk_cap))
            chunks.append(rest[:n])
            rest = rest[n:]
        with self._lock:
            if self._closed:
                for msg, _r in survivors:
                    if (msg.wallet_id, msg.tx_id) in owned_set:
                        self.on_tx_released(msg.wallet_id, msg.tx_id)
                return
            for chunk in chunks:
                for msg, _r in chunk:
                    k = (msg.wallet_id, msg.tx_id)
                    if k in owned_set:
                        d = self._dedup_str("sign", k)
                        self._batch_claims[d] = (
                            self._batch_claims.get(d, 0) + 1
                        )
        for ci, chunk in enumerate(chunks):
            self._m_repacked.inc()
            child = f"{batch_id}r{ci}"
            inherited = [
                (m.wallet_id, m.tx_id) for m, _r in chunk
                if (m.wallet_id, m.tx_id) in owned_set
            ]
            log.info("survivors re-packed after cohort abort",
                     batch=batch_id, child=child, size=len(chunk),
                     node=self.node.node_id)
            threading.Thread(
                target=self._run_guarded,
                args=("sign", self._run_batch, child, chunk,
                      resolve_cohorts(len(chunk))),
                kwargs={"inherited": inherited},
                name=f"bsign-{child}", daemon=True,
            ).start()

    def _observe_e2e_locked(self, kind: str, ek: Tuple[str, str]) -> None:  # mpclint: holds=_lock
        t0 = self._intake_ts.pop((kind, ek[0], ek[1]), None)
        if t0 is not None:
            self._m_e2e.observe(time.monotonic() - t0)

    def _observe_e2e(self, kind: str, ek: Tuple[str, str]) -> None:
        with self._lock:
            self._observe_e2e_locked(kind, ek)

    def _threshold(self) -> int:
        from ..config import get_config

        return get_config().mpc_threshold

    def _decline_batch(self, session_id: str, topic: str, reason: str) -> None:
        """Announce that this node will NOT join a batch session, and keep
        answering peers' hellos with the decline for one patience window
        (a peer may still be minutes inside party-construction compiles
        when the first decline goes out). Peers fail retryably instead of
        waiting out their generous hello deadline."""
        from ..node.session import HELLO_ROUND, Session
        from ..wire import Envelope

        def bye():
            try:
                Session.send_decline(
                    self.transport, self.node.identity, self.node.node_id,
                    session_id, topic, reason,
                )
            except Exception:  # noqa: BLE001
                pass  # transport shutting down

        bye()
        if self._closed:
            return

        def on_raw(raw: bytes) -> None:
            try:
                env = Envelope.decode(raw)
            except Exception:  # noqa: BLE001
                return
            if (
                env.session_id == session_id
                and env.from_id != self.node.node_id
                and env.round == HELLO_ROUND
                and not env.payload.get("bye")
                # same gate as Session._on_raw: only a PEER's authentic
                # hello earns an answer — otherwise any bus client could
                # use this responder as a signed-decline amplifier
                and env.from_id in self.node.peer_ids
                and self.node.identity.verify_envelope(env)
            ):
                bye()

        sub = self.transport.pubsub.subscribe(topic, on_raw)

        def expire():
            with self._lock:
                s = self._decline_responders.pop(session_id, None)
            if s is not None:
                s.unsubscribe()

        evicted = []
        with self._lock:
            if self._closed:
                sub.unsubscribe()
                return
            self._decline_responders[session_id] = sub
            # cap concurrent responders: a burst of refused batches must
            # not park one subscription each for the full patience window.
            # Evict the OLDEST (its decline has been broadcast longest);
            # a late hello to an evicted session goes unanswered and fails
            # at the asker's hello deadline instead — degraded, not wrong.
            while len(self._decline_responders) > self.decline_cap:
                old_sid, old_sub = self._decline_responders.popitem(last=False)
                self._wheel.cancel(("decl", old_sid))
                evicted.append(old_sub)
                self._m_decl_evict.inc()
        self._wheel.schedule(("decl", session_id), self.batch_patience_s,
                             expire)
        for old_sub in evicted:
            try:
                old_sub.unsubscribe()
            except Exception:  # noqa: BLE001
                pass

    # -- leader: manifest emission ------------------------------------------

    def _fire(self, key: Tuple, only_full: bool = False) -> None:
        """Publish manifests covering the bucket's unfired entries, filled
        interactive-lane-first / oldest-deadline-first and drained in
        pow-2 chunks of at most ``floor_bucket(max_batch)`` (continuous
        batching: every full chunk goes now; with ``only_full`` the
        sub-chunk remainder waits for its window). Chunk sizes snap DOWN
        to the bucket grid — a window flush of 6 entries goes as 4 + 2,
        never as a one-off 6-wide compile.
        The entries STAY in the bucket (marked fired) until the manifest
        loops back through _on_manifest_raw, which removes them and hands
        their dedup claims to the batch — the same path followers take, so
        the leader's claims can never be stranded by the old
        pop-and-forget."""
        while True:
            now = time.monotonic()
            t_fire0 = tracing.now_ns()
            with self._lock:
                self._wheel.cancel(("win", key))
                unfired = [
                    e for e in self._buckets.get(key, []) if not e.fired
                ]
                if not unfired or (only_full
                                   and len(unfired) < self._chunk_cap):
                    return
                unfired.sort(key=_Entry.fill_rank)
                chunk = floor_bucket(min(len(unfired), self._chunk_cap))
                entries = unfired[:chunk]
                for e in entries:
                    e.fired = True
                self._m_batches.inc()
                self._m_fill.observe(len(entries) / self._chunk_cap)
                for e in entries:
                    self._m_age.observe(now - e.added_at)
            kind = entries[0].kind
            batch_id = secrets.token_hex(8)
            requests = [
                {"msg": e.msg.to_json(), "reply": e.reply_topic}
                for e in entries
            ]
            # cohort-aligned manifest: the chunk is a bucket, and the
            # advertised counter-phase cohort count keeps every cohort
            # slice (chunk ÷ K) on the bucket grid too, so engines reuse
            # prewarmed compiles at any K (engine/pipeline.resolve_cohorts
            # falls back toward K=1 rather than leave the grid)
            cohorts = resolve_cohorts(len(entries))
            body = _manifest_body(
                batch_id, self.node.node_id, requests, kind, cohorts
            )
            manifest = {
                "batch_id": batch_id,
                "leader": self.node.node_id,
                "requests": requests,
                "kind": kind,
                "cohorts": cohorts,
                "sig": self.node.identity.sign_raw(body).hex(),
            }
            self.transport.pubsub.publish(
                wire.TOPIC_BATCH_MANIFEST, json.dumps(manifest).encode()
            )
            # the dispatch decision + each entry's queued lifetime, on the
            # lane track, linked to the downstream batch session by id
            t_disp = tracing.now_ns()
            for e in entries:
                tracing.emit(
                    "queue", int(e.added_at * 1e9), t_disp,
                    node=self.node.node_id, tid=f"lane:{e.lane}",
                    req_kind=kind, outcome="dispatched", batch=batch_id,
                )
            tracing.emit(
                "dispatch", t_fire0, t_disp,
                node=self.node.node_id, tid=f"lane:{entries[0].lane}",
                req_kind=kind, batch=batch_id, n=len(entries),
            )
            if len(entries) == len(unfired):
                return  # bucket drained (sub-bucket tails fired above)

    def _fallback_sweep(self, key: Tuple) -> None:
        """Follower liveness, with deputy escalation: when the acting
        leader (smallest LIVE participant) is THIS node, entries the
        previous leader never covered are re-fired under our own manifest
        instead of dropping to the per-session path — the static-leader
        throughput cliff. Entries whose takeover also times out (our
        manifest lost too) go per-session on the next sweep; re-arm while
        the bucket stays non-empty."""
        now = time.monotonic()
        stale: List[_Entry] = []
        takeover: List[_Entry] = []
        expired: List[_Entry] = []
        with self._lock:
            if self._closed:
                return
            bucket = self._buckets.get(key, [])
            # Deadline gate FIRST: an entry whose SLO already expired is
            # shed retryably, never re-fired — a deputy taking over a dead
            # leader's backlog must not double-fire work whose client has
            # given up (the leader's original manifest may still be in
            # flight; two manifests for a live entry are idempotent, but
            # an expired one only wastes a batch slot and risks a
            # confusing late success).
            expired = [e for e in bucket if e.deadline_at <= now]
            if expired:
                bucket[:] = [e for e in bucket if e.deadline_at > now]
                for e in expired:
                    self._note_depth(e.lane, -1)
            # Escalation schedule: at age T the acting leader (deputy,
            # once the registry has marked the old leader dead) re-fires
            # the entries under its own manifest; everyone else waits 2T
            # before the per-session path so a follower's fallback can't
            # race the deputy's manifest. A taken-over entry's clock is
            # reset — if the deputy's manifest is lost too, it reaches
            # per-session one T later.
            T = self.manifest_timeout_s
            if self._acting_leader(
                _key_participants(key)
            ) == self.node.node_id:
                takeover = [
                    e for e in bucket
                    if now - e.added_at >= T and not e.took_over
                ]
                for e in takeover:
                    e.took_over = True
                    e.fired = False
                    e.added_at = now
            stale = [
                e for e in bucket
                if e not in takeover
                and now - e.added_at >= (T if e.took_over else 2 * T)
            ]
            bucket[:] = [e for e in bucket if e not in stale]
            for e in stale:
                self._note_depth(e.lane, -1)
            if bucket:
                self._wheel.schedule(
                    ("fb", key), T, lambda: self._fallback_sweep(key)
                )
        for e in expired:
            self._shed(e, "deadline expired awaiting manifest")
        if takeover:
            self._m_takeover.inc()
            log.warn(
                "batch leader timed out — deputy taking over manifest",
                node=self.node.node_id, entries=len(takeover),
                kind=takeover[0].kind,
            )
            self._fire(key)
        for e in stale:
            self._m_fallback.inc()
            log.warn("batch manifest timeout — per-session fallback",
                     wallet=e.msg.wallet_id, kind=e.kind,
                     node=self.node.node_id)
            if e.kind == "kg":
                if self.on_fallback_keygen:
                    self.on_fallback_keygen(e.msg)
            elif e.kind == "rs":
                if self.on_fallback_reshare:
                    self.on_fallback_reshare(e.msg)
            elif self.on_fallback:
                self.on_fallback(e.msg, e.reply_topic)

    # -- all quorum members: manifest execution ------------------------------

    def _on_manifest_raw(self, raw: bytes) -> None:
        try:
            man = json.loads(raw)
            batch_id = man["batch_id"]
            leader = man["leader"]
            sig = bytes.fromhex(man["sig"])
            requests = man["requests"]
            kind = man.get("kind", "sign")
            cohorts = int(man.get("cohorts", 1))
            msg_cls = {
                "kg": wire.GenerateKeyMessage,
                "rs": wire.ResharingMessage,
            }.get(kind, wire.SignTxMessage)
            reqs = [
                (msg_cls.from_json(r["msg"]), r.get("reply", ""))
                for r in requests
            ]
        except Exception as e:  # noqa: BLE001
            log.warn("bad batch manifest dropped", error=repr(e))
            return
        if not reqs:
            return
        # the cohort count is leader-advertised but engine-clamped: an
        # off-grid K degrades to the serial oracle, it cannot force a
        # foreign compile shape (resolve_cohorts re-validates against B)
        cohorts = resolve_cohorts(len(reqs), cohorts)
        # leader authenticity: must be signed by the node it claims to be
        # from, and that node must be a MEMBER of the wallets' topology
        # (checked against OUR keyinfo below; rank decides who sends, not
        # who is accepted — deputy takeover depends on that)
        body = _manifest_body(
            batch_id, leader, requests, kind, int(man.get("cohorts", 1))
        )
        if not self.node.identity.verify_peer(leader, body, sig):
            log.warn("batch manifest with BAD leader signature dropped",
                     batch=batch_id)
            return
        if kind == "kg":
            self._on_keygen_manifest(batch_id, leader, reqs, cohorts)
            return
        if kind == "rs":
            self._on_reshare_manifest(batch_id, leader, reqs, cohorts)
            return
        # leadership is rank-based with deputy takeover (_acting_leader):
        # any MEMBER of the wallet topology may lead; signatures and
        # content checks below carry the trust, rank only picks the sender
        info = self.node.keyinfo.get(reqs[0][0].key_type, reqs[0][0].wallet_id)
        if info is None or leader not in info.participant_peer_ids:
            log.warn("batch manifest from non-member dropped",
                     batch=batch_id, claimed=leader)
            return
        # batch homogeneity: the leader is untrusted — every request must
        # share the first's curve and (participants, threshold, epoch)
        # bucket (otherwise a leader for ONE wallet could smuggle foreign
        # topologies/curves into followers' batches). ECDSA's Paillier-
        # material homogeneity is enforced by the party constructor in
        # _run_batch (requires share loads; a mixed batch fails retryably).
        kt = reqs[0][0].key_type
        if kt not in (wire.KEY_TYPE_ED25519, wire.KEY_TYPE_SECP256K1):
            log.warn("unsupported curve in manifest dropped", batch=batch_id)
            return
        want = _bucket_key(info)
        for msg, _reply in reqs:
            if msg.key_type != kt:
                log.warn("mixed-curve batch manifest dropped", batch=batch_id)
                return
            winfo = self.node.keyinfo.get(msg.key_type, msg.wallet_id)
            if winfo is None or _bucket_key(winfo) != want:
                log.warn("mixed-topology batch manifest dropped",
                         batch=batch_id, wallet=msg.wallet_id)
                return
        # the leader is untrusted for content: re-verify every initiator
        # signature
        for msg, _reply in reqs:
            if not self.node.identity.verify_initiator(msg.raw(), msg.signature):
                log.warn("batch manifest with BAD initiator signature dropped",
                         batch=batch_id)
                return
        # drop covered entries from local buffers BEFORE any early return,
        # so follower fallback timers cannot race a manifest we act on.
        # Entries pulled from our buckets carry a dedup claim acquired by
        # the consumer's _on_sign before submit() — the batch inherits those
        # claims and must finish/release them (a claim whose entry was never
        # in a bucket belongs to a live per-session run, not to us).
        covered = {_entry_key("sign", m) for m, _ in reqs}
        inherited = self._inherit_covered("sign", covered)
        threading.Thread(
            target=self._run_guarded,
            args=("sign", self._run_batch, batch_id, reqs, cohorts),
            kwargs={"inherited": inherited},
            name=f"bsign-{batch_id}", daemon=True,
        ).start()

    @staticmethod
    def _dedup_str(kind: str, ek: Tuple[str, str]) -> str:
        """Map an _entry_key to the consumer's dedup-claim string."""
        if kind == "kg":
            return f"keygen-{ek[0]}"
        if kind == "rs":
            kt, w = ek[0].split(":", 1)
            return f"reshare-{kt}-{w}"
        return f"{ek[0]}-{ek[1]}"

    def owns_dedup(self, dedup_key: str) -> bool:
        """True while this scheduler is responsible for the claim — the
        request sits in a bucket awaiting a manifest, or a running batch
        inherited it. The consumer's GC must not reap (and error-report)
        such claims: full-size batches legitimately outlive the session
        timeout."""
        with self._lock:
            if self._batch_claims.get(dedup_key, 0) > 0:
                return True
            for claims in self._live_claims.values():
                if dedup_key in claims:
                    return True
            for bucket in self._buckets.values():
                for e in bucket:
                    if self._dedup_str(
                        e.kind, _entry_key(e.kind, e.msg)
                    ) == dedup_key:
                        return True
        return False

    def _inherit_covered(self, kind: str, covered) -> List[Tuple[str, str]]:
        """Remove manifest-covered entries of ``kind`` from local buckets,
        returning their claim keys (inherited by the batch; tracked in
        _batch_claims until the batch thread forgets them)."""
        inherited: List[Tuple[str, str]] = []
        with self._lock:
            for bucket in self._buckets.values():
                kept = []
                for e in bucket:
                    k = _entry_key(e.kind, e.msg)
                    if e.kind == kind and k in covered:
                        inherited.append(k)
                        self._note_depth(e.lane, -1)
                    else:
                        kept.append(e)
                bucket[:] = kept
            for k in inherited:
                d = self._dedup_str(kind, k)
                self._batch_claims[d] = self._batch_claims.get(d, 0) + 1
        return inherited

    def _settle_locked(self, dedups) -> None:  # mpclint: holds=_lock
        """Stamp settled SIGN dedup strings for the late-duplicate
        absorption window (see _settled). Caller holds self._lock."""
        now = time.monotonic()
        for d in dedups:
            self._settled[d] = now
            self._settled.move_to_end(d)
        while len(self._settled) > _SETTLED_CAP:
            self._settled.popitem(last=False)

    def _forget_locked(self, kind: str, keys) -> None:  # mpclint: holds=_lock
        """Decrement (and drop at zero) the refcounts for ``keys``.
        Caller holds self._lock."""
        for k in keys:
            d = self._dedup_str(kind, k)
            n = self._batch_claims.get(d, 0) - 1
            if n > 0:
                self._batch_claims[d] = n
            else:
                self._batch_claims.pop(d, None)
                if kind == "sign":
                    self._settle_locked([d])

    def _forget_batch_claims(self, kind: str, inherited) -> None:
        """Batch thread is done (success, release, or crash): the
        consumer's GC owns any still-unreleased claims from here on."""
        with self._lock:
            self._forget_locked(kind, inherited)

    def _run_guarded(self, kind: str, runner, batch_id, reqs, *mid,
                     inherited):
        """Thread entry for every batch runner: registers ALL the
        batch's request keys in _batch_claims for the run's duration
        (conservative — claims held by live per-session runs have
        tracked sessions and never consult owns_dedup), and guarantees
        they are forgotten even if the runner crashes, so a dead batch's
        claims age into the consumer GC instead of black-holing.

        ``inherited`` is keyword-only (misrouting it would leak the
        inherit-phase refcounts forever): the covered entries' holds
        from _inherit_covered transfer to this registration — register
        first, then release, under one lock, so the count never touches
        zero and the GC can't reap in between. The runner receives it
        as its final positional argument after ``mid``."""
        keys = [_entry_key(kind, m) for m, _r in reqs]
        with self._lock:
            for k in keys:
                d = self._dedup_str(kind, k)
                self._batch_claims[d] = self._batch_claims.get(d, 0) + 1
            self._forget_locked(kind, inherited)
        try:
            runner(batch_id, reqs, *mid, inherited)
        except BaseException:
            # runner died before (or during) the session handoff: purge
            # THIS batch's _live_claims registration (session ids embed
            # the batch id — another concurrent batch covering the same
            # requests must keep its own protection)
            with self._lock:
                for sid in list(self._live_claims):
                    if sid.endswith(batch_id):
                        del self._live_claims[sid]
            raise
        finally:
            self._forget_batch_claims(kind, keys)

    # -- batched DKG (kind == "kg") ------------------------------------------

    def _on_keygen_manifest(
        self, batch_id: str, leader: str, reqs, cohorts: int = 1
    ) -> None:
        node = self.node
        # rank-based leadership with deputy takeover: any cluster member
        # may lead (signatures + content checks carry the trust)
        if leader not in node.peer_ids:
            log.warn("keygen manifest from non-member dropped",
                     batch=batch_id, claimed=leader)
            return
        for msg, _r in reqs:
            if not node.identity.verify_initiator(msg.raw(), msg.signature):
                log.warn("keygen manifest with BAD initiator signature "
                         "dropped", batch=batch_id)
                return
        covered = {_entry_key("kg", m) for m, _ in reqs}
        inherited = self._inherit_covered("kg", covered)
        threading.Thread(
            target=self._run_guarded,
            args=("kg", self._run_keygen_batch, batch_id, reqs, cohorts),
            kwargs={"inherited": inherited},
            name=f"bdkg-{batch_id}", daemon=True,
        ).start()

    def _run_keygen_batch(
        self, batch_id: str, reqs, cohorts: int = 1,
        inherited: List[Tuple[str, str]] = (),
    ) -> None:
        from ..protocol.batch_dkg import BatchedDKGParty

        node = self.node
        owned = set(inherited)
        for msg, _r in reqs:
            k = _entry_key("kg", msg)
            if k not in owned and self.claim_kg(msg.wallet_id):
                owned.add(k)
        def decline_both(reason: str):
            for kt in (wire.KEY_TYPE_SECP256K1, wire.KEY_TYPE_ED25519):
                self._decline_batch(
                    f"bdkg:{kt}:{batch_id}",
                    f"bdkg:broadcast:{kt}:{batch_id}", reason,
                )

        if len(owned) < len(reqs):
            # some lane's claim is held by a live per-session fallback run
            # (the manifest arrived late). Unlike signing — where running
            # both paths is harmless (results are idempotent, nothing is
            # persisted) — a keygen batch PERSISTS key material, and two
            # concurrent DKGs for one wallet could write shares of
            # different keys on different nodes. Refuse the whole batch:
            # peers that did join fail cleanly without persisting; the
            # initiator retries.
            log.warn("keygen batch refused — lane owned by live fallback",
                     batch=batch_id, node=node.node_id)
            for w, _t in owned:
                self.on_kg_released(w)
            decline_both("lane owned by live fallback")
            return

        def emit_error(wallet_id: str, reason: str):
            ev = wire.KeygenSuccessEvent(
                wallet_id=wallet_id, ecdsa_pub_key="", eddsa_pub_key="",
                result_type=wire.RESULT_ERROR, error_reason=reason,
            )
            self.transport.queues.enqueue(
                f"{wire.TOPIC_KEYGEN_RESULT}.{wallet_id}",
                wire.canonical_json(ev.to_json()),
                idempotency_key=f"{wallet_id}-err",
            )

        def fail_all(reason: str):
            # mpc:generate is an ephemeral command (no durable redelivery,
            # reference semantics) — surface terminal errors
            for msg, _r in reqs:
                if _entry_key("kg", msg) in owned:
                    emit_error(msg.wallet_id, reason)
                    self.on_kg_done(msg.wallet_id)

        if node.registry.ready_count() < len(node.peer_ids):
            fail_all("cluster not ready for keygen")
            decline_both("cluster not ready for keygen")
            return
        threshold = self._threshold()
        B = len(reqs)
        participants = list(node.peer_ids)
        results: Dict[str, list] = {}
        errors: List = []
        done_evt = threading.Event()
        lock = threading.Lock()

        def mk_done(kt):
            def _d(shares):
                with lock:
                    results[kt] = shares
                    if len(results) == 2:
                        done_evt.set()
            return _d

        def mk_err(kt):
            def _e(err):
                with lock:
                    errors.append((kt, err))
                done_evt.set()
            return _e

        sessions = []
        try:
            for kt in (wire.KEY_TYPE_SECP256K1, wire.KEY_TYPE_ED25519):
                party = BatchedDKGParty(
                    f"bdkg:{kt}:{batch_id}", node.node_id, participants,
                    threshold, kt, B,
                    preparams=(
                        node.preparams
                        if kt == wire.KEY_TYPE_SECP256K1
                        else None
                    ),
                    min_paillier_bits=node.min_paillier_bits,
                    cohorts=cohorts,
                )
                sessions.append(
                    Session(
                        session_id=f"bdkg:{kt}:{batch_id}",
                        party=party,
                        node_id=node.node_id,
                        participants=participants,
                        transport=self.transport,
                        identity=node.identity,
                        broadcast_topic=f"bdkg:broadcast:{kt}:{batch_id}",
                        direct_topic_fn=(
                            lambda n, kt=kt:
                            f"bdkg:direct:{kt}:{n}:{batch_id}"
                        ),
                        on_done=mk_done(kt),
                        on_error=mk_err(kt),
                        hello_timeout_s=self.batch_patience_s,
                        send_patience_s=self.batch_patience_s,
                    )
                )
        except Exception as e:  # noqa: BLE001
            log.error("batched DKG setup failed", batch=batch_id,
                      error=str(e))
            fail_all(str(e))
            decline_both(str(e))
            return
        with self._lock:
            if self._closed:
                for w, _ in owned:
                    self.on_kg_released(w)
                return
            self._sessions.extend(sessions)
            self.batches_run += 1
        for s in sessions:
            s.listen()
        finished = done_evt.wait(3600)
        with self._lock:
            for s in sessions:
                if s in self._sessions:
                    self._sessions.remove(s)
        for s in sessions:
            s.close()
        if errors or not finished or len(results) != 2:
            reason = (
                "; ".join(f"{kt}: {e}" for kt, e in errors)
                if errors else "batched keygen timed out"
            )
            log.error("batched DKG failed", batch=batch_id, reason=reason,
                      node=node.node_id)
            fail_all(reason)
            return
        secp = results[wire.KEY_TYPE_SECP256K1]
        ed = results[wire.KEY_TYPE_ED25519]
        for i, (msg, _r) in enumerate(reqs):
            wid = msg.wallet_id
            node.save_share(secp[i], wid)
            node.save_share(ed[i], wid)
            ev = wire.KeygenSuccessEvent(
                wallet_id=wid,
                ecdsa_pub_key=secp[i].public_key.hex(),
                eddsa_pub_key=ed[i].public_key.hex(),
            )
            self.transport.queues.enqueue(
                f"{wire.TOPIC_KEYGEN_RESULT}.{wid}",
                wire.canonical_json(ev.to_json()),
                idempotency_key=wid,
            )
            if _entry_key("kg", msg) in owned:
                self.on_kg_done(wid)
            self._observe_e2e("kg", _entry_key("kg", msg))
        log.info("batched DKG complete", batch=batch_id, wallets=B,
                 node=node.node_id)

    # -- batched resharing (kind == "rs") ------------------------------------

    def _on_reshare_manifest(
        self, batch_id: str, leader: str, reqs, cohorts: int = 1
    ) -> None:
        node = self.node
        first = reqs[0][0]
        info = node.keyinfo.get(first.key_type, first.wallet_id)
        # rank-based leadership with deputy takeover (see _acting_leader)
        if info is None or leader not in info.participant_peer_ids:
            log.warn("reshare manifest from non-member dropped",
                     batch=batch_id, claimed=leader)
            return
        want = (
            first.key_type, tuple(info.participant_peer_ids),
            info.threshold, info.epoch, first.new_threshold,
        )
        for msg, _r in reqs:
            winfo = node.keyinfo.get(msg.key_type, msg.wallet_id)
            got = None if winfo is None else (
                msg.key_type, tuple(winfo.participant_peer_ids),
                winfo.threshold, winfo.epoch, msg.new_threshold,
            )
            if got != want:
                log.warn("mixed-topology reshare manifest dropped",
                         batch=batch_id, wallet=msg.wallet_id)
                return
            if not node.identity.verify_initiator(msg.raw(), msg.signature):
                log.warn("reshare manifest with BAD initiator signature "
                         "dropped", batch=batch_id)
                return
        covered = {_entry_key("rs", m) for m, _ in reqs}
        inherited = self._inherit_covered("rs", covered)
        threading.Thread(
            target=self._run_guarded,
            args=("rs", self._run_reshare_batch, batch_id, reqs, info,
                  cohorts),
            kwargs={"inherited": inherited},
            name=f"brs-{batch_id}", daemon=True,
        ).start()

    def _run_reshare_batch(
        self, batch_id: str, reqs, info, cohorts: int = 1, inherited=()
    ) -> None:
        from ..node.node import share_key
        from ..protocol.batch_dkg import BatchedReshareParty
        from ..store.keyinfo import KeyInfo

        node = self.node
        first = reqs[0][0]
        kt = first.key_type
        owned = set(inherited)
        for msg, _r in reqs:
            k = _entry_key("rs", msg)
            if k not in owned and self.claim_rs(msg.key_type, msg.wallet_id):
                owned.add(k)
        if len(owned) < len(reqs):
            # same rule as keygen: a reshare batch persists key material —
            # never run it concurrently with a live per-session rotation of
            # the same wallet (two independent re-deal polynomials both at
            # epoch+1 would be indistinguishable to the epoch fence)
            log.warn("reshare batch refused — lane owned by live fallback",
                     batch=batch_id, node=node.node_id)
            for w, _t in owned:
                self.on_rs_released(kt, w.split(":", 1)[1])
            self._decline_batch(
                f"brs:{kt}:{batch_id}", f"brs:broadcast:{kt}:{batch_id}",
                "lane owned by live fallback",
            )
            return

        def emit_error(msg, reason: str):
            ev = wire.ResharingSuccessEvent(
                wallet_id=msg.wallet_id, new_threshold=msg.new_threshold,
                key_type=msg.key_type, pub_key="",
                result_type=wire.RESULT_ERROR, error_reason=reason,
            )
            self.transport.queues.enqueue(
                f"{wire.TOPIC_RESHARING_RESULT}.{msg.wallet_id}",
                wire.canonical_json(ev.to_json()),
                idempotency_key=f"{msg.wallet_id}-{msg.key_type}-err",
            )

        def fail_all(reason: str):
            # mpc:reshare is an ephemeral command (reference semantics)
            for msg, _r in reqs:
                if _entry_key("rs", msg) in owned:
                    emit_error(msg, reason)
                    self.on_rs_done(msg.key_type, msg.wallet_id)

        try:
            old_quorum = node._ready_quorum(
                info.participant_peer_ids, info.threshold + 1
            )[: info.threshold + 1]
            new_committee = node.registry.ready_peers()
            if len(new_committee) < first.new_threshold + 1:
                raise NotEnoughParticipants(
                    f"{len(new_committee)} ready < new threshold"
                )
            is_old = node.node_id in old_quorum
            old_shares = None
            pubs = []
            for msg, _r in reqs:
                winfo = node.keyinfo.get(kt, msg.wallet_id)
                pubs.append(bytes.fromhex(winfo.public_key))
            if is_old:
                old_shares = []
                for msg, _r in reqs:
                    share = node.load_share(kt, msg.wallet_id)
                    winfo = node.keyinfo.get(kt, msg.wallet_id)
                    if share.epoch != winfo.epoch:
                        raise NotEnoughParticipants("epoch fence (mid-reshare)")
                    old_shares.append(share)
            party = BatchedReshareParty(
                f"brs:{kt}:{batch_id}", node.node_id, kt,
                old_quorum, new_committee, first.new_threshold, len(reqs),
                old_shares=old_shares, old_public_keys=pubs,
                preparams=(
                    node.preparams if kt == wire.KEY_TYPE_SECP256K1 else None
                ),
                min_paillier_bits=node.min_paillier_bits,
                old_epoch=info.epoch,
                cohorts=cohorts,
            )
        except (ProtocolError, NotEnoughParticipants) as e:
            log.warn("batched reshare not runnable", batch=batch_id,
                     reason=str(e), node=node.node_id)
            fail_all(str(e))
            self._decline_batch(
                f"brs:{kt}:{batch_id}", f"brs:broadcast:{kt}:{batch_id}",
                str(e),
            )
            return

        def on_done(new_shares):
            new_epoch = info.epoch + 1
            for i, (msg, _r) in enumerate(reqs):
                wid = msg.wallet_id
                if new_shares is not None:
                    node.save_share(new_shares[i], wid)
                elif party.is_old:
                    # old-only member: superseded share — delete + point
                    # keyinfo at the new topology (node.py persist_and_done)
                    node.kvstore.delete(share_key(kt, wid))
                    node.keyinfo.save(
                        kt, wid,
                        KeyInfo(
                            participant_peer_ids=list(party.new_committee),
                            threshold=party.t_new,
                            is_reshared=True,
                            public_key=pubs[i].hex(),
                            vss_commitments=[],
                            epoch=new_epoch,
                        ),
                    )
                if new_shares is not None:
                    ev = wire.ResharingSuccessEvent(
                        wallet_id=wid, new_threshold=msg.new_threshold,
                        key_type=kt,
                        pub_key=new_shares[i].public_key.hex(),
                    )
                    self.transport.queues.enqueue(
                        f"{wire.TOPIC_RESHARING_RESULT}.{wid}",
                        wire.canonical_json(ev.to_json()),
                        idempotency_key=f"{wid}-{kt}",
                    )
                if _entry_key("rs", msg) in owned:
                    self.on_rs_done(kt, wid)
                self._observe_e2e("rs", _entry_key("rs", msg))
            log.info("batched reshare complete", batch=batch_id,
                     wallets=len(reqs), node=node.node_id)
            _prune()

        def on_error(e):
            log.error("batched reshare failed", batch=batch_id,
                      error=str(e), node=node.node_id)
            fail_all(str(e))
            _prune()

        def _prune():
            with self._lock:
                if session in self._sessions:
                    self._sessions.remove(session)
                self._live_claims.pop(f"brs:{kt}:{batch_id}", None)
            session.close()

        session = Session(
            session_id=f"brs:{kt}:{batch_id}",
            party=party,
            node_id=node.node_id,
            participants=sorted(set(old_quorum) | set(new_committee)),
            transport=self.transport,
            identity=node.identity,
            broadcast_topic=f"brs:broadcast:{kt}:{batch_id}",
            direct_topic_fn=lambda n: f"brs:direct:{kt}:{n}:{batch_id}",
            on_done=on_done,
            on_error=on_error,
            hello_timeout_s=self.batch_patience_s,
            send_patience_s=self.batch_patience_s,
        )
        with self._lock:
            if self._closed:
                for w in list(owned):
                    self.on_rs_released(kt, w[0].split(":", 1)[1])
                return
            self._sessions.append(session)
            # async handoff: the session owns the claims until _prune
            self._live_claims[f"brs:{kt}:{batch_id}"] = {
                self._dedup_str("rs", k) for k in owned
            }
            self.batches_run += 1
        session.listen()

    def _run_batch(
        self,
        batch_id: str,
        reqs: List[Tuple[wire.SignTxMessage, str]],
        cohorts: int = 1,
        inherited: List[Tuple[str, str]] = (),
    ) -> None:
        node = self.node
        first = reqs[0][0]
        info = node.keyinfo.get(first.key_type, first.wallet_id)
        if info is None:
            return
        # The batch owns two kinds of dedup claims: (a) claims inherited
        # from entries the manifest pulled out of our local buckets (the
        # consumer's _on_sign claimed, then routed to submit()), and
        # (b) claims we acquire here for lanes the manifest beat the
        # pub/sub copy of the request to. A claim that is neither — held by
        # a live per-session run because the manifest raced the fallback —
        # must not be finished/released by us; that run owns its lifecycle.
        owned_set = set(inherited)
        for msg, _r in reqs:
            k = (msg.wallet_id, msg.tx_id)
            if k not in owned_set and self.claim_tx(*k):
                owned_set.add(k)
        owned = list(owned_set)

        def release_all(reason: str = ""):
            for w, t in owned:
                self.on_tx_released(w, t)
            # tell peers (possibly mid-compile at their hello barrier) we
            # are not coming, so they fail retryably NOW
            self._decline_batch(
                f"bsign:{batch_id}", f"bsign:broadcast:{batch_id}", reason
            )

        try:
            quorum = node._ready_quorum(
                info.participant_peer_ids, info.threshold + 1
            )
        except NotEnoughParticipants as e:
            release_all(str(e))
            return  # no reply ⇒ durable redelivery retries
        if node.node_id not in quorum:
            release_all("not in quorum")
            return
        shares: List[KeygenShare] = []
        messages: List[bytes] = []
        kt = first.key_type
        try:
            for msg, _r in reqs:
                share = node.load_share(msg.key_type, msg.wallet_id)
                winfo = node.keyinfo.get(msg.key_type, msg.wallet_id)
                if winfo is None or share.epoch != winfo.epoch:
                    raise NotEnoughParticipants("epoch fence (mid-reshare)")
                shares.append(share)
                messages.append(msg.tx)
            if kt == wire.KEY_TYPE_SECP256K1:
                from ..engine.gg18_batch import Domains
                from ..protocol.ecdsa.batch_signing import (
                    BatchedECDSASigningParty,
                )

                party = BatchedECDSASigningParty(
                    f"bsign:{batch_id}", node.node_id, quorum, shares,
                    messages, dom=self.gg18_dom or Domains(),
                    cohorts=cohorts,
                )
            else:
                party = BatchedEDDSASigningParty(
                    f"bsign:{batch_id}", node.node_id, quorum, shares,
                    messages, cohorts=cohorts,
                )
        except (ProtocolError, NotEnoughParticipants) as e:
            log.warn("batch not signable here — waiting for redelivery",
                     batch=batch_id, reason=str(e), node=node.node_id)
            release_all(str(e))
            return

        def on_done(result):
            ok = result["ok"]
            for i, (msg, reply) in enumerate(reqs):
                if bool(ok[i]) and kt == wire.KEY_TYPE_SECP256K1:
                    ev = wire.SigningResultEvent(
                        result_type=wire.RESULT_SUCCESS,
                        wallet_id=msg.wallet_id,
                        tx_id=msg.tx_id,
                        network_internal_code=msg.network_internal_code,
                        r=result["r"][i].tobytes().hex(),
                        s=result["s"][i].tobytes().hex(),
                        signature_recovery=format(
                            int(result["recovery"][i]), "02x"
                        ),
                    )
                elif bool(ok[i]):
                    ev = wire.SigningResultEvent(
                        result_type=wire.RESULT_SUCCESS,
                        wallet_id=msg.wallet_id,
                        tx_id=msg.tx_id,
                        network_internal_code=msg.network_internal_code,
                        signature=result["signatures"][i].tobytes().hex(),
                    )
                else:
                    ev = wire.SigningResultEvent(
                        result_type=wire.RESULT_ERROR,
                        wallet_id=msg.wallet_id,
                        tx_id=msg.tx_id,
                        network_internal_code=msg.network_internal_code,
                        error_reason="batched signature failed verification",
                    )
                self.transport.queues.enqueue(
                    f"{wire.TOPIC_SIGNING_RESULT}.{msg.tx_id}",
                    wire.canonical_json(ev.to_json()),
                    idempotency_key=msg.tx_id,
                )
                if reply:
                    self.transport.pubsub.publish(
                        reply, b"OK" if bool(ok[i]) else b"ERR"
                    )
                if (msg.wallet_id, msg.tx_id) in owned_set:
                    self.on_tx_done(msg.wallet_id, msg.tx_id)
                self._observe_e2e("sign", (msg.wallet_id, msg.tx_id))
            log.info("batch signed", batch=batch_id, size=len(reqs),
                     node=node.node_id)
            _prune()

        def on_error(e):
            # Identifiable abort (engine.abort.CohortAbort, duck-typed on
            # .culprits so the distributed party can forward a peer's
            # abort without importing the engine): quarantine exactly the
            # blamed sessions and re-pack the survivors — never the
            # whole-batch release below, which would retry the cheater
            # alongside its victims forever.
            culprits = getattr(e, "culprits", None)
            if culprits:
                self._absorb_cohort_abort(
                    batch_id, reqs, owned_set, culprits
                )
                _prune()
                return
            # retryable/protocol failure: emit nothing — durable redelivery
            # retries each request (possibly down the per-session path)
            log.warn("batch signing failed", batch=batch_id, error=str(e),
                     node=node.node_id)
            release_all()
            _prune()

        def _prune():
            with self._lock:
                if session in self._sessions:
                    self._sessions.remove(session)
                owned_ds = self._live_claims.pop(f"bsign:{batch_id}", None)
                if owned_ds:
                    self._settle_locked(owned_ds)
            session.close()

        session = Session(
            session_id=f"bsign:{batch_id}",
            party=party,
            node_id=node.node_id,
            participants=quorum,
            transport=self.transport,
            identity=node.identity,
            broadcast_topic=f"bsign:broadcast:{batch_id}",
            direct_topic_fn=lambda n: f"bsign:direct:{n}:{batch_id}",
            on_done=on_done,
            on_error=on_error,
            hello_timeout_s=self.batch_patience_s,
            send_patience_s=self.batch_patience_s,
        )
        with self._lock:
            if self._closed:
                release_all()
                return
            self._sessions.append(session)
            # the session now owns the claims (this runner RETURNS while
            # the rounds run for up to an hour); _prune hands them back
            self._live_claims[f"bsign:{batch_id}"] = {
                self._dedup_str("sign", k) for k in owned
            }
            self.batches_run += 1
        session.listen()
